"""Batched dispatch: many problems per device call.

``run_bucket`` stacks shape-identical padded problems (``bucketing``)
along a leading batch axis and drives ONE compiled program per schedule
segment — ``vmap`` of the fused RBCD segment (``models.rbcd._rbcd_segment``)
over the problem axis — instead of one driver loop per problem.  The
device amortizes dispatch and compilation across the batch; the math per
problem is the single-problem ELL formulation unchanged (vmap is
semantically per-example), so batched results match sequential solves
within kernel tolerance.

The batch axis is padded to the next power of two by replicating the last
problem, so one executable per (bucket, pow2-width) serves every
occupancy instead of one per exact batch size.

Executables come from the caller's ``ExecutableCache`` keyed by the
config fingerprint (``cache.problem_fingerprint``): segment, metrics, and
terminal-epilogue programs are each cached independently; with
``params.certify_mode="device"`` the epilogue program also computes the
per-member dual-certificate payload so the certificate rides the batch's
single terminal fetch.  With telemetry on, the
cached entries are ``obs.profile.ProfiledExecutable``\\ s (AOT compile
wall-time + XLA cost/memory analysis recorded per fingerprint key), each
dispatch window times itself into ``serve_dispatch_device_seconds``, and
the stack/dispatch/slice stages emit spans under the server's per-batch
``dispatch`` span; with telemetry off none of that machinery exists.

Termination mirrors ``run_rbcd``: per problem, the centralized gradient
norm against ``grad_norm_tol`` or all-agents consensus; the batch keeps
stepping until every member has terminated (a converged member's extra
rounds only polish its iterate — cost is monotone under the plain
schedule), with each member's history truncated at its own termination
eval.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..config import RobustCostType
from ..models import rbcd
from ..obs.trace import span
from ..ops import manifold, quadratic
from .bucketing import PaddedProblem
from .cache import ExecutableCache, fingerprint_key, problem_fingerprint


def _tree_stack(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _slice_states(state_b, n: int):
    """Per-problem views of the stacked batch state (device slices; the
    session store materializes them on save)."""
    return [jax.tree.map(lambda a: a[b], state_b) for b in range(n)]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _make_segment_exec(meta: rbcd.GraphMeta, params):
    def seg(state_b, graph_b, k, uw, rs):
        one = lambda s, g: rbcd._rbcd_segment(
            s, g, k, meta, params, first_update_weights=uw, first_restart=rs)
        return jax.vmap(one)(state_b, graph_b)

    return jax.jit(seg, static_argnames=("uw", "rs"))


def _make_metrics_exec(meta: rbcd.GraphMeta, n_total: int, num_meas: int):
    def one(Xa, weights, ready, graph, eg):
        Xg = rbcd.gather_to_global(Xa, graph, n_total)
        egw = eg._replace(
            weight=rbcd.global_weights(weights, graph, num_meas))
        f = quadratic.cost(Xg, egw)
        g = manifold.rgrad(Xg, quadratic.egrad(Xg, egw))
        return jnp.stack(
            [f, manifold.norm(g), jnp.all(ready).astype(f.dtype)])

    return jax.jit(jax.vmap(one))


def _make_verdict_exec(meta: rbcd.GraphMeta, n_total: int, num_meas: int,
                       grad_norm_tol: float):
    """Batched fused-eval program of the verdict mode: per problem, the
    centralized metrics, the convergence test, and a non-finite sentinel
    fold into a packed per-problem verdict word (``rbcd``'s word layout),
    the terminal eval latches on device, and the metric row appends to a
    device-side history — so the host reads back ONE ``[B]`` int32 vector
    per K rounds instead of the ``[B, 3]`` float stack per eval."""

    def one(Xa, weights, ready, graph, eg, iteration,
            word, term_eval, term_it, eval_idx, hist):
        Xg = rbcd.gather_to_global(Xa, graph, n_total)
        egw = eg._replace(
            weight=rbcd.global_weights(weights, graph, num_meas))
        f = quadratic.cost(Xg, egw)
        g = manifold.rgrad(Xg, quadratic.egrad(Xg, egw))
        gn = manifold.norm(g)
        consensus = jnp.all(ready).astype(f.dtype)
        vec = jnp.stack([f, gn, consensus])
        status_now = jnp.where(
            gn < grad_norm_tol, rbcd.VERDICT_GRAD_NORM,
            jnp.where(consensus > 0, rbcd.VERDICT_CONSENSUS,
                      rbcd.VERDICT_RUNNING)).astype(jnp.int32)
        status = jnp.where(term_eval >= 0, word & 7, status_now)
        finite = jnp.isfinite(f) & jnp.isfinite(gn)
        anom = jnp.maximum((word >> 3) & 7,
                           jnp.where(finite, 0, rbcd.ANOMALY_NON_FINITE))
        first = (term_eval < 0) & (status != rbcd.VERDICT_RUNNING)
        term_eval = jnp.where(first, eval_idx, term_eval)
        term_it = jnp.where(first, iteration.astype(jnp.int32), term_it)
        hist = jax.lax.dynamic_update_slice(
            hist, vec[None, :].astype(hist.dtype),
            (eval_idx, jnp.zeros((), eval_idx.dtype)))
        return ((status | (anom << 3)).astype(jnp.int32),
                term_eval, term_it, eval_idx + 1, hist)

    return jax.jit(jax.vmap(one))


def _make_epilogue_exec(meta: rbcd.GraphMeta, n_total: int, num_meas: int,
                        certify_mode: str = "off", certify_seed: int = 0):
    """Batched fused terminal epilogue (the vmap analog of
    ``rbcd.make_terminal_epilogue``): rounding/anchoring + the weight
    collapse, plus — with ``certify_mode="device"`` — the gauge-deflated
    device-certificate eigensolve per batch member.  Padded members are
    benign: a padded pose contributes zero rows to the dual operator,
    whose zero eigenvalue is clamped by the payload's ``min(lam, 0)``."""
    device_cert = certify_mode == "device"
    want_xg = certify_mode in ("device", "host")
    if device_cert:
        from ..models import certify as certify_mod

    def one(Xa, weights, graph, eg):
        Xg = rbcd.gather_to_global(Xa, graph, n_total)
        w = rbcd.global_weights(weights, graph, num_meas)
        out = {"T": rbcd.round_global(Xg, rbcd.lifting_matrix(meta,
                                                              Xg.dtype)),
               "w": w}
        if want_xg:
            out["Xg"] = Xg
        if device_cert:
            out["cert"] = certify_mod.device_certificate_payload(
                Xg, eg._replace(weight=w),
                jax.random.PRNGKey(certify_seed))
        return out

    return jax.jit(jax.vmap(one))


def _cached_exec(cache: ExecutableCache, fp: dict, make,
                 static_names: tuple = ()):
    """Cache lookup with the compile-profiling wrap applied behind the
    telemetry fence: with a run live, the cached entry is a
    ``ProfiledExecutable`` (AOT compile + cost/memory analysis recorded
    per fingerprint key); with telemetry off the bare jit wrapper is
    stored and no profiling object ever exists.

    A cache carrying a persistent disk tier stores ``AOTExecutable``
    entries instead, on BOTH telemetry paths: the disk tier is a
    durability feature (replica restarts must skip XLA with telemetry
    off too), and the wrapper keeps its own obs emission behind the
    fence."""
    if cache.disk is not None:
        from .fleet.aotcache import AOTExecutable

        return cache.get(fp, lambda: AOTExecutable(
            make(), cache.disk, key=fingerprint_key(fp),
            label=fp.get("kind", "?"), static_names=static_names,
            bucket=fp.get("bucket_shape"), batch=fp.get("batch")))
    run = obs.get_run()
    if run is None:
        return cache.get(fp, make)
    from ..obs.profile import ProfiledExecutable

    return cache.get(fp, lambda: ProfiledExecutable(
        make(), key=fingerprint_key(fp), label=fp.get("kind", "?"),
        static_names=static_names,
        bucket=fp.get("bucket_shape"), batch=fp.get("batch")))


def run_bucket(padded: list[PaddedProblem], cache: ExecutableCache,
               max_iters: int | None = None, grad_norm_tol: float = 0.1,
               eval_every: int = 1, verdict_every: int | None = None,
               session_cb=None, session_every: int = 1,
               should_stop=None):
    """Solve a list of same-bucket padded problems as one batched program.

    Returns ``(results, info)``: per-problem ``RBCDResult`` (trajectories
    and weights sliced back to the problem's real pose/measurement
    counts), and a dict of batch statistics (rounds, evals, batch width,
    occupancy) for the serving metrics.

    ``verdict_every`` (a positive multiple of ``eval_every``) switches
    the batch to the device-resident verdict loop: per-problem
    termination latches on device (``_make_verdict_exec``) and the host
    reads back one packed ``[B]`` int32 verdict vector per K rounds per
    bucket, with the per-eval histories fetched once at the end.  A
    member that terminates mid-window runs up to ``K - eval_every``
    extra polish rounds (monotone under the plain schedule, like the
    legacy batch's wait-for-the-batch behavior); its reported history
    and round count are truncated at its latched terminal eval.

    ``session_cb(iteration, states)`` — the crash-recovery hook
    (``serve.session``): called every ``session_every`` eval boundaries
    (and at the verdict-mode K boundaries) with the per-problem sliced
    solver states, so a server can persist resumable snapshots while the
    batch is in flight.  A member problem carrying ``state0`` resumes
    from that exact state instead of its ``X0`` init.

    ``should_stop()`` — the live-migration hook (``serve.fleet``):
    polled at eval/verdict boundaries, AFTER the boundary's
    ``session_cb`` snapshot lands (when one is due it is forced, so a
    stopping batch always leaves a resume point).  A True return breaks
    the loop early; the partial results return as usual and ``info``
    carries ``interrupted=True`` so the server can evacuate instead of
    replying."""
    if not padded:
        return [], {"rounds": 0, "evals": 0, "batch": 0, "occupancy": 0.0,
                    "interrupted": False}
    first = padded[0]
    meta, params, dtype = first.meta, first.prob.params, first.prob.dtype
    shape = first.shape
    for p in padded[1:]:
        if p.shape != shape or p.meta != meta or p.prob.params != params \
                or p.prob.dtype != dtype:
            raise ValueError(
                "run_bucket requires shape/config-identical problems — "
                "bucketing must never mix incompatible shapes "
                f"({p.shape} vs {shape})")
    max_iters = params.max_num_iters if max_iters is None else max_iters

    B_real = len(padded)
    B = _next_pow2(B_real)

    def _initial_state(p: PaddedProblem):
        if p.state0 is not None:
            st = p.state0
            # Persisted snapshots drop the recomputable factors; restore
            # them from the carried weights (bit-identical refresh).
            if st.chol is None:
                st = rbcd.refresh_problem(st, p.graph, meta, params)
            return st
        return rbcd.init_state(p.graph, meta, p.X0, params=params)

    with span("stack", phase="serve", batch=B, size=B_real):
        states = [_initial_state(p) for p in padded]
        graphs = [p.graph for p in padded]
        edges_g = [p.edges_g for p in padded]
        while len(states) < B:  # replicate the tail to the pow2 width
            states.append(states[B_real - 1])
            graphs.append(graphs[B_real - 1])
            edges_g.append(edges_g[B_real - 1])
        state_b = _tree_stack(states)
        graph_b = _tree_stack(graphs)
        eg_b = _tree_stack(edges_g)

    seg = _cached_exec(
        cache, problem_fingerprint(meta, params, dtype, shape, B, "segment"),
        lambda: _make_segment_exec(meta, params),
        static_names=("uw", "rs"))
    met = _cached_exec(
        cache, problem_fingerprint(meta, params, dtype, shape, B, "metrics"),
        lambda: _make_metrics_exec(meta, shape.n_total, shape.num_meas))
    certify_mode = getattr(params, "certify_mode", "off")
    fin = _cached_exec(
        cache, problem_fingerprint(meta, params, dtype, shape, B,
                                   f"epilogue:{certify_mode}"),
        lambda: _make_epilogue_exec(meta, shape.n_total, shape.num_meas,
                                    certify_mode))

    robust_on = params.robust.cost_type != RobustCostType.L2
    accel_on = params.acceleration

    it = 0
    nwu = 0
    evals = 0
    done = [False] * B_real
    cost_hist = [[] for _ in range(B_real)]
    gn_hist = [[] for _ in range(B_real)]
    term = ["max_iters"] * B_real
    iters = [max_iters] * B_real
    interrupted = False
    run = obs.get_run()

    if verdict_every is not None:
        if verdict_every <= 0 or verdict_every % eval_every != 0:
            raise ValueError(
                f"verdict_every={verdict_every} must be a positive "
                f"multiple of eval_every={eval_every}")
        vex = _cached_exec(
            cache, problem_fingerprint(meta, params, dtype, shape, B,
                                       f"verdict{grad_norm_tol}"),
            lambda: _make_verdict_exec(meta, shape.n_total, shape.num_meas,
                                       grad_norm_tol))
        max_evals = -(-max_iters // eval_every)
        word = jnp.zeros((B,), jnp.int32)
        term_eval = jnp.full((B,), -1, jnp.int32)
        term_it = jnp.full((B,), -1, jnp.int32)
        eidx = jnp.zeros((B,), jnp.int32)
        hist = jnp.zeros((B, max_evals, 3), jnp.dtype(dtype))
        eval_its: list[int] = []
        while True:
            vtarget = min(((it // verdict_every) + 1) * verdict_every,
                          max_iters)
            t_d0 = time.monotonic() if run is not None else 0.0
            with span("device_dispatch", phase="serve", batch=B,
                      verdict=True):
                while it < vtarget:
                    target = min(((it // eval_every) + 1) * eval_every,
                                 vtarget)
                    while it < target:
                        uw, rs, end = rbcd.schedule_bounds(
                            it, nwu, max_iters=max_iters,
                            eval_every=eval_every, params=params,
                            robust_on=robust_on, accel_on=accel_on)
                        nwu += int(uw)
                        state_b = seg(state_b, graph_b, end - it,
                                      uw=uw, rs=rs)
                        it = end
                    word, term_eval, term_it, eidx, hist = vex(
                        state_b.X, state_b.weights, state_b.ready,
                        graph_b, eg_b, state_b.iteration,
                        word, term_eval, term_it, eidx, hist)
                    evals += 1
                    eval_its.append(it)
                # The batch's one readback per K rounds: the packed
                # per-problem verdict vector.
                # dpgolint: disable=DPG003 -- sanctioned verdict fetch
                wv = rbcd._host_fetch(word)
            if run is not None:
                dt = time.monotonic() - t_d0
                run.gauge("serve_dispatch_device_seconds",
                          "wall-clock of the last batched dispatch window "
                          "(segment launches through metrics readback)",
                          unit="s").set(dt)
                run.counter("serve_device_time_seconds_total",
                            "cumulative batched-dispatch wall-clock",
                            unit="s").inc(dt)
            if session_cb is not None:
                # Snapshot at the verdict boundary: the live batch state is
                # on hand and the window's segments have already retired.
                session_cb(it, _slice_states(state_b, B_real))
            if should_stop is not None and should_stop():
                # Stop AFTER the boundary snapshot: the batch leaves a
                # resume point at exactly this iteration.
                interrupted = True
                break
            all_terminal = ((wv & 7) != rbcd.VERDICT_RUNNING).all()
            if it >= max_iters or bool(all_terminal):
                break

    while verdict_every is None and it < max_iters and not all(done) \
            and not interrupted:
        target = min(((it // eval_every) + 1) * eval_every, max_iters)
        t_d0 = time.monotonic() if run is not None else 0.0
        with span("device_dispatch", phase="serve", batch=B):
            while it < target:
                uw, rs, end = rbcd.schedule_bounds(
                    it, nwu, max_iters=max_iters, eval_every=eval_every,
                    params=params, robust_on=robust_on, accel_on=accel_on)
                nwu += int(uw)
                state_b = seg(state_b, graph_b, end - it, uw=uw, rs=rs)
                it = end
            # The metrics readback is the batch's existing sync point —
            # timing to here measures dispatch -> materialized without
            # adding a transfer or a block_until_ready.
            # dpgolint: disable=DPG003 -- sanctioned seam: the batch's one
            vec = np.asarray(met(state_b.X, state_b.weights, state_b.ready,
                                 graph_b, eg_b))  # metrics fetch per eval
        if run is not None:
            dt = time.monotonic() - t_d0
            run.gauge("serve_dispatch_device_seconds",
                      "wall-clock of the last batched dispatch window "
                      "(segment launches through metrics readback)",
                      unit="s").set(dt)
            run.counter("serve_device_time_seconds_total",
                        "cumulative batched-dispatch wall-clock",
                        unit="s").inc(dt)
        evals += 1
        stop = should_stop is not None and should_stop()
        if session_cb is not None and (
                stop or evals % max(int(session_every), 1) == 0):
            # A stopping batch forces the boundary snapshot even when the
            # cadence would skip it — migration needs the resume point.
            session_cb(it, _slice_states(state_b, B_real))
        if stop:
            interrupted = True
        for b in range(B_real):
            if done[b]:
                continue
            f, gn, consensus = vec[b]
            cost_hist[b].append(float(f))
            gn_hist[b].append(float(gn))
            if float(gn) < grad_norm_tol:
                done[b], term[b], iters[b] = True, "grad_norm", it
            elif consensus > 0:
                done[b], term[b], iters[b] = True, "consensus", it

    with span("slice", phase="serve", batch=B, certify=certify_mode):
        # The batch's ONE terminal blocking read: rounded trajectories,
        # collapsed weights, the raw batch iterate, the verdict mode's
        # device-side histories + latched indices, and (certify on) the
        # per-member certificate payload — a single fused pytree fetch
        # through the sanctioned seam.
        ep = {"fin": fin(state_b.X, state_b.weights, graph_b, eg_b),
              "X": state_b.X}
        if verdict_every is not None:
            ep["hist"] = hist
            ep["te"] = jnp.stack([term_eval, term_it])
        # dpgolint: disable=DPG003 -- sanctioned terminal epilogue fetch
        ep = rbcd._host_fetch(ep)
    if verdict_every is not None:
        hist_h, te_h = ep["hist"], ep["te"]
        for b in range(B_real):
            te, ti = int(te_h[0, b]), int(te_h[1, b])
            status = int(wv[b]) & 7
            if te >= 0:
                n_keep = te + 1
                iters[b] = ti
                term[b] = rbcd._VERDICT_STATUS.get(status, "max_iters")
            else:
                n_keep = len(eval_its)
                iters[b] = it
                term[b] = "max_iters"
            cost_hist[b] = [float(hist_h[b, r, 0]) for r in range(n_keep)]
            gn_hist[b] = [float(hist_h[b, r, 1]) for r in range(n_keep)]
    T_b, w_b, X_b = ep["fin"]["T"], ep["fin"]["w"], ep["X"]
    results = []
    for b, p in enumerate(padded):
        certificate = None
        if certify_mode != "off":
            # Host decision per member on the already-fetched payload —
            # the f64 REFUSE fallback reads the fetched Xg, never the
            # device.
            with span("certify_decide", phase="serve", member=b):
                fin_b = jax.tree.map(lambda a: a[b], ep["fin"])
                fin_b["w_glob"] = fin_b.pop("w")
                certificate = rbcd._epilogue_certificate(
                    fin_b, p.edges_g, params, dtype)
        results.append(rbcd.RBCDResult(
            T=jnp.asarray(T_b[b, :p.prob.n_total]),
            X=jnp.asarray(X_b[b, :, :p.prob.meta.n_max]),
            cost_history=cost_hist[b],
            grad_norm_history=gn_hist[b],
            iterations=iters[b],
            terminated_by=term[b],
            weights=jnp.asarray(w_b[b, :p.prob.num_meas]),
            certificate=certificate,
        ))
    info = {"rounds": it, "evals": evals, "batch": B,
            "size": B_real, "occupancy": B_real / float(B),
            "interrupted": interrupted}
    return results, info
