"""Shape bucketing: pad prepared problems so compatible requests stack.

A batched solve (``runner.run_bucket``) vmaps one compiled RBCD program
over a leading problem axis, which requires every problem in the batch to
share its padded array shapes exactly.  Requests rarely arrive
shape-identical, so each prepared problem is *padded up* to a bucket
shape — every padded dimension rounded to a quantum — and problems land
in the same bucket iff all rounded dimensions (and the solver config)
agree.

Padding is pure masking, not new math: padded poses carry
``pose_mask = 0`` and no edges, padded edges carry ``mask = 0``, so every
kernel the solver runs already ignores them — the same mechanism that
handles agents shorter than ``n_max`` in any unpadded graph.  The one
subtlety is index remapping: edge endpoints in the neighbor-slot range
``[n_max, n_max + s_max)`` shift with the local-pose range they sit
behind, and ELL incidence slots in the ``j``-endpoint half
``[e_max, 2 e_max)`` shift with the edge count.

The Pallas edge-tile fields are deliberately dropped (the serving plane
builds graphs with ``pallas_sel=False``): tile layouts bake ``n + s``
into their one-hot pad index, and a ``vmap`` over the kernel call is not
part of the supported surface.  Batched serving runs the ELL/dense
formulations; the single-problem kernel path is unaffected.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Schedule
from ..models import rbcd
from ..types import EdgeSet, edge_set_from_measurements


class BucketShape(NamedTuple):
    """Padded array dimensions of one shape bucket (all ints)."""

    n_max: int
    e_max: int
    s_max: int
    p_max: int
    k_inc: int
    n_total: int
    num_meas: int


@dataclasses.dataclass(frozen=True)
class PaddedProblem:
    """A prepared problem padded to its bucket shape, ready to stack."""

    prob: rbcd.PreparedProblem  # the original (unpadded) problem
    graph: rbcd.MultiAgentGraph
    meta: rbcd.GraphMeta
    edges_g: EdgeSet  # padded global edge set (metrics + init)
    X0: jax.Array
    shape: BucketShape
    #: Exact solver state to resume from instead of ``init_state(X0)`` —
    #: the crash-recovery path (``serve.session``) re-admits a died-mid-
    #: batch request with its last snapshot here.  Shapes must match the
    #: bucket; carried factors are refreshed by the runner when absent.
    state0: "rbcd.RBCDState | None" = None


def _round_up(x: int, q: int) -> int:
    return max(q, -(-int(x) // q) * q)


def bucket_shape_of(prob: rbcd.PreparedProblem, quantum: int = 32,
                    small_quantum: int = 8) -> BucketShape:
    """The bucket this problem pads into: large dimensions (pose/edge
    counts) round to ``quantum``, small per-agent tables (neighbor slots,
    public poses, ELL degree) to ``small_quantum``.  Problems whose raw
    sizes differ by less than a quantum coalesce; the config fields that
    must also agree live in the cache key (``cache.problem_fingerprint``),
    not here."""
    m = prob.meta
    return BucketShape(
        n_max=_round_up(m.n_max, quantum),
        e_max=_round_up(m.e_max, quantum),
        s_max=_round_up(m.s_max, small_quantum),
        p_max=_round_up(m.p_max, small_quantum),
        k_inc=_round_up(prob.graph.inc_slot.shape[-1], small_quantum),
        n_total=_round_up(prob.n_total, quantum),
        num_meas=_round_up(prob.num_meas, quantum),
    )


def padded_meta(prob: rbcd.PreparedProblem, shape: BucketShape) -> rbcd.GraphMeta:
    """GraphMeta at the bucket shape.  ``num_colors`` is normalized to 1
    for every schedule but COLORED (the only consumer), so two problems
    whose greedy colorings happen to differ still share a bucket."""
    m = prob.meta
    colors = m.num_colors if prob.params.schedule == Schedule.COLORED else 1
    return rbcd.GraphMeta(
        num_robots=m.num_robots, n_max=shape.n_max, e_max=shape.e_max,
        s_max=shape.s_max, p_max=shape.p_max, d=m.d, rank=m.rank,
        num_colors=colors)


def _pad_tail(a: np.ndarray, axis: int, target: int, fill=0) -> np.ndarray:
    grow = target - a.shape[axis]
    if grow == 0:
        return a
    width = [(0, 0)] * a.ndim
    width[axis] = (0, grow)
    return np.pad(a, width, constant_values=fill)


def pad_problem(prob: rbcd.PreparedProblem, shape: BucketShape,
                init: str = "chordal") -> PaddedProblem:
    """Pad a prepared problem to ``shape`` and (if it carries no ``X0``)
    initialize it on the *padded* problem, so the compiled init program is
    shared bucket-wide."""
    g, m = prob.graph, prob.meta
    dn = shape.n_max - m.n_max
    de = shape.e_max - m.e_max
    ds = shape.s_max - m.s_max
    dp = shape.p_max - m.p_max
    k_old = g.inc_slot.shape[-1]
    dk = shape.k_inc - k_old
    if min(dn, de, ds, dp, dk, shape.n_total - prob.n_total,
           shape.num_meas - prob.num_meas) < 0:
        raise ValueError(f"bucket shape {shape} smaller than problem "
                         f"({m}, K={k_old}, n_total={prob.n_total}, "
                         f"m={prob.num_meas})")
    A, d = m.num_robots, m.d
    fdt = np.asarray(g.edges.R).dtype

    e = g.edges
    # Endpoint indices: the neighbor-slot range moves with n_max.
    ei = np.asarray(e.i)
    ej = np.asarray(e.j)
    ei = np.where(ei >= m.n_max, ei + dn, ei)
    ej = np.where(ej >= m.n_max, ej + dn, ej)
    eye = np.broadcast_to(np.eye(d, dtype=fdt), (A, de, d, d))
    edges = EdgeSet(
        i=jnp.asarray(_pad_tail(ei, 1, shape.e_max)),
        j=jnp.asarray(_pad_tail(ej, 1, shape.e_max)),
        R=jnp.asarray(np.concatenate([np.asarray(e.R), eye], axis=1)),
        t=jnp.asarray(_pad_tail(np.asarray(e.t), 1, shape.e_max)),
        kappa=jnp.asarray(_pad_tail(np.asarray(e.kappa), 1, shape.e_max)),
        tau=jnp.asarray(_pad_tail(np.asarray(e.tau), 1, shape.e_max)),
        weight=jnp.asarray(
            _pad_tail(np.asarray(e.weight), 1, shape.e_max, fill=1.0)),
        mask=jnp.asarray(_pad_tail(np.asarray(e.mask), 1, shape.e_max)),
        is_lc=jnp.asarray(_pad_tail(np.asarray(e.is_lc), 1, shape.e_max)),
        fixed_weight=jnp.asarray(
            _pad_tail(np.asarray(e.fixed_weight), 1, shape.e_max)),
    )

    # ELL incidence: the j-endpoint half [e_max, 2 e_max) moves with e_max.
    inc = np.asarray(g.inc_slot)
    inc = np.where(inc >= m.e_max, inc + de, inc)
    inc = _pad_tail(_pad_tail(inc, 2, shape.k_inc), 1, shape.n_max)
    inc_mask = _pad_tail(_pad_tail(np.asarray(g.inc_mask), 2, shape.k_inc),
                         1, shape.n_max)

    graph = rbcd.MultiAgentGraph(
        edges=edges,
        meas_id=jnp.asarray(_pad_tail(np.asarray(g.meas_id), 1, shape.e_max)),
        n=g.n,
        pose_mask=jnp.asarray(
            _pad_tail(np.asarray(g.pose_mask), 1, shape.n_max)),
        pub_idx=jnp.asarray(_pad_tail(np.asarray(g.pub_idx), 1, shape.p_max)),
        pub_mask=jnp.asarray(
            _pad_tail(np.asarray(g.pub_mask), 1, shape.p_max)),
        nbr_robot=jnp.asarray(
            _pad_tail(np.asarray(g.nbr_robot), 1, shape.s_max)),
        nbr_pub=jnp.asarray(_pad_tail(np.asarray(g.nbr_pub), 1, shape.s_max)),
        nbr_mask=jnp.asarray(
            _pad_tail(np.asarray(g.nbr_mask), 1, shape.s_max)),
        # Padded rows point at global pose 0 — masked out of the global
        # gather, and resolving to a valid Stiefel block on scatter (the
        # same convention build_graph uses for agents shorter than n_max).
        global_index=jnp.asarray(
            _pad_tail(np.asarray(g.global_index), 1, shape.n_max)),
        inc_slot=jnp.asarray(inc),
        inc_mask=jnp.asarray(inc_mask),
        color=g.color,
        eidx_i=None, eidx_j=None, rot_t=None, trn_t=None,
    )
    meta = padded_meta(prob, shape)
    edges_g = edge_set_from_measurements(
        prob.part.meas_global, pad_to=shape.num_meas, dtype=prob.dtype)

    if prob.X0 is not None:
        X0 = np.asarray(prob.X0)
        pad_rows = np.broadcast_to(
            X0[:, :1], (A, dn) + X0.shape[2:])
        X0 = jnp.asarray(np.concatenate([X0, pad_rows], axis=1))
    else:
        X0 = rbcd.lifted_init(edges_g, graph, meta, shape.n_total, init)
    return PaddedProblem(prob=prob, graph=graph, meta=meta,
                         edges_g=edges_g, X0=X0, shape=shape)
