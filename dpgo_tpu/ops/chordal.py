"""Chordal and odometry initialization.

TPU-native equivalent of reference ``chordalInitialization`` /
``recoverTranslations`` / ``odometryInitialization``
(``src/DPGO_utils.cpp:377-476``).  The reference solves two sparse
least-squares problems with SuiteSparse SPQR; there is no sparse QR on TPU,
so both solves become Jacobi-preconditioned conjugate gradients on the
normal equations, with the graph operators applied edge-wise
(gather / scatter-add) — the same technique as ``ops.quadratic``.

Stage 1 (rotations): minimize  sum_e kappa_e ||R_j - R_i Rtilde_e||_F^2
over unconstrained d x d blocks with R_0 = I pinned (the reference drops the
first block column of B3, ``DPGO_utils.cpp:390``), then project each block
to SO(d).

Stage 2 (translations): with rotations fixed, minimize
sum_e tau_e ||t_j - t_i - R_i ttilde_e||^2 with t_0 = 0 pinned.

Both systems are graph-Laplacian-like: SPD on the pinned subspace, diagonal
blocks = (weighted) vertex degrees, so Jacobi scaling is a natural
preconditioner.  This is init-only work; a few hundred CG iterations are
acceptable (SURVEY.md hard-part #6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..types import EdgeSet
from ..utils.lie import project_to_rotation


def _pin0(x: jax.Array) -> jax.Array:
    """Zero the slot-0 block (the pinned/anchored pose)."""
    return x.at[0].set(0.0)


def _cg(matvec, b, precond, maxiter: int, tol: float):
    """Standard preconditioned CG (jax.scipy's cg with explicit M)."""
    x, _ = jax.scipy.sparse.linalg.cg(matvec, b, M=precond, maxiter=maxiter, tol=tol)
    return x


@partial(jax.jit, static_argnames=("n", "maxiter", "tol"))
def chordal_rotations(edges: EdgeSet, n: int, maxiter: int = 2000,
                      tol: float = 1e-10) -> jax.Array:
    """Solve the chordal rotation relaxation; returns [n, d, d] in SO(d).

    Equivalent to the reference's B3 SPQR solve + per-block SO(d) projection
    (``DPGO_utils.cpp:388-410``).
    """
    d = edges.d
    dtype = edges.R.dtype
    wk = edges.mask * edges.weight * edges.kappa

    def residual_op(Rs):
        # A: [n, d, d] -> per-edge sqrt(kappa)-weighted residual (R fixed at
        # identity handled by caller via constant split).
        Ri = Rs[edges.i]
        Rj = Rs[edges.j]
        return Rj - jnp.einsum("eab,ebc->eac", Ri, edges.R)

    def residual_adjoint(res):
        # A^T: per-edge residuals -> per-vertex accumulation.
        out = jnp.zeros((n, d, d), dtype)
        contrib_j = wk[:, None, None] * res
        contrib_i = -jnp.einsum("eab,ecb->eac", wk[:, None, None] * res, edges.R)
        return out.at[edges.j].add(contrib_j).at[edges.i].add(contrib_i)

    def H(Rs):  # normal operator restricted to the pinned subspace
        return _pin0(residual_adjoint(residual_op(_pin0(Rs))))

    # Constant part: pose 0 fixed at identity.
    R_fixed = jnp.zeros((n, d, d), dtype).at[0].set(jnp.eye(d, dtype=dtype))
    b = _pin0(-residual_adjoint(residual_op(R_fixed)))

    # Jacobi preconditioner: weighted degree per vertex (diagonal blocks of
    # the rotation connection Laplacian are kappa-degree * I).
    deg = jnp.zeros((n,), dtype).at[edges.i].add(wk).at[edges.j].add(wk)
    deg = jnp.maximum(deg, 1e-12)

    def precond(Rs):
        return _pin0(Rs / deg[:, None, None])

    sol = _cg(H, b, precond, maxiter, tol)
    Rs = sol.at[0].set(jnp.eye(d, dtype=dtype))
    return project_to_rotation(Rs)


@partial(jax.jit, static_argnames=("n", "maxiter", "tol"))
def recover_translations(edges: EdgeSet, Rs: jax.Array, n: int,
                         maxiter: int = 2000, tol: float = 1e-10) -> jax.Array:
    """Least-squares translations given rotations; returns [n, d], t_0 = 0.

    Equivalent to the reference's B1/B2 SPQR solve
    (``recoverTranslations``, ``DPGO_utils.cpp:449-476``).
    """
    d = edges.d
    dtype = Rs.dtype
    wt = edges.mask * edges.weight * edges.tau

    def residual_op(ts):
        return ts[edges.j] - ts[edges.i]

    def residual_adjoint(res):
        out = jnp.zeros((n, d), dtype)
        wres = wt[:, None] * res
        return out.at[edges.j].add(wres).at[edges.i].add(-wres)

    def H(ts):
        return _pin0(residual_adjoint(residual_op(_pin0(ts))))

    # Constant: measured offsets R_i ttilde_e (and the pinned t_0 = 0).
    offs = jnp.einsum("eab,eb->ea", Rs[edges.i], edges.t)
    b = _pin0(residual_adjoint(offs))

    deg = jnp.zeros((n,), dtype).at[edges.i].add(wt).at[edges.j].add(wt)
    deg = jnp.maximum(deg, 1e-12)

    def precond(ts):
        return _pin0(ts / deg[:, None])

    return _cg(H, b, precond, maxiter, tol)


@partial(jax.jit, static_argnames=("n", "maxiter", "tol"))
def chordal_initialization(edges: EdgeSet, n: int, maxiter: int = 2000,
                           tol: float = 1e-10) -> jax.Array:
    """Full chordal init; returns T [n, d, d+1] = [R_i | t_i] per pose.

    Matches the output convention of reference ``chordalInitialization``
    (``DPGO_utils.cpp:377-424``), reshaped pose-major.
    """
    Rs = chordal_rotations(edges, n, maxiter, tol)
    ts = recover_translations(edges, Rs, n, maxiter, tol)
    return jnp.concatenate([Rs, ts[..., None]], axis=-1)


@partial(jax.jit, static_argnames=("n",))
def odometry_from_edges(edges: EdgeSet, n: int) -> jax.Array:
    """Select the odometry chain (k -> k+1) out of an arbitrary edge set and
    chain-propagate it; returns T [n, d, d+1].

    Robust to duplicates: among candidate edges with ``j == i + 1``, an edge
    flagged as odometry (``is_lc == 0``) wins over a consecutive loop
    closure, ties broken by edge order (scatter-min priority selection).  A
    pose with no incoming odometry edge gets an identity step — the chain
    continues rather than silently mis-pairing measurements.
    """
    E = edges.i.shape[0]
    d = edges.d
    dtype = edges.R.dtype
    cand = (edges.j == edges.i + 1) & (edges.mask > 0) & (edges.i < n - 1)
    big = jnp.asarray(2 * E + 1, jnp.int32)
    # priority = is_lc * E + edge_index: odometry-flagged first, then stable.
    prio = (edges.is_lc > 0).astype(jnp.int32) * E + jnp.arange(E, dtype=jnp.int32)
    prio = jnp.where(cand, prio, big)
    i_safe = jnp.where(cand, edges.i, 0)  # keep scatter indices in bounds
    best = jnp.full((n - 1,), big, jnp.int32).at[i_safe].min(prio)
    valid = best < big
    idx = jnp.where(valid, best % E, 0)
    eye = jnp.eye(d, dtype=dtype)
    R_odo = jnp.where(valid[:, None, None], edges.R[idx], eye)
    t_odo = jnp.where(valid[:, None], edges.t[idx], jnp.zeros(d, dtype))
    return odometry_initialization(R_odo, t_odo)


def odometry_initialization(R_odo: jax.Array, t_odo: jax.Array) -> jax.Array:
    """Chain-propagate odometry; returns T [n, d, d+1], pose 0 = identity.

    ``R_odo: [n-1, d, d]``, ``t_odo: [n-1, d]`` are measurements k -> k+1.
    Reference ``odometryInitialization`` (``DPGO_utils.cpp:426-447``), as an
    associative scan over SE(d) composition (log-depth on device instead of
    a sequential chain).
    """
    d = R_odo.shape[-1]
    dtype = R_odo.dtype
    eye = jnp.broadcast_to(jnp.eye(d, dtype=dtype), (1, d, d))
    zero = jnp.zeros((1, d), dtype)
    Rs = jnp.concatenate([eye, R_odo], axis=0)
    ts = jnp.concatenate([zero, t_odo], axis=0)

    def compose(a, b):
        # (Ra, ta) then relative (Rb, tb): R = Ra Rb, t = ta + Ra tb
        Ra, ta = a
        Rb, tb = b
        return Ra @ Rb, ta + jnp.einsum("...ab,...b->...a", Ra, tb)

    R_acc, t_acc = jax.lax.associative_scan(compose, (Rs, ts))
    return jnp.concatenate([R_acc, t_acc[..., None]], axis=-1)
