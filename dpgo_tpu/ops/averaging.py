"""Single-pose rotation / translation averaging, plain and robust (GNC-TLS).

TPU-native equivalent of reference ``src/DPGO_utils.cpp:533-726``.  The
reference loops over ``std::vector`` inputs and runs a data-dependent GNC
loop; here everything is batched (``[k, d, d]`` stacks) and the GNC loop is a
``lax.while_loop`` with masked convergence counting, so the robust variants
are jittable and vmappable (used per neighbor-pair in distributed
initialization, ``PGOAgent.cpp:290-331``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..config import RobustCostParams, RobustCostType
from ..utils.lie import project_to_rotation
from .. import robust

def _w_tol(dtype) -> float:
    """Weight convergence tolerance (reference 1e-8, DPGO_utils.cpp:585),
    widened to a few ulps of the compute dtype when that is coarser: in
    float32 the spacing around 1.0 is ~1.2e-7, so ``1.0 - 1e-8`` rounds to
    exactly 1.0 and ``w > 1.0 - 1e-8`` would hold for NO weight — GNC
    averaging would report zero inliers even on perfectly agreeing
    measurements (the TPU deployment precision)."""
    return max(1e-8, 32.0 * float(jnp.finfo(dtype).eps))


def single_translation_averaging(ts: jax.Array, tau: jax.Array | None = None,
                                 mask: jax.Array | None = None) -> jax.Array:
    """Weighted mean of translations ``ts [k, d]`` (reference ``DPGO_utils.cpp:533-550``)."""
    k = ts.shape[0]
    w = jnp.ones(k, ts.dtype) if tau is None else tau
    if mask is not None:
        w = w * mask
    # Guard the all-zero-weight case (e.g. GNC rejected every measurement):
    # return 0 instead of NaN; callers detect failure via an empty inlier set.
    return (w[:, None] * ts).sum(0) / jnp.maximum(w.sum(), 1e-30)


def single_rotation_averaging(Rs: jax.Array, kappa: jax.Array | None = None,
                              mask: jax.Array | None = None) -> jax.Array:
    """Project the weighted sum of ``Rs [k, d, d]`` onto SO(d)
    (reference ``DPGO_utils.cpp:552-566``).

    Degenerate all-zero-weight input (e.g. GNC rejected every
    measurement): the weighted sum is the zero matrix, whose SO(d)
    projection is a valid (arbitrary but finite and deterministic)
    rotation — never NaN.  Callers must detect the failure through the
    empty ``inlier_mask`` of the robust variants, not through the
    returned value (same contract as the 0-not-NaN translation
    average)."""
    k = Rs.shape[0]
    w = jnp.ones(k, Rs.dtype) if kappa is None else kappa
    if mask is not None:
        w = w * mask
    M = (w[:, None, None] * Rs).sum(0)
    return project_to_rotation(M)


def single_pose_averaging(Rs, ts, kappa=None, tau=None, mask=None):
    """Independent rotation + translation averaging (reference ``DPGO_utils.cpp:568-580``)."""
    return (
        single_rotation_averaging(Rs, kappa, mask),
        single_translation_averaging(ts, tau, mask),
    )


class RobustAveragingResult(NamedTuple):
    R: jax.Array  # [d, d] averaged rotation
    t: jax.Array  # [d] averaged translation (zeros for rotation-only)
    inlier_mask: jax.Array  # [k] bool, weight > 1 - tol (see _w_tol)
    weights: jax.Array  # [k] final GNC weights


def _gnc_averaging_loop(solve_fn, residual_sq_fn, init_sol, barc: float,
                        max_iters: int, weights0: jax.Array, mask: jax.Array):
    """Shared GNC-TLS loop for robust averaging.

    Mirrors the solve -> reweight -> anneal loop of reference
    ``robustSingleRotationAveraging`` (``DPGO_utils.cpp:582-644``):
    mu0 = min(barc^2 / (2 max rSq - barc^2), 1e-5); skip GNC entirely when
    mu0 <= 0 (all residuals already small); stop when every weight has
    converged to {0, 1}.
    """
    # A numpy scalar barc would silently promote float32 weights to float64
    # inside the while_loop carry (numpy scalars are strongly typed under
    # x64); a Python float is weakly typed and preserves the input dtype.
    barc = float(barc)
    barc_sq = barc * barc
    r_sq0 = residual_sq_fn(init_sol, weights0)
    max_r_sq = jnp.max(jnp.where(mask > 0, r_sq0, 0.0))
    mu_init = jnp.minimum(barc_sq / (2.0 * max_r_sq - barc_sq), 1e-5)
    params = RobustCostParams(cost_type=RobustCostType.GNC_TLS, gnc_barc=barc)

    tol = _w_tol(weights0.dtype)

    def converged(w):
        conv = (w < tol) | (w > 1.0 - tol)
        return jnp.all(conv | (mask <= 0))

    def cond(state):
        it, _, weights, _, done = state
        return (it < max_iters) & ~done

    def body(state):
        it, mu, weights, sol, _ = state
        sol = solve_fn(weights)
        r_sq = residual_sq_fn(sol, weights)
        w = robust.gnc_tls_weight(jnp.sqrt(r_sq), mu, barc) * mask
        done = converged(w)
        mu = robust.gnc_update_mu(mu, params)
        return it + 1, mu, w, sol, done

    def run_gnc(_):
        state = (jnp.array(0), mu_init.astype(r_sq0.dtype), weights0, init_sol, jnp.array(False))
        _, _, weights, sol, _ = jax.lax.while_loop(cond, body, state)
        return weights, sol

    def skip_gnc(_):
        return weights0, init_sol

    return jax.lax.cond(mu_init > 0, run_gnc, skip_gnc, operand=None)


def robust_single_rotation_averaging(
    Rs: jax.Array,
    kappa: jax.Array | None = None,
    error_threshold: float = 0.1,
    mask: jax.Array | None = None,
    max_iters: int = 1000,
) -> RobustAveragingResult:
    """GNC-TLS robust rotation averaging (reference ``DPGO_utils.cpp:582-644``).

    ``error_threshold`` is the chordal-distance barc (callers typically pass
    ``angular_to_chordal_so3(angle)``); residual^2 = kappa * ||R - R_i||_F^2.
    """
    k = Rs.shape[0]
    kappa_ = jnp.ones(k, Rs.dtype) if kappa is None else kappa
    mask_ = jnp.ones(k, Rs.dtype) if mask is None else mask.astype(Rs.dtype)

    def solve(w):
        return single_rotation_averaging(Rs, kappa_ * w, mask_)

    def residual_sq(R, _w):
        return kappa_ * jnp.sum((R[None] - Rs) ** 2, axis=(-2, -1))

    R0 = solve(jnp.ones(k, Rs.dtype))
    weights, R = _gnc_averaging_loop(solve, residual_sq, R0, error_threshold,
                                     max_iters, jnp.ones(k, Rs.dtype) * mask_, mask_)
    R = solve(weights)
    inliers = (weights > 1.0 - _w_tol(weights.dtype)) & (mask_ > 0)
    return RobustAveragingResult(R=R, t=jnp.zeros(Rs.shape[-1], Rs.dtype),
                                 inlier_mask=inliers, weights=weights)


def robust_single_pose_averaging(
    Rs: jax.Array,
    ts: jax.Array,
    kappa: jax.Array | None = None,
    tau: jax.Array | None = None,
    error_threshold: float = 0.1,
    mask: jax.Array | None = None,
    max_iters: int = 10000,
) -> RobustAveragingResult:
    """GNC-TLS robust SE(d) averaging (reference ``DPGO_utils.cpp:646-726``).

    Defaults kappa=1e4, tau=1e2 as in the reference; residual^2 =
    kappa ||R - R_i||^2 + tau ||t - t_i||^2.
    """
    k = Rs.shape[0]
    kappa_ = jnp.full(k, 1e4, Rs.dtype) if kappa is None else kappa
    tau_ = jnp.full(k, 1e2, Rs.dtype) if tau is None else tau
    mask_ = jnp.ones(k, Rs.dtype) if mask is None else mask.astype(Rs.dtype)

    def solve(w):
        R = single_rotation_averaging(Rs, kappa_ * w, mask_)
        t = single_translation_averaging(ts, tau_ * w, mask_)
        return R, t

    def residual_sq(sol, _w):
        R, t = sol
        return kappa_ * jnp.sum((R[None] - Rs) ** 2, axis=(-2, -1)) + \
            tau_ * jnp.sum((t[None] - ts) ** 2, axis=-1)

    sol0 = solve(jnp.ones(k, Rs.dtype))
    weights, sol = _gnc_averaging_loop(solve, residual_sq, sol0, error_threshold,
                                       max_iters, jnp.ones(k, Rs.dtype) * mask_, mask_)
    R, t = solve(weights)
    inliers = (weights > 1.0 - _w_tol(weights.dtype)) & (mask_ > 0)
    return RobustAveragingResult(R=R, t=t, inlier_mask=inliers, weights=weights)
