"""Closed-form batched kernels for the tiny fixed-size matrices of PGO.

Everything hot in this framework factors through matrices of static size
``d`` or ``d+1`` with d in {2, 3}: Stiefel blocks are ``r x d``, the
block-Jacobi preconditioner blocks are ``(d+1) x (d+1)``.  XLA lowers
``jnp.linalg.{svd,qr,cholesky}`` on TPU to generic iterative algorithms
(one-sided Jacobi SVD, blocked Householder QR, loop-based Cholesky) whose
latency on [N, 5, 4]-shaped batches dwarfs the surrounding math — profiled
at ~12 ms for a batched QR retraction on sphere2500/8 agents where the
whole gradient evaluation is ~1 ms.  These replacements unroll the fixed
dimension entirely: the polar factor via Newton–Schulz iterations (pure
d x d matmuls, MXU/VPU-friendly, quadratic convergence) and the Cholesky /
triangular solves via explicit scalar formulas on the last two axes.

The reference leans on Eigen/LAPACK for the same operations
(``projectToStiefelManifold``, ``DPGO_utils.cpp:494-500``; CHOLMOD
factorization, ``QuadraticProblem.cpp:31-42``) — dense LAPACK on tiny
matrices is cheap on CPU, which is why this divergence is TPU-specific
design rather than translation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _eye_like(A: jax.Array) -> jax.Array:
    return jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)


def polar_orthonormalize(M: jax.Array, num_iters: int = 40) -> jax.Array:
    """Closest (Frobenius) orthonormal-columns factor of ``M [..., r, d]``:
    the polar factor ``U = M (M^T M)^{-1/2}``.

    Computed with the coupled Newton–Schulz iteration for the inverse
    square root of ``A = M^T M`` (d x d, symmetric PD):

        Y_0 = A / s,  Z_0 = I,
        T_k = (3 I - Z_k Y_k) / 2,   Y_{k+1} = Y_k T_k,  Z_{k+1} = T_k Z_k,
        Z_k -> (A / s)^{-1/2}

    with ``s = tr(A)`` so the normalized spectrum lies in (0, 1], the
    iteration's convergence region.  The smallest normalized eigenvalue
    grows by ~2.25x per sweep until the quadratic phase kicks in, so
    ``num_iters = 40`` covers condition(M) up to ~1e5-1e6 in float64
    (validated against SVD in tests/test_smallmat.py); beyond that the
    fixed-sweep iteration degrades — callers with potentially
    rank-deficient inputs should use ``lie.project_to_stiefel_svd``.  The
    hot-path arguments (retraction points ``Y + tangent``, Nesterov
    combinations of on-manifold points) stay far inside the ceiling.
    All work is d x d matmuls — no SVD/QR, no data-dependent control flow.

    For exactly rank-deficient ``M`` the polar factor is not unique and
    this returns a non-orthonormal limit, exactly like the SVD-based
    ``U V^T`` which is what the reference uses
    (``projectToStiefelManifold``, ``DPGO_utils.cpp:494-500``); optimization
    iterates stay well-conditioned (retraction arguments are
    ``Y + tangent``).
    """
    d = M.shape[-1]
    A = jnp.swapaxes(M, -1, -2) @ M
    s = jnp.trace(A, axis1=-2, axis2=-1)[..., None, None]
    s = jnp.maximum(s, jnp.finfo(M.dtype).tiny)
    An = A / s

    # The iteration runs in component-major form [d, d, batch...]: a d x d
    # matmul over a [..., d, d] batch would use d of the TPU's 128 lanes,
    # while the same arithmetic unrolled over the d^2 components (batch in
    # the minor axis -> lanes) is fully lane-parallel elementwise work.
    # The sweep itself is a fori_loop so the unrolled body (~2 d^3 fmas)
    # compiles once, not num_iters times — a Python-unrolled version sits
    # inside the RTR rejection while_loop and multiplies XLA compile time
    # by the iteration count.
    Yc = jnp.moveaxis(jnp.moveaxis(An, -1, 0), -1, 0)  # [d, d, ...] (j, i)
    Yc = jnp.swapaxes(Yc, 0, 1)                        # [d(i), d(j), ...]
    eye = jnp.zeros_like(Yc).at[jnp.arange(d), jnp.arange(d)].set(1.0)

    def matmul(P, Q):
        rows = [[sum(P[i, k] * Q[k, j] for k in range(d)) for j in range(d)]
                for i in range(d)]
        return jnp.stack([jnp.stack(r, axis=0) for r in rows], axis=0)

    def sweep(_, YZ):
        Y, Z = YZ
        T = 0.5 * (3.0 * eye - matmul(Z, Y))
        return matmul(Y, T), matmul(T, Z)

    _, Zc = jax.lax.fori_loop(0, num_iters, sweep, (Yc, eye))

    # Zc approx (A/s)^{-1/2}  =>  A^{-1/2} = Z / sqrt(s)
    Zm = jnp.moveaxis(jnp.moveaxis(Zc, 0, -1), 0, -1)  # [..., d(j), d(i)]
    Zm = jnp.swapaxes(Zm, -1, -2)
    return M @ (Zm / jnp.sqrt(s))


def cholesky_small(A: jax.Array) -> jax.Array:
    """Lower Cholesky factor of SPD ``A [..., k, k]`` for small static k,
    fully unrolled (k^3/6 scalar ops on the batch, no loops on device)."""
    k = A.shape[-1]
    eps = jnp.finfo(A.dtype).tiny
    cols = [[None] * k for _ in range(k)]
    for j in range(k):
        s = A[..., j, j]
        for p in range(j):
            s = s - cols[j][p] * cols[j][p]
        diag = jnp.sqrt(jnp.maximum(s, eps))
        cols[j][j] = diag
        for i in range(j + 1, k):
            s = A[..., i, j]
            for p in range(j):
                s = s - cols[i][p] * cols[j][p]
            cols[i][j] = s / diag
    rows = [jnp.stack([cols[i][j] if j <= i else jnp.zeros_like(A[..., 0, 0])
                       for j in range(k)], axis=-1)
            for i in range(k)]
    return jnp.stack(rows, axis=-2)


def cho_solve_small(L: jax.Array, B: jax.Array) -> jax.Array:
    """Solve ``A X = B`` given the small unrolled Cholesky ``L`` of ``A``.

    ``L: [..., k, k]`` lower, ``B: [..., k, m]``; forward/back substitution
    unrolled over the static k."""
    k = L.shape[-1]
    # Forward: L y = B
    y = [None] * k
    for i in range(k):
        s = B[..., i, :]
        for p in range(i):
            s = s - L[..., i, p, None] * y[p]
        y[i] = s / L[..., i, i, None]
    # Backward: L^T x = y
    x = [None] * k
    for i in reversed(range(k)):
        s = y[i]
        for p in range(i + 1, k):
            s = s - L[..., p, i, None] * x[p]
        x[i] = s / L[..., i, i, None]
    return jnp.stack(x, axis=-2)
