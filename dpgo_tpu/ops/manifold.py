"""The lifted SE(d) product manifold (St(r, d) x R^r)^n, as pure batched ops.

TPU-native replacement for the reference's ROPTLIB wrapper layer
(``src/manifold/LiftedSEManifold.cpp``, ``LiftedSEVariable.cpp``,
``LiftedSEVector.cpp``) and for ROPTLIB's Stiefel geometry (tangent
projection, retraction, Riemannian-Hessian conversion).  A point is stored
as ``X: [..., n, r, d+1]`` where each pose block is ``[Y_i | p_i]`` with
``Y_i in St(r, d)`` (lifted rotation) and ``p_i in R^r`` (lifted
translation).  The reference's per-pose OpenMP loop
(``LiftedSEManifold.cpp:40-44``) becomes a single batched SVD.

All functions treat the last three axes as ``(n, r, d+1)`` and broadcast
over any leading batch axes (vmap over agents is free).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.lie import project_to_stiefel


def sym(A: jax.Array) -> jax.Array:
    """Symmetric part, 0.5 (A + A^T), over the last two axes."""
    return 0.5 * (A + jnp.swapaxes(A, -1, -2))


def split(X: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Split pose blocks [..., r, d+1] into (Y [..., r, d], p [..., r])."""
    return X[..., :-1], X[..., -1]


def join(Y: jax.Array, p: jax.Array) -> jax.Array:
    return jnp.concatenate([Y, p[..., None]], axis=-1)


def project(X: jax.Array) -> jax.Array:
    """Project an ambient matrix onto the manifold: per-pose Stiefel
    projection of the Y factor, Euclidean part untouched.

    Equivalent of ``LiftedSEManifold::project`` (reference
    ``LiftedSEManifold.cpp:34-45``), used by Nesterov's Y/V updates.
    """
    Y, p = split(X)
    return join(project_to_stiefel(Y), p)


def tangent_project(X: jax.Array, V: jax.Array) -> jax.Array:
    """Project ambient ``V`` onto the tangent space at ``X``.

    Stiefel factor: ``P_Y(W) = W - Y sym(Y^T W)`` (embedded metric);
    Euclidean factor: identity.  Replaces ROPTLIB's
    ``Manifold::Projection`` used at reference ``QuadraticProblem.cpp:82,95``.
    """
    Y, p = split(X)
    W, w = split(V)
    W = W - Y @ sym(jnp.swapaxes(Y, -1, -2) @ W)
    return join(W, w)


def retract(X: jax.Array, V: jax.Array) -> jax.Array:
    """Polar retraction: R_X(V) = qf_polar(Y + V_Y) for the Stiefel factor,
    plain addition for the Euclidean factor.

    ROPTLIB's Stiefel uses a QR retraction by default; the polar retraction
    (SVD) is second-order and maps better to TPU (one batched SVD of tiny
    ``r x d`` blocks instead of column-sequential Householder QR).
    """
    Y, p = split(X)
    W, w = split(V)
    return join(project_to_stiefel(Y + W), p + w)


def inner(U: jax.Array, V: jax.Array) -> jax.Array:
    """Euclidean inner product over the trailing (n, r, d+1) axes."""
    return jnp.sum(U * V, axis=(-3, -2, -1))


def norm(U: jax.Array) -> jax.Array:
    return jnp.sqrt(inner(U, U))


def ehess_to_rhess(X: jax.Array, egrad: jax.Array, ehess_v: jax.Array,
                   V: jax.Array) -> jax.Array:
    """Euclidean Hessian-vector -> Riemannian Hessian-vector at ``X``.

    Standard embedded-Stiefel formula (what ROPTLIB's ``EucHvToHv`` computes
    for the product manifold): per pose block,

        Hess f[V] = P_X( EucHess[V] - [ V_Y sym(Y^T G_Y) | 0 ] )

    with ``G`` the Euclidean gradient.  The Euclidean factor has no
    curvature correction.
    """
    Y, _ = split(X)
    G_Y, _ = split(egrad)
    V_Y, _ = split(V)
    corr_Y = V_Y @ sym(jnp.swapaxes(Y, -1, -2) @ G_Y)
    corr = join(corr_Y, jnp.zeros(V.shape[:-1], V.dtype))
    return tangent_project(X, ehess_v - corr)


def rgrad(X: jax.Array, egrad: jax.Array) -> jax.Array:
    """Riemannian gradient = tangent projection of the Euclidean gradient
    (reference ``QuadraticProblem::RieGrad``, ``QuadraticProblem.cpp:89-97``)."""
    return tangent_project(X, egrad)
