"""Double-float32 (two-float) arithmetic for on-device f64-grade scalars.

TPU v5e has no hardware f64, and the tunneled-TPU process cannot enable
x64 even for host math (bench.py).  The round-4 certified-gap pipeline
therefore kept its f64 work — manifold projection, recentered-gradient
constants, the gap oracle — on the HOST, paying a fixed ~90 ms tunnel
round-trip per device<->host handoff (two per certified run, ~47% of the
wall clock, BASELINE.md).  This module provides the arithmetic that moves
that work ON TO the device: every value is an unevaluated sum ``hi + lo``
of two f32s (a "double-f32"), giving ~49 mantissa bits — measured
1e-13-relative add/mul/dot accuracy on the actual TPU backend
(``experiments/df32_spike.py``), far beyond the ~1e-9 the recentered
refinement needs.

The primitives are the classical error-free transforms:

* ``two_sum`` (Knuth 1969): a + b = s + e exactly, 6 flops, no branches;
* ``two_prod`` via Dekker's split (2^12 + 1 for the 24-bit f32 mantissa):
  a * b = p + e exactly provided the compiler neither reassociates nor
  contracts ``a * b - p`` into an fma with different rounding.  XLA's
  default semantics preserve both (verified empirically by the spike and
  pinned by ``tests/test_df32.py`` on every backend the suite runs on).

Values travel as ``DF(hi, lo)`` pairs of same-shape arrays (a pytree), so
whole tensors run in df32 with vectorized elementwise ops.  Reductions
use pairwise folding (``fold_sum``) — O(log n) sequential df-adds of
vectorized halves, cheap on the VPU.

The reference framework never needed any of this: it runs f64 end-to-end
on CPU (Eigen/ROPTLIB, e.g. ``CartanSyncVariable.cpp``); this module is
what makes the equivalent precision reachable on f32 accelerator
hardware without leaving the device.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DF(NamedTuple):
    """A double-f32 value: the unevaluated exact sum ``hi + lo`` with
    ``|lo| <= ulp(hi)/2`` (after renormalization)."""

    hi: jax.Array
    lo: jax.Array


_SPLIT = np.float32(4097.0)  # 2^12 + 1: Dekker split constant for f32


def _opaque(x):
    """Hide a value's defining expression from XLA's algebraic simplifier.

    Error-free transforms compute expressions like ``(a + b) - a`` whose
    VALUE is the rounding error — exactly the quantity an algebraic
    simplifier is licensed to cancel to ``b`` under real-number axioms.
    XLA leaves the straight-line f32 versions alone, but pattern-matched
    rewrites (observed: the broadcast-slice mul-add chain of a small
    matmul on XLA:CPU gets turned into a ``dot``) re-associate through
    them and collapse the error terms to zero, silently degrading df32
    to f32 (caught by ``tests/test_df32.py``).  An optimization_barrier
    on the primary result before the error-term computation makes the
    cancellation invisible to the simplifier at the cost of one no-op
    in the schedule."""
    return jax.lax.optimization_barrier(x)


def two_sum(a, b):
    """Error-free sum: returns (s, e) with a + b == s + e exactly."""
    s = _opaque(a + b)
    bb = _opaque(s - a)
    e = (a - _opaque(s - bb)) + (b - bb)
    return s, e


def quick_two_sum(a, b):
    """Error-free sum assuming |a| >= |b| (3 flops)."""
    s = _opaque(a + b)
    return s, b - _opaque(s - a)


def _split(a):
    c = _SPLIT * a
    hi = _opaque(c - _opaque(c - a))
    return hi, a - hi


def two_prod(a, b):
    """Error-free product: returns (p, e) with a * b == p + e exactly."""
    p = _opaque(a * b)
    ah, al = _split(a)
    bh, bl = _split(b)
    e = (_opaque(ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


# ---------------------------------------------------------------------------
# Construction / destruction
# ---------------------------------------------------------------------------

def from_f32(x) -> DF:
    x = jnp.asarray(x, jnp.float32)
    return DF(x, jnp.zeros_like(x))


def from_f64(x64) -> DF:
    """HOST-side split of a numpy f64 array into an exact df32 pair
    (|x| < ~1e31 so the lo part cannot underflow to zero significance)."""
    x64 = np.asarray(x64, np.float64)
    hi = x64.astype(np.float32)
    lo = (x64 - hi.astype(np.float64)).astype(np.float32)
    return DF(jnp.asarray(hi), jnp.asarray(lo))


def to_f64(x: DF) -> np.ndarray:
    """HOST-side exact reconstruction (for verification paths)."""
    return (np.asarray(x.hi, np.float64) + np.asarray(x.lo, np.float64))


# ---------------------------------------------------------------------------
# Arithmetic (all elementwise, broadcasting like jnp)
# ---------------------------------------------------------------------------

def add(x: DF, y: DF) -> DF:
    s, e = two_sum(x.hi, y.hi)
    e = e + (x.lo + y.lo)
    return DF(*quick_two_sum(s, e))


def add_f(x: DF, y) -> DF:
    s, e = two_sum(x.hi, y)
    e = e + x.lo
    return DF(*quick_two_sum(s, e))


def neg(x: DF) -> DF:
    return DF(-x.hi, -x.lo)


def sub(x: DF, y: DF) -> DF:
    return add(x, neg(y))


def mul(x: DF, y: DF) -> DF:
    p, e = two_prod(x.hi, y.hi)
    e = e + (x.hi * y.lo + x.lo * y.hi)
    return DF(*quick_two_sum(p, e))


def mul_f(x: DF, y) -> DF:
    p, e = two_prod(x.hi, y)
    e = e + x.lo * y
    return DF(*quick_two_sum(p, e))


def scale(x: DF, c: float) -> DF:
    """Multiply by an exactly-representable f32 scalar (e.g. 0.5, -1, 2)."""
    c = jnp.float32(c)
    return DF(x.hi * c, x.lo * c)


def div(x: DF, y: DF) -> DF:
    """Quotient via one Newton correction of the f32 estimate —
    relative error ~2^-45, plenty for the tolerance scalars it serves."""
    q1 = x.hi / y.hi
    r = add(x, neg(mul_f(y, q1)))  # x - y*q1, exact to df32
    q2 = r.hi / y.hi
    return DF(*quick_two_sum(q1, q2))


def sqrt(x: DF) -> DF:
    """Square root via one Newton correction of the f32 estimate."""
    s1 = jnp.sqrt(x.hi)
    p, e = two_prod(s1, s1)  # s1^2 exactly, as a df pair
    r = add(x, DF(-p, -e))
    s2 = r.hi / (2.0 * s1)
    return DF(*quick_two_sum(s1, s2))


# ---------------------------------------------------------------------------
# Reductions / contractions
# ---------------------------------------------------------------------------

def fold_sum(x: DF, axis: int = -1) -> DF:
    """Pairwise (tree) df32 sum along ``axis``: O(log n) sequential
    vectorized df-adds.  Error ~ eps_df * log2(n) * sum|terms|."""
    hi = jnp.moveaxis(x.hi, axis, -1)
    lo = jnp.moveaxis(x.lo, axis, -1)
    n = hi.shape[-1]
    m = 1 << max(0, (n - 1)).bit_length()  # next power of two
    if m != n:
        pad = [(0, 0)] * (hi.ndim - 1) + [(0, m - n)]
        hi, lo = jnp.pad(hi, pad), jnp.pad(lo, pad)
    cur = DF(hi, lo)
    while cur.hi.shape[-1] > 1:
        half = cur.hi.shape[-1] // 2
        cur = add(DF(cur.hi[..., :half], cur.lo[..., :half]),
                  DF(cur.hi[..., half:], cur.lo[..., half:]))
    return DF(cur.hi[..., 0], cur.lo[..., 0])


def dot(x: DF, y: DF, axis: int = -1) -> DF:
    """df32 inner product along ``axis`` (pairwise-folded)."""
    return fold_sum(mul(x, y), axis=axis)


def matmul_small(x: DF, y: DF) -> DF:
    """Batched matmul ``[..., m, k] @ [..., k, n]`` with the contraction
    UNROLLED over k (static, small — pose-graph dims d, d+1, r).  Stays
    on the VPU in df32; never touches the MXU (whose f32 is not exact)."""
    k = x.hi.shape[-1]
    assert y.hi.shape[-2] == k
    acc = None
    for t in range(k):
        term = mul(DF(x.hi[..., :, t, None], x.lo[..., :, t, None]),
                   DF(y.hi[..., None, t, :], y.lo[..., None, t, :]))
        acc = term if acc is None else add(acc, term)
    return acc


def transpose(x: DF, axes=None) -> DF:
    return DF(jnp.transpose(x.hi, axes), jnp.transpose(x.lo, axes))


def index(x: DF, idx) -> DF:
    """Exact gather (indexing applies to both components)."""
    return DF(x.hi[idx], x.lo[idx])


def sym(x: DF) -> DF:
    """0.5 * (M + M^T) on the last two axes (exact halving in f32)."""
    xt = DF(jnp.swapaxes(x.hi, -1, -2), jnp.swapaxes(x.lo, -1, -2))
    return scale(add(x, xt), 0.5)


def precise_jit(fn, **jit_kw):
    """``jax.jit`` for df32-heavy functions.

    On the CPU backend, LLVM's optimizer re-associates the error-free
    transforms even through HLO optimization barriers (instruction-level
    fast-math flags; measured: ``quick_two_sum`` loses its defining
    property s + lo == a + b and df32 collapses to f32 accuracy).  TPU's
    Mosaic/VPU path is unaffected (measured exact by
    ``experiments/df32_spike.py``).  Compiling the df32 sections at
    backend optimization level 0 on CPU restores correctness; these
    functions run once per recenter, so the CPU-side slowdown only
    affects tests."""
    if jax.default_backend() == "cpu":
        jit_kw.setdefault("compiler_options",
                          {"xla_backend_optimization_level": 0})
    return jax.jit(fn, **jit_kw)
