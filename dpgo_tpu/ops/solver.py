"""Riemannian trust-region (RTR) with Steihaug truncated CG, and RGD.

TPU-native replacement for ROPTLIB's ``RTRNewton`` / ``RSD`` as driven by the
reference's ``QuadraticOptimizer`` (``src/QuadraticOptimizer.cpp``).  The
solver is generic over a problem expressed as closures (cost / Euclidean
gradient / Euclidean Hessian-vector / preconditioner), with all control flow
as ``lax.while_loop`` so the entire optimization — including the
shrink-radius-until-accepted retry of the reference's single-step mode
(``QuadraticOptimizer.cpp:92-110``) — compiles to one XLA program and can be
vmapped over agents.

Semantics matched to the reference configuration:
* tCG stop: negative curvature, trust-region boundary, max inner iterations,
  or ``||r|| <= ||r0|| min(kappa, ||r0||^theta)`` (ROPTLIB defaults
  kappa=0.1, theta=1).
* Single-step mode: one outer iteration at a fixed radius; on rejection the
  radius shrinks by 4, up to ``max_rejections`` tries, else the input is
  returned unchanged.
* Full solve: classic radius adaptation (shrink x0.25 when rho < 0.25, grow
  x2 up to ``max_radius`` when rho > 0.75 at the boundary), stop on
  gradient-norm tolerance or ``max_outer_iters``.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from ..config import SolverParams
from . import manifold


class Problem(NamedTuple):
    """A Riemannian quadratic-like problem as pure closures.

    cost(X) -> scalar; egrad(X) -> ambient gradient; ehess(X, V) -> ambient
    Hessian-vector (V a tangent at X, constant blocks excluded);
    precond(X, V) -> preconditioned tangent vector.
    """

    cost: Callable[[jax.Array], jax.Array]
    egrad: Callable[[jax.Array], jax.Array]
    ehess: Callable[[jax.Array, jax.Array], jax.Array]
    precond: Callable[[jax.Array, jax.Array], jax.Array]


def identity_precond(X, V):
    return V


class TCGResult(NamedTuple):
    eta: jax.Array
    heta: jax.Array  # Hessian applied to eta (for the model value)
    iters: jax.Array
    hit_boundary: jax.Array


def truncated_cg(
    X: jax.Array,
    grad: jax.Array,
    hvp: Callable[[jax.Array], jax.Array],
    precond: Callable[[jax.Array], jax.Array],
    radius: jax.Array,
    max_iters: int,
    kappa: float = 0.1,
    theta: float = 1.0,
) -> TCGResult:
    """Preconditioned Steihaug-Toint truncated CG on the tangent space at X.

    Solves ``min_eta <grad, eta> + 0.5 <eta, H eta>`` s.t. ``||eta|| <= radius``
    (Euclidean trust-region norm).  Replaces the tCG inside ROPTLIB's
    ``RTRNewton`` (the hot inner loop of reference ``QuadraticOptimizer.cpp:76-90``).
    """
    dtype = grad.dtype
    eps = jnp.asarray(1e-30, dtype)

    r0 = grad
    z0 = precond(r0)
    delta0 = -z0
    rz0 = manifold.inner(r0, z0)
    r0_norm = manifold.norm(r0)
    target = r0_norm * jnp.minimum(kappa, r0_norm**theta)

    zero = jnp.zeros_like(grad)

    # State: (k, eta, Heta, r, z, delta, rz, done, hit_boundary)
    def cond(s):
        k, _, _, _, _, _, _, done, _ = s
        return (k < max_iters) & ~done

    def body(s):
        k, eta, Heta, r, z, delta, rz, done, hit = s
        Hd = hvp(delta)
        d_Hd = manifold.inner(delta, Hd)
        alpha = rz / jnp.where(jnp.abs(d_Hd) < eps, eps, d_Hd)

        e_e = manifold.inner(eta, eta)
        e_d = manifold.inner(eta, delta)
        d_d = manifold.inner(delta, delta)
        e_e_next = e_e + 2.0 * alpha * e_d + alpha * alpha * d_d

        crossing = (d_Hd <= 0) | (e_e_next >= radius * radius)
        # tau >= 0 with ||eta + tau delta|| = radius
        disc = jnp.maximum(e_d * e_d + d_d * (radius * radius - e_e), 0.0)
        tau = (-e_d + jnp.sqrt(disc)) / jnp.where(d_d < eps, eps, d_d)
        eta_b = eta + tau * delta
        Heta_b = Heta + tau * Hd

        eta_in = eta + alpha * delta
        Heta_in = Heta + alpha * Hd
        r_in = r + alpha * Hd
        z_in = precond(r_in)
        rz_in = manifold.inner(r_in, z_in)
        converged = manifold.norm(r_in) <= target
        beta = rz_in / jnp.where(jnp.abs(rz) < eps, eps, rz)
        delta_in = -z_in + beta * delta

        eta_n = jnp.where(crossing, eta_b, eta_in)
        Heta_n = jnp.where(crossing, Heta_b, Heta_in)
        done_n = crossing | converged
        hit_n = hit | crossing
        return (k + 1, eta_n, Heta_n, r_in, z_in, delta_in, rz_in, done_n, hit_n)

    init = (
        jnp.array(0, jnp.int32), zero, zero, r0, z0, delta0, rz0,
        rz0 <= 0,  # degenerate: zero/NaN gradient
        jnp.array(False),
    )
    k, eta, Heta, *_ , hit = jax.lax.while_loop(cond, body, init)
    return TCGResult(eta=eta, heta=Heta, iters=k, hit_boundary=hit)


class RTRState(NamedTuple):
    X: jax.Array
    radius: jax.Array
    f: jax.Array
    grad_norm: jax.Array
    grad_norm_init: jax.Array  # gradient norm at the starting point
    iters: jax.Array
    accepted: jax.Array  # was the last proposed step accepted?
    done: jax.Array


def _rtr_attempt(problem: Problem, X, fX, g, eg, radius, params: SolverParams,
                 tcg_fn=None):
    """One tCG solve + acceptance test at the given radius.

    ``g`` is the Riemannian gradient, ``eg`` the Euclidean gradient at X.
    ``tcg_fn(X, g, eg, radius) -> TCGResult`` overrides the inner solver
    (the Pallas VMEM-resident kernel, ``ops.pallas_tcg``).
    Returns (X_new, f_new, accepted, hit_boundary, rho).
    """
    if tcg_fn is not None:
        res = tcg_fn(X, g, eg, radius)
    else:
        hvp = lambda V: manifold.ehess_to_rhess(X, eg, problem.ehess(X, V), V)
        pre = lambda V: manifold.tangent_project(X, problem.precond(X, V))
        res = truncated_cg(X, g, hvp, pre, radius, params.max_inner_iters,
                           params.tcg_kappa, params.tcg_theta)
    X_prop = manifold.retract(X, res.eta)
    f_prop = problem.cost(X_prop)
    model_decrease = -(manifold.inner(g, res.eta) + 0.5 * manifold.inner(res.eta, res.heta))
    eps = jnp.asarray(1e-30, fX.dtype)
    rho = (fX - f_prop) / jnp.maximum(model_decrease, eps)
    accept = (rho > 0.1) & (f_prop <= fX)
    X_new = jnp.where(accept, X_prop, X)
    f_new = jnp.where(accept, f_prop, fX)
    return X_new, f_new, accept, res.hit_boundary, rho


def rtr_solve(problem: Problem, X0: jax.Array, params: SolverParams,
              max_iters: int | None = None,
              grad_norm_tol: float | None = None) -> RTRState:
    """Full RTR loop (centralized solves; reference ``trustRegion`` with
    Max_Iteration > 1, ``QuadraticOptimizer.cpp:61-116``)."""
    max_iters = params.max_outer_iters if max_iters is None else max_iters
    gtol = params.grad_norm_tol if grad_norm_tol is None else grad_norm_tol
    max_radius = 5.0 * params.initial_radius  # QuadraticOptimizer.cpp:81

    f0 = problem.cost(X0)
    eg0 = problem.egrad(X0)
    g0 = manifold.rgrad(X0, eg0)
    gn0 = manifold.norm(g0)

    # The Euclidean gradient is the dominant per-iteration kernel; carry
    # (eg, g) in the loop state so each X is evaluated exactly once.
    def cond(s):
        rtr, eg, g = s
        return (rtr.iters < max_iters) & ~rtr.done

    def body(s):
        rtr, eg, g = s
        X_new, f_new, accept, hit, rho = _rtr_attempt(
            problem, rtr.X, rtr.f, g, eg, rtr.radius, params)
        radius = jnp.where(
            rho < 0.25, rtr.radius * 0.25,
            jnp.where((rho > 0.75) & hit, jnp.minimum(2.0 * rtr.radius, max_radius),
                      rtr.radius))
        eg_new = problem.egrad(X_new)
        g_new = manifold.rgrad(X_new, eg_new)
        gn = manifold.norm(g_new)
        return (RTRState(X=X_new, radius=radius, f=f_new, grad_norm=gn,
                         grad_norm_init=rtr.grad_norm_init,
                         iters=rtr.iters + 1, accepted=accept, done=gn < gtol),
                eg_new, g_new)

    init = (RTRState(X=X0, radius=jnp.asarray(params.initial_radius, X0.dtype),
                     f=f0, grad_norm=gn0, grad_norm_init=gn0,
                     iters=jnp.array(0, jnp.int32),
                     accepted=jnp.array(False), done=gn0 < gtol),
            eg0, g0)
    out, _, _ = jax.lax.while_loop(cond, body, init)
    return out


def rtr_single_step(problem: Problem, X0: jax.Array,
                    params: SolverParams, tcg_fn=None,
                    final_grad_norm: bool = True) -> RTRState:
    """The RBCD per-iteration local update: one accepted RTR step.

    Mirrors the reference's Max_Iteration == 1 path
    (``QuadraticOptimizer.cpp:92-110``): try a step at the current radius; on
    rejection shrink the radius by 4 and retry, at most ``max_rejections``
    times, else return the input unchanged.  Early-exits (identity) when the
    gradient norm is already below ``grad_norm_tol``
    (``QuadraticOptimizer.cpp:65-69``).
    """
    f0 = problem.cost(X0)
    eg = problem.egrad(X0)
    g = manifold.rgrad(X0, eg)
    gn0 = manifold.norm(g)
    below_tol = gn0 < params.grad_norm_tol

    def cond(s: RTRState):
        return (s.iters < params.max_rejections) & ~s.done

    def body(s: RTRState):
        X_new, f_new, accept, _, _ = _rtr_attempt(problem, s.X, s.f, g, eg,
                                                  s.radius, params, tcg_fn)
        return RTRState(X=X_new, radius=jnp.where(accept, s.radius, s.radius / 4.0),
                        f=f_new, grad_norm=s.grad_norm, grad_norm_init=s.grad_norm_init,
                        iters=s.iters + 1, accepted=accept, done=accept)

    init = RTRState(X=X0, radius=jnp.asarray(params.initial_radius, X0.dtype),
                    f=f0, grad_norm=gn0, grad_norm_init=gn0,
                    iters=jnp.array(0, jnp.int32),
                    accepted=jnp.array(False), done=below_tol)
    out = jax.lax.while_loop(cond, body, init)
    if not final_grad_norm:
        # Skip the post-step gradient evaluation (a full egrad whose only
        # consumer is status reporting; the RBCD round never reads it —
        # greedy selection uses grad_norm_init).
        return out
    # Recompute the gradient norm at the final point for status reporting.
    gn1 = manifold.norm(manifold.rgrad(out.X, problem.egrad(out.X)))
    return out._replace(grad_norm=gn1)


def rgd_step(problem: Problem, X0: jax.Array, stepsize: float) -> jax.Array:
    """One fixed-step Riemannian gradient descent step (reference
    ``gradientDescent``, ``QuadraticOptimizer.cpp:124-149``: project, scale
    by -stepsize, retract; preconditioning deliberately off)."""
    g = manifold.rgrad(X0, problem.egrad(X0))
    return manifold.retract(X0, -stepsize * g)


def rgd_linesearch(problem: Problem, X0: jax.Array, max_iters: int = 10,
                   grad_norm_tol: float = 1e-2, initial_step: float = 1.0,
                   backtrack: float = 0.5, armijo: float = 1e-4,
                   max_backtracks: int = 25):
    """Armijo line-search Riemannian steepest descent.

    Replaces ROPTLIB's RSD as used by ``gradientDescentLS``
    (``QuadraticOptimizer.cpp:151-172``).
    """

    def cond(s):
        X, f, g, gn, k = s
        return (k < max_iters) & (gn >= grad_norm_tol)

    def body(s):
        X, f, g, gn, k = s
        gsq = manifold.inner(g, g)

        def ls_cond(ls):
            step, f_new, j, ok = ls
            return (j < max_backtracks) & ~ok

        def ls_body(ls):
            step, _, j, _ = ls
            X_try = manifold.retract(X, -step * g)
            f_try = problem.cost(X_try)
            ok = f_try <= f - armijo * step * gsq
            return (jnp.where(ok, step, step * backtrack), f_try, j + 1, ok)

        step0 = jnp.asarray(initial_step, X.dtype)
        step, _, _, _ = jax.lax.while_loop(
            ls_cond, ls_body, (step0, f, jnp.array(0, jnp.int32), jnp.array(False)))
        X_new = manifold.retract(X, -step * g)
        f_new = problem.cost(X_new)
        keep = f_new <= f
        X_new = jnp.where(keep, X_new, X)
        f_new = jnp.where(keep, f_new, f)
        g_new = manifold.rgrad(X_new, problem.egrad(X_new))
        return (X_new, f_new, g_new, manifold.norm(g_new), k + 1)

    f0 = problem.cost(X0)
    g0 = manifold.rgrad(X0, problem.egrad(X0))
    X, f, g, gn, _ = jax.lax.while_loop(
        cond, body, (X0, f0, g0, manifold.norm(g0), jnp.array(0, jnp.int32)))
    return X
