"""Pallas TPU kernel: the whole preconditioned truncated-CG trust-region
subproblem for one agent, resident in VMEM.

This is the framework's hot loop — the replacement for ROPTLIB's
``RTRNewton`` inner iteration (reference ``QuadraticOptimizer.cpp:76-90``)
one level deeper than ``ops.solver.truncated_cg``: the XLA formulation runs
each tCG iteration as a chain of ~30 small kernels (gathers, per-edge
einsums, reductions) whose dispatch latency dominates at per-agent problem
sizes (~25 KB of state, ~50 KB of edges).  Here the entire loop — Hessian-
vector products, Riemannian corrections, block-Jacobi preconditioning,
tangent projections, and the Steihaug-Toint logic — executes inside one
kernel with every operand in VMEM:

* Pose gathers/scatters are one-hot matmuls: ``V_i = V @ Sel_i^T`` and
  ``H = g_i @ Sel_i + g_j @ Sel_j`` ride the MXU instead of lowering to
  serialized scatter ops.  ``Sel_i/Sel_j [E, n]`` are 0/1 selection
  matrices for the *local* endpoints of each edge (neighbor endpoints give
  zero rows — exactly the "neighbors are constants" Hessian semantics of
  ``quadratic.hessvec``).
* All per-edge and per-pose arithmetic is unrolled over the static
  ``(r, d)`` components and runs on [E]- / [n]-shaped rows (component-major
  layout, batch in lanes) — fully lane-parallel VPU work.
* The d x d / (d+1) x (d+1) math (curvature correction, tangent projection,
  preconditioner solves) is the same closed-form unrolled style as
  ``ops.smallmat``.

Numerics match ``ops.solver.truncated_cg`` (same stopping rule, same
epsilons); equivalence is asserted in tests/test_pallas_tcg.py, which runs
the kernel in interpreter mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

HI = jax.lax.Precision.HIGHEST


def _tcg_kernel(sel_i_ref, sel_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                x_ref, scorr_ref, chol_ref, g_ref, radius_ref,
                eta_ref, heta_ref, stats_ref,
                *, r: int, d: int, max_iters: int, kappa: float,
                theta: float):
    k = d + 1
    rk = r * k
    f32 = jnp.float32

    def q(a, c):  # component row of pose-block entry (a, c)
        return a * k + c

    sel_i = sel_i_ref[...]          # [E, n]
    sel_j = sel_j_ref[...]
    rot = rot_ref[...]              # [d*d, E] (row-major R components)
    trn = trn_ref[...]              # [d, E]
    wk = wk_ref[...][0]             # [E]
    wt = wt_ref[...][0]
    X = x_ref[...]                  # [rk, n]
    S = scorr_ref[...]              # [d*d, n]  sym(Y^T G_Y) per pose
    L = chol_ref[...]               # [k*k, n]  lower Cholesky components
    g = g_ref[...]                  # [rk, n]
    radius = radius_ref[0, 0]

    eps = jnp.asarray(1e-30, f32)

    def dotT(V, Sel):  # [rk, n] x [E, n] -> [rk, E]   (gather)
        return jax.lax.dot_general(V, Sel, (((1,), (1,)), ((), ())),
                                   precision=HI, preferred_element_type=f32)

    def dot(G, Sel):   # [rk, E] x [E, n] -> [rk, n]   (scatter-add)
        return jax.lax.dot_general(G, Sel, (((1,), (0,)), ((), ())),
                                   precision=HI, preferred_element_type=f32)

    def rows(mat):
        return [mat[i] for i in range(mat.shape[0])]

    def stack(rlist):
        return jnp.stack(rlist, axis=0)

    def hess_euclidean(V):
        """(V Q)_local on the buffer graph: per-edge residual forms of the
        tangent vector, one-hot scatter back (``quadratic.hessvec``)."""
        Vi = rows(dotT(V, sel_i))   # r*k rows of [E]
        Vj = rows(dotT(V, sel_j))
        R = rows(rot)
        t = rows(trn)
        # rR[a][c] = Vj_Y[a,c] - sum_b Vi_Y[a,b] R[b,c]
        rR = [[Vj[q(a, c)] - sum(Vi[q(a, b)] * R[b * d + c]
                                 for b in range(d))
               for c in range(d)] for a in range(r)]
        # rt[a] = Vj_p[a] - Vi_p[a] - sum_b Vi_Y[a,b] t[b]
        rt = [Vj[q(a, d)] - Vi[q(a, d)] - sum(Vi[q(a, b)] * t[b]
                                              for b in range(d))
              for a in range(r)]
        gj = [None] * rk
        gi = [None] * rk
        for a in range(r):
            for c in range(d):
                gj[q(a, c)] = wk * rR[a][c]
                # gi_Y[a,c] = -wk (rR R^T)[a,c] - wt rt[a] t[c]
                gi[q(a, c)] = -wk * sum(rR[a][b] * R[c * d + b]
                                        for b in range(d)) \
                    - wt * rt[a] * t[c]
            gj[q(a, d)] = wt * rt[a]
            gi[q(a, d)] = -wt * rt[a]
        return dot(stack(gi), sel_i) + dot(stack(gj), sel_j)

    Xr = rows(X)
    Sr = rows(S)
    Lr = rows(L)

    def tangent_project(W):
        """W_Y - Y sym(Y^T W_Y) per pose; translation rows unchanged."""
        Wr = rows(W)
        M = [[sum(Xr[q(a, b)] * Wr[q(a, c)] for a in range(r))
              for c in range(d)] for b in range(d)]
        sym = [[0.5 * (M[b][c] + M[c][b]) for c in range(d)]
               for b in range(d)]
        out = [None] * rk
        for a in range(r):
            for c in range(d):
                out[q(a, c)] = Wr[q(a, c)] - sum(
                    Xr[q(a, b)] * sym[b][c] for b in range(d))
            out[q(a, d)] = Wr[q(a, d)]
        return stack(out)

    def hess_riemannian(V):
        """P_X(EucHess[V] - [V_Y sym(Y^T G_Y) | 0])
        (``manifold.ehess_to_rhess``)."""
        Hd = hess_euclidean(V)
        Hr = rows(Hd)
        Vr = rows(V)
        out = [None] * rk
        for a in range(r):
            for c in range(d):
                out[q(a, c)] = Hr[q(a, c)] - sum(
                    Vr[q(a, b)] * Sr[b * d + c] for b in range(d))
            out[q(a, d)] = Hr[q(a, d)]
        return tangent_project(stack(out))

    def precond(V):
        """Tangent-projected block-Jacobi solve: each pose row a solves the
        (d+1) x (d+1) SPD block via unrolled substitution
        (``quadratic.precond_apply`` + projection)."""
        Vr = rows(V)
        out = [None] * rk
        for a in range(r):
            y = [None] * k
            for i in range(k):
                s = Vr[q(a, i)]
                for p in range(i):
                    s = s - Lr[i * k + p] * y[p]
                y[i] = s / Lr[i * k + i]
            x = [None] * k
            for i in reversed(range(k)):
                s = y[i]
                for p in range(i + 1, k):
                    s = s - Lr[p * k + i] * x[p]
                x[i] = s / Lr[i * k + i]
            for i in range(k):
                out[q(a, i)] = x[i]
        return tangent_project(stack(out))

    def inner(U, V):
        return jnp.sum(U * V)

    # --- Steihaug-Toint tCG (mirrors ops.solver.truncated_cg) ---
    r0 = g
    z0 = precond(r0)
    rz0 = inner(r0, z0)
    r0n = jnp.sqrt(inner(r0, r0))
    # theta is static; Mosaic has no powf, so expand the common cases.
    if theta == 1.0:
        r0n_th = r0n
    elif theta == 0.0:
        r0n_th = jnp.ones_like(r0n)
    else:
        r0n_th = jnp.exp(theta * jnp.log(jnp.maximum(r0n, eps)))
    target = r0n * jnp.minimum(kappa, r0n_th)
    zero = jnp.zeros_like(g)

    def body(_, s):
        kit, eta, Heta, rr, z, delta, rz, done, hit = s
        Hd = hess_riemannian(delta)
        d_Hd = inner(delta, Hd)
        alpha = rz / jnp.where(jnp.abs(d_Hd) < eps, eps, d_Hd)

        e_e = inner(eta, eta)
        e_d = inner(eta, delta)
        d_d = inner(delta, delta)
        e_e_next = e_e + 2.0 * alpha * e_d + alpha * alpha * d_d

        crossing = (d_Hd <= 0) | (e_e_next >= radius * radius)
        disc = jnp.maximum(e_d * e_d + d_d * (radius * radius - e_e), 0.0)
        tau = (-e_d + jnp.sqrt(disc)) / jnp.where(d_d < eps, eps, d_d)
        step = jnp.where(crossing, tau, alpha)
        eta_n = eta + step * delta
        Heta_n = Heta + step * Hd

        r_in = rr + alpha * Hd
        z_in = precond(r_in)
        rz_in = inner(r_in, z_in)
        converged = jnp.sqrt(inner(r_in, r_in)) <= target
        beta = rz_in / jnp.where(jnp.abs(rz) < eps, eps, rz)
        delta_in = -z_in + beta * delta

        # Predicated update: finished lanes keep their state.
        keep = done
        eta_o = jnp.where(keep, eta, eta_n)
        Heta_o = jnp.where(keep, Heta, Heta_n)
        rr_o = jnp.where(keep, rr, r_in)
        z_o = jnp.where(keep, z, z_in)
        delta_o = jnp.where(keep, delta, delta_in)
        rz_o = jnp.where(keep, rz, rz_in)
        kit_o = jnp.where(keep, kit, kit + 1.0)
        done_o = done | crossing | converged
        hit_o = hit | (~keep & crossing)
        return (kit_o, eta_o, Heta_o, rr_o, z_o, delta_o, rz_o, done_o,
                hit_o)

    init = (jnp.asarray(0.0, f32), zero, zero, r0, z0, -z0, rz0,
            rz0 <= 0, jnp.asarray(False))
    kit, eta, Heta, *_, hit = jax.lax.fori_loop(0, max_iters, body, init)

    eta_ref[...] = eta
    heta_ref[...] = Heta
    stats_ref[...] = jnp.stack([kit, hit.astype(f32)]).reshape(1, 2)


def comp_major(X: jax.Array) -> jax.Array:
    """[n, r, k] pose blocks -> [r*k, n] component-major."""
    n, r, k = X.shape
    return X.transpose(1, 2, 0).reshape(r * k, n)


def comp_minor(Xc: jax.Array, r: int, k: int) -> jax.Array:
    """[r*k, n] -> [n, r, k]."""
    n = Xc.shape[-1]
    return Xc.reshape(r, k, n).transpose(2, 0, 1)


@functools.partial(jax.jit, static_argnames=("r", "d", "max_iters", "kappa",
                                             "theta", "interpret"))
def tcg_call(sel_i, sel_j, rot, trn, wk, wt, Xc, Sc, Lc, gc, radius,
             *, r: int, d: int, max_iters: int, kappa: float, theta: float,
             interpret: bool = False):
    """Invoke the kernel for one agent (vmap adds the agent grid axis).

    All tensor operands are component-major float32; ``radius`` is [1, 1].
    Returns (eta_c [rk, n], heta_c [rk, n], stats [1, 2] = (iters, hit)).
    """
    rk, n = Xc.shape
    E = sel_i.shape[0]
    kern = functools.partial(_tcg_kernel, r=r, d=d, max_iters=max_iters,
                             kappa=kappa, theta=theta)
    vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ),
        in_specs=[vspec] * 11,
        out_specs=(vspec, vspec, vspec),
        interpret=interpret,
    )(sel_i, sel_j, rot, trn, wk, wt, Xc, Sc, Lc, gc, radius)
