"""Pallas TPU kernels: the RBCD local trust-region solve, VMEM-resident.

This is the framework's hot loop — the replacement for ROPTLIB's
``RTRNewton`` (reference ``QuadraticOptimizer.cpp:76-116``) one level deeper
than ``ops.solver``: the XLA formulation runs each truncated-CG iteration as
a chain of ~30 small kernels (gathers, per-edge einsums, reductions) whose
dispatch latency dominates at per-agent problem sizes (~25 KB of state,
~50 KB of edges).  Here the solver executes inside a kernel with every
operand in VMEM:

* Pose gathers/scatters ride the MXU as one-hot matmuls, but the one-hot
  selection matrices are never stored: the kernel holds only the int32
  endpoint indices (``[nt, 1, T]`` edge tiles) and materializes each
  ``[n, T]`` one-hot tile on the fly (``broadcasted_iota`` + compare)
  inside a ``fori_loop`` over edge tiles.  Memory is O(E + T·n) instead of
  the O(E·n) resident selection matrices of the first design — per-agent
  edge counts in the thousands fit comfortably where the old kernel's
  ceiling was ~765 edges.  An endpoint index that falls outside the
  compared range produces an all-zero one-hot column, which encodes both
  "neighbors are constants" (local selection skips buffer slots >= n) and
  edge padding (index n + s matches neither range) with no masks.
* All per-edge and per-pose arithmetic is unrolled over the static
  ``(r, d)`` components on [T]- / [n]-shaped rows (component-major layout,
  batch in lanes) — fully lane-parallel VPU work; the d x d / (d+1) x (d+1)
  math (curvature correction, tangent projection, preconditioner solves,
  Newton-Schulz retraction) is the same closed-form unrolled style as
  ``ops.smallmat``.

The kernels share one math module (``_build_math``):

* ``tcg_call`` — the truncated-CG subproblem alone (used by tests as the
  parity harness against ``ops.solver.truncated_cg``).
* ``rtr_call`` — single-step RTR from a precomputed gradient: the
  Steihaug-Toint solve plus retraction, cost evaluation, acceptance test,
  and the shrink-radius-until-accepted retry (reference
  ``QuadraticOptimizer.cpp:92-110``).
* ``rtr_full_call`` — the production round: ``rtr_call`` plus the
  start-point Euclidean/Riemannian gradient, curvature term, gradient
  norm and below-tolerance early exit computed IN-kernel.
* ``rtr_refine_full_call`` — the re-centered equivalent for
  ``models.refine`` (correction variable D at a host-held f64 reference).

Numerics match the XLA solver (same stopping rules, same epsilons);
equivalence is asserted in tests/test_pallas_tcg.py, which runs the kernels
in interpreter mode on CPU.

Edge-tile layout (built by ``models.rbcd.build_graph``): edges are padded
to ``nt * T`` (tile size ``T`` a lane multiple) and stored tile-major so
the kernel indexes tiles on the leading axis —

* ``idx_i / idx_j [nt, 1, T]`` int32 endpoint indices into the
  ``[n + s]`` pose buffer (``n + s`` for padding),
* ``rot_t [nt, d*d, T]`` / ``trn_t [nt, d, T]`` edge transforms,
* ``wk_t / wt_t [nt, 1, T]`` the weighted kappa/tau (zero on padding).
"""

from __future__ import annotations

import functools
import os
from types import SimpleNamespace

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

HI = jax.lax.Precision.HIGHEST

#: Edge-tile lane width: tiles are [n, T] one-hots and [*, T] payload rows.
TILE = 256

def _ab_gates() -> SimpleNamespace:
    """Experiment gates, read at KERNEL-BUILD time (inside ``_build_math``)
    rather than import time, so they are toggleable per-process and
    testable (a test can set the env var, rebuild a kernel, and unset it —
    no interpreter restart).  experiments/kernel_breakdown.py A/Bs these
    at the 100k shape — see BASELINE.md round-5 VPU entry and the round-6
    promotion record.

    * ``PALLAS_NS_SWEEPS`` — Newton-Schulz sweeps in the retraction.
      DECIDED (round 5, reaffirmed round 6): the default stays 24 — ns8's
      ~5-7% is not worth its 7e-4..2.6e-3 trajectory drift.  The gate is
      the one remaining live A/B, kept so the tradeoff stays re-measurable
      as shapes change.

    Gates RETIRED in round 6 (decisions recorded in BASELINE.md):

    * ``PALLAS_SEL_PACKED`` — the measured winner at every shape tested
      (bf16x3 100k/64: 36.7 unpacked -> 57.6 packed in the defaults-
      relative ablation; exact — identical MACs, 1/passes the dot
      issues).  Packed selection is now UNCONDITIONAL; the unpacked
      per-pass code path is deleted.
    * ``PALLAS_UNROLL_TILES`` — measured dead end: Mosaic keeps every
      unrolled tile's transient one-hots live concurrently, overflowing
      scoped VMEM (16.55M > 16M at T=128 bf16x3) at exactly the shapes
      that needed the pipelining.  Deleted.

    NOTE: jit/pallas caches key on shapes and function identity, not on
    these env vars — toggling a gate affects kernels built AFTER the
    toggle, not already-compiled ones.
    """
    return SimpleNamespace(
        ns_sweeps=int(os.environ.get("PALLAS_NS_SWEEPS", "24")))


def _build_math(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                X, S, L, *, r, d, max_iters, kappa, theta, refine=None,
                hoist_scratch=None, Z=None, sel_mode="f32"):
    """Closures over the per-agent VMEM refs (component-major layout).

    Edge data arrives as tile-major refs (see module docstring) read
    tile-by-tile inside ``fori_loop``; ``X`` is the expansion point (fixed
    during a solve): tangent projection and the Riemannian curvature
    correction are taken at ``X``; ``S = sym(Y^T G_Y)`` per pose; ``L`` the
    preconditioner Cholesky components.

    ``S = None`` (requires ``Z``) switches to the fully-fused mode: the
    Euclidean gradient at the buffer point [X | Z], the curvature term S,
    the Riemannian gradient and its norm are all computed IN-kernel
    (``m.g``, ``m.gn0``) — the XLA pre-pass that previously produced
    S and g per round (65% of a small-problem round, measured) disappears.

    ``refine = (rho_rot_ref [nt, r*d, T], rho_trn_ref [nt, r, T],
    Rc [rk, n], D [rk, n])`` switches the kernel to the
    re-centered terminal-refinement mode (``models.refine``): ``X`` is then
    the evaluation point Y = R + D (projections/curvature only ever
    multiply small vectors, so f32 Y is fine), ``D`` the small correction
    the solve updates, ``cost`` evaluates the cross + quadratic increment
    against the reference residuals rho (so its f32 error scales with |D|,
    not with f), and ``retract`` maps eta to D_new via the polar correction
    series without ever materializing R + D in the state.
    """
    k = d + 1
    rk = r * k
    n = X.shape[-1]
    nt = idx_i_ref.shape[0]
    T = idx_i_ref.shape[-1]
    f32 = jnp.float32
    eps = jnp.asarray(1e-30, f32)
    gates = _ab_gates()  # read per kernel build, not per import

    def q(a, c):  # component row of pose-block entry (a, c)
        return a * k + c

    bf16 = jnp.bfloat16
    sel_t = f32 if sel_mode == "f32" else bf16
    sel_passes = {"f32": 0, "bf16": 2, "bf16x3": 3}[sel_mode]

    def _split(V, parts):
        """f32 -> ``parts`` bf16 terms summing back to V.

        Each term peels the next ~8 mantissa bits: 2 parts cover 16 bits
        (~2^-16 relative error), 3 parts cover the full 24-bit f32
        mantissa — the reconstruction is f32-exact up to the residual
        term's own rounding (<= f32 eps), so 3-pass selection is an
        f32-equivalent gather/scatter at bf16 MXU rates."""
        outs = []
        rem = V
        for _ in range(parts - 1):
            hi = rem.astype(bf16)
            outs.append(hi)
            rem = rem - hi.astype(f32)
        outs.append(rem.astype(bf16))
        return outs

    def _sel_dot(V, Sel, dims):
        if sel_passes == 0:
            return jax.lax.dot_general(V, Sel, dims, precision=HI,
                                       preferred_element_type=f32)
        # One-hots are EXACT in bf16 (entries 0/1); V splits into bf16
        # passes at the MXU's native bf16 rate — 2 or 3 passes instead of
        # the f32 HIGHEST emulation's 6.  No cross terms arise because Sel
        # needs no split, which is why 3 passes already reach f32-grade
        # accuracy.  precision must be DEFAULT explicitly: with bf16
        # operands and no precision, Mosaic resolves contract precision to
        # fp32 and rejects the matmul ("Bad lhs type").
        parts = _split(V, sel_passes)
        # PACKED selection (unconditional since round 6 — the measured
        # winner at every shape tested): one dot on the row-stacked
        # splits instead of ``sel_passes`` separate dots.  At the 100k
        # shape the kernel is dot-ISSUE-bound, not MAC-bound (round-5
        # breakdown) — identical MXU work, 1/passes the issues.  The
        # contraction axis is the same for every split (dims contracts
        # V's axis ``cdim`` with Sel), so stacking rides the output row
        # axis.
        stacked = jnp.concatenate(parts, axis=0)
        t = jax.lax.dot_general(stacked, Sel, dims,
                                precision=jax.lax.Precision.DEFAULT,
                                preferred_element_type=f32)
        rows_out = t.shape[0] // sel_passes
        return sum(t[p * rows_out:(p + 1) * rows_out]
                   for p in range(sel_passes))

    def onehot2(ii, jj, m, base):
        """[m, 2T] PAIRED one-hot: columns [:T] select the i endpoints,
        [T:] the j endpoints — one iota compare builds both, one matmul
        gathers both, one matmul scatters both (half the dot count of
        separate i/j selection; the MXU work is identical but the
        fori_loop interleaves fewer, wider dots with the VPU edge math)."""
        idx2 = jnp.concatenate([ii, jj], axis=-1)
        io = jax.lax.broadcasted_iota(jnp.int32, (m, 2 * T), 0)
        return ((idx2 - base) == io).astype(sel_t)

    def gather_pair(V, Sel2):  # [rk, m] x [m, 2T] -> ([rk, T], [rk, T])
        g = _sel_dot(V, Sel2, (((1,), (0,)), ((), ())))
        return g[:, :T], g[:, T:]

    def scatter_pair(Gi, Gj, Sel2):  # scatter-add both endpoint stacks
        return _sel_dot(jnp.concatenate([Gi, Gj], axis=-1), Sel2,
                        (((1,), (1,)), ((), ())))

    def rows(mat):
        return [mat[i] for i in range(mat.shape[0])]

    def stack(rlist):
        return jnp.stack(rlist, axis=0)

    if hoist_scratch is not None:
        # Small-shape fast path: materialize the local one-hot tiles once
        # per kernel invocation into VMEM scratch (an [nt, n, 2T] ref,
        # which supports the tile loop's dynamic index) instead of
        # rebuilding them in every tCG iteration — the compare/convert
        # VPU work is ~10% of a small-problem round.
        s2_scr, = hoist_scratch
        for t in range(nt):  # static-index stores, once per invocation
            s2_scr[t] = onehot2(idx_i_ref[t], idx_j_ref[t], n, 0)
        local_sel2 = lambda ti: s2_scr[ti]
    else:
        local_sel2 = lambda ti: onehot2(idx_i_ref[ti], idx_j_ref[ti], n, 0)

    def tile_loop(tile_fn, init):
        # Always the loop-carried fori_loop: static unroll (the retired
        # PALLAS_UNROLL_TILES experiment) made Mosaic keep every tile's
        # transient one-hots live concurrently — scoped-VMEM overflow at
        # exactly the shapes that wanted the pipelining (BASELINE.md).
        return jax.lax.fori_loop(0, nt, tile_fn, init)

    Xr = rows(X)
    Lr = rows(L)

    def edge_residuals(Vi, Vj, R, t):
        """Per-edge lifted residual components from gathered endpoints
        (per-tile: rows are [T])."""
        rR = [[Vj[q(a, c)] - sum(Vi[q(a, b)] * R[b * d + c]
                                 for b in range(d))
               for c in range(d)] for a in range(r)]
        rt = [Vj[q(a, d)] - Vi[q(a, d)] - sum(Vi[q(a, b)] * t[b]
                                              for b in range(d))
              for a in range(r)]
        return rR, rt

    def edge_grad_rows(rR, rt, R, t, wk, wt):
        """Per-edge endpoint gradient rows gi/gj from residual components
        (``quadratic._edge_grad_terms``)."""
        gj = [None] * rk
        gi = [None] * rk
        for a in range(r):
            for c in range(d):
                gj[q(a, c)] = wk * rR[a][c]
                # gi_Y[a,c] = -wk (rR R^T)[a,c] - wt rt[a] t[c]
                gi[q(a, c)] = -wk * sum(rR[a][b] * R[c * d + b]
                                        for b in range(d)) \
                    - wt * rt[a] * t[c]
            gj[q(a, d)] = wt * rt[a]
            gi[q(a, d)] = -wt * rt[a]
        return gi, gj

    def hess_euclidean(V):
        """(V Q)_local on the buffer graph, accumulated over edge tiles:
        per-tile one-hot gather, residual forms, one-hot scatter back
        (``quadratic.hessvec``)."""

        def tile(ti, acc):
            sel2 = local_sel2(ti)
            R = rows(rot_ref[ti])
            t = rows(trn_ref[ti])
            wk = wk_ref[ti][0]
            wt = wt_ref[ti][0]
            Vi2, Vj2 = gather_pair(V, sel2)
            Vi = rows(Vi2)
            Vj = rows(Vj2)
            rR, rt = edge_residuals(Vi, Vj, R, t)
            gi, gj = edge_grad_rows(rR, rt, R, t, wk, wt)
            return acc + scatter_pair(stack(gi), stack(gj), sel2)

        return tile_loop(tile, jnp.zeros((rk, n), f32))

    def grad_euclidean(Xv, Zv):
        """Euclidean gradient rows of the LOCAL poses at the buffer point
        [Xv | Zv]: same tile loop as ``hess_euclidean`` with the fixed
        neighbor values folded into the gathers (``quadratic.egrad``) —
        neighbor-slot contributions scatter to all-zero one-hot columns
        and vanish, exactly the n_out=n truncation.  (In refine mode this
        is called on the correction [D | Dz]: the residual map is affine
        with exactly this linear part, so the same loop yields the
        increment gradient dG.)"""
        s = Zv.shape[-1]

        def tile(ti, acc):
            ii = idx_i_ref[ti]
            jj = idx_j_ref[ti]
            sel2 = local_sel2(ti)
            seln2 = onehot2(ii, jj, s, n)
            R = rows(rot_ref[ti])
            t = rows(trn_ref[ti])
            wk = wk_ref[ti][0]
            wt = wt_ref[ti][0]
            Xi2, Xj2 = gather_pair(Xv, sel2)
            Zi2, Zj2 = gather_pair(Zv, seln2)
            Vi = rows(Xi2 + Zi2)
            Vj = rows(Xj2 + Zj2)
            rR, rt = edge_residuals(Vi, Vj, R, t)
            gi, gj = edge_grad_rows(rR, rt, R, t, wk, wt)
            return acc + scatter_pair(stack(gi), stack(gj), sel2)

        return tile_loop(tile, jnp.zeros((rk, n), f32))

    def cost(V, Z):
        """f over the full buffer: local candidate V plus fixed neighbors Z
        (``quadratic.cost`` semantics), accumulated over edge tiles.

        Refine mode: the per-edge terms are the recentered increment
        ``w <rho, L> + 0.5 w |L|^2`` (= f(R + D) - f(R) exactly — the
        ambient cost is quadratic), never the large |rho + L|^2."""
        s = Z.shape[-1]

        def tile(ti, acc):
            ii = idx_i_ref[ti]
            jj = idx_j_ref[ti]
            sel2 = local_sel2(ti)
            seln2 = onehot2(ii, jj, s, n)
            R = rows(rot_ref[ti])
            t = rows(trn_ref[ti])
            wk = wk_ref[ti][0]
            wt = wt_ref[ti][0]
            Vi2, Vj2 = gather_pair(V, sel2)
            Zi2, Zj2 = gather_pair(Z, seln2)
            Vi = rows(Vi2 + Zi2)
            Vj = rows(Vj2 + Zj2)
            rR, rt = edge_residuals(Vi, Vj, R, t)
            quad = wk * sum(rR[a][c] * rR[a][c]
                            for a in range(r) for c in range(d)) \
                + wt * sum(rt[a] * rt[a] for a in range(r))
            if refine is not None:
                rho_rot = rows(refine[0][ti])
                rho_trn = rows(refine[1][ti])
                cross = wk * sum(rho_rot[a * d + c] * rR[a][c]
                                 for a in range(r) for c in range(d)) \
                    + wt * sum(rho_trn[a] * rt[a] for a in range(r))
                return acc + jnp.sum(cross + 0.5 * quad)
            return acc + 0.5 * jnp.sum(quad)

        return tile_loop(tile, jnp.asarray(0.0, f32))

    def tangent_project(W):
        """W_Y - Y sym(Y^T W_Y) per pose; translation rows unchanged."""
        Wr = rows(W)
        M = [[sum(Xr[q(a, b)] * Wr[q(a, c)] for a in range(r))
              for c in range(d)] for b in range(d)]
        sym = [[0.5 * (M[b][c] + M[c][b]) for c in range(d)]
               for b in range(d)]
        out = [None] * rk
        for a in range(r):
            for c in range(d):
                out[q(a, c)] = Wr[q(a, c)] - sum(
                    Xr[q(a, b)] * sym[b][c] for b in range(d))
            out[q(a, d)] = Wr[q(a, d)]
        return stack(out)

    g_k = gn0_k = None
    if S is None and refine is None:
        # Fused mode: gradient, curvature term, Riemannian gradient and its
        # norm from one in-VMEM tile sweep (replaces the per-round XLA
        # egrad_ell + rgrad + S pre-pass of ``rbcd._agent_update``).
        G = grad_euclidean(X, Z)
        Gr = rows(G)
        M = [[sum(Xr[q(a, b)] * Gr[q(a, c)] for a in range(r))
              for c in range(d)] for b in range(d)]
        Ssym = [[0.5 * (M[b][c] + M[c][b]) for c in range(d)]
                for b in range(d)]
        S = stack([Ssym[b][c] for b in range(d) for c in range(d)])
        gl = [None] * rk
        for a in range(r):
            for c in range(d):
                gl[q(a, c)] = Gr[q(a, c)] - sum(
                    Xr[q(a, b)] * Ssym[b][c] for b in range(d))
            gl[q(a, d)] = Gr[q(a, d)]
        g_k = stack(gl)
        gn0_k = jnp.sqrt(jnp.sum(g_k * g_k))
    elif S is None:
        # Fused RE-CENTERED mode (``models.refine._agent_refine`` math,
        # in-kernel): refine = (rho_rot, rho_trn, Rc, D, Dz, g0, Gref, S0)
        # with the last four the extra per-recenter constants.
        #   dG = increment gradient at [D | Dz]
        #   S1 = sym(D_Y^T Gref_Y + Y_Y^T dG_Y),  S = S0 + S1
        #   g  = g0 + dG;  g_Y -= R S1 + D (S0 + S1)
        Dst, Dz_k, g0_k, Gref_k, S0_k = (refine[3], refine[4], refine[5],
                                         refine[6], refine[7])
        Rc_k = refine[2]
        dG = grad_euclidean(Dst, Dz_k)
        dGr = rows(dG)
        Dr = rows(Dst)
        Grefr = rows(Gref_k)
        S0r = rows(S0_k)
        # Y = X here (the caller passes Y = Rc + D as the expansion point).
        M1 = [[sum(Dr[q(a, b)] * Grefr[q(a, c)]
                   + Xr[q(a, b)] * dGr[q(a, c)] for a in range(r))
               for c in range(d)] for b in range(d)]
        S1 = [[0.5 * (M1[b][c] + M1[c][b]) for c in range(d)]
              for b in range(d)]
        Stot = [[S0r[b * d + c] + S1[b][c] for c in range(d)]
                for b in range(d)]
        S = stack([Stot[b][c] for b in range(d) for c in range(d)])
        Rr_k = rows(Rc_k)
        g0r = rows(g0_k)
        gl = [None] * rk
        for a in range(r):
            for c in range(d):
                gl[q(a, c)] = g0r[q(a, c)] + dGr[q(a, c)] - sum(
                    Rr_k[q(a, b)] * S1[b][c]
                    + Dr[q(a, b)] * Stot[b][c] for b in range(d))
            gl[q(a, d)] = g0r[q(a, d)] + dGr[q(a, d)]
        g_k = stack(gl)
        gn0_k = jnp.sqrt(jnp.sum(g_k * g_k))
    Sr = rows(S)

    def hess_riemannian(V):
        """P_X(EucHess[V] - [V_Y sym(Y^T G_Y) | 0])
        (``manifold.ehess_to_rhess``)."""
        Hd = hess_euclidean(V)
        Hr = rows(Hd)
        Vr = rows(V)
        out = [None] * rk
        for a in range(r):
            for c in range(d):
                out[q(a, c)] = Hr[q(a, c)] - sum(
                    Vr[q(a, b)] * Sr[b * d + c] for b in range(d))
            out[q(a, d)] = Hr[q(a, d)]
        return tangent_project(stack(out))

    def precond(V):
        """Tangent-projected block-Jacobi solve: each pose row a solves the
        (d+1) x (d+1) SPD block via unrolled substitution
        (``quadratic.precond_apply`` + projection)."""
        Vr = rows(V)
        out = [None] * rk
        for a in range(r):
            y = [None] * k
            for i in range(k):
                s = Vr[q(a, i)]
                for p in range(i):
                    s = s - Lr[i * k + p] * y[p]
                y[i] = s / Lr[i * k + i]
            x = [None] * k
            for i in reversed(range(k)):
                s = y[i]
                for p in range(i + 1, k):
                    s = s - Lr[p * k + i] * x[p]
                x[i] = s / Lr[i * k + i]
            for i in range(k):
                out[q(a, i)] = x[i]
        return tangent_project(stack(out))

    def inner(U, V):
        return jnp.sum(U * V)

    def tcg(g, radius):
        """Steihaug-Toint truncated CG (mirrors ops.solver.truncated_cg).
        Returns (eta, Heta, iters, hit_boundary)."""
        r0 = g
        z0 = precond(r0)
        rz0 = inner(r0, z0)
        r0n = jnp.sqrt(inner(r0, r0))
        # theta is static; Mosaic has no powf, so expand the common cases.
        if theta == 1.0:
            r0n_th = r0n
        elif theta == 0.0:
            r0n_th = jnp.ones_like(r0n)
        else:
            r0n_th = jnp.exp(theta * jnp.log(jnp.maximum(r0n, eps)))
        target = r0n * jnp.minimum(kappa, r0n_th)
        zero = jnp.zeros_like(g)

        def body(s):
            kit, eta, Heta, rr, z, delta, rz, done, hit = s
            Hd = hess_riemannian(delta)
            d_Hd = inner(delta, Hd)
            alpha = rz / jnp.where(jnp.abs(d_Hd) < eps, eps, d_Hd)

            e_e = inner(eta, eta)
            e_d = inner(eta, delta)
            d_d = inner(delta, delta)
            e_e_next = e_e + 2.0 * alpha * e_d + alpha * alpha * d_d

            crossing = (d_Hd <= 0) | (e_e_next >= radius * radius)
            disc = jnp.maximum(e_d * e_d + d_d * (radius * radius - e_e),
                               0.0)
            tau = (-e_d + jnp.sqrt(disc)) / jnp.where(d_d < eps, eps, d_d)
            step = jnp.where(crossing, tau, alpha)
            eta_n = eta + step * delta
            Heta_n = Heta + step * Hd

            r_in = rr + alpha * Hd
            z_in = precond(r_in)
            rz_in = inner(r_in, z_in)
            converged = jnp.sqrt(inner(r_in, r_in)) <= target
            beta = rz_in / jnp.where(jnp.abs(rz) < eps, eps, rz)
            delta_in = -z_in + beta * delta
            return (kit + 1.0, eta_n, Heta_n, r_in, z_in, delta_in, rz_in,
                    done | crossing | converged, hit | crossing)

        def not_done(s):
            kit, *_, done, _ = s
            return (kit < max_iters) & ~done

        init = (jnp.asarray(0.0, f32), zero, zero, r0, z0, -z0, rz0,
                rz0 <= 0, jnp.asarray(False))
        kit, eta, Heta, *_, hit = jax.lax.while_loop(not_done, body, init)
        return eta, Heta, kit, hit

    def retract_refine(V):
        """Refine mode: D_new with X_new = polar(R + D + eta), via the
        correction series C = (I + E)^{-1/2} - I on small quantities only
        (mirrors ``models.refine._retract_d``)."""
        Rc, Dstate = refine[2], refine[3]
        Rr = rows(Rc)
        U = Dstate + V  # D + eta, rows [rk, n]
        Ur = rows(U)
        MY = [[Rr[q(a, c)] + Ur[q(a, c)] for c in range(d)]
              for a in range(r)]
        # E = R^T U + U^T R + U^T U (Y-part, d x d over [n] lanes;
        # R^T R = I exactly — R is the f64-projected host reference)
        E = [[sum(Rr[q(a, b)] * Ur[q(a, c)]
                    + Ur[q(a, b)] * Rr[q(a, c)]
                    + Ur[q(a, b)] * Ur[q(a, c)] for a in range(r))
              for c in range(d)] for b in range(d)]
        E = [[0.5 * (E[b][c] + E[c][b]) for c in range(d)] for b in range(d)]

        def mm(P, Q):
            return [[sum(P[b, e] * Q[e, c] for e in range(d))
                     for c in range(d)] for b in range(d)]

        En = stack([stack(rw) for rw in E])
        E2 = stack([stack(rw) for rw in mm(En, En)])
        E3 = stack([stack(rw) for rw in mm(E2, En)])
        E4 = stack([stack(rw) for rw in mm(E2, E2)])
        C = -0.5 * En + 0.375 * E2 - 0.3125 * E3 + 0.2734375 * E4
        out = [None] * rk
        for a in range(r):
            for c in range(d):
                out[q(a, c)] = Ur[q(a, c)] + sum(
                    MY[a][b] * C[b, c] for b in range(d))
            out[q(a, d)] = Ur[q(a, d)]
        return stack(out)

    def retract(V):
        """R_X(V): per-pose Newton-Schulz polar of (Y + V_Y), translation
        add (``manifold.retract`` / ``smallmat.polar_orthonormalize``)."""
        if refine is not None:
            return retract_refine(V)
        Vr = rows(V)
        M = [[Xr[q(a, c)] + Vr[q(a, c)] for c in range(d)]
             for a in range(r)]
        # A = M^T M  (d x d symmetric, components over [n])
        A = [[sum(M[a][b] * M[a][c] for a in range(r)) for c in range(d)]
             for b in range(d)]
        s = sum(A[b][b] for b in range(d))
        s = jnp.maximum(s, jnp.asarray(1e-37, f32))
        An = stack([stack([A[b][c] / s for c in range(d)]) for b in range(d)])
        one = jnp.ones_like(An[0, 0])
        eye = stack([stack([one if b == c else jnp.zeros_like(one)
                            for c in range(d)]) for b in range(d)])

        def matmul3(P, Q):
            return stack([stack([
                sum(P[b, e] * Q[e, c] for e in range(d))
                for c in range(d)]) for b in range(d)])

        def sweep(_, YZ):
            Y, Z = YZ
            T_ = 0.5 * (3.0 * eye - matmul3(Z, Y))
            return matmul3(Y, T_), matmul3(T_, Z)

        _, Zc = jax.lax.fori_loop(0, gates.ns_sweeps, sweep, (An, eye))
        inv_sqrt_s = jax.lax.rsqrt(s)
        out = [None] * rk
        for a in range(r):
            for c in range(d):
                out[q(a, c)] = sum(M[a][b] * Zc[b, c] for b in range(d)) \
                    * inv_sqrt_s
            out[q(a, d)] = Xr[q(a, d)] + Vr[q(a, d)]
        return stack(out)

    return SimpleNamespace(tcg=tcg, inner=inner, retract=retract, cost=cost,
                           precond=precond, g=g_k, gn0=gn0_k)


def _tcg_kernel(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                x_ref, scorr_ref, chol_ref, g_ref, radius_ref,
                eta_ref, heta_ref, stats_ref, *scratch,
                r: int, d: int, max_iters: int, kappa: float,
                theta: float):
    m = _build_math(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                    x_ref[...], scorr_ref[...], chol_ref[...],
                    r=r, d=d, max_iters=max_iters, kappa=kappa, theta=theta,
                    hoist_scratch=scratch or None)
    eta, Heta, kit, hit = m.tcg(g_ref[...], radius_ref[0, 0])
    eta_ref[...] = eta
    heta_ref[...] = Heta
    stats_ref[...] = jnp.stack([kit, hit.astype(jnp.float32)]).reshape(1, 2)


def _rtr_kernel(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                x_ref, z_ref, scorr_ref, chol_ref, g_ref,
                x_out_ref, stats_ref, *scratch,
                r: int, d: int, max_iters: int, kappa: float,
                theta: float, initial_radius: float, max_rejections: int):
    """Full single-step RTR (reference ``QuadraticOptimizer.cpp:92-110``):
    repeat {tCG at current radius; retract; evaluate cost; accept when
    rho > 0.1 and the cost does not increase; else radius /= 4} at most
    ``max_rejections`` times; on total rejection X is returned unchanged."""
    f32 = jnp.float32
    X = x_ref[...]
    Z = z_ref[...]
    g = g_ref[...]
    m = _build_math(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                    X, scorr_ref[...], chol_ref[...],
                    r=r, d=d, max_iters=max_iters, kappa=kappa, theta=theta,
                    hoist_scratch=scratch or None)

    f0 = m.cost(X, Z)
    eps = jnp.asarray(1e-30, f32)

    def attempt_body(s):
        k_att, radius, X_best, f_best, accepted = s
        eta, Heta, _, _ = m.tcg(g, radius)
        X_prop = m.retract(eta)
        f_prop = m.cost(X_prop, Z)
        mdec = -(m.inner(g, eta) + 0.5 * m.inner(eta, Heta))
        rho = (f0 - f_prop) / jnp.maximum(mdec, eps)
        ok = (rho > 0.1) & (f_prop <= f0)
        X_n = jnp.where(ok, X_prop, X_best)
        f_n = jnp.where(ok, f_prop, f_best)
        return (k_att + 1.0, jnp.where(ok, radius, radius / 4.0),
                X_n, f_n, accepted | ok)

    def attempt_cond(s):
        k_att, _, _, _, accepted = s
        return (k_att < max_rejections) & ~accepted

    init = (jnp.asarray(0.0, f32), jnp.asarray(initial_radius, f32),
            X, f0, jnp.asarray(False))
    k_att, _, X_out, f_out, accepted = jax.lax.while_loop(
        attempt_cond, attempt_body, init)

    x_out_ref[...] = X_out
    stats_ref[...] = jnp.stack(
        [k_att, accepted.astype(f32), f0, f_out]).reshape(1, 4)


def _rtr_full_kernel(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                     x_ref, z_ref, chol_ref, x_out_ref, stats_ref, *scratch,
                     r: int, d: int, max_iters: int, kappa: float,
                     theta: float, initial_radius: float,
                     max_rejections: int, grad_tol: float,
                     sel_mode: str):
    """Fully-fused single-step RTR: the start-point gradient, curvature
    term, gradient norm, AND the attempt loop of ``_rtr_kernel`` in one
    kernel — one invocation is the complete local solve of
    ``QuadraticOptimizer::optimize`` (reference ``QuadraticOptimizer.cpp:
    34-59``), including the below-tolerance early exit (``:65-69``)."""
    f32 = jnp.float32
    X = x_ref[...]
    Z = z_ref[...]
    m = _build_math(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                    X, None, chol_ref[...],
                    r=r, d=d, max_iters=max_iters, kappa=kappa, theta=theta,
                    hoist_scratch=scratch or None, Z=Z,
                    sel_mode=sel_mode)
    g = m.g
    gn0 = m.gn0

    f0 = m.cost(X, Z)
    eps = jnp.asarray(1e-30, f32)

    def attempt_body(s):
        k_att, radius, X_best, f_best, accepted = s
        eta, Heta, _, _ = m.tcg(g, radius)
        X_prop = m.retract(eta)
        f_prop = m.cost(X_prop, Z)
        mdec = -(m.inner(g, eta) + 0.5 * m.inner(eta, Heta))
        rho = (f0 - f_prop) / jnp.maximum(mdec, eps)
        ok = (rho > 0.1) & (f_prop <= f0)
        return (k_att + 1.0, jnp.where(ok, radius, radius / 4.0),
                jnp.where(ok, X_prop, X_best),
                jnp.where(ok, f_prop, f_best), accepted | ok)

    def attempt_cond(s):
        k_att, _, _, _, accepted = s
        return (k_att < max_rejections) & ~accepted

    below = gn0 < grad_tol  # early exit: X returned unchanged
    init = (jnp.where(below, jnp.asarray(float(max_rejections), f32),
                      jnp.asarray(0.0, f32)),
            jnp.asarray(initial_radius, f32), X, f0, jnp.asarray(False))
    k_att, _, X_out, f_out, accepted = jax.lax.while_loop(
        attempt_cond, attempt_body, init)

    x_out_ref[...] = X_out
    stats_ref[...] = jnp.stack(
        [k_att, accepted.astype(f32), f0, f_out, gn0]).reshape(1, 5)


def _rtr_refine_full_kernel(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref,
                            wt_ref, rho_rot_ref, rho_trn_ref, rc_ref,
                            d_ref, dz_ref, g0_ref, gref_ref, s0_ref,
                            chol_ref, d_out_ref, stats_ref, *scratch,
                            r: int, d: int, max_iters: int, kappa: float,
                            theta: float, initial_radius: float,
                            max_rejections: int, grad_tol: float,
                            sel_mode: str = "f32"):
    """Fully-fused re-centered single-step RTR: the recentered gradient
    (g0 + dG with the S0/S1 curvature corrections), the adaptive initial
    radius, and the shrink-radius attempt loop in one kernel —
    the XLA pre-pass of ``models.refine._agent_refine`` disappears, same
    as ``_rtr_full_kernel`` did for the plain round."""
    f32 = jnp.float32
    D = d_ref[...]
    Dz = dz_ref[...]
    Rc = rc_ref[...]
    Y = Rc + D
    m = _build_math(idx_i_ref, idx_j_ref, rot_ref, trn_ref, wk_ref, wt_ref,
                    Y, None, chol_ref[...],
                    r=r, d=d, max_iters=max_iters, kappa=kappa, theta=theta,
                    refine=(rho_rot_ref, rho_trn_ref, Rc, D, Dz,
                            g0_ref[...], gref_ref[...], s0_ref[...]),
                    hoist_scratch=scratch or None, sel_mode=sel_mode)
    g = m.g
    gn0 = m.gn0

    # Refinement steps live at the |D| scale: start the trust region near
    # the preconditioned-gradient (Cauchy) scale (models.refine rationale).
    pg = m.precond(g)
    radius0 = jnp.minimum(jnp.asarray(initial_radius, f32),
                          10.0 * jnp.sqrt(m.inner(pg, pg)))

    f0 = m.cost(D, Dz)
    eps = jnp.asarray(1e-30, f32)

    def attempt_body(s):
        k_att, radius, D_best, f_best, accepted = s
        eta, Heta, _, _ = m.tcg(g, radius)
        D_prop = m.retract(eta)
        f_prop = m.cost(D_prop, Dz)
        mdec = -(m.inner(g, eta) + 0.5 * m.inner(eta, Heta))
        rho = (f0 - f_prop) / jnp.maximum(mdec, eps)
        ok = (rho > 0.1) & (f_prop <= f0)
        return (k_att + 1.0, jnp.where(ok, radius, radius / 4.0),
                jnp.where(ok, D_prop, D_best),
                jnp.where(ok, f_prop, f_best), accepted | ok)

    def attempt_cond(s):
        k_att, _, _, _, accepted = s
        return (k_att < max_rejections) & ~accepted

    below = gn0 < grad_tol
    init = (jnp.where(below, jnp.asarray(float(max_rejections), f32),
                      jnp.asarray(0.0, f32)),
            radius0, D, f0, jnp.asarray(False))
    k_att, _, D_out, f_out, accepted = jax.lax.while_loop(
        attempt_cond, attempt_body, init)

    d_out_ref[...] = D_out
    stats_ref[...] = jnp.stack(
        [k_att, accepted.astype(f32), f0, f_out, gn0]).reshape(1, 5)


def comp_major(X: jax.Array) -> jax.Array:
    """[n, r, k] pose blocks -> [r*k, n] component-major."""
    n, r, k = X.shape
    return X.transpose(1, 2, 0).reshape(r * k, n)


def comp_minor(Xc: jax.Array, r: int, k: int) -> jax.Array:
    """[r*k, n] -> [n, r, k]."""
    n = Xc.shape[-1]
    return Xc.reshape(r, k, n).transpose(2, 0, 1)


def edge_tiles(w: jax.Array, nt: int, tile: int = TILE) -> jax.Array:
    """Pad a per-edge row [E] to the kernel's tile-major [nt, 1, T]."""
    E = w.shape[-1]
    wp = jnp.pad(w, (0, nt * tile - E))
    return wp.reshape(nt, tile)[:, None, :]


@functools.partial(jax.jit, static_argnames=("r", "d", "max_iters", "kappa",
                                             "theta", "interpret", "hoist"))
def tcg_call(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, Xc, Sc, Lc, gc, radius,
             *, r: int, d: int, max_iters: int, kappa: float, theta: float,
             interpret: bool = False, hoist: bool | None = None):
    """Invoke the tCG kernel for one agent (vmap adds the agent grid axis).

    Edge operands are tile-major (module docstring); pose operands are
    component-major float32; ``radius`` is [1, 1].
    Returns (eta_c [rk, n], heta_c [rk, n], stats [1, 2] = (iters, hit)).
    """
    rk, n = Xc.shape
    kern = functools.partial(_tcg_kernel, r=r, d=d, max_iters=max_iters,
                             kappa=kappa, theta=theta)
    vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
    nt, T = idx_i.shape[0], idx_i.shape[-1]
    if hoist is None:
        hoist = should_hoist(nt, T, n)
    scratch = [pltpu.VMEM((nt, n, 2 * T), jnp.float32)] if hoist else []
    return pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ),
        in_specs=[vspec] * 11,
        out_specs=(vspec, vspec, vspec),
        scratch_shapes=scratch,
        interpret=interpret,
    )(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, Xc, Sc, Lc, gc, radius)


@functools.partial(jax.jit, static_argnames=(
    "r", "d", "max_iters", "kappa", "theta", "initial_radius",
    "max_rejections", "interpret", "hoist"))
def rtr_call(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, Xc, Zc, Sc, Lc,
             gc, *, r: int, d: int, max_iters: int, kappa: float,
             theta: float, initial_radius: float, max_rejections: int,
             interpret: bool = False, hoist: bool | None = None):
    """Invoke the full single-step RTR kernel for one agent.

    Returns (X_out_c [rk, n], stats [1, 4] = (attempts, accepted, f0, f)).
    """
    rk, n = Xc.shape
    kern = functools.partial(_rtr_kernel, r=r, d=d, max_iters=max_iters,
                             kappa=kappa, theta=theta,
                             initial_radius=initial_radius,
                             max_rejections=max_rejections)
    vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
    nt, T = idx_i.shape[0], idx_i.shape[-1]
    if hoist is None:
        hoist = should_hoist(nt, T, n)
    scratch = [pltpu.VMEM((nt, n, 2 * T), jnp.float32)] if hoist else []
    return pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 4), jnp.float32),
        ),
        in_specs=[vspec] * 11,
        out_specs=(vspec, vspec),
        scratch_shapes=scratch,
        interpret=interpret,
    )(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, Xc, Zc, Sc, Lc, gc)


@functools.partial(jax.jit, static_argnames=(
    "r", "d", "max_iters", "kappa", "theta", "initial_radius",
    "max_rejections", "grad_tol", "interpret", "hoist", "sel_mode"))
def rtr_full_call(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, Xc, Zc, Lc,
                  *, r: int, d: int, max_iters: int, kappa: float,
                  theta: float, initial_radius: float, max_rejections: int,
                  grad_tol: float = 0.0, interpret: bool = False,
                  hoist: bool | None = None, sel_mode: str = "f32"):
    """Invoke the fully-fused single-step RTR kernel for one agent: only
    the pose buffer halves [Xc | Zc], the preconditioner factors and the
    edge tiles go in — gradient, curvature and norm are computed in-kernel.

    Returns (X_out_c [rk, n],
             stats [1, 5] = (attempts, accepted, f0, f, gn0)).
    """
    rk, n = Xc.shape
    kern = functools.partial(_rtr_full_kernel, r=r, d=d,
                             max_iters=max_iters, kappa=kappa, theta=theta,
                             initial_radius=initial_radius,
                             max_rejections=max_rejections,
                             grad_tol=grad_tol, sel_mode=sel_mode)
    vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
    nt, T = idx_i.shape[0], idx_i.shape[-1]
    if hoist is None:
        hoist = should_hoist(nt, T, n, itemsize=4 if sel_mode == "f32" else 2)
    sel_t = jnp.float32 if sel_mode == "f32" else jnp.bfloat16
    scratch = [pltpu.VMEM((nt, n, 2 * T), sel_t)] if hoist else []
    return pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 5), jnp.float32),
        ),
        in_specs=[vspec] * 9,
        out_specs=(vspec, vspec),
        scratch_shapes=scratch,
        interpret=interpret,
    )(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, Xc, Zc, Lc)


@functools.partial(jax.jit, static_argnames=(
    "r", "d", "max_iters", "kappa", "theta", "initial_radius",
    "max_rejections", "grad_tol", "interpret", "hoist", "sel_mode"))
def rtr_refine_full_call(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, rho_rot,
                         rho_trn, Rc, Dc, Dzc, g0c, Grefc, S0c, Lc, *,
                         r: int, d: int, max_iters: int, kappa: float,
                         theta: float, initial_radius: float,
                         max_rejections: int, grad_tol: float = 0.0,
                         interpret: bool = False, hoist: bool | None = None,
                         sel_mode: str = "f32"):
    """Invoke the fully-fused re-centered RTR kernel for one agent: the
    recenter constants go in (reference point, residuals, g0, G_ref, S0 in
    component-major/tile layouts), the updated correction comes out.

    Returns (D_out_c [rk, n],
             stats [1, 5] = (attempts, accepted, df0, df, gn0)).
    """
    rk, n = Dc.shape
    kern = functools.partial(_rtr_refine_full_kernel, r=r, d=d,
                             max_iters=max_iters, kappa=kappa, theta=theta,
                             initial_radius=initial_radius,
                             max_rejections=max_rejections,
                             grad_tol=grad_tol, sel_mode=sel_mode)
    vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
    nt, T = idx_i.shape[0], idx_i.shape[-1]
    if hoist is None:
        hoist = should_hoist(nt, T, n, itemsize=4 if sel_mode == "f32" else 2)
    sel_t = jnp.float32 if sel_mode == "f32" else jnp.bfloat16
    scratch = [pltpu.VMEM((nt, n, 2 * T), sel_t)] if hoist else []
    return pl.pallas_call(
        kern,
        out_shape=(
            jax.ShapeDtypeStruct((rk, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 5), jnp.float32),
        ),
        in_specs=[vspec] * 15,
        out_specs=(vspec, vspec),
        scratch_shapes=scratch,
        interpret=interpret,
    )(idx_i, idx_j, rot_t, trn_t, wk_t, wt_t, rho_rot, rho_trn,
      Rc, Dc, Dzc, g0c, Grefc, S0c, Lc)


#: Hoisted one-hot budget: materialize the [nt, n, 2T] paired local
#: selection stack once per kernel invocation when it fits alongside the
#: rest of the working set.
HOIST_BUDGET_BYTES = 4 << 20


def hoist_scratch_bytes(nt: int, tile: int, n: int,
                        itemsize: int = 4) -> int:
    """Bytes of the single [nt, n, 2T] PAIRED one-hot scratch stack
    (i-columns then j-columns per tile) — the single source for
    ``should_hoist``, the kernels' ``scratch_shapes``, and the dispatch
    gate's VMEM estimate (``rbcd._pallas_vmem_ok``).  ``itemsize`` is 2
    under the bf16 selection modes (bf16 one-hots), else 4."""
    return nt * (2 * tile) * n * itemsize


def should_hoist(nt: int, tile: int, n: int, itemsize: int = 4) -> bool:
    return hoist_scratch_bytes(nt, tile, n, itemsize) <= HOIST_BUDGET_BYTES
