from . import averaging  # noqa: F401
