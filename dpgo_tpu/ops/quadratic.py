"""Edge-list quadratic PGO cost: f(X) = 0.5 <Q, X^T X> + <X, G>, without Q or G.

The reference materializes the sparse connection Laplacian ``Q`` (CHOLMOD /
Eigen sparse, ``DPGO_utils.cpp:214-286``, ``PGOAgent.cpp:720-781``) and the
linear term ``G`` from neighbor poses (``PGOAgent.cpp:783-859``), then
multiplies ``X * Q`` (``QuadraticProblem.cpp:50-73``).  On TPU, sparse
matrices with (d+1)-block structure are better expressed as the edge list
itself: residuals per edge via two gathers, gradients via scatter-add
(segment sum).  XLA fuses the whole thing; there is no assembled matrix.

For an SE(d) edge e = (i -> j) with measurement (R_e, t_e), precisions
(kappa_e, tau_e) and GNC weight w_e, and pose blocks X_i = [Y_i | p_i]:

    rR_e = Y_j - Y_i R_e          (r x d)     "rotation residual"
    rt_e = p_j - p_i - Y_i t_e    (r,)        "translation residual"

    f(X) = 0.5 sum_e w_e (kappa_e ||rR_e||_F^2 + tau_e ||rt_e||^2)

which reproduces the reference cost exactly (the connection Laplacian is
the Gram matrix of these residuals; see ``constructOrientedConnection-
IncidenceMatrixSE``, ``DPGO_utils.cpp:214-276``).

A *local* (per-agent) problem evaluates the same sum over a buffer
``Xbuf = concat([X_local (n), Z_neighbor (s)])``: private edges index both
endpoints < n, shared edges have one endpoint >= n.  The gradient restricted
to the first n slots is then exactly ``X Q + G`` of the reference; the
Hessian-vector product is the same linear map with the neighbor slots zeroed
(neighbors are constants).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..types import EdgeSet


def _edge_terms(Xbuf: jax.Array, edges: EdgeSet):
    """Per-edge residuals. Xbuf: [N, r, d+1] -> (rR [E, r, d], rt [E, r])."""
    Xi = Xbuf[edges.i]  # [E, r, d+1]
    Xj = Xbuf[edges.j]
    Yi, pi = Xi[..., :-1], Xi[..., -1]
    Yj, pj = Xj[..., :-1], Xj[..., -1]
    rR = Yj - Yi @ edges.R
    rt = pj - pi - jnp.einsum("erd,ed->er", Yi, edges.t)
    return rR, rt


def cost(Xbuf: jax.Array, edges: EdgeSet) -> jax.Array:
    """f(X) = 0.5 sum_e w_e (kappa ||rR||^2 + tau ||rt||^2).

    Matches reference ``QuadraticProblem::f`` (``QuadraticProblem.cpp:50-60``)
    up to the constant ||neighbor||^2 terms for shared edges (which the
    reference's <X,G> form drops; irrelevant for optimization).
    """
    rR, rt = _edge_terms(Xbuf, edges)
    w = edges.mask * edges.weight
    quad = edges.kappa * jnp.sum(rR * rR, axis=(-2, -1)) + \
        edges.tau * jnp.sum(rt * rt, axis=-1)
    return 0.5 * jnp.sum(w * quad)


def egrad(Xbuf: jax.Array, edges: EdgeSet, n_out: int | None = None) -> jax.Array:
    """Euclidean gradient d f / d Xbuf, accumulated for the first ``n_out`` slots.

    Equivalent of the reference's ``X Q + G`` (``QuadraticProblem.cpp:62-66``)
    when ``Xbuf``'s tail slots hold (fixed) neighbor poses.  The map is linear
    in ``Xbuf``, so it doubles as the Hessian-vector product ``V Q``
    (``QuadraticProblem.cpp:68-73``) when called on a tangent vector whose
    neighbor slots are zero — see ``hessvec``.
    """
    N = Xbuf.shape[0]
    dtype = Xbuf.dtype
    # d/d X_j = [ wk * rR | wt * rt ];
    # d/d X_i = [ -wk * rR R^T - wt * outer(rt, t) | -wt * rt ].
    gi, gj = _edge_grad_terms(Xbuf, edges)
    out = jnp.zeros((N,) + Xbuf.shape[1:], dtype)
    out = out.at[edges.i].add(gi).at[edges.j].add(gj)
    return out if n_out is None else out[:n_out]


def _edge_grad_terms(Xbuf: jax.Array, edges: EdgeSet):
    """Per-edge gradient contributions (gi to endpoint i, gj to endpoint j),
    each [E, r, d+1] — the shared core of the scatter and gather paths."""
    rR, rt = _edge_terms(Xbuf, edges)
    w = edges.mask * edges.weight
    wk = (w * edges.kappa)[:, None, None]
    wt = (w * edges.tau)[:, None]
    gj = jnp.concatenate([wk * rR, (wt * rt)[..., None]], axis=-1)
    giY = -(wk * rR) @ jnp.swapaxes(edges.R, -1, -2) \
        - (wt * rt)[..., None] * edges.t[:, None, :]
    gi = jnp.concatenate([giY, -(wt * rt)[..., None]], axis=-1)
    return gi, gj


def egrad_ell(Xbuf: jax.Array, edges: EdgeSet, inc_slot: jax.Array,
              inc_mask: jax.Array) -> jax.Array:
    """Euclidean gradient via a padded per-pose incidence list (ELL layout):
    gather-only, no scatter.

    ``inc_slot: [n_out, K]`` indexes into the concatenation ``[gi | gj]``
    (slot ``e`` for edges where the pose is endpoint i, ``E + e`` where it
    is endpoint j); ``inc_mask: [n_out, K]`` zeroes padding.  Pose-graph
    degrees are small and near-uniform (4-12 across the benchmark suite),
    so the ELL padding waste is bounded while the summation becomes a dense
    gather + masked reduction — on TPU this beats XLA's scatter-add
    lowering of the ``egrad`` path, and it is the layout the tCG
    Hessian-vector hot loop runs on.
    """
    gi, gj = _edge_grad_terms(Xbuf, edges)
    g_both = jnp.concatenate([gi, gj], axis=0)  # [2E, r, d+1]
    contrib = g_both[inc_slot]                  # [n_out, K, r, d+1]
    return jnp.sum(contrib * inc_mask[:, :, None, None], axis=1)


def hessvec_ell(Vlocal: jax.Array, edges: EdgeSet, inc_slot: jax.Array,
                inc_mask: jax.Array, n_buf: int) -> jax.Array:
    """Hessian-vector product on the ELL layout (see ``egrad_ell``);
    the same linear map with neighbor slots zeroed."""
    pad = jnp.zeros((n_buf - Vlocal.shape[0],) + Vlocal.shape[1:],
                    Vlocal.dtype)
    Vbuf = jnp.concatenate([Vlocal, pad], axis=0)
    return egrad_ell(Vbuf, edges, inc_slot, inc_mask)


def hessvec(Vlocal: jax.Array, edges: EdgeSet, n_buf: int) -> jax.Array:
    """Hessian-vector product restricted to local poses: (V Q)_local.

    ``Vlocal: [n_local, r, d+1]`` is zero-padded to the full buffer size so
    neighbor poses act as constants (their Hessian block is excluded).
    """
    n_local = Vlocal.shape[0]
    pad = jnp.zeros((n_buf - n_local,) + Vlocal.shape[1:], Vlocal.dtype)
    Vbuf = jnp.concatenate([Vlocal, pad], axis=0)
    return egrad(Vbuf, edges, n_out=n_local)


def dense_q(edges: EdgeSet, n_buf: int) -> jax.Array:
    """Materialized connection Laplacian Q over the pose buffer,
    [(d+1) n_buf, (d+1) n_buf], pose-block-major.

    The reference assembles exactly this sparse matrix
    (``constructConnectionLaplacianSE``, ``DPGO_utils.cpp:214-286``;
    shared-edge diagonal blocks, ``PGOAgent.cpp:744-777``) for Eigen sparse
    products.  On TPU, for per-agent problems (a few hundred to a few
    thousand poses) the *dense* form is the fast path: the tCG
    Hessian-vector product becomes a single [r, (d+1)n] x [(d+1)n, (d+1)n]
    MXU matmul instead of a latency-bound gather/compute/reduce chain.
    Built by scatter-add once at setup and on GNC weight updates — never in
    the solver loop.

    Per SE(d) edge e = (i -> j) with T = [R_e | t_e] embedded as the
    (d+1) x (d+1) block [[R, t], [0, 1]] and Omega = diag(w kappa I_d,
    w tau):

        Q[ii] += T Omega T^T   Q[ij] -= T Omega
        Q[ji] -= Omega T^T     Q[jj] += Omega
    """
    E, d = edges.t.shape
    dtype = edges.t.dtype
    k = d + 1
    w = edges.mask * edges.weight
    wk = w * edges.kappa
    wt = w * edges.tau

    # T Omega = [[wk R, wt t], [0, wt]]  (k x k per edge)
    TOm = jnp.zeros((E, k, k), dtype)
    TOm = TOm.at[:, :d, :d].set(wk[:, None, None] * edges.R)
    TOm = TOm.at[:, :d, d].set(wt[:, None] * edges.t)
    TOm = TOm.at[:, d, d].set(wt)
    # T Omega T^T = [[wk I + wt t t^T, wt t], [wt t^T, wt]]
    Bii = jnp.zeros((E, k, k), dtype)
    Bii = Bii.at[:, :d, :d].set(
        wk[:, None, None] * jnp.eye(d, dtype=dtype)
        + wt[:, None, None] * edges.t[:, :, None] * edges.t[:, None, :])
    Bii = Bii.at[:, :d, d].set(wt[:, None] * edges.t)
    Bii = Bii.at[:, d, :d].set(wt[:, None] * edges.t)
    Bii = Bii.at[:, d, d].set(wt)
    # Omega
    om_diag = jnp.concatenate([jnp.tile(wk[:, None], (1, d)), wt[:, None]],
                              axis=-1)
    Bjj = om_diag[:, :, None] * jnp.eye(k, dtype=dtype)

    Q = jnp.zeros((n_buf, k, n_buf, k), dtype)
    Q = Q.at[edges.i, :, edges.i, :].add(Bii)
    Q = Q.at[edges.i, :, edges.j, :].add(-TOm)
    Q = Q.at[edges.j, :, edges.i, :].add(-jnp.swapaxes(TOm, -1, -2))
    Q = Q.at[edges.j, :, edges.j, :].add(Bjj)
    return Q.reshape(n_buf * k, n_buf * k)


def to_mat(X: jax.Array) -> jax.Array:
    """Pose blocks [..., n, r, d+1] -> stacked matrix [..., r, (d+1) n]
    (the reference's trajectory layout, ``PGOAgent.h:222``)."""
    n, r, k = X.shape[-3:]
    Xt = jnp.swapaxes(X, -3, -2)  # [..., r, n, d+1]
    return Xt.reshape(X.shape[:-3] + (r, n * k))


def from_mat(Xm: jax.Array, n: int) -> jax.Array:
    """Inverse of ``to_mat``: [..., r, (d+1) n] -> [..., n, r, d+1]."""
    r = Xm.shape[-2]
    k = Xm.shape[-1] // n
    Xt = Xm.reshape(Xm.shape[:-2] + (r, n, k))
    return jnp.swapaxes(Xt, -3, -2)


def diag_blocks(edges: EdgeSet, n_buf: int, n_out: int | None = None) -> jax.Array:
    """Diagonal (d+1)x(d+1) blocks of the connection Laplacian Q.

    Per edge (i -> j), block i receives T Omega T^T and block j receives
    Omega (the same structure the reference assembles for shared edges at
    ``PGOAgent.cpp:744-777``; for private edges these are Q's diagonal
    blocks from ``A Omega A^T``):

        B_ii = [[ w kappa I + w tau t t^T ,  w tau t ],
                [ w tau t^T               ,  w tau   ]]
        B_jj = diag(w kappa, ..., w kappa, w tau)

    Used by the block-Jacobi preconditioner that replaces the reference's
    CHOLMOD factorization of Q + 0.1 I (``QuadraticProblem.cpp:31-42``).
    """
    E, d = edges.t.shape
    dtype = edges.t.dtype
    w = edges.mask * edges.weight
    wk = w * edges.kappa
    wt = w * edges.tau

    Bi = jnp.zeros((E, d + 1, d + 1), dtype)
    Bi = Bi.at[:, :d, :d].set(
        wk[:, None, None] * jnp.eye(d, dtype=dtype)
        + wt[:, None, None] * edges.t[:, :, None] * edges.t[:, None, :]
    )
    Bi = Bi.at[:, :d, d].set(wt[:, None] * edges.t)
    Bi = Bi.at[:, d, :d].set(wt[:, None] * edges.t)
    Bi = Bi.at[:, d, d].set(wt)

    diag_j = jnp.concatenate([jnp.tile(wk[:, None], (1, d)), wt[:, None]], axis=-1)
    Bj = diag_j[:, :, None] * jnp.eye(d + 1, dtype=dtype)

    out = jnp.zeros((n_buf, d + 1, d + 1), dtype)
    out = out.at[edges.i].add(Bi).at[edges.j].add(Bj)
    return out if n_out is None else out[:n_out]


def precond_factors(blocks: jax.Array, shift: float) -> jax.Array:
    """Cholesky factors of (B_pose + shift I), batched over poses.

    The shift mirrors the reference's regularized factorization of
    Q + 0.1 I (``QuadraticProblem.cpp:37-42``) and guarantees SPD blocks.
    Unrolled fixed-size Cholesky (``ops.smallmat``): XLA's generic batched
    ``jnp.linalg.cholesky`` on [n, 4, 4] blocks is loop-lowered on TPU and
    profiled ~100x slower than the scalar-unrolled form.
    """
    from .smallmat import cholesky_small

    dh = blocks.shape[-1]
    return cholesky_small(blocks + shift * jnp.eye(dh, dtype=blocks.dtype))


def precond_apply(chol: jax.Array, V: jax.Array) -> jax.Array:
    """Solve V_pose (B_pose + shift I)^{-1} per pose.

    V: [n, r, d+1], chol: [n, d+1, d+1] lower.  Because each block is
    symmetric, right-division is a cho_solve on V^T (unrolled small-k
    substitution, ``ops.smallmat``).
    """
    from .smallmat import cho_solve_small

    Vt = jnp.swapaxes(V, -1, -2)  # [n, d+1, r]
    sol = cho_solve_small(chol, Vt)
    return jnp.swapaxes(sol, -1, -2)
