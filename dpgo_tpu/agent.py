"""Per-robot agent runtime with the reference's message-passing surface.

The batched RBCD core (``dpgo_tpu.models.rbcd``) is the TPU-native way to run
*all* agents on a chip/mesh.  This module is the complementary *deployment*
shape: one ``PGOAgent`` object per robot — each on its own host/process, with
any transport (ROS, gRPC, in-process calls) carrying the pose dictionaries —
mirroring the reference's ``PGOAgent`` (``include/DPGO/PGOAgent.h:284-486``,
``src/PGOAgent.cpp``) so a user of the reference finds the same surface:

=========================================  ====================================
reference (C++)                            here
=========================================  ====================================
``setPoseGraph``                           ``set_pose_graph``
``setLiftingMatrix``/``getLiftingMatrix``  ``set_lifting_matrix``/``get_lifting_matrix``
``getSharedPoseDict``                      ``get_shared_pose_dict``
``updateNeighborPoses``                    ``update_neighbor_poses``
``getAuxSharedPoseDict``                   ``get_aux_shared_pose_dict``
``updateAuxNeighborPoses``                 ``update_aux_neighbor_poses``
``getStatus``/``setNeighborStatus``        ``get_status``/``set_neighbor_status``
``shouldTerminate``                        ``should_terminate``
``setGlobalAnchor``                        ``set_global_anchor``
``getTrajectoryInLocalFrame``              ``trajectory_in_local_frame``
``getTrajectoryInGlobalFrame``             ``trajectory_in_global_frame``
``iterate``                                ``iterate``
``startOptimizationLoop``                  ``start_optimization_loop``
``endOptimizationLoop``                    ``end_optimization_loop``
``reset``                                  ``reset``
=========================================  ====================================

The compute inside ``iterate`` is the same jitted single-agent RTR step the
batched core vmaps (``models.rbcd._agent_update``); per-agent shapes are
static after ``set_pose_graph`` so each agent compiles its step once.

Deployment fast path (see ARCHITECTURE "Deployment fast path"): neighbor
poses live in a preallocated slot-indexed ``[S, r, d+1]`` buffer updated
by vectorized scatter (``update_neighbor_poses_packed`` consumes the
packed columnar wire vocabulary directly — no per-pose dicts), the buffer
and the lifted iterate ``X`` stay device-resident across iterates (the
step reads back one scalar, not ``X``; ``donate_argnums`` reuses the
buffer on accelerator backends), and publishing gathers only the public
rows (``get_public_pose_arrays``).  The
async optimization loop (``start_optimization_loop``) is a host thread firing
``iterate`` at ``Exp(rate)``-distributed intervals — the RA-L 2020
Poisson-clock model of ``runOptimizationLoop`` (``PGOAgent.cpp:876-898``) —
with a lock serializing iterate against concurrent pose updates (the
reference's three mutexes, ``PGOAgent.h:589-597``, collapse to one because
the jitted step consumes a consistent snapshot taken under the lock).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from .config import AgentParams, RobustCostType
from . import obs
from .obs import trace
from . import robust as robust_mod
from .types import EdgeSet, Measurements
from .utils import logger as logger_mod
from .utils.lie import lifting_matrix as make_lifting_matrix
from .ops import chordal, manifold, quadratic
from .models.rbcd import _agent_update, _edge_residuals
from .models.dist_init import _se, _se_inv, robust_frame_alignment
from .models.local_pgo import lift, round_solution

PoseID = tuple[int, int]  # (robot_id, pose_index) — reference DPGO_types.h:64
PoseDict = dict  # PoseID -> np.ndarray [r, d+1]


class AgentState(enum.Enum):
    """Agent lifecycle (reference ``PGOAgentState``, ``PGOAgent.h:46-54``)."""

    WAIT_FOR_DATA = 0
    WAIT_FOR_INITIALIZATION = 1
    INITIALIZED = 2


@dataclasses.dataclass
class PGOAgentStatus:
    """Gossiped observability struct (reference ``PGOAgent.h:163-207``)."""

    robot_id: int
    state: AgentState = AgentState.WAIT_FOR_DATA
    instance_number: int = 0
    iteration_number: int = 0
    ready_to_terminate: bool = False
    relative_change: float = float("inf")


class PGOAgent:
    """One robot's PGO runtime; the caller supplies the transport."""

    def __init__(self, robot_id: int, params: AgentParams):
        self.robot_id = int(robot_id)
        self.params = params
        self.d = params.d
        self.r = params.r
        self.num_robots = params.num_robots

        self._lock = threading.RLock()
        self._status = PGOAgentStatus(robot_id=self.robot_id)
        self._neighbor_status: dict[int, PGOAgentStatus] = {}

        self._ylift: np.ndarray | None = None
        if self.robot_id == 0:
            # Robot 0 generates the deterministic shared lifting matrix
            # (PGOAgent.cpp:46, fixedStiefelVariable DPGO_utils.cpp:502-507)
            # and its local frame is the global frame (PGOAgent.cpp:182-186).
            self.set_lifting_matrix(
                np.asarray(make_lifting_matrix(self.r, self.d, jnp.float64)))

        self._clear_problem()

        # Async loop (startOptimizationLoop, PGOAgent.cpp:861-916)
        self._loop_thread: threading.Thread | None = None
        self._end_loop = threading.Event()

    # -- problem ingestion --------------------------------------------------

    def _clear_problem(self):
        self.n = 0
        self._meas: Measurements | None = None
        self._edges: EdgeSet | None = None
        self._is_shared: np.ndarray | None = None   # [E] bool
        self._shared_other: np.ndarray | None = None  # [E] neighbor robot (or -1)
        self._is_lc: np.ndarray | None = None       # [E] bool (odometry = False)
        self._lc_upd: np.ndarray | None = None      # [E] LC & not known-inlier
        self._nbr_slot: dict[PoseID, int] = {}      # remote PoseID -> buffer slot
        self._slot_pose: list[PoseID] = []
        self._public: list[int] = []                # local public pose indices
        self._public_np = np.zeros(0, np.int64)
        self.X = None                               # [n, r, d+1] lifted
        self._T_local: np.ndarray | None = None     # [n, d, d+1] own frame
        self._X_init: np.ndarray | None = None
        self._weights: np.ndarray | None = None     # [E]
        self._weights_dev = None                    # device cache of weights
        self._shared_key_to_edge: dict = {}         # ((r1,p1),(r2,p2)) -> row
        self._mu = self.params.robust.gnc_init_mu
        self._num_weight_updates = 0
        # Slot-indexed neighbor cache (the deployment fast path): one
        # preallocated [S, r, d+1] buffer per pose family, updated by
        # vectorized scatter (no per-pose dict churn), with a device-
        # resident copy re-uploaded only when a neighbor update landed.
        self._nbr_vals = np.zeros((0, self.r, self.d + 1))
        self._nbr_have = np.zeros(0, bool)
        self._aux_vals = np.zeros((0, self.r, self.d + 1))
        self._aux_have = np.zeros(0, bool)
        self._nbr_ver = 0                # bumped on every regular scatter
        self._aux_ver = 0                # bumped on every aux scatter
        self._nbr_dev = None             # device mirror of _nbr_vals
        self._nbr_dev_ver = -1
        self._aux_dev = None             # merged aux-over-regular mirror
        self._aux_dev_ver = (-1, -1)
        self._slot_enc = np.zeros(0, np.int64)    # sorted (robot<<32)|pose
        self._slot_enc_order = np.zeros(0, np.int64)  # slot id per enc row
        # Transport bookkeeping (dpgo_tpu.comms): last accepted pose-frame
        # sequence per neighbor, and neighbors declared dead by the
        # transport (excluded from the should_terminate quorum; their
        # cached poses above stay frozen — the RA-L delay-tolerance model).
        self._nbr_pose_seq: dict[int, int] = {}
        self._nbr_aux_seq: dict[int, int] = {}
        self._lost_neighbors: set[int] = set()
        # Numerical-health bookkeeping (dpgo_tpu.obs.health): anomalies
        # this robot detected locally (NaN'd neighbor frames, non-finite
        # iterate change).  The counters ride the agent's outgoing bus
        # frame (``comms.bus.pack_agent_frame``) so the hub sees
        # fleet-wide health; nonzero only when telemetry was on (detection
        # is behind the zero-overhead fence).
        self._anom_count = 0
        self._anom_worst = 0  # 0 none / 1 warning / 2 critical
        self._global_anchor: np.ndarray | None = None
        # Nesterov sequences (PGOAgent.cpp:1054-1091)
        self._V: np.ndarray | None = None
        self._Y: np.ndarray | None = None
        self._gamma = 0.0
        self._alpha = 0.0
        self._step_fn = None
        self._status.state = AgentState.WAIT_FOR_DATA
        self._status.iteration_number = 0
        self._status.ready_to_terminate = False
        self._status.relative_change = float("inf")

    # -- device-resident iterate state --------------------------------------
    #
    # ``X`` stays on device across iterates (the jitted step's output feeds
    # the next step's input with no host round-trip); host code that reads
    # ``self.X`` gets a lazily materialized numpy mirror.  Assigning either
    # a numpy or a jax array works — the other representation is dropped
    # and rebuilt on demand.

    @property
    def X(self):
        if self._X_host is None and self._X_dev is not None:
            self._X_host = np.asarray(self._X_dev)
        return self._X_host

    @X.setter
    def X(self, value):
        if value is None:
            self._X_dev = None
            self._X_host = None
        elif isinstance(value, jax.Array):
            self._X_dev = value
            self._X_host = None
        else:
            self._X_host = np.asarray(value)
            self._X_dev = None

    def _X_device(self):
        """The lifted iterate as a device array (uploaded once, reused)."""
        if self._X_dev is None and self._X_host is not None:
            self._X_dev = jnp.asarray(self._X_host)
        return self._X_dev

    def _weights_device(self):
        if self._weights_dev is None:
            self._weights_dev = jnp.asarray(self._weights)
        return self._weights_dev

    def set_lifting_matrix(self, ylift: np.ndarray) -> None:
        """Install the shared lifting matrix (reference ``setLiftingMatrix``,
        broadcast from robot 0, ``MultiRobotExample.cpp:139-146``)."""
        ylift = np.asarray(ylift, np.float64)
        assert ylift.shape == (self.r, self.d), ylift.shape
        self._ylift = ylift

    def get_lifting_matrix(self) -> np.ndarray:
        assert self._ylift is not None, "lifting matrix not set"
        return self._ylift

    def set_pose_graph(self, odometry: Measurements,
                       private_loop_closures: Measurements,
                       shared_loop_closures: Measurements) -> None:
        """Ingest this robot's measurements (reference ``setPoseGraph``,
        ``PGOAgent.cpp:126-195`` + ``addOdometry``/``add*LoopClosure``
        ``:197-248``) and run local initialization in the robot's own frame.
        """
        with self._lock:
            if self._status.state != AgentState.WAIT_FOR_DATA:
                # The reference requires WAIT_FOR_DATA here (assert at
                # PGOAgent.cpp:128); re-ingestion on a live agent rolls to a
                # new problem instance like reset() so no stale state (X,
                # neighbor caches, aux sequences, gossiped statuses of the
                # previous instance) survives into the new graph.
                instance = self._status.instance_number + 1
                self._clear_problem()
                self._status.instance_number = instance
                self._neighbor_status.clear()
            me = self.robot_id
            all_meas = Measurements.concatenate(
                [odometry, private_loop_closures, shared_loop_closures])
            n = 0
            for k in range(len(all_meas)):
                if int(all_meas.r1[k]) == me:
                    n = max(n, int(all_meas.p1[k]) + 1)
                if int(all_meas.r2[k]) == me:
                    n = max(n, int(all_meas.p2[k]) + 1)
            self.n = n
            self._meas = all_meas

            E = len(all_meas)
            is_shared = np.zeros(E, bool)
            shared_other = np.full(E, -1, np.int64)
            ti = np.zeros(E, np.int64)
            hi = np.zeros(E, np.int64)
            pub: dict[int, None] = {}
            self._nbr_slot = {}
            self._slot_pose = []
            for k in range(E):
                a, p = int(all_meas.r1[k]), int(all_meas.p1[k])
                b, q = int(all_meas.r2[k]), int(all_meas.p2[k])
                if a == me and b == me:
                    ti[k], hi[k] = p, q
                    continue
                is_shared[k] = True
                if a == me:
                    shared_other[k] = b
                    pub.setdefault(p)
                    ti[k] = p
                    hi[k] = n + self._slot(b, q)
                else:
                    shared_other[k] = a
                    pub.setdefault(q)
                    hi[k] = q
                    ti[k] = n + self._slot(a, p)
            self._public = sorted(pub)
            self._public_np = np.asarray(self._public, np.int64)
            self._is_shared = is_shared
            self._shared_other = shared_other
            # Preallocate the slot-indexed neighbor buffers and the sorted
            # encoded-key table the vectorized scatter searches against.
            S = len(self._slot_pose)
            self._nbr_vals = np.zeros((S, self.r, self.d + 1))
            self._nbr_have = np.zeros(S, bool)
            self._aux_vals = np.zeros((S, self.r, self.d + 1))
            self._aux_have = np.zeros(S, bool)
            enc = np.fromiter(((r << 32) | p for (r, p) in self._slot_pose),
                              np.int64, S)
            order = np.argsort(enc, kind="stable")
            self._slot_enc = enc[order]
            self._slot_enc_order = order.astype(np.int64)
            self._shared_key_to_edge = {
                ((int(all_meas.r1[k]), int(all_meas.p1[k])),
                 (int(all_meas.r2[k]), int(all_meas.p2[k]))): k
                for k in np.nonzero(is_shared)[0]}

            is_lc = np.arange(E) >= len(odometry)
            from .types import edge_set_from_measurements
            self._edges = edge_set_from_measurements(
                all_meas, tail_index=ti, head_index=hi, is_lc=is_lc,
                dtype=jnp.float64)
            # Static masks hoisted out of the iterate() hot path.
            self._is_lc = np.asarray(is_lc, bool)
            self._lc_upd = is_lc & ~np.asarray(all_meas.is_known_inlier, bool)
            self._weights = np.asarray(all_meas.weight, np.float64).copy()
            self._mu = self.params.robust.gnc_init_mu

            # Local init in own frame (localInitialization, PGOAgent.cpp:947-962)
            priv = ~is_shared
            sub = all_meas.select(priv)
            sub = dataclasses.replace(sub, num_poses=n,
                                      r1=np.zeros(len(sub), np.int32),
                                      r2=np.zeros(len(sub), np.int32))
            sub_edges = edge_set_from_measurements(sub, dtype=jnp.float64)
            if self.params.robust.cost_type == RobustCostType.L2:
                T0 = chordal.chordal_initialization(sub_edges, n)
            else:
                T0 = chordal.odometry_from_edges(sub_edges, n)
            self._T_local = np.asarray(T0)

            if self.robot_id == 0:
                self._lift_and_initialize(self._T_local)
            else:
                self._status.state = AgentState.WAIT_FOR_INITIALIZATION
                self._obs_state_event()

    def _slot(self, robot: int, pose: int) -> int:
        key = (robot, pose)
        if key not in self._nbr_slot:
            self._nbr_slot[key] = len(self._slot_pose)
            self._slot_pose.append(key)
        return self._nbr_slot[key]

    def _lift_and_initialize(self, T_global_frame: np.ndarray) -> None:
        """X = YLift . T per pose (PGOAgent.cpp:183, 415), enter INITIALIZED."""
        assert self._ylift is not None, "lifting matrix required before init"
        X = np.asarray(lift(jnp.asarray(T_global_frame), jnp.asarray(self._ylift)))
        self.X = X
        self._X_init = X.copy()
        self._V = X.copy()
        self._Y = X.copy()
        self._gamma = 0.0
        self._alpha = 0.0
        self._status.state = AgentState.INITIALIZED
        self._obs_state_event()
        self._build_step()

    def _build_step(self):
        params = self.params
        pallas = self._pallas_tiles()
        n = max(self.n, 1)

        def step(X_local, z, weights):
            edges = self._edges._replace(weight=weights)
            X_new, gn = _agent_update(X_local, z, edges, params,
                                      pallas=pallas)
            # Relative change in-kernel: the host needs one scalar per
            # iterate, not the full X buffer, to update the status gossip.
            rel = jnp.sqrt(jnp.sum((X_new - X_local) ** 2) / n)
            return X_new, gn, rel

        # Donating X lets the jitted step reuse the iterate buffer in
        # place round over round (X never round-trips to host).  CPU's
        # runtime does not implement donation and would warn every solve.
        donate = (0,) if jax.default_backend() != "cpu" else ()
        self._step_fn = jax.jit(step, donate_argnums=donate)

    def _pallas_tiles(self):
        """Tile-major edge arrays when this robot's iterate should run the
        VMEM Pallas kernel — the same engine/gates as the batched core
        (``rbcd._formulation``): RTR, TPU backend (or pallas_tcg=True for
        interpreter-mode testing), within the kernel's VMEM budget.  The
        deployment surface previously always took the ELL path, so a
        per-robot ``iterate()`` ran a different engine than ``solve_rbcd``
        on the identical problem."""
        from .config import ROptAlg
        from .models.rbcd import (_edge_tile_shape, agent_edge_tiles,
                                  pallas_vmem_ok)

        sp = self.params.solver
        forced = sp.pallas_tcg is True
        if sp.algorithm != ROptAlg.RTR:
            if forced:
                raise ValueError(
                    "pallas_tcg=True cannot run on this agent: "
                    "algorithm is not RTR")
            return None
        if sp.pallas_tcg is False or \
                not (forced or jax.default_backend() == "tpu"):
            return None
        if jax.config.read("jax_enable_x64") and not forced:
            # The kernel is float32-only; with x64 live this agent's f64
            # arrays would be silently clamped every iterate (the batched
            # core's _formulation routes f64 problems to the f64 ELL path
            # for the same reason).  An explicit pallas_tcg=True still
            # honors the force — the deployment surface documents that the
            # kernel computes in f32 (interpreter-mode testing).
            return None
        from .models.rbcd import resolved_sel_mode

        n, s = self.n, len(self._slot_pose)
        e = int(self._edges.i.shape[0])
        bf16 = resolved_sel_mode(self.params) != "f32"
        # Wide-tile parity with the batched core (``build_graph``): bf16
        # selection modes stream T=256 tiles up to ~3000-pose buffers
        # (half-size one-hot transients) — the deployment surface
        # previously always took the narrow adaptive tile, so a per-robot
        # ``iterate()`` ran measurably narrower dots than ``solve_rbcd``
        # on the identical problem (the round-5 packed+wide promotion).
        T, nt = _edge_tile_shape(n, s, e, wide=bf16)
        if not pallas_vmem_ok(n, s, self.params.r, self.d, T, nt, bf16):
            if forced:
                # Same no-silent-downgrade contract as the batched core
                # (rbcd._formulation): an explicit force that cannot be
                # honored must raise, not quietly run another engine.
                raise ValueError(
                    "pallas_tcg=True cannot run on this agent: the "
                    "per-robot problem exceeds the kernel's VMEM budget")
            return None
        eidx_i, eidx_j, rot_t, trn_t = agent_edge_tiles(
            self._edges.i, self._edges.j, self._edges.R, self._edges.t,
            n, s, wide=bf16)
        interpret = jax.default_backend() != "tpu"
        return (eidx_i, eidx_j, rot_t, trn_t, interpret)

    # -- observability hooks (dpgo_tpu.obs; no-ops when telemetry is off) ---

    def _obs_state_event(self) -> None:
        """Emit a lifecycle transition event (WAIT_FOR_DATA ->
        WAIT_FOR_INITIALIZATION -> INITIALIZED).  Called at the points the
        state actually changes; zero work when no run is ambient."""
        run = obs.get_run()
        if run is None:
            return
        run.event("agent_state", phase="lifecycle", robot=self.robot_id,
                  state=self._status.state.name,
                  instance=self._status.instance_number,
                  iteration=self._status.iteration_number)

    def _obs_comms_bytes(self, direction: str, nbytes: int,
                         neighbor_id: int | None = None) -> None:
        """Account one pose message: messages + bytes, labeled by robot and
        (for receives) the peer — the per-neighbor communication volume the
        reference driver hand-counts (``MultiRobotExample.cpp:274-279``).
        ``nbytes`` is read off the packed payload by the callers — no
        per-block iteration on the hot path."""
        run = obs.get_run()
        if run is None or not nbytes:
            return
        labels = {"robot": self.robot_id}
        if neighbor_id is not None:
            labels["neighbor"] = neighbor_id
        run.counter(f"comms_messages_{direction}",
                    f"pose messages {direction}").inc(1, **labels)
        run.counter(f"comms_bytes_{direction}",
                    f"pose payload bytes {direction}",
                    unit="bytes").inc(int(nbytes), **labels)

    def _obs_comms(self, direction: str, pose_dict: PoseDict,
                   neighbor_id: int | None = None) -> None:
        """Dict-vocabulary wrapper of ``_obs_comms_bytes`` (v1 callers)."""
        if obs.get_run() is None or not pose_dict:
            return
        nbytes = sum(np.asarray(b).nbytes for b in pose_dict.values())
        self._obs_comms_bytes(direction, nbytes, neighbor_id)

    # -- pose sharing (the message vocabulary, SURVEY.md section 2.4) -------

    def get_shared_pose_dict(self) -> PoseDict:
        """Public poses of X (reference ``getSharedPoseDict``,
        ``PGOAgent.cpp:95-105``)."""
        with self._lock:
            if self.X is None:
                return {}
            out = {(self.robot_id, p): self.X[p].copy() for p in self._public}
        self._obs_comms("sent", out)
        return out

    def get_public_pose_arrays(self):
        """Packed publish fast path: ``(robot_ids, pose_ids, values)`` for
        this robot's public poses as three arrays (the columnar wire
        vocabulary), or None while uninitialized.  When X is device-
        resident only the public rows are gathered and transferred — the
        full buffer never round-trips to host just to publish."""
        with self._lock:
            if self._X_dev is None and self._X_host is None:
                return None
            idx = self._public_np
            if self._X_host is not None:
                vals = self._X_host[idx].copy()
            else:
                vals = np.asarray(self._X_dev[jnp.asarray(idx)])
        self._obs_comms_bytes("sent", vals.nbytes + 8 * len(idx))
        return (np.full(len(idx), self.robot_id, np.int32),
                idx.astype(np.int32), vals)

    def get_aux_shared_pose_dict(self) -> PoseDict:
        """Public poses of the Nesterov aux sequence Y
        (``getAuxSharedPoseDict``, ``PGOAgent.cpp:107-118``)."""
        with self._lock:
            if self._Y is None:
                return {}
            out = {(self.robot_id, p): self._Y[p].copy() for p in self._public}
        self._obs_comms("sent", out)
        return out

    def _check_pose_seq(self, seq_cache: dict, neighbor_id: int,
                        sequence: int | None) -> bool:
        """Monotonic per-neighbor sequence check (under the lock).  Returns
        True when the message is fresh; a stale/reordered/duplicate frame
        (sequence at or below the highest already accepted) must not roll
        the neighbor cache backwards."""
        if sequence is None:
            return True  # sequence-less transport (in-process calls)
        if sequence <= seq_cache.get(neighbor_id, -1):
            return False
        seq_cache[neighbor_id] = int(sequence)
        return True

    def _obs_anomaly(self, kind: str, severity: str, **fields) -> None:
        """Report one locally-detected numerical anomaly through the run's
        health monitor (``anomaly`` event + counter + dump/abort policy)
        and bump the counters that ride this robot's outgoing bus frame.
        Zero work when no run is ambient."""
        run = obs.get_run()
        if run is None:
            return
        from .obs.health import SEVERITIES, monitor_for

        monitor_for(run).anomaly(kind, severity, robot=self.robot_id,
                                 iteration=self._status.iteration_number,
                                 **fields)
        self._anom_count += 1
        self._anom_worst = max(self._anom_worst,
                               SEVERITIES.index(severity) + 1)

    def health_counters(self) -> tuple[int, int]:
        """``(anomaly_count, worst_severity)`` — worst is 0 none /
        1 warning / 2 critical.  The payload ``pack_agent_frame`` ships."""
        return self._anom_count, self._anom_worst

    def _obs_stale_dropped(self, neighbor_id: int) -> None:
        run = obs.get_run()
        if run is None:
            return
        run.counter("comms_stale_dropped",
                    "pose messages dropped as stale/reordered").inc(
            1, robot=self.robot_id, neighbor=neighbor_id)

    def _scatter_neighbor(self, robots: np.ndarray, poses: np.ndarray,
                          vals: np.ndarray, aux: bool = False) -> None:
        """Vectorized slot scatter (under the lock): binary-search the
        incoming ``(robot, pose)`` keys against the sorted encoded slot
        table, write the matching rows of the preallocated buffer in one
        fancy-index assignment, drop keys this agent never references."""
        if robots.size == 0 or self._slot_enc.size == 0:
            return
        enc = (robots.astype(np.int64) << 32) | poses.astype(np.int64)
        pos = np.searchsorted(self._slot_enc, enc)
        pos = np.minimum(pos, self._slot_enc.size - 1)
        ok = self._slot_enc[pos] == enc
        slots = self._slot_enc_order[pos[ok]]
        if slots.size == 0:
            return
        if aux:
            self._aux_vals[slots] = vals[ok]
            self._aux_have[slots] = True
            self._aux_ver += 1
        else:
            self._nbr_vals[slots] = vals[ok]
            self._nbr_have[slots] = True
            self._nbr_ver += 1

    @staticmethod
    def _pose_dict_arrays(pose_dict: PoseDict):
        keys = list(pose_dict)
        robots = np.fromiter((k[0] for k in keys), np.int64, len(keys))
        poses = np.fromiter((k[1] for k in keys), np.int64, len(keys))
        vals = np.stack([np.asarray(pose_dict[k], np.float64) for k in keys])
        return robots, poses, vals

    def update_neighbor_poses(self, neighbor_id: int, pose_dict: PoseDict,
                              sequence: int | None = None) -> None:
        """Receive a neighbor's public poses (``updateNeighborPoses``,
        ``PGOAgent.cpp:434-458``) in the v1 dict vocabulary.  The packed
        wire path lands in ``update_neighbor_poses_packed`` instead.

        ``sequence`` is the transport's monotonic frame number for this
        neighbor (``dpgo_tpu.comms`` stamps it): a stale or reordered frame
        is dropped and counted instead of silently overwriting fresher
        cached poses.  A fresh frame from a neighbor previously declared
        lost revives it (it is talking again).
        """
        if pose_dict:
            robots, poses, vals = self._pose_dict_arrays(pose_dict)
        else:
            robots = poses = np.zeros(0, np.int64)
            vals = np.zeros((0, self.r, self.d + 1))
        self.update_neighbor_poses_packed(neighbor_id, robots, poses, vals,
                                          sequence=sequence)

    def _invalidate_neighbor_cache(self, neighbor_id: int) -> None:
        """Drop every cached pose (regular + aux) received from
        ``neighbor_id`` (under the lock).  The iterate skips optimization
        until the revived neighbor's fresh frames refill its slots —
        exactly the missing-pose contract of ``_neighbor_buffer``."""
        slots = np.asarray([s for (r, _p), s in self._nbr_slot.items()
                            if r == neighbor_id], np.int64)
        if slots.size:
            self._nbr_have[slots] = False
            self._aux_have[slots] = False
            self._nbr_ver += 1
            self._aux_ver += 1

    def update_neighbor_poses_packed(self, neighbor_id: int,
                                     robots: np.ndarray, poses: np.ndarray,
                                     vals: np.ndarray,
                                     sequence: int | None = None) -> None:
        """The columnar receive fast path: index vectors + one contiguous
        value payload feed the vectorized buffer scatter directly.  The
        first message from an INITIALIZED neighbor triggers robust frame
        alignment (``PGOAgent.cpp:369-432``).

        A frame from a neighbor previously declared lost REVIVES it with a
        sequence reset: the revived robot may have restarted (its sequence
        numbering starts over, so the monotonic check must not drop its
        fresh frames as stale), and its pre-outage cached poses are
        invalidated rather than merged — only the fresh stream is trusted
        after a partition heals."""
        revived = False
        with self._lock:
            if neighbor_id in self._lost_neighbors:
                revived = True
                stale = False
                self._nbr_pose_seq.pop(neighbor_id, None)
                self._nbr_aux_seq.pop(neighbor_id, None)
                self._invalidate_neighbor_cache(neighbor_id)
                self._lost_neighbors.discard(neighbor_id)
                if sequence is not None:
                    self._nbr_pose_seq[neighbor_id] = int(sequence)
            elif not self._check_pose_seq(self._nbr_pose_seq, neighbor_id,
                                          sequence):
                stale = True
            else:
                stale = False
        if stale:
            self._obs_stale_dropped(neighbor_id)
            return
        if revived:
            run = obs.get_run()
            if run is not None:
                run.event("peer_revived", phase="comms",
                          robot=self.robot_id, peer=neighbor_id,
                          iteration=self._status.iteration_number)
        robots, poses = np.asarray(robots), np.asarray(poses)
        vals = np.asarray(vals, np.float64)
        self._obs_comms_bytes("received", vals.nbytes + 8 * robots.size,
                              neighbor_id)
        # NaN sentinel on the ingested neighbor frame (telemetry-on only:
        # the isfinite sweep over the few public pose blocks is obs-owned
        # work).  Detection only — the frame is still applied, so the
        # solver's math is identical with telemetry on or off; the
        # anomaly event + flight recorder are how the poisoning is
        # diagnosed, and the counters ride the bus for fleet-wide view.
        if obs.get_run() is not None and vals.size \
                and not np.isfinite(vals).all():
            self._obs_anomaly("non_finite_neighbor_frame", "critical",
                              neighbor=int(neighbor_id),
                              poses=int(vals.shape[0]))
        with self._lock:
            self._scatter_neighbor(robots, poses, vals)
            if (self._status.state == AgentState.WAIT_FOR_INITIALIZATION
                    and self._neighbor_is_initialized(neighbor_id)):
                self._try_initialize_in_global_frame(neighbor_id)

    def update_aux_neighbor_poses(self, neighbor_id: int, pose_dict: PoseDict,
                                  sequence: int | None = None) -> None:
        """(``updateAuxNeighborPoses``, ``PGOAgent.cpp:460-479``)."""
        if pose_dict:
            robots, poses, vals = self._pose_dict_arrays(pose_dict)
        else:
            robots = poses = np.zeros(0, np.int64)
            vals = np.zeros((0, self.r, self.d + 1))
        self.update_aux_neighbor_poses_packed(neighbor_id, robots, poses,
                                              vals, sequence=sequence)

    def update_aux_neighbor_poses_packed(self, neighbor_id: int,
                                         robots: np.ndarray,
                                         poses: np.ndarray,
                                         vals: np.ndarray,
                                         sequence: int | None = None) -> None:
        with self._lock:
            stale = not self._check_pose_seq(self._nbr_aux_seq, neighbor_id,
                                             sequence)
        if stale:
            self._obs_stale_dropped(neighbor_id)
            return
        robots, poses = np.asarray(robots), np.asarray(poses)
        vals = np.asarray(vals, np.float64)
        self._obs_comms_bytes("received", vals.nbytes + 8 * robots.size,
                              neighbor_id)
        with self._lock:
            self._scatter_neighbor(robots, poses, vals, aux=True)

    # -- dict-compat views of the slot-indexed neighbor cache ---------------

    def _nbr_lookup(self, key: PoseID, aux: bool = False) -> np.ndarray | None:
        """One cached neighbor block by ``(robot, pose)`` key (under the
        lock), or None when it has not been received."""
        slot = self._nbr_slot.get(key)
        if slot is None:
            return None
        if aux:
            if not self._aux_have[slot]:
                return None
            return self._aux_vals[slot]
        if not self._nbr_have[slot]:
            return None
        return self._nbr_vals[slot]

    @property
    def _neighbor_poses(self) -> dict:
        """Received regular neighbor poses as a dict (diagnostics/tests —
        the hot path reads the slot buffer directly)."""
        return {key: self._nbr_vals[slot]
                for key, slot in self._nbr_slot.items()
                if self._nbr_have[slot]}

    def _neighbor_is_initialized(self, neighbor_id: int) -> bool:
        st = self._neighbor_status.get(neighbor_id)
        if st is not None:
            return st.state == AgentState.INITIALIZED
        if self._neighbor_status:
            # The transport does gossip statuses (we hold some): a neighbor
            # whose status has not arrived cannot be assumed initialized —
            # an early-publishing transport would otherwise let us frame-
            # align against garbage poses (``PGOAgent.cpp:434-458`` gates on
            # the gossiped ``mState`` for the same reason).
            return False
        # Status-less transport: receiving poses implies the sender is
        # initialized (the reference transport only publishes after init).
        return True

    def _try_initialize_in_global_frame(self, neighbor_id: int) -> None:
        """Robust frame alignment against ``neighbor_id``
        (``initializeInGlobalFrame`` + two-stage GNC averaging,
        ``PGOAgent.cpp:250-331``, ``369-432``).  Abort-and-retry on an empty
        inlier set (``:396-400``): state stays WAIT_FOR_INITIALIZATION and the
        next pose message tries again."""
        if self._meas is None or self._ylift is None:
            # Lifting-matrix broadcast has not arrived yet; defer — the next
            # pose message retries (same contract as the empty-inlier abort).
            return
        me, d = self.robot_id, self.d
        m = self._meas
        Rs, ts = [], []
        for k in np.nonzero(self._shared_other == neighbor_id)[0]:
            a, p = int(m.r1[k]), int(m.p1[k])
            b, q = int(m.r2[k]), int(m.p2[k])
            dT = _se(np.asarray(m.R[k]), np.asarray(m.t[k]), d)
            if a == me:  # outgoing me -> neighbor; frame1 = my p
                blk = self._nbr_lookup((b, q))
                if blk is None:
                    continue
                T_f1_f2 = dT
                p_mine = p
            else:        # incoming neighbor -> me; frame1 = my q
                blk = self._nbr_lookup((a, p))
                if blk is None:
                    continue
                T_f1_f2 = _se_inv(dT, d)
                p_mine = q
            # Round the neighbor's lifted public pose to SE(d) via YLift^T
            # (computeNeighborTransform, PGOAgent.cpp:250-288).
            Tn = np.asarray(round_solution(
                jnp.asarray(blk)[None],
                jnp.asarray(self._ylift)))[0]
            T_w2_f2 = _se(Tn[:, :d], Tn[:, d], d)
            T_w1_f1 = _se(self._T_local[p_mine, :, :d],
                          self._T_local[p_mine, :, d], d)
            T = T_w2_f2 @ _se_inv(T_f1_f2, d) @ _se_inv(T_w1_f1, d)
            Rs.append(T[:d, :d])
            ts.append(T[:d, d])
        if not Rs:
            return
        R, t, ninl = robust_frame_alignment(np.stack(Rs), np.stack(ts))
        if ninl == 0:
            return  # abort; retry on the next message (PGOAgent.cpp:396-400)
        Rl = self._T_local[:, :, :d]
        tl = self._T_local[:, :, d]
        T_global = np.zeros_like(self._T_local)
        T_global[:, :, :d] = np.einsum("ab,nbc->nac", R, Rl)
        T_global[:, :, d] = tl @ R.T + t
        self._lift_and_initialize(T_global)

    # -- status gossip ------------------------------------------------------

    def get_status(self) -> PGOAgentStatus:
        with self._lock:
            return dataclasses.replace(self._status)

    def set_neighbor_status(self, status: PGOAgentStatus) -> None:
        """(``setNeighborStatus``, ``PGOAgent.h:383-388``)."""
        with self._lock:
            self._neighbor_status[status.robot_id] = dataclasses.replace(status)

    def mark_neighbor_lost(self, neighbor_id: int) -> None:
        """The transport declared ``neighbor_id`` dead (closed connection,
        heartbeat silence).  Its cached poses stay frozen — optimization
        continues against the last received iterate, the RA-L 2020 delay
        tolerance — and it no longer blocks the ``should_terminate``
        quorum, so the surviving team can still finish.  A fresh pose
        message revives the neighbor (``update_neighbor_poses``) with a
        sequence reset and its stale cached poses invalidated — only data
        received after the heal is trusted."""
        neighbor_id = int(neighbor_id)
        if neighbor_id == self.robot_id:
            return
        with self._lock:
            if neighbor_id in self._lost_neighbors:
                return
            self._lost_neighbors.add(neighbor_id)
        run = obs.get_run()
        if run is not None:
            run.event("peer_lost", phase="comms", robot=self.robot_id,
                      peer=neighbor_id,
                      iteration=self._status.iteration_number)

    @property
    def lost_neighbors(self) -> list[int]:
        with self._lock:
            return sorted(self._lost_neighbors)

    def admit_neighbor(self, neighbor_id: int,
                       shared_loop_closures: "Measurements | None" = None
                       ) -> int:
        """The inverse of ``mark_neighbor_lost``: a robot JOINED the live
        solve (the bus's ``_joined`` handshake).  Clears any lost/sequence
        state for it, grows the termination quorum when the joiner's id
        exceeds the known fleet size — so a joining robot *extends* the
        consensus test: ``should_terminate`` now also requires the
        newcomer to be INITIALIZED and ready — and, when
        ``shared_loop_closures`` carries the inter-robot measurements
        connecting this agent to the joiner (robot-local indexing, the
        ``setPoseGraph`` vocabulary), extends the live problem in place:
        new edge rows, new neighbor slots grown through the existing
        packed-scatter seam, new public poses, and a rebuilt jitted step —
        with the iterate ``X``, GNC weights of existing edges, and all
        cached neighbor poses preserved.  Returns the number of edges
        added.  This agent's ``ready_to_terminate`` resets: consensus must
        re-form around the larger problem."""
        neighbor_id = int(neighbor_id)
        if neighbor_id == self.robot_id:
            return 0
        with self._lock:
            self._lost_neighbors.discard(neighbor_id)
            self._nbr_pose_seq.pop(neighbor_id, None)
            self._nbr_aux_seq.pop(neighbor_id, None)
            # A joiner is new or rebooted either way: whatever this agent
            # cached from it belongs to a previous life (same invalidation
            # as the lost->revive path — fresh frames refill the slots).
            self._invalidate_neighbor_cache(neighbor_id)
            if neighbor_id >= self.num_robots:
                self.num_robots = neighbor_id + 1
            added = 0
            if shared_loop_closures is not None \
                    and len(shared_loop_closures):
                added = self._extend_problem(shared_loop_closures)
            self._status.ready_to_terminate = False
        run = obs.get_run()
        if run is not None:
            run.event("peer_joined", phase="comms", robot=self.robot_id,
                      peer=neighbor_id, edges_added=added,
                      num_robots=self.num_robots,
                      iteration=self._status.iteration_number)
        return added

    def _extend_problem(self, new_meas: "Measurements") -> int:
        """Append measurements to the live problem (under the lock): the
        same deterministic index build as ``set_pose_graph``, re-run over
        the concatenated edge list.  The prefix rows reproduce the
        original slot/public assignment exactly (same first-reference
        order), so the preallocated neighbor buffers carry over by prefix
        copy and only the NEW slots grow the packed-scatter tables.  The
        iterate, GNC weights of existing edges, and mu are untouched; the
        jitted step rebuilds for the grown shapes (one recompile per
        admit, the price of a bigger problem)."""
        from .types import edge_set_from_measurements

        me = self.robot_id
        if self._meas is None:
            raise RuntimeError("admit_neighbor with measurements requires "
                               "set_pose_graph first")
        mine = (np.asarray(new_meas.r1) == me) | \
            (np.asarray(new_meas.r2) == me)
        sub = new_meas.select(mine) if not mine.all() else new_meas
        if len(sub) == 0:
            return 0
        own1 = np.asarray(sub.r1) == me
        own2 = np.asarray(sub.r2) == me
        if (np.asarray(sub.p1)[own1] >= self.n).any() or \
                (np.asarray(sub.p2)[own2] >= self.n).any():
            raise ValueError(
                "admitted measurements reference own poses this agent "
                "does not have — the joiner cannot add poses to a "
                "survivor's trajectory")
        all_meas = Measurements.concatenate([self._meas, sub])
        E = len(all_meas)
        is_lc = np.concatenate(
            [self._is_lc, np.ones(len(sub), bool)])

        old_S = len(self._slot_pose)
        old_nbr_vals, old_nbr_have = self._nbr_vals, self._nbr_have
        old_aux_vals, old_aux_have = self._aux_vals, self._aux_have
        self._nbr_slot = {}
        self._slot_pose = []
        is_shared = np.zeros(E, bool)
        shared_other = np.full(E, -1, np.int64)
        ti = np.zeros(E, np.int64)
        hi = np.zeros(E, np.int64)
        pub: dict[int, None] = {}
        n = self.n
        for k in range(E):
            a, p = int(all_meas.r1[k]), int(all_meas.p1[k])
            b, q = int(all_meas.r2[k]), int(all_meas.p2[k])
            if a == me and b == me:
                ti[k], hi[k] = p, q
                continue
            is_shared[k] = True
            if a == me:
                shared_other[k] = b
                pub.setdefault(p)
                ti[k] = p
                hi[k] = n + self._slot(b, q)
            else:
                shared_other[k] = a
                pub.setdefault(q)
                hi[k] = q
                ti[k] = n + self._slot(a, p)
        assert len(self._slot_pose) >= old_S and all(
            self._nbr_slot[key] == s
            for s, key in enumerate(self._slot_pose[:old_S])), \
            "prefix slot assignment must be stable across an extension"
        self._public = sorted(pub)
        self._public_np = np.asarray(self._public, np.int64)
        self._is_shared = is_shared
        self._shared_other = shared_other
        S = len(self._slot_pose)
        self._nbr_vals = np.zeros((S, self.r, self.d + 1))
        self._nbr_have = np.zeros(S, bool)
        self._aux_vals = np.zeros((S, self.r, self.d + 1))
        self._aux_have = np.zeros(S, bool)
        self._nbr_vals[:old_S] = old_nbr_vals
        self._nbr_have[:old_S] = old_nbr_have
        self._aux_vals[:old_S] = old_aux_vals
        self._aux_have[:old_S] = old_aux_have
        enc = np.fromiter(((r << 32) | p for (r, p) in self._slot_pose),
                          np.int64, S)
        order = np.argsort(enc, kind="stable")
        self._slot_enc = enc[order]
        self._slot_enc_order = order.astype(np.int64)
        self._nbr_ver += 1
        self._aux_ver += 1
        self._shared_key_to_edge = {
            ((int(all_meas.r1[k]), int(all_meas.p1[k])),
             (int(all_meas.r2[k]), int(all_meas.p2[k]))): k
            for k in np.nonzero(is_shared)[0]}
        self._meas = all_meas
        self._is_lc = np.asarray(is_lc, bool)
        self._edges = edge_set_from_measurements(
            all_meas, tail_index=ti, head_index=hi, is_lc=is_lc,
            dtype=jnp.float64)
        self._lc_upd = is_lc & ~np.asarray(all_meas.is_known_inlier, bool)
        # Existing edges keep their live (possibly GNC-updated) weights;
        # new edges start at their measurement weight.
        self._weights = np.concatenate(
            [self._weights, np.asarray(sub.weight, np.float64)])
        self._weights_dev = None
        if self._status.state == AgentState.INITIALIZED:
            self._build_step()  # grown shapes: one recompile
        return len(sub)

    def should_terminate(self) -> bool:
        """Team consensus (``shouldTerminate``, ``PGOAgent.cpp:1007-1031``):
        every robot INITIALIZED on this instance and ready to terminate.
        Robots declared lost by the transport (``mark_neighbor_lost``) are
        excluded from the quorum — a dead robot must not veto forever."""
        with self._lock:
            me = self._status
            if (me.state != AgentState.INITIALIZED
                    or not me.ready_to_terminate):
                return False
            for rid in range(self.num_robots):
                if rid == self.robot_id or rid in self._lost_neighbors:
                    continue
                st = self._neighbor_status.get(rid)
                if (st is None or st.state != AgentState.INITIALIZED
                        or st.instance_number != me.instance_number
                        or not st.ready_to_terminate):
                    return False
            return True

    # -- anchors & trajectories --------------------------------------------

    def set_global_anchor(self, anchor: np.ndarray) -> None:
        """Shared gauge for rounding (``setGlobalAnchor``,
        ``PGOAgent.cpp:1001-1005``): robot 0's first pose block of X."""
        with self._lock:
            anchor = np.asarray(anchor, np.float64)
            assert anchor.shape == (self.r, self.d + 1)
            self._global_anchor = anchor

    def get_global_anchor(self) -> np.ndarray | None:
        with self._lock:
            if self.robot_id == 0 and self.X is not None:
                return self.X[0].copy()
            return self._global_anchor

    def trajectory_in_local_frame(self) -> np.ndarray:
        """Rounded trajectory relative to this robot's first pose
        (``getTrajectoryInLocalFrame``, ``PGOAgent.cpp:481-498``)."""
        with self._lock:
            T = self._round(self.X)
            return _express_in_frame(T, T[0])

    def trajectory_in_global_frame(self) -> np.ndarray:
        """Rounded trajectory in the anchor's frame
        (``getTrajectoryInGlobalFrame``, ``PGOAgent.cpp:500-519``)."""
        with self._lock:
            assert self.X is not None, "agent not initialized"
            anchor = self.get_global_anchor()
            assert anchor is not None, "global anchor not set"
            Ta = np.asarray(round_solution(
                jnp.asarray(anchor)[None], jnp.asarray(self._ylift)))[0]
            return _express_in_frame(self._round(self.X), Ta)

    def _round(self, X: np.ndarray) -> np.ndarray:
        assert X is not None, "agent not initialized"
        return np.asarray(round_solution(jnp.asarray(X), jnp.asarray(self._ylift)))

    # -- fine-grained pose getters (PGOAgent.h:312-364) ---------------------

    def get_neighbors(self) -> list[int]:
        """Sorted neighbor robot IDs (``getNeighbors``,
        ``PGOAgent.cpp:577-581``)."""
        with self._lock:
            return sorted({r for (r, _p) in self._nbr_slot})

    def get_neighbor_public_poses(self, neighbor_id: int) -> list[int]:
        """Pose indices needed from ``neighbor_id``
        (``getNeighborPublicPoses``, ``PGOAgent.cpp:564-575``)."""
        with self._lock:
            return sorted(p for (r, p) in self._nbr_slot if r == neighbor_id)

    def get_shared_pose(self, index: int) -> np.ndarray | None:
        """Single pose block of X by local index, or None when the agent is
        uninitialized / the index is out of range (``getSharedPose``,
        ``PGOAgent.cpp:76-83``; like the reference, the index is not checked
        to be a public pose)."""
        with self._lock:
            if self._status.state != AgentState.INITIALIZED \
                    or not 0 <= index < self.n:
                return None
            return self.X[index].copy()

    def get_aux_shared_pose(self, index: int) -> np.ndarray | None:
        """Single pose block of the Nesterov aux sequence Y
        (``getAuxSharedPose``, ``PGOAgent.cpp:85-93``)."""
        assert self.params.acceleration, \
            "aux poses exist only with acceleration enabled"
        with self._lock:
            if self._status.state != AgentState.INITIALIZED \
                    or self._Y is None or not 0 <= index < self.n:
                return None
            return self._Y[index].copy()

    def _to_global_frame(self, Xi: np.ndarray) -> np.ndarray | None:
        """Anchor-frame [d, d+1] of one lifted block: ``Ya^T Xi`` with the
        anchor translation subtracted — the reference's linear map
        (``getPoseInGlobalFrame``, ``PGOAgent.cpp:521-538``), deliberately
        without an SO(d) projection."""
        anchor = self.get_global_anchor()
        if anchor is None:
            return None
        d = self.d
        Ya, pa = anchor[:, :d], anchor[:, d]
        Ti = Ya.T @ Xi
        Ti[:, d] -= Ya.T @ pa
        return Ti

    def get_pose_in_global_frame(self, pose_id: int) -> np.ndarray | None:
        """One of this robot's poses in the global (anchor) frame, or None
        when the anchor/initialization/index is missing
        (``getPoseInGlobalFrame``, ``PGOAgent.cpp:521-538``)."""
        with self._lock:
            if self._status.state != AgentState.INITIALIZED \
                    or not 0 <= pose_id < self.n:
                return None
            return self._to_global_frame(self.X[pose_id])

    def get_neighbor_pose_in_global_frame(self, neighbor_id: int,
                                          pose_id: int) -> np.ndarray | None:
        """A cached neighbor public pose in the global frame, or None when
        it has not been received (``getNeighborPoseInGlobalFrame``,
        ``PGOAgent.cpp:540-562``)."""
        with self._lock:
            if self._status.state != AgentState.INITIALIZED:
                return None
            Xi = self._nbr_lookup((neighbor_id, pose_id))
            if Xi is None:
                return None
            return self._to_global_frame(Xi.copy())

    # -- GNC weights --------------------------------------------------------

    def _update_loop_closure_weights(self) -> bool:
        """Recompute robust weights from current residuals
        (``updateLoopClosuresWeights``, ``PGOAgent.cpp:1181-1245``).

        Ownership (``:1201-1206``): for a shared edge, the LOWER robot id
        computes the weight; the other endpoint receives it via
        ``get_shared_weight_dict``/``update_shared_weights`` (the
        ``mPublishWeightsRequested`` path consumed by dpgo_ros).

        Returns False (without consuming the weight-update budget or
        annealing mu) when neighbor poses are missing so no residual can be
        evaluated yet.
        """
        z = self._neighbor_buffer()
        if z is None:
            return False
        edges = self._edges._replace(weight=self._weights_device())
        res = np.asarray(_edge_residuals(self._X_device(), z, edges))
        w_new = np.asarray(robust_mod.weight(
            jnp.asarray(res), self.params.robust, self._mu))
        own = (~self._is_shared) | (self._shared_other > self.robot_id)
        upd = self._lc_upd & own
        self._weights = np.where(upd, w_new, self._weights)
        self._weights_dev = None  # device copy re-uploads next step
        self._mu = float(robust_mod.gnc_update_mu(
            jnp.asarray(self._mu), self.params.robust))
        run = obs.get_run()
        if run is not None:
            # ``w_new`` is already a host array (the residual evaluation
            # above materialized it) — no device readback happens here.
            w_lc = self._weights[self._lc_upd]
            inl = float((w_lc > 0.5).mean()) if w_lc.size else 1.0
            run.gauge("gnc_mu", "GNC control parameter").set(
                self._mu, robot=self.robot_id)
            run.gauge("gnc_inlier_fraction",
                      "fraction of updatable LC edges at w>0.5").set(
                inl, robot=self.robot_id)
            run.histogram(
                "gnc_weight", "GNC weight distribution over updatable "
                "loop closures",
                buckets=(0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0),
            ).observe_many(w_lc, robot=self.robot_id)
            run.metric("gnc_mu", self._mu, phase="weight_update",
                       robot=self.robot_id,
                       iteration=self._status.iteration_number,
                       inlier_fraction=inl, num_lc=int(w_lc.size))
        if not self.params.robust_opt_warm_start and self._X_init is not None:
            self.X = self._X_init.copy()  # PGOAgent.cpp:657-662
        # initializeAcceleration after a weight update (PGOAgent.cpp:1054-1063)
        if self.params.acceleration:
            self._V = self.X.copy()
            self._gamma = 0.0
            self._alpha = 0.0
        return True

    def get_shared_weight_dict(self) -> dict:
        """Weights of owned shared edges, keyed ((r1,p1),(r2,p2)).

        Empty before ``set_pose_graph`` (a transport may poll any time)."""
        with self._lock:
            if self._is_shared is None:
                return {}
            out = {}
            m = self._meas
            for k in np.nonzero(self._is_shared &
                                (self._shared_other > self.robot_id))[0]:
                key = ((int(m.r1[k]), int(m.p1[k])), (int(m.r2[k]), int(m.p2[k])))
                out[key] = float(self._weights[k])
            return out

    def update_shared_weights(self, weight_dict: dict) -> None:
        """Receive weights for shared edges owned by a lower-id robot."""
        with self._lock:
            m = self._meas
            changed = False
            for key, w in weight_dict.items():
                k = self._shared_key_to_edge.get(key)
                if k is not None and not bool(m.is_known_inlier[k]):
                    self._weights[k] = float(w)
                    changed = True
            if changed:
                self._weights_dev = None

    # -- the RBCD step ------------------------------------------------------

    def _neighbor_buffer(self, aux: bool = False) -> jax.Array | None:
        """The slot-indexed neighbor buffer as a device array; None when
        any needed pose is missing (constructGMatrix failure -> skip
        update, ``PGOAgent.cpp:1122-1128``).  The device copy is uploaded
        only when a scatter landed since the last call — an iterate round
        with no fresh neighbor frames reuses the resident buffer."""
        if aux:
            # Aux poses fall back to regular ones for neighbors that have
            # not published Y yet (first accelerated round).
            if not (self._aux_have | self._nbr_have).all():
                return None
            ver = (self._aux_ver, self._nbr_ver)
            if self._aux_dev is None or self._aux_dev_ver != ver:
                z = np.where(self._aux_have[:, None, None],
                             self._aux_vals, self._nbr_vals)
                self._aux_dev = jnp.asarray(z)
                self._aux_dev_ver = ver
            return self._aux_dev
        if not self._nbr_have.all():
            return None
        if self._nbr_dev is None or self._nbr_dev_ver != self._nbr_ver:
            self._nbr_dev = jnp.asarray(self._nbr_vals)
            self._nbr_dev_ver = self._nbr_ver
        return self._nbr_dev

    def iterate(self, do_optimization: bool = True) -> bool:
        """One RBCD iteration (reference ``iterate``, ``PGOAgent.cpp:642-718``).

        Returns True when an optimization step was actually taken.  With
        acceleration, non-optimizing iterations still advance the momentum
        bookkeeping (X <- Y), as ``updateX(false, true)`` does
        (``PGOAgent.cpp:1094-1098``).
        """
        run = obs.get_run()
        # monotonic (not perf_counter) so the iterate span shares the
        # event stream's clock and lands on the merged fleet timeline.
        t0 = time.monotonic() if run is not None else 0.0
        t0_wall = time.time() if run is not None else 0.0
        with self._lock:
            if self._status.state != AgentState.INITIALIZED:
                return False
            params = self.params
            self._status.iteration_number += 1
            # Early-stop trajectory snapshot at iteration 50
            # (reference iterate(), PGOAgent.cpp:646-651).
            if self._status.iteration_number == 50 and params.log_data:
                self._log_global_trajectory("trajectory_early_stop.csv")
            robust_on = params.robust.cost_type != RobustCostType.L2
            if robust_on and \
                    self._status.iteration_number % params.robust_opt_inner_iters == 0 and \
                    (params.robust_opt_num_weight_updates <= 0 or
                     self._num_weight_updates < params.robust_opt_num_weight_updates):
                if self._update_loop_closure_weights():
                    self._num_weight_updates += 1

            accel = params.acceleration
            restart = accel and params.restart_interval > 0 and \
                self._status.iteration_number % params.restart_interval == 0

            if accel and restart:
                # restartNesterovAcceleration (PGOAgent.cpp:1040-1052)
                self._V = self.X.copy()
                self._Y = self.X.copy()
                self._gamma = 0.0
                self._alpha = 0.0
                accel = False

            stepped = False
            if accel:
                # Accelerated path: the momentum bookkeeping is host math,
                # so X materializes on host here (the deployment hot path
                # is the non-accelerated branch below).
                X_prev = self.X.copy()
                N = self.num_robots
                self._gamma = (1.0 + np.sqrt(1.0 + 4.0 * (N * self._gamma) ** 2)) \
                    / (2.0 * N)
                self._alpha = 1.0 / (self._gamma * N)
                Y = np.asarray(manifold.project(jnp.asarray(
                    (1.0 - self._alpha) * self.X + self._alpha * self._V)))
                self._Y = Y
                z = self._neighbor_buffer(aux=True)
                if do_optimization and z is not None \
                        and self._step_fn is not None:
                    X_new, _gn, _rel = self._step_fn(
                        jnp.asarray(Y), z, self._weights_device())
                    self.X = np.asarray(X_new)
                    stepped = True
                else:
                    self.X = self._Y.copy()  # updateX(false, true)
                self._V = np.asarray(manifold.project(jnp.asarray(
                    self._V + self._gamma * (self.X - self._Y))))
                rel = float(np.sqrt(
                    np.sum((self.X - X_prev) ** 2) / max(self.n, 1)))
            else:
                # Deployment fast path: X stays device-resident (the step
                # consumes last round's output in place — with donation on
                # accelerator backends the buffer is reused), the neighbor
                # buffer re-uploads only after a scatter, and the host
                # reads back ONE scalar (the relative change), not X.
                z = self._neighbor_buffer()
                rel = 0.0
                if do_optimization and z is not None \
                        and self._step_fn is not None:
                    X_new, _gn, rel_dev = self._step_fn(
                        self._X_device(), z, self._weights_device())
                    self.X = X_new
                    stepped = True
                    fetch_k = max(int(params.status_fetch_every), 1)
                    if run is not None or fetch_k == 1 or \
                            self._status.iteration_number % fetch_k == 0:
                        rel = float(rel_dev)
                    else:
                        # Verdict-cadence discipline (status_fetch_every):
                        # the scalar stays device-latched; the gossiped
                        # status reuses the last fetched value, so this
                        # iterate performs ZERO device->host transfers.
                        rel = self._status.relative_change
            self._status.relative_change = rel
            ready = stepped and rel <= params.rel_change_tol
            if robust_on and params.robust.cost_type == RobustCostType.GNC_TLS:
                lc = self._lc_upd
                if lc.any():
                    conv = np.asarray(robust_mod.is_weight_converged(
                        self._weights[lc]))
                    ready = ready and conv.mean() >= \
                        params.robust_opt_min_convergence_ratio
            self._status.ready_to_terminate = bool(ready)
            if run is not None:
                # The scalar rel-change readback above materialized the
                # step — the latency below includes the device work, with
                # no telemetry-added sync.
                dt = time.monotonic() - t0
                run.histogram(
                    "agent_iterate_seconds",
                    "PGOAgent.iterate wall-clock (lock + step + readback)",
                    unit="s").observe(dt, robot=self.robot_id)
                run.counter("agent_iterations",
                            "iterate() calls that took an optimization "
                            "step").inc(int(stepped), robot=self.robot_id)
                run.gauge("agent_rel_change",
                          "per-agent iterate relative change").set(
                    rel, robot=self.robot_id)
                run.event("agent_iterate", phase="iterate",
                          robot=self.robot_id,
                          iteration=self._status.iteration_number,
                          stepped=stepped, rel_change=rel,
                          ready=bool(ready), latency_s=dt)
                if stepped and not np.isfinite(rel):
                    # The one scalar this path reads back went non-finite:
                    # this robot's iterate (or a poisoned neighbor frame
                    # it consumed) has diverged.
                    self._obs_anomaly("non_finite_rel_change", "critical",
                                      rel_change=rel)
                # The compute half of the fleet timeline: one span per
                # iterate, reusing the timestamps measured above.
                trace.emit_span(run, "iterate", t0, t0_wall, dt,
                                phase="compute", robot=self.robot_id,
                                iteration=self._status.iteration_number,
                                stepped=stepped, rel_change=rel)
            return stepped

    # -- async runtime ------------------------------------------------------

    def start_optimization_loop(self, rate_hz: float = 10.0,
                                seed: int | None = None) -> None:
        """Spawn the Poisson-clock optimization thread
        (``startOptimizationLoop``, ``PGOAgent.cpp:861-898``): sleep
        ``Exp(rate)`` then ``iterate(True)`` until stopped.  Acceleration is
        rejected in async mode as in the reference (assert ``:863``)."""
        if self.params.acceleration:
            raise ValueError("acceleration is not supported in async mode")
        if self._loop_thread is not None and self._loop_thread.is_alive():
            return
        self._end_loop.clear()
        rng = np.random.default_rng(self.robot_id if seed is None else seed)

        def run():
            while not self._end_loop.is_set():
                self._end_loop.wait(float(rng.exponential(1.0 / rate_hz)))
                if self._end_loop.is_set():
                    break
                self.iterate(True)

        self._loop_thread = threading.Thread(
            target=run, name=f"pgo-agent-{self.robot_id}", daemon=True)
        self._loop_thread.start()

    def end_optimization_loop(self) -> None:
        """Stop and join (``endOptimizationLoop``, ``PGOAgent.cpp:900-916``)."""
        if self._loop_thread is None:
            return
        self._end_loop.set()
        self._loop_thread.join()
        self._loop_thread = None

    def is_optimization_running(self) -> bool:
        return self._loop_thread is not None and self._loop_thread.is_alive()

    # -- lifecycle ----------------------------------------------------------

    def reset(self) -> None:
        """Roll to the next problem instance keeping the lifting matrix
        (``reset``, ``PGOAgent.cpp:583-640``), dumping the solve's data first
        when logging is enabled (``:587-603``)."""
        # Join the loop thread BEFORE taking the lock: the thread's iterate()
        # needs the lock, so joining under it would deadlock.
        self.end_optimization_loop()
        with self._lock:
            if self.params.log_data:
                self._log_measurements("measurements.csv")
                self._log_global_trajectory("trajectory_optimized.csv")
                self._log_x("X.txt")
            instance = self._status.instance_number + 1
            self._clear_problem()
            self._status.instance_number = instance
            self._neighbor_status.clear()
            self._obs_state_event()

    def log_trajectory(self) -> None:
        """Mid-run dump with per-robot file names (reference
        ``log_trajectory``, ``PGOAgent.cpp:1301-1319``): measurements incl.
        current GNC weights, the rounded global-frame trajectory as
        ``robot+{id}+trajectory_optimized.csv``, and the raw lifted iterate
        as ``{id}_X.txt``."""
        with self._lock:
            if not self.params.log_data:
                return
            self._log_measurements("measurements.csv")
            self._log_global_trajectory(
                f"robot+{self.robot_id}+trajectory_optimized.csv")
            self._log_x(f"{self.robot_id}_X.txt")

    # -- data logging (reference PGOLogger wiring) --------------------------

    def _log_path(self, name: str) -> str:
        """Per-robot dump location ``log_directory/robot{id}/``.

        The reference runs one process per robot, each with its own
        ``logDirectory``; here many agents commonly share one ``AgentParams``
        (in-process examples/tests), so a flat directory would have robots
        silently overwriting each other's fixed-name dumps — the per-robot
        subdirectory keeps the reference's file names collision-free."""
        directory = os.path.join(self.params.log_directory or ".",
                                 f"robot{self.robot_id}")
        os.makedirs(directory, exist_ok=True)
        return os.path.join(directory, name)

    def _log_measurements(self, name: str) -> None:
        """All of this robot's measurements with their live GNC weights
        (reference reset()/log_trajectory(), PGOAgent.cpp:587-593)."""
        if self._meas is None:
            return
        meas = dataclasses.replace(
            self._meas, weight=np.asarray(self._weights, np.float64).copy())
        logger_mod.log_measurements(meas, self._log_path(name))

    def _log_global_trajectory(self, name: str) -> None:
        """Rounded global-frame trajectory; skipped (like the reference's
        ``if getTrajectoryInGlobalFrame(T)``) when the agent is not
        initialized or no anchor is known yet."""
        if self.X is None or self.get_global_anchor() is None:
            return
        logger_mod.log_trajectory(self.trajectory_in_global_frame(),
                                  self._log_path(name))

    def _log_x(self, name: str) -> None:
        """Raw lifted iterate before rounding (``writeMatrixToFile(X, ...)``,
        PGOAgent.cpp:602; layout [r, (d+1)n] like the reference's X)."""
        if self.X is None:
            return
        X2d = np.asarray(self.X).transpose(1, 0, 2).reshape(self.r, -1)
        logger_mod.save_matrix(X2d, self._log_path(name))

    # -- diagnostics --------------------------------------------------------

    def local_cost(self) -> float | None:
        """f(X) against cached neighbor poses (None while any are missing)."""
        with self._lock:
            z = self._neighbor_buffer()
            if z is None or self.X is None:
                return None
            buf = jnp.concatenate([self._X_device(), z], axis=0)
            edges = self._edges._replace(weight=self._weights_device())
            return float(quadratic.cost(buf, edges))


def _express_in_frame(T: np.ndarray, T_frame: np.ndarray) -> np.ndarray:
    """Apply ``T_frame^-1`` to every pose of ``T`` ([n, d, d+1])."""
    d = T.shape[1]
    R0, t0 = T_frame[:, :d], T_frame[:, d]
    R = np.einsum("ba,nbc->nac", R0, T[:, :, :d])
    t = np.einsum("ba,nb->na", R0, T[:, :, d] - t0)
    return np.concatenate([R, t[:, :, None]], axis=-1)
