"""Core data containers.

The reference stores measurements as ``std::vector<RelativeSEMeasurement>``
(``include/DPGO/RelativeSEMeasurement.h:21-89``) and poses as an Eigen matrix
``r x (d+1)n``.  The TPU-native layout is struct-of-arrays throughout:

* ``Measurements`` — host-side numpy arrays for a batch of relative SE(d)
  measurements (the full dataset, or one agent's slice).
* ``EdgeSet`` — the on-device pytree used by all jitted kernels.  Edges index
  into a pose buffer ``X: [N, r, d+1]`` where each pose block is
  ``[Y_i | p_i]`` (lifted rotation ``Y_i in St(r, d)``, translation
  ``p_i in R^r``).  A local problem's buffer is ``concat([local X, neighbor
  Z])`` so private and inter-agent edges share one code path; gradients are
  only accumulated for the first ``n_local`` slots.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class Measurements:
    """A batch of relative SE(d) measurements (host side, numpy).

    Fields mirror ``RelativeSEMeasurement`` (reference
    ``RelativeSEMeasurement.h:21-89``): edge (r1, p1) -> (r2, p2), rotation
    ``R``, translation ``t``, precisions ``kappa``/``tau``, GNC ``weight``,
    and the fixed-inlier flag.
    """

    d: int
    num_poses: int  # total number of poses referenced (global indexing)
    r1: np.ndarray  # [m] uint robot id of tail
    p1: np.ndarray  # [m] pose index of tail
    r2: np.ndarray  # [m] robot id of head
    p2: np.ndarray  # [m] pose index of head
    R: np.ndarray  # [m, d, d]
    t: np.ndarray  # [m, d]
    kappa: np.ndarray  # [m]
    tau: np.ndarray  # [m]
    weight: np.ndarray  # [m], GNC weight in [0, 1]
    is_known_inlier: np.ndarray  # [m] bool

    def __len__(self) -> int:
        return int(self.r1.shape[0])

    def select(self, idx) -> "Measurements":
        """A new Measurements containing rows ``idx`` (bool mask or indices)."""
        return Measurements(
            d=self.d,
            num_poses=self.num_poses,
            r1=self.r1[idx],
            p1=self.p1[idx],
            r2=self.r2[idx],
            p2=self.p2[idx],
            R=self.R[idx],
            t=self.t[idx],
            kappa=self.kappa[idx],
            tau=self.tau[idx],
            weight=self.weight[idx],
            is_known_inlier=self.is_known_inlier[idx],
        )

    @staticmethod
    def concatenate(parts: list["Measurements"]) -> "Measurements":
        assert parts
        return Measurements(
            d=parts[0].d,
            num_poses=max(p.num_poses for p in parts),
            r1=np.concatenate([p.r1 for p in parts]),
            p1=np.concatenate([p.p1 for p in parts]),
            r2=np.concatenate([p.r2 for p in parts]),
            p2=np.concatenate([p.p2 for p in parts]),
            R=np.concatenate([p.R for p in parts]),
            t=np.concatenate([p.t for p in parts]),
            kappa=np.concatenate([p.kappa for p in parts]),
            tau=np.concatenate([p.tau for p in parts]),
            weight=np.concatenate([p.weight for p in parts]),
            is_known_inlier=np.concatenate([p.is_known_inlier for p in parts]),
        )


class EdgeSet(NamedTuple):
    """On-device struct-of-arrays edge list (optionally with leading batch dims).

    ``i``/``j`` index the tail/head pose blocks in a pose buffer
    ``X: [N, r, d+1]``.  ``weight`` is the (mutable) GNC weight; ``mask`` is
    1.0 for valid edges and 0.0 for padding; ``is_lc`` marks loop closures
    (only these are ever reweighted by GNC — odometry edges are trusted,
    reference ``PGOAgent.cpp:1181-1245`` iterates loop closures only);
    ``fixed_weight`` marks known inliers whose weight is pinned to 1
    (reference ``RelativeSEMeasurement.h:47``).
    """

    i: jax.Array  # [E] int32
    j: jax.Array  # [E] int32
    R: jax.Array  # [E, d, d]
    t: jax.Array  # [E, d]
    kappa: jax.Array  # [E]
    tau: jax.Array  # [E]
    weight: jax.Array  # [E]
    mask: jax.Array  # [E]
    is_lc: jax.Array  # [E]
    fixed_weight: jax.Array  # [E]

    @property
    def d(self) -> int:
        return self.R.shape[-1]


def loop_closure_mask(meas: Measurements) -> np.ndarray:
    """Bool mask of loop closures: an edge is odometry iff same robot and
    consecutive indices (the partitioning convention of
    ``MultiRobotExample.cpp:104-113``); everything else is a loop closure.

    Note this is the GLOBAL-indexing convention.  After partitioning,
    globally-consecutive edges that span a robot boundary become *shared*
    edges (``Partition.classify``) and are GNC-reweightable like any loop
    closure — so rejection metrics must not assume weights outside this
    mask are untouched (see ``utils.synthetic.rejection_scores``).
    """
    return ~((meas.r1 == meas.r2) & (meas.p1 + 1 == meas.p2))


def edge_set_from_measurements(
    meas: Measurements,
    tail_index: np.ndarray | None = None,
    head_index: np.ndarray | None = None,
    is_lc: np.ndarray | None = None,
    pad_to: int | None = None,
    dtype=jnp.float32,
    as_numpy: bool = False,
) -> EdgeSet:
    """Build an on-device EdgeSet from host measurements.

    By default edges index poses by their global index ``p1``/``p2``
    (single-buffer, centralized problem).  ``tail_index``/``head_index``
    override the buffer indices (used by the multi-agent builder to point
    shared-edge endpoints into the neighbor section of the buffer).

    ``as_numpy`` keeps the arrays on the host (numpy) instead of shipping
    them to a device — the float64 gap-oracle path in processes where x64
    cannot be enabled (the TPU tunnel), where ``jnp.asarray`` would
    silently truncate ``dtype=float64`` to f32.
    """
    m = len(meas)
    ti = np.asarray(meas.p1 if tail_index is None else tail_index, np.int32)
    hi = np.asarray(meas.p2 if head_index is None else head_index, np.int32)
    if is_lc is None:
        is_lc = loop_closure_mask(meas)
    is_lc = np.asarray(is_lc, bool)

    n_pad = (pad_to or m) - m
    assert n_pad >= 0

    def pad(x, fill=0):
        if n_pad == 0:
            return x
        width = [(0, n_pad)] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, width, constant_values=fill)

    d = meas.d
    conv = np.asarray if as_numpy else jnp.asarray
    return EdgeSet(
        i=conv(pad(ti)),
        j=conv(pad(hi)),
        R=conv(pad(np.broadcast_to(np.eye(d), (m, d, d)) if m == 0 else meas.R), dtype),
        t=conv(pad(meas.t), dtype),
        kappa=conv(pad(meas.kappa), dtype),
        tau=conv(pad(meas.tau), dtype),
        weight=conv(pad(meas.weight), dtype),
        mask=conv(pad(np.ones(m)), dtype),
        is_lc=conv(pad(is_lc.astype(np.float64)), dtype),
        fixed_weight=conv(pad(meas.is_known_inlier.astype(np.float64)), dtype),
    )
