"""Sharded RBCD: agents distributed over a TPU device mesh.

This is the framework's distributed communication backend (SURVEY.md
section 2.4).  The reference has *no* networking code in-library — transport
is supplied externally (in-process calls in ``examples/MultiRobotExample.cpp``,
ROS pub/sub in ``dpgo_ros``).  Here the transport is the device mesh itself:

* agents = shards of a 1-D mesh axis ``"agent"`` (several agents per device
  when ``num_robots > mesh size``), or of the flattened ``("dcn", "ici")``
  product axis of a multi-slice mesh (``make_multislice_mesh`` — BASELINE
  config #5's 64-agents-across-slices deployment);
* public-pose exchange (``getSharedPoseDict`` -> ``updateNeighborPoses``,
  reference ``PGOAgent.cpp:95-105``, ``434-458``) = one ``all_gather`` of the
  padded public-pose table over ICI (DCN across slices — same code);
* status consensus (``PGOAgentStatus`` gossip + ``shouldTerminate``,
  reference ``PGOAgent.cpp:1007-1031``) = the driver reducing the sharded
  ``ready`` flags (a tiny all-reduce under jit);
* the lifting matrix / global anchor broadcast
  (``MultiRobotExample.cpp:139-146``, ``258-263``) = replicated arrays.

The per-shard round body is ``models.rbcd._rbcd_round`` with ``axis_name``
set to the mesh's full axis-name tuple (``("agent",)``, or
``("dcn", "ici")`` on a multi-slice mesh) — identical math to the
single-device path, so the sharded and unsharded solvers agree bitwise up
to XLA reduction order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..config import AgentParams
from ..types import Measurements
from ..utils.partition import Partition, partition_contiguous
from ..utils.profiling import RoundTimer
from ..models import rbcd
from ..models.rbcd import (GraphMeta, MultiAgentGraph, RBCDState,
                           init_state)

AXIS = "agent"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the ``"agent"`` axis.

    On real hardware this spans the TPU slice (ICI); under
    ``--xla_force_host_platform_device_count=N`` it spans N virtual CPU
    devices, which is how the collective paths are tested without a TPU.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > devices.size:
            raise ValueError(
                f"requested {num_devices} devices but only {devices.size} "
                "are available")
        devices = devices[:num_devices]
    return Mesh(devices, (AXIS,))


def make_multislice_mesh(num_slices: int, devices=None) -> Mesh:
    """A 2-D ``("dcn", "ici")`` mesh: ``num_slices`` TPU slices (DCN edges
    between them) x devices-per-slice (ICI within).  Agents shard over the
    flattened product axis; XLA routes each hop of the pose-exchange
    collective over the interconnect that actually links the devices — the
    multi-slice deployment of SURVEY.md section 2.4 / BASELINE config #5
    (64 agents across slices).  On real multi-slice hardware pass the
    devices in slice-major order (``jax.devices()`` already is); under the
    virtual CPU mesh the axis split exercises the identical program.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size % num_slices != 0:
        raise ValueError(
            f"{devices.size} devices do not split into {num_slices} slices")
    return Mesh(devices.reshape(num_slices, -1), ("dcn", "ici"))


def _axes(mesh: Mesh) -> tuple:
    """All mesh axis names — the agent axis is their flattened product."""
    return tuple(mesh.axis_names)


def _specs(mesh: Mesh, tree):
    """PartitionSpec pytree: leading axis over agents for [A, ...] arrays,
    replicated for scalars."""
    ax = _axes(mesh)
    def spec(x):
        return P(ax) if jnp.ndim(x) >= 1 else P()
    return jax.tree.map(spec, tree)


def shard_problem(mesh: Mesh, state: RBCDState, graph: MultiAgentGraph):
    """Place state and graph on the mesh: agent-sharded leading axes.

    ``num_robots`` must be a multiple of the mesh size (each device holds
    the same number of agent blocks).
    """
    A = state.X.shape[0]
    n_dev = mesh.devices.size
    if A % n_dev != 0:
        raise ValueError(
            f"num_robots={A} must be a multiple of mesh size {n_dev}; "
            "pick a divisible robot count or a smaller mesh")

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    state = jax.tree.map(put, state, _specs(mesh, state))
    graph = jax.tree.map(put, graph, _specs(mesh, graph))
    return state, graph


def _exchange_plan(mesh: Mesh, meta: GraphMeta, graph: MultiAgentGraph,
                   exchange: str):
    """Resolve the pose-exchange backend: ``"all_gather"`` (v1, full public
    table to every device) or ``"ppermute"`` (one collective per device
    shift that actually carries an edge — the optimized ICI route of
    SURVEY.md section 2.4).  Returns ``(shifts, plan)`` with plan arrays
    placed like the rest of the per-agent graph data."""
    if exchange == "all_gather":
        return (), None
    if exchange != "ppermute":
        raise ValueError(f"unknown exchange backend {exchange!r}")
    if len(_axes(mesh)) > 1:
        raise ValueError(
            "ppermute exchange plans device-ring shifts over a 1-D mesh; "
            "use exchange='all_gather' on a multi-slice mesh (XLA routes "
            "each gather hop over the linking interconnect)")
    shifts, plan = rbcd.plan_ppermute(graph, meta.num_robots,
                                      mesh.devices.size)
    plan = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(_axes(mesh)))), plan)
    return shifts, plan


def make_sharded_step(mesh: Mesh, meta: GraphMeta, params: AgentParams,
                      shifts: tuple = (), plan=None):
    """Compile the sharded RBCD round: shard_map of the per-shard body over
    the agent axis, jitted as one XLA program (collectives included).

    The returned callable takes the driver's two static schedule flags
    (``update_weights``, ``restart``); each (True/False) combination compiles
    once.  ``shifts``/``plan`` (from ``_exchange_plan``) select the ppermute
    pose exchange; default is the all_gather v1."""

    @partial(jax.jit, static_argnames=("update_weights", "restart"))
    def step(state: RBCDState, graph: MultiAgentGraph,
             update_weights: bool = False, restart: bool = False) -> RBCDState:
        def body(s, g, p):
            return rbcd._rbcd_round(s, g, meta=meta, params=params,
                                    axis_name=_axes(mesh),
                                    update_weights=update_weights,
                                    restart=restart, plan=p, shifts=shifts)

        in_specs = (_specs(mesh, state), _specs(mesh, graph),
                    _specs(mesh, plan))
        out_specs = _specs(mesh, state)
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             check_vma=False)(state, graph, plan)

    return step


def make_sharded_multi_step(mesh: Mesh, meta: GraphMeta, params: AgentParams,
                            shifts: tuple = (), plan=None):
    """Compile the fused plain-round loop for the mesh path: ``k`` consecutive
    rounds (collective pose exchange included in each) as one on-device
    ``fori_loop`` inside shard_map — one dispatch per schedule segment
    instead of per round (see ``models.rbcd.rbcd_steps``).  ``k`` is traced,
    so one compile serves every segment length."""

    @jax.jit
    def steps(state: RBCDState, graph: MultiAgentGraph, num_rounds) -> RBCDState:
        def body(s, g, n, p):
            return rbcd._rbcd_rounds(s, g, n, meta, params, axis_name=_axes(mesh),
                                     plan=p, shifts=shifts)

        in_specs = (_specs(mesh, state), _specs(mesh, graph), P(),
                    _specs(mesh, plan))
        out_specs = _specs(mesh, state)
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             check_vma=False)(state, graph, num_rounds, plan)

    return steps


def make_sharded_segment(mesh: Mesh, meta: GraphMeta, params: AgentParams,
                         shifts: tuple = (), plan=None):
    """Compile the fused schedule segment for the mesh path: a (possibly
    flagged) first round + the plain stretch as one dispatch
    (``models.rbcd.rbcd_segment``).  ``k`` is traced; the two first-round
    flags are static (<= 4 compiled variants)."""

    @partial(jax.jit, static_argnames=("update_weights", "restart"))
    def seg(state: RBCDState, graph: MultiAgentGraph, num_rounds,
            update_weights: bool = False, restart: bool = False) -> RBCDState:
        def body(s, g, n, p):
            return rbcd._rbcd_segment(s, g, n, meta, params, axis_name=_axes(mesh),
                                      plan=p, shifts=shifts,
                                      first_update_weights=update_weights,
                                      first_restart=restart)

        in_specs = (_specs(mesh, state), _specs(mesh, graph), P(),
                    _specs(mesh, plan))
        out_specs = _specs(mesh, state)
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             check_vma=False)(state, graph, num_rounds, plan)

    return seg


def comm_bytes_per_round(meta: GraphMeta, mesh_size: int,
                         shifts: tuple | None = None,
                         accel: bool = False, itemsize: int = 4,
                         greedy: bool = False) -> int:
    """Modeled per-device ICI/DCN bytes for one round's pose exchange —
    the mesh analog of the reference driver's hand-counted communication
    bytes (``MultiRobotExample.cpp:60,143,195,209,274-279``; the in-process
    model lives in ``examples/multi_robot_example.py``).

    all_gather (``shifts=None``) moves each device's public table to every
    other device: ``mesh_size - 1`` table hops on a ring.  The ppermute
    route moves it once per planned shift (``len(shifts)`` hops).  Nesterov
    acceleration doubles the volume (aux poses Y exchanged too);
    ``greedy`` adds the greedy schedule's [A]-float gradient-norm
    all_gather (absent from the compiled Jacobi/async rounds —
    tests/test_sharded.py validates the model against the collectives XLA
    actually emits).
    """
    if meta.num_robots % mesh_size != 0:
        raise ValueError(
            f"num_robots={meta.num_robots} must be a multiple of "
            f"mesh_size={mesh_size} (shard_problem's layout)")
    A_loc = meta.num_robots // mesh_size
    table = A_loc * meta.p_max * meta.rank * (meta.d + 1) * itemsize
    hops = (mesh_size - 1) if shifts is None else len(shifts)
    exchanges = 2 if accel else 1
    greedy_gather = (mesh_size - 1) * A_loc * itemsize if greedy else 0
    return exchanges * hops * table + greedy_gather


def solve_rbcd_sharded(
    meas: Measurements,
    num_robots: int,
    mesh: Mesh | None = None,
    params: AgentParams | None = None,
    max_iters: int | None = None,
    grad_norm_tol: float = 0.1,
    eval_every: int = 1,
    dtype=jnp.float64,
    part: Partition | None = None,
    init: str = "chordal",
    exchange: str = "all_gather",
) -> rbcd.RBCDResult:
    """Distributed solve over a device mesh — the deployment path of the
    framework (``models.rbcd.solve_rbcd`` is the single-device debug path).
    Shares the driver loop (``rbcd.run_rbcd``); only problem placement and
    the step function differ.  ``exchange`` selects the pose-exchange
    collective: ``"all_gather"`` (v1) or ``"ppermute"`` (one collective per
    ring offset that carries a cross-device edge — fewer hops than the
    all_gather ring when the device adjacency is near-chain)."""
    mesh = mesh or make_mesh()
    params = params or AgentParams(d=meas.d, r=5, num_robots=num_robots)
    max_iters = params.max_num_iters if max_iters is None else max_iters

    # Telemetry (dpgo_tpu.obs): per-phase setup timings and the per-device
    # communication model for this mesh.  With no ambient run the timer is
    # never created and the path below is the uninstrumented one.
    run = obs.get_run()
    timer = RoundTimer() if run is not None else None

    part = part or partition_contiguous(meas, num_robots)
    if timer is not None:
        timer.start("build_graph")
    graph, meta = rbcd.build_graph(
        part, params.r, dtype, sel_mode=rbcd.resolved_sel_mode(params))
    if timer is not None:
        timer.stop("build_graph")
        timer.start("init")
    X0 = rbcd.initial_state_for(init, part, meta, graph, params, dtype)
    state = init_state(graph, meta, X0, params=params)
    if timer is not None:
        # The init chord/odometry solve runs on device; the obs-owned fence
        # materializes it so the phase boundary is trustworthy (telemetry-on
        # only — the off path never reaches this transfer).
        timer.stop("init", sync=obs.materialize(state.X))
        timer.start("shard")
    state, graph = shard_problem(mesh, state, graph)

    shifts, plan = _exchange_plan(mesh, meta, graph, exchange)
    if timer is not None:
        timer.stop("shard")
    sharded_step = make_sharded_step(mesh, meta, params, shifts, plan)
    sharded_multi = make_sharded_multi_step(mesh, meta, params, shifts, plan)
    sharded_seg = make_sharded_segment(mesh, meta, params, shifts, plan)
    step = lambda s, uw, rs: sharded_step(s, graph, update_weights=uw, restart=rs)
    multi = lambda s, k: sharded_multi(s, graph, k)
    seg = lambda s, k, uw, rs: sharded_seg(s, graph, k, update_weights=uw,
                                           restart=rs)
    if run is not None:
        mesh_size = int(mesh.devices.size)
        bytes_round = comm_bytes_per_round(
            meta, mesh_size, shifts=shifts if exchange == "ppermute" else None,
            accel=params.acceleration,
            itemsize=np.dtype(dtype).itemsize,
            greedy=params.schedule.value == "greedy")
        run.event("sharded_solve", phase="setup", mesh_size=mesh_size,
                  mesh_axes=list(mesh.axis_names), exchange=exchange,
                  num_robots=num_robots,
                  agents_per_shard=num_robots // mesh_size,
                  comm_bytes_per_round=bytes_round)
        run.gauge("sharded_comm_bytes_per_round",
                  "modeled per-device interconnect bytes per round",
                  unit="bytes").set(bytes_round)
        run.event("phase_timings", phase="setup", timings=timer.as_dict())
        # Mesh identity into the run fingerprint: a 1-device and an
        # 8-device solve of the same problem are not comparable runs for
        # the convergence regression gate (report --compare).
        run.set_fingerprint(solver="solve_rbcd_sharded",
                            mesh_size=mesh_size, exchange=exchange)
    return rbcd.run_rbcd(state, graph, meta, step, part, max_iters,
                         grad_norm_tol, eval_every, dtype, params=params,
                         multi_step=multi, segment=seg)
