"""Sharded RBCD: agents distributed over a TPU device mesh.

This is the framework's distributed communication backend (SURVEY.md
section 2.4).  The reference has *no* networking code in-library — transport
is supplied externally (in-process calls in ``examples/MultiRobotExample.cpp``,
ROS pub/sub in ``dpgo_ros``).  Here the transport is the device mesh itself:

* agents = shards of a 1-D mesh axis ``"agent"`` (several agents per device
  when ``num_robots > mesh size``), or of the flattened ``("dcn", "ici")``
  product axis of a multi-slice mesh (``make_multislice_mesh`` — BASELINE
  config #5's 64-agents-across-slices deployment);
* public-pose exchange (``getSharedPoseDict`` -> ``updateNeighborPoses``,
  reference ``PGOAgent.cpp:95-105``, ``434-458``) = one ``all_gather`` of the
  padded public-pose table over ICI (DCN across slices — same code);
* status consensus (``PGOAgentStatus`` gossip + ``shouldTerminate``,
  reference ``PGOAgent.cpp:1007-1031``) = the driver reducing the sharded
  ``ready`` flags (a tiny all-reduce under jit);
* the lifting matrix / global anchor broadcast
  (``MultiRobotExample.cpp:139-146``, ``258-263``) = replicated arrays.

The per-shard round body is ``models.rbcd._rbcd_round`` with ``axis_name``
set to the mesh's full axis-name tuple (``("agent",)``, or
``("dcn", "ici")`` on a multi-slice mesh) — identical math to the
single-device path, so the sharded and unsharded solvers agree bitwise up
to XLA reduction order.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..config import AgentParams
from ..ops import manifold, quadratic
from ..types import Measurements, edge_set_from_measurements
from ..utils.partition import Partition, partition_contiguous
from ..utils.profiling import RoundTimer
from ..models import rbcd, refine
from ..models.rbcd import (GraphMeta, MultiAgentGraph, RBCDState,
                           init_state)
from . import resilience as resilience_mod

AXIS = "agent"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the ``"agent"`` axis.

    On real hardware this spans the TPU slice (ICI); under
    ``--xla_force_host_platform_device_count=N`` it spans N virtual CPU
    devices, which is how the collective paths are tested without a TPU.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > devices.size:
            raise ValueError(
                f"requested {num_devices} devices but only {devices.size} "
                "are available")
        devices = devices[:num_devices]
    return Mesh(devices, (AXIS,))


def make_multislice_mesh(num_slices: int, devices=None) -> Mesh:
    """A 2-D ``("dcn", "ici")`` mesh: ``num_slices`` TPU slices (DCN edges
    between them) x devices-per-slice (ICI within).  Agents shard over the
    flattened product axis; XLA routes each hop of the pose-exchange
    collective over the interconnect that actually links the devices — the
    multi-slice deployment of SURVEY.md section 2.4 / BASELINE config #5
    (64 agents across slices).  On real multi-slice hardware pass the
    devices in slice-major order (``jax.devices()`` already is); under the
    virtual CPU mesh the axis split exercises the identical program.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size % num_slices != 0:
        raise ValueError(
            f"{devices.size} devices do not split into {num_slices} slices")
    return Mesh(devices.reshape(num_slices, -1), ("dcn", "ici"))


def _axes(mesh: Mesh) -> tuple:
    """All mesh axis names — the agent axis is their flattened product."""
    return tuple(mesh.axis_names)


def _shard_map(f, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across JAX versions: the public API (``check_vma``)
    when present, the experimental one (``check_rep``) otherwise — jax
    0.4.x ships only the latter, and without this shim the whole sharded
    plane is untestable on such an image (the per-eval readback era's
    "13 environmental failures" were exactly this)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _specs(mesh: Mesh, tree):
    """PartitionSpec pytree: leading axis over agents for [A, ...] arrays,
    replicated for scalars."""
    ax = _axes(mesh)
    def spec(x):
        return P(ax) if jnp.ndim(x) >= 1 else P()
    return jax.tree.map(spec, tree)


def shard_problem(mesh: Mesh, state: RBCDState, graph: MultiAgentGraph):
    """Place state and graph on the mesh: agent-sharded leading axes.

    ``num_robots`` must be a multiple of the mesh size (each device holds
    the same number of agent blocks).
    """
    A = state.X.shape[0]
    n_dev = mesh.devices.size
    if A % n_dev != 0:
        raise ValueError(
            f"num_robots={A} must be a multiple of mesh size {n_dev}; "
            "pick a divisible robot count or a smaller mesh")

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    state = jax.tree.map(put, state, _specs(mesh, state))
    graph = jax.tree.map(put, graph, _specs(mesh, graph))
    return state, graph


def _exchange_plan(mesh: Mesh, meta: GraphMeta, graph: MultiAgentGraph,
                   exchange: str):
    """Resolve the pose-exchange backend: ``"all_gather"`` (v1, full public
    table to every device) or ``"ppermute"`` (one collective per device
    shift that actually carries an edge — the optimized ICI route of
    SURVEY.md section 2.4).  Returns ``(shifts, plan)`` with plan arrays
    placed like the rest of the per-agent graph data."""
    if exchange == "all_gather":
        return (), None
    if exchange != "ppermute":
        raise ValueError(f"unknown exchange backend {exchange!r}")
    if len(_axes(mesh)) > 1:
        raise ValueError(
            "ppermute exchange plans device-ring shifts over a 1-D mesh; "
            "use exchange='all_gather' on a multi-slice mesh (XLA routes "
            "each gather hop over the linking interconnect)")
    shifts, plan = rbcd.plan_ppermute(graph, meta.num_robots,
                                      mesh.devices.size)
    plan = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(_axes(mesh)))), plan)
    return shifts, plan


def make_sharded_step(mesh: Mesh, meta: GraphMeta, params: AgentParams,
                      shifts: tuple = (), plan=None):
    """Compile the sharded RBCD round: shard_map of the per-shard body over
    the agent axis, jitted as one XLA program (collectives included).

    The returned callable takes the driver's two static schedule flags
    (``update_weights``, ``restart``); each (True/False) combination compiles
    once.  ``shifts``/``plan`` (from ``_exchange_plan``) select the ppermute
    pose exchange; default is the all_gather v1."""

    @partial(jax.jit, static_argnames=("update_weights", "restart"))
    def step(state: RBCDState, graph: MultiAgentGraph,
             update_weights: bool = False, restart: bool = False) -> RBCDState:
        def body(s, g, p):
            return rbcd._rbcd_round(s, g, meta=meta, params=params,
                                    axis_name=_axes(mesh),
                                    update_weights=update_weights,
                                    restart=restart, plan=p, shifts=shifts)

        in_specs = (_specs(mesh, state), _specs(mesh, graph),
                    _specs(mesh, plan))
        out_specs = _specs(mesh, state)
        return _shard_map(body, mesh, in_specs, out_specs)(state, graph, plan)

    return step


def make_sharded_multi_step(mesh: Mesh, meta: GraphMeta, params: AgentParams,
                            shifts: tuple = (), plan=None,
                            overlap: bool = True):
    """Compile the fused plain-round loop for the mesh path: ``k`` consecutive
    rounds (collective pose exchange included in each) as one on-device
    ``fori_loop`` inside shard_map — one dispatch per schedule segment
    instead of per round (see ``models.rbcd.rbcd_steps``).  ``k`` is traced,
    so one compile serves every segment length.

    ``overlap`` (default on — the mesh fast path) software-pipelines the
    halo exchange: the loop carries each round's neighbor buffer and
    issues the next round's ``ppermute``/``all_gather`` right after the
    Stiefel update produces the poses it carries, so the interconnect
    transfer overlaps the round's trailing status/momentum math instead of
    gating the next round's solve (``models.rbcd._rbcd_rounds``; identical
    values round for round)."""

    @jax.jit
    def steps(state: RBCDState, graph: MultiAgentGraph, num_rounds) -> RBCDState:
        def body(s, g, n, p):
            return rbcd._rbcd_rounds(s, g, n, meta, params, axis_name=_axes(mesh),
                                     plan=p, shifts=shifts, overlap=overlap)

        in_specs = (_specs(mesh, state), _specs(mesh, graph), P(),
                    _specs(mesh, plan))
        out_specs = _specs(mesh, state)
        return _shard_map(body, mesh, in_specs, out_specs)(state, graph, num_rounds, plan)

    return steps


def make_sharded_segment(mesh: Mesh, meta: GraphMeta, params: AgentParams,
                         shifts: tuple = (), plan=None,
                         overlap: bool = True):
    """Compile the fused schedule segment for the mesh path: a (possibly
    flagged) first round + the plain stretch as one dispatch
    (``models.rbcd.rbcd_segment``).  ``k`` is traced; the two first-round
    flags are static (<= 4 compiled variants).  ``overlap`` pipelines the
    plain stretch's halo exchange (see ``make_sharded_multi_step``)."""

    @partial(jax.jit, static_argnames=("update_weights", "restart"))
    def seg(state: RBCDState, graph: MultiAgentGraph, num_rounds,
            update_weights: bool = False, restart: bool = False) -> RBCDState:
        def body(s, g, n, p):
            return rbcd._rbcd_segment(s, g, n, meta, params, axis_name=_axes(mesh),
                                      plan=p, shifts=shifts,
                                      first_update_weights=update_weights,
                                      first_restart=restart,
                                      overlap=overlap)

        in_specs = (_specs(mesh, state), _specs(mesh, graph), P(),
                    _specs(mesh, plan))
        out_specs = _specs(mesh, state)
        return _shard_map(body, mesh, in_specs, out_specs)(state, graph, num_rounds, plan)

    return seg


def comm_bytes_per_round(meta: GraphMeta, mesh_size: int,
                         shifts: tuple | None = None,
                         accel: bool = False, itemsize: int = 4,
                         greedy: bool = False) -> int:
    """Modeled per-device ICI/DCN bytes for one round's pose exchange —
    the mesh analog of the reference driver's hand-counted communication
    bytes (``MultiRobotExample.cpp:60,143,195,209,274-279``; the in-process
    model lives in ``examples/multi_robot_example.py``).

    all_gather (``shifts=None``) moves each device's public table to every
    other device: ``mesh_size - 1`` table hops on a ring.  The ppermute
    route moves it once per planned shift (``len(shifts)`` hops).  Nesterov
    acceleration doubles the volume (aux poses Y exchanged too);
    ``greedy`` adds the greedy schedule's [A]-float gradient-norm
    all_gather (absent from the compiled Jacobi/async rounds —
    tests/test_sharded.py validates the model against the collectives XLA
    actually emits).
    """
    if meta.num_robots % mesh_size != 0:
        raise ValueError(
            f"num_robots={meta.num_robots} must be a multiple of "
            f"mesh_size={mesh_size} (shard_problem's layout)")
    A_loc = meta.num_robots // mesh_size
    table = A_loc * meta.p_max * meta.rank * (meta.d + 1) * itemsize
    hops = (mesh_size - 1) if shifts is None else len(shifts)
    exchanges = 2 if accel else 1
    greedy_gather = (mesh_size - 1) * A_loc * itemsize if greedy else 0
    return exchanges * hops * table + greedy_gather


# ---------------------------------------------------------------------------
# Sharded verdict program (the device-resident loop under shard_map)
# ---------------------------------------------------------------------------

#: Collective fault-injection hook (``parallel.resilience``) — the
#: shard_map twin of ``rbcd._exchange_wrap``: when set, every exchange
#: closure built below passes through it at trace time.
_gather_wrap = None


def _gather_exchange(graph: MultiAgentGraph, ax):
    """Neighbor-buffer exchange inside a shard_map body: all_gather of the
    public table over the mesh axes, then the slot resolve — the same v1
    exchange as the solver round (``rbcd.neighbor_buffer``)."""
    gather = lambda t: jax.lax.all_gather(t, ax, axis=0, tiled=True)
    exchange = lambda Vl: rbcd.neighbor_buffer(
        gather(rbcd.public_table(Vl, graph)), graph)
    if _gather_wrap is not None:
        exchange = _gather_wrap(exchange)
    return exchange


def local_grad_rows(V, Vz, graph: MultiAgentGraph):
    """Complete local gradient rows of the global linear map ``V Q`` for
    every agent held by this shard: the per-agent edge list applied to the
    ``[local | neighbor]`` buffer through the gather-only ELL incidence
    (``quadratic.egrad_ell`` is linear, so it doubles as the ``Q`` matvec
    on probe blocks).  Shared edges appear in both endpoint agents' lists
    with the remote endpoint in a neighbor slot, so local rows accumulate
    exactly the global rows with no double counting — the matvec of the
    sharded certificate AND the sharded GN-CG tail."""

    def one(vl, vz, e, s, m):
        return quadratic.egrad_ell(jnp.concatenate([vl, vz]), e, s, m)

    return jax.vmap(one)(V, Vz, graph.edges, graph.inc_slot, graph.inc_mask)


def make_sharded_metrics_body(mesh: Mesh, graph: MultiAgentGraph,
                              edges_g, n_total: int, num_meas: int,
                              telemetry: bool):
    """The stacked-metrics body of the verdict program, traced under
    ``shard_map`` — ``rbcd._central_metrics_body`` with every centralized
    reduction expressed as a mesh collective:

    * the global iterate assembly is a ``psum`` of each shard's
      owner-scatter (disjoint supports — each global pose has exactly one
      owner agent, so the sum adds one value to zeros and is EXACT, not
      merely reduction-order-close);
    * the per-measurement weight collapse psums the per-shard scatter
      numerators/denominators (a measurement has at most two owner copies
      with identical weights, so this too is exact);
    * agent consensus is a psum of the not-ready count;
    * the telemetry extras (GNC inlier fraction, mean weight) psum their
      per-shard partial sums, and the per-agent relative-change row is an
      ``all_gather`` in agent order.

    The centralized cost/gradient then evaluate REPLICATED on every shard
    from the psum'd global assembly — identical math to the single-device
    body, so the verdict word, history rows, and termination latch carry
    over unchanged (``make_verdict_program(metrics_body=...)`` keeps all
    of that downstream logic shared).  Fed to ``rbcd.run_rbcd`` via its
    ``metrics_body_factory`` seam by ``solve_rbcd_sharded``."""
    ax = _axes(mesh)
    rep = lambda tree: jax.tree.map(lambda _: P(), tree)

    def shard_body(Xa, weights, ready, mu, rel, graph_s, eg):
        psum = lambda v: jax.lax.psum(v, ax)
        Xg = psum(rbcd.gather_to_global(Xa, graph_s, n_total))
        ids = graph_s.meas_id.reshape(-1)
        m = graph_s.edges.mask.reshape(-1)
        w = weights.reshape(-1)
        num = psum(jnp.zeros((num_meas,), weights.dtype).at[ids].add(w * m))
        den = psum(jnp.zeros((num_meas,), weights.dtype).at[ids].add(m))
        w_glob = jnp.where(den > 0, num / jnp.maximum(den, 1.0), 1.0)
        eg = eg._replace(weight=w_glob)
        f = quadratic.cost(Xg, eg)
        g = manifold.rgrad(Xg, quadratic.egrad(Xg, eg))
        not_ready = psum(jnp.sum(jnp.logical_not(ready).astype(jnp.int32)))
        vals = [f, manifold.norm(g), (not_ready == 0).astype(f.dtype)]
        if telemetry:
            e = graph_s.edges
            upd = e.mask * e.is_lc * (1.0 - e.fixed_weight)
            n_upd = jnp.maximum(psum(jnp.sum(upd)), 1.0)
            vals += [mu.astype(f.dtype),
                     psum(jnp.sum((weights > 0.5) * upd)) / n_upd,
                     psum(jnp.sum(weights * upd)) / n_upd]
            rel_all = jax.lax.all_gather(rel.astype(f.dtype), ax, axis=0,
                                         tiled=True)
            return jnp.concatenate([jnp.stack(vals), rel_all])
        return jnp.stack(vals)

    def metrics_body(Xa, weights, ready, mu, rel_change):
        in_specs = (P(ax), P(ax), P(ax), P(), P(ax),
                    _specs(mesh, graph), rep(edges_g))
        return _shard_map(shard_body, mesh, in_specs, P())(
            Xa, weights, ready, mu, rel_change, graph, edges_g)

    return metrics_body


# ---------------------------------------------------------------------------
# Sharded device-resident Gauss-Newton-CG tail
# ---------------------------------------------------------------------------
#
# ``refine.gn_tail`` breaks the block-coordinate floor with a centralized
# Gauss-Newton-CG polish, but it assembles S = Q - Lambda on the HOST in
# f64 scipy — a full global round-trip per outer step that cannot fit the
# serve plane's budget at 100k+ poses.  Here the same algorithm runs
# device-resident on the agent mesh: the S matvec is each shard's local
# ELL edge product plus the halo pose exchange (``local_grad_rows`` — the
# identical sharding as the solver round and the distributed certificate),
# every CG dot product is a psum, the block-Jacobi preconditioner is
# ``refine.gn_precond_blocks`` vectorized per shard, and the whole inner
# CG + backtracking retraction executes as ONE jitted shard_map program
# per outer step — zero host transfers inside the CG loop.  The host
# driver reads one small stats vector per outer step through the
# sanctioned ``rbcd._host_fetch`` seam.


def _gn_outer_shard(X, graph: MultiAgentGraph, *, ax, meta: GraphMeta,
                    cfg: "refine.GNTailConfig"):
    """shard_map body of one GN outer step: gradient, preconditioned
    Steihaug-CG Newton solve, backtracking projective retraction —
    ``refine.gn_tail``'s per-outer-iteration math on the agent-sharded
    layout.  Returns ``(X_new [A_loc, ...], stats [7] replicated)`` with
    stats = [cost, grad_norm, cg_iters, neg_curv, accepted, new_cost,
    step]."""
    d = meta.d
    n_max = meta.n_max
    dtype = X.dtype
    psum = lambda v: jax.lax.psum(v, ax)
    pdot = lambda u, w: psum(jnp.sum(u * w))
    exchange = _gather_exchange(graph, ax)
    pmask = graph.pose_mask[..., None, None]
    edges = graph.edges
    # Each cross-robot measurement appears in BOTH endpoint agents' edge
    # lists (neighbor-slot endpoint >= n_max marks it), so the global cost
    # halves the shared copies before the psum.
    shared = ((edges.i >= n_max) | (edges.j >= n_max)).astype(dtype)
    cscale = edges.mask * edges.weight * (1.0 - 0.5 * shared)

    def grad_rows(V):
        return local_grad_rows(V, exchange(V), graph)

    def cost_of(V):
        Vz = exchange(V)

        def one(vl, vz, e, cs):
            rR, rt = quadratic._edge_terms(jnp.concatenate([vl, vz]), e)
            return 0.5 * jnp.sum(
                cs * (e.kappa * jnp.sum(rR * rR, axis=(-2, -1))
                      + e.tau * jnp.sum(rt * rt, axis=-1)))

        return psum(jnp.sum(jax.vmap(one)(V, Vz, edges, cscale)))

    def tangent(W):
        return manifold.tangent_project(X, W) * pmask

    # Gradient and dual blocks: G = rows of X Q; Lambda_i = sym(Y_i^T G_Y,i)
    # per pose; rgrad = X S = G - [Y Lambda | 0] (already tangent — Lambda
    # IS the projection multiplier; re-project for hygiene, as the host
    # tail does).
    G = grad_rows(X)
    lam = manifold.sym(
        jnp.einsum("xnra,xnrb->xnab", X[..., :d], G[..., :d]))
    lam_of = lambda V: jnp.concatenate(
        [jnp.einsum("xnra,xnab->xnrb", V[..., :d], lam),
         jnp.zeros_like(V[..., -1:])], axis=-1)
    grad = tangent(G - lam_of(X))
    f0 = cost_of(X)
    gn = jnp.sqrt(pdot(grad, grad))

    blocks = refine.gn_precond_blocks(edges, lam, n_max, meta.s_max, d,
                                      cfg.precond_shift)

    def Av(V):
        W = grad_rows(V) - lam_of(V)
        if cfg.damping:
            W = W + cfg.damping * V
        return tangent(W)

    def Minv(V):
        W = jnp.linalg.solve(blocks, jnp.swapaxes(V, -1, -2))
        return tangent(jnp.swapaxes(W, -1, -2))

    # Preconditioned CG on the tangent space, Steihaug negative-curvature
    # exit — the host tail's loop as a lax.while_loop (no host in sight).
    b = -grad
    b_norm = jnp.sqrt(pdot(b, b))
    z0 = Minv(b)
    eps = jnp.asarray(1e-300 if dtype == jnp.float64 else 1e-30, dtype)

    def cg_cond(c):
        k, _v, _res, _p, _rz, done, _neg = c
        return (k < cfg.cg_max_iters) & jnp.logical_not(done)

    def cg_body(c):
        k, v, res, p, rz, done, neg_seen = c
        Ap = Av(p)
        pAp = pdot(p, Ap)
        neg = pAp <= 0
        # Negative curvature on the very first iteration: fall back to
        # the gradient direction; later: keep the accumulated step.
        v_fallback = jnp.where(k == 0, b, v)
        alpha = rz / jnp.where(neg, jnp.ones_like(pAp), pAp)
        v_new = v + alpha * p
        res_new = res - alpha * Ap
        small = jnp.sqrt(pdot(res_new, res_new)) <= cfg.cg_rtol * b_norm
        z = Minv(res_new)
        rz_new = pdot(res_new, z)
        p_new = z + (rz_new / jnp.maximum(rz, eps)) * p
        stop = neg | small
        return (k + 1,
                jnp.where(neg, v_fallback, v_new),
                jnp.where(neg, res, res_new),
                jnp.where(stop, p, p_new),
                jnp.where(stop, rz, rz_new),
                stop, neg_seen | neg)

    k0 = jnp.zeros((), jnp.int32)
    cg_iters, v, _res, _p, _rz, _done, neg_seen = jax.lax.while_loop(
        cg_cond, cg_body,
        (k0, jnp.zeros_like(b), b, z0, pdot(b, z0),
         jnp.zeros((), bool), jnp.zeros((), bool)))

    # Backtracking projective retraction on the true (psum'd) cost.
    def bt_cond(c):
        j, _step, _Xb, _fb, acc = c
        return (j < cfg.max_backtracks) & jnp.logical_not(acc)

    def bt_body(c):
        j, step, Xb, fb, acc = c
        Xc = manifold.project(X + step * v)
        fc = cost_of(Xc)
        ok = jnp.isfinite(fc) & (fc < f0)
        return (j + 1, step * cfg.step_shrink,
                jnp.where(ok, Xc, Xb), jnp.where(ok, fc, fb), acc | ok)

    _j, last_step, X_new, f_new, accepted = jax.lax.while_loop(
        bt_cond, bt_body,
        (jnp.zeros((), jnp.int32), jnp.asarray(1.0, dtype), X, f0,
         jnp.zeros((), bool)))

    stats = jnp.stack([f0, gn, cg_iters.astype(dtype),
                       neg_seen.astype(dtype), accepted.astype(dtype),
                       f_new, last_step])
    return X_new, stats


def _gn_gradnorm_shard(X, graph: MultiAgentGraph, *, ax, meta: GraphMeta):
    """shard_map body: the centralized Riemannian gradient norm of the
    agent-sharded iterate (the GN tail's gate quantity) — one matvec."""
    d = meta.d
    psum = lambda v: jax.lax.psum(v, ax)
    exchange = _gather_exchange(graph, ax)
    G = local_grad_rows(X, exchange(X), graph)
    lam = manifold.sym(
        jnp.einsum("xnra,xnrb->xnab", X[..., :d], G[..., :d]))
    S_rot = G[..., :d] - jnp.einsum("xnra,xnab->xnrb", X[..., :d], lam)
    grad = jnp.concatenate([S_rot, G[..., -1:]], axis=-1)
    grad = manifold.tangent_project(X, grad) \
        * graph.pose_mask[..., None, None]
    return jnp.sqrt(psum(jnp.sum(grad * grad)))


#: Compiled sharded-GN-tail program cache, FIFO-bounded for the same
#: reason as the certificate cache: each entry pins a Mesh.
_GN_CACHE: dict = {}
_GN_CACHE_MAX = 8


def _gn_programs(mesh: Mesh, meta: GraphMeta, cfg):
    key = (mesh, meta, cfg)
    progs = _GN_CACHE.get(key)
    if progs is not None:
        return progs
    ax = _axes(mesh)

    @jax.jit
    def outer(X, graph):
        body = partial(_gn_outer_shard, ax=ax, meta=meta, cfg=cfg)
        return _shard_map(body, mesh, (P(ax), _specs(mesh, graph)),
                          (P(ax), P()))(X, graph)

    @jax.jit
    def gradnorm(X, graph):
        body = partial(_gn_gradnorm_shard, ax=ax, meta=meta)
        return _shard_map(body, mesh, (P(ax), _specs(mesh, graph)),
                          P())(X, graph)

    while len(_GN_CACHE) >= _GN_CACHE_MAX:
        _GN_CACHE.pop(next(iter(_GN_CACHE)))
    _GN_CACHE[key] = (outer, gradnorm)
    return outer, gradnorm


def gn_tail_sharded(X, graph: MultiAgentGraph, meta: GraphMeta,
                    mesh: Mesh | None = None,
                    cfg: "refine.GNTailConfig | None" = None,
                    weights=None, log=None,
                    fetch_deadline_s: float | None = None):
    """Sharded, device-resident Gauss-Newton-CG polish of an
    agent-partitioned iterate — ``refine.gn_tail`` without the host-f64
    scipy round-trip.

    ``X [A, n_max, r, d+1]`` and ``graph`` may be host or mesh-placed;
    they are sharded over ``mesh`` (default: all devices).  ``weights
    [A, E]``, when given, replaces ``graph.edges.weight`` — pass the final
    GNC weights when polishing a robust solve.  Per outer step ONE small
    stats vector crosses the link (through ``rbcd._host_fetch``); the CG
    loop and the backtracking retraction run entirely on device.

    ``fetch_deadline_s`` arms a ``parallel.resilience.Watchdog`` around
    those blocking reads: a dead mesh raises a phase-naming
    ``MeshFaultError`` instead of hanging the caller forever.  (Inside
    ``solve_rbcd_sharded(resilience=...)`` the solve's own guard already
    covers this tail — leave it None there.)

    Returns ``(X_agents, refine.GNTailResult)`` — the polished iterate in
    the sharded per-agent layout plus the host result record (global
    assembly, histories, totals) in ``gn_tail``'s schema."""
    mesh = mesh or make_mesh()
    cfg = cfg or refine.GNTailConfig()
    if weights is not None:
        graph = rbcd.with_weights(graph, weights)
    put = lambda t: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        t, _specs(mesh, t))
    X = put(jnp.asarray(X))
    graph = put(graph)
    outer, gradnorm = _gn_programs(mesh, meta, cfg)

    cost_hist: list = []
    gn_hist: list = []
    cg_total = 0
    outer_done = 0
    terminated_by = "max_outer"
    with contextlib.ExitStack() as stack:
        if fetch_deadline_s is not None:
            # Watchdog scope: the two sanctioned fetches below route
            # through rbcd._host_fetch, which the guard deadline-wraps.
            stack.enter_context(resilience_mod.fetch_guard(
                resilience_mod.Watchdog(fetch_deadline_s), None,
                ["gn_tail"], close=True))
        for k in range(int(cfg.max_outer) + 1):
            # One scalar per outer step: the gate quantity.  The stats
            # fetch below is the only other transfer — the CG loop itself
            # never touches the host.
            # dpgolint: disable=DPG003 -- sanctioned GN-tail gate fetch
            gn = float(rbcd._host_fetch(gradnorm(X, graph)))
            gn_hist.append(gn)
            if log is not None:
                cst = cost_hist[-1] if cost_hist else float("nan")
                log(f"  gn_tail_sharded outer {k}: cost {cst:.9g} "
                    f"gn {gn:.4g}")
            if gn < cfg.grad_norm_tol:
                terminated_by = "grad_norm"
                break
            if k == int(cfg.max_outer):
                break  # budget exhausted; final point's gate value recorded
            X_new, stats = outer(X, graph)
            # dpgolint: disable=DPG003 -- sanctioned per-outer stats fetch
            st = rbcd._host_fetch(stats)
            f0, _gn_s, cg_iters, _neg, accepted, f_new, _step = \
                (float(v) for v in st)
            if not cost_hist:
                cost_hist.append(f0)
            cg_total += int(cg_iters)
            outer_done = k + 1
            if accepted <= 0:
                cost_hist.append(f0)
                terminated_by = "no_decrease"
                break
            cost_hist.append(f_new)
            X = X_new

    n_total = int(np.asarray(graph.global_index).max()) + 1
    Xg = np.asarray(rbcd.gather_to_global(X, graph, n_total), np.float64)
    result = refine.GNTailResult(
        X=Xg, cost_history=cost_hist, grad_norm_history=gn_hist,
        outer_iterations=outer_done, cg_iterations=cg_total,
        converged=terminated_by == "grad_norm", terminated_by=terminated_by)
    return X, result


#: Fused rounds per arm of the ``overlap="auto"`` calibration, timed
#: repetitions per arm (best-of, alternating), and the A/B efficiency
#: the overlapped arm must clear to win.  The threshold is deliberate
#: hysteresis sized ABOVE the scheduling-noise band of short best-of-N
#: walls on a shared-core mesh: measured across ~100 calibrations on the
#: 8-virtual-device CPU mesh the A/B efficiency of two equivalent
#: schedules wanders in roughly [-0.10, +0.18], so anything below 0.25
#: is indistinguishable from noise there.  Pipelining's genuine win is
#: the hidden collective fraction of the round — tens of percent on a
#: real interconnect when it pays at all (its loss on the CPU mesh is
#: what MULTICHIP_r06 measured at -0.05) — so the gate only flips to
#: overlapped on a decisive, better-than-noise win and resolves
#: everything else to the simpler lockstep schedule.
_AUTO_CALIB_ROUNDS = 8
_AUTO_CALIB_REPS = 3
_AUTO_THRESHOLD = 0.25


def _resolve_overlap_auto(mesh, state, graph, meta, params, exchange,
                          calib_rounds: int = _AUTO_CALIB_ROUNDS) -> bool:
    """The adaptive overlap gate: a bounded lockstep-vs-overlapped
    calibration on the real sharded problem, arbitrated by
    ``obs.devprof.decide_overlap``.

    Each arm compiles its fused multi-round program, warms it (paying the
    compile outside the timed window), then times ``calib_rounds``-round
    segments to a ``jax.block_until_ready`` fence — alternating arms,
    best of ``_AUTO_CALIB_REPS``, with NO profiler active: trace capture
    slows the traced
    program, so the decision walls stay clean.  With telemetry on, one
    additional segment per arm then runs under a ``DeviceTraceWindow``
    so the ``overlap_decision`` event carries the measured device-time
    evidence (collective/compute split, measured overlap efficiency)
    next to the A/B walls.  Calibration segments are pure functions of
    the sharded state and are discarded — the solve proper starts from
    the untouched initial state, so forced ``overlap=True/False`` modes
    remain bitwise references."""
    from ..obs import devprof

    run = obs.get_run()
    size = int(mesh.devices.size)
    if size == 1:
        # No collectives to hide on one device — nothing to calibrate.
        if run is not None:
            run.event("overlap_decision", phase="setup", mesh_size=size,
                      exchange=exchange, overlap=False,
                      reason="single_device_mesh", calib_rounds=0)
        return False
    shifts, plan = _exchange_plan(mesh, meta, graph, exchange)
    names = ("lockstep", "overlapped")
    multis = {}
    arms = {}
    for name, ov in zip(names, (False, True)):
        multis[name] = make_sharded_multi_step(mesh, meta, params, shifts,
                                               plan, overlap=ov)
        devprof.time_arm(multis[name], state, graph,
                         calib_rounds)  # compile + warm
        arms[name] = {"seconds": float("inf"), "rounds": calib_rounds,
                      "attribution": None}
    for _rep in range(_AUTO_CALIB_REPS):
        for name in names:
            dt = devprof.time_arm(multis[name], state, graph, calib_rounds)
            arms[name]["seconds"] = min(arms[name]["seconds"], dt)
    if run is not None:
        for name in names:
            window = devprof.DeviceTraceWindow(
                os.path.join(run.run_dir, f"devprof_auto_{name}"),
                plane="sharded").start()
            devprof.time_arm(multis[name], state, graph, calib_rounds)
            arms[name]["attribution"] = window.stop(
                num_rounds=calib_rounds, label=f"auto_{name}")
    decision = devprof.decide_overlap(arms, threshold=_AUTO_THRESHOLD)
    if run is not None:
        run.event("overlap_decision", phase="setup", mesh_size=size,
                  exchange=exchange, **decision)
    return bool(decision["overlap"])


def _resume_from_store(sup, mesh, graph_host, meta, params, run):
    """Warm entry for ``solve_rbcd_sharded(resume=True)``: the newest
    usable snapshot of the supervisor's session, resharded onto the
    caller's mesh (snapshots are mesh-shape-agnostic), or ``None`` for a
    cold start.  Same refresh-then-shard order as fault recovery, so a
    same-mesh resume is bitwise."""
    flush = getattr(sup.store, "flush", None)
    if flush is not None:
        flush()
    snap = sup.store.load_newest(sup.session_id)
    if snap is None:
        return None
    if snap.global_index is not None and not np.array_equal(
            np.asarray(snap.global_index), sup._gidx):
        return None  # different problem layout — fail open to cold start
    host_state = rbcd.refresh_problem(snap.state, graph_host, meta, params)
    state, graph = shard_problem(mesh, host_state, graph_host)
    if run is not None:
        run.event("mesh_resume", phase="resilience",
                  session=sup.session_id, iteration=int(snap.iteration),
                  mesh_size=int(mesh.devices.size))
    return state, graph, int(snap.iteration), int(snap.num_weight_updates)


def solve_rbcd_sharded(
    meas: Measurements,
    num_robots: int,
    mesh: Mesh | None = None,
    params: AgentParams | None = None,
    max_iters: int | None = None,
    grad_norm_tol: float = 0.1,
    eval_every: int = 1,
    dtype=jnp.float64,
    part: Partition | None = None,
    init: str = "chordal",
    exchange: str = "all_gather",
    verdict_every: int | None = None,
    overlap: "bool | str" = True,
    gn_tail: "refine.GNTailConfig | None" = None,
    resilience: "resilience_mod.ResilienceConfig | None" = None,
    boundary_cb=None,
    resume: bool = False,
) -> rbcd.RBCDResult:
    """Distributed solve over a device mesh — the deployment path of the
    framework (``models.rbcd.solve_rbcd`` is the single-device debug path).
    Shares the driver loop (``rbcd.run_rbcd``); only problem placement and
    the step function differ.  ``exchange`` selects the pose-exchange
    collective: ``"all_gather"`` (v1) or ``"ppermute"`` (one collective per
    ring offset that carries a cross-device edge — fewer hops than the
    all_gather ring when the device adjacency is near-chain).

    ``verdict_every`` (K, a positive multiple of ``eval_every``) switches
    the sharded driver to the DEVICE-RESIDENT verdict loop: the centralized
    metrics trace under shard_map with their reductions as psums
    (``make_sharded_metrics_body``), termination latches on device, and the
    host reads back ONE replicated packed int32 per K rounds through the
    same ``rbcd._host_fetch`` seam as the single-device loop — killing the
    per-eval readback on the mesh path too.  ``overlap`` (default on)
    software-pipelines the halo exchange inside the fused round loops
    (``make_sharded_multi_step``); ``overlap="auto"`` runs a bounded
    lockstep-vs-overlapped calibration on the sharded problem
    (``_resolve_overlap_auto``) and picks the winner, recording an
    ``overlap_decision`` event with the A/B walls and — with telemetry on
    — the measured device-time attribution as evidence.  Forced
    ``overlap=True/False`` stay bitwise-unchanged reference modes.
    ``gn_tail`` (a ``refine.GNTailConfig``)
    appends the sharded device-resident Gauss-Newton-CG polish
    (``gn_tail_sharded``) after the BCD loop, extending the returned
    histories with the tail's trajectory and re-finalizing the rounded
    trajectory from the polished iterate.

    ``resilience`` (a ``resilience_mod.ResilienceConfig``, requires the
    verdict loop) arms the pod-scale fault story: mesh-elastic
    checkpoints at verdict boundaries, watchdog deadlines on every
    blocking fetch, and a supervisor that catches latched verdict
    anomalies and ``MeshFaultError``\\ s, rewinds to the last good
    checkpoint — on a smaller mesh after a device loss — and resumes at
    the exact absolute round index.  The returned result then carries a
    ``resilience`` summary dict and ``recovered=True`` if any rewind
    happened; its histories cover the final (resumed) attempt — a
    numerically-pinned suffix of the undisturbed run's.

    ``boundary_cb(it, nwu, state, word, terminal)`` (requires the
    verdict loop) is an external verdict-boundary hook that runs BEFORE
    the resilience supervisor's own: the multihost lockstep
    (``parallel.multihost``) rides it to cross-check the replicated
    verdict word across processes and surface a dead peer as
    ``MeshFaultError(kind="process_lost")``.  ``resume=True`` (requires
    ``resilience``) enters the solve at the newest usable checkpoint of
    ``resilience.session_id`` instead of the initial guess — the restart
    path of a multihost generation whose predecessor lost a process —
    falling back to a cold start when the store holds nothing usable."""
    mesh = mesh or make_mesh()
    mesh_size = int(mesh.devices.size)
    if num_robots % mesh_size != 0:
        # Validated up front — the alternative is an opaque failure deep
        # inside shard_problem/comm_bytes_per_round after the full graph
        # build has already been paid for.
        raise ValueError(
            f"num_robots={num_robots} is not divisible by the mesh size "
            f"{mesh_size}: solve_rbcd_sharded lays agents out in equal "
            f"contiguous blocks per device.  Pick num_robots as a "
            f"multiple of {mesh_size}, or build a smaller mesh "
            f"(make_mesh(n) with n dividing {num_robots}).")
    if resilience is not None and verdict_every is None:
        raise ValueError(
            "resilience=ResilienceConfig(...) rides the verdict-boundary "
            "contract (checkpoints at word-fetch boundaries); pass "
            "verdict_every=K to use it")
    if boundary_cb is not None and verdict_every is None:
        raise ValueError(
            "boundary_cb is a verdict-boundary hook; pass verdict_every=K "
            "to use it")
    if resume and resilience is None:
        raise ValueError(
            "resume=True restores from the resilience checkpoint store; "
            "pass resilience=ResilienceConfig(...) to use it")
    params = params or AgentParams(d=meas.d, r=5, num_robots=num_robots)
    max_iters = params.max_num_iters if max_iters is None else max_iters

    # Telemetry (dpgo_tpu.obs): per-phase setup timings and the per-device
    # communication model for this mesh.  With no ambient run the timer is
    # never created and the path below is the uninstrumented one.
    run = obs.get_run()
    timer = RoundTimer() if run is not None else None

    part = part or partition_contiguous(meas, num_robots)
    if timer is not None:
        timer.start("build_graph")
    graph_host, meta = rbcd.build_graph(
        part, params.r, dtype, sel_mode=rbcd.resolved_sel_mode(params))
    if timer is not None:
        timer.stop("build_graph")
        timer.start("init")
    X0 = rbcd.initial_state_for(init, part, meta, graph_host, params, dtype)
    state_host0 = init_state(graph_host, meta, X0, params=params)
    if timer is not None:
        # The init chord/odometry solve runs on device; the obs-owned fence
        # materializes it so the phase boundary is trustworthy (telemetry-on
        # only — the off path never reaches this transfer).
        timer.stop("init", sync=obs.materialize(state_host0.X))
        timer.start("shard")
    state, graph = shard_problem(mesh, state_host0, graph_host)
    if timer is not None:
        timer.stop("shard")
        run.event("phase_timings", phase="setup", timings=timer.as_dict())

    if overlap == "auto":
        # Adaptive overlap gate (ISSUE 16): decide pipelining from a
        # measured A/B on this mesh/problem, not a hand-set flag.
        overlap = _resolve_overlap_auto(mesh, state, graph, meta, params,
                                        exchange)
    elif not isinstance(overlap, bool):
        raise ValueError(
            f"overlap={overlap!r}: expected True, False, or 'auto'")

    n_total = part.meas_global.num_poses
    num_meas = len(part.meas_global)
    certify_mode = getattr(params, "certify_mode", "off")
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype) \
        if (verdict_every is not None or certify_mode != "off") else None

    def _attempt(mesh_a, state_a, graph_a, start_it, start_nwu,
                 boundary_cb, injector):
        """One driver entry on one mesh: build the compiled step/segment
        wrappers for ``mesh_a`` and run ``rbcd.run_rbcd`` from the given
        absolute round index.  The supervisor loop below re-invokes this
        after a rewind — possibly on a smaller mesh."""
        size_a = int(mesh_a.devices.size)
        shifts, plan = _exchange_plan(mesh_a, meta, graph_a, exchange)
        sharded_step = make_sharded_step(mesh_a, meta, params, shifts, plan)
        sharded_multi = make_sharded_multi_step(mesh_a, meta, params, shifts,
                                                plan, overlap=overlap)
        sharded_seg = make_sharded_segment(mesh_a, meta, params, shifts,
                                           plan, overlap=overlap)
        if run is not None:
            # Compile accounting with the bytes-per-flop roofline for
            # every hot sharded program (devprof.profiled_program AOT-
            # compiles once per static combo — same compile count as the
            # plain jit path — and falls back to it on any probe
            # failure).  Fence-guarded: telemetry off keeps the bare jit
            # callables.
            from ..obs import devprof

            sharded_step = devprof.profiled_program(
                run, sharded_step, key=f"sharded/{size_a}/step",
                label="sharded_step", plane="sharded",
                static_names=("update_weights", "restart"),
                mesh_size=size_a)
            sharded_multi = devprof.profiled_program(
                run, sharded_multi, key=f"sharded/{size_a}/multi_step",
                label="sharded_multi_step", plane="sharded",
                mesh_size=size_a)
            sharded_seg = devprof.profiled_program(
                run, sharded_seg, key=f"sharded/{size_a}/segment",
                label="sharded_segment", plane="sharded",
                static_names=("update_weights", "restart"),
                mesh_size=size_a)
        if injector is not None:
            # Chaos seam (parallel.resilience): the injector counts
            # dispatched rounds and may poison a seeded public pose —
            # an async device op, never a host sync.
            step = lambda s, uw, rs: sharded_step(
                injector.before_dispatch(s, 1), graph_a,
                update_weights=uw, restart=rs)
            multi = lambda s, k: sharded_multi(
                injector.before_dispatch(s, k), graph_a, k)
            seg = lambda s, k, uw, rs: sharded_seg(
                injector.before_dispatch(s, k), graph_a, k,
                update_weights=uw, restart=rs)
        else:
            step = lambda s, uw, rs: sharded_step(s, graph_a,
                                                  update_weights=uw,
                                                  restart=rs)
            multi = lambda s, k: sharded_multi(s, graph_a, k)
            seg = lambda s, k, uw, rs: sharded_seg(s, graph_a, k,
                                                   update_weights=uw,
                                                   restart=rs)
        metrics_factory = None
        if verdict_every is not None:
            # The device-resident verdict loop under sharding: the same
            # driver (run_rbcd -> _run_verdict_loop), with the stacked-
            # metrics body traced inside shard_map, reductions as psums.
            metrics_factory = lambda telemetry: make_sharded_metrics_body(
                mesh_a, graph_a, edges_g, n_total, num_meas, telemetry)
        if run is not None:
            bytes_round = comm_bytes_per_round(
                meta, size_a,
                shifts=shifts if exchange == "ppermute" else None,
                accel=params.acceleration,
                itemsize=np.dtype(dtype).itemsize,
                greedy=params.schedule.value == "greedy")
            run.event("sharded_solve", phase="setup", mesh_size=size_a,
                      mesh_axes=list(mesh_a.axis_names), exchange=exchange,
                      num_robots=num_robots,
                      agents_per_shard=num_robots // size_a,
                      comm_bytes_per_round=bytes_round,
                      overlap=overlap, verdict_every=verdict_every,
                      start_iteration=int(start_it))
            run.gauge("sharded_comm_bytes_per_round",
                      "modeled per-device interconnect bytes per round",
                      unit="bytes").set(bytes_round)
            # Mesh identity into the run fingerprint: a 1-device and an
            # 8-device solve of the same problem are not comparable runs
            # for the convergence regression gate (report --compare).
            run.set_fingerprint(solver="solve_rbcd_sharded",
                                mesh_size=size_a, exchange=exchange)
        return rbcd.run_rbcd(state_a, graph_a, meta, step, part, max_iters,
                             grad_norm_tol, eval_every, dtype, params=params,
                             multi_step=multi, segment=seg,
                             verdict_every=verdict_every,
                             metrics_body_factory=metrics_factory,
                             start_iteration=start_it,
                             start_num_weight_updates=start_nwu,
                             boundary_cb=boundary_cb)

    def _append_gn_tail(res, graph_a, mesh_a):
        """Device-resident GN-CG polish on the terminal iterate (the
        sharded stall-breaker): same weighted objective the solve
        minimized."""
        Xa, tail = gn_tail_sharded(res.state.X, graph_a, meta, mesh=mesh_a,
                                   cfg=gn_tail, weights=res.state.weights)
        if run is not None:
            run.event("gn_tail", phase="refine", sharded=True,
                      outer_iterations=tail.outer_iterations,
                      cg_iterations=tail.cg_iterations,
                      terminated_by=tail.terminated_by,
                      cost=tail.cost_history[-1]
                      if tail.cost_history else None,
                      grad_norm=tail.grad_norm_history[-1]
                      if tail.grad_norm_history else None)

        # Re-finalize from the polished iterate through the shared fused
        # epilogue: with a certify mode on, the certificate is recomputed
        # on the POLISHED iterate (superseding the loop's) and rides the
        # same single terminal fetch.
        epilogue = rbcd.make_terminal_epilogue(
            graph_a, edges_g, n_total, num_meas, meta,
            certify_mode=certify_mode)
        fin = epilogue(Xa, res.state.weights, {})
        certificate = res.certificate
        if certify_mode != "off":
            # dpgolint: disable=DPG003 -- sanctioned terminal epilogue fetch
            fin = rbcd._host_fetch(fin)
            certificate = rbcd._epilogue_certificate(fin, edges_g, params,
                                                     dtype)
        T, w_glob = fin["T"], fin["w_glob"]
        return dataclasses.replace(
            res, T=T, X=Xa, weights=w_glob,
            cost_history=res.cost_history + tail.cost_history,
            grad_norm_history=res.grad_norm_history
            + tail.grad_norm_history,
            terminated_by=tail.terminated_by if tail.converged
            else res.terminated_by,
            state=res.state._replace(X=Xa),
            certificate=certificate)

    if resilience is None:
        res = _attempt(mesh, state, graph, 0, 0, boundary_cb, None)
        return res if gn_tail is None else _append_gn_tail(res, graph, mesh)

    # -- the rewind supervisor (parallel.resilience) ------------------------
    cfg = resilience
    store = cfg.resolve_store()
    sup = resilience_mod.CheckpointSupervisor(cfg, store, graph_host)
    injector = cfg.injector
    if injector is not None:
        injector.arm(graph_host)
    watchdog = resilience_mod.Watchdog(cfg.fetch_deadline_s) \
        if cfg.fetch_deadline_s is not None else None
    phase = ["sharded_verdict"]
    mesh_cur, state_cur, graph_cur = mesh, state, graph
    start_it = start_nwu = 0
    if boundary_cb is None:
        chained_cb = sup.boundary_cb
    else:
        def chained_cb(it, nwu, st, word, terminal, _ext=boundary_cb):
            # External hook first: the multihost lockstep must agree the
            # boundary is clean ACROSS processes before this rank commits
            # a checkpoint of it (a desync or dead peer aborts the save).
            _ext(it, nwu, st, word, terminal)
            sup.boundary_cb(it, nwu, st, word, terminal)
    if resume:
        restored = _resume_from_store(sup, mesh_cur, graph_host, meta,
                                      params, run)
        if restored is not None:
            state_cur, graph_cur, start_it, start_nwu = restored
    sup.attach_mesh(mesh_size)
    try:
        with resilience_mod.fetch_guard(watchdog, injector, phase):
            while True:
                try:
                    res = _attempt(mesh_cur, state_cur, graph_cur,
                                   start_it, start_nwu, chained_cb,
                                   injector)
                    break
                except (resilience_mod.AnomalyRewind,
                        resilience_mod.MeshFaultError) as e:
                    t0 = time.perf_counter()
                    if injector is not None:
                        # Unblock any simulated hang so abandoned
                        # watchdog workers can exit.
                        injector.release_hangs()
                    new_size, host_state, start_it, start_nwu = \
                        sup.recover(e, int(mesh_cur.devices.size),
                                    num_robots)
                    if new_size != int(mesh_cur.devices.size):
                        mesh_cur = make_mesh(new_size)
                    if host_state is None:
                        # Cold restart: no usable snapshot — back to the
                        # initial guess (factors already baked).
                        host_state = state_host0
                    else:
                        # Rebake the factors from the stored weights
                        # BEFORE sharding — the same host-then-shard
                        # order as the initial build, so a same-mesh
                        # resume is bitwise.
                        host_state = rbcd.refresh_problem(
                            host_state, graph_host, meta, params)
                    state_cur, graph_cur = shard_problem(
                        mesh_cur, host_state, graph_host)
                    sup.attach_mesh(new_size)
                    sup.note_overhead(time.perf_counter() - t0)
            if gn_tail is not None:
                phase[0] = "gn_tail"
                res = _append_gn_tail(res, graph_cur, mesh_cur)
    finally:
        if injector is not None:
            injector.release_hangs()
        if watchdog is not None:
            watchdog.close()
    return dataclasses.replace(
        res, recovered=res.recovered or sup.recoveries > 0,
        resilience=sup.finish(injector))
