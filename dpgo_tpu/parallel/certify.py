"""Distributed solution certification over the agent mesh.

``models.certify`` evaluates the dual certificate on the assembled global
solution (centralized).  This module is the decentralized counterpart — the
certification half of "Distributed Certifiably Correct Pose-Graph
Optimization" (T-RO 2021) that the reference never implemented (no
certificate code exists in ``/root/reference/src``): the minimum eigenvalue
of the dual-certificate operator ``S = Q - Lambda`` is computed by
distributed subspace (simultaneous orthogonal) iteration over the same
``"agent"`` mesh axis the RBCD solver runs on, with no agent ever holding
the global problem:

* ``S``'s matvec shards exactly like the RBCD gradient: each agent applies
  its local edge list to its own pose rows after a public-pose exchange of
  the probe block (same ``all_gather`` + neighbor-buffer machinery as the
  solver round; shared edges appear in both endpoint agents' lists with the
  remote endpoint in a neighbor slot, so local rows accumulate exactly the
  global ``Q V`` rows with no double counting).
* The dual blocks ``Lambda_i = sym(Y_i^T (XQ)_i)`` are per-pose quantities
  each agent computes from its own complete gradient rows.
* Every global scalar the eigensolver needs (norms, p x p Gram and
  Rayleigh-Ritz matrices) is a ``psum`` over the mesh axis of local masked
  contractions; the tiny p x p factorizations run replicated on every
  shard, so all shards stay in lockstep deterministically.

The result matches ``models.certify.certify_solution``'s LOBPCG value on
the assembled problem (asserted in tests/test_dist_certify.py on the
virtual 8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .. import obs
from ..ops import manifold, quadratic
from ..models import rbcd
from ..models.rbcd import MultiAgentGraph
from .sharded import (AXIS, _axes, _gather_exchange,  # noqa: F401
                      _shard_map, _specs, make_mesh)  # (re-export mesh)


def _egrad_local(V, Vz, graph: MultiAgentGraph):
    """Complete local gradient rows of the global map ``V Q`` for every
    agent: per-agent edge list applied to the [local | neighbor] buffer
    (``quadratic.egrad`` is linear, so it doubles as the ``Q`` matvec on
    probe blocks — the trailing axes just ride along)."""
    n = V.shape[1]

    def one(vl, vz, e):
        return quadratic.egrad(jnp.concatenate([vl, vz]), e, n_out=n)

    return jax.vmap(one)(V, Vz, graph.edges)


def _certificate_shard(X, graph: MultiAgentGraph, key, *, axis_name,
                       num_probe: int, power_iters: int, sub_iters: int):
    """shard_map body: distributed lambda_min(S) at the iterate X.

    X: [A_loc, n, r, dh] local agents' poses.  Returns per-shard-identical
    (lambda_min, sigma, stat, direction [A_loc, n, dh]).
    """
    A_loc, n, r, dh = X.shape
    d = dh - 1
    dtype = X.dtype
    mask = graph.pose_mask[..., None, None]  # [A, n, 1, 1]

    psum = lambda v: jax.lax.psum(v, axis_name)
    # Shared with the solver round and the sharded GN tail: the v1
    # all_gather neighbor-buffer exchange (sharded._gather_exchange).
    exchange = _gather_exchange(graph, axis_name)

    # Dual blocks from each agent's complete local gradient rows.
    Z = exchange(X)
    G = _egrad_local(X, Z, graph)
    lam = manifold.sym(
        jnp.einsum("xnra,xnrb->xnab", X[..., :d], G[..., :d]))

    def S(V):  # [A, n, p, dh] -> [A, n, p, dh]
        Vz = exchange(V)
        QV = _egrad_local(V, Vz, graph)
        LV_rot = jnp.einsum("xnpa,xnab->xnpb", V[..., :-1], lam)
        LV = jnp.concatenate([LV_rot, jnp.zeros_like(V[..., -1:])], axis=-1)
        return (QV - LV) * mask

    def inner_block(U, W):  # local contribution to the [p, q] Gram
        return jnp.einsum("anpd,anqd->pq", U * mask, W)

    # Per-shard deterministic randomness: fold the mesh position in.
    key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))

    # --- spectral shift: power iteration on S for the dominant |lambda| ---
    v = jax.random.normal(key, (A_loc, n, 1, dh), dtype) * mask

    def power_body(_, v):
        w = S(v)
        nrm = jnp.sqrt(psum(jnp.sum(w * w)))
        return w / jnp.maximum(nrm, 1e-30)

    v = power_body(0, v)  # normalize the random start
    v = jax.lax.fori_loop(0, power_iters, power_body, v)
    lam_dom = psum(jnp.sum(v * S(v)))
    sigma = 1.1 * jnp.abs(lam_dom) + 1e-3

    # --- subspace iteration on (sigma I - S)/sigma: spectrum in [0, ~1],
    # top eigenvalue 1 - lambda_min(S)/sigma.  The normalization keeps the
    # Rayleigh-Ritz / Gram matrices O(1) regardless of problem scale — at
    # sigma ~ 1e7 (100k-pose synthetic) the unnormalized f32 eigh/cholesky
    # on ~sigma-sized entries went NaN on TPU.
    def Aop(V):
        return (V - S(V) / sigma) * mask

    def _svqb(V):
        # SVQB whitening (Stathopoulos & Wu 2002): eigendecompose the
        # psum'd Gram and rotate by U diag(lam)^{-1/2} with the spectrum
        # clamped at eps * lam_max.  Unlike Cholesky-QR there is no
        # factorization to fail: a rank-deficient block (converged LOBPCG
        # basis, duplicated directions) just collapses the deficient
        # columns onto the clamp instead of producing NaN — measured on
        # the 100k-pose TPU run, where the f32 Cholesky path went NaN.
        gram = psum(inner_block(V, V))
        lam, U = jnp.linalg.eigh(0.5 * (gram + gram.T))
        lam = jnp.maximum(lam, 100 * jnp.finfo(dtype).eps * lam[-1] + 1e-30)
        C = U * jax.lax.rsqrt(lam)[None, :]
        return jnp.einsum("xnpd,pq->xnqd", V, C)

    def ortho_block(V):
        # Two passes: one whitening pass loses orthogonality like
        # kappa(V)^2 * eps — in f32 at 1e5-dimensional problems the
        # [V, R, P] basis collapses and LOBPCG stalls at an interior Ritz
        # value (measured on city10000: distributed f32 lambda_min came
        # out 1.3e3 vs the centralized f64 1.2e-2).  The second pass
        # restores O(eps) orthogonality (same argument as CholeskyQR2,
        # Yamamoto et al. 2015).
        return _svqb(_svqb(V))

    def rotate(V, C):  # apply a [p_in, p_out] coefficient matrix
        return jnp.einsum("xnpd,pq->xnqd", V, C)

    # Distributed block LOBPCG (no preconditioner): basis [V, R, P] per
    # iteration, every reduction a psum'd Gram, the 3p x 3p Rayleigh-Ritz
    # replicated on all shards.  Plain subspace iteration stalls on the
    # clustered bottom spectrum of S (gauge near-zeros); the conjugate
    # block makes the sphere2500 certificate match the centralized LOBPCG
    # in a few hundred matvecs.
    key2 = jax.random.fold_in(key, 1)
    p = num_probe
    # Warm start: at a near-stationary iterate the r rows of X^T nearly
    # span ker(S) (S X^T ~ stationarity gap), i.e. the bottom eigenspace —
    # seed min(p-1, r) probes with X rows (the last probe stays random so
    # a suboptimality direction OUTSIDE span(X^T) is still found; that
    # direction is exactly what certification is about).  From a purely
    # random block the LOBPCG must resolve the clustered bottom spectrum
    # unaided, which in f32 at 1e5-pose scale does not converge in any
    # reasonable iteration budget (measured: 100k synthetic reported an
    # interior Ritz value 6.5e6 vs the true 3.0).
    V0 = jax.random.normal(key2, (A_loc, n, p, dh), dtype) * mask
    n_warm = min(p - 1, X.shape[2])
    if n_warm > 0:
        V0 = V0.at[:, :, :n_warm, :].set(X[:, :, :n_warm, :] * mask)
    V = ortho_block(V0)
    P = ortho_block(
        jax.random.normal(jax.random.fold_in(key, 2),
                          (A_loc, n, p, dh), dtype) * mask)

    def colnorm(U):
        # Per-probe normalization before the joint [V, R, P] Gram: the raw
        # residual block has column norms ~sigma (1e7 at 100k scale) next
        # to V's unit columns — the combined Gram then spans ~sigma^2
        # dynamic range and the f32 Cholesky ridge (scaled by the trace)
        # swamps the V block entirely, stalling LOBPCG at an interior Ritz
        # value.  Unit columns keep the Gram O(1)-conditioned per block.
        nrm = jnp.sqrt(psum(jnp.einsum("anpd,anpd->p", U * mask, U)))
        return U / jnp.maximum(nrm, 1e-30)[None, None, :, None]

    def lobpcg_body(_, VP):
        V, P = VP
        W = Aop(V)
        Hv = psum(inner_block(V, W))
        R = colnorm(W - rotate(V, Hv))   # block residual, unit columns
        Zb = jnp.concatenate([V, R, P], axis=2)
        Zb = ortho_block(Zb)
        Hz = psum(inner_block(Zb, Aop(Zb)))
        Hz = 0.5 * (Hz + Hz.T)
        _, C = jnp.linalg.eigh(Hz)       # ascending
        Ctop = C[:, -p:]
        V_new = ortho_block(rotate(Zb, Ctop))
        # Conjugate block: the R/P components of the new Ritz vectors.
        Ctail = Ctop.at[:p].set(0.0)
        P_new = ortho_block(rotate(Zb, Ctail))
        return V_new, P_new

    V, P = jax.lax.fori_loop(0, sub_iters, lobpcg_body, (V, P))

    # Final Rayleigh-Ritz on the converged block.
    H = psum(inner_block(V, Aop(V)))
    H = 0.5 * (H + H.T)
    theta, Q = jnp.linalg.eigh(H)          # ascending
    lam_min = sigma * (1.0 - theta[-1])    # Aop spectrum is lambda/sigma
    direction = jnp.einsum("xnpd,p->xnd", V, Q[:, -1])

    # Stationarity residual ||X S|| (X's r rows ride as probe rows).
    XS = S(X)
    stat = jnp.sqrt(psum(jnp.sum(XS * XS)))
    return lam_min, sigma, stat, direction


def make_sharded_certificate(mesh, num_probe: int = 4,
                             power_iters: int = 50, sub_iters: int = 100):
    """Compile the distributed certificate: one shard_map program computing
    lambda_min(S) (plus shift, stationarity residual and the minimal
    eigendirection) for an agent-sharded iterate."""

    @partial(jax.jit, static_argnames=())
    def cert(X, graph: MultiAgentGraph, key):
        body = partial(_certificate_shard, axis_name=_axes(mesh),
                       num_probe=num_probe, power_iters=power_iters,
                       sub_iters=sub_iters)
        in_specs = (_specs(mesh, X), _specs(mesh, graph),
                    jax.sharding.PartitionSpec())
        from jax.sharding import PartitionSpec as P
        out_specs = (P(), P(), P(), P(_axes(mesh)))
        return _shard_map(body, mesh, in_specs, out_specs)(X, graph, key)

    return cert


def solve_staircase_sharded(meas, num_robots: int, mesh=None,
                            r_min: int | None = None, r_max: int = 10,
                            rounds_per_rank: int = 300,
                            grad_norm_tol: float = 1e-8,
                            eta: float = 1e-5, dtype=None, X0=None,
                            accel: bool = False,
                            restart_interval: int = 100,
                            verbose: bool = False):
    """Distributed certifiably correct PGO, end to end on the mesh.

    The full loop of the T-RO 2021 title: RBCD solve sharded over the agent
    mesh, the dual certificate via the distributed block LOBPCG, and — on
    failure — the saddle escape to rank r+1 applied per agent (the lift
    ``X+ = [[X], [alpha v^T]]`` is a per-pose operation; only the
    backtracking line search consults the global cost, a scalar consensus).
    ``models.certify.solve_staircase`` is the centralized counterpart.

    Returns ``(T, X_agents, rank, CertificateResult, history)`` with ``T``
    the rounded global trajectory and ``history`` a list of per-rank
    4-tuples ``(rank, cost_f64, lambda_min, wall_seconds)`` — one entry
    per staircase level, wall covering that level's solve + certificate.
    """
    import numpy as np

    from ..config import AgentParams, SolverParams
    from ..models import refine
    from ..models.certify import _recover_rounding_basis
    from ..models.local_pgo import round_solution
    from ..types import edge_set_from_measurements
    from ..utils.partition import partition_contiguous
    from .sharded import make_sharded_multi_step, shard_problem

    mesh = mesh or make_mesh()
    d = meas.d
    r_min = d + 1 if r_min is None else r_min
    dtype = dtype or jnp.float32
    part = partition_contiguous(meas, num_robots)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    n_total = part.meas_global.num_poses

    import time as _time

    Xa = X0
    history = []
    for r in range(r_min, r_max + 1):
        t_rank = _time.perf_counter()
        params = AgentParams(
            d=d, r=r, num_robots=num_robots, rel_change_tol=0.0,
            # Post-escape descent is a long-wavelength coherent mode
            # (e.g. cycle unwinding); Nesterov momentum traverses it in
            # O(sqrt) of the plain-BCD round count (``accel=True`` is the
            # at-scale escape configuration, experiments/
            # staircase_escape_100k.py).
            acceleration=accel, restart_interval=restart_interval,
            solver=SolverParams(grad_norm_tol=grad_norm_tol,
                                max_inner_iters=10))
        graph, meta = rbcd.build_graph(
            part, r, dtype, sel_mode=rbcd.resolved_sel_mode(params))
        if Xa is None:
            Xa = rbcd.centralized_chordal_init(part, meta, graph, dtype)
        state = rbcd.init_state(graph, meta, jnp.asarray(Xa, dtype),
                                params=params)
        state, graph_s = shard_problem(mesh, state, graph)
        steps = make_sharded_multi_step(mesh, meta, params)
        # Chunked dispatch: a single >~35 s device program kills the
        # tunneled TPU worker (measured round 5 — 400 rounds at the 100k
        # SE(2) shape crashed it); sync between ~100-round programs.
        left = rounds_per_rank
        while left > 0:
            k = min(100, left)
            state = steps(state, graph_s, k)
            # Keeps each device program under the tunnel's ~35 s ceiling.
            # dpgolint: disable=DPG003 -- sanctioned chunk-boundary sync
            jax.block_until_ready(state.X)
            left -= k
        Xa = state.X

        # One readback per staircase rank, after rounds_per_rank rounds.
        # dpgolint: disable=DPG003 -- sanctioned rank-boundary readback
        Xg = np.asarray(rbcd.gather_to_global(Xa, graph, n_total),
                        np.float64)
        # Stationarity polish before certifying: lambda_min(S) at a
        # non-stationary X carries a -O(||rgrad||) term, so the f32
        # descent floor (gn ~1e-3 at 100k) reads "not certified" even at
        # the optimum (measured round 5).  Re-centered refine cycles
        # drive gn to f64 grade; the certificate then answers curvature,
        # not leftover gradient.  (f32 solves only: an f64 solve reaches
        # tight gn by plain descent, and the tests' virtual-mesh runs
        # would pay interpreter-mode kernels for nothing.)
        if dtype == jnp.float32:
            Xg, gn_hist = refine.polish(Xg, graph, meta, params,
                                        part.meas_global, cycles=3,
                                        rounds_per_cycle=200)
            Xa = jnp.asarray(rbcd.scatter_to_agents(
                jnp.asarray(Xg, dtype), graph))
            if verbose:
                print(f"[staircase-sharded] rank {r}: polish gn "
                      f"{gn_hist[0]:.2e} -> {gn_hist[-1]:.2e}")
        f = refine.global_cost(Xg, edges_g)
        cert = certify_sharded(Xa, graph_s, mesh=mesh, eta=eta, seed=r,
                               global_ctx=(Xg, edges_g))
        # Per-rank wall (solve + certificate) — the config #5 staircase
        # benchmark reads these (experiments/staircase_100k.py).
        history.append((r, f, cert.lambda_min,
                        round(_time.perf_counter() - t_rank, 2)))
        if verbose:
            print(f"[staircase-sharded] rank {r}: cost {f:.6f}, "
                  f"lambda_min {cert.lambda_min:.3e}, "
                  f"certified={cert.certified} "
                  f"(tol {cert.tol:.1e}, sigma {cert.sigma:.1e}, "
                  f"decidable={cert.decidable}, "
                  f"lam_f64={cert.lambda_min_f64})")
        if cert.certified or r == r_max:
            X64 = jnp.asarray(Xg)
            ylift = _recover_rounding_basis(X64, d)
            T = round_solution(X64, ylift)
            return T, Xa, r, cert, history

        # Saddle escape per agent: append the negative-curvature row, pick
        # alpha by a geometric sweep on the global cost (scalar consensus).
        # The eigendirection is GLOBALLY unit-norm, so at N poses its
        # per-pose rows are O(1/sqrt(N)) — the round-4 backtracking from
        # alpha=1e-2 produced O(1e-5) per-pose nudges at 100k, which
        # descent could not carry out of the saddle basin (measured round
        # 5: cost moved 2.8e-4 of 3946 in 400 rounds).  Normalize to unit
        # MAX per-pose row norm and take the best alpha of a sweep, so the
        # escape amplitude is scale-free.
        # Per failed certificate, not per round; the sweep is host math.
        # dpgolint: disable=DPG003 -- sanctioned escape-side readback
        v = np.asarray(cert.direction, np.float64)        # [A, n, dh]
        vmax = np.sqrt((v * v).sum(-1).max())
        v = v / max(vmax, 1e-30)
        # dpgolint: disable=DPG003 -- sanctioned escape-side readback
        Xa_np = np.asarray(Xa, np.float64)
        f0 = f

        def lifted(alpha):
            rows = alpha * v[:, :, None, :]
            Xp = np.concatenate([Xa_np, rows], axis=2)    # [A, n, r+1, dh]
            return np.asarray(jax.vmap(manifold.project)(
                jnp.asarray(Xp)), np.float64)

        best_alpha, best_f = 0.0, f0
        for p in range(22):
            alpha = 2.0 ** (-p)                           # 1.0 ... ~2.4e-7
            # 22 host cost evals per escape; escapes are rank transitions.
            # dpgolint: disable=DPG003 -- sanctioned escape-sweep eval
            Xg_p = np.asarray(rbcd.gather_to_global(
                jnp.asarray(lifted(alpha)), graph, n_total), np.float64)
            f_p = refine.global_cost(Xg_p, edges_g)
            if f_p < best_f:
                best_alpha, best_f = alpha, f_p
        Xa = lifted(best_alpha)
    raise AssertionError("unreachable")


#: Compiled-certificate cache, FIFO-bounded: each entry pins a shard_map
#: executable and its Mesh, so an unbounded dict would leak stale meshes in
#: long-lived processes that rebuild meshes (e.g. test suites).
_CERT_CACHE: dict = {}
_CERT_CACHE_MAX = 8


def certify_sharded(X, graph: MultiAgentGraph, mesh=None,
                    eta: float = 1e-5, seed: int = 0, num_probe: int = 4,
                    power_iters: int = 50, sub_iters: int = 100,
                    weights=None, global_ctx=None):
    """Distributed dual certificate of an agent-partitioned iterate.

    ``X [A, n_max, r, d+1]`` and ``graph`` may be host or mesh-placed; they
    are sharded over ``mesh`` (default: all devices).  Returns a
    ``models.certify.CertificateResult`` whose ``direction`` is the
    per-agent [A, n_max, d+1] eigendirection.

    ``global_ctx = (Xg64 [N, r, d+1], edges_global)``: when the on-device
    eigensolve's dtype error cannot resolve the weight-scale tolerance
    (``decidable`` would be False — large sigma in f32), the minimum
    eigenvalue is re-verified on the host in f64 from this global
    assembly; without it, such a certificate is refused rather than
    over-claimed.

    ``weights [A, E]``, when given, replaces ``graph.edges.weight`` — pass
    the final GNC weights (``RBCDState.weights``) when certifying a robust
    solve: the certificate is of the weighted objective the solver actually
    minimized, not the build-time unit-weight one.
    """
    from jax.sharding import NamedSharding
    from ..models.certify import CertificateResult

    mesh = mesh or make_mesh()
    if weights is not None:
        graph = rbcd.with_weights(graph, weights)
    put = lambda t: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        t, _specs(mesh, t))
    X = put(X)
    graph = put(graph)
    # Cache the compiled certificate per configuration: jax.jit caches by
    # function identity, so a fresh closure per call would recompile the
    # shard_map LOBPCG every invocation (staircase re-certifies repeatedly).
    cfg = (mesh, num_probe, power_iters, sub_iters)
    cert = _CERT_CACHE.get(cfg)
    if cert is None:
        while len(_CERT_CACHE) >= _CERT_CACHE_MAX:
            _CERT_CACHE.pop(next(iter(_CERT_CACHE)))
        cert = _CERT_CACHE[cfg] = make_sharded_certificate(
            mesh, num_probe=num_probe, power_iters=power_iters,
            sub_iters=sub_iters)
    lam_min, sigma, stat, direction = cert(X, graph,
                                           jax.random.PRNGKey(seed))
    lam_min_f = float(lam_min)
    sigma_f = float(sigma)
    # Weight-scale tolerance + dtype decidability (VERDICT r4 item 3) —
    # shared semantics with models.certify.certify_solution.  The
    # per-agent edge table holds each cross edge in both endpoint agents,
    # which leaves the MEDIAN weighted concentration unchanged.
    from ..models.certify import (decide_certificate, lambda_min_f64,
                                  weight_scale)
    wscale = weight_scale(graph.edges)
    tol = eta * wscale
    import numpy as np

    def f64_solve(t):
        # Host-f64 verification: polish the distributed eigenvector on
        # the GLOBAL operator (Xg64, global EdgeSet supplied by the
        # caller, e.g. solve_staircase_sharded).
        Xg64, edges_global = global_ctx
        if weights is not None:
            # The certificate is of the WEIGHTED objective: fold the
            # per-agent GNC weights back to global measurement ids so
            # the f64 operator matches the one the device certified
            # (unit-weight edges_global would include rejected
            # outliers' full-strength blocks).
            M = int(np.asarray(graph.meas_id).max()) + 1
            w_glob = np.ones(M)
            mid = np.asarray(graph.meas_id).ravel()
            msk = np.asarray(graph.edges.mask).ravel() > 0
            w_glob[mid[msk]] = np.asarray(weights).ravel()[msk]
            edges_g = edges_global._replace(
                weight=np.asarray(edges_global.weight) * w_glob)
        else:
            edges_g = edges_global
        gi = np.asarray(graph.global_index)
        pmask = np.asarray(graph.pose_mask) > 0
        warm = np.zeros((Xg64.shape[0], Xg64.shape[2]))
        warm[gi[pmask]] = np.asarray(direction, np.float64)[pmask]
        lam64, v64, resid = lambda_min_f64(np.asarray(Xg64, np.float64),
                                           edges_g, warm=warm, tol=t,
                                           tol_cert=tol)
        # Scatter the polished f64 eigenvector back to the per-agent
        # layout via global_index so a failing certificate hands the
        # staircase the f64 descent direction, not the stale f32 one.
        vec_pa = None
        if v64 is not None:
            vec_pa = np.zeros(np.asarray(direction).shape, np.float64)
            vec_pa[pmask] = np.asarray(v64, np.float64)[gi[pmask]]
        return lam64, vec_pa, resid

    run = obs.get_run()
    f64_secs: list = []
    chosen_f64 = f64_solve if global_ctx is not None else None
    if run is not None and chosen_f64 is not None:
        from ..models.certify import _timed_f64
        chosen_f64 = _timed_f64(chosen_f64, f64_secs)
    certified, decidable, _, lam_f64, vec64 = decide_certificate(
        lam_min_f, sigma_f, tol, float(jnp.finfo(jnp.asarray(X).dtype).eps),
        chosen_f64)
    if vec64 is not None:
        direction = jnp.asarray(vec64, jnp.asarray(direction).dtype)
    if run is not None:
        # Verdict timeline on the distributed path too: the staircase's
        # REFUSE loops (docs/NEXT.md) are exactly the streaks the health
        # layer flags; every scalar here was already materialized above.
        lam_used = lam_f64 if lam_f64 is not None else lam_min_f
        from ..models.certify import _tally_cert
        _tally_cert(run, certified, decidable, f64_secs,
                    source="certify_sharded")
        run.event("certificate", phase="certify", sharded=True,
                  certified=certified, decidable=decidable,
                  lambda_min=lam_min_f, lambda_min_f64=lam_f64,
                  eigenvalue_gap=lam_used + tol, tol=tol, sigma=sigma_f,
                  f64_fallback_s=sum(f64_secs) if f64_secs else None,
                  stationarity_gap=float(stat))
        from ..obs.health import monitor_for as _monitor_for

        _monitor_for(run).observe_certificate(
            certified=certified, decidable=decidable, lambda_min=lam_used,
            source="certify_sharded")
    return CertificateResult(
        certified=certified,
        lambda_min=lam_min_f,
        direction=direction,
        stationarity_gap=float(stat),
        sigma=sigma_f,
        tol=tol,
        weight_scale=wscale,
        decidable=decidable,
        lambda_min_f64=lam_f64,
    )
