"""Pod-scale resilience for the sharded solve plane (ISSUE 14).

The ``shard_map`` fast path (``parallel.sharded``) made the mesh the
default deployment plane, but until this module a lost or hung device
killed the whole program and a multi-hour solve restarted from zero.
Three pieces close that gap, all riding contracts the solver already
pays for:

* **Mesh-elastic checkpoints** — at verdict boundaries (the existing
  one-int32-per-K-rounds readback, so checkpointing adds ZERO new
  steady-state synchronization points) the sharded ``RBCDState`` is
  gathered into a mesh-shape-independent host layout: every persisted
  array keeps its per-agent ``[A, ...]`` leading axis, and the snapshot
  carries ``graph.global_index`` so a reader can verify the agent->pose
  layout before resuming.  Snapshots persist through
  ``serve.session.SessionStore`` (atomic write + quarantine semantics
  reused, schema v2), so a solve checkpointed on 8 devices resumes on
  4 or 2 — ``shard_problem`` re-blocks the same per-agent arrays over
  whatever mesh is left.

* **A deterministic collective fault injector** —
  ``CollectiveFaultInjector`` wraps the exchange seams
  (``rbcd._exchange_for`` / ``sharded._gather_exchange`` via their
  module-level ``_exchange_wrap`` / ``_gather_wrap`` hooks) and the
  driver's ``rbcd._host_fetch`` reads to inject NaN/corrupt halo
  payloads, simulated device loss, and hung fetches — seeded per-link
  like the deployment plane's ``comms.faults.FaultInjector``, so chaos
  runs replay exactly.

* **Anomaly-triggered rewind** — the verdict word's latched anomaly
  bits (non-finite / cost-spike / stall / grad-explosion) already
  detect trouble ON DEVICE; the supervisor loop in
  ``solve_rbcd_sharded(resilience=ResilienceConfig(...))`` turns a
  latched anomaly or a ``MeshFaultError`` into a rewind to the last
  good checkpoint (optionally on a smaller mesh) instead of a dead
  program.  ``Watchdog`` deadlines around every blocking fetch make a
  dead mesh raise a structured, phase-naming ``MeshFaultError``
  (mirroring ``RoundTimer.stop``'s open-phase guard) instead of
  hanging forever.

The checkpoint gather routes through this module's own ``_host_fetch``
seam — NOT ``rbcd._host_fetch`` — because the driver-loop sync-rate
contract (``host_syncs_per_100_rounds == 100/K``, counted by patching
``rbcd._host_fetch``) must hold with resilience enabled: the gather
rides a boundary the word fetch just drained, so it adds bytes to an
already-paid synchronization point, never a new stall.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FetchTimeout

import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models import rbcd
from ..serve.session import SessionStore

#: RBCDState fields a checkpoint persists (the recomputable factors are
#: dropped; ``rbcd.refresh_problem`` restores them bit-for-bit from the
#: stored weights — same contract as ``serve.session``).
_CHECKPOINT_FIELDS = ("X", "weights", "iteration", "key", "rel_change",
                      "ready", "gamma", "alpha", "mu", "V", "X_init")

#: Anomaly names a ``ResilienceConfig.rewind_on`` entry may use (the
#: verdict word's latched anomaly vocabulary, ``rbcd._VERDICT_ANOMALY``).
REWINDABLE_ANOMALIES = frozenset(
    name for name in rbcd._VERDICT_ANOMALY.values() if name is not None)


def _host_fetch(x):
    """The resilience plane's device->host transfer seam.

    Deliberately separate from ``rbcd._host_fetch``: the checkpoint
    gather happens at a verdict boundary the word fetch has already
    drained, so it must not count against (or be hidden inside) the
    driver loop's sync-rate contract.  Tests count checkpoint transfers
    by patching THIS function.  Semantically just ``np.asarray``."""
    return np.asarray(x)


class MeshFaultError(RuntimeError):
    """A structured mesh fault: which phase was blocked, what kind of
    fault, and (for device loss) which device — the sharded plane's
    analog of the serve plane's typed worker-death errors."""

    def __init__(self, message: str, *, phase: str, kind: str = "fault",
                 device: int | None = None):
        super().__init__(message)
        self.phase = str(phase)
        self.kind = str(kind)
        self.device = device


#: Fault kinds scoped to the WORLD, not the local mesh: a peer process
#: that died (``process_lost``, the multihost barrier timeout) or whose
#: lockstep verdict word diverged (``desync``).  ``CheckpointSupervisor
#: .recover`` re-raises these instead of rewinding — a dead or diverged
#: peer cannot be repaired in-process; the multihost launcher shrinks
#: the world and respawns the survivors, whose supervisor then resumes
#: from the same checkpoint store (``parallel.multihost``).
WORLD_FAULT_KINDS = frozenset({"process_lost", "desync"})


class DeviceLostError(MeshFaultError):
    """A device (simulated or real) dropped out of the mesh."""

    def __init__(self, message: str, *, phase: str, device: int | None = None):
        super().__init__(message, phase=phase, kind="device_loss",
                         device=device)


class AnomalyRewind(Exception):
    """Internal control-flow signal: a verdict boundary latched an
    anomaly the policy rewinds on.  Raised by the supervisor's boundary
    callback, caught by ``solve_rbcd_sharded``'s recovery loop — it
    never escapes to callers (a blown rewind budget surfaces as
    ``MeshFaultError(kind="rewind_budget")``)."""

    def __init__(self, anomaly: str, iteration: int, word: int):
        super().__init__(f"verdict anomaly {anomaly!r} latched at "
                         f"iteration {iteration}")
        self.anomaly = str(anomaly)
        self.iteration = int(iteration)
        self.word = int(word)


# ---------------------------------------------------------------------------
# Watchdog: deadline-guarded blocking fetches
# ---------------------------------------------------------------------------

class Watchdog:
    """Deadline guard for blocking device->host reads.

    Each guarded fetch runs on a worker thread; if it does not complete
    within ``deadline_s`` the caller gets a phase-naming
    ``MeshFaultError`` (mirroring ``RoundTimer.stop``'s open-phase
    guard message style) while the stuck transfer is abandoned to a
    fresh worker.  ``close()`` joins every worker — callers must
    release whatever is blocking them first (the injector's
    ``release_hangs``; on real hardware, process teardown)."""

    def __init__(self, deadline_s: float):
        if not deadline_s or deadline_s <= 0:
            raise ValueError(f"watchdog deadline must be > 0, "
                             f"got {deadline_s!r}")
        self.deadline_s = float(deadline_s)
        self._pool: ThreadPoolExecutor | None = None
        self._abandoned: list[ThreadPoolExecutor] = []

    def fetch(self, fn, x, phase: str):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dpgo-mesh-watchdog")
        fut = self._pool.submit(fn, x)
        try:
            return fut.result(timeout=self.deadline_s)
        except _FetchTimeout:
            # The worker is stuck inside the transfer; abandon it (a
            # later close() joins it) so a post-rewind fetch does not
            # queue behind the hung one.
            self._abandoned.append(self._pool)
            self._pool.shutdown(wait=False)
            self._pool = None
            raise MeshFaultError(
                f"host fetch in phase {phase!r} exceeded the "
                f"{self.deadline_s:g}s watchdog deadline (dead mesh or "
                f"hung collective — no data arrived)",
                phase=phase, kind="fetch_timeout") from None

    def close(self):
        """Join every worker thread (leak-free teardown)."""
        for pool in [*self._abandoned,
                     *([self._pool] if self._pool is not None else [])]:
            pool.shutdown(wait=True)
        self._abandoned = []
        self._pool = None


@contextlib.contextmanager
def fetch_guard(watchdog: Watchdog | None,
                injector: "CollectiveFaultInjector | None",
                phase: list, *, close: bool = False):
    """Scope that routes every ``rbcd._host_fetch`` through the watchdog
    deadline and the injector's fetch-side faults.

    ``phase`` is a one-element list the caller mutates as the solve
    moves between phases (``["sharded_verdict"]`` -> ``"gn_tail"``), so
    a timeout names what was actually blocked.  The guard wraps
    whatever ``rbcd._host_fetch`` currently is — a test's counting shim
    installed first keeps counting — and restores it on exit.  The
    injector's hang/device-loss faults execute INSIDE the guarded
    worker so the watchdog can time them out like a real dead mesh."""
    orig = rbcd._host_fetch

    def fetch_with_faults(x):
        if injector is not None:
            injector.on_fetch(phase[0])
        return orig(x)

    def guarded(x):
        if watchdog is not None:
            return watchdog.fetch(fetch_with_faults, x, phase[0])
        return fetch_with_faults(x)

    rbcd._host_fetch = guarded
    try:
        yield
    finally:
        rbcd._host_fetch = orig
        if close and watchdog is not None:
            watchdog.close()


# ---------------------------------------------------------------------------
# Deterministic collective fault injection
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MeshFaultSpec:
    """What to break, and when (in DISPATCHED solver rounds — the host
    schedule is deterministic, so a chaos run replays exactly).

    Each entry in a ``*_rounds`` tuple fires once, the first time the
    dispatch counter crosses it.  Halo faults poison a seeded public
    pose at dispatch time (an async device op — no host sync); device
    loss and hangs fire at the next guarded fetch, where the driver
    would actually observe a dead mesh."""

    #: Dispatch rounds at which a NaN halo payload is injected.
    nan_halo_rounds: tuple = ()
    #: Dispatch rounds at which a finite-garbage halo payload is injected.
    corrupt_halo_rounds: tuple = ()
    #: Dispatch rounds after which the next fetch raises DeviceLostError.
    device_loss_rounds: tuple = ()
    #: Which device "dies" (bookkeeping only on the virtual mesh).
    lost_device: int = 0
    #: Dispatch rounds after which the next fetch blocks for ``hang_s``.
    hang_rounds: tuple = ()
    hang_s: float = 3600.0
    #: (src_agent, dst_agent) link to corrupt; None = seeded choice.
    link: tuple | None = None


class CollectiveFaultInjector:
    """Deterministic fault injection on the mesh's collective seams.

    Seeded per-link exactly like the deployment plane's
    ``comms.faults.FaultInjector`` (``default_rng((seed << 32) ^
    crc32(repr(link)))``), so which pose gets poisoned and which slot a
    wrapped exchange corrupts replay across runs.  Two injection levels:

    * **dispatch-time** (``before_dispatch``): the supervisor wraps the
      segment dispatch; when a configured round is crossed, one seeded
      public pose of one seeded agent is set to NaN/garbage so the NEXT
      exchange carries the corrupt halo to every neighbor — the
      mid-solve transient that must trip the verdict anomaly latch.
    * **trace-time** (``installed()`` / ``wrap_exchange``): the
      ``rbcd._exchange_wrap`` / ``sharded._gather_wrap`` hooks pass
      every exchange closure built while installed through
      ``wrap_exchange``, which corrupts a seeded neighbor-buffer slot in
      the traced program itself — persistent corruption for seam-level
      tests.  (Only programs COMPILED while installed are affected;
      jit caches keep earlier traces.)

    Fetch-side faults (``on_fetch``) run inside the ``fetch_guard``
    worker: device loss raises ``DeviceLostError``; a hang blocks until
    ``release_hangs()`` or ``hang_s`` — which the watchdog times out,
    exactly like a real dead mesh."""

    def __init__(self, spec: MeshFaultSpec | None = None, seed: int = 0,
                 enabled: bool = True):
        self.spec = spec or MeshFaultSpec()
        self.seed = int(seed)
        self.enabled = bool(enabled)
        self.stats = {"rounds_dispatched": 0, "halo_nan": 0,
                      "halo_corrupt": 0, "device_loss": 0,
                      "hung_fetches": 0, "links_wrapped": 0}
        self._fired: set = set()
        self._lock = threading.Lock()
        self._hang_release = threading.Event()
        self._pub = None  # host public-slot table, captured by arm()

    def _rng(self, link):
        return np.random.default_rng(
            (self.seed << 32) ^ zlib.crc32(repr(link).encode()))

    def arm(self, graph) -> None:
        """Capture the host-side public-slot table ONCE, before the solve
        loop, so mid-solve poisoning needs no extra device reads."""
        self._pub = np.asarray(graph.pub_idx)

    # -- dispatch-time halo poisoning ---------------------------------------

    def _due(self, kind: str, rounds: tuple, r0: int):
        for i, r in enumerate(rounds):
            key = (kind, i)
            if r0 >= int(r) and key not in self._fired:
                self._fired.add(key)
                return key
        return None

    def before_dispatch(self, state, k: int):
        """Called by the supervisor's segment wrapper with the state about
        to be dispatched for ``k`` rounds; returns the (possibly
        poisoned) state.  Pure host bookkeeping plus at most one async
        ``.at[].set`` — never a device sync."""
        with self._lock:
            r0 = self.stats["rounds_dispatched"]
            self.stats["rounds_dispatched"] = r0 + int(k)
            if not self.enabled:
                return state
            nan_due = self._due("nan", self.spec.nan_halo_rounds, r0)
            bad_due = self._due("corrupt", self.spec.corrupt_halo_rounds, r0)
        if nan_due is not None:
            state = self._poison(state, nan_due, jnp.nan, "halo_nan")
        if bad_due is not None:
            state = self._poison(state, bad_due, 1e30, "halo_corrupt")
        return state

    def _poison(self, state, token, payload, stat: str):
        A = int(state.X.shape[0])
        rng = self._rng(self.spec.link if self.spec.link is not None
                        else token)
        a = int(self.spec.link[0]) % A if self.spec.link is not None \
            else int(rng.integers(A))
        # A PUBLIC pose of agent a, so the next exchange carries the
        # poison to every neighbor as a corrupt halo payload (pose 0
        # when arm() was skipped — still poisons the central metrics).
        p = int(self._pub[a, int(rng.integers(self._pub.shape[1]))]) \
            if self._pub is not None else 0
        with self._lock:
            self.stats[stat] += 1
        return state._replace(X=state.X.at[a, p].set(payload))

    # -- trace-time exchange corruption -------------------------------------

    def wrap_exchange(self, exchange):
        """Wrap an exchange closure (``rbcd._exchange_for`` /
        ``sharded._gather_exchange`` product) so the resolved neighbor
        buffer carries one seeded corrupted slot — trace-level, so every
        round of a program compiled through the wrap is affected."""
        link = self.spec.link if self.spec.link is not None else (0, 1)
        rng = self._rng(link)
        payload = jnp.nan if self.spec.nan_halo_rounds else 1e30
        dst = int(link[1])
        with self._lock:
            self.stats["links_wrapped"] += 1

        def wrapped(Xl):
            Z = exchange(Xl)
            if not self.enabled:
                return Z
            slot = int(rng.integers(max(int(Z.shape[1]), 1)))
            return Z.at[dst % int(Z.shape[0]), slot].set(payload)

        return wrapped

    @contextlib.contextmanager
    def installed(self):
        """Install the trace-level wrap on both exchange seams for the
        scope's duration (see class docstring for the jit-cache caveat)."""
        from . import sharded  # late import: sharded imports this module
        prev_r, prev_s = rbcd._exchange_wrap, sharded._gather_wrap
        rbcd._exchange_wrap = self.wrap_exchange
        sharded._gather_wrap = self.wrap_exchange
        try:
            yield self
        finally:
            rbcd._exchange_wrap = prev_r
            sharded._gather_wrap = prev_s

    # -- fetch-side faults ---------------------------------------------------

    def on_fetch(self, phase: str) -> None:
        """Runs inside the guarded fetch worker (see ``fetch_guard``)."""
        if not self.enabled:
            return
        with self._lock:
            r0 = self.stats["rounds_dispatched"]
            hang = self._due("hang", self.spec.hang_rounds, r0)
            loss = self._due("loss", self.spec.device_loss_rounds, r0)
            if hang is not None:
                self.stats["hung_fetches"] += 1
            if loss is not None:
                self.stats["device_loss"] += 1
        if hang is not None:
            self._hang_release.wait(self.spec.hang_s)
        if loss is not None:
            raise DeviceLostError(
                f"simulated loss of device {self.spec.lost_device} after "
                f"{r0} dispatched rounds (CollectiveFaultInjector)",
                phase=phase, device=self.spec.lost_device)

    def release_hangs(self) -> None:
        """Unblock any in-flight simulated hang (the supervisor calls this
        on fault recovery so abandoned watchdog workers can exit)."""
        self._hang_release.set()


# ---------------------------------------------------------------------------
# Mesh-elastic checkpoints + the rewind supervisor
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Policy knobs for ``solve_rbcd_sharded(resilience=...)``."""

    #: SessionStore root for checkpoints (or pass a prebuilt ``store``).
    checkpoint_dir: str | None = None
    store: SessionStore | None = None
    session_id: str = "sharded-solve"
    #: Checkpoint every Nth clean verdict boundary (1 = every boundary,
    #: i.e. every K rounds — the at-most-K-rounds-lost guarantee).
    checkpoint_every: int = 1
    #: Snapshots retained per session (SessionStore pruning).
    keep: int = 3
    #: Rewind budget; exhausted -> MeshFaultError(kind="rewind_budget").
    max_rewinds: int = 3
    #: Latched verdict anomalies that trigger a rewind (names from
    #: ``REWINDABLE_ANOMALIES``).  Cost spikes and stalls are normal in
    #: GNC schedules, so only divergence anomalies rewind by default.
    rewind_on: tuple = ("non_finite", "grad_explosion")
    #: Watchdog deadline for every blocking fetch; None = no watchdog.
    fetch_deadline_s: float | None = None
    #: On device loss / fetch timeout, resume on the next smaller mesh
    #: that still divides the agent count.
    reshard_on_fault: bool = True
    min_mesh_size: int = 1
    #: Off-thread checkpoint writes (default on): the boundary npz
    #: compression + fsync runs on the store's writer thread,
    #: double-buffered last-writer-wins, so checkpoint overhead hides
    #: under the next K-round device segment.  The device->host gather
    #: stays synchronous at the boundary either way (the snapshot must
    #: capture THIS boundary's state), so the solve's
    #: host_syncs_per_100_rounds is unchanged.  ``recover`` flushes the
    #: writer before reading snapshots back.
    async_checkpoint: bool = True
    #: Deterministic chaos source (tests / chaos arms); None in prod.
    injector: CollectiveFaultInjector | None = None
    #: Whether THIS process persists boundary checkpoints.  Multihost
    #: runs replicate the solve across ranks over one shared store: only
    #: the controller (rank 0) writes — concurrent ranks saving the same
    #: iteration would race the atomic tmp+rename — while every rank
    #: still reads the store on resume/recovery.  Anomaly detection and
    #: rewind bookkeeping are unaffected by this flag.
    checkpoint_writer: bool = True

    def __post_init__(self):
        if self.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1, got "
                             f"{self.checkpoint_every}")
        if self.max_rewinds < 0:
            raise ValueError(f"max_rewinds must be >= 0, got "
                             f"{self.max_rewinds}")
        bad = set(self.rewind_on) - REWINDABLE_ANOMALIES
        if bad:
            raise ValueError(
                f"unknown anomaly names in rewind_on: {sorted(bad)} "
                f"(valid: {sorted(REWINDABLE_ANOMALIES)})")
        if self.store is None and self.checkpoint_dir is None:
            raise ValueError("ResilienceConfig needs a checkpoint_dir "
                             "or a prebuilt SessionStore")
        if self.fetch_deadline_s is not None and self.fetch_deadline_s <= 0:
            raise ValueError(f"fetch_deadline_s must be > 0, got "
                             f"{self.fetch_deadline_s}")
        if self.min_mesh_size < 1:
            raise ValueError(f"min_mesh_size must be >= 1, got "
                             f"{self.min_mesh_size}")

    def resolve_store(self) -> SessionStore:
        if self.store is not None:
            return self.store
        return SessionStore(self.checkpoint_dir, keep=self.keep,
                            async_write=self.async_checkpoint)


def shrink_mesh_size(cur: int, num_robots: int, min_size: int = 1) -> int:
    """The largest mesh size strictly below ``cur`` that still divides the
    agent count (``shard_problem``'s layout contract); ``cur`` when none
    exists — the supervisor then retries on the same mesh."""
    for s in range(int(cur) - 1, max(int(min_size), 1) - 1, -1):
        if num_robots % s == 0:
            return s
    return int(cur)


def checkpoint_arrays(state) -> dict:
    """Gather a (possibly mesh-sharded) ``RBCDState`` into the
    mesh-shape-independent host layout: every field keeps its per-agent
    ``[A, ...]`` leading axis, which the mesh only ever shards in equal
    contiguous blocks — so the SAME arrays re-shard onto any mesh whose
    size divides A.  The gather is the resilience plane's one sanctioned
    transfer and rides a verdict boundary the word fetch just drained."""
    host = {}
    for f in _CHECKPOINT_FIELDS:
        v = getattr(state, f)
        if v is None:
            continue
        # dpgolint: disable=DPG003 -- sanctioned mesh checkpoint gather
        host[f] = _host_fetch(v)
    return host


def _host_state(host: dict) -> "rbcd.RBCDState":
    """A host-array ``RBCDState`` for ``SessionStore.save`` (its
    ``state_to_arrays`` codec is then copy-free); factors recompute on
    restore via ``rbcd.refresh_problem``."""
    return rbcd.RBCDState(
        X=host["X"], weights=host["weights"],
        iteration=host["iteration"], key=host["key"],
        rel_change=host["rel_change"], ready=host["ready"],
        V=host.get("V"), gamma=host["gamma"], alpha=host["alpha"],
        mu=host["mu"], X_init=host.get("X_init"), chol=None, Qbuf=None)


class CheckpointSupervisor:
    """Verdict-boundary checkpointing + rewind bookkeeping for one solve.

    ``boundary_cb`` is handed to ``rbcd.run_rbcd``: at every verdict
    boundary it either checkpoints a clean state or raises
    ``AnomalyRewind`` when the word latched an anomaly the policy
    rewinds on.  ``recover`` maps a caught fault to (new mesh size,
    restored host state, resume iteration, resume weight-update count);
    the caller rebuilds the mesh programs and re-enters the driver.  A
    snapshot whose ``global_index`` does not match the live graph is
    unusable (different problem layout) and recovery degrades to a cold
    restart — fail-open, like ``SessionStore.load_newest`` itself."""

    def __init__(self, cfg: ResilienceConfig, store: SessionStore,
                 graph_host, session_id: str | None = None):
        self.cfg = cfg
        self.store = store
        self.session_id = session_id or cfg.session_id
        self._gidx = np.asarray(graph_host.global_index)
        self.recoveries = 0
        self.checkpoints = 0
        self.cold_restarts = 0
        self.recovery_overhead_s = 0.0
        self.mesh_sizes: list[int] = []
        self.fault_kinds: list[str] = []
        self._boundaries = 0
        self._last_saved_it = -1

    def attach_mesh(self, mesh_size: int) -> None:
        self.mesh_sizes.append(int(mesh_size))

    # -- boundary hook (called from inside the driver loop) ------------------

    def boundary_cb(self, it, nwu, state, word, terminal) -> None:
        anomaly = rbcd.unpack_verdict(word)["anomaly"]
        if anomaly is not None and anomaly in self.cfg.rewind_on:
            # Anomalous terminal words rewind too: a solve that latched
            # non_finite and then "converged" converged on garbage.
            raise AnomalyRewind(anomaly, it, word)
        if terminal:
            return
        self._boundaries += 1
        if (self._boundaries - 1) % self.cfg.checkpoint_every:
            return
        if anomaly is not None or it == self._last_saved_it:
            return  # never checkpoint an anomalous state
        if not self.cfg.checkpoint_writer:
            return  # reader rank: the controller persists for the world
        self.save(state, it, nwu)

    def save(self, state, it: int, nwu: int) -> str:
        host = checkpoint_arrays(state)
        mesh_shape = (self.mesh_sizes[-1],) if self.mesh_sizes else None
        # The gather above is synchronous (the snapshot pins THIS
        # boundary's state); the npz write itself lands off-thread when
        # the store was built with async_write, hiding the compression +
        # fsync under the next K-round segment.
        save = getattr(self.store, "save_async", self.store.save)
        path = save(
            self.session_id, _host_state(host), iteration=int(it),
            num_weight_updates=int(nwu), mesh_shape=mesh_shape,
            global_index=self._gidx)
        self.checkpoints += 1
        self._last_saved_it = int(it)
        run = obs.get_run()
        if run is not None:
            run.counter("mesh_checkpoints_total",
                        "mesh-elastic verdict-boundary checkpoints").inc()
            run.event("mesh_checkpoint", phase="resilience",
                      session=self.session_id, iteration=int(it),
                      mesh_size=mesh_shape[0] if mesh_shape else None)
        return path

    # -- fault recovery ------------------------------------------------------

    def recover(self, exc, mesh_size: int, num_robots: int):
        """Map a caught fault to ``(new_mesh_size, host_state | None,
        start_iteration, start_num_weight_updates)``; ``None`` state
        means cold restart from the initial guess."""
        if isinstance(exc, MeshFaultError) and exc.kind in WORLD_FAULT_KINDS:
            # A dead or diverged PEER PROCESS is not fixable by an
            # in-process rewind: the world itself must shrink.  Propagate
            # to the multihost launcher, which respawns the surviving
            # ranks as a new generation; that generation's supervisor
            # resumes from this same store (solve_rbcd_sharded(resume=)).
            raise exc
        self.recoveries += 1
        kind = exc.kind if isinstance(exc, MeshFaultError) \
            else f"anomaly:{exc.anomaly}"
        self.fault_kinds.append(kind)
        if self.recoveries > self.cfg.max_rewinds:
            raise MeshFaultError(
                f"rewind budget exhausted after {self.cfg.max_rewinds} "
                f"recoveries (last fault: {kind})",
                phase="resilience", kind="rewind_budget") from exc
        new_size = int(mesh_size)
        if isinstance(exc, MeshFaultError) and self.cfg.reshard_on_fault:
            new_size = shrink_mesh_size(mesh_size, num_robots,
                                        self.cfg.min_mesh_size)
        flush = getattr(self.store, "flush", None)
        if flush is not None:
            # Drain the async writer before reading back: the freshest
            # boundary snapshot may still be in the pending slot.
            flush()
        snap = self.store.load_newest(self.session_id)
        usable = snap is not None and (
            snap.global_index is None
            or np.array_equal(np.asarray(snap.global_index), self._gidx))
        run = obs.get_run()
        if run is not None:
            run.counter("mesh_rewinds_total",
                        "supervisor rewinds after mesh faults").inc()
            run.event("mesh_fault", phase="resilience", kind=kind,
                      fault_phase=getattr(exc, "phase", None),
                      device=getattr(exc, "device", None))
            run.event("mesh_rewind", phase="resilience", kind=kind,
                      mesh_from=int(mesh_size), mesh_to=new_size,
                      resume_iteration=int(snap.iteration) if usable else 0,
                      cold=not usable)
        if not usable:
            self.cold_restarts += 1
            return new_size, None, 0, 0
        return (new_size, snap.state, int(snap.iteration),
                int(snap.num_weight_updates))

    def note_overhead(self, seconds: float) -> None:
        self.recovery_overhead_s += float(seconds)

    def finish(self, injector: CollectiveFaultInjector | None) -> dict:
        """The ``RBCDResult.resilience`` summary; also emits the gated
        recovery-overhead metric when telemetry is on."""
        run = obs.get_run()
        if run is not None and self.recoveries:
            run.metric("mesh_recovery_overhead_s", self.recovery_overhead_s,
                       phase="resilience", recoveries=self.recoveries)
        return {
            "recoveries": self.recoveries,
            "checkpoints": self.checkpoints,
            "cold_restarts": self.cold_restarts,
            "recovery_overhead_s": round(self.recovery_overhead_s, 6),
            "mesh_sizes": list(self.mesh_sizes),
            "fault_kinds": list(self.fault_kinds),
            "injector": dict(injector.stats) if injector is not None
            else None,
        }
