"""Distributed (device-mesh) execution layer — see ``sharded.py``.

``resilience.py`` adds the pod-scale fault story: mesh-elastic verdict-
boundary checkpoints, a deterministic collective fault injector, and the
anomaly-triggered rewind supervisor behind
``solve_rbcd_sharded(resilience=...)``.

``multihost.py`` makes the scale real: the same verdict-loop solve
across multiple OS processes joined by ``jax.distributed``, with
verdict-boundary lockstep over the coordination service and actual
``kill -9`` recovery via generation respawn + checkpoint resume.
"""

from .resilience import (WORLD_FAULT_KINDS, CollectiveFaultInjector,
                         DeviceLostError, MeshFaultError, MeshFaultSpec,
                         ResilienceConfig, Watchdog, shrink_mesh_size)
from .sharded import (AXIS, comm_bytes_per_round, gn_tail_sharded,
                      make_mesh, make_multislice_mesh,
                      make_sharded_metrics_body,
                      make_sharded_multi_step, make_sharded_segment,
                      make_sharded_step, shard_problem, solve_rbcd_sharded)

__all__ = ["AXIS", "CollectiveFaultInjector", "DeviceLostError",
           "EXIT_DESYNC", "EXIT_PROCESS_LOST", "MeshFaultError",
           "MeshFaultSpec", "MultihostWorld", "ResilienceConfig",
           "WORLD_FAULT_KINDS", "Watchdog", "WorldConfig",
           "comm_bytes_per_round", "gn_tail_sharded", "launch_world",
           "make_mesh", "make_multislice_mesh",
           "make_sharded_metrics_body", "make_sharded_multi_step",
           "make_sharded_segment", "make_sharded_step", "shard_problem",
           "shrink_mesh_size", "shrink_world", "solve_rbcd_sharded"]

#: Lazily re-exported from ``.multihost``: importing it eagerly would
#: re-execute the module when invoked as ``python -m dpgo_tpu.parallel
#: .multihost`` (the worker/launcher CLI), tripping runpy's
#: found-in-sys.modules warning in every worker log.
_MULTIHOST_EXPORTS = frozenset({
    "EXIT_DESYNC", "EXIT_PROCESS_LOST", "MultihostWorld", "WorldConfig",
    "launch_world", "shrink_world"})


def __getattr__(name):
    if name in _MULTIHOST_EXPORTS:
        from . import multihost

        return getattr(multihost, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
