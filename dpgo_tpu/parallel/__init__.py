"""Distributed (device-mesh) execution layer — see ``sharded.py``.

``resilience.py`` adds the pod-scale fault story: mesh-elastic verdict-
boundary checkpoints, a deterministic collective fault injector, and the
anomaly-triggered rewind supervisor behind
``solve_rbcd_sharded(resilience=...)``.
"""

from .resilience import (CollectiveFaultInjector, DeviceLostError,
                         MeshFaultError, MeshFaultSpec, ResilienceConfig,
                         Watchdog, shrink_mesh_size)
from .sharded import (AXIS, comm_bytes_per_round, gn_tail_sharded,
                      make_mesh, make_multislice_mesh,
                      make_sharded_metrics_body,
                      make_sharded_multi_step, make_sharded_segment,
                      make_sharded_step, shard_problem, solve_rbcd_sharded)

__all__ = ["AXIS", "CollectiveFaultInjector", "DeviceLostError",
           "MeshFaultError", "MeshFaultSpec", "ResilienceConfig",
           "Watchdog", "comm_bytes_per_round", "gn_tail_sharded",
           "make_mesh", "make_multislice_mesh",
           "make_sharded_metrics_body", "make_sharded_multi_step",
           "make_sharded_segment", "make_sharded_step", "shard_problem",
           "shrink_mesh_size", "solve_rbcd_sharded"]
