"""Distributed (device-mesh) execution layer — see ``sharded.py``."""

from .sharded import (AXIS, comm_bytes_per_round, gn_tail_sharded,
                      make_mesh, make_multislice_mesh,
                      make_sharded_metrics_body,
                      make_sharded_multi_step, make_sharded_segment,
                      make_sharded_step, shard_problem, solve_rbcd_sharded)

__all__ = ["AXIS", "comm_bytes_per_round", "gn_tail_sharded", "make_mesh",
           "make_multislice_mesh", "make_sharded_metrics_body",
           "make_sharded_multi_step", "make_sharded_segment",
           "make_sharded_step", "shard_problem",
           "solve_rbcd_sharded"]
