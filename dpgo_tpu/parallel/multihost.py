"""True multi-host execution: one solve, N OS processes, kill -9 recovery.

This module makes the "distributed" in distributed PGO real: the
verdict-loop solve (``solve_rbcd_sharded``) runs across multiple
*processes* joined into one world by ``jax.distributed``, and a worker
that dies — actually dies, ``kill -9``, not a raised exception — is
detected, the world shrinks, and the survivors resume from the last v2
checkpoint.  Three layers:

* **World membership** (``MultihostWorld``) — ``jax.distributed
  .initialize`` joins each rank to the coordination service (a gRPC
  control plane owned by rank 0).  The service's key-value store and
  named barriers are the cross-process primitives; they work on every
  backend, including CPU.

* **Lockstep compute** — XLA refuses cross-process computations on the
  CPU backend (``INVALID_ARGUMENT: Multiprocess computations aren't
  implemented on the CPU backend``, probed, both pmap and jit+shard_map),
  so each rank executes the identical deterministic sharded solve on its
  own local mesh and the world proves lockstep where the driver already
  surfaces to the host: the ONE int32 verdict word per K rounds.  At
  each verdict boundary every rank publishes ``iteration:word`` to the
  KV store, crosses a named barrier, and checks its word against the
  controller's (rank 0).  No new device syncs — the word is already on
  the host at a boundary, so ``host_syncs_per_100_rounds == 100/K``
  holds unchanged.  On a TPU pod the same entry points would place one
  global mesh across the processes; the control plane is identical.

* **Failure recovery** — a SIGKILLed peer never reaches its barrier, so
  the survivors' ``wait_at_barrier`` raises ``DEADLINE_EXCEEDED``,
  surfaced as ``MeshFaultError(phase="verdict_sync",
  kind="process_lost")``.  ``CheckpointSupervisor.recover`` re-raises
  world faults (a dead peer cannot be rewound away in-process), the
  worker writes a structured fault record and exits
  ``EXIT_PROCESS_LOST``, and the generation launcher (``launch_world``)
  respawns the survivors as generation g+1 on a shrunken world
  (``shrink_world``) with ``solve_rbcd_sharded(resume=True)`` — the
  supervisor restores the newest mesh-shape-agnostic v2 checkpoint from
  the shared ``SessionStore`` and the solve continues at the exact
  absolute round index.  Only rank 0 persists checkpoints
  (``ResilienceConfig.checkpoint_writer``); every rank reads them.

Barrier timeouts are two-tier: the first boundary lands after each rank
compiles its sharded programs (minutes of skew on a contended box), so
it gets ``first_barrier_timeout_s``; steady-state boundaries are
deterministic lockstep and get the tight ``barrier_timeout_s``, which is
also the fault-detection latency.

CLI (also the README quickstart)::

    python -m dpgo_tpu.parallel.multihost --procs 2
    python -m dpgo_tpu.parallel.multihost --procs 2 --kill-rank 1 \\
        --kill-at-boundary 3   # kill -9 a worker mid-solve, watch recovery
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

from .. import obs
from ..comms.protocol import ORIGIN_FLEET_PARENT, mh_rank_actor
from ..obs.trace import emit_span
from .resilience import MeshFaultError, shrink_mesh_size

#: Worker exit codes the launcher classifies (anything else is a crash).
EXIT_PROCESS_LOST = 17  # a peer died: barrier timed out at a boundary
EXIT_DESYNC = 18        # lockstep broken: verdict words diverged


def shrink_world(cur: int, num_robots: int, min_size: int = 1) -> int:
    """The next smaller world size after losing a process: the largest
    count strictly below ``cur`` that still divides the agent count —
    the same divisibility planning as a mesh shrink, because each rank's
    local mesh must go on dividing ``num_robots``."""
    return shrink_mesh_size(cur, num_robots, min_size)


# ---------------------------------------------------------------------------
# World membership + verdict lockstep
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class WorldConfig:
    """One rank's view of the world (`--worker` CLI args, test kwargs)."""

    coordinator: str
    world_size: int
    rank: int
    generation: int = 0
    #: Steady-state barrier deadline == fault-detection latency.
    barrier_timeout_s: float = 20.0
    #: First-boundary deadline: absorbs cross-rank XLA compile skew.
    first_barrier_timeout_s: float = 600.0
    init_timeout_s: float = 300.0

    def __post_init__(self):
        if self.world_size < 1:
            raise ValueError(f"world_size must be >= 1, got "
                             f"{self.world_size}")
        if not 0 <= self.rank < self.world_size:
            raise ValueError(f"rank {self.rank} outside world of "
                             f"{self.world_size}")
        if self.barrier_timeout_s <= 0 or self.first_barrier_timeout_s <= 0:
            raise ValueError("barrier timeouts must be > 0")


def _coordination_client():
    """The live process's coordination-service handle (requires a prior
    ``jax.distributed.initialize``)."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:  # pragma: no cover - misuse guard
        raise RuntimeError("jax.distributed is not initialized; "
                           "call MultihostWorld.join() or initialize first")
    return client


class MultihostWorld:
    """Verdict-boundary lockstep across the ranks of one generation.

    ``boundary_cb`` plugs into ``solve_rbcd_sharded(boundary_cb=...)``:
    at every verdict boundary it publishes this rank's ``iteration:word``
    to the coordination-service KV store, crosses a generation-scoped
    named barrier, and cross-checks against the controller's word.  A
    barrier deadline means a peer never arrived —
    ``MeshFaultError(kind="process_lost")``; a word mismatch means
    replicated lockstep broke — ``MeshFaultError(kind="desync")``.

    ``client`` is injectable (tests drive the protocol with a fake);
    production ranks call :meth:`join` which initializes
    ``jax.distributed`` and grabs the real client.
    """

    def __init__(self, cfg: WorldConfig, client=None):
        self.cfg = cfg
        self.rank = cfg.rank
        self.world_size = cfg.world_size
        self.generation = cfg.generation
        self.client = client
        self.boundaries = 0  # completed lockstep syncs
        self.desync_checks = 0

    @classmethod
    def join(cls, cfg: WorldConfig) -> "MultihostWorld":
        """Initialize ``jax.distributed`` for this rank and return the
        joined world.  Must run before the first JAX computation."""
        import jax

        jax.distributed.initialize(
            coordinator_address=cfg.coordinator,
            num_processes=cfg.world_size,
            process_id=cfg.rank,
            initialization_timeout=int(cfg.init_timeout_s))
        return cls(cfg, client=_coordination_client())

    # -- key naming ---------------------------------------------------------

    def _word_key(self, seq: int, rank: int) -> str:
        return f"dpgo/mh/g{self.generation}/s{seq}/r{rank}"

    def _stamp_key(self, seq: int, rank: int) -> str:
        # Telemetry-only clock stamps ride their own key family: with
        # telemetry off these keys are never written and the KV/barrier
        # traffic is byte-identical to the uninstrumented protocol.
        return f"dpgo/mh/g{self.generation}/c{seq}/r{rank}"

    def _barrier_id(self, seq: int) -> str:
        return f"dpgo/mh/g{self.generation}/b{seq}"

    # -- the lockstep protocol ----------------------------------------------

    def verdict_sync(self, it: int, word: int) -> None:
        """One boundary's cross-process agreement: publish, barrier,
        cross-check.  Raises the structured world faults above."""
        if self.world_size == 1:
            self.boundaries += 1
            return
        seq = self.boundaries
        payload = f"{int(it)}:{int(word)}"
        timeout_s = self.cfg.first_barrier_timeout_s if seq == 0 \
            else self.cfg.barrier_timeout_s
        run = obs.get_run()
        actor = mh_rank_actor(self.rank) if run is not None else None
        if run is not None:
            # The verdict_publish event is this rank's own durable copy
            # of what it pushed to the KV store — the launcher's
            # postmortem harvester decodes a SIGKILLed rank's last word
            # from here.  The clock stamp key (c-family) pairs the
            # barrier round-trip into clock_sample samples below.
            run.event("verdict_publish", phase="comms", robot=actor,
                      seq_boundary=seq, iteration=int(it),
                      word=int(word),
                      key=self._word_key(seq, self.rank))
            self.client.key_value_set(
                self._stamp_key(seq, self.rank),
                f"{time.monotonic()}:{time.time()}")
        self.client.key_value_set(self._word_key(seq, self.rank), payload)
        t0_mono, t0_wall = time.monotonic(), time.time()
        try:
            self.client.wait_at_barrier(self._barrier_id(seq),
                                        int(timeout_s * 1000))
        except Exception as e:
            raise MeshFaultError(
                f"rank {self.rank}: peer lost at verdict boundary {seq} "
                f"(iteration {it}): barrier {self._barrier_id(seq)!r} "
                f"timed out after {timeout_s:g}s",
                phase="verdict_sync", kind="process_lost") from e
        if run is not None:
            emit_span(run, "barrier_wait", t0_mono, t0_wall,
                      time.monotonic() - t0_mono, phase="comms",
                      robot=actor, seq_boundary=seq,
                      generation=self.generation)
            # Post-barrier every telemetry-on peer's stamp exists: the
            # controller samples every rank's clock and every rank
            # samples the controller's — bidirectional pairs for the
            # merged-timeline offset solve.  Fail-open (short timeout)
            # so a telemetry-off peer can't stall a telemetry-on one.
            peers = [r for r in range(self.world_size) if r != self.rank] \
                if self.rank == 0 else [0]
            for r in peers:
                try:
                    raw = self.client.blocking_key_value_get(
                        self._stamp_key(seq, r), 2000)
                    if isinstance(raw, bytes):
                        raw = raw.decode("utf-8", "replace")
                    mono_s, wall_s = raw.split(":")
                    run.event("clock_sample", phase="comms",
                              src=mh_rank_actor(r), dst=actor,
                              channel="coord_kv", kind="barrier",
                              seq_boundary=seq,
                              t_send_mono=float(mono_s),
                              t_send_wall=float(wall_s))
                except Exception:
                    pass
        if self.rank != 0:
            # The barrier just proved rank 0 published; the get is a
            # KV read of an existing key, not a second wait.
            ref = self.client.blocking_key_value_get(
                self._word_key(seq, 0), int(timeout_s * 1000))
            if isinstance(ref, bytes):
                ref = ref.decode("utf-8", "replace")
            self.desync_checks += 1
            if ref != payload:
                raise MeshFaultError(
                    f"rank {self.rank}: verdict desync at boundary {seq}: "
                    f"controller says {ref!r}, this rank computed "
                    f"{payload!r} — replicated lockstep broken",
                    phase="verdict_sync", kind="desync")
        self.boundaries += 1
        run = obs.get_run()
        if run is not None:
            run.counter("multihost_boundary_syncs_total",
                        "verdict-boundary lockstep syncs").inc()

    def boundary_cb(self, it, nwu, state, word, terminal) -> None:
        """The ``solve_rbcd_sharded(boundary_cb=...)`` adapter; ``state``
        stays on device — lockstep rides the already-fetched word."""
        self.verdict_sync(int(it), int(word))


# ---------------------------------------------------------------------------
# Worker: one rank of one generation (its own OS process)
# ---------------------------------------------------------------------------

def _write_json(path, record: dict) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(p.suffix + ".tmp")
    tmp.write_text(json.dumps(record, indent=2, sort_keys=True))
    os.replace(tmp, p)


def _read_json(path) -> dict | None:
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None


def _solve_problem(args):
    """The deterministic demo problem every rank rebuilds identically
    (seeded synthetic odometry chain + loop closures)."""
    from ..utils.synthetic import make_measurements

    rng = np.random.default_rng(args.seed)
    meas, _ = make_measurements(rng, n=args.n, d=3, num_lc=args.num_lc,
                                rot_noise=args.noise,
                                trans_noise=args.noise)
    return meas


def run_worker(args) -> int:
    """``--worker`` entry: join the world, run the lockstep solve, write
    a result (or structured fault) record, exit with a classifiable rc.

    With ``--telemetry-dir`` (threaded by the launcher) the whole worker
    runs inside its own generation-scoped ``TelemetryRun`` — the per-rank
    stream the launcher harvests and merges after the generation ends,
    SIGKILL or not (events.jsonl is flushed per line; the harvest is
    tail-tolerant)."""
    import jax

    # Mirror tests/conftest.py: the environment's sitecustomize may
    # register a hardware tunnel; workers are pinned to the CPU backend
    # the launcher sized via XLA_FLAGS.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    boot = (time.monotonic(), time.time())
    if getattr(args, "telemetry_dir", ""):
        with obs.run_scope(args.telemetry_dir):
            return _worker_main(args, boot)
    return _worker_main(args, boot)


def _worker_main(args, boot) -> int:
    import jax

    cfg = WorldConfig(coordinator=args.coordinator, world_size=args.world,
                      rank=args.rank, generation=args.generation,
                      barrier_timeout_s=args.barrier_timeout,
                      first_barrier_timeout_s=args.first_barrier_timeout,
                      init_timeout_s=args.init_timeout)
    world = MultihostWorld.join(cfg)

    run = obs.get_run()
    if run is not None:
        actor = mh_rank_actor(world.rank)
        run.set_fingerprint(plane="multihost", rank=world.rank,
                            generation=world.generation,
                            world_size=world.world_size)
        # Pair the launcher's spawn stamp with this (receive-side) event:
        # the forward leg of the launcher<->rank clock sample; the
        # harvester emits the reverse leg off the result record's stamp.
        if getattr(args, "launch_stamp", ""):
            try:
                mono_s, wall_s = args.launch_stamp.split(",")
                run.event("clock_sample", phase="comms",
                          src=ORIGIN_FLEET_PARENT, dst=actor,
                          channel="spawn", kind="launch",
                          t_send_mono=float(mono_s),
                          t_send_wall=float(wall_s))
            except (ValueError, IndexError):
                pass
        # The boot span anchors this stream's home to the rank's actor
        # id even for a 1-rank world that never crosses a barrier.
        emit_span(run, "worker_boot", boot[0], boot[1],
                  time.monotonic() - boot[0], phase="comms", robot=actor,
                  rank=world.rank, generation=world.generation)

    from ..config import AgentParams
    from ..models import rbcd
    from ..serve.session import SessionStore
    from .resilience import ResilienceConfig
    from .sharded import make_mesh, solve_rbcd_sharded

    meas = _solve_problem(args)
    params = AgentParams(d=3, r=5, num_robots=args.robots,
                        rel_change_tol=0.0)
    rcfg = ResilienceConfig(
        checkpoint_dir=args.checkpoint_dir, session_id=args.session,
        checkpoint_every=1, keep=4,
        checkpoint_writer=(world.rank == 0))

    resume = args.generation > 0
    resume_iteration = 0
    if resume:
        snap = SessionStore(args.checkpoint_dir).load_newest(args.session)
        if snap is not None:
            resume_iteration = int(snap.iteration)

    chaos_cb = world.boundary_cb
    if args.kill_at_boundary >= 0 and args.kill_rank == world.rank \
            and args.generation == 0:
        def chaos_cb(it, nwu, state, word, terminal):
            if world.boundaries == args.kill_at_boundary:
                sys.stdout.flush()
                # A REAL kill -9 of this worker, mid-solve: uncatchable,
                # no cleanup, no flush — exactly what the survivors must
                # detect and recover from.
                os.kill(os.getpid(), signal.SIGKILL)
            world.boundary_cb(it, nwu, state, word, terminal)

    # Count driver-loop host syncs through the sanctioned seam, the same
    # shim as tests/test_mesh_resilience.py: the lockstep must not add
    # any (it rides words already fetched).  The coordination-rate metric
    # counts ONLY the packed verdict words (the scalar readbacks) — the
    # telemetry plane's recurring lazy-history fetch is the single-host
    # telemetry cost the solver's own gauge already accounts for, so
    # ``host_syncs_per_100_rounds`` stays pinned at 100/K whether the
    # rank runs instrumented (harvested) or dark.
    fetches = [0, 0]  # [total, scalar verdict words]
    orig_fetch = rbcd._host_fetch

    def counting_fetch(x):
        fetches[0] += 1
        if getattr(x, "ndim", None) == 0:
            fetches[1] += 1
        return orig_fetch(x)

    # The rank's mesh spans its LOCAL devices only.  With jax.distributed
    # active, ``jax.devices()`` is the GLOBAL list — a mesh slicing it
    # would hand every rank but 0 remote devices, and a device_put onto a
    # non-fully-addressable sharding routes through a cross-process
    # psum (multihost_utils.assert_equal) the CPU backend refuses.  Each
    # rank hosting the replicated solve on its own mesh is the lockstep
    # design; on a TPU pod the same call site would place one global mesh.
    mesh = make_mesh(args.mesh_size, devices=jax.local_devices())

    t0 = time.monotonic()
    rbcd._host_fetch = counting_fetch
    try:
        res = solve_rbcd_sharded(
            meas, args.robots, mesh=mesh,
            params=params, max_iters=args.rounds,
            verdict_every=args.verdict_every,
            eval_every=args.verdict_every, grad_norm_tol=0.0,
            resilience=rcfg, resume=resume, boundary_cb=chaos_cb)
    except MeshFaultError as e:
        _write_json(args.out, {
            "ok": False, "kind": e.kind, "phase": e.phase,
            "rank": world.rank, "generation": world.generation,
            "world_size": world.world_size,
            "boundaries": world.boundaries, "error": str(e),
            "t_record_mono": time.monotonic(),
            "t_record_wall": time.time()})
        if run is not None and getattr(args, "telemetry_dir", ""):
            # os._exit skips the run_scope teardown; finalize this
            # rank's run artifacts so the harvest sees a closed stream.
            try:
                obs.end_run()
            except Exception:
                pass
        sys.stdout.flush()
        sys.stderr.flush()
        # A peer is gone: the coordination service cannot complete a
        # clean shutdown handshake, so the atexit hook would hang on the
        # dead rank.  Exit hard with the classifiable code instead.
        os._exit(EXIT_PROCESS_LOST if e.kind == "process_lost"
                 else EXIT_DESYNC)
    finally:
        rbcd._host_fetch = orig_fetch

    rounds = args.rounds - resume_iteration
    # The sync-rate metric counts the scalar verdict-word fetches only
    # (one per K-round boundary; the terminal epilogue and the
    # telemetry-on lazy-history legs are pytree transfers, so they never
    # land in the word tally) — rbcd._emit_sync_rate's convention for
    # the raw total still governs the solver's own gauge.
    loop_fetches = fetches[1]
    _write_json(args.out, {
        "ok": True, "rank": world.rank, "generation": world.generation,
        "world_size": world.world_size, "mesh_size": args.mesh_size,
        "boundaries": world.boundaries,
        "desync_checks": world.desync_checks,
        "resumed": resume, "resume_iteration": resume_iteration,
        "iterations": int(res.iterations),
        "terminated_by": res.terminated_by,
        "final_cost": float(res.cost_history[-1]),
        "cost_history": [float(c) for c in res.cost_history],
        "grad_norm_history": [float(g) for g in res.grad_norm_history],
        "recovered": bool(res.recovered),
        "resilience": res.resilience,
        "host_fetches": int(fetches[0]),
        "rounds_executed": int(rounds),
        "host_syncs_per_100_rounds":
            100.0 * loop_fetches / max(rounds, 1),
        "wall_s": round(time.monotonic() - t0, 3),
        "t_record_mono": time.monotonic(),
        "t_record_wall": time.time()})
    return 0


# ---------------------------------------------------------------------------
# Launcher: generations of worker processes
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _classify(rc: int) -> str:
    if rc == 0:
        return "ok"
    if rc == EXIT_PROCESS_LOST:
        return "process_lost"
    if rc == EXIT_DESYNC:
        return "desync"
    if rc < 0:
        try:
            return f"signal:{signal.Signals(-rc).name}"
        except ValueError:
            return f"signal:{-rc}"
    return f"crash:{rc}"


def launch_world(procs: int = 2, *, robots: int = 8, mesh_size: int = 2,
                 n: int = 64, num_lc: int = 12, noise: float = 0.05,
                 seed: int = 7, rounds: int = 24, verdict_every: int = 4,
                 workdir: str | None = None,
                 barrier_timeout_s: float = 20.0,
                 first_barrier_timeout_s: float = 600.0,
                 init_timeout_s: float = 300.0,
                 kill_rank: int | None = None,
                 kill_at_boundary: int | None = None,
                 kill_after_s: float | None = None,
                 max_generations: int = 3,
                 worker_timeout_s: float = 1800.0,
                 session: str = "multihost-solve",
                 telemetry_dir: str | None = None) -> dict:
    """Run one multihost solve to completion, across generations.

    Spawns ``procs`` worker processes joined by ``jax.distributed``; if
    a generation loses a process (the two chaos levers: a worker
    SIGKILLs itself at a named verdict boundary, or the launcher
    ``kill -9``\\ s a rank after a wall-clock delay), the surviving ranks
    exit with structured fault records and the next generation respawns
    them on the shrunken world with ``resume=True`` — the supervisor
    restores the newest v2 checkpoint from the shared store and the
    solve continues.  Returns the final generation's controller record
    plus the per-generation fault ledger.

    With ``telemetry_dir`` the launcher opens its own run there (unless
    one is already ambient), hands every rank a generation-scoped run
    directory, harvests every rank's stream after each generation
    (``generation_postmortem`` + ``process_lost`` forensics — the
    SIGKILLed rank's tail survives it), and merges launcher + all ranks
    into ONE validated Chrome trace (``summary["telemetry"]``)."""
    if robots % mesh_size != 0:
        raise ValueError(f"mesh_size {mesh_size} must divide robots "
                         f"{robots}")
    workdir = Path(workdir or tempfile.mkdtemp(prefix="dpgo-multihost-"))
    workdir.mkdir(parents=True, exist_ok=True)
    checkpoint_dir = workdir / "checkpoints"
    repo_root = Path(__file__).resolve().parents[2]

    from ..obs import fleetobs

    tel_root = Path(telemetry_dir).resolve() if telemetry_dir else None
    if tel_root is not None:
        tel_root.mkdir(parents=True, exist_ok=True)
    rank_dirs_all: list = []   # every generation's per-rank run dirs
    summary: dict | None = None

    with contextlib.ExitStack() as stack:
        run = obs.get_run()
        launcher_dir = None
        if tel_root is not None and run is None:
            launcher_dir = tel_root / "launcher"
            run = stack.enter_context(obs.run_scope(str(launcher_dir)))
        elif run is not None:
            launcher_dir = Path(run.run_dir)
        if run is not None:
            run.set_fingerprint(plane="multihost", role="launcher",
                                procs=int(procs))

        world = int(procs)
        generations = []
        gen = 0
        while True:
            port = _free_port()
            outs, log_files, procs_list = [], [], []
            gen_rank_dirs: dict = {}
            if run is not None:
                run.event("generation_start", phase="fleet",
                          generation=gen, world_size=world)
            for rank in range(world):
                out = workdir / f"g{gen}-r{rank}.json"
                log = workdir / f"g{gen}-r{rank}.log"
                outs.append(out)
                cmd = [sys.executable, "-m",
                       "dpgo_tpu.parallel.multihost",
                       "--worker", "--rank", str(rank),
                       "--world", str(world),
                       "--coordinator", f"127.0.0.1:{port}",
                       "--generation", str(gen),
                       "--robots", str(robots),
                       "--mesh-size", str(mesh_size),
                       "--n", str(n), "--num-lc", str(num_lc),
                       "--noise", str(noise), "--seed", str(seed),
                       "--rounds", str(rounds),
                       "--verdict-every", str(verdict_every),
                       "--checkpoint-dir", str(checkpoint_dir),
                       "--session", session, "--out", str(out),
                       "--barrier-timeout", str(barrier_timeout_s),
                       "--first-barrier-timeout",
                       str(first_barrier_timeout_s),
                       "--init-timeout", str(init_timeout_s)]
                if gen == 0 and kill_rank is not None \
                        and kill_at_boundary is not None:
                    cmd += ["--kill-rank", str(kill_rank),
                            "--kill-at-boundary", str(kill_at_boundary)]
                if tel_root is not None:
                    rank_dir = fleetobs.generation_run_dir(
                        tel_root, gen, rank)
                    gen_rank_dirs[rank] = rank_dir
                    # Stamped immediately before the spawn: the forward
                    # leg of the launcher<->rank clock pairing.
                    cmd += ["--telemetry-dir", rank_dir,
                            "--launch-stamp",
                            f"{time.monotonic()},{time.time()}"]
                env = dict(os.environ)
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + " --xla_force_host_platform_device_count="
                    + str(mesh_size)
                ).strip()
                env["PYTHONPATH"] = str(repo_root) + (
                    os.pathsep + env["PYTHONPATH"]
                    if env.get("PYTHONPATH") else "")
                lf = open(log, "w")
                log_files.append(lf)
                procs_list.append(subprocess.Popen(
                    cmd, env=env, stdout=lf, stderr=subprocess.STDOUT,
                    cwd=str(repo_root)))

            if gen == 0 and kill_rank is not None \
                    and kill_after_s is not None \
                    and kill_at_boundary is None:
                time.sleep(kill_after_s)
                if procs_list[kill_rank].poll() is None:
                    procs_list[kill_rank].send_signal(signal.SIGKILL)

            deadline = time.monotonic() + worker_timeout_s
            rcs = []
            for p in procs_list:
                try:
                    p.wait(timeout=max(deadline - time.monotonic(), 1.0))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                rcs.append(p.returncode)
            for lf in log_files:
                lf.close()

            records = [_read_json(o) for o in outs]
            faults = [r for r in records
                      if r is not None and not r.get("ok", False)]
            outcomes = [_classify(rc) for rc in rcs]
            gen_entry = {"generation": gen, "world_size": world,
                         "rcs": list(rcs), "outcomes": outcomes,
                         "faults": faults}
            generations.append(gen_entry)
            if run is not None:
                run.event("generation_end", phase="fleet",
                          generation=gen, world_size=world,
                          outcomes=outcomes)
                # Fail-open forensics: every rank's stream harvested,
                # the victim's tail + last published verdict included.
                fleetobs.harvest_generation(
                    run, gen, gen_rank_dirs,
                    outcomes={r: outcomes[r] for r in gen_rank_dirs},
                    records={r: records[r] for r in gen_rank_dirs
                             if r < len(records)},
                    plane="multihost", lost_actor=mh_rank_actor)
                rank_dirs_all.extend(gen_rank_dirs.values())

            if all(rc == 0 for rc in rcs):
                result = records[0]
                if result is None or not result.get("ok"):
                    raise RuntimeError(
                        f"generation {gen}: all ranks exited 0 but the "
                        f"controller record at {outs[0]} is "
                        f"missing/faulted")
                summary = {"result": result, "generations": generations,
                           "world_sizes": [g["world_size"]
                                           for g in generations],
                           "recovered": gen > 0,
                           "workdir": str(workdir)}
                break

            if gen + 1 >= max_generations:
                raise RuntimeError(
                    f"multihost solve failed after {gen + 1} "
                    f"generations: "
                    f"{[g['outcomes'] for g in generations]}")
            world = shrink_world(world, robots) if world > 1 else world
            gen += 1

    # The launcher run (if this call opened one) is finalized here; the
    # merged generation timeline spans launcher + every rank of every
    # generation — the kill shows up as a process_lost instant on the
    # victim's own track.
    if tel_root is not None and launcher_dir is not None:
        try:
            trace_info = fleetobs.write_fleet_trace(
                [str(launcher_dir)] + [str(d) for d in rank_dirs_all],
                str(tel_root / "fleet_trace.json"))
            summary["telemetry"] = {"dir": str(tel_root), **trace_info}
        except Exception as e:
            summary["telemetry"] = {"dir": str(tel_root),
                                    "error": f"{type(e).__name__}: {e}"}
    return summary


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dpgo_tpu.parallel.multihost",
        description="Multi-process mesh solve with kill -9 recovery",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog=(
            "worker exit codes (the launcher classifies these per rank "
            "in the final outcome line):\n"
            f"  {EXIT_PROCESS_LOST}  process_lost: a peer died — the "
            "verdict-boundary barrier timed out\n"
            f"  {EXIT_DESYNC}  desync: replicated lockstep broke — "
            "verdict words diverged from rank 0\n"
            "  -N  signal:<name>: the worker was killed by signal N "
            "(e.g. the kill -9 chaos levers)\n\n"
            "on success the launcher prints ONE machine-readable JSON "
            "line: world sizes, recovery,\nper-rank outcome "
            "classifications per generation, solve result fields, and "
            "(with\n--telemetry-dir) the merged-trace location."))
    p.add_argument("--procs", type=int, default=2,
                   help="world size (worker processes) for generation 0")
    p.add_argument("--robots", type=int, default=8)
    p.add_argument("--mesh-size", type=int, default=2,
                   help="local device-mesh size per rank (virtual CPU "
                        "devices; must divide --robots)")
    p.add_argument("--n", type=int, default=64,
                   help="poses in the synthetic demo problem")
    p.add_argument("--num-lc", type=int, default=12)
    p.add_argument("--noise", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--rounds", type=int, default=24)
    p.add_argument("--verdict-every", type=int, default=4)
    p.add_argument("--workdir", default=None)
    p.add_argument("--telemetry-dir", default="",
                   help="enable fleet telemetry rooted here: launcher "
                        "run + per-rank generation-scoped runs, "
                        "post-generation harvest, and ONE merged Chrome "
                        "trace at <dir>/fleet_trace.json (in worker "
                        "mode: this rank's own run directory)")
    p.add_argument("--session", default="multihost-solve")
    p.add_argument("--barrier-timeout", type=float, default=20.0)
    p.add_argument("--first-barrier-timeout", type=float, default=600.0)
    p.add_argument("--init-timeout", type=float, default=300.0)
    p.add_argument("--max-generations", type=int, default=3)
    p.add_argument("--kill-rank", type=int, default=-1,
                   help="chaos: the rank to kill -9 in generation 0")
    p.add_argument("--kill-at-boundary", type=int, default=-1,
                   help="chaos: the victim SIGKILLs itself at this "
                        "verdict boundary (deterministic)")
    p.add_argument("--kill-after", type=float, default=None,
                   help="chaos: the launcher kill -9s --kill-rank after "
                        "this many seconds (wall-clock)")
    # Hidden worker-mode flags (the launcher spawns these).
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--rank", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--world", type=int, default=1, help=argparse.SUPPRESS)
    p.add_argument("--coordinator", default="", help=argparse.SUPPRESS)
    p.add_argument("--generation", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--checkpoint-dir", default="", help=argparse.SUPPRESS)
    p.add_argument("--out", default="", help=argparse.SUPPRESS)
    p.add_argument("--launch-stamp", default="", help=argparse.SUPPRESS)
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.worker:
        return run_worker(args)
    kill_rank = args.kill_rank if args.kill_rank >= 0 else None
    kill_at = args.kill_at_boundary if args.kill_at_boundary >= 0 else None
    summary = launch_world(
        args.procs, robots=args.robots, mesh_size=args.mesh_size,
        n=args.n, num_lc=args.num_lc, noise=args.noise, seed=args.seed,
        rounds=args.rounds, verdict_every=args.verdict_every,
        workdir=args.workdir, barrier_timeout_s=args.barrier_timeout,
        first_barrier_timeout_s=args.first_barrier_timeout,
        init_timeout_s=args.init_timeout,
        kill_rank=kill_rank, kill_at_boundary=kill_at,
        kill_after_s=args.kill_after,
        max_generations=args.max_generations, session=args.session,
        telemetry_dir=args.telemetry_dir or None)
    res = summary["result"]
    # ONE machine-readable line (json.loads-able whether callers read
    # the whole file or the last line) — the scripting/CI contract.
    outcome = {
        "world_sizes": summary["world_sizes"],
        "recovered": summary["recovered"],
        "generations": [{"generation": g["generation"],
                         "world_size": g["world_size"],
                         "outcomes": g["outcomes"]}
                        for g in summary["generations"]],
        "resume_iteration": res["resume_iteration"],
        "final_cost": res["final_cost"],
        "iterations": res["iterations"],
        "host_syncs_per_100_rounds": res["host_syncs_per_100_rounds"],
        "boundaries": res["boundaries"],
        "workdir": summary["workdir"]}
    if "telemetry" in summary:
        tel = summary["telemetry"]
        outcome["telemetry"] = {
            k: tel[k] for k in ("dir", "trace", "streams", "spans",
                                "flows", "pids", "error") if k in tel}
    print(json.dumps(outcome, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
