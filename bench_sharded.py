"""Pod-scale sharded bench (ISSUE 11): weak scaling of the sharded
verdict loop, halo-overlap A/B, comm-model validation, the sharded GN-CG
tail parity arm, and the large-scale functional solve.

Arms (each skippable):

* **weak scaling** — poses/s of the device-resident sharded verdict loop
  (``solve_rbcd_sharded(verdict_every=K)``'s driver machinery) at a
  constant per-device problem size as the mesh grows 1 -> N devices.
  Host syncs during the timed trials are counted through the sanctioned
  ``rbcd._host_fetch`` seam, exactly like ``bench.py``.
* **overlap A/B** — the halo-pipelined fused round loop vs the lockstep
  one at the largest arm; ``efficiency = 1 - t_overlap/t_lockstep``.
* **comm model** — modeled per-device interconnect bytes per round
  (``comm_bytes_per_round``) vs the bytes moved by the collectives XLA
  actually compiled (parsed from partitioned HLO).
* **GN tail** — the sharded device-resident Gauss-Newton-CG tail vs the
  host-f64 ``refine.gn_tail`` from the same handoff iterate on the noisy
  probe (final-cost parity, transfer count).
* **scale test** — a synthetic large solve (the 1M-pose / 256-agent
  configuration) driven end to end through the sharded verdict loop.
* **resilience (chaos)** — a device is killed mid-solve under the
  ``parallel.resilience`` supervisor: the solve must recover from the
  last verdict-boundary checkpoint on a halved mesh and land within
  rtol of the fault-free reference (``tools/check_bench_floor.py``
  enforces recoveries >= 1 and bounded recovery overhead).

Runs FUNCTIONALLY on CPU via the virtual device mesh
(``--xla_force_host_platform_device_count``); absolute TPU readings are
recorded as deferred when no TPU is attached.  Prints exactly one JSON
line — the MULTICHIP record (``tools/check_bench_floor.py`` validates the
schema).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated mesh sizes for the weak-scaling "
                         "arm (default 1,2,4,8)")
    ap.add_argument("--poses-per-dev", type=int, default=256)
    ap.add_argument("--agents-per-dev", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=64,
                    help="rounds per timed weak-scaling trial")
    ap.add_argument("--verdict-k", type=int, default=16)
    ap.add_argument("--gn-poses", type=int, default=2000,
                    help="noisy-probe size for the GN-tail parity arm "
                         "(0 skips the arm)")
    ap.add_argument("--gn-handoff-rounds", type=int, default=60)
    ap.add_argument("--scale-poses", type=int, default=0,
                    help="pose count for the functional scale test "
                         "(0 skips; the record run uses 1000000)")
    ap.add_argument("--scale-robots", type=int, default=256)
    ap.add_argument("--scale-rounds", type=int, default=8)
    ap.add_argument("--scale-verdict-k", type=int, default=4)
    ap.add_argument("--chaos-poses", type=int, default=0,
                    help="pose count for the resilience chaos arm "
                         "(0 skips; kills a device mid-solve and gates "
                         "the recovery)")
    ap.add_argument("--chaos-rounds", type=int, default=24)
    ap.add_argument("--chaos-verdict-k", type=int, default=4)
    ap.add_argument("--telemetry", metavar="RUN_DIR", default=None,
                    help="also emit the obs event stream (sharded report "
                         "section) into RUN_DIR")
    return ap.parse_args(argv)


ARGS = parse_args()

# Backend pinning must precede the jax import.  The TPU readings of this
# bench are explicitly deferred to a TPU-attached round
# (BENCH_SHARDED_TPU=1 leaves the default platform alone); the default
# run is the functional CPU arm on the virtual device mesh.
_MAX_DEV = max(int(x) for x in ARGS.devices.split(","))
if os.environ.get("BENCH_SHARDED_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_MAX_DEV}"
        ).strip()

import jax  # noqa: E402

if os.environ.get("BENCH_SHARDED_TPU") != "1":
    # The image's sitecustomize overrides jax_platforms (see bench.py):
    # pin in code, and enable x64 — the GN parity arm is an f64 contract.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def compiled_collective_bytes(txt: str, n_dev: int):
    """Per-device cross-device bytes of a compiled program's collectives
    (partitioned HLO): an all-gather sends all but its own shard on the
    ring; a collective-permute forwards its operand block once.  The
    measured side of the comm-model check (tests/test_sharded.py pins the
    same parse against ``comm_bytes_per_round``)."""
    total = 0
    for line in txt.splitlines():
        m = re.search(r"= (f64|f32|s32|u32|pred)\[([\d,]*)\][^ ]* "
                      r"(all-gather|collective-permute)\(", line)
        if not m:
            continue
        ty, dims, op = m.groups()
        size = 1
        for x in dims.split(","):
            if x:
                size *= int(x)
        nbytes = size * {"f64": 8, "f32": 4, "s32": 4, "u32": 4,
                         "pred": 1}[ty]
        total += nbytes * (n_dev - 1) // n_dev if op == "all-gather" \
            else nbytes
    return total


def build_problem(n, robots, dtype, seed=0, noise=0.01, lc_frac=0.3,
                  init="chordal"):
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous
    from dpgo_tpu.utils.synthetic import make_measurements_vectorized

    meas, _ = make_measurements_vectorized(
        np.random.default_rng(seed), n=n, d=3,
        num_lc=max(4, int(lc_frac * n)), rot_noise=noise,
        trans_noise=noise)
    params = AgentParams(d=3, r=5, num_robots=robots, rel_change_tol=0.0)
    part = partition_contiguous(meas, robots)
    graph, meta = rbcd.build_graph(part, params.r, dtype)
    X0 = rbcd.initial_state_for(init, part, meta, graph, params, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return meas, params, part, graph, meta, state


def sharded_driver(mesh, part, graph, meta, state, params, dtype, k):
    """The solve_rbcd_sharded machinery with the build hoisted out, so
    repeated drives reuse the compiled step/segment programs (the same
    structure bench.py's ``time_verdict_loop`` uses)."""
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.parallel import (make_sharded_metrics_body,
                                   make_sharded_multi_step,
                                   make_sharded_segment, make_sharded_step,
                                   shard_problem)
    from dpgo_tpu.types import edge_set_from_measurements

    state, graph_s = shard_problem(mesh, state, graph)
    sh_step = make_sharded_step(mesh, meta, params)
    sh_multi = make_sharded_multi_step(mesh, meta, params)
    sh_seg = make_sharded_segment(mesh, meta, params)
    step = lambda s, uw, rs: sh_step(s, graph_s, update_weights=uw,
                                     restart=rs)
    multi = lambda s, kk: sh_multi(s, graph_s, kk)
    seg = lambda s, kk, uw, rs: sh_seg(s, graph_s, kk, update_weights=uw,
                                       restart=rs)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)
    factory = lambda tel: make_sharded_metrics_body(
        mesh, graph_s, edges_g, part.meas_global.num_poses,
        len(part.meas_global), tel)

    def drive(rounds):
        return rbcd.run_rbcd(state, graph_s, meta, step, part, rounds,
                             grad_norm_tol=0.0, eval_every=k, dtype=dtype,
                             params=params, multi_step=multi, segment=seg,
                             verdict_every=k, metrics_body_factory=factory)

    return drive, state, graph_s, sh_multi


def weak_scaling_arm(dev_list, dtype):
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.parallel import make_mesh

    arms = []
    syncs_last = None
    for n_dev in dev_list:
        n = ARGS.poses_per_dev * n_dev
        robots = ARGS.agents_per_dev * n_dev
        rounds, k = ARGS.rounds, ARGS.verdict_k
        _meas, params, part, graph, meta, state = build_problem(
            n, robots, dtype, seed=n_dev)
        mesh = make_mesh(n_dev)
        drive, *_ = sharded_driver(mesh, part, graph, meta, state, params,
                                   dtype, k)
        t0 = time.perf_counter()
        res = drive(k)
        log(f"  [{n_dev} dev] compile+first block: "
            f"{time.perf_counter() - t0:.1f}s "
            f"({n} poses / {robots} agents)")
        assert res.iterations == k

        counted = [0]
        orig = rbcd._host_fetch

        def counting(x):
            counted[0] += 1
            return orig(x)

        rates = []
        rbcd._host_fetch = counting
        try:
            for _ in range(2):
                counted[0] = 0
                t0 = time.perf_counter()
                res = drive(rounds)
                dt = time.perf_counter() - t0
                assert res.iterations == rounds, res.iterations
                rates.append(rounds / dt)
        finally:
            rbcd._host_fetch = orig
        rps = float(np.median(rates))
        # The one fused terminal-epilogue fetch excluded, as in bench.py.
        syncs_last = 100.0 * max(counted[0] - 1, 0) / rounds
        arms.append({"devices": n_dev, "num_robots": robots, "n_poses": n,
                     "rounds_per_s": round(rps, 3),
                     "poses_per_s": round(rps * n, 1),
                     "host_syncs_per_100_rounds": round(syncs_last, 4)})
        log(f"  [{n_dev} dev] {rps:.2f} rounds/s = "
            f"{rps * n:.0f} poses/s, {syncs_last:.3g} syncs/100 rounds")
    return arms, syncs_last


def overlap_arm(dtype, obs_run=None):
    """Halo-pipelined vs lockstep fused rounds at the largest mesh."""
    from dpgo_tpu.parallel import (make_mesh, make_sharded_multi_step,
                                   shard_problem)

    n_dev = _MAX_DEV
    n = ARGS.poses_per_dev * n_dev
    robots = ARGS.agents_per_dev * n_dev
    _meas, params, _part, graph, meta, state = build_problem(
        n, robots, dtype, seed=99)
    mesh = make_mesh(n_dev)
    state, graph_s = shard_problem(mesh, state, graph)
    rates, multis = {}, {}
    for name, overlap in (("lockstep", False), ("overlap", True)):
        multi = make_sharded_multi_step(mesh, meta, params, overlap=overlap)
        multis[name] = multi
        _ = np.asarray(multi(state, graph_s, 2).X)  # compile + warm
        t0 = time.perf_counter()
        out = multi(state, graph_s, ARGS.rounds)
        _ = np.asarray(out.X)
        rates[name] = ARGS.rounds / (time.perf_counter() - t0)
        log(f"  [overlap A/B] {name}: {rates[name]:.2f} rounds/s")
    eff = 1.0 - rates["lockstep"] / max(rates["overlap"], 1e-9)
    rec = {"efficiency": round(eff, 4),
           "overlap_rounds_per_s": round(rates["overlap"], 3),
           "lockstep_rounds_per_s": round(rates["lockstep"], 3)}
    if obs_run is not None:
        # Device-time attribution per arm (ISSUE 16): a separate traced
        # segment AFTER the clean A/B walls above (the profiler slows
        # execution, so it must never touch the timed arms).  The
        # measured split says WHERE the A/B delta comes from.
        from dpgo_tpu.obs import devprof

        calib = max(4, min(ARGS.rounds, 16))
        for name in ("lockstep", "overlap"):
            win = devprof.DeviceTraceWindow(
                os.path.join(obs_run.run_dir, f"devprof_ab_{name}"),
                plane="sharded").start()
            _ = np.asarray(multis[name](state, graph_s, calib).X)
            att = win.stop(num_rounds=calib, label=f"ab_{name}")
            if att is not None:
                rec[f"{name}_measured_overlap"] = round(
                    att["overlap_efficiency_measured"], 4)
                rec[f"{name}_collective_s_per_round"] = round(
                    att["per_round"]["collective_s"], 6)
                log(f"  [overlap A/B] {name} attribution: "
                    f"{att['overlap_efficiency_measured'] * 100:.1f}% of "
                    f"collective time hidden")
        obs_run.metric("sharded_overlap_efficiency", rec["efficiency"],
                       phase="bench",
                       overlap_rounds_per_s=rec["overlap_rounds_per_s"],
                       lockstep_rounds_per_s=rec["lockstep_rounds_per_s"])
    return rec


def comm_arm(dtype, obs_run=None):
    """Modeled vs compiled interconnect bytes for one sharded round."""
    from dpgo_tpu.parallel import (comm_bytes_per_round, make_mesh,
                                   make_sharded_step, shard_problem)

    n_dev = _MAX_DEV
    if n_dev < 2:
        return {"skipped": "single-device mesh has no collectives"}
    n = ARGS.poses_per_dev * n_dev
    robots = ARGS.agents_per_dev * n_dev
    _meas, params, _part, graph, meta, state = build_problem(
        n, robots, dtype, seed=5)
    mesh = make_mesh(n_dev)
    state, graph_s = shard_problem(mesh, state, graph)
    step = make_sharded_step(mesh, meta, params)
    txt = step.lower(state, graph_s, update_weights=False,
                     restart=False).compile().as_text()
    measured = compiled_collective_bytes(txt, n_dev)
    modeled = comm_bytes_per_round(meta, n_dev,
                                   itemsize=np.dtype(dtype).itemsize)
    log(f"  [comm] modeled {modeled} vs compiled {measured} bytes/round")
    if obs_run is not None:
        obs_run.metric("sharded_comm_bytes_measured", measured,
                       phase="bench", modeled=modeled)
    return {"modeled_bytes_per_round": modeled,
            "measured_bytes_per_round": measured,
            "match": bool(measured == modeled)}


def gn_tail_arm(dtype):
    """Sharded device-resident GN-CG tail vs host refine.gn_tail on the
    noisy probe, from the same sharded handoff iterate."""
    from dpgo_tpu.models import rbcd, refine
    from dpgo_tpu.parallel import gn_tail_sharded, make_mesh

    if ARGS.gn_poses <= 0:
        return {"skipped": "disabled (--gn-poses 0)"}
    n = ARGS.gn_poses
    robots = ARGS.agents_per_dev * _MAX_DEV
    _meas, params, part, graph, meta, state = build_problem(
        n, robots, dtype, seed=7, noise=0.1, lc_frac=0.2)
    mesh = make_mesh(_MAX_DEV)
    drive, state_s, graph_s, _ = sharded_driver(
        mesh, part, graph, meta, state, params, dtype,
        max(ARGS.gn_handoff_rounds // 4, 1))
    t0 = time.perf_counter()
    res = drive(ARGS.gn_handoff_rounds)
    log(f"  [gn] handoff after {res.iterations} BCD rounds "
        f"({time.perf_counter() - t0:.1f}s)")

    cfg = refine.GNTailConfig()
    e64 = refine.host_edges_f64(part.meas_global)
    Xg0 = np.asarray(rbcd.gather_to_global(res.X, graph,
                                           part.meas_global.num_poses),
                     np.float64)
    t0 = time.perf_counter()
    host = refine.gn_tail(Xg0, e64, cfg)
    t_host = time.perf_counter() - t0

    counted = [0]
    orig = rbcd._host_fetch

    def counting(x):
        counted[0] += 1
        return orig(x)

    rbcd._host_fetch = counting
    try:
        t0 = time.perf_counter()
        _Xa, sh = gn_tail_sharded(res.X, graph, meta, mesh=mesh, cfg=cfg)
        t_sh = time.perf_counter() - t0
    finally:
        rbcd._host_fetch = orig
    parity = abs(sh.cost_history[-1] - host.cost_history[-1]) \
        / max(abs(host.cost_history[-1]), 1e-300)
    log(f"  [gn] host: {host.terminated_by} cost {host.cost_history[-1]:.6g} "
        f"gn {host.grad_norm_history[-1]:.3g} ({t_host:.1f}s)  "
        f"sharded: {sh.terminated_by} cost {sh.cost_history[-1]:.6g} "
        f"gn {sh.grad_norm_history[-1]:.3g} ({t_sh:.1f}s, "
        f"{counted[0]} host fetches / {sh.cg_iterations} CG iters)  "
        f"parity {parity:.2e}")
    return {"n_poses": n, "num_robots": robots,
            "handoff_rounds": int(res.iterations),
            "host": {"terminated_by": host.terminated_by,
                     "final_cost": host.cost_history[-1],
                     "final_gn": host.grad_norm_history[-1],
                     "outer": host.outer_iterations,
                     "wall_s": round(t_host, 2)},
            "sharded": {"terminated_by": sh.terminated_by,
                        "final_cost": sh.cost_history[-1],
                        "final_gn": sh.grad_norm_history[-1],
                        "outer": sh.outer_iterations,
                        "cg_iterations": sh.cg_iterations,
                        "host_fetches": counted[0],
                        "wall_s": round(t_sh, 2)},
            "parity_rel": parity}


def scale_arm(dtype=jnp.float32):
    """The functional large-scale solve, end to end through the sharded
    verdict loop (odometry init — chordal at this scale is a bench of the
    init, not the loop), then the CERTIFIED row: the terminal iterate
    polished by the sharded GN-CG tail and judged by the fused device
    certificate (``rbcd.make_terminal_epilogue(certify_mode="device")``)
    — a true dual certificate at the 1M-pose scale, not a proxy.  The
    host-f64 REFUSE fallback is deliberately NOT run here (a sparse
    million-pose eigensolve on the bench host is its own benchmark); a
    REFUSE is recorded as refused."""
    from dpgo_tpu.models import certify as certify_mod
    from dpgo_tpu.models import rbcd, refine
    from dpgo_tpu.parallel import gn_tail_sharded, make_mesh
    from dpgo_tpu.types import edge_set_from_measurements

    if ARGS.scale_poses <= 0:
        return {"skipped": "disabled (--scale-poses 0)"}
    n, robots = ARGS.scale_poses, ARGS.scale_robots
    t_build0 = time.perf_counter()
    _meas, params, part, graph, meta, state = build_problem(
        n, robots, dtype, seed=11, noise=0.05, lc_frac=0.2,
        init="odometry")
    t_build = time.perf_counter() - t_build0
    log(f"  [scale] built {n} poses / {robots} agents in {t_build:.1f}s")
    mesh = make_mesh(_MAX_DEV)
    drive, _state_s, graph_s, _ = sharded_driver(
        mesh, part, graph, meta, state, params, dtype,
        ARGS.scale_verdict_k)
    t0 = time.perf_counter()
    res = drive(ARGS.scale_rounds)
    wall = time.perf_counter() - t0
    ok = res.iterations == ARGS.scale_rounds \
        and all(np.isfinite(c) for c in res.cost_history) \
        and bool(np.isfinite(np.asarray(res.X)).all())
    log(f"  [scale] {res.iterations} rounds through the sharded verdict "
        f"loop in {wall:.1f}s; cost {res.cost_history[0]:.4g} -> "
        f"{res.cost_history[-1]:.4g}")

    # Certified row: GN-CG polish + device certificate, one terminal
    # fetch through the fused epilogue.
    t_c0 = time.perf_counter()
    Xa, tail = gn_tail_sharded(res.state.X, graph_s, meta, mesh=mesh,
                               cfg=refine.GNTailConfig(max_outer=4),
                               weights=res.state.weights)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=dtype)
    epilogue = rbcd.make_terminal_epilogue(
        graph_s, edges_g, part.meas_global.num_poses,
        len(part.meas_global), meta, certify_mode="device")
    eta = 1e-3 if np.dtype(dtype) == np.float32 else 1e-5
    fin = rbcd._host_fetch(epilogue(Xa, res.state.weights, {}))
    cert = certify_mod.decide_device_certificate(
        fin["cert"], eta, float(np.finfo(np.dtype(dtype)).eps),
        f64_solve=None, source="bench_scale")
    t_cert = time.perf_counter() - t_c0
    log(f"  [scale] certificate: "
        f"{certify_mod.CERT_STATUS[cert.device_verdict]} "
        f"(lam_min {cert.lambda_min:.3g}, tol {cert.tol:.3g}) "
        f"in {t_cert:.1f}s")
    return {"n_poses": n, "num_robots": robots,
            "devices": _MAX_DEV, "rounds": int(res.iterations),
            "verdict_every": ARGS.scale_verdict_k,
            "completed": bool(ok), "build_s": round(t_build, 1),
            "solve_s": round(wall, 1),
            "rounds_per_s": round(res.iterations / wall, 4),
            "poses_per_s": round(n * res.iterations / wall, 1),
            "cost_first_eval": res.cost_history[0],
            "cost_last_eval": res.cost_history[-1],
            "certified": bool(cert.certified),
            "cert_status": certify_mod.CERT_STATUS[cert.device_verdict],
            "cert_lambda_min": float(cert.lambda_min),
            "cert_tol": float(cert.tol),
            "cert_eta": eta,
            "gn_tail_terminated_by": tail.terminated_by,
            "certify_s": round(t_cert, 1),
            "dtype": str(np.dtype(dtype))}


def resilience_arm(dtype):
    """Chaos arm (ISSUE 14): kill a device mid-solve under the rewind
    supervisor and gate the recovery against the fault-free run."""
    import tempfile

    from dpgo_tpu.parallel import (CollectiveFaultInjector, MeshFaultSpec,
                                   ResilienceConfig, make_mesh,
                                   solve_rbcd_sharded)

    if ARGS.chaos_poses <= 0:
        return {"skipped": "disabled (--chaos-poses 0)"}
    n = ARGS.chaos_poses
    robots = ARGS.agents_per_dev * _MAX_DEV
    k, rounds = ARGS.chaos_verdict_k, ARGS.chaos_rounds
    meas, params, part, *_ = build_problem(n, robots, dtype, seed=13,
                                           noise=0.1, lc_frac=0.2)
    common = dict(num_robots=robots, part=part, params=params,
                  max_iters=rounds, verdict_every=k, grad_norm_tol=0.0,
                  eval_every=k, dtype=dtype)
    t0 = time.perf_counter()
    ref = solve_rbcd_sharded(meas, mesh=make_mesh(_MAX_DEV), **common)
    t_ref = time.perf_counter() - t0
    # Kill a device just past the midpoint so at least one checkpoint
    # exists; the supervisor resumes on a halved mesh.
    inj = CollectiveFaultInjector(
        MeshFaultSpec(device_loss_rounds=(rounds // 2 + 1,),
                      lost_device=_MAX_DEV - 1), seed=13)
    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        res = solve_rbcd_sharded(
            meas, mesh=make_mesh(_MAX_DEV),
            resilience=ResilienceConfig(checkpoint_dir=td, injector=inj),
            **common)
        t_chaos = time.perf_counter() - t0
    rz = res.resilience
    rel = abs(res.cost_history[-1] - ref.cost_history[-1]) \
        / max(abs(ref.cost_history[-1]), 1e-300)
    log(f"  [chaos] device {_MAX_DEV - 1} killed after "
        f"{rounds // 2 + 1} rounds: {rz['recoveries']} recoveries, "
        f"mesh {rz['mesh_sizes']}, overhead "
        f"{rz['recovery_overhead_s']:.2f}s, final-cost rel err {rel:.2e} "
        f"({t_ref:.1f}s fault-free vs {t_chaos:.1f}s chaos)")
    return {"n_poses": n, "num_robots": robots, "devices": _MAX_DEV,
            "rounds": rounds, "verdict_every": k,
            "recoveries": rz["recoveries"],
            "checkpoints": rz["checkpoints"],
            "cold_restarts": rz["cold_restarts"],
            "mesh_sizes": rz["mesh_sizes"],
            "fault_kinds": rz["fault_kinds"],
            "recovery_overhead_s": rz["recovery_overhead_s"],
            "final_cost_rel_err": rel,
            "fault_free_s": round(t_ref, 2),
            "chaos_s": round(t_chaos, 2)}


def main():
    from dpgo_tpu import obs

    backend = jax.default_backend()
    avail = len(jax.devices())
    dev_list = [int(x) for x in ARGS.devices.split(",") if int(x) <= avail]
    log(f"bench_sharded: backend {backend}, {avail} devices, "
        f"weak-scaling arms {dev_list}")
    dtype = jnp.float64 if backend == "cpu" else jnp.float32

    scope = obs.run_scope(ARGS.telemetry) if ARGS.telemetry \
        else None
    run = None
    if scope is not None:
        scope.__enter__()
        run = obs.get_run()
    try:
        ws, syncs = weak_scaling_arm(dev_list, dtype)
        ov = overlap_arm(dtype, obs_run=run)
        comm = comm_arm(dtype, obs_run=run)
        gn = gn_tail_arm(dtype)
        scale = scale_arm()
        rz = resilience_arm(dtype)
    finally:
        if scope is not None:
            scope.__exit__(None, None, None)

    rec = {
        "record": "MULTICHIP",
        "metric": "sharded_verdict_poses_per_sec",
        "value": ws[-1]["poses_per_s"],
        "unit": "poses/s",
        "n_devices": _MAX_DEV,
        "rc": 0, "ok": True, "skipped": False,
        "backend": backend,
        "tpu_attached": backend == "tpu",
        "verdict_every": ARGS.verdict_k,
        "host_syncs_per_100_rounds": round(syncs, 4),
        "weak_scaling": ws,
        "overlap": ov,
        "comm": comm,
        "gn_tail": gn,
        "scale_test": scale,
        "resilience": rz,
    }
    if backend != "tpu":
        rec["notes"] = ("functional CPU run on the virtual device mesh; "
                        "TPU absolute readings deferred to a TPU-attached "
                        "round (single-core CPU: virtual shards share one "
                        "core, so weak-scaling poses/s is a correctness "
                        "arm here, not a throughput claim)")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
