"""Benchmark: the DEPLOYMENT plane's per-round cost on sphere2500/8.

Where ``bench.py`` measures the batched TPU core, this measures the
per-robot ``PGOAgent`` + ``dpgo_tpu.comms`` path the reference deploys via
ROS: each round every robot packs its public poses onto the wire, the
``RoundBus`` hub gathers and rebroadcasts, every robot ingests its peers
and takes one RTR step.  Wall-clock here is dominated by data movement —
serialization, framing, neighbor-cache updates — not FLOPs, which is
exactly what the packed wire format (v2), the slot-indexed neighbor
scatter, and the compute/comm overlap (bounded staleness) attack.

Arms (``--arms``):

* ``fast``   — v2 packed columnar frames (zero-copy decode), packed pose
  vocabulary feeding the vectorized neighbor scatter, compute/comm
  overlap at ``--staleness`` (default 1).
* ``legacy`` — the pre-PR configuration: v1 npz frames (one zip member
  per pose block), per-pose dict vocabulary, strict lockstep
  serialize -> exchange -> deserialize -> compute.
* ``bf16``   — the fast arm with the opt-in bf16 pose payload (half the
  f32 wire bytes; f32-accumulated on receipt, parity-bounded by
  ``BF16_REL_ERR``).

Transports: ``loopback`` (in-process pair — the serialization/framing
cost without socket noise) and/or ``tcp`` (real localhost sockets,
threads in-process).

Prints exactly ONE JSON line through the obs ``metric_record`` schema
(same leading metric/value/unit keys as bench.py and the telemetry
stream), with per-arm sub-records and the fast-vs-legacy ratios.

Usage::

    python bench_deployment.py [--rounds 40] [--robots 8] [--rank 5]
        [--transport loopback|tcp|both] [--arms fast,legacy,bf16]
        [--staleness 1] [--n 2500] [--telemetry DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

DATASET = "/root/reference/data/sphere2500.g2o"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def load_measurements(n: int):
    if n == 2500 and os.path.exists(DATASET):
        from dpgo_tpu.utils.g2o import read_g2o
        return read_g2o(DATASET), "sphere2500"
    from dpgo_tpu.utils.synthetic import make_measurements
    # Same edge density as sphere2500 (~2449 LCs at 2500 poses).
    meas, _ = make_measurements(np.random.default_rng(0), n=n, d=3,
                                num_lc=max(4, int(n * 0.98)),
                                rot_noise=0.01, trans_noise=0.01)
    return meas, f"synthetic{n}"


def build_agents(meas, robots: int, rank: int):
    from dpgo_tpu.agent import PGOAgent
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.utils.partition import agent_measurements, \
        partition_contiguous

    params = AgentParams(d=meas.d, r=rank, num_robots=robots)
    part = partition_contiguous(meas, robots)
    agents = [PGOAgent(a, params) for a in range(robots)]
    for ag in agents[1:]:
        ag.set_lifting_matrix(agents[0].get_lifting_matrix())
    for ag in agents:
        ag.set_pose_graph(*agent_measurements(part, ag.robot_id))
    return agents


def make_tcp_fleet(robots: int, wire_format: str):
    """Real localhost sockets, all endpoints in-process (threads)."""
    from dpgo_tpu.comms import (BusClient, ReliableChannel, RetryPolicy,
                                RoundBus, TcpTransport, connect_tcp,
                                listen_tcp)
    from dpgo_tpu.comms.bus import accept_robots

    policy = RetryPolicy(send_timeout_s=30.0, recv_timeout_s=30.0)
    srv = listen_tcp(port=0)
    port = srv.getsockname()[1]
    clients: dict[int, BusClient] = {}

    def dial(rid):
        sock = connect_tcp("127.0.0.1", port)
        t = TcpTransport(sock, src=f"robot{rid}", dst="bus",
                         wire_format=wire_format)
        c = BusClient(ReliableChannel(t, f"robot{rid}->bus", policy), rid)
        clients[rid] = c
        c.hello(timeout=30.0)

    dialers = [threading.Thread(target=dial, args=(rid,))
               for rid in range(robots)]
    for t in dialers:
        t.start()
    channels = accept_robots(srv, robots, policy=policy,
                             wire_format=wire_format)
    for t in dialers:
        t.join()
    srv.close()
    bus = RoundBus(channels, round_timeout_s=30.0)
    return bus, clients


def run_arm(agents, transport: str, *, wire_format: str, packed: bool,
            wire_dtype: str, staleness: int, rounds: int,
            warmup: int = 10) -> dict:
    # warmup must cover the init handshake (non-anchor robots frame-align
    # only after receiving robot 0's poses) AND every robot's first
    # stepped iterate (the jit compile) — all robots run the SAME warmup
    # count so the lockstep bus schedule stays aligned.
    """Drive ``rounds`` timed exchange+iterate rounds; returns rates and
    per-round wire bytes."""
    from dpgo_tpu.comms import (RetryPolicy, apply_peer_frame,
                                loopback_fleet, pack_agent_frame)

    robots = len(agents)
    if transport == "tcp":
        bus, clients = make_tcp_fleet(robots, wire_format)
    else:
        bus, clients = loopback_fleet(
            robots, policy=RetryPolicy(send_timeout_s=30.0,
                                       recv_timeout_s=30.0),
            round_timeout_s=30.0, wire_format=wire_format)

    # The bus serves EXACTLY one round per robot exchange (fault-free,
    # generous deadlines keep the schedule aligned), so a fixed count
    # terminates it cleanly — no close-under-a-live-round teardown race
    # that would read as dead robots in the telemetry.
    total_rounds = warmup + rounds

    def bus_loop():
        for _ in range(total_rounds):
            if len(bus.lost) == len(bus.channels):
                return
            bus.round()

    start_barrier = threading.Barrier(robots + 1)
    done_at = [0.0] * robots

    def robot_loop(rid: int):
        ag = agents[rid]
        client = clients[rid]
        if staleness > 0:
            client.start_overlap(staleness, timeout=30.0)

        def one_round():
            frame = pack_agent_frame(ag, include_anchor=(rid == 0),
                                     wire_dtype=wire_dtype, packed=packed)
            merged = client.exchange(frame, timeout=30.0)
            if merged is not None:
                for peer, pf in client.peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
            ag.iterate(True)

        for _ in range(warmup):
            one_round()
        start_barrier.wait()
        for _ in range(rounds):
            one_round()
        client.drain_overlap(timeout=60.0)
        done_at[rid] = time.perf_counter()

    bus_thread = threading.Thread(target=bus_loop, daemon=True)
    bus_thread.start()
    threads = [threading.Thread(target=robot_loop, args=(rid,), daemon=True)
               for rid in range(robots)]
    for t in threads:
        t.start()
    start_barrier.wait()
    up0 = sum(c.channel.totals.bytes_sent for c in clients.values())
    down0 = sum(ch.totals.bytes_sent for ch in bus.channels.values())
    t0 = time.perf_counter()
    for t in threads:
        t.join(timeout=900)
    wall = max(done_at) - t0
    up = sum(c.channel.totals.bytes_sent for c in clients.values()) - up0
    down = sum(ch.totals.bytes_sent
               for ch in bus.channels.values()) - down0
    bus_thread.join(timeout=60)
    for c in clients.values():
        c.close()
    bus.close()
    return {
        "rounds_per_s": round(rounds / wall, 3),
        "wall_s": round(wall, 3),
        # Upstream = all robots' publishes per round; downstream = the
        # bus's rebroadcast fan-out per round (wire bytes incl. headers).
        "bytes_per_round_up": int(up / rounds),
        "bytes_per_round_down": int(down / rounds),
    }


ARMS = {
    # name: (wire_format, packed-vocabulary, wire_dtype, use-staleness)
    "fast": ("packed", True, "f64", True),
    "bf16": ("packed", True, "bf16", True),
    "legacy": ("npz", False, "f64", False),
}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int,
                    default=int(os.environ.get("BENCH_DEPLOY_ROUNDS", "40")))
    ap.add_argument("--robots", type=int, default=8)
    ap.add_argument("--rank", type=int, default=5)
    ap.add_argument("--n", type=int, default=2500,
                    help="pose count (2500 reads sphere2500.g2o when "
                         "present, else a same-density synthetic)")
    ap.add_argument("--transport", choices=("loopback", "tcp", "both"),
                    default="both")
    ap.add_argument("--arms", default="fast,legacy,bf16",
                    help=f"comma list from {sorted(ARMS)}")
    ap.add_argument("--staleness", type=int, default=1,
                    help="overlap bound for the fast arms (0 = lockstep)")
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="scope an obs run here; the final record also "
                         "rides the event stream")
    args = ap.parse_args()

    from dpgo_tpu import obs
    from dpgo_tpu.obs.events import metric_record

    run = obs.start_run(args.telemetry) if args.telemetry else None

    meas, ds_name = load_measurements(args.n)
    if run is not None:
        run.set_fingerprint(dataset=ds_name, num_robots=args.robots,
                            rank=args.rank)
    log(f"[bench_deployment] {ds_name}: {len(meas)} measurements over "
        f"{meas.num_poses} poses, {args.robots} robots, r={args.rank}")

    transports = ["loopback", "tcp"] if args.transport == "both" \
        else [args.transport]
    arm_names = [a for a in args.arms.split(",") if a]
    results: dict[str, dict] = {}
    for transport in transports:
        for arm in arm_names:
            wire_format, packed, wire_dtype, overlap = ARMS[arm]
            # Fresh agents per arm: identical start state, no cross-arm
            # warm caches.
            agents = build_agents(meas, args.robots, args.rank)
            r = run_arm(agents, transport, wire_format=wire_format,
                        packed=packed, wire_dtype=wire_dtype,
                        staleness=args.staleness if overlap else 0,
                        rounds=args.rounds)
            results[f"{transport}/{arm}"] = r
            log(f"  [{transport}/{arm}] {r['rounds_per_s']} rounds/s, "
                f"{r['bytes_per_round_up']} B/round up, "
                f"{r['bytes_per_round_down']} B/round down")

    def ratio(tr, num, den, key):
        a, b = results.get(f"{tr}/{num}"), results.get(f"{tr}/{den}")
        if not a or not b or not b[key]:
            return None
        return round(a[key] / b[key], 3)

    headline = results.get("loopback/fast") or \
        next(iter(results.values()))
    out = metric_record(
        f"deployment_rounds_per_sec_{ds_name}_{args.robots}robots"
        f"_r{args.rank}",
        headline["rounds_per_s"], "rounds/s",
        staleness=args.staleness,
        rounds=args.rounds,
        arms=results,
        speedup_vs_legacy=ratio("loopback", "fast", "legacy",
                                "rounds_per_s"),
        tcp_bytes_ratio_legacy_over_fast=(
            None if ratio("tcp", "legacy", "fast", "bytes_per_round_up")
            is None else ratio("tcp", "legacy", "fast",
                               "bytes_per_round_up")),
        bf16_bytes_ratio_fast_over_bf16=ratio(
            transports[0], "fast", "bf16", "bytes_per_round_up"),
    )
    if run is not None:
        run.metric(out["metric"], out["value"], out.get("unit"),
                   phase="report", **{k: v for k, v in out.items()
                                      if k not in ("metric", "value",
                                                   "unit")})
        obs.end_run()
        # The bench rounds ran traced (spans ride the same run): export
        # the Perfetto timeline so a slow arm can be eyeballed directly.
        from dpgo_tpu.obs import timeline
        trace_path = timeline.write_chrome_trace(
            os.path.join(args.telemetry, "trace.json"),
            timeline.merge([args.telemetry]))
        log(f"[bench_deployment] Perfetto timeline: {trace_path}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
