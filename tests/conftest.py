"""Test configuration: run on a virtual 8-device CPU mesh with float64.

The reference validates all math on CPU with gtest (``tests/*.cpp``); here
the same pyramid runs under pytest on the CPU backend so collective code
paths execute without TPU hardware (multi-device via
``--xla_force_host_platform_device_count``), and in f64 so golden-value
comparisons are tight.
"""

import os

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize force-registers a TPU-tunnel platform and
# overrides jax_platforms to "axon,cpu"; pin tests back to the virtual
# multi-device CPU backend (a single TPU grant exists — concurrent test
# processes would deadlock on it, and tests must not depend on hardware).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

DATA_DIR = "/root/reference/data"


# Compile-heavy tests (measured >= ~8 s each on the single-core CPU backend;
# durations from a full-suite run) are auto-marked ``slow`` so the default
# iteration loop is `pytest -m "not slow"` (< ~2 min); the full suite
# (~25 min on this 1-core box) remains the pre-commit gate for solver math.
SLOW_TESTS = {
    "test_colored_schedule_with_acceleration",
    "test_four_process_robust_tcp_matches_in_process",
    "test_agent_iterate_pallas_kernel_matches_ell",
    "test_four_process_tcp_solve_matches_two",
    "test_four_process_async_tcp_solve",
    "test_rounds_bf16_select_tracks_ell_path",
    "test_rounds_bf16x3_select_matches_f32_kernel",
    "test_colored_fixes_jacobi_oscillation_ais2klinik",
    "test_colored_schedule_converges_and_matches_structure",
    "test_accelerated_solve",
    "test_ppermute_exchange_matches_all_gather",
    "test_sharded_matches_single_device_accel_robust",
    "test_fused_segments_respect_gnc_and_restart_schedule",
    "test_sharded_solve_robust_accel",
    "test_winding_local_minimum_fails_certificate_and_staircase_escapes",
    "test_kernel_matches_xla_tcg",
    "test_rbcd_scale_20k_poses_32_agents",
    "test_async_solve_kitti_se2",
    "test_solve_staircase_end_to_end",
    "test_solve_rbcd_distributed_init_end_to_end",
    "test_sharded_64_agents_on_8_devices",
    "test_rbcd_smallgrid_vs_centralized",
    "test_rbcd_dense_matches_ell_rounds",
    "test_gnc_accelerated",
    "test_solve_rbcd_distributed_init_robust_odometry_start",
    "test_accelerated_not_slower_than_plain",
    "test_distributed_initialization_and_consensus_solve",
    "test_gnc_rejects_outliers_and_recovers",
    "test_gnc_corruption_protocol_precision_recall",
    "test_gnc_reinstatement_recovers_over_rejected_edges",
    "test_sharded_matches_single_device",
    "test_checkpoint_resume_matches_uninterrupted",
    "test_rbcd_matches_centralized_on_noisy_graph",
    "test_sharded_solve_smallgrid",
    "test_rounds_match_ell_path_se2",
    "test_rounds_match_ell_path",
    "test_partition_by_keys",
    "test_robust_solve_rejects_outliers",
    "test_ppermute_solve_end_to_end",
    "test_gnc_weights_consistent_between_shared_copies",
    "test_rbcd_rgd_algorithm",
    "test_accelerated_rbcd_converges",
    "test_lifted_rank_matches_unlifted_optimum",
    "test_log_data_dumps_on_reset_and_iter50",
    "test_distributed_init_robust_to_outlier_shared_edges",
    "test_rbcd_cost_monotone_jacobi",
    "test_non_gnc_robust_costs_downweight_outliers",
    "test_smallgrid_end_to_end",
    "test_gnc_known_inliers_pinned",
    "test_certificate_operator_matches_dense_eig",
    "test_rbcd_se2",
    "test_rgd_linesearch_converges",
    "test_accelerated_restart_rounds_run",
    "test_gnc_warm_start_disabled_resets",
    "test_block_jacobi_precond_speeds_tcg",
    "test_gnc_convergence_ratio_gates_consensus",
    "test_optimal_solution_certifies",
    "test_sharded_fused_rounds_match_per_round",
    # Fleet scale-out (ISSUE 13): the heavy migration/warm-restart
    # soaks run explicitly in the CI fleet job (no slow filter there).
    "test_session_affinity_and_status",
    "test_affinity_survives_fleet_rebuild",
    "test_kill_mid_solve_migrates_and_recovers",
    "test_drain_migration_bitwise_parity",
    "test_warm_restart_first_solve_skips_xla",
    "test_rtr_monotone_and_reaches_tol",
    "test_mesh_size_divisibility",
    "test_fused_rounds_match_sequential",
    "test_distributed_init_aligns_frames",
    "test_local_initialization_per_agent_frames",
    "test_rbcd_async_schedule_runs",
    "test_rtr_single_step_decreases_cost",
    "test_rbcd_converges_noiseless",
    "test_early_publishing_uninitialized_neighbor_does_not_align",
    "test_accelerated_greedy_schedule",
    "test_staircase_rounding_handles_rotated_basis",
    "test_async_solve_while_running",
    "test_solver_uses_fused_segments",
    "test_single_robot_iterate_converges",
    "test_tcg_on_pgo_model_decreases",
    "test_weight_update_cap_honored",
    "test_dense_opt_in_without_qbuf_raises",
    "test_chordal_init_exact_on_noiseless_graph",
    "test_refresh_problem_rebakes_factors",
    "test_forced_pallas_without_sel_raises",
    "test_rgd_step_decreases_cost",
    "test_solve_local_noiseless_exact",
    "test_dense_q_problem_matches_edges",
    "test_edge_tiles_layout",
    "test_sharded_certificate_matches_centralized",
    "test_sharded_certificate_sphere2500",
    "test_solve_refine_beats_f32_floor",
    "test_kernel_refine_matches_xla_refine",
    "test_recentered_gradient_error_scales_with_d",
    "test_two_process_tcp_solve_converges",
    "test_three_process_tcp_chaos_degrades_gracefully",
    "test_tcp_serve_solve_roundtrip",
    "test_comm_model_matches_compiled_collectives",
    "test_sharded_staircase_escapes_winding_minimum",
    "test_f32_staircase_polishes_before_certifying",
    "test_sharded_staircase_certifies_clean_graph",
    # ISSUE 11: the pod-scale verdict/overlap/GN-tail suite compiles
    # shard_map programs on the virtual mesh — CI's `sharded` job runs it.
    "test_sharded_metrics_body_bitwise_vs_central",
    "test_sharded_verdict_matches_single_device_verdict",
    "test_sharded_verdict_matches_sharded_per_eval",
    "test_sharded_verdict_host_sync_rate",
    "test_sharded_overlap_matches_unpipelined",
    "test_sharded_verdict_ppermute_matches_all_gather",
    "test_sharded_gn_tail_matches_host_gn_tail",
    "test_sharded_gn_tail_zero_transfers_inside_cg",
    "test_solve_sharded_with_gn_tail_extends_histories",
    "test_sharded_verdict_telemetry_and_report",
    # ISSUE 14: the mesh-chaos acceptance suite re-solves the 8-device
    # problem several times (fault-free reference + chaos runs, with
    # recompiles on the shrunken 4/2-device meshes) — CI's `sharded`
    # job runs it unfiltered under leakcheck.
    "test_device_loss_resumes_on_smaller_mesh",
    "test_nan_halo_trips_anomaly_rewind",
    "test_double_device_loss_reshards_8_4_2",
    "test_resilience_sync_rate_unchanged",
    "test_hung_fetch_watchdog_rewind",
    # ISSUE 17: the multi-process mesh acceptance (real jax.distributed
    # worker processes, kill -9 chaos) and the out-of-process fleet
    # suite (real replica child processes) — CI's `multihost` job runs
    # them unfiltered under leakcheck.
    "test_two_process_solve_matches_single_process",
    "test_kill9_worker_recovers_on_shrunken_world",
    "test_proc_server_lifecycle_and_sigkill_mid_flight",
    "test_proc_server_drain_evacuates_for_migration",
    "test_proc_fleet_kill9_loses_zero_sessions",
    # ISSUE 16: the device-profiling acceptance tests compile both
    # overlap arms (auto-gate calibration) and/or profiled shard_map
    # programs on the virtual mesh — CI's `profiling` job runs them.
    "test_sharded_overlap_auto_gates_off_with_evidence",
    "test_overlap_auto_single_device_shortcut",
    "test_profiled_sharded_run_merged_trace_device_track",
    "test_telemetry_off_devprof_is_fenced",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.originalname in SLOW_TESTS or item.name in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables after each test module.

    The full suite compiles many hundred XLA programs; keeping them all
    live in one process eventually crashes XLA:CPU's compiler (observed:
    deterministic SIGSEGV inside LLVM during the shard_map accel+robust
    compile at ~165 tests in, while any subset of the suite passes).
    Clearing between modules bounds the live-executable count; modules
    recompile their own programs anyway, so the wall-clock cost is small.
    """
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def data_dir():
    return DATA_DIR


@pytest.fixture
def rng():
    return np.random.default_rng(42)
