"""Test configuration: run on a virtual 8-device CPU mesh with float64.

The reference validates all math on CPU with gtest (``tests/*.cpp``); here
the same pyramid runs under pytest on the CPU backend so collective code
paths execute without TPU hardware (multi-device via
``--xla_force_host_platform_device_count``), and in f64 so golden-value
comparisons are tight.
"""

import os

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize force-registers a TPU-tunnel platform and
# overrides jax_platforms to "axon,cpu"; pin tests back to the virtual
# multi-device CPU backend (a single TPU grant exists — concurrent test
# processes would deadlock on it, and tests must not depend on hardware).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

DATA_DIR = "/root/reference/data"


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables after each test module.

    The full suite compiles many hundred XLA programs; keeping them all
    live in one process eventually crashes XLA:CPU's compiler (observed:
    deterministic SIGSEGV inside LLVM during the shard_map accel+robust
    compile at ~165 tests in, while any subset of the suite passes).
    Clearing between modules bounds the live-executable count; modules
    recompile their own programs anyway, so the wall-clock cost is small.
    """
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def data_dir():
    return DATA_DIR


@pytest.fixture
def rng():
    return np.random.default_rng(42)
