"""Tests for robust cost weights (reference src/DPGO_robust.cpp:23-103)."""

import jax.numpy as jnp
import numpy as np

from dpgo_tpu import robust
from dpgo_tpu.config import RobustCostParams, RobustCostType


def P(ct, **kw):
    return RobustCostParams(cost_type=ct, **kw)


def test_l2():
    r = jnp.array([0.1, 1.0, 100.0])
    assert np.allclose(robust.weight(r, P(RobustCostType.L2)), 1.0)


def test_l1():
    r = jnp.array([0.5, 2.0])
    assert np.allclose(robust.weight(r, P(RobustCostType.L1)), [2.0, 0.5])


def test_huber():
    p = P(RobustCostType.Huber)  # threshold 3
    r = jnp.array([1.0, 3.0, 6.0])
    assert np.allclose(robust.weight(r, p), [1.0, 1.0, 0.5])


def test_tls():
    p = P(RobustCostType.TLS)  # threshold 10
    r = jnp.array([9.0, 11.0])
    assert np.allclose(robust.weight(r, p), [1.0, 0.0])


def test_gm():
    r = jnp.array([0.0, 1.0])
    assert np.allclose(robust.weight(r, P(RobustCostType.GM)), [1.0, 0.25])


def test_gnc_tls_branches():
    barc, mu = 10.0, 0.5
    barc_sq = barc * barc
    upper = (mu + 1) / mu * barc_sq  # 300
    lower = mu / (mu + 1) * barc_sq  # 100/1.5

    r = jnp.sqrt(jnp.array([upper + 1, lower - 1, (upper + lower) / 2]))
    w = np.asarray(robust.gnc_tls_weight(r, mu, barc))
    assert w[0] == 0.0
    assert w[1] == 1.0
    mid_expected = np.sqrt(barc_sq * mu * (mu + 1) / ((upper + lower) / 2)) - mu
    assert np.isclose(w[2], mid_expected)
    assert 0.0 < w[2] < 1.0


def test_gnc_tls_monotone_in_residual():
    w = np.asarray(robust.gnc_tls_weight(jnp.linspace(0.1, 50.0, 100), 0.3, 10.0))
    assert np.all(np.diff(w) <= 1e-12)


def test_gnc_mu_annealing():
    p = P(RobustCostType.GNC_TLS)
    mu = jnp.asarray(p.gnc_init_mu)
    mu2 = robust.gnc_update_mu(mu, p)
    assert np.isclose(float(mu2), 1e-4 * 1.4)


def test_weight_converged():
    w = jnp.array([0.0, 1.0, 0.5, 1e-9, 1 - 1e-9])
    conv = np.asarray(robust.is_weight_converged(w))
    assert conv.tolist() == [True, True, False, True, True]
