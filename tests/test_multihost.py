"""True multi-host execution (``parallel.multihost``, ISSUE 17): the
verdict-boundary lockstep protocol against a fake coordination client,
world-shrink planning, the launcher's exit-code classifier, and the
checkpoint-writer gating a multi-rank world relies on.

The slow-marked tests run the REAL thing: worker processes joined by
``jax.distributed``, a 2-process solve bit-matching the single-process
reference, and a ``kill -9``'d worker whose survivors respawn on a
shrunken world and resume from the last v2 checkpoint.
"""

import signal

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.parallel import MeshFaultError, ResilienceConfig
from dpgo_tpu.parallel import resilience as resilience_mod
from dpgo_tpu.parallel.multihost import (EXIT_DESYNC, EXIT_PROCESS_LOST,
                                         MultihostWorld, WorldConfig,
                                         _classify, launch_world,
                                         shrink_world)


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


class FakeCoord:
    """In-memory stand-in for jax's coordination-service client: the KV
    store plus a barrier that can be armed to time out."""

    def __init__(self):
        self.kv = {}
        self.barrier_calls = []
        self.fail_barrier = False

    def key_value_set(self, key, value):
        self.kv[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        return self.kv[key]

    def wait_at_barrier(self, barrier_id, timeout_ms):
        self.barrier_calls.append((barrier_id, timeout_ms))
        if self.fail_barrier:
            raise RuntimeError("DEADLINE_EXCEEDED: barrier wait timed out")


def _world(rank=1, world_size=2, client=None, **kw):
    cfg = WorldConfig(coordinator="127.0.0.1:0", world_size=world_size,
                      rank=rank, **kw)
    return MultihostWorld(cfg, client=client if client is not None
                          else FakeCoord())


# ---------------------------------------------------------------------------
# WorldConfig / shrink / classifier
# ---------------------------------------------------------------------------

def test_world_config_validation():
    with pytest.raises(ValueError, match="world_size"):
        WorldConfig(coordinator="c", world_size=0, rank=0)
    with pytest.raises(ValueError, match="rank"):
        WorldConfig(coordinator="c", world_size=2, rank=2)
    with pytest.raises(ValueError, match="timeouts"):
        WorldConfig(coordinator="c", world_size=2, rank=0,
                    barrier_timeout_s=0.0)


def test_shrink_world_preserves_divisibility():
    # The next world must still divide the agent count (each rank's
    # local mesh partitions robots), exactly like a mesh shrink.
    assert shrink_world(4, 8) == 2
    assert shrink_world(2, 8) == 1
    assert shrink_world(3, 8) == 2


def test_exit_code_classifier():
    assert _classify(0) == "ok"
    assert _classify(EXIT_PROCESS_LOST) == "process_lost"
    assert _classify(EXIT_DESYNC) == "desync"
    assert _classify(-int(signal.SIGKILL)) == "signal:SIGKILL"
    assert _classify(3) == "crash:3"


# ---------------------------------------------------------------------------
# Verdict lockstep against the fake client
# ---------------------------------------------------------------------------

def test_single_process_world_syncs_without_a_client():
    w = _world(rank=0, world_size=1, client=None)
    w.client = None  # must never be consulted
    w.verdict_sync(4, 123)
    assert w.boundaries == 1 and w.desync_checks == 0


def test_verdict_sync_publishes_and_cross_checks():
    coord = FakeCoord()
    # Controller's word for boundary 0 is already in the KV store (the
    # barrier, passed, proves it would be).
    coord.kv["dpgo/mh/g0/s0/r0"] = "4:123"
    w = _world(rank=1, world_size=2, client=coord)
    w.verdict_sync(4, 123)
    assert coord.kv["dpgo/mh/g0/s0/r1"] == "4:123"
    assert w.boundaries == 1 and w.desync_checks == 1


def test_verdict_desync_is_a_structured_world_fault():
    coord = FakeCoord()
    coord.kv["dpgo/mh/g0/s0/r0"] = "4:999"  # controller disagrees
    w = _world(rank=1, world_size=2, client=coord)
    with pytest.raises(MeshFaultError) as ei:
        w.verdict_sync(4, 123)
    assert ei.value.kind == "desync"
    assert ei.value.phase == "verdict_sync"
    assert ei.value.kind in resilience_mod.WORLD_FAULT_KINDS


def test_barrier_timeout_reads_as_process_lost():
    coord = FakeCoord()
    coord.fail_barrier = True
    w = _world(rank=0, world_size=2, client=coord)
    with pytest.raises(MeshFaultError) as ei:
        w.verdict_sync(8, 5)
    assert ei.value.kind == "process_lost"
    assert ei.value.phase == "verdict_sync"
    assert w.boundaries == 0  # the boundary never completed


def test_first_boundary_gets_the_long_compile_skew_timeout():
    coord = FakeCoord()
    coord.kv["dpgo/mh/g0/s0/r0"] = "0:1"
    coord.kv["dpgo/mh/g0/s1/r0"] = "4:1"
    w = _world(rank=1, world_size=2, client=coord,
               barrier_timeout_s=7.0, first_barrier_timeout_s=120.0)
    w.verdict_sync(0, 1)
    w.verdict_sync(4, 1)
    timeouts = [ms for _, ms in coord.barrier_calls]
    assert timeouts == [120_000, 7_000]


def test_rank0_never_runs_the_desync_check():
    class NoGetCoord(FakeCoord):
        def blocking_key_value_get(self, key, timeout_ms):
            raise AssertionError("rank 0 must not wait on itself")

    w = _world(rank=0, world_size=2, client=NoGetCoord())
    w.verdict_sync(4, 7)
    assert w.boundaries == 1 and w.desync_checks == 0


def test_generation_scopes_the_keyspace():
    coord = FakeCoord()
    coord.kv["dpgo/mh/g3/s0/r0"] = "12:9"
    w = _world(rank=1, world_size=2, client=coord, generation=3)
    w.verdict_sync(12, 9)
    assert coord.kv["dpgo/mh/g3/s0/r1"] == "12:9"
    assert coord.barrier_calls[0][0] == "dpgo/mh/g3/b0"


# ---------------------------------------------------------------------------
# Fleet telemetry at the lockstep boundary (ISSUE 20)
# ---------------------------------------------------------------------------

def test_telemetry_off_keeps_the_kv_wire_byte_identical():
    """DPG005 symmetry of the boundary instrumentation: with no ambient
    run, verdict_sync writes EXACTLY the word keys — no clock-stamp
    c-keys, no extra barrier traffic."""
    coord = FakeCoord()
    coord.kv["dpgo/mh/g0/s0/r0"] = "4:123"
    w = _world(rank=1, world_size=2, client=coord)
    w.verdict_sync(4, 123)
    assert set(coord.kv) == {"dpgo/mh/g0/s0/r0", "dpgo/mh/g0/s0/r1"}
    assert len(coord.barrier_calls) == 1


def test_telemetry_on_stamps_and_samples_the_barrier(tmp_path):
    """With a run on, the boundary publishes its durable verdict_publish
    copy + a c-key clock stamp, times the barrier as a span, and pairs
    the controller's stamp into a clock_sample — all on its OWN key
    family, leaving the word protocol untouched."""
    import json as _json

    from dpgo_tpu.comms.protocol import mh_rank_actor

    coord = FakeCoord()
    coord.kv["dpgo/mh/g0/s0/r0"] = "4:123"
    coord.kv["dpgo/mh/g0/c0/r0"] = "12.5:1000.5"  # controller's stamp
    w = _world(rank=1, world_size=2, client=coord)
    with obs.run_scope(str(tmp_path / "r1")):
        w.verdict_sync(4, 123)
    assert coord.kv["dpgo/mh/g0/s0/r1"] == "4:123"
    mono, wall = map(float, coord.kv["dpgo/mh/g0/c0/r1"].split(":"))
    assert mono > 0 and wall > 0
    with open(tmp_path / "r1" / "events.jsonl") as fh:
        evs = [_json.loads(ln) for ln in fh if ln.strip()]
    (pub,) = [e for e in evs if e["event"] == "verdict_publish"]
    assert pub["word"] == 123 and pub["robot"] == mh_rank_actor(1)
    assert pub["key"] == "dpgo/mh/g0/s0/r1"
    (bw,) = [e for e in evs if e.get("name") == "barrier_wait"]
    assert bw["robot"] == mh_rank_actor(1) and bw["seq_boundary"] == 0
    (cs,) = [e for e in evs if e["event"] == "clock_sample"]
    assert cs["src"] == mh_rank_actor(0) and cs["t_send_mono"] == 12.5


def test_telemetry_on_survives_a_stampless_controller(tmp_path):
    """Mixed telemetry: a telemetry-off peer never writes its c-key; the
    telemetry-on rank's stamp read fails open and the boundary still
    completes."""
    class NoStampCoord(FakeCoord):
        def blocking_key_value_get(self, key, timeout_ms):
            if "/c" in key:
                raise RuntimeError("NOT_FOUND: no stamp")
            return self.kv[key]

    coord = NoStampCoord()
    coord.kv["dpgo/mh/g0/s0/r0"] = "4:123"
    w = _world(rank=1, world_size=2, client=coord)
    with obs.run_scope(str(tmp_path / "r1")):
        w.verdict_sync(4, 123)
    assert w.boundaries == 1


# ---------------------------------------------------------------------------
# World faults vs the checkpoint supervisor
# ---------------------------------------------------------------------------

def _supervisor(tmp_path, **cfg_kw):
    import types

    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path), **cfg_kw)
    graph = types.SimpleNamespace(global_index=np.arange(8))
    return resilience_mod.CheckpointSupervisor(
        cfg, cfg.resolve_store(), graph, session_id="mh")


def test_recover_reraises_world_faults(tmp_path):
    """A dead or diverged PEER cannot be rewound away in-process: the
    supervisor propagates the fault to the generation launcher instead
    of consuming a rewind."""
    sup = _supervisor(tmp_path)
    for kind in sorted(resilience_mod.WORLD_FAULT_KINDS):
        exc = MeshFaultError("peer gone", phase="verdict_sync", kind=kind)
        with pytest.raises(MeshFaultError):
            sup.recover(exc, mesh_size=2, num_robots=8)
    assert sup.recoveries == 0


def test_checkpoint_writer_gating(tmp_path, monkeypatch):
    """Only the controller rank persists checkpoints; reader ranks skip
    the save but still run the boundary bookkeeping."""
    from dpgo_tpu.models import rbcd

    clean = rbcd.pack_verdict(rbcd.VERDICT_RUNNING)
    saves = []
    reader = _supervisor(tmp_path, checkpoint_writer=False)
    monkeypatch.setattr(reader, "save",
                        lambda *a, **k: saves.append(("reader", a)))
    reader.boundary_cb(4, 1, state=None, word=clean, terminal=False)
    assert saves == []

    writer = _supervisor(tmp_path)  # checkpoint_writer defaults True
    monkeypatch.setattr(writer, "save",
                        lambda *a, **k: saves.append(("writer", a)))
    writer.boundary_cb(4, 1, state=None, word=clean, terminal=False)
    assert [who for who, _ in saves] == ["writer"]


# ---------------------------------------------------------------------------
# The real thing: worker processes joined by jax.distributed (slow)
# ---------------------------------------------------------------------------

_DEMO = dict(robots=8, mesh_size=2, n=40, num_lc=8, rounds=12,
             verdict_every=4, first_barrier_timeout_s=600.0)


def test_two_process_solve_matches_single_process(tmp_path):
    """Acceptance: the 2-process jax.distributed solve reproduces the
    single-process history at rtol 1e-6 (bit-identical on CPU — the
    lockstep is replicated determinism, not averaging) with
    ``host_syncs_per_100_rounds == 100/K`` unchanged."""
    ref = launch_world(1, workdir=str(tmp_path / "w1"), **_DEMO)
    two = launch_world(2, workdir=str(tmp_path / "w2"), **_DEMO)
    assert ref["world_sizes"] == [1] and two["world_sizes"] == [2]
    assert not two["recovered"]
    r1, r2 = ref["result"], two["result"]
    np.testing.assert_allclose(r2["cost_history"], r1["cost_history"],
                               rtol=1e-6)
    np.testing.assert_allclose(r2["grad_norm_history"],
                               r1["grad_norm_history"], rtol=1e-6)
    # One host sync per K rounds — the lockstep rides words the driver
    # already fetched, adding ZERO device syncs.
    assert r2["host_syncs_per_100_rounds"] == pytest.approx(100.0 / 4)
    assert r2["host_syncs_per_100_rounds"] == \
        pytest.approx(r1["host_syncs_per_100_rounds"])
    assert r2["boundaries"] == _DEMO["rounds"] // _DEMO["verdict_every"]
    assert r2["desync_checks"] == 0  # the controller record is rank 0's


def test_kill9_worker_recovers_on_shrunken_world(tmp_path):
    """Acceptance: an ACTUAL ``kill -9`` of a worker mid-solve.  The
    survivor's barrier times out into a structured ``process_lost``
    fault, the launcher respawns a shrunken generation, and the resumed
    solve continues from the last v2 checkpoint to a final cost within
    1% of the fault-free reference."""
    kw = dict(_DEMO, rounds=24)
    ref = launch_world(1, workdir=str(tmp_path / "ref"), **kw)
    chaos = launch_world(2, workdir=str(tmp_path / "chaos"),
                         kill_rank=1, kill_at_boundary=3,
                         barrier_timeout_s=10.0,
                         telemetry_dir=str(tmp_path / "tel"), **kw)
    assert chaos["recovered"] is True
    assert chaos["world_sizes"] == [2, 1]
    # ISSUE 20 acceptance: the kill demo yields ONE schema-valid merged
    # Chrome trace spanning launcher + both ranks + the respawned
    # generation, with the kill as a process_lost instant on the
    # victim's own track and the victim's harvested tail in the
    # generation_postmortem.
    tel = chaos["telemetry"]
    assert "error" not in tel, tel
    assert tel["streams"] == 4  # launcher + g0 r0/r1 + g1 r0
    # Pid bands: launcher (200) + one track per RANK (300/301) — the
    # respawned generation-1 rank 0 continues on rank 0's track, its
    # presence visible as a second worker_boot span with generation 1.
    assert tel["spans"] > 0 and tel["pids"] == 3
    import json as _json

    with open(tel["trace"]) as fh:
        trace = _json.load(fh)
    lost = [e for e in trace["traceEvents"]
            if e.get("ph") == "i" and e["name"] == "process_lost"]
    assert lost and all(e["pid"] == 301 for e in lost)  # rank 1's track
    boots = [e for e in trace["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "worker_boot"]
    assert {b["args"].get("generation") for b in boots
            if b["pid"] == 300} == {0, 1}
    with open(tmp_path / "tel" / "launcher" / "events.jsonl") as fh:
        levs = [_json.loads(ln) for ln in fh if ln.strip()]
    pms = [e for e in levs if e["event"] == "generation_postmortem"]
    assert len(pms) == 2  # one per generation
    victim = pms[0]["ranks"]["1"]
    assert victim["outcome"] == "signal:SIGKILL"
    assert victim["events"] > 0 and victim["tail"]
    assert victim["last_verdict"] is not None
    # Clock alignment found a bidirectional path to every rank stream.
    assert all(s["aligned"] for s in tel["clock"]["streams"])
    gen0 = chaos["generations"][0]
    assert "signal:SIGKILL" in gen0["outcomes"]  # the victim
    assert "process_lost" in gen0["outcomes"]    # the survivor
    faults = gen0["faults"]
    assert faults and all(f["kind"] == "process_lost"
                          and f["phase"] == "verdict_sync" for f in faults)
    res = chaos["result"]
    # Telemetry + harvest on must not add device syncs: the KV clock
    # stamps ride the coordination service, not the device.
    assert res["host_syncs_per_100_rounds"] == \
        pytest.approx(100.0 / kw["verdict_every"])
    # The victim died at boundary 3 = iteration K*3; generation 1
    # resumed from the controller's checkpoint there, not from zero.
    assert res["resumed"] is True
    assert res["resume_iteration"] == 3 * kw["verdict_every"]
    assert res["iterations"] == kw["rounds"]
    ref_cost = ref["result"]["final_cost"]
    assert abs(res["final_cost"] - ref_cost) <= 1e-2 * abs(ref_cost)
    # The resumed history is the fault-free trajectory's suffix.
    nsuf = len(res["cost_history"])
    np.testing.assert_allclose(
        res["cost_history"], ref["result"]["cost_history"][-nsuf:],
        rtol=1e-6)
