"""Round-trip tests for the CSV logger / checkpoint subsystem.

Mirrors the reference's use of ``PGOLogger`` (write at ``PGOAgent.cpp:583-603``,
load for warm restart via ``PGOLogger.cpp:83-225``).
"""

import numpy as np
import pytest

from dpgo_tpu.utils import logger
from dpgo_tpu.utils.lie import rotation2d
from dpgo_tpu.utils.synthetic import make_measurements


def random_rotations(rng, n):
    A = rng.normal(size=(n, 3, 3))
    U, _, Vt = np.linalg.svd(A)
    R = U @ Vt
    det = np.linalg.det(R)
    U[:, :, -1] *= np.sign(det)[:, None]
    return U @ Vt


def test_trajectory_roundtrip_3d(tmp_path):
    rng = np.random.default_rng(0)
    n = 17
    T = np.zeros((n, 3, 4))
    T[:, :, :3] = random_rotations(rng, n)
    T[:, :, 3] = rng.normal(size=(n, 3))
    path = str(tmp_path / "trajectory.csv")
    logger.log_trajectory(T, path)
    with open(path) as f:
        assert f.readline().strip() == logger.TRAJECTORY_HEADER
    T2 = logger.load_trajectory(path)
    np.testing.assert_allclose(T2, T, atol=1e-12)


def test_trajectory_roundtrip_2d(tmp_path):
    rng = np.random.default_rng(1)
    n = 9
    T = np.zeros((n, 2, 3))
    T[:, :, :2] = rotation2d(rng.uniform(-np.pi, np.pi, size=n))
    T[:, :, 2] = rng.normal(size=(n, 2))
    path = str(tmp_path / "trajectory2d.csv")
    logger.log_trajectory(T, path)
    T2 = logger.load_trajectory(path, d=2)
    np.testing.assert_allclose(T2, T, atol=1e-12)


def test_measurements_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    meas, _ = make_measurements(rng, n=12, d=3, num_lc=6)
    meas.weight[:] = rng.uniform(0, 1, size=len(meas))
    meas.is_known_inlier[::3] = True
    path = str(tmp_path / "measurements.csv")
    logger.log_measurements(meas, path)
    with open(path) as f:
        assert f.readline().strip() == logger.MEASUREMENT_HEADER

    out = logger.load_measurements(path)
    np.testing.assert_array_equal(out.r1, meas.r1)
    np.testing.assert_array_equal(out.p1, meas.p1)
    np.testing.assert_array_equal(out.r2, meas.r2)
    np.testing.assert_array_equal(out.p2, meas.p2)
    np.testing.assert_allclose(out.R, meas.R, atol=1e-12)
    np.testing.assert_allclose(out.t, meas.t, atol=1e-12)
    np.testing.assert_allclose(out.kappa, meas.kappa, atol=1e-12)
    np.testing.assert_allclose(out.tau, meas.tau, atol=1e-12)
    np.testing.assert_allclose(out.weight, meas.weight, atol=1e-12)
    np.testing.assert_array_equal(out.is_known_inlier, meas.is_known_inlier)

    # load_weight=False resets GNC weights (PGOLogger.cpp:148, 217-218)
    fresh = logger.load_measurements(path, load_weight=False)
    np.testing.assert_array_equal(fresh.weight, np.ones(len(meas)))


def test_measurements_roundtrip_2d(tmp_path):
    rng = np.random.default_rng(3)
    meas, _ = make_measurements(rng, n=10, d=2, num_lc=4)
    path = str(tmp_path / "m2d.csv")
    logger.log_measurements(meas, path)
    out = logger.load_measurements(path, d=2)
    np.testing.assert_allclose(out.R, meas.R, atol=1e-12)
    np.testing.assert_allclose(out.t, meas.t, atol=1e-12)


def test_matrix_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(7, 5, 4))
    path = str(tmp_path / "X.txt")
    logger.save_matrix(X, path)
    X2 = logger.load_matrix(path, shape=X.shape)
    np.testing.assert_allclose(X2, X, atol=1e-14)


def test_checkpoint_roundtrip(tmp_path):
    rng = np.random.default_rng(5)
    ckpt = logger.Checkpoint(
        X=rng.normal(size=(3, 5, 10, 4)),
        weights=rng.uniform(0, 1, size=(3, 20)),
        mu=0.125,
        iteration=42,
    )
    logger.save_checkpoint(ckpt, str(tmp_path / "ckpt"))
    out = logger.load_checkpoint(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(out.X, ckpt.X)
    np.testing.assert_allclose(out.weights, ckpt.weights)
    assert out.mu == ckpt.mu
    assert out.iteration == ckpt.iteration


def test_checkpoint_resume_matches_uninterrupted(rng, tmp_path):
    """The checkpoint/resume contract end to end: a robust RBCD solve
    checkpointed mid-GNC and resumed into a fresh state (X, weights, mu,
    iteration + refresh_problem for the carried factors) must continue
    exactly like the uninterrupted solve."""
    import jax.numpy as jnp

    from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous

    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10, outlier_lc=3,
                                rot_noise=0.01, trans_noise=0.01)
    params = AgentParams(
        d=3, r=5, num_robots=4,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=0.5),
        robust_opt_inner_iters=10)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)

    def step_to(state, start, stop):
        for it in range(start, stop):
            uw = (it + 1) % params.robust_opt_inner_iters == 0
            state = rbcd.rbcd_step(state, graph, meta, params,
                                   update_weights=uw)
        return state

    # Uninterrupted run to round 40, checkpointing at 25.
    state = rbcd.init_state(graph, meta, X0, params=params)
    state = step_to(state, 0, 25)
    ckpt = logger.Checkpoint(X=np.asarray(state.X),
                             weights=np.asarray(state.weights),
                             mu=float(state.mu),
                             iteration=int(state.iteration))
    logger.save_checkpoint(ckpt, str(tmp_path))
    full = step_to(state, 25, 40)

    # Fresh process: rebuild the problem, load, resume.
    loaded = logger.load_checkpoint(str(tmp_path))
    resumed = rbcd.init_state(graph, meta, X0, params=params)
    resumed = resumed._replace(
        X=jnp.asarray(loaded.X), weights=jnp.asarray(loaded.weights),
        mu=jnp.asarray(loaded.mu, jnp.float64),
        iteration=jnp.asarray(loaded.iteration, jnp.int32))
    resumed = rbcd.refresh_problem(resumed, graph, meta, params)
    resumed = step_to(resumed, 25, 40)

    assert int(resumed.iteration) == int(full.iteration) == 40
    np.testing.assert_allclose(np.asarray(resumed.X), np.asarray(full.X),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(resumed.weights),
                               np.asarray(full.weights), atol=1e-12)
    assert np.isclose(float(resumed.mu), float(full.mu))


def test_orbax_checkpoint_roundtrip(rng, tmp_path):
    """The Orbax backend stores the same Checkpoint contents (atomic
    directory commit, sharding-aware restore for multi-host runs)."""
    pytest.importorskip("orbax.checkpoint")
    ckpt = logger.Checkpoint(
        X=rng.standard_normal((3, 6, 5, 4)),
        weights=rng.uniform(size=(3, 9)),
        mu=0.014,
        iteration=123,
    )
    logger.save_checkpoint_orbax(ckpt, str(tmp_path / "ocp"))
    out = logger.load_checkpoint_orbax(str(tmp_path / "ocp"))
    np.testing.assert_allclose(out.X, ckpt.X)
    np.testing.assert_allclose(out.weights, ckpt.weights)
    assert out.mu == ckpt.mu
    assert out.iteration == ckpt.iteration


def test_orbax_checkpoint_restore_with_target(rng, tmp_path):
    """Restoring against an abstract target (the sharding-aware path)."""
    pytest.importorskip("orbax.checkpoint")
    ckpt = logger.Checkpoint(
        X=rng.standard_normal((2, 5, 5, 4)),
        weights=rng.uniform(size=(2, 6)),
        mu=2e-3,
        iteration=9,
    )
    logger.save_checkpoint_orbax(ckpt, str(tmp_path / "ocp"))
    out = logger.load_checkpoint_orbax(str(tmp_path / "ocp"), like=ckpt)
    np.testing.assert_allclose(out.X, ckpt.X)
    np.testing.assert_allclose(out.weights, ckpt.weights)
    assert out.mu == ckpt.mu and out.iteration == ckpt.iteration
