"""pytest plugins for the test suite (loaded via ``-p``, e.g.
``-p tests.plugins.leakcheck``)."""
