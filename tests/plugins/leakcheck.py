"""Runtime leak detector: no test leaves threads or sockets behind.

The serving plane, comms bus, overlap workers, and HTTP sidecar all
spawn background machinery; a test that forgets to close them poisons
every later test in the process (ports stay bound, worker threads keep
polling dead queues, and the failure shows up three files away).  This
plugin makes the leak fail the *offending* test:

* **threads** — any live **non-daemon** thread that appeared during the
  test and survives a short grace period;
* **sockets** — any ``socket.socket`` constructed during the test that
  is still open (``fileno() != -1``) after teardown and garbage
  collection (sockets are tracked via a constructor shim installed at
  ``pytest_configure``; closing in a ``finally``/``close()`` path — the
  contract this enforces — passes).

Scope: non-``slow`` tests only (the tier-1 set; slow/deployment tests
spawn real multi-process fleets with their own teardown story), and a
test may opt out explicitly with ``@pytest.mark.allow_leaks`` plus a
reason in the marker args.

Activate with ``-p tests.plugins.leakcheck`` (the tier-1 CI command
does).
"""

from __future__ import annotations

import gc
import socket
import threading
import time
import weakref

import pytest

_GRACE_S = 1.5          # wind-down allowance for naturally-exiting threads
_POLL_S = 0.05

_tracked_sockets: "weakref.WeakSet[socket.socket]" = weakref.WeakSet()
_orig_socket_init = socket.socket.__init__


def _tracking_init(self, *args, **kwargs):
    _orig_socket_init(self, *args, **kwargs)
    try:
        _tracked_sockets.add(self)
    except TypeError:  # exotic subclasses without weakref support
        pass


class LeakError(AssertionError):
    """A test left live threads or open sockets behind."""


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "allow_leaks(reason): exempt this test from the leakcheck "
        "thread/socket assertions (say why)")
    socket.socket.__init__ = _tracking_init


def pytest_unconfigure(config):
    socket.socket.__init__ = _orig_socket_init


def _open_sockets() -> set:
    out = set()
    for s in list(_tracked_sockets):
        try:
            if s.fileno() != -1:
                out.add(s)
        except Exception:
            pass
    return out


def _live_nondaemon_threads() -> set:
    return {t for t in threading.enumerate()
            if t.is_alive() and not t.daemon}


def _sock_desc(s: socket.socket) -> str:
    try:
        laddr = s.getsockname()
    except Exception:
        laddr = "?"
    return f"fd={s.fileno()} laddr={laddr}"


def _enforced(item) -> bool:
    if item.get_closest_marker("slow") is not None:
        return False
    if item.get_closest_marker("allow_leaks") is not None:
        return False
    return True


@pytest.hookimpl(wrapper=True)
def pytest_runtest_protocol(item, nextitem):
    """Snapshot before setup, verify after teardown — fixtures get their
    full teardown window to close what they opened."""
    if not _enforced(item):
        return (yield)
    threads_before = _live_nondaemon_threads()
    socks_before = _open_sockets()
    result = yield

    # Grace: a cleanly-stopping thread may still be mid-exit, and a
    # dropped-reference socket may await collection.
    deadline = time.monotonic() + _GRACE_S
    leaked_threads = leaked_socks = None
    while time.monotonic() < deadline:
        gc.collect()
        leaked_threads = _live_nondaemon_threads() - threads_before
        leaked_socks = _open_sockets() - socks_before
        if not leaked_threads and not leaked_socks:
            break
        time.sleep(_POLL_S)

    if leaked_threads or leaked_socks:
        parts = []
        if leaked_threads:
            parts.append("non-daemon threads still alive: " + ", ".join(
                sorted(t.name for t in leaked_threads)))
        if leaked_socks:
            parts.append("sockets still open: " + "; ".join(
                sorted(_sock_desc(s) for s in leaked_socks)))
        msg = (f"leakcheck: {item.nodeid} leaked {' | '.join(parts)} — "
               "close servers/transports/sidecars in a finally/with, or "
               "mark the test @pytest.mark.allow_leaks(reason=...)")
        item.ihook.pytest_runtest_logreport(report=_leak_report(item, msg))
        # Leave the tracked sets clean for the NEXT test: what leaked here
        # must not be double-reported downstream.
        return result
    return result


def _leak_report(item, msg: str):
    """An extra failed report for the leaking test, attributed to a
    dedicated 'leakcheck' phase so it cannot be mistaken for the test's
    own assertion."""
    from _pytest.reports import TestReport

    return TestReport(
        nodeid=item.nodeid,
        location=item.location,
        keywords={k: 1 for k in item.keywords},
        outcome="failed",
        longrepr=msg,
        when="teardown",
        sections=[],
        duration=0.0,
        start=time.time(),
        stop=time.time(),
    )
