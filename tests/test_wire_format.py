"""The deployment fast path: packed wire format v2 (round-trip, corruption,
bf16 parity, npz interop), the agent's slot-indexed neighbor buffer
(vectorized scatter vs. the per-pose dict vocabulary on a golden graph),
the packed publish/ingest fast path, and the overlapped bus client."""

import struct
import threading
import time

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.agent import AgentState, PGOAgent
from dpgo_tpu.comms import (BF16_REL_ERR, PACKED_MAGIC, LoopbackTransport,
                            ProtocolError, RetryPolicy,
                            bf16_decode, bf16_encode, loopback_fleet,
                            pack_agent_frame, apply_peer_frame)
from dpgo_tpu.comms.protocol import (decode_payload,
                                     decode_payload_packed, encode_payload,
                                     pack_pose_dict,
                                     pack_pose_set, pose_payload_nbytes,
                                     unpack_pose_arrays,
                                     unpack_pose_set)
from dpgo_tpu.config import AgentParams
from dpgo_tpu.utils.partition import agent_measurements, partition_contiguous
from dpgo_tpu.utils.synthetic import make_measurements


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _vocab_frame():
    """A frame exercising every dtype the agent vocabulary ships."""
    rng = np.random.default_rng(0)
    return {
        "_seq": np.asarray(7, np.int64),
        "_kind": np.asarray("data"),
        "status": np.arange(5, dtype=np.int64),
        "relchange": np.asarray(0.25),
        "pose:r": np.zeros(3, np.int32),
        "pose:p": np.arange(3, dtype=np.int32),
        "pose:x": rng.standard_normal((3, 5, 4)),
        "anchor": rng.standard_normal((5, 4)).astype(np.float32),
        "_lost": np.zeros(0, np.int64),
        "flag": np.asarray(True),
    }


# ---------------------------------------------------------------------------
# Packed codec
# ---------------------------------------------------------------------------

def test_packed_roundtrip_matches_npz():
    frame = _vocab_frame()
    packed = decode_payload(encode_payload(frame, "packed"))
    npz = decode_payload(encode_payload(frame, "npz"))
    assert set(packed) == set(npz) == set(frame)
    for k in frame:
        np.testing.assert_array_equal(np.asarray(packed[k]),
                                      np.asarray(npz[k]))
        assert np.asarray(packed[k]).dtype == np.asarray(frame[k]).dtype
        assert np.asarray(packed[k]).shape == np.asarray(frame[k]).shape


def test_packed_is_smaller_than_npz_on_pose_frames():
    rng = np.random.default_rng(1)
    pose_dict = {(0, p): rng.standard_normal((5, 4)) for p in range(40)}
    v2 = encode_payload(pack_pose_set("pose", pose_dict), "packed")
    v1 = encode_payload(pack_pose_dict("pose", pose_dict), "npz")
    # The acceptance bar is >= 2x fewer wire bytes per round in f32; the
    # f64 payload alone already clears 2x (npz zip members cost ~hundreds
    # of bytes per pose block).
    assert len(v1) / len(v2) >= 2.0


def test_packed_corruption_and_truncation_raise_protocol_error():
    data = encode_payload(_vocab_frame(), "packed")
    assert data[:4] == PACKED_MAGIC
    # Bit flips anywhere in the body fail the CRC.
    for pos in (5, len(data) // 2, len(data) - 3):
        bad = bytearray(data)
        bad[pos] ^= 0xFF
        with pytest.raises(ProtocolError):
            decode_payload(bytes(bad))
    # Truncation at every region boundary dies cleanly.
    for cut in (2, 6, 11, len(data) // 2, len(data) - 1):
        with pytest.raises(ProtocolError):
            decode_payload_packed(data[:cut])
    # An entry header lying about its size is caught before allocation.
    with pytest.raises(ProtocolError):
        decode_payload_packed(PACKED_MAGIC + struct.pack("<II", 0, 5))


def test_decode_sniffs_format_both_ways():
    """Old/new peer interop: one receiver decodes both encodings."""
    frame = {"v": np.arange(4.0)}
    for fmt in ("packed", "npz"):
        out = decode_payload(encode_payload(frame, fmt))
        np.testing.assert_array_equal(out["v"], frame["v"])
    with pytest.raises(ValueError):
        encode_payload(frame, "protobuf")


def test_mixed_wire_transport_pair_interoperates():
    """A packed sender and an npz sender share one link: each end decodes
    whatever arrives (the rolling-upgrade scenario)."""
    a, b = LoopbackTransport.pair(wire_format="packed")
    b.wire_format = "npz"  # old peer: still sends v1
    a.send({"v": np.asarray(1)})
    assert int(b.recv(timeout=1.0)["v"]) == 1
    b.send({"v": np.asarray(2)})
    assert int(a.recv(timeout=1.0)["v"]) == 2


# ---------------------------------------------------------------------------
# bf16 wire dtype
# ---------------------------------------------------------------------------

def test_bf16_roundtrip_parity_bound():
    rng = np.random.default_rng(2)
    x = rng.standard_normal(4096) * np.exp(rng.uniform(-8, 8, 4096))
    rt = bf16_decode(bf16_encode(x))
    rel = np.abs(rt - x) / np.abs(x)
    assert rel.max() <= BF16_REL_ERR
    # Exact values representable in bf16 survive unchanged.
    exact = np.asarray([0.0, 1.0, -2.0, 0.5, 384.0])
    np.testing.assert_array_equal(bf16_decode(bf16_encode(exact)), exact)


def test_bf16_pose_set_halves_f32_bytes_and_accumulates_f64():
    rng = np.random.default_rng(3)
    pose_dict = {(1, p): rng.standard_normal((5, 4)) for p in range(8)}
    f32 = pack_pose_set("pose", pose_dict, wire_dtype="f32")
    b16 = pack_pose_set("pose", pose_dict, wire_dtype="bf16")
    assert pose_payload_nbytes(b16, "pose") < pose_payload_nbytes(f32, "pose")
    assert b16["pose:xb"].dtype == np.uint16
    robots, poses, vals = unpack_pose_arrays(b16, "pose")
    assert vals.dtype == np.float64  # f32-widened, f64-accumulated
    for i, (r, p) in enumerate(zip(robots, poses)):
        ref = pose_dict[(int(r), int(p))]
        rel = np.abs(vals[i] - ref) / np.maximum(np.abs(ref), 1e-12)
        assert rel.max() <= BF16_REL_ERR + 1e-7


# ---------------------------------------------------------------------------
# Pose vocabulary equivalence
# ---------------------------------------------------------------------------

def test_pose_set_roundtrip_matches_v1_dict():
    rng = np.random.default_rng(4)
    pose_dict = {(2, 11): rng.standard_normal((5, 4)),
                 (0, 3): rng.standard_normal((5, 4))}
    via_v2 = unpack_pose_set(
        decode_payload(encode_payload(pack_pose_set("pose", pose_dict))),
        "pose")
    via_v1 = unpack_pose_set(
        decode_payload(encode_payload(pack_pose_dict("pose", pose_dict),
                                      "npz")), "pose")
    assert set(via_v2) == set(via_v1) == set(pose_dict)
    for k in pose_dict:
        np.testing.assert_allclose(via_v2[k], pose_dict[k])
        np.testing.assert_allclose(via_v1[k], pose_dict[k])
    assert pack_pose_set("pose", {}) == {}
    assert unpack_pose_arrays({"other": np.zeros(1)}, "pose") is None


# ---------------------------------------------------------------------------
# Agent neighbor buffer: vectorized scatter vs the dict path (golden graph)
# ---------------------------------------------------------------------------

def _golden_agents(num_robots=3, n=18, num_lc=12, seed=0):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.005, trans_noise=0.005)
    part = partition_contiguous(meas, num_robots)
    params = AgentParams(d=3, r=5, num_robots=num_robots)
    agents = [PGOAgent(a, params) for a in range(num_robots)]
    for ag in agents[1:]:
        ag.set_lifting_matrix(agents[0].get_lifting_matrix())
    for ag in agents:
        ag.set_pose_graph(*agent_measurements(part, ag.robot_id))
    return agents


def test_packed_scatter_matches_dict_path_on_golden_graph():
    """The same neighbor poses delivered (a) as per-pose dicts and (b) as
    packed index/value arrays must produce identical neighbor buffers,
    identical initialization, and identical iterates."""
    agents_a = _golden_agents()
    agents_b = _golden_agents()
    for _ in range(3):
        dicts = [ag.get_shared_pose_dict() for ag in agents_a]
        for src in range(len(agents_a)):
            for dst in range(len(agents_a)):
                if src == dst:
                    continue
                # Arm A: v1 dict vocabulary.
                agents_a[dst].update_neighbor_poses(src, dicts[src])
                # Arm B: packed arrays of the SAME payload (an
                # uninitialized sender publishes an empty set).
                keys = list(dicts[src])
                robots = np.asarray([k[0] for k in keys], np.int64)
                poses = np.asarray([k[1] for k in keys], np.int64)
                vals = np.stack([dicts[src][k] for k in keys]) if keys \
                    else np.zeros((0, 5, 4))
                agents_b[dst].update_neighbor_poses_packed(
                    src, robots, poses, vals)
            st = agents_a[src].get_status()
            for dst in range(len(agents_a)):
                if src != dst:
                    agents_a[dst].set_neighbor_status(st)
                    agents_b[dst].set_neighbor_status(
                        agents_b[src].get_status())
        for ag_a, ag_b in zip(agents_a, agents_b):
            ag_a.iterate(True)
            ag_b.iterate(True)
    for ag_a, ag_b in zip(agents_a, agents_b):
        assert ag_a.get_status().state == AgentState.INITIALIZED
        assert ag_b.get_status().state == AgentState.INITIALIZED
        za = ag_a._neighbor_buffer()
        zb = ag_b._neighbor_buffer()
        assert za is not None and zb is not None
        np.testing.assert_array_equal(np.asarray(za), np.asarray(zb))
        np.testing.assert_allclose(ag_a.X, ag_b.X, atol=1e-12)
        # The dict-compat view agrees with the buffer.
        for key, blk in ag_a._neighbor_poses.items():
            np.testing.assert_array_equal(ag_b._nbr_lookup(key), blk)


def test_scatter_ignores_unknown_keys_and_partial_frames():
    agents = _golden_agents()
    ag = agents[0]
    s_before = ag._nbr_have.copy()
    # Keys this agent never references scatter to nothing.
    ag.update_neighbor_poses_packed(
        1, np.asarray([1, 9]), np.asarray([997, 998]),
        np.zeros((2, 5, 4)))
    np.testing.assert_array_equal(ag._nbr_have, s_before)
    # A partial frame fills only its slots; the buffer is still incomplete.
    (key, slot) = next(iter(ag._nbr_slot.items()))
    ag.update_neighbor_poses_packed(
        key[0], np.asarray([key[0]]), np.asarray([key[1]]),
        np.full((1, 5, 4), 3.25))
    assert ag._nbr_have[slot]
    if not ag._nbr_have.all():
        assert ag._neighbor_buffer() is None
    np.testing.assert_array_equal(ag._nbr_lookup(key),
                                  np.full((5, 4), 3.25))


def test_public_pose_arrays_match_shared_pose_dict():
    agents = _golden_agents()
    for ag in agents:
        if ag.get_status().state != AgentState.INITIALIZED:
            continue
        pub = ag.get_public_pose_arrays()
        d = ag.get_shared_pose_dict()
        assert pub is not None
        robots, poses, vals = pub
        assert robots.dtype == np.int32 and poses.dtype == np.int32
        assert len(robots) == len(d)
        for i, (r, p) in enumerate(zip(robots, poses)):
            np.testing.assert_array_equal(vals[i], d[(int(r), int(p))])
    # Uninitialized agents return None (nothing to publish).
    fresh = PGOAgent(1, AgentParams(d=3, r=5, num_robots=2))
    assert fresh.get_public_pose_arrays() is None


def test_packed_agent_frame_roundtrip_equivalent_to_v1():
    """pack_agent_frame(packed) -> wire -> apply_peer_frame lands the same
    state as the v1 frame, including sequence-stamped stale drops."""
    agents_a = _golden_agents(seed=5)
    agents_b = _golden_agents(seed=5)
    src_a, dst_a = agents_a[0], agents_a[1]
    src_b, dst_b = agents_b[0], agents_b[1]
    for packed, (src, dst) in ((False, (src_a, dst_a)),
                               (True, (src_b, dst_b))):
        frame = pack_agent_frame(src, include_anchor=True, packed=packed)
        wire = decode_payload(encode_payload(frame))
        wire["_pseq"] = np.asarray(4, np.int64)
        dst.set_neighbor_status(src.get_status())
        apply_peer_frame(dst, 0, wire, accept_anchor=True)
    assert dst_a.get_status().state == dst_b.get_status().state
    za, zb = dst_a._neighbor_poses, dst_b._neighbor_poses
    assert set(za) == set(zb) and len(za) > 0
    for k in za:
        np.testing.assert_array_equal(za[k], zb[k])
    # Stale packed frame (same sequence) must not roll the cache back.
    frame = pack_agent_frame(src_b, packed=True)
    wire = decode_payload(encode_payload(frame))
    wire["pose:x"] = np.zeros_like(wire["pose:x"])
    wire["_pseq"] = np.asarray(4, np.int64)
    apply_peer_frame(dst_b, 0, wire)
    for k in zb:
        np.testing.assert_array_equal(dst_b._neighbor_poses[k], zb[k])


# ---------------------------------------------------------------------------
# Overlapped bus client
# ---------------------------------------------------------------------------

FAST = RetryPolicy(max_attempts=3, base_delay_s=0.005, max_delay_s=0.02,
                   send_timeout_s=1.0, recv_timeout_s=1.0)


def test_overlap_client_bounded_staleness_and_drain():
    bus, clients = loopback_fleet(2, policy=FAST, round_timeout_s=1.0)
    stop = threading.Event()

    def bus_loop():
        while not stop.is_set():
            bus.round()

    t = threading.Thread(target=bus_loop, daemon=True)
    t.start()
    try:
        for c in clients.values():
            c.start_overlap(staleness=1, timeout=1.0)

        def robot(rid, log):
            c = clients[rid]
            for it in range(6):
                merged = c.exchange({"v": np.asarray(it)}, timeout=1.0)
                lag = c._ov_submitted - c._ov_done
                assert lag <= 1 + 1  # bound: staleness + the one in flight
                log.append(merged)
            c.drain_overlap(timeout=10.0)

        logs = [[], []]
        rts = [threading.Thread(target=robot, args=(r, logs[r]))
               for r in range(2)]
        for rt in rts:
            rt.start()
        for rt in rts:
            rt.join(timeout=30)
        for rid in (0, 1):
            # After draining, every submitted exchange completed.
            assert clients[rid]._ov_submitted == clients[rid]._ov_done
            # The final broadcast carries the peer's late-round value.
            final = clients[rid].drain_overlap()
            peer = 1 - rid
            assert final is not None
            assert int(final[f"r{peer}|v"]) >= 3
    finally:
        stop.set()
        for c in clients.values():
            c.close()
        bus.close()
        t.join(timeout=5)


def test_overlap_staleness_zero_is_lockstep():
    bus, clients = loopback_fleet(2, policy=FAST, round_timeout_s=1.0)
    for c in clients.values():
        c.start_overlap(staleness=0)  # no thread: exchange == lockstep
        assert c._ov_thread is None
    for c in clients.values():
        c.publish({"v": np.asarray(1)})
    bus.round()
    for c in clients.values():
        got = c.collect(timeout=1.0)
        assert got is not None
    bus.close()
    for c in clients.values():
        c.close()


def test_overlap_surfaces_transport_closed():
    from dpgo_tpu.comms import TransportClosed

    bus, clients = loopback_fleet(1, policy=FAST, round_timeout_s=0.3)
    c = clients[0]
    c.start_overlap(staleness=1, timeout=0.3)
    bus.close()  # the hub dies
    with pytest.raises(TransportClosed):
        for _ in range(50):
            c.exchange({"v": np.asarray(0)}, timeout=0.3)
            time.sleep(0.01)
    c.close()
