"""Re-centered terminal refinement (models.refine): f64-grade gaps from
f32 device arithmetic.

The load-bearing property is numerical: the re-centered gradient and
delta-cost evaluated in f32 must match the direct f64 evaluation with an
error that scales with |D| (the correction magnitude), not with the large
gradient/cost magnitudes — that scaling is what dissolves the f32 floor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import AgentParams, SolverParams
from dpgo_tpu.models import rbcd, refine
from dpgo_tpu.ops import manifold, quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements


def _problem(rng, n=40, A=3, r=5, rounds=50, pallas=False):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=n // 2,
                                rot_noise=0.02, trans_noise=0.02)
    # Tight local tolerance: refinement operates past the reference's 1e-2
    # per-step budget (same setting as bench_convergence.py).
    params = AgentParams(d=3, r=r, num_robots=A, rel_change_tol=0.0,
                         solver=SolverParams(grad_norm_tol=1e-12,
                                             max_inner_iters=10))
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, jnp.float32, pallas_sel=pallas)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    state = rbcd.init_state(graph, meta, X0, params=params)
    state = rbcd.rbcd_steps(state, graph, rounds, meta, params)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float32)
    Xg = np.asarray(rbcd.gather_to_global(state.X, graph, meas.num_poses),
                    np.float64)
    return meas, part, graph, meta, params, edges_g, Xg


def _f64_buffers(Xg64, graph):
    gi = np.asarray(graph.global_index)
    R_loc = Xg64[gi]
    pub = np.take_along_axis(
        R_loc, np.asarray(graph.pub_idx)[:, :, None, None], axis=1)
    Rz = pub[np.asarray(graph.nbr_robot), np.asarray(graph.nbr_pub)] \
        * np.asarray(graph.nbr_mask)[:, :, None, None]
    return R_loc, Rz


def test_recentered_gradient_error_scales_with_d(rng):
    """f32 re-centered rgrad vs f64 direct: error must drop with |D| while
    the naive f32 evaluation's error stays at the eps*|G| floor."""
    meas, part, graph, meta, params, edges_g, Xg = _problem(rng)
    ref = refine.recenter(Xg, graph, meta, params, edges_g)
    # The f64 comparison point must be the recenter's own (projected)
    # reference — comparing at the pre-projection iterate shifts the true
    # gradient by ~|Hess| * projection-delta, a constant offset.
    R_loc64, Rz64 = _f64_buffers(ref.Xg, graph)
    d, n = meta.d, meta.n_max
    a = 0
    e_a = jax.tree.map(lambda t: t[a], graph.edges)
    errs = {}
    for scale in (1e-3, 1e-5):
        Dn = rng.standard_normal(ref.consts.R.shape) * scale
        D32 = jnp.asarray(Dn, jnp.float32)
        Dz32 = rbcd.neighbor_buffer(rbcd.public_table(D32, graph), graph)
        # f32 re-centered gradient (the _agent_refine formula).
        ca = jax.tree.map(lambda x: x[a], ref.consts)
        Dbuf = jnp.concatenate([D32[a], Dz32[a]], axis=0)
        dG = quadratic.egrad(Dbuf, e_a, n_out=n)
        Y = ca.R + D32[a]
        S1 = manifold.sym(
            jnp.swapaxes(D32[a][..., :d], -1, -2) @ ca.G_ref[..., :d]
            + jnp.swapaxes(Y[..., :d], -1, -2) @ dG[..., :d])
        g32 = (ca.g0 + dG).at[..., :d].add(
            -(ca.R[..., :d] @ S1) - D32[a][..., :d] @ (ca.S0 + S1))
        # f64 direct.
        Y64 = jnp.asarray(R_loc64[a] + Dn[a], jnp.float64)
        buf64 = jnp.concatenate(
            [Y64, jnp.asarray(Rz64[a] + np.asarray(Dz32[a], np.float64))])
        e64 = jax.tree.map(lambda t: t[a].astype(jnp.float64)
                           if jnp.issubdtype(t.dtype, jnp.floating) else t[a],
                           graph.edges)
        g64 = manifold.rgrad(Y64, quadratic.egrad(buf64, e64, n_out=n))
        errs[scale] = float(jnp.max(jnp.abs(g32.astype(jnp.float64) - g64)))
        # naive f32 evaluation for contrast
        g32n = manifold.rgrad(buf64[:n].astype(jnp.float32),
                              quadratic.egrad(buf64.astype(jnp.float32),
                                              e_a, n_out=n))
        err_naive = float(jnp.max(jnp.abs(g32n.astype(jnp.float64) - g64)))
        # naive f32's error is a constant eps*|G| floor; the re-centered
        # error scales with |D|, so it beats naive decisively once D is
        # small (at large |D| the two are legitimately comparable).
        if scale <= 1e-5:
            assert errs[scale] < err_naive / 20
    # |D|-scaling: two decades smaller D -> at least ~one decade less error.
    assert errs[1e-5] < errs[1e-3] / 10


def test_recentered_delta_cost_matches_f64(rng):
    meas, part, graph, meta, params, edges_g, Xg = _problem(rng)
    ref = refine.recenter(Xg, graph, meta, params, edges_g)
    R_loc64, Rz64 = _f64_buffers(ref.Xg, graph)
    a = 0
    e_a = jax.tree.map(lambda t: t[a], graph.edges)
    e64 = jax.tree.map(lambda t: t[a].astype(jnp.float64)
                       if jnp.issubdtype(t.dtype, jnp.floating) else t[a],
                       graph.edges)
    Dn = rng.standard_normal(ref.consts.R.shape) * 1e-4
    D32 = jnp.asarray(Dn, jnp.float32)
    Dz32 = rbcd.neighbor_buffer(rbcd.public_table(D32, graph), graph)
    ca = jax.tree.map(lambda x: x[a], ref.consts)
    rhoR, rhot = quadratic._edge_terms(jnp.concatenate([ca.R, ca.Rz]), e_a)
    df32 = float(refine._delta_cost(
        jnp.concatenate([D32[a], Dz32[a]]), rhoR, rhot, e_a))
    buf_at = jnp.concatenate([
        jnp.asarray(R_loc64[a] + Dn[a]),
        jnp.asarray(Rz64[a] + np.asarray(Dz32[a], np.float64))])
    buf_ref = jnp.concatenate([jnp.asarray(R_loc64[a]),
                               jnp.asarray(Rz64[a])])
    df64 = float(quadratic.cost(buf_at, e64) - quadratic.cost(buf_ref, e64))
    assert abs(df32 - df64) < 1e-6 * max(1.0, abs(df64))


def test_retract_d_matches_polar(rng):
    """The series-corrected D update must reproduce the true polar
    retraction of R + D + eta."""
    meas, part, graph, meta, params, edges_g, Xg = _problem(rng)
    ref = refine.recenter(Xg, graph, meta, params, edges_g)
    D = jnp.asarray(rng.standard_normal(ref.consts.R.shape) * 1e-3,
                    jnp.float32)
    eta = jnp.asarray(rng.standard_normal(ref.consts.R.shape) * 1e-3,
                      jnp.float32)
    Dn = jax.vmap(refine._retract_d)(D, eta, ref.consts.R)
    X_new = ref.consts.R.astype(jnp.float64) + Dn.astype(jnp.float64)
    R_loc64, _ = _f64_buffers(ref.Xg, graph)
    X_true = manifold.retract(
        jnp.asarray(R_loc64) + D.astype(jnp.float64),
        eta.astype(jnp.float64))
    assert float(jnp.max(jnp.abs(X_new - X_true))) < 1e-6


def test_kernel_refine_matches_xla_refine(rng):
    """The VMEM refine kernel (interpret mode) must match the XLA refine
    round bit-tight."""
    meas, part, graph, meta, params, edges_g, Xg = _problem(
        rng, rounds=30, pallas=True)
    ref = refine.recenter(Xg, graph, meta, params, edges_g)
    assert ref.consts.Rc is not None
    D0 = jnp.asarray(rng.standard_normal(ref.consts.R.shape) * 1e-4,
                     jnp.float32)
    Dk, gk = refine.refine_round(D0, ref.consts, graph, meta, params)
    consts_x = ref.consts._replace(rho_rot_t=None, rho_trn_t=None, Rc=None,
                                   wk_t=None, wt_t=None)
    Dx, gx = refine.refine_round(D0, consts_x, graph, meta, params)
    assert np.allclose(gk, gx, atol=1e-6)
    assert np.allclose(Dk, Dx, atol=2e-6)


def test_solve_refine_beats_f32_floor(rng):
    """From an f32-converged iterate, refinement must keep decreasing the
    f64 global cost (plain f32 rounds cannot — that is the floor) and keep
    the iterate on the manifold to f64 tightness."""
    meas, part, graph, meta, params, edges_g, Xg = _problem(
        rng, n=60, rounds=300)
    X64, gap, cycles, hist = refine.solve_refine(
        Xg, graph, meta, params, edges_g,
        f_opt=1.0, rel_gap=-1.0,  # unreachable target: run max_cycles
        rounds_per_cycle=50, max_cycles=3)
    # hist[0] is the cost at the (projected) f32 floor; every cycle must
    # strictly descend and the total descent must be visible (the floor
    # point is stationary only for f32 arithmetic).
    f_before = (1.0 + hist[0][0])  # entries are (f/f_opt - 1, elapsed_s)
    f_after = refine.global_cost(X64, edges_g)
    assert f_after < f_before
    drop = f_before - f_after
    assert drop > 1e-9 * f_before
    # descent across recenters: every VERIFIED entry improves on the
    # start (the final accelerated segment may overshoot slightly, which
    # solve_refine absorbs by returning the best point)
    gaps = [h[0] for h in hist]
    assert min(gaps) < gaps[0]
    assert gap <= min(gaps) + 1e-15
    # the refined point is on the manifold to f64 tightness
    YY = X64[..., :meta.d]
    gram = np.swapaxes(YY, -1, -2) @ YY
    assert np.allclose(gram, np.eye(meta.d), atol=1e-8)


def test_solve_refine_uses_given_weights(rng):
    """Refining a robust (GNC) solve must optimize the weighted objective:
    with down-weighted loop closures passed via ``weights``, the refined
    point improves the weighted global cost, and the recenter's f_ref is
    the weighted cost (not the build-time unit-weight one)."""
    meas, part, graph, meta, params, edges_g, Xg = _problem(rng, n=40,
                                                           rounds=120)
    # Down-weight every loop-closure edge (as a converged GNC would).
    is_lc = np.asarray(graph.edges.is_lc)
    wA = np.where(is_lc > 0, 0.25, 1.0) * np.asarray(graph.edges.mask)
    wA = jnp.asarray(wA, jnp.float32)
    wg = rbcd.global_weights(wA, graph, len(part.meas_global))
    edges_w = edges_g._replace(weight=wg.astype(edges_g.weight.dtype))

    ref = refine.recenter(Xg, graph, meta, params, edges_w, weights=wA)
    f_w = refine.global_cost(refine._np_project_manifold(Xg, meta.d),
                             edges_w)
    assert ref.f_ref == pytest.approx(f_w, rel=1e-12)
    f_u = refine.global_cost(refine._np_project_manifold(Xg, meta.d),
                             edges_g)
    assert abs(f_w - f_u) > 1e-6 * max(1.0, f_u)  # the two objectives differ

    X64, gap, cycles, hist = refine.solve_refine(
        Xg, graph, meta, params, edges_w, f_opt=1.0, rel_gap=-1.0,
        rounds_per_cycle=30, max_cycles=2, weights=wA)
    assert refine.global_cost(X64, edges_w) < f_w
    # the returned point carries the best verified WEIGHTED gap (the final
    # accelerated segment may overshoot; solve_refine returns the best)
    gaps = [h[0] for h in hist]
    assert gap <= min(gaps) + 1e-15


def test_accel_colored_sweeps_descend(rng):
    """Nesterov over FULL COLORED SWEEPS (accel_sweep_carry): must
    strictly decrease the f64 global cost from a converged-f32 iterate
    (the f32 floor), like the Jacobi-accel rounds — the operator exists
    for strongly-coupled graphs where Jacobi+momentum diverges
    (ais2klinik, round 5), so stability-with-momentum is the contract."""
    meas, part, graph, meta, params, edges_g, Xg = _problem(
        rng, n=60, rounds=300)
    ref = refine.recenter(Xg, graph, meta, params, edges_g)
    D0 = jnp.zeros(ref.consts.R.shape, jnp.float32)
    f0 = refine.global_cost(ref.Xg, edges_g)
    D = refine.refine_rounds_accel_colored_chunked(
        D0, ref.consts, graph, meta, params, 60, chunk=20)
    X1 = refine.global_x(ref, np.asarray(D), graph)
    X1 = refine._np_project_manifold(np.asarray(X1, np.float64), meta.d)
    f1 = refine.global_cost(X1, edges_g)
    assert np.isfinite(f1)
    assert f1 < f0
