"""The A/B experiment gates (ADVICE r5, pruned round 6): ``PALLAS_TILE``
is scoped out of the production path behind ``DPGO_AB=1`` with
validation, and the one surviving ``pallas_tcg`` gate (``ns_sweeps``) is
read at kernel-build time so it is toggleable per-process.  The decided
gates are gone: packed selection is unconditional, the tile-unroll dead
end is deleted."""

import pytest


def test_pallas_tile_ignored_without_ab_optin(monkeypatch):
    from dpgo_tpu.models.rbcd import _edge_tile_shape

    monkeypatch.delenv("DPGO_AB", raising=False)
    monkeypatch.setenv("PALLAS_TILE", "512")  # leaked env var
    T, nt = _edge_tile_shape(500, 100, 2000)
    assert T == 256  # adaptive tile, override NOT applied
    assert nt == -(-2000 // T) or nt >= 1


def test_pallas_tile_applies_and_validates_with_ab(monkeypatch):
    from dpgo_tpu.models.rbcd import _edge_tile_shape

    monkeypatch.setenv("DPGO_AB", "1")
    monkeypatch.setenv("PALLAS_TILE", "512")
    T, _ = _edge_tile_shape(500, 100, 2000)
    assert T == 512
    for bad in ("abc", "0", "-128", "100"):  # 100: not a lane multiple
        monkeypatch.setenv("PALLAS_TILE", bad)
        with pytest.raises(ValueError):
            _edge_tile_shape(500, 100, 2000)


def test_pallas_tcg_gates_read_per_call(monkeypatch):
    from dpgo_tpu.ops.pallas_tcg import _ab_gates

    monkeypatch.delenv("PALLAS_NS_SWEEPS", raising=False)
    g = _ab_gates()
    assert g.ns_sweeps == 24
    # Toggling mid-process takes effect on the NEXT kernel build — no
    # interpreter restart (the old import-time read froze these forever).
    monkeypatch.setenv("PALLAS_NS_SWEEPS", "8")
    g = _ab_gates()
    assert g.ns_sweeps == 8


def test_decided_gates_are_retired(monkeypatch):
    """Round-6 decisions are enforced, not advisory: a leaked
    PALLAS_SEL_PACKED=0 / PALLAS_UNROLL_TILES=1 in the environment can no
    longer change the kernel build (packed selection is unconditional,
    the unroll path is deleted)."""
    from dpgo_tpu.ops.pallas_tcg import _ab_gates

    monkeypatch.setenv("PALLAS_SEL_PACKED", "0")
    monkeypatch.setenv("PALLAS_UNROLL_TILES", "1")
    g = _ab_gates()
    assert not hasattr(g, "sel_packed") and not hasattr(g, "unroll_tiles")
