"""Serve-fleet scale-out (``serve.fleet``): rendezvous session affinity,
replica kill with zero-loss live migration, drain-migration bitwise
parity, queue-wait autoscaling, the frontend drain race, and the fleet
report section (ISSUE 13).

None of these tests carry ``allow_leaks``: a fleet that killed and
respawned replicas mid-solve must still tear down to zero orphan
threads/sockets (leakcheck-enforced — the monitor thread, worker
threads, and sidecars all die with ``router.close()``)."""

import threading
import time

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams
from dpgo_tpu.serve import (FleetRouter, ReplicaManager, SolveRequest,
                            SolveServer)
from dpgo_tpu.utils.synthetic import make_measurements

#: Consensus unreachable (rel_change_tol < 0) + grad_norm_tol 0: solves
#: run their full iteration budget, so long solves stay in flight long
#: enough to kill/drain mid-schedule.
PARAMS = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=-1.0)


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _problem(seed=0, n=24):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=8, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _req(meas, sid=None, iters=2, eval_every=2):
    return SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                        max_iters=iters, grad_norm_tol=0.0,
                        eval_every=eval_every, session_id=sid)


@pytest.fixture(scope="module")
def meas():
    return _problem()


@pytest.fixture(scope="module")
def aot_root(tmp_path_factory, meas):
    """Shared persistent AOT cache: the first solve pays the compile,
    every fleet test after that disk-loads in milliseconds."""
    root = str(tmp_path_factory.mktemp("aot"))
    with SolveServer(max_batch=2, batch_window_s=0.0,
                     aot_cache_dir=root) as srv:
        srv.solve(_req(meas), timeout=600)
    return root


def _fleet(n, aot_root, sess_root=None, max_replicas=None,
           batch_window_s=0.0, **mgr_kw):
    def make_server(rid):
        return SolveServer(max_batch=2, batch_window_s=batch_window_s,
                           replica_id=rid, aot_cache_dir=aot_root,
                           session_store=sess_root, session_every=1,
                           resume_sessions=sess_root is not None)

    mgr = ReplicaManager(make_server, min_replicas=n,
                         max_replicas=max_replicas,
                         monitor_interval_s=0.05, **mgr_kw)
    return FleetRouter(mgr)


def _wait_for_snapshot(sess_root, sid, timeout=30.0):
    """Block until the session has persisted at least one snapshot (the
    state a migration will resume from)."""
    import os

    deadline = time.monotonic() + timeout
    sdir = os.path.join(str(sess_root), sid)
    while time.monotonic() < deadline:
        if os.path.isdir(sdir) and any(
                f.startswith("snap-") for f in os.listdir(sdir)):
            return
        time.sleep(0.01)
    raise AssertionError(f"no snapshot for {sid} within {timeout}s")


# ---------------------------------------------------------------------------
# Router: rendezvous affinity + status
# ---------------------------------------------------------------------------

def test_session_affinity_and_status(meas, aot_root):
    with _fleet(2, aot_root) as router:
        t1 = router.submit(_req(meas, sid="sess-A"))
        t2 = router.submit(_req(meas, sid="sess-A"))
        t3 = router.submit(_req(meas, sid="sess-B"))
        for t in (t1, t2, t3):
            t.result(timeout=600)
        # Same session -> same replica, every time.
        assert t1._replica is t2._replica
        st = router.status()
        assert st["n_replicas"] == 2 and st["accepting"]
        rids = {row["replica_id"] for row in st["replicas"]}
        assert rids == {"r0", "r1"}
        assert sum(row["requests_served"] for row in st["replicas"]) >= 3
        assert st["migrations"] == 0 and st["requests_routed"] == 3
    assert router.status()["closed"]


def test_affinity_survives_fleet_rebuild(meas, aot_root):
    """Rendezvous hashing is a pure function of (key, replica ids): a
    rebuilt fleet with the same replica ids routes the same sessions to
    the same members — the property live migration relies on."""
    owners = []
    for _ in range(2):
        with _fleet(2, aot_root) as router:
            t = router.submit(_req(meas, sid="stable-sess"))
            t.result(timeout=600)
            owners.append(t._replica.replica_id)
    assert owners[0] == owners[1]


# ---------------------------------------------------------------------------
# Kill + zero-loss migration (the chaos-soak acceptance, in miniature)
# ---------------------------------------------------------------------------

def test_kill_mid_solve_migrates_and_recovers(meas, aot_root, tmp_path):
    sess_root = str(tmp_path / "sess")
    with _fleet(2, aot_root, sess_root=sess_root) as router:
        mgr = router.manager
        t = router.submit(_req(meas, sid="live-1", iters=2500,
                               eval_every=1))
        _wait_for_snapshot(sess_root, "live-1")
        victim = t._replica
        mgr.kill_replica(victim.replica_id)
        res = t.result(timeout=600)
        # The solve completed on another replica, resumed from the
        # snapshot (not restarted): fewer local iterations than the
        # budget, flagged recovered.
        assert t.migrations >= 1 and router.migrations >= 1
        assert t._replica is not victim
        assert res.recovered
        assert res.terminated_by == "max_iters"
        assert 0 < res.iterations < 2500
        # The pool healed: the manager respawned to min_replicas.
        assert mgr.status()["respawns"] >= 1
        assert len(mgr.replicas()) == 2


# ---------------------------------------------------------------------------
# Drain-migration bitwise parity (satellite: migration must not perturb
# the trajectory)
# ---------------------------------------------------------------------------

def test_drain_migration_bitwise_parity(meas, aot_root, tmp_path):
    """A session drained from replica A and resumed on replica B produces
    BITWISE-identical history rows to an undisturbed run: same compiled
    programs (shared AOT cache), lossless npz snapshot round-trip, and a
    resume that continues the exact iteration schedule."""
    iters = 1500
    with _fleet(1, aot_root, sess_root=str(tmp_path / "base")) as router:
        base = router.submit(
            _req(meas, sid="par", iters=iters, eval_every=1)).result(
                timeout=600)
    assert len(base.cost_history) == iters

    sess_root = str(tmp_path / "mig")
    with _fleet(2, aot_root, sess_root=sess_root) as router:
        t = router.submit(_req(meas, sid="par", iters=iters, eval_every=1))
        _wait_for_snapshot(sess_root, "par")
        moved = router.migrate_from(t._replica)
        assert moved == 1 and t.migrations == 1
        res = t.result(timeout=600)
    assert res.recovered
    # The migrated run's histories are the suffix of the undisturbed
    # run's, bit for bit — from its resume iteration to the end.
    m = len(res.cost_history)
    assert 0 < m < iters
    np.testing.assert_array_equal(np.asarray(res.cost_history),
                                  np.asarray(base.cost_history)[-m:])
    np.testing.assert_array_equal(np.asarray(res.grad_norm_history),
                                  np.asarray(base.grad_norm_history)[-m:])
    np.testing.assert_array_equal(np.asarray(res.T), np.asarray(base.T))


# ---------------------------------------------------------------------------
# Autoscaling
# ---------------------------------------------------------------------------

def test_autoscale_up_on_queue_wait_burn_then_scale_down(meas, aot_root):
    router = _fleet(1, aot_root, max_replicas=2, queue_wait_slo_s=0.0,
                    min_scale_observations=2, scale_cooldown_s=0.2,
                    scale_window_s=60.0, batch_window_s=0.01)
    mgr = router.manager
    try:
        # Every completed request burns the (zero) wait budget; the
        # monitor must bring up a second replica.
        deadline = time.monotonic() + 15.0
        while mgr.status()["scale_ups"] < 1:
            router.submit(_req(meas)).result(timeout=600)
            assert time.monotonic() < deadline, "autoscaler never tripped"
        assert len(mgr.replicas()) == 2
        # Graceful scale-down retires the newest replica (no live
        # tickets -> nothing to migrate) and the pool shrinks to min.
        assert mgr.scale_down()
        assert len(mgr.replicas()) == 1
        st = mgr.status()
        assert st["scale_downs"] == 1
        # At min_replicas a further scale-down is refused.
        assert not mgr.scale_down()
    finally:
        router.close()


# ---------------------------------------------------------------------------
# Frontend drain race (satellite: send-lock + structured draining reply)
# ---------------------------------------------------------------------------

def test_frontend_draining_server_reply_carries_draining_flag(
        meas, aot_root, tmp_path):
    """A request shed while a drain is IN PROGRESS (in-flight batch
    still finishing) comes back with the structured ``draining`` flag —
    reconnect to the fleet's next replica, don't back off."""
    from dpgo_tpu.serve.frontend import ServeFrontend, solve_g2o
    from dpgo_tpu.utils.g2o import write_g2o

    path = str(tmp_path / "p.g2o")
    write_g2o(meas, path)
    server = SolveServer(max_batch=2, batch_window_s=0.0,
                         aot_cache_dir=aot_root)
    try:
        with ServeFrontend(server) as fe:
            t = server.submit(_req(meas, iters=2000, eval_every=1))
            deadline = time.monotonic() + 30.0
            while server.status()["queue_depth"] > 0:  # dispatched yet?
                assert time.monotonic() < deadline
                time.sleep(0.005)
            closer = threading.Thread(
                target=lambda: server.close(drain=True))
            closer.start()
            while not server.status()["draining"]:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            out = solve_g2o("127.0.0.1", fe.port, path, num_robots=2,
                            timeout=30)
            res = t.result(timeout=600)  # in-flight work still completes
            closer.join(timeout=60)
    finally:
        server.close()
    assert not out["ok"] and out["shed"] and out["reason"] == "closed"
    assert out["draining"] is True
    assert res.terminated_by == "max_iters"


def test_frontend_close_races_inflight_reply_cleanly():
    """A reply in flight when ``close()`` begins is either delivered
    whole or skipped entirely — the handler's send serializes with the
    teardown on the per-connection send lock and never writes into a
    closing socket."""
    from dpgo_tpu.comms.transport import (TcpTransport, TransportClosed,
                                          connect_tcp)
    from dpgo_tpu.serve import frontend as frontend_mod
    from dpgo_tpu.serve.frontend import ServeFrontend, _pack_str

    entered, release = threading.Event(), threading.Event()
    real_handle = frontend_mod.handle_request

    def slow_handle(server, frame):
        entered.set()
        release.wait(timeout=30)
        return real_handle(server, frame)

    server = SolveServer(max_batch=2, batch_window_s=0.0)
    try:
        with pytest.MonkeyPatch.context() as mp:
            mp.setattr(frontend_mod, "handle_request", slow_handle)
            fe = ServeFrontend(server)
            tr = TcpTransport(connect_tcp("127.0.0.1", fe.port),
                              src="test-client")
            try:
                tr.send({"op": _pack_str("ping")})
                assert entered.wait(timeout=10)
                # Teardown begins while the request is in flight: close()
                # must return without waiting for the handler...
                fe.close()
                release.set()
                # ...and the client sees a clean close, never a torn or
                # interleaved frame.
                with pytest.raises(TransportClosed):
                    tr.recv(timeout=10)
            finally:
                tr.close()
    finally:
        server.close()


# ---------------------------------------------------------------------------
# Report section (obs.report fleet_serve_stats)
# ---------------------------------------------------------------------------

def test_fleet_serve_stats_and_lines():
    from dpgo_tpu.obs.report import _fleet_serve_lines, fleet_serve_stats

    evs = [
        {"event": "replica_spawn", "phase": "fleet", "replica": "r0",
         "reason": "initial", "pool": 1, "t_mono": 0.0},
        {"event": "replica_spawn", "phase": "fleet", "replica": "r1",
         "reason": "scale_up", "pool": 2, "t_mono": 1.0},
        {"event": "replica_death", "phase": "fleet", "replica": "r0",
         "pool": 1, "t_mono": 2.0},
        {"event": "fleet_scale", "phase": "fleet", "direction": "up",
         "burn": 12.5, "pool": 2, "t_mono": 3.0},
        {"event": "session_migrated", "phase": "fleet", "kind": "death",
         "ok": True, "session": "s1", "t_mono": 4.0},
        {"event": "session_migrated", "phase": "fleet", "kind": "drain",
         "ok": True, "session": "s2", "t_mono": 5.0},
        {"event": "compile_profile", "phase": "serve", "disk_hit": True,
         "t_mono": 6.0},
        {"event": "compile_profile", "phase": "serve", "t_mono": 7.0},
        {"event": "metric", "metric": "serve_cold_start_seconds",
         "value": 0.124, "arm": "warm", "compile_seconds_total": 0.0,
         "disk_hits": 3, "t_mono": 8.0},
    ]
    st = fleet_serve_stats(evs)
    assert st["replicas"]["spawned"] == 2 and st["replicas"]["deaths"] == 1
    assert st["replicas"]["spawn_reasons"] == {"initial": 1, "scale_up": 1}
    assert st["migrations"]["count"] == 2
    assert st["migrations"]["by_kind"] == {"death": 1, "drain": 1}
    assert st["migrations"]["failed"] == 0
    assert st["scale"]["by_direction"] == {"up": 1}
    assert st["aot"] == {"disk_hits": 1, "compiles": 1, "quarantined": 0,
                         "store_failures": 0}
    assert st["cold_start"][0]["compile_seconds_total"] == 0.0
    text = "\n".join(_fleet_serve_lines(st))
    assert "2 replicas spawned" in text and "death 1, drain 1" in text
    assert "cold start [warm]" in text
    # No fleet-phase events -> no section (the serve plane alone must not
    # grow a fleet block).
    assert fleet_serve_stats([{"event": "metric", "t_mono": 0.0}]) is None
    assert _fleet_serve_lines(None) == []
