"""Native (C++) g2o loader: parity with the Python parser.

The reference's IO layer is C++ (``read_g2o_file``, ``DPGO_utils.cpp:78-212``);
``native/g2o_parser.cpp`` is its TPU-framework counterpart.  These tests pin
the native loader bit-for-bit (integers) / to float tolerance (precisions)
against the vectorized Python parser on real SE(2) and SE(3) datasets and on
multi-robot key-encoded files.
"""

import os

import numpy as np
import pytest

from dpgo_tpu.utils import native_io
from dpgo_tpu.utils.g2o import read_g2o, read_g2o_python

pytestmark = pytest.mark.skipif(
    not native_io.native_available(),
    reason="native loader unavailable (no C++ toolchain)")


def _assert_parity(a, b):
    assert a.d == b.d
    assert a.num_poses == b.num_poses
    assert len(a) == len(b)
    for f in ["r1", "p1", "r2", "p2"]:
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f), err_msg=f)
    for f in ["R", "t", "kappa", "tau"]:
        x, y = getattr(a, f), getattr(b, f)
        scale = max(1.0, float(np.abs(x).max()))
        np.testing.assert_allclose(y, x, rtol=0, atol=1e-9 * scale, err_msg=f)


@pytest.mark.parametrize("dataset", ["smallGrid3D", "kitti_00", "CSAIL"])
def test_native_matches_python_on_reference_data(data_dir, dataset):
    path = os.path.join(data_dir, f"{dataset}.g2o")
    if not os.path.exists(path):
        pytest.skip(f"{dataset} not in snapshot")
    _assert_parity(read_g2o_python(path), native_io.read_g2o_native(path))


def test_dispatcher_prefers_native(data_dir):
    path = os.path.join(data_dir, "smallGrid3D.g2o")
    _assert_parity(read_g2o(path, backend="native"), read_g2o(path))


def test_native_key_encoded_multi_robot(tmp_path):
    """gtsam symbol keys (robot char in the top byte) round-trip exactly —
    they exceed 2^53 so any float path would corrupt the index bits."""
    def key(c, i):
        return (ord(c) << 56) | i

    info = "1 0 0 0 0 0 1 0 0 0 0 1 0 0 0 1 0 0 1 0 1"
    lines = []
    for c in "ab":
        for i in range(3):
            lines.append(f"EDGE_SE3:QUAT {key(c, i)} {key(c, i + 1)} "
                         f"1 0 0 0 0 0 1 {info}")
    lines.append(f"EDGE_SE3:QUAT {key('a', 0)} {key('b', 0)} 0 1 0 0 0 0 1 {info}")
    p = tmp_path / "two_robot.g2o"
    p.write_text("\n".join(lines) + "\n")

    a = read_g2o_python(str(p))
    b = native_io.read_g2o_native(str(p))
    _assert_parity(a, b)
    assert set(int(x) for x in np.unique(b.r1)) | \
        set(int(x) for x in np.unique(b.r2)) == {ord("a"), ord("b")}


def test_native_accepts_fix_lines(tmp_path):
    info = "1 0 0 1 0 1"
    p = tmp_path / "fix.g2o"
    p.write_text("VERTEX_SE2 0 0 0 0\nVERTEX_SE2 1 1 0 0\nFIX 0\n"
                 f"EDGE_SE2 0 1 1 0 0 {info}\n")
    _assert_parity(read_g2o_python(str(p)), native_io.read_g2o_native(str(p)))


def test_native_error_surfaces(tmp_path):
    with pytest.raises(RuntimeError, match="cannot open"):
        native_io.read_g2o_native(str(tmp_path / "missing.g2o"))
    bad = tmp_path / "bad.g2o"
    bad.write_text("EDGE_BOGUS 0 1\n")
    with pytest.raises(ValueError, match="unrecognized token"):
        native_io.read_g2o_native(str(bad))
    empty = tmp_path / "empty.g2o"
    empty.write_text("VERTEX_SE2 0 0 0 0\n")
    with pytest.raises(ValueError, match="no edges"):
        native_io.read_g2o_native(str(empty))
    # Truncated edge lines must fail loudly, not zero-fill (NaN R / kappa).
    trunc = tmp_path / "trunc.g2o"
    trunc.write_text("EDGE_SE3:QUAT 0 1 1 0 0\nEDGE_SE2 0 1 1 0 0 1 0 0 1 0 1\n")
    with pytest.raises(ValueError, match="malformed"):
        native_io.read_g2o_native(str(trunc))
