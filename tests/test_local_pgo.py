"""End-to-end single-agent solves (reference single-robot-example path)."""

import jax.numpy as jnp
import numpy as np

from dpgo_tpu.models import local_pgo
from dpgo_tpu.ops import quadratic
from dpgo_tpu.types import edge_set_from_measurements
from synthetic import make_measurements, trajectory_error


def test_solve_local_noiseless_exact(rng):
    meas, (Rs, ts) = make_measurements(rng, n=12, d=3, num_lc=6)
    res = local_pgo.solve_local(meas, grad_norm_tol=1e-9, max_iters=100)
    assert res.cost < 1e-12
    assert trajectory_error(res.T, Rs, ts) < 1e-5


def test_solve_local_odometry_init(rng):
    meas, (Rs, ts) = make_measurements(rng, n=12, d=3, num_lc=4)
    res = local_pgo.solve_local(meas, init="odometry", grad_norm_tol=1e-9)
    assert res.cost < 1e-12
    assert trajectory_error(res.T, Rs, ts) < 1e-5


def test_solve_local_se2(rng):
    meas, (Rs, ts) = make_measurements(rng, n=15, d=2, num_lc=6,
                                       rot_noise=0.02, trans_noise=0.02)
    res = local_pgo.solve_local(meas, grad_norm_tol=1e-6)
    assert res.grad_norm < 1e-6
    R = res.T[..., :2]
    eye = np.broadcast_to(np.eye(2), np.asarray(R).shape)
    assert np.allclose(np.swapaxes(np.asarray(R), -1, -2) @ np.asarray(R), eye, atol=1e-8)


def test_lifted_rank_matches_unlifted_optimum(rng):
    # Burer-Monteiro: at moderate noise the rank-d and rank-r solves must
    # round to (essentially) the same rotation-valid cost.
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10,
                                rot_noise=0.03, trans_noise=0.03)
    res_d = local_pgo.solve_local(meas, rank=3, grad_norm_tol=1e-8, max_iters=300)
    res_r = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-8, max_iters=300)

    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    eye3 = jnp.eye(3, dtype=jnp.float64)

    def rounded_cost(T):
        return float(quadratic.cost(local_pgo.lift(jnp.asarray(T), eye3), edges))

    c_d = rounded_cost(res_d.T)
    c_r = rounded_cost(res_r.T)
    assert c_r <= c_d * 1.01 + 1e-12


def test_smallgrid_end_to_end(data_dir):
    # The reference demo dataset: 125 poses, 297 edges (README.md:31-34).
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(f"{data_dir}/smallGrid3D.g2o")
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-4, max_iters=200)
    assert res.grad_norm < 1e-4
    # Solution improves on the chordal initialization.
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    from dpgo_tpu.ops import chordal

    T0 = chordal.chordal_initialization(edges, meas.num_poses)
    from dpgo_tpu.utils.lie import fixed_stiefel

    ylift = fixed_stiefel(5, 3, jnp.float64)
    f0 = float(quadratic.cost(local_pgo.lift(T0, ylift), edges))
    assert res.cost <= f0
