"""Pallas VMEM tCG kernel vs the XLA truncated_cg (interpreter mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import AgentParams, Schedule, SolverParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.ops import manifold, solver
from dpgo_tpu.ops import pallas_tcg as ptcg
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements


def _setup(rng, n=24, A=4, rank=5, num_lc=12):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.05, trans_noise=0.05)
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, rank, jnp.float32, pallas_sel=True)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    return graph, meta, X0


@pytest.mark.parametrize("radius", [0.05, 1.0, 100.0])
def test_kernel_matches_xla_tcg(rng, radius):
    graph, meta, X0 = _setup(rng)
    params = AgentParams(d=3, r=5, num_robots=4)
    Z = rbcd.neighbor_buffer(rbcd.public_table(X0, graph), graph)
    chol = rbcd.precond_chol(graph.edges, meta.n_max, meta.s_max, params)
    d, k, r = meta.d, meta.d + 1, meta.rank

    for a in range(2):
        e = jax.tree.map(lambda t: t[a], graph.edges)
        x, z = X0[a], Z[a]
        prob = rbcd._agent_local_problem(
            z, e, chol[a], meta.n_max,
            inc=(graph.inc_slot[a], graph.inc_mask[a]))
        eg = prob.egrad(x)
        g = manifold.rgrad(x, eg)
        rad = jnp.asarray(radius, jnp.float32)

        hvp = lambda V: manifold.ehess_to_rhess(x, eg, prob.ehess(x, V), V)
        pre = lambda V: manifold.tangent_project(x, prob.precond(x, V))
        ref = solver.truncated_cg(x, g, hvp, pre, rad, 10, 0.1, 1.0)

        nt, tile = graph.eidx_i.shape[1], graph.eidx_i.shape[-1]
        w = (e.mask * e.weight).astype(jnp.float32)
        wk = ptcg.edge_tiles(w * e.kappa, nt, tile)
        wt = ptcg.edge_tiles(w * e.tau, nt, tile)
        Y, GY = x[..., :d], eg[..., :d]
        M = jnp.einsum("nab,nac->nbc", Y, GY)
        S = 0.5 * (M + jnp.swapaxes(M, -1, -2))
        Sc = S.transpose(1, 2, 0).reshape(d * d, meta.n_max)
        Lc = chol[a].transpose(1, 2, 0).reshape(k * k, meta.n_max)
        eta_c, heta_c, stats = ptcg.tcg_call(
            graph.eidx_i[a], graph.eidx_j[a], graph.rot_t[a], graph.trn_t[a],
            wk, wt, ptcg.comp_major(x), Sc, Lc, ptcg.comp_major(g),
            rad.reshape(1, 1), r=r, d=d, max_iters=10, kappa=0.1, theta=1.0,
            interpret=True)

        assert np.allclose(ptcg.comp_minor(eta_c, r, k), ref.eta, atol=1e-5)
        assert np.allclose(ptcg.comp_minor(heta_c, r, k), ref.heta, atol=1e-4)
        assert int(stats[0, 0]) == int(ref.iters)
        assert bool(stats[0, 1] > 0) == bool(ref.hit_boundary)


def test_rounds_match_ell_path(rng):
    """Full RBCD rounds through the Pallas tCG (forced, interpreter mode)
    track the ELL path to float32 tolerance."""
    graph, meta, X0 = _setup(rng)
    pp = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=True))
    pe = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=False))
    sp = rbcd.init_state(graph, meta, X0, params=pp)
    se = rbcd.init_state(graph, meta, X0, params=pe)
    for _ in range(3):
        sp = rbcd.rbcd_step(sp, graph, meta, pp)
        se = rbcd.rbcd_step(se, graph, meta, pe)
    assert np.allclose(sp.X, se.X, atol=1e-5)


def test_edge_tiles_layout(rng):
    """Tile-major edge indices: valid edges carry the planner's endpoint
    (local < n_max, neighbor in [n_max, n_max + s_max)); padding carries
    n_max + s_max, which one-hots to all-zero in both ranges."""
    graph, meta, _ = _setup(rng)
    assert graph.eidx_i is not None  # pallas_sel=True: always built
    a = 0
    i = np.asarray(graph.edges.i[a])
    mask = np.asarray(graph.edges.mask[a]) > 0
    flat = np.asarray(graph.eidx_i[a]).reshape(-1)  # [nt*T]
    e_max = i.shape[0]
    assert np.array_equal(flat[:e_max][mask], i[mask])
    assert np.all(flat[:e_max][~mask] == meta.n_max + meta.s_max)
    assert np.all(flat[e_max:] == meta.n_max + meta.s_max)
    # Payload tiles carry the edge rotations at the matching positions.
    rot = np.asarray(graph.rot_t[a])  # [nt, d*d, T]
    nt, dd, T = rot.shape
    rot_flat = rot.transpose(1, 0, 2).reshape(dd, nt * T)
    R = np.asarray(graph.edges.R[a])  # [e_max, d, d]
    ref = R.transpose(1, 2, 0).reshape(dd, e_max)
    assert np.allclose(rot_flat[:, :e_max][:, mask], ref[:, mask], atol=1e-6)


def test_rounds_match_ell_path_se2(rng):
    """The kernel is generic over (r, d): SE(2) rounds must also track the
    ELL path."""
    meas, _ = make_measurements(rng, n=16, d=2, num_lc=6,
                                rot_noise=0.03, trans_noise=0.03)
    part = partition_contiguous(meas, 2)
    graph, meta = rbcd.build_graph(part, 3, jnp.float32, pallas_sel=True)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    pp = AgentParams(d=2, r=3, num_robots=2, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=True))
    pe = AgentParams(d=2, r=3, num_robots=2, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=False))
    sp = rbcd.init_state(graph, meta, X0, params=pp)
    se = rbcd.init_state(graph, meta, X0, params=pe)
    for _ in range(3):
        sp = rbcd.rbcd_step(sp, graph, meta, pp)
        se = rbcd.rbcd_step(se, graph, meta, pe)
    assert np.allclose(sp.X, se.X, atol=1e-5)


def test_forced_pallas_without_sel_raises(rng):
    """pallas_tcg=True on a graph without edge tiles must raise, not
    silently downgrade to another formulation."""
    meas, _ = make_measurements(rng, n=16, d=3, num_lc=6)
    part = partition_contiguous(meas, 2)
    graph, meta = rbcd.build_graph(part, 5, jnp.float32, pallas_sel=False)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    pp = AgentParams(d=3, r=5, num_robots=2,
                     solver=SolverParams(pallas_tcg=True))
    with pytest.raises(ValueError, match="edge tiles"):
        state = rbcd.init_state(graph, meta, X0, params=pp)
        rbcd.rbcd_step(state, graph, meta, pp)


def test_rounds_bf16_select_tracks_ell_path(rng):
    """bf16 selection mode (hi/lo split gathers): rounds track the ELL
    path to the split's ~2^-16 relative error budget."""
    graph, meta, X0 = _setup(rng)
    pp = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=True,
                                         pallas_bf16_select=True))
    pe = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=False))
    sp = rbcd.init_state(graph, meta, X0, params=pp)
    se = rbcd.init_state(graph, meta, X0, params=pe)
    for _ in range(3):
        sp = rbcd.rbcd_step(sp, graph, meta, pp)
        se = rbcd.rbcd_step(se, graph, meta, pe)
    assert np.allclose(sp.X, se.X, atol=3e-4)


def test_rounds_bf16x3_select_matches_f32_kernel(rng):
    """bf16x3 selection (hi/mid/lo split covers the full 24-bit f32
    mantissa; the 0/1 one-hots are bf16-exact, so no cross terms): rounds
    must match BOTH the f32-precision kernel and the ELL path to f32
    round-off scale — an order tighter than the 2-pass mode's 3e-4
    budget — making it an f32-equivalent mode at half the MXU passes."""
    graph, meta, X0 = _setup(rng)
    px = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=True,
                                         pallas_sel_mode="bf16x3"))
    pf = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=True))
    pe = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                     solver=SolverParams(pallas_tcg=False))
    sx = rbcd.init_state(graph, meta, X0, params=px)
    sf = rbcd.init_state(graph, meta, X0, params=pf)
    se = rbcd.init_state(graph, meta, X0, params=pe)
    for _ in range(3):
        sx = rbcd.rbcd_step(sx, graph, meta, px)
        sf = rbcd.rbcd_step(sf, graph, meta, pf)
        se = rbcd.rbcd_step(se, graph, meta, pe)
    assert np.allclose(sx.X, sf.X, atol=2e-5), \
        np.abs(np.asarray(sx.X) - np.asarray(sf.X)).max()
    assert np.allclose(sx.X, se.X, atol=2e-5), \
        np.abs(np.asarray(sx.X) - np.asarray(se.X)).max()
