"""Distributed certification (parallel.certify) vs the centralized
dual-certificate eigensolve (models.certify) on the virtual 8-device mesh.

The T-RO 2021 capability the reference never implemented: lambda_min of
S = Q - Lambda computed with every agent holding only its own edges, via
psum'd Gram matrices and a distributed block LOBPCG.
"""

import jax.numpy as jnp
import numpy as np

from dpgo_tpu.config import AgentParams
from dpgo_tpu.models import certify, rbcd
from dpgo_tpu.parallel import certify as dcert
from dpgo_tpu.parallel.sharded import make_mesh
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.g2o import read_g2o
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements


def _setup(meas, A, r, rounds):
    params = AgentParams(d=meas.d, r=r, num_robots=A)
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    if rounds:
        state = rbcd.rbcd_steps(state, graph, rounds, meta, params)
    Xg = rbcd.gather_to_global(state.X, graph, meas.num_poses)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    return state, graph, meta, part, Xg, edges_g


def test_sharded_certificate_matches_centralized(rng):
    """Certified case: a well-converged iterate of a clean synthetic graph —
    lambda_min ~ 0 on both paths, matching to eigensolver tolerance."""
    meas, _ = make_measurements(rng, n=48, d=3, num_lc=24,
                                rot_noise=0.01, trans_noise=0.01)
    state, graph, meta, part, Xg, edges_g = _setup(meas, 8, 5, rounds=150)
    c = certify.certify_solution(Xg, edges_g)
    cd = dcert.certify_sharded(state.X, graph, mesh=make_mesh(8))
    assert abs(cd.sigma - c.sigma) < 0.2 * max(1.0, c.sigma)
    assert abs(cd.stationarity_gap - c.stationarity_gap) \
        < 1e-6 * max(1.0, c.sigma)
    assert abs(cd.lambda_min - c.lambda_min) < 1e-3 * max(1.0, c.sigma)
    assert cd.certified == c.certified


def test_sharded_certificate_multislice_mesh(rng):
    """The distributed certificate runs unchanged over a 2-D ("dcn","ici")
    multi-slice mesh — the collectives span the flattened product axis."""
    from dpgo_tpu.parallel.sharded import make_multislice_mesh

    meas, _ = make_measurements(rng, n=48, d=3, num_lc=24,
                                rot_noise=0.01, trans_noise=0.01)
    state, graph, meta, part, Xg, edges_g = _setup(meas, 8, 5, rounds=150)
    c = certify.certify_solution(Xg, edges_g)
    cd = dcert.certify_sharded(state.X, graph, mesh=make_multislice_mesh(2))
    assert abs(cd.lambda_min - c.lambda_min) < 1e-3 * max(1.0, c.sigma)
    assert cd.certified == c.certified


def test_sharded_certificate_detects_suboptimality():
    """Uncertified case: the classic winding-cycle local minimum (rank-2
    critical point of an identity cycle, test_certify.py) partitioned over
    8 agents — both paths must report the same clearly negative lambda_min.
    """
    from test_certify import _winding_cycle

    meas, Xw = _winding_cycle(n=16)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 2, jnp.float64)
    Xa = rbcd.scatter_to_agents(jnp.asarray(Xw, jnp.float64), graph)
    Xg = rbcd.gather_to_global(Xa, graph, meas.num_poses)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    c = certify.certify_solution(Xg, edges_g)
    assert not c.certified and c.lambda_min < -1e-3
    cd = dcert.certify_sharded(Xa, graph, mesh=make_mesh(8))
    assert not cd.certified
    assert abs(cd.lambda_min - c.lambda_min) < 1e-2 * abs(c.lambda_min)


def test_sharded_staircase_escapes_winding_minimum():
    """End-to-end distributed certifiably correct PGO: from the winding
    local minimum, the sharded staircase (mesh RBCD solve + distributed
    certificate + per-agent saddle escape) must descend the cost at every
    rank and certify a near-zero-cost solution — the same escape the
    centralized staircase makes (test_certify.py)."""
    from test_certify import _winding_cycle

    meas, Xw = _winding_cycle(n=16)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 2, jnp.float64)
    Xa0 = rbcd.scatter_to_agents(jnp.asarray(Xw, jnp.float64), graph)
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 8, mesh=make_mesh(8), r_min=2, r_max=6, rounds_per_rank=800,
        dtype=jnp.float64, X0=np.asarray(Xa0))
    assert cert.certified
    assert rank >= 3  # the winding configuration is rank-2 critical
    costs = [f for _, f, *_ in hist]
    assert all(b < a for a, b in zip(costs, costs[1:]))  # strict descent
    assert costs[0] > 1.0      # started at the suboptimal critical point
    assert costs[-1] < 1e-2    # certified solution is the near-zero optimum
    assert T.shape == (meas.num_poses, meas.d, meas.d + 1)


def test_sharded_staircase_certifies_clean_graph(rng):
    """Default path (chordal init, X0=None): a clean synthetic graph
    certifies at the starting rank without any escape."""
    meas, _ = make_measurements(rng, n=32, d=3, num_lc=16,
                                rot_noise=0.01, trans_noise=0.01)
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 8, mesh=make_mesh(8), r_max=6, rounds_per_rank=200,
        dtype=jnp.float64)
    assert cert.certified
    assert rank == meas.d + 1          # r_min, no escapes needed
    assert len(hist) == 1
    assert T.shape == (meas.num_poses, meas.d, meas.d + 1)


def test_sharded_certificate_sphere2500(rng, data_dir):
    """BASELINE config #5 capability on the real dataset: the sharded
    lambda_min matches the centralized LOBPCG value on sphere2500 over the
    8-device CPU mesh (VERDICT round-1 item 6)."""
    meas = read_g2o(f"{data_dir}/sphere2500.g2o")
    state, graph, meta, part, Xg, edges_g = _setup(meas, 8, 5, rounds=150)
    c = certify.certify_solution(Xg, edges_g)
    cd = dcert.certify_sharded(state.X, graph, mesh=make_mesh(8))
    assert abs(cd.lambda_min - c.lambda_min) < 1e-3 * max(1.0, c.sigma)
    assert cd.certified == c.certified
    # the eigendirection is a genuine unit near-null direction of S:
    # its Rayleigh quotient matches lambda_min.
    v = cd.direction  # [A, n, dh]
    Vp = v[:, :, None, :]
    # evaluate <v, S v> / <v, v> centrally via the certificate operator
    vg = rbcd.gather_to_global(Vp[:, :, 0, :], graph, meas.num_poses)
    lam = certify.dual_blocks(Xg, edges_g)
    Sv = certify.certificate_matvec(vg[:, None, :], edges_g, lam)
    rq = float(jnp.sum(vg[:, None, :] * Sv) / jnp.sum(vg * vg))
    assert abs(rq - cd.lambda_min) < 1e-3 * max(1.0, c.sigma)


def test_sharded_certificate_uses_given_weights(rng):
    """Certifying a robust (GNC) solve: ``weights`` must flow into the
    certificate operator — the distributed result matches the centralized
    certificate of the WEIGHTED objective, and differs from the
    unit-weight certificate."""
    meas, _ = make_measurements(rng, n=48, d=3, num_lc=24,
                                rot_noise=0.01, trans_noise=0.01)
    state, graph, meta, part, Xg, edges_g = _setup(meas, 8, 5, rounds=150)
    rw = np.random.default_rng(7)
    wg = jnp.asarray(0.3 + 0.7 * rw.random(len(part.meas_global)))
    wA = wg[np.asarray(graph.meas_id)] * graph.edges.mask
    edges_w = edges_g._replace(weight=wg)

    c = certify.certify_solution(Xg, edges_w)
    cd = dcert.certify_sharded(state.X, graph, mesh=make_mesh(8),
                               weights=wA)
    assert abs(cd.stationarity_gap - c.stationarity_gap) \
        < 1e-6 * max(1.0, c.sigma)
    assert abs(cd.lambda_min - c.lambda_min) < 1e-3 * max(1.0, c.sigma)
    # and the weighted certificate is a different object from the
    # unit-weight one (the weights actually changed the operator)
    c_unit = certify.certify_solution(Xg, edges_g)
    assert abs(c.stationarity_gap - c_unit.stationarity_gap) > 1e-9
