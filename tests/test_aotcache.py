"""Persistent AOT executable cache (``serve.fleet.aotcache``): disk
round-trips across fresh caches, identity-mismatch refusal, corrupt-entry
quarantine with fail-open fallback, and the cold-start acceptance pin —
a restarted server's first solve with ``serve_compile_seconds_total``
exactly 0 (ISSUE 13).

The server-level test carries no ``allow_leaks`` marker on purpose: a
warm restart through the disk tier must tear down as cleanly as a cold
one (leakcheck-enforced)."""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams
from dpgo_tpu.serve import SolveRequest, SolveServer
from dpgo_tpu.serve.fleet import aotcache
from dpgo_tpu.serve.fleet.aotcache import (AOTDiskCache, AOTExecutable,
                                           entry_identity)
from dpgo_tpu.utils.synthetic import make_measurements

PARAMS = AgentParams(d=3, r=5, num_robots=2)


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _problem(seed=0, n=24):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=8, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _req(meas):
    return SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                        max_iters=4, grad_norm_tol=1e-12, eval_every=2)


def _compiled():
    jitfn = jax.jit(lambda x: x * 2.0 + 1.0)
    return jitfn, jitfn.lower(jnp.ones(4)).compile()


# ---------------------------------------------------------------------------
# AOTDiskCache mechanics
# ---------------------------------------------------------------------------

def test_disk_round_trip(tmp_path):
    _, compiled = _compiled()
    ident = entry_identity("fp-A", ())
    ds = AOTDiskCache(str(tmp_path / "aot"))
    assert ds.load(ident) is None  # plain miss first
    assert ds.store(ident, compiled)
    loaded = AOTDiskCache(str(tmp_path / "aot")).load(ident)  # fresh tier
    assert loaded is not None
    np.testing.assert_array_equal(np.asarray(loaded(jnp.ones(4))),
                                  np.asarray(compiled(jnp.ones(4))))
    st = ds.stats()
    assert st["disk_misses"] == 1 and st["stores"] == 1


def test_identity_mismatch_refused_and_quarantined(tmp_path):
    """A stale/colliding entry whose embedded identity disagrees with the
    requested one is never deserialized: quarantined aside, load returns
    None (the caller recompiles)."""
    _, compiled = _compiled()
    ident = entry_identity("fp-A", ())
    ds = AOTDiskCache(str(tmp_path / "aot"))
    ds.store(ident, compiled)
    path = ds._path(ident)
    with open(path, "rb") as fh:
        entry = pickle.load(fh)
    entry["ident"] = dict(entry["ident"], fingerprint="fp-OTHER")
    with open(path, "wb") as fh:
        pickle.dump(entry, fh)
    assert ds.load(ident) is None
    assert ds.stats()["quarantined"] == 1
    assert (tmp_path / "aot" / (path.split("/")[-1] + ".quarantined")).exists()


def test_schema_version_keys_the_entry(tmp_path, monkeypatch):
    """A schema bump changes the entry identity (and thus its path): old
    entries become plain misses, never deserialization attempts."""
    _, compiled = _compiled()
    ds = AOTDiskCache(str(tmp_path / "aot"))
    ds.store(entry_identity("fp-A", ()), compiled)
    monkeypatch.setattr(aotcache, "AOT_CACHE_SCHEMA_VERSION",
                        aotcache.AOT_CACHE_SCHEMA_VERSION + 1)
    assert ds.load(entry_identity("fp-A", ())) is None
    st = ds.stats()
    assert st["disk_misses"] == 1 and st["quarantined"] == 0


def test_corrupt_entry_quarantined_and_fail_open(tmp_path):
    """Garbage bytes on disk: the executable wrapper quarantines the
    entry, falls back to a fresh compile (fail-open — no exception ever
    reaches the caller), and re-persists a good entry."""
    jitfn, _ = _compiled()
    ds = AOTDiskCache(str(tmp_path / "aot"))
    ident = entry_identity("fp-K", ())
    with open(ds._path(ident), "wb") as fh:
        fh.write(b"\x00not a pickle")
    ex = AOTExecutable(jitfn, ds, key="fp-K", label="test")
    np.testing.assert_array_equal(np.asarray(ex(jnp.ones(4))),
                                  np.full(4, 3.0))
    st = ds.stats()
    assert st["quarantined"] == 1 and st["stores"] == 1
    # The re-persisted entry serves the next fresh process.
    assert AOTDiskCache(str(tmp_path / "aot")).load(ident) is not None


def test_store_failure_swallowed(tmp_path):
    """An unserializable 'executable' must not raise out of store()."""
    ds = AOTDiskCache(str(tmp_path / "aot"))
    assert ds.store(entry_identity("fp-B", ()), object()) is False
    assert ds.stats()["store_errors"] == 1


# ---------------------------------------------------------------------------
# Server-level cold-start pin (the ISSUE 13 acceptance)
# ---------------------------------------------------------------------------

def test_warm_restart_first_solve_skips_xla(tmp_path):
    """Cold server compiles + persists; a FRESH server on the same cache
    root serves its first solve with ``serve_compile_seconds_total``
    exactly 0 and only disk hits — XLA never ran on the restart."""
    meas = _problem()
    aot = str(tmp_path / "aot")
    with SolveServer(max_batch=2, batch_window_s=0.0,
                     aot_cache_dir=aot) as srv:
        base = srv.solve(_req(meas), timeout=600)
        assert srv.cache.stats()["disk"]["stores"] >= 1
    with obs.run_scope(str(tmp_path / "run")) as run:
        with SolveServer(max_batch=2, batch_window_s=0.0,
                         aot_cache_dir=aot) as srv:
            res = srv.solve(_req(meas), timeout=600)
            disk = srv.cache.stats()["disk"]
        compile_s = sum(run.counter(
            "serve_compile_seconds_total",
            "wall-clock spent in XLA compiles of serving executables",
            unit="s").series().values())
        lookups = run.counter("serve_cache_requests_total",
                              "executable-cache lookups by outcome")
        disk_hit_lookups = lookups.value(outcome="disk_hit")
    assert compile_s == 0.0
    assert disk["disk_hits"] >= 1 and disk["disk_misses"] == 0
    assert disk["quarantined"] == 0
    assert disk_hit_lookups >= 1
    np.testing.assert_allclose(np.asarray(res.T), np.asarray(base.T),
                               rtol=1e-12)
    np.testing.assert_array_equal(np.asarray(res.cost_history),
                                  np.asarray(base.cost_history))
