"""M4 tests: Nesterov-accelerated RBCD and the GNC robust outer loop.

Mirrors what the reference exercises through ``examples/MultiRobotExample.cpp``
(acceleration flag) and the robust defaults of ``PGOAgentParameters``
(GNC_TLS, weight updates every ``robustOptInnerIters``), plus the outlier
recovery property tests of ``tests/testUtils.cpp:72-180`` lifted to the full
distributed solve.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import (AgentParams, RobustCostParams, RobustCostType,
                             Schedule, SolverParams)
from dpgo_tpu.models import rbcd
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements, trajectory_error


def robust_params(num_robots, d=3, r=5, inner_iters=10, **kw):
    return AgentParams(
        d=d, r=r, num_robots=num_robots, schedule=Schedule.JACOBI,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=0.5),
        robust_opt_inner_iters=inner_iters,
        rel_change_tol=1e-8,
        solver=SolverParams(grad_norm_tol=1e-6),
        **kw,
    )


# ---------------------------------------------------------------------------
# Acceleration
# ---------------------------------------------------------------------------

def test_accelerated_rbcd_converges(rng):
    meas, (Rs, ts) = make_measurements(rng, n=20, d=3, num_lc=10)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                         acceleration=True, restart_interval=30)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=200, grad_norm_tol=1e-6)
    assert res.grad_norm_history[-1] < 1e-6
    assert trajectory_error(res.T, Rs, ts) < 1e-4


def test_accelerated_restart_rounds_run(rng):
    # A tiny restart interval forces several restart-variant rounds.
    meas, _ = make_measurements(rng, n=16, d=3, num_lc=6,
                                rot_noise=0.03, trans_noise=0.03)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                         acceleration=True, restart_interval=5)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=40, grad_norm_tol=1e-5)
    assert res.cost_history[-1] <= res.cost_history[0]


def test_accelerated_not_slower_than_plain(rng):
    # On a noisy graph, acceleration should reach the reference driver's
    # gradnorm gate (0.1, MultiRobotExample.cpp:238 — tightened to 0.05
    # here) in no more rounds than the plain schedule, modulo small-problem
    # noise.  Note the per-step solver floor of 1e-2 (the reference's forced
    # trust-region tolerance, PGOAgent.cpp:1134) makes gates far below that
    # floor unreachable with momentum on: once an agent's local gradient is
    # under the floor the solver early-exits and X tracks the momentum point
    # Y, so the iterate dithers at the floor level by design (same behavior
    # as the reference; its demo only ever gates at 0.1).
    meas, _ = make_measurements(rng, n=40, d=3, num_lc=20,
                                rot_noise=0.05, trans_noise=0.05)
    base = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                       rel_change_tol=1e-10)
    accel = dataclasses.replace(base, acceleration=True, restart_interval=30)
    r_base = rbcd.solve_rbcd(meas, 4, base, max_iters=150, grad_norm_tol=0.05)
    r_accel = rbcd.solve_rbcd(meas, 4, accel, max_iters=150, grad_norm_tol=0.05)
    assert r_accel.grad_norm_history[-1] < 0.05
    assert r_accel.iterations <= r_base.iterations + 5


def test_accelerated_greedy_schedule(rng):
    meas, _ = make_measurements(rng, n=16, d=3, num_lc=6)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.GREEDY,
                         acceleration=True, restart_interval=30)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=150, grad_norm_tol=1e-4)
    assert res.grad_norm_history[-1] < 1e-4


def test_async_with_acceleration_rejected(rng):
    meas, _ = make_measurements(rng, n=12, d=3, num_lc=4)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.ASYNC,
                         acceleration=True)
    with pytest.raises(ValueError, match="acceleration"):
        rbcd.solve_rbcd(meas, 4, params, max_iters=5)


# ---------------------------------------------------------------------------
# GNC robust outer loop
# ---------------------------------------------------------------------------

def test_gnc_rejects_outliers_and_recovers(rng):
    meas, (Rs, ts) = make_measurements(rng, n=24, d=3, num_lc=10,
                                       outlier_lc=6)
    m_in = len(meas) - 6  # outliers appended last by make_measurements
    params = robust_params(4)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=120, grad_norm_tol=1e-6)
    w = np.asarray(res.weights)
    assert np.all(w[m_in:] < 0.01), f"outlier weights not rejected: {w[m_in:]}"
    assert np.all(w[:m_in] > 0.99), "inlier weights decayed"
    assert trajectory_error(res.T, Rs, ts) < 1e-3


def test_gnc_weights_consistent_between_shared_copies(rng):
    # Shared-edge weights must be identical in both endpoint agents' edge
    # lists (replaces the reference's ownership/publish rule,
    # PGOAgent.cpp:1201-1221).
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=10, outlier_lc=4)
    params = robust_params(4, inner_iters=5)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    for it in range(12):
        state = rbcd.rbcd_step(state, graph, meta, params,
                               update_weights=(it + 1) % 5 == 0)
    ids = np.asarray(graph.meas_id).reshape(-1)
    msk = np.asarray(graph.edges.mask).reshape(-1) > 0
    w = np.asarray(state.weights).reshape(-1)
    for k in np.unique(ids[msk]):
        copies = w[msk & (ids == k)]
        assert np.allclose(copies, copies[0], atol=1e-12), f"meas {k}"


def test_gnc_known_inliers_pinned(rng):
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8, outlier_lc=4)
    # Pin all true inlier LCs as known: their weights must stay 1 even
    # under GNC (reference RelativeSEMeasurement.h:47, PGOAgent.cpp:1186).
    known = np.zeros(len(meas), bool)
    known[: len(meas) - 4] = True
    meas = dataclasses.replace(meas, is_known_inlier=known)
    params = robust_params(4)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=60, grad_norm_tol=1e-6)
    w = np.asarray(res.weights)
    assert np.all(w[: len(known) - 4] == 1.0)


def test_gnc_convergence_ratio_gates_consensus(rng):
    # With undecided weights the agents must not report ready; after enough
    # GNC annealing rounds, all weights converge to {0,1} and the gate opens.
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8, outlier_lc=4)
    params = robust_params(4, inner_iters=5)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=150, grad_norm_tol=0.0)
    w = np.asarray(res.weights)
    lc = np.arange(len(meas)) >= (20 - 1)  # loop closures follow odometry
    assert np.all((w[lc] < 1e-4) | (w[lc] > 1 - 1e-4))


def test_gnc_weight_freeze_on_device(rng):
    """The ratio-gated weight freeze is decided inside the flagged round:
    once all LC weights sit in {0, 1} and at least two updates have run,
    a weight-update round must leave weights, mu, and the iterate exactly
    as a plain round would — and before that ordinal the same converged
    weights must NOT freeze (the first two updates always run)."""
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8, outlier_lc=4)
    params = robust_params(4, inner_iters=5)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)

    # Converged weights (exactly {0,1}): every LC edge decided.
    w_conv = jnp.where(graph.edges.is_lc > 0,
                       jnp.round(graph.edges.weight), graph.edges.weight)
    state = state._replace(weights=w_conv, mu=jnp.asarray(7.0, jnp.float64))

    # Ordinal >= 3 (iteration + 1 = 3 * inner_iters): frozen — the flagged
    # round equals a plain round on every carried quantity.
    st3 = state._replace(iteration=jnp.asarray(3 * 5 - 1, jnp.int32))
    upd = rbcd.rbcd_step(st3, graph, meta, params, update_weights=True)
    plain = rbcd.rbcd_step(st3, graph, meta, params, update_weights=False)
    assert np.array_equal(np.asarray(upd.weights), np.asarray(w_conv))
    assert float(upd.mu) == 7.0
    assert np.allclose(np.asarray(upd.X), np.asarray(plain.X), atol=1e-12)

    # Ordinal 2: NOT frozen even with converged weights — mu must anneal.
    st2 = state._replace(iteration=jnp.asarray(2 * 5 - 1, jnp.int32))
    upd2 = rbcd.rbcd_step(st2, graph, meta, params, update_weights=True)
    assert float(upd2.mu) > 7.0


def test_gnc_warm_start_disabled_resets(rng):
    # Warm start off: X resets to the initial guess after every weight
    # update (reference PGOAgent.cpp:657-662), so each GNC cycle re-solves
    # from scratch — use the reference's 30-round inner budget
    # (robustOptInnerIters default, PGOAgent.h:123).
    # Each weight update resets the iterate to the initial guess, so the
    # budget must leave recovery rounds after the LAST update — the finite
    # robust_opt_num_weight_updates cap passed here (the default is 0 =
    # unlimited; beyond-reference, see config.py) is what makes full
    # convergence reachable on this path.
    meas, (Rs, ts) = make_measurements(rng, n=20, d=3, num_lc=8, outlier_lc=4)
    params = robust_params(4, inner_iters=30, robust_opt_warm_start=False,
                           robust_opt_num_weight_updates=10)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=600, grad_norm_tol=1e-6)
    w = np.asarray(res.weights)
    assert np.all(w[-4:] < 0.01)
    assert trajectory_error(res.T, Rs, ts) < 1e-3


def test_gnc_accelerated(rng):
    # Acceleration resets on every weight update (initializeAcceleration,
    # PGOAgent.cpp:1054-1063); the combined path must still converge.
    meas, (Rs, ts) = make_measurements(rng, n=24, d=3, num_lc=10, outlier_lc=4)
    params = dataclasses.replace(robust_params(4), acceleration=True,
                                 restart_interval=30)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=120, grad_norm_tol=1e-6)
    w = np.asarray(res.weights)
    assert np.all(w[-4:] < 0.01)
    assert trajectory_error(res.T, Rs, ts) < 1e-3


@pytest.mark.parametrize("cost_type,kw", [
    (RobustCostType.Huber, dict(huber_threshold=0.5)),
    # Residuals are sqrt(kappa)-scaled (~0.1-0.5 for inliers at this noise,
    # ~20 for gross outliers); the hard TLS cut must sit between.
    (RobustCostType.TLS, dict(tls_threshold=5.0)),
    (RobustCostType.GM, dict()),
    (RobustCostType.L1, dict()),
])
def test_non_gnc_robust_costs_downweight_outliers(rng, cost_type, kw):
    """The reference's RobustCost supports more than GNC_TLS
    (DPGO_robust.cpp:23-67); every weight function must run through the
    actual RBCD reweighting loop and pull outlier weights below inlier
    weights."""
    meas, (Rs, ts) = make_measurements(rng, n=20, d=3, num_lc=10,
                                       outlier_lc=3, rot_noise=0.005,
                                       trans_noise=0.005)
    params = AgentParams(
        d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
        robust=RobustCostParams(cost_type=cost_type, **kw),
        robust_opt_inner_iters=10, rel_change_tol=1e-10,
        solver=SolverParams(grad_norm_tol=1e-6))
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=60, grad_norm_tol=0.0)
    w = np.asarray(res.weights)
    # The 3 outlier loop closures are the last measurements.
    assert w[-3:].max() < w[:-3].min(), (cost_type, w[-6:])
    assert res.cost_history[-1] <= res.cost_history[0]


def test_gnc_corruption_protocol_precision_recall(rng):
    """The corrupted-data benchmark protocol at test scale (VERDICT r3
    item 3): corrupt 20% of the loop closures of a noisy graph with
    gross random poses (``corrupt_loop_closures``), run the full GNC
    annealing from the trusted-odometry init, and pin exact-set
    edge-rejection precision/recall plus trajectory recovery.

    The at-scale version of this (sphere2500/city10000 at 10/20/40%)
    lives in ``experiments/gnc_corruption.py`` with its results table in
    BASELINE.md; this test keeps the protocol itself honest on every
    commit.  Reference anchor: the machinery under test is
    ``updateLoopClosuresWeights`` (``PGOAgent.cpp:1181-1245``) /
    ``RobustCost`` (``DPGO_robust.cpp:23-103``), which the reference only
    ever exercises on hand-made micro graphs (``testUtils.cpp:72-180``).
    """
    from dpgo_tpu.utils.synthetic import (corrupt_loop_closures,
                                          rejection_scores)

    clean, (Rs, ts) = make_measurements(rng, n=120, d=3, num_lc=60,
                                        rot_noise=0.02, trans_noise=0.02)
    meas, outlier_idx = corrupt_loop_closures(clean, 0.2, seed=7)
    assert len(outlier_idx) == 12
    # barc=2: the clean residuals at this noise level reach ~0.3-0.8
    # (sqrt(kappa)-scaled), gross outliers ~20+; the threshold sits
    # between, as the benchmark uses the reference default barc=10 on
    # the real datasets whose inlier residuals are larger.
    params = AgentParams(
        d=3, r=5, num_robots=4, schedule=Schedule.COLORED,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=2.0),
        robust_opt_inner_iters=10, rel_change_tol=0.0,
        solver=SolverParams(grad_norm_tol=1e-6))
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=500, grad_norm_tol=0.0,
                          eval_every=100, init="odometry")
    prec, recall, n_rej = rejection_scores(np.asarray(res.weights), meas,
                                           outlier_idx)
    assert prec >= 0.95, (prec, n_rej)
    assert recall >= 0.95, (recall, n_rej)
    # With the outliers rejected, the iterate must recover the ground
    # truth to noise level despite 20% corruption: the max-abs pose error
    # of a CLEAN (uncorrupted) solve of this graph is ~0.21 (accumulated
    # drift at noise 0.02 under this metric), so 0.45 pins "no worse than
    # ~2x the clean noise floor" while a corruption-driven failure would
    # sit far above 1.
    assert trajectory_error(res.T, Rs, ts) < 0.45


def test_gnc_reinstatement_recovers_over_rejected_edges(rng):
    """The iterated solve's between-pass reinstatement (consensus
    re-test): at heavy corruption the re-anneal over-rejects borderline
    clean edges, and re-testing dropped edges against the cleaner
    iterate must recover precision without losing recall (measured at
    benchmark scale: city10000 40% precision 0.868 -> 0.990, BASELINE.md
    round-4 robustness table)."""
    from dpgo_tpu.utils.synthetic import (corrupt_loop_closures,
                                          rejection_scores)

    clean, _ = make_measurements(rng, n=60, d=3, num_lc=30,
                                 rot_noise=0.02, trans_noise=0.02)
    meas, outlier_idx = corrupt_loop_closures(clean, 0.4, seed=5)
    params = AgentParams(
        d=3, r=5, num_robots=4, schedule=Schedule.COLORED,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS,
                                gnc_barc=2.0),
        robust_opt_inner_iters=10, rel_change_tol=0.0,
        solver=SolverParams(grad_norm_tol=1e-6))
    kw = dict(max_iters=400, grad_norm_tol=0.0, eval_every=100,
              init="odometry")
    _, w2, _ = rbcd.solve_rbcd_robust_iterated(meas, 4, params, passes=2,
                                               **kw)
    _, w3, kept3 = rbcd.solve_rbcd_robust_iterated(meas, 4, params,
                                                   passes=3, **kw)
    p2, r2, _ = rejection_scores(w2, meas, outlier_idx)
    p3, r3, _ = rejection_scores(w3, meas, outlier_idx)
    assert r3 >= 0.95, r3
    assert p3 >= p2 - 1e-9, (p2, p3)
    assert p3 >= 0.9, (p2, p3)
    # Reinstatement must actually have kept more edges than the 2-pass
    # hard-drop would (the small graph over-rejects at 40% corruption).
    assert kept3.sum() >= (w2 >= 0.5).sum()
