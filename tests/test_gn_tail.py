"""Gauss-Newton-CG tail unit suite (``models.refine.gn_tail``): CG
convergence on a small f64 assembly, preconditioner sanity, and the
stall-handoff trigger."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from dpgo_tpu.config import AgentParams  # noqa: E402
from dpgo_tpu.models import rbcd, refine  # noqa: E402
from dpgo_tpu.models.certify import sparse_certificate  # noqa: E402
from dpgo_tpu.ops import manifold, quadratic  # noqa: E402
from dpgo_tpu.types import edge_set_from_measurements  # noqa: E402
from dpgo_tpu.utils.synthetic import make_measurements  # noqa: E402


def _problem(n=60, seed=0, noise=0.05):
    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=n // 2, rot_noise=noise,
                                trans_noise=noise)
    return meas


def _stalled_iterate(meas, rounds=12):
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    prob = rbcd.prepare_problem(meas, 2, params=params, dtype=jnp.float64)
    res = rbcd.dispatch_prepared(prob, max_iters=rounds, eval_every=rounds,
                                 grad_norm_tol=1e-12)
    Xg = np.asarray(rbcd.gather_to_global(jnp.asarray(res.X), prob.graph,
                                          prob.n_total), np.float64)
    edges = edge_set_from_measurements(prob.part.meas_global,
                                       dtype=jnp.float64)
    return Xg, edges


def test_gradient_matches_driver_oracle():
    """X @ S IS the centralized Riemannian gradient: the tail's gate
    quantity agrees with run_rbcd's ``manifold.norm(rgrad)`` oracle."""
    meas = _problem()
    Xg, edges = _stalled_iterate(meas)
    g_ref = manifold.rgrad(jnp.asarray(Xg),
                           quadratic.egrad(jnp.asarray(Xg), edges))
    gn_ref = float(manifold.norm(g_ref))
    S = sparse_certificate(Xg, edges)
    n, r, dh = Xg.shape
    Xf = Xg.transpose(1, 0, 2).reshape(r, n * dh)
    grad = refine._gn_tangent(
        Xg, (Xf @ S).reshape(r, n, dh).transpose(1, 0, 2), 3)
    gn = float(np.sqrt(np.sum(grad * grad)))
    assert abs(gn - gn_ref) <= 1e-9 * max(gn_ref, 1.0)


def test_gn_tail_converges_below_gate():
    """ACCEPTANCE (unit scale): from a BCD iterate far above the gate,
    the tail drives the centralized gradient norm to 1e-6 in a handful
    of outer steps, with monotone f64 cost."""
    meas = _problem()
    Xg, edges = _stalled_iterate(meas)
    t = refine.gn_tail(Xg, edges,
                       refine.GNTailConfig(max_outer=12,
                                           grad_norm_tol=1e-6))
    assert t.converged and t.terminated_by == "grad_norm"
    assert t.grad_norm_history[0] > 1e-2  # genuinely started above
    assert t.grad_norm_history[-1] < 1e-6
    costs = t.cost_history
    assert all(b <= a + 1e-12 for a, b in zip(costs, costs[1:]))
    assert t.outer_iterations <= 12


def test_diag_blocks_match_dense():
    """Preconditioner sanity: the vectorized block extraction equals the
    dense diagonal blocks of S, and the shifted blocks are SPD."""
    meas = _problem(n=30)
    Xg, edges = _stalled_iterate(meas, rounds=4)
    S = sparse_certificate(Xg, edges)
    n, _, dh = Xg.shape
    blocks = refine._gn_diag_blocks(S, n, dh, shift=0.1)
    Sd = S.toarray()
    for i in (0, 7, n - 1):
        ref = Sd[i * dh:(i + 1) * dh, i * dh:(i + 1) * dh] \
            + 0.1 * np.eye(dh)
        assert np.allclose(blocks[i], ref, atol=1e-12)
    # SPD after the shift: Cholesky must succeed on every block.
    np.linalg.cholesky(blocks)


def test_preconditioner_accelerates_cg():
    """The block-Jacobi preconditioner pays: the preconditioned tail
    reaches the gate in no more total CG iterations than a run with the
    preconditioner degraded to (shifted) identity."""
    meas = _problem(n=80, noise=0.08)
    Xg, edges = _stalled_iterate(meas)
    cfg = refine.GNTailConfig(max_outer=8, grad_norm_tol=1e-5)
    t_pre = refine.gn_tail(Xg, edges, cfg)

    orig = refine._gn_diag_blocks
    try:
        refine._gn_diag_blocks = \
            lambda S, n, dh, shift: np.tile(np.eye(dh), (n, 1, 1))
        t_id = refine.gn_tail(Xg, edges, cfg)
    finally:
        refine._gn_diag_blocks = orig
    assert t_pre.converged
    assert t_pre.cg_iterations <= t_id.cg_iterations


def test_stall_handoff_trigger():
    """Trigger fires on a plateaued-above-gate history; stays quiet while
    the trajectory still improves or is already through the gate."""
    assert refine.stall_handoff([1.2] * 10, window=8, grad_norm_tol=0.1)
    improving = [10, 5, 2, 1, 0.5, 0.28, 0.25, 0.22, 0.19, 0.15]
    assert not refine.stall_handoff(improving, window=8)
    assert not refine.stall_handoff([0.05] * 10, window=8,
                                    grad_norm_tol=0.1)
    assert not refine.stall_handoff([1.2] * 5, window=8)  # window unfilled
    assert not refine.stall_handoff([np.nan] * 10, window=8)


def test_no_decrease_terminates_cleanly():
    """At a (near-)stationary point the backtracking line search cannot
    decrease the cost — the tail reports no_decrease/grad_norm instead of
    looping or raising."""
    meas = _problem(n=30)
    Xg, edges = _stalled_iterate(meas, rounds=4)
    t0 = refine.gn_tail(Xg, edges,
                        refine.GNTailConfig(max_outer=20,
                                            grad_norm_tol=1e-9))
    # Restart from the converged point with an unreachable tolerance.
    t1 = refine.gn_tail(t0.X, edges,
                        refine.GNTailConfig(max_outer=5,
                                            grad_norm_tol=0.0,
                                            max_backtracks=3))
    assert t1.terminated_by in ("no_decrease", "max_outer")
    assert np.isfinite(t1.cost_history[-1])
