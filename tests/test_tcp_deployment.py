"""Two-process TCP deployment (examples/tcp_deployment_example.py): the
agent message vocabulary serializes over a real socket and the two-process
solve converges to the in-process solution on smallGrid3D."""

import json
import os
import subprocess
import sys

import pytest

EXAMPLE = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "tcp_deployment_example.py")


def test_two_process_tcp_solve_converges(tmp_path, data_dir):
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--rounds", "60", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    # Both robots reached INITIALIZED and completed every round.
    assert res["states"] == [2, 2]
    assert res["iterations"] == [60, 60]
    assert all(b > 0 for b in res["bytes_sent"])
    # The assembled rounded trajectory matches the in-process 2-agent
    # solution (512.70 on smallGrid3D at r=5; chordal init starts far
    # higher) — the wire did not perturb the math.
    assert res["cost"] < 515.0


def test_four_process_tcp_solve_matches_two(tmp_path, data_dir):
    """N-robot generalization: 4 processes through the launcher's bus
    reach the same smallGrid3D optimum as the 2-process run."""
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--robots", "4", "--rounds", "60", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["states"] == [2, 2, 2, 2]
    # robots >0 initialize via the first pose message, so late robots may
    # run one round fewer — all must have done essentially every round.
    assert all(it >= 59 for it in res["iterations"])
    assert res["cost"] < 515.0  # same optimum as the 2-process run


def test_four_process_robust_tcp_matches_in_process(tmp_path, data_dir):
    """GNC weights over the wire: the 4-process --robust run must land on
    the SAME trajectory cost as the in-process robust 4-agent loop with
    the same exchange schedule (sync mode is deterministic in f64; the
    in-process value at 60 rounds is 2135.651039987529 — measured by
    running both paths; a broken wt_* key round-trip or ownership rule
    would diverge)."""
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--robots", "4", "--rounds", "60", "--robust",
         "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["states"] == [2, 2, 2, 2]
    # Tolerance covers f64 reduction-order drift across toolchains (the
    # two paths matched to all printed digits when measured on one build);
    # a broken wt_* round-trip or ownership rule diverges by orders of
    # magnitude, not fractions.
    assert abs(res["cost"] - 2135.651039987529) < 0.5


def test_four_process_async_tcp_solve(tmp_path, data_dir):
    """Async deployment model over the wire: every robot runs its own
    Poisson-clock optimization thread while the bus exchanges poses —
    still converges to the optimum."""
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--robots", "4", "--rounds", "40", "--mode", "async",
         "--async-rate", "30", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["states"] == [2, 2, 2, 2]
    # Every robot's own thread took at least some steps.  No lower bound
    # tied to the bus-round count: the Poisson-clock thread's effective
    # rate depends on iterate() duration and first-call compile time, so
    # a count assertion would be flaky on loaded machines.
    assert all(it >= 1 for it in res["iterations"])
    assert res["cost"] < 520.0
