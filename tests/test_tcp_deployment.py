"""Two-process TCP deployment (examples/tcp_deployment_example.py): the
agent message vocabulary serializes over a real socket and the two-process
solve converges to the in-process solution on smallGrid3D."""

import json
import os
import subprocess
import sys

import pytest

EXAMPLE = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "tcp_deployment_example.py")


def test_two_process_tcp_solve_converges(tmp_path, data_dir):
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--rounds", "60", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    # Both robots reached INITIALIZED and completed every round.
    assert res["states"] == [2, 2]
    assert res["iterations"] == [60, 60]
    assert all(b > 0 for b in res["bytes_sent"])
    # The assembled rounded trajectory matches the in-process 2-agent
    # solution (512.70 on smallGrid3D at r=5; chordal init starts far
    # higher) — the wire did not perturb the math.
    assert res["cost"] < 515.0
