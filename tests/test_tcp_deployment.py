"""Two-process TCP deployment (examples/tcp_deployment_example.py): the
agent message vocabulary serializes over a real socket and the two-process
solve converges to the in-process solution on smallGrid3D — plus the
fault-injected chaos run over real sockets (drop/delay + a robot killed
mid-solve) degrading gracefully instead of hanging."""

import json
import os
import subprocess
import sys

import numpy as np

EXAMPLE = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "tcp_deployment_example.py")


def test_two_process_tcp_solve_converges(tmp_path, data_dir):
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--rounds", "60", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    # Both robots reached INITIALIZED and completed every round.
    assert res["states"] == [2, 2]
    assert res["iterations"] == [60, 60]
    assert all(b > 0 for b in res["bytes_sent"])
    # The assembled rounded trajectory matches the in-process 2-agent
    # solution (512.70 on smallGrid3D at r=5; chordal init starts far
    # higher) — the wire did not perturb the math.
    assert res["cost"] < 515.0


def test_four_process_tcp_solve_matches_two(tmp_path, data_dir):
    """N-robot generalization: 4 processes through the launcher's bus
    reach the same smallGrid3D optimum as the 2-process run."""
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--robots", "4", "--rounds", "60", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["states"] == [2, 2, 2, 2]
    # robots >0 initialize via the first pose message, so late robots may
    # run one round fewer — all must have done essentially every round.
    assert all(it >= 59 for it in res["iterations"])
    assert res["cost"] < 515.0  # same optimum as the 2-process run


def test_four_process_robust_tcp_matches_in_process(tmp_path, data_dir):
    """GNC weights over the wire: the 4-process --robust run must land on
    the SAME trajectory cost as the in-process robust 4-agent loop with
    the same exchange schedule (sync mode is deterministic in f64; the
    in-process value at 60 rounds is 2135.651039987529 — measured by
    running both paths; a broken wt_* key round-trip or ownership rule
    would diverge)."""
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--robots", "4", "--rounds", "60", "--robust",
         "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["states"] == [2, 2, 2, 2]
    # Tolerance covers f64 reduction-order drift across toolchains (the
    # two paths matched to all printed digits when measured on one build);
    # a broken wt_* round-trip or ownership rule diverges by orders of
    # magnitude, not fractions.
    assert abs(res["cost"] - 2135.651039987529) < 0.5


def test_three_process_tcp_chaos_degrades_gracefully(tmp_path):
    """Real sockets under injected faults (seeded drop + delay) with one
    robot killed mid-solve: the launcher must terminate (no hang), report
    the dead robot in ``lost``, and the survivors must still converge —
    the same acceptance scenario tests/test_chaos.py runs in-process.
    Self-contained dataset (write_g2o) so no external data dir is needed."""
    from dpgo_tpu.utils.g2o import write_g2o
    from dpgo_tpu.utils.synthetic import make_measurements

    meas, _ = make_measurements(np.random.default_rng(0), n=36, d=3,
                                num_lc=18, rot_noise=0.01, trans_noise=0.01)
    dataset = str(tmp_path / "chaos.g2o")
    write_g2o(meas, dataset)
    out = subprocess.run(
        [sys.executable, EXAMPLE, dataset,
         "--robots", "3", "--rounds", "40", "--round-timeout", "3",
         "--fault-drop", "0.1", "--fault-delay", "0.2",
         "--fault-delay-s", "0.02", "0.1", "--fault-seed", "7",
         "--kill-robot", "2", "--kill-round", "25",
         "--out-dir", str(tmp_path / "run")],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["lost"] == [2]
    assert res["states"][:2] == [2, 2] and res["states"][2] is None
    # Survivors completed essentially every round despite the faults.
    assert all(it >= 35 for it in res["iterations"][:2])
    # Cost is evaluated over the surviving robots' edges and must be a
    # sane optimum (chordal init starts orders of magnitude higher).
    assert res["cost"] < 100.0


def test_four_process_async_tcp_solve(tmp_path, data_dir):
    """Async deployment model over the wire: every robot runs its own
    Poisson-clock optimization thread while the bus exchanges poses —
    still converges to the optimum."""
    out = subprocess.run(
        [sys.executable, EXAMPLE, f"{data_dir}/smallGrid3D.g2o",
         "--robots", "4", "--rounds", "40", "--mode", "async",
         "--async-rate", "30", "--out-dir", str(tmp_path)],
        env=dict(os.environ, DPGO_PLATFORM="cpu"),
        capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["states"] == [2, 2, 2, 2]
    # Every robot's own thread took at least some steps.  No lower bound
    # tied to the bus-round count: the Poisson-clock thread's effective
    # rate depends on iterate() duration and first-call compile time, so
    # a count assertion would be flaky on loaded machines.
    assert all(it >= 1 for it in res["iterations"])
    assert res["cost"] < 520.0
