"""Convergence regression gate (``dpgo_tpu.obs.regress`` /
``report --compare``): clean seeded runs pass, synthetic regressions fail
with rc 2 and a readable delta table, mismatched fingerprints are refused."""

import json
import os
import shutil

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.obs.regress import compare_runs, tail_band
from dpgo_tpu.obs.report import main as report_main


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _tiny_problem(n=40, num_lc=20, seed=0):
    from dpgo_tpu.utils.synthetic import make_measurements

    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=num_lc, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _solve_into(run_dir, seed=0, num_robots=2, max_iters=8):
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.models import rbcd

    with obs.run_scope(run_dir):
        rbcd.solve_rbcd(_tiny_problem(seed=seed), num_robots,
                        params=AgentParams(d=3, r=5, num_robots=num_robots,
                                           rel_change_tol=1e-16),
                        max_iters=max_iters, eval_every=2,
                        grad_norm_tol=1e-12, dtype=jnp.float64)


def test_tail_band_matches_cpu_arm_band_schema():
    band = tail_band([3.0, 1.0, 2.0, 4.0], k=3)
    # The cpu_arm_band key set of bench.py's metric_record.
    assert {"min", "median", "max", "windows"} <= set(band)
    assert band["min"] == 1.0 and band["max"] == 4.0
    assert band["median"] == 2.0
    nanband = tail_band([float("nan")])
    assert np.isnan(nanband["median"])


def test_clean_seeded_runs_compare_equal(tmp_path, capsys):
    a, b = str(tmp_path / "runA"), str(tmp_path / "runB")
    _solve_into(a, seed=0)
    _solve_into(b, seed=0)
    cmp = compare_runs(a, b)
    assert cmp["rc"] == 0 and cmp["regressions"] == []
    assert cmp["fingerprint_mismatches"] == {}
    # The deterministic CPU trajectories are identical.
    assert cmp["metrics"]["solver_cost"]["max_rel_deviation"] == 0.0
    assert report_main(["--compare", a, b]) == 0
    out = capsys.readouterr().out
    assert "no regression" in out
    # --json emits the machine document.
    assert report_main(["--compare", a, b, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rc"] == 0


def test_corrupted_metric_fails_with_rc2(tmp_path, capsys):
    """The CI scenario: copy a clean run, inflate its final solver_cost,
    compare must fail rc 2 with a human-readable delta table."""
    a, c = str(tmp_path / "runA"), str(tmp_path / "runC")
    _solve_into(a, seed=0)
    shutil.copytree(a, c)
    ev_path = os.path.join(c, "events.jsonl")
    lines = open(ev_path).read().splitlines()
    out, seen = [], 0
    cost_lines = sum(1 for ln in lines if '"metric": "solver_cost"' in ln)
    for ln in lines:
        if '"metric": "solver_cost"' in ln:
            seen += 1
            if seen == cost_lines:  # corrupt the FINAL cost event
                ev = json.loads(ln)
                ev["value"] = ev["value"] * 10.0
                ln = json.dumps(ev)
        out.append(ln)
    open(ev_path, "w").write("\n".join(out) + "\n")

    assert report_main(["--compare", a, c]) == 2
    text = capsys.readouterr().out
    assert "REGRESSED" in text and "solver_cost" in text
    assert "REGRESSION" in text
    # Direction matters: the corrupted run as baseline sees an
    # IMPROVEMENT, which does not regress.
    assert report_main(["--compare", c, a]) == 0
    capsys.readouterr()


def test_nonfinite_final_value_regresses(tmp_path):
    a, c = str(tmp_path / "runA"), str(tmp_path / "runC")
    _solve_into(a, seed=0)
    shutil.copytree(a, c)
    ev_path = os.path.join(c, "events.jsonl")
    lines = open(ev_path).read().splitlines()
    for i in range(len(lines) - 1, -1, -1):
        if '"metric": "solver_grad_norm"' in lines[i]:
            ev = json.loads(lines[i])
            ev["value"] = "NaN"  # the canonical non-finite serialization
            lines[i] = json.dumps(ev)
            break
    open(ev_path, "w").write("\n".join(lines) + "\n")
    cmp = compare_runs(a, c)
    assert "solver_grad_norm" in cmp["regressions"]
    assert cmp["metrics"]["solver_grad_norm"]["reason"] \
        == "non-finite final value"


def test_critical_anomalies_regress_even_with_equal_metrics(tmp_path):
    a, c = str(tmp_path / "runA"), str(tmp_path / "runC")
    _solve_into(a, seed=0)
    shutil.copytree(a, c)
    with open(os.path.join(c, "events.jsonl"), "a") as fh:
        fh.write(json.dumps({"run": "x", "seq": 999, "t_wall": 0.0,
                             "t_mono": 0.0, "event": "anomaly",
                             "kind": "non_finite",
                             "severity": "critical"}) + "\n")
    cmp = compare_runs(a, c)
    assert cmp["rc"] == 2 and "anomalies" in cmp["regressions"]


def test_fingerprint_mismatch_refused(tmp_path, capsys):
    """Apples-to-oranges comparisons (different robot counts here) are
    refused with a clear message; --allow-mismatch overrides."""
    a, b = str(tmp_path / "runA"), str(tmp_path / "runB")
    _solve_into(a, seed=0, num_robots=2)
    _solve_into(b, seed=0, num_robots=4)
    assert report_main(["--compare", a, b]) == 2
    out = capsys.readouterr().out
    assert "REFUSED" in out and "num_robots" in out
    assert "2" in out and "4" in out
    # Override: compared anyway, mismatches noted.
    rc = report_main(["--compare", a, b, "--allow-mismatch"])
    out = capsys.readouterr().out
    assert "overridden" in out
    assert rc in (0, 2)  # gate result now depends on the actual deltas


def test_compare_rejects_non_run_dir(tmp_path, capsys):
    a = str(tmp_path / "runA")
    _solve_into(a, seed=0)
    assert report_main(["--compare", a, str(tmp_path / "nope")]) == 2
    assert "not a telemetry run" in capsys.readouterr().err


def test_fingerprint_persisted_into_run_json(tmp_path):
    a = str(tmp_path / "runA")
    _solve_into(a, seed=0)
    meta = json.load(open(os.path.join(a, "run.json")))
    fp = meta["fingerprint"]
    assert fp["num_robots"] == 2 and fp["rank"] == 5
    assert fp["dtype"] == "float64"
    assert "version" in fp


def _qps_run_into(run_dir, values):
    """A minimal run whose only gated trajectory is ``fleet_qps`` —
    fingerprint-free (no solve), so any two such runs are comparable."""
    with obs.run_scope(run_dir) as run:
        for v in values:
            run.metric("fleet_qps", float(v), unit="1/s")


def test_higher_direction_metric_regresses_on_drop(tmp_path, capsys):
    """``fleet_qps`` gates the OTHER way: run B's final value falling
    below run A's band MIN (beyond rtol) regresses; matching or beating
    the band does not (ISSUE 13)."""
    a = str(tmp_path / "runA")
    _qps_run_into(a, [4.0, 4.2, 4.1, 4.3, 4.2])

    ok = str(tmp_path / "runOK")
    _qps_run_into(ok, [4.0, 4.1, 4.4, 4.5, 4.6])  # higher: never regresses
    assert report_main(["--compare", a, ok]) == 0
    capsys.readouterr()

    bad = str(tmp_path / "runBAD")
    _qps_run_into(bad, [4.0, 4.1, 4.2, 4.1, 2.0])  # final far below band min
    assert report_main(["--compare", a, bad]) == 2
    text = capsys.readouterr().out
    assert "fleet_qps" in text and "REGRESSED" in text
    cmp = compare_runs(a, bad)
    assert "fleet_qps" in cmp["regressions"]
    assert "below band min" in cmp["metrics"]["fleet_qps"]["reason"]
    # The same drop as baseline-vs-improvement does not regress.
    assert report_main(["--compare", bad, a]) == 0
    capsys.readouterr()
