"""Session durability (``serve.session``) and server elasticity: snapshot
robustness (truncation / bit flips / wrong schema -> quarantine fallback,
never a crash), the worker crash-recovery path, and graceful drain.

The crash-recovery acceptance test deliberately carries NO ``allow_leaks``
marker: the leakcheck plugin asserting zero orphan threads/sockets after a
mid-batch worker kill + recovery IS part of the contract."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams
from dpgo_tpu.models.incremental import state_to_arrays
from dpgo_tpu.serve import (OverCapacityError, SessionStore, SolveRequest,
                            SolveServer)
from dpgo_tpu.serve import server as server_mod
from dpgo_tpu.serve.session import SESSION_SCHEMA_VERSION
from dpgo_tpu.utils.synthetic import make_measurements

PARAMS = AgentParams(d=3, r=5, num_robots=2)


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _problem(seed=0, n=24):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=8, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _solved_state(meas):
    from dpgo_tpu.models.incremental import LiveProblem

    live = LiveProblem(meas, 2, params=PARAMS)
    res = live.solve(max_iters=6, grad_norm_tol=1e-9)
    return res.state


# ---------------------------------------------------------------------------
# SessionStore robustness (satellite: corrupt snapshots must quarantine)
# ---------------------------------------------------------------------------

def test_store_round_trip_and_prune(tmp_path):
    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"), keep=2)
    for it in (10, 20, 30):
        store.save("sess", st, iteration=it, meta={"tenant": "t"})
    sdir = tmp_path / "s" / "sess"
    names = sorted(p.name for p in sdir.iterdir())
    assert names == ["snap-00000020.npz", "snap-00000030.npz"]  # pruned
    snap = store.load_newest("sess")
    assert snap.iteration == 30 and snap.meta == {"tenant": "t"}
    for f, v in state_to_arrays(st).items():
        np.testing.assert_array_equal(np.asarray(getattr(snap.state, f)), v)
    store.discard("sess")
    assert store.load_newest("sess") is None
    assert not sdir.exists()


@pytest.mark.parametrize("corrupt", ["truncate", "bitflip", "schema"])
def test_corrupt_newest_falls_back_to_previous(tmp_path, corrupt):
    """Truncated / bit-flipped / wrong-schema newest snapshot: quarantined
    aside, the previous one loads; no exception escapes."""
    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"), keep=3)
    store.save("sess", st, iteration=10)
    if corrupt == "schema":
        arrays = state_to_arrays(st)
        arrays["__schema__"] = np.asarray(SESSION_SCHEMA_VERSION + 7)
        arrays["__iteration__"] = np.asarray(20)
        arrays["__nwu__"] = np.asarray(0)
        path = tmp_path / "s" / "sess" / "snap-00000020.npz"
        with open(path, "wb") as fh:
            np.savez_compressed(fh, **arrays)
    else:
        store.save("sess", st, iteration=20)
        path = tmp_path / "s" / "sess" / "snap-00000020.npz"
        blob = bytearray(path.read_bytes())
        if corrupt == "truncate":
            path.write_bytes(bytes(blob[: len(blob) // 3]))
        else:
            blob[len(blob) // 2] ^= 0xFF  # flip bits mid-zip-stream
            path.write_bytes(bytes(blob))
    snap = store.load_newest("sess")
    assert snap is not None and snap.iteration == 10
    names = sorted(p.name for p in (tmp_path / "s" / "sess").iterdir())
    assert "snap-00000020.npz.quarantined" in names
    assert "snap-00000020.npz" not in names
    # quarantined files are never retried
    assert store.load_newest("sess").iteration == 10


@pytest.mark.parametrize("kill_at", ["mid_write", "pre_replace"])
def test_sigkill_mid_save_leaves_store_loadable(tmp_path, kill_at):
    """A writer SIGKILLed MID-SAVE — the out-of-process fleet's failure
    mode: a replica child dies with a snapshot half-written.  The
    tmp+rename discipline means the torn artifact is always a ``.tmp``
    the snapshot regex never admits: the previous boundary keeps
    loading, nothing needs quarantining, and the next writer simply
    reuses the name.  This extends the 3-way corruption matrix with an
    ACTUAL ``kill -9`` (rc -9), not a simulated truncation."""
    import os
    import signal
    import subprocess
    import sys
    import textwrap

    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"), keep=3)
    store.save("sess", st, iteration=10)

    script = textwrap.dedent(f"""
        import io, os, signal
        import numpy as np
        from dpgo_tpu.serve import session as session_mod
        from dpgo_tpu.serve.session import SessionStore

        store = SessionStore({str(tmp_path / "s")!r}, keep=3)
        snap = store.load_newest("sess")

        if {kill_at!r} == "mid_write":
            real = np.savez_compressed

            def torn(fh, **arrays):
                buf = io.BytesIO()
                real(buf, **arrays)
                data = buf.getvalue()
                fh.write(data[: len(data) // 2])
                fh.flush()
                os.fsync(fh.fileno())
                os.kill(os.getpid(), signal.SIGKILL)

            session_mod.np.savez_compressed = torn
        else:  # pre_replace: full tmp written+fsynced, rename never ran

            def boom(src, dst):
                os.kill(os.getpid(), signal.SIGKILL)

            session_mod.os.replace = boom

        store.save("sess", snap.state, iteration=20)
        raise SystemExit("unreachable: the save must have died")
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH="/root/repo" + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, proc.stderr

    sdir = tmp_path / "s" / "sess"
    names = sorted(p.name for p in sdir.iterdir())
    assert "snap-00000010.npz" in names
    assert "snap-00000020.npz" not in names
    assert "snap-00000020.npz.tmp" in names  # the torn artifact
    assert store.load_newest("sess").iteration == 10
    # The next writer (the respawned replica) reuses the name; the
    # stale tmp is overwritten, never read.
    store.save("sess", st, iteration=20)
    assert store.load_newest("sess").iteration == 20
    assert "snap-00000020.npz.tmp" not in sorted(
        p.name for p in sdir.iterdir())


def test_v1_snapshot_loads_under_v2_reader(tmp_path):
    """Schema back-compat (ISSUE 14): a v1-era snapshot (no mesh tags)
    is a strict subset of v2 and must keep loading — mesh_shape /
    global_index simply come back None."""
    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"))
    arrays = state_to_arrays(st)
    arrays["__schema__"] = np.asarray(1, np.int64)
    arrays["__iteration__"] = np.asarray(40, np.int64)
    arrays["__nwu__"] = np.asarray(3, np.int64)
    sdir = tmp_path / "s" / "sess"
    sdir.mkdir(parents=True)
    with open(sdir / "snap-00000040.npz", "wb") as fh:
        np.savez_compressed(fh, **arrays)
    snap = store.load_newest("sess")
    assert snap is not None and snap.iteration == 40
    assert snap.num_weight_updates == 3
    assert snap.mesh_shape is None and snap.global_index is None
    for f, v in state_to_arrays(st).items():
        np.testing.assert_array_equal(np.asarray(getattr(snap.state, f)), v)


def test_mesh_tagged_snapshot_round_trips_and_old_reader_fails_open(
        tmp_path, monkeypatch):
    """Mesh-tagged v2 snapshots (parallel.resilience) round-trip the
    mesh shape + global-index layout; a v1-era reader (emulated by
    pinning _COMPAT_SCHEMAS back to (1,)) refuses them — quarantined,
    then fail-open to an older v1 snapshot rather than mis-resuming."""
    from dpgo_tpu.serve import session as session_mod

    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"), keep=3)
    # An old v1 snapshot underneath...
    arrays = state_to_arrays(st)
    arrays["__schema__"] = np.asarray(1, np.int64)
    arrays["__iteration__"] = np.asarray(10, np.int64)
    sdir = tmp_path / "s" / "sess"
    sdir.mkdir(parents=True)
    with open(sdir / "snap-00000010.npz", "wb") as fh:
        np.savez_compressed(fh, **arrays)
    # ...then a newer mesh-tagged v2 one.
    gidx = np.arange(48).reshape(2, 24)
    store.save("sess", st, iteration=20, mesh_shape=(8,),
               global_index=gidx)
    snap = store.load_newest("sess")
    assert snap.iteration == 20 and snap.mesh_shape == (8,)
    np.testing.assert_array_equal(snap.global_index, gidx)

    # The v1-era reader: quarantines the v2 file, falls back to v1.
    monkeypatch.setattr(session_mod, "_COMPAT_SCHEMAS", (1,))
    old = store.load_newest("sess")
    assert old is not None and old.iteration == 10
    names = sorted(p.name for p in sdir.iterdir())
    assert "snap-00000020.npz.quarantined" in names
    assert "snap-00000020.npz" not in names


def test_all_snapshots_corrupt_yields_none(tmp_path):
    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"))
    store.save("sess", st, iteration=10)
    p = tmp_path / "s" / "sess" / "snap-00000010.npz"
    p.write_bytes(b"not a zip at all")
    assert store.load_newest("sess") is None


def test_save_async_read_after_save_and_flush(tmp_path):
    """Off-thread writes (the checkpoint-overlap satellite): save_async
    returns the promised path immediately, and load_newest drains the
    writer first — a read-after-save always sees the snapshot the save
    promised, with no explicit flush() at the call site."""
    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"), keep=4, async_write=True)
    path = store.save_async("sess", st, iteration=10)
    assert path.endswith("snap-00000010.npz")
    snap = store.load_newest("sess")
    assert snap is not None and snap.iteration == 10
    assert store.flush(timeout=10)
    assert store.last_write_error is None


def test_save_async_last_writer_wins(tmp_path):
    """The pending slot is ONE deep: with the writer pinned mid-write, a
    third save replaces the unwritten second — a slow disk coalesces to
    the freshest boundary instead of queueing a stale backlog."""
    meas = _problem()
    st = _solved_state(meas)
    store = SessionStore(str(tmp_path / "s"), keep=8, async_write=True)
    gate, started = threading.Event(), threading.Event()
    orig_write = store._write

    def slow_write(session_id, arrays, iteration):
        started.set()
        assert gate.wait(10)
        return orig_write(session_id, arrays, iteration)

    store._write = slow_write
    store.save_async("sess", st, iteration=1)
    assert started.wait(10)                    # writer busy on snap-1
    store.save_async("sess", st, iteration=2)  # parked in the slot
    store.save_async("sess", st, iteration=3)  # replaces 2
    gate.set()
    assert store.flush(timeout=10)
    names = sorted(p.name for p in (tmp_path / "s" / "sess").iterdir())
    assert names == ["snap-00000001.npz", "snap-00000003.npz"]
    assert store.load_newest("sess").iteration == 3
    assert store.last_write_error is None


def test_session_id_sanitization(tmp_path):
    store = SessionStore(str(tmp_path / "s"))
    meas = _problem()
    st = _solved_state(meas)
    store.save("tenant/../../evil", st, iteration=1)
    (entry,) = (tmp_path / "s").iterdir()
    # no path separators survive: the session dir sits directly under the
    # store root, whatever the id contained
    assert "/" not in entry.name and "\\" not in entry.name
    assert entry.parent == tmp_path / "s"
    assert store.load_newest("tenant/../../evil").iteration == 1


# ---------------------------------------------------------------------------
# Crash recovery (ACCEPTANCE) — no allow_leaks: leakcheck must stay clean
# ---------------------------------------------------------------------------

class _WorkerKilled(BaseException):
    """Escapes ``_run_batch``'s Exception handling — the in-test stand-in
    for a mid-batch worker death (TaskStop, OOM-killer, fatal runtime)."""


def test_worker_killed_mid_batch_recovers_from_snapshot(tmp_path,
                                                        monkeypatch):
    """ACCEPTANCE: the worker dies mid-batch after a session snapshot
    landed; the supervisor respawns, re-admits the request from the
    snapshot, the reply completes with ``recovered=True``,
    ``session_recoveries_total`` increments — and the leakcheck plugin
    (active, no opt-out) sees no orphan threads/sockets."""
    meas = _problem()
    real_run_bucket = server_mod.run_bucket
    calls = {"n": 0}

    def killer(padded, cache, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            # Real work first so a boundary snapshot lands, then die the
            # way a killed worker does: nothing catches BaseException on
            # the batch path.
            real_run_bucket(padded, cache, max_iters=4,
                            grad_norm_tol=kw["grad_norm_tol"],
                            eval_every=kw["eval_every"],
                            session_cb=kw["session_cb"], session_every=1)
            raise _WorkerKilled("killed mid-batch")
        return real_run_bucket(padded, cache, **kw)

    monkeypatch.setattr(server_mod, "run_bucket", killer)
    with obs.run_scope(str(tmp_path / "run")) as run:
        store = SessionStore(str(tmp_path / "sessions"))
        with SolveServer(max_batch=2, batch_window_s=0.0,
                         session_store=store) as srv:
            t = srv.submit(SolveRequest(
                meas=meas, num_robots=2, params=PARAMS, max_iters=40,
                grad_norm_tol=1e-3, session_id="tenant-a-42"))
            res = t.result(timeout=300)
            assert res.recovered is True
            assert calls["n"] == 2  # died once, completed on respawn
            assert srv.status()["worker_crashes"] == 1
        snap = run.registry.snapshot()
    families = [v for k, v in snap.items()
                if "session_recoveries_total" in k]
    assert families and families[0]["series"][0]["value"] == 1.0
    # the finished session's snapshots were discarded
    assert store.load_newest("tenant-a-42") is None


def test_worker_kill_without_session_fails_cleanly(monkeypatch, tmp_path):
    """No session id -> nothing to recover: the request fails with a
    clear error, the server stays alive for the next request."""
    meas = _problem()
    real_run_bucket = server_mod.run_bucket
    calls = {"n": 0}

    def killer(padded, cache, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise _WorkerKilled("killed")
        return real_run_bucket(padded, cache, **kw)

    monkeypatch.setattr(server_mod, "run_bucket", killer)
    with SolveServer(max_batch=2, batch_window_s=0.0,
                     session_store=SessionStore(str(tmp_path))) as srv:
        t = srv.submit(SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                                    max_iters=10, grad_norm_tol=1e-3))
        with pytest.raises(RuntimeError, match="died mid-batch"):
            t.result(timeout=300)
        # the respawned worker serves the next request normally
        t2 = srv.submit(SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                                     max_iters=10, grad_norm_tol=1e-3))
        assert t2.result(timeout=300).recovered is False


def test_crash_loop_gives_up_and_sheds(monkeypatch, tmp_path):
    meas = _problem()

    def always_dies(padded, cache, **kw):
        raise _WorkerKilled("again")

    monkeypatch.setattr(server_mod, "run_bucket", always_dies)
    srv = SolveServer(max_batch=2, batch_window_s=0.0, worker_restarts=0)
    try:
        t = srv.submit(SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                                    max_iters=10))
        with pytest.raises((OverCapacityError, RuntimeError)):
            t.result(timeout=300)
        deadline = time.monotonic() + 30
        while srv._worker.is_alive() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not srv._worker.is_alive()  # gave up: no crash-looping
        with pytest.raises(RuntimeError, match="closed"):
            srv.submit(SolveRequest(meas=meas, num_robots=2, params=PARAMS))
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Graceful drain (satellite)
# ---------------------------------------------------------------------------

def test_close_drain_stops_admission_and_reports(tmp_path, monkeypatch):
    """close(drain=True): in-flight batch finishes and replies; admission
    during the drain is a STRUCTURED shed (reason=closed); /healthz says
    draining (200) for the window and 503 only once closed."""
    meas = _problem()
    gate = threading.Event()
    release = threading.Event()
    real_run_bucket = server_mod.run_bucket

    def slow(padded, cache, **kw):
        gate.set()
        assert release.wait(60)
        return real_run_bucket(padded, cache, **kw)

    monkeypatch.setattr(server_mod, "run_bucket", slow)
    with obs.run_scope(str(tmp_path / "run")):
        srv = SolveServer(max_batch=1, batch_window_s=0.0, metrics_port=0)
        base = f"http://{srv.sidecar.host}:{srv.sidecar.port}"
        t1 = srv.submit(SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                                     max_iters=6, grad_norm_tol=1e-3))
        assert gate.wait(60)  # batch in flight and parked

        closer = threading.Thread(target=lambda: srv.close(drain=True))
        closer.start()
        deadline = time.monotonic() + 10
        while not srv.status()["draining"] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.status()["draining"] is True
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            body = json.loads(r.read())
            assert r.status == 200 and body["draining"] is True

        # admission during drain: structured shed, not a bare error
        with pytest.raises(OverCapacityError) as exc:
            srv.submit(SolveRequest(meas=meas, num_robots=2, params=PARAMS))
        assert exc.value.reason == "closed"

        release.set()
        closer.join(timeout=120)
        assert not closer.is_alive()
        assert t1.result(timeout=60).iterations >= 1  # in-flight completed
        st = srv.status()
        assert st["closed"] is True and st["draining"] is False
        # Once closed, /healthz is 503 for as long as the sidecar still
        # answers, then the endpoint disappears with it — either way the
        # 200/draining phase is over.
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=5) as r:
                raise AssertionError(f"healthz still ok after close: "
                                     f"{r.status}")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            e.close()
        except urllib.error.URLError:
            pass  # sidecar already down
