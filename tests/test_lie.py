"""Tests for rotation/Stiefel primitives, mirroring reference tests/testUtils.cpp."""

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.utils import lie


def test_fixed_stiefel_orthonormal_and_deterministic():
    # Mirrors testUtils.cpp:12-25 (orthonormality + determinism across calls).
    d, r = 3, 5
    Y1 = np.asarray(lie.fixed_stiefel(r, d, dtype=jnp.float64))
    Y2 = np.asarray(lie.fixed_stiefel(r, d, dtype=jnp.float64))
    assert np.allclose(Y1.T @ Y1, np.eye(d), atol=1e-12)
    assert np.array_equal(Y1, Y2)


@pytest.mark.parametrize("d,r", [(2, 2), (3, 3), (3, 5), (2, 5)])
def test_project_to_stiefel(rng, d, r):
    # Mirrors testUtils.cpp:27-37 (random-matrix projection, 50 trials batched).
    M = rng.standard_normal((50, r, d))
    Y = np.asarray(lie.project_to_stiefel(jnp.asarray(M)))
    eye = np.broadcast_to(np.eye(d), (50, d, d))
    assert np.allclose(np.swapaxes(Y, -1, -2) @ Y, eye, atol=1e-10)


@pytest.mark.parametrize("d", [2, 3])
def test_project_to_rotation(rng, d):
    M = rng.standard_normal((100, d, d))
    R = np.asarray(lie.project_to_rotation(jnp.asarray(M)))
    eye = np.broadcast_to(np.eye(d), (100, d, d))
    assert np.allclose(np.swapaxes(R, -1, -2) @ R, eye, atol=1e-10)
    assert np.allclose(np.linalg.det(R), 1.0, atol=1e-10)


def test_project_to_rotation_chunked_matches_batch(rng, monkeypatch):
    """The >_SVD_CHUNK path (pad + lax.map + slice, used by 100k-pose cold
    init) must match the single-batch projection on a non-multiple size."""
    monkeypatch.setattr(lie, "_SVD_CHUNK", 8)
    M = rng.standard_normal((27, 3, 3))
    R = np.asarray(lie.project_to_rotation(jnp.asarray(M)))
    R_ref = np.asarray(lie._project_to_rotation_batch(jnp.asarray(M)))
    assert np.allclose(R, R_ref, atol=1e-12)


def test_project_to_rotation_fixes_reflection():
    # A reflection must be mapped to a proper rotation, not itself.
    M = np.diag([1.0, 1.0, -1.0])
    R = np.asarray(lie.project_to_rotation(jnp.asarray(M)))
    assert np.allclose(np.linalg.det(R), 1.0, atol=1e-12)


def test_quat_roundtrip(rng):
    q = rng.standard_normal((200, 4))
    q /= np.linalg.norm(q, axis=-1, keepdims=True)
    R = lie.quat_to_rotation(q)
    eye = np.broadcast_to(np.eye(3), (200, 3, 3))
    assert np.allclose(np.swapaxes(R, -1, -2) @ R, eye, atol=1e-12)
    assert np.allclose(np.linalg.det(R), 1.0, atol=1e-12)
    q2 = lie.rotation_to_quat(R)
    R2 = lie.quat_to_rotation(q2)
    assert np.allclose(R, R2, atol=1e-10)


def test_rotation2d():
    R = lie.rotation2d(np.pi / 2)
    assert np.allclose(R, [[0, -1], [1, 0]], atol=1e-12)


def test_chi2inv_matches_empirical(rng):
    # Mirrors testUtils.cpp:55-70: quantile vs empirical quantile of samples.
    quantile, dof = 0.9, 3
    thresh = lie.chi2inv(quantile, dof)
    samples = rng.chisquare(dof, size=100_000)
    frac = np.mean(samples < thresh)
    assert abs(frac - quantile) < 0.01


def test_angular_to_chordal():
    assert lie.angular_to_chordal_so3(0.0) == 0.0
    # A rotation by pi about z has chordal distance ||R - I||_F = 2*sqrt(2).
    assert np.isclose(lie.angular_to_chordal_so3(np.pi), 2 * np.sqrt(2))
    Rz = lie.quat_to_rotation(np.array([0.0, 0.0, np.sin(0.3), np.cos(0.3)]))
    ang = 0.6
    assert np.isclose(np.linalg.norm(Rz - np.eye(3)), lie.angular_to_chordal_so3(ang))


def test_random_stiefel_batch():
    import jax

    Y = lie.random_stiefel(jax.random.PRNGKey(0), 5, 3, batch=(7,), dtype=jnp.float64)
    Y = np.asarray(Y)
    eye = np.broadcast_to(np.eye(3), (7, 3, 3))
    assert np.allclose(np.swapaxes(Y, -1, -2) @ Y, eye, atol=1e-12)


def test_check_rotation_matrix(rng):
    """checkRotationMatrix parity (reference DPGO_utils.cpp:526-531)."""
    from dpgo_tpu.utils.synthetic import random_rotation

    R = random_rotation(rng)
    assert lie.check_rotation_matrix(R)
    assert not lie.check_rotation_matrix(2.0 * R)          # not orthonormal
    Rf = R.copy()
    Rf[:, 0] *= -1.0                                        # det -1
    assert not lie.check_rotation_matrix(Rf)
    batch = np.stack([R, Rf])
    assert lie.check_rotation_matrix(batch).tolist() == [True, False]
