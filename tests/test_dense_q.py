"""Dense-Q local problem formulation vs the edge-list reference path."""

import jax
import jax.numpy as jnp
import numpy as np

from dpgo_tpu.config import AgentParams, Schedule
from dpgo_tpu.models import rbcd
from dpgo_tpu.ops import quadratic
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements


def _setup(rng, n=24, A=4):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=n // 2,
                                rot_noise=0.05, trans_noise=0.05)
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, rank=5, dtype=jnp.float64)
    Xa = jnp.asarray(np.random.default_rng(7).standard_normal(
        (A, meta.n_max, 5, 4)))
    Z = rbcd.neighbor_buffer(rbcd.public_table(Xa, graph), graph)
    return graph, meta, Xa, Z


def test_to_from_mat_roundtrip(rng):
    X = jnp.asarray(rng.standard_normal((6, 5, 4)))
    assert np.allclose(quadratic.from_mat(quadratic.to_mat(X), 6), X)


def test_dense_q_problem_matches_edges(rng):
    graph, meta, Xa, Z = _setup(rng)
    qbuf = rbcd.dense_q_all(graph.edges, meta)
    params = AgentParams(d=3, r=5, num_robots=4)
    chol = rbcd.precond_chol(graph.edges, meta.n_max, meta.s_max, params)
    for a in range(4):
        e = jax.tree.map(lambda x: x[a], graph.edges)
        pd = rbcd._agent_local_problem(Z[a], e, chol[a], meta.n_max,
                                       qbuf=qbuf[a])
        pe = rbcd._agent_local_problem(Z[a], e, chol[a], meta.n_max,
                                       inc=(graph.inc_slot[a],
                                            graph.inc_mask[a]))
        x = Xa[a]
        # Cost including the constant neighbor-neighbor-free term matches
        # the edge-sum cost exactly.
        assert np.allclose(pd.cost(x), pe.cost(x), atol=1e-9)
        assert np.allclose(pd.egrad(x), pe.egrad(x), atol=1e-9)
        V = jnp.asarray(np.random.default_rng(a).standard_normal(x.shape))
        assert np.allclose(pd.ehess(x, V), pe.ehess(x, V), atol=1e-9)


def test_rbcd_dense_matches_ell_rounds(rng):
    """Full RBCD rounds agree (to fp tolerance) whether the dense-Q or the
    ELL path runs."""
    from dpgo_tpu.config import SolverParams

    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10,
                                rot_noise=0.05, trans_noise=0.05)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI,
                         solver=SolverParams(dense_quadratic=True))
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    assert rbcd.use_dense_q(meta, params, itemsize=8)
    params_ell = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI)
    s_dense = rbcd.init_state(graph, meta, X0, params=params)
    assert s_dense.Qbuf is not None
    s_ell = rbcd.init_state(graph, meta, X0, params=params_ell)
    assert s_ell.Qbuf is None
    for _ in range(5):
        s_dense = rbcd.rbcd_step(s_dense, graph, meta, params)
        s_ell = rbcd.rbcd_step(s_ell, graph, meta, params_ell)
    assert np.allclose(s_dense.X, s_ell.X, atol=1e-7)


def test_dense_opt_in_without_qbuf_raises(rng):
    """dense_quadratic=True with a state lacking Qbuf raises instead of
    silently running another formulation (mirrors the forced-Pallas
    behavior)."""
    from dpgo_tpu.config import SolverParams

    meas, _ = make_measurements(rng, n=12, d=3, num_lc=4)
    params_d = AgentParams(d=3, r=5, num_robots=2,
                           solver=SolverParams(dense_quadratic=True))
    params_e = AgentParams(d=3, r=5, num_robots=2)
    part = partition_contiguous(meas, 2)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params_e)  # no Qbuf
    import pytest

    with pytest.raises(ValueError, match="no Qbuf"):
        rbcd.rbcd_step(state, graph, meta, params_d)


def test_use_dense_q_budget():
    from dpgo_tpu.config import SolverParams

    meta_small = rbcd.GraphMeta(num_robots=8, n_max=316, e_max=675,
                                s_max=100, p_max=100, d=3, rank=5)
    on = AgentParams(d=3, r=5, num_robots=8,
                     solver=SolverParams(dense_quadratic=True))
    assert rbcd.use_dense_q(meta_small, on, itemsize=4)
    assert not rbcd.use_dense_q(meta_small, AgentParams(d=3, r=5,
                                                        num_robots=8),
                                itemsize=4)
    assert not rbcd.use_dense_q(meta_small, None, itemsize=4)
    meta_huge = rbcd.GraphMeta(num_robots=64, n_max=100000, e_max=300000,
                               s_max=1000, p_max=1000, d=3, rank=5)
    assert not rbcd.use_dense_q(meta_huge, on, itemsize=4)
    # The itemsize must reflect the problem dtype: a float64 graph doubles
    # the footprint and can flip the verdict near the budget edge.
    meta_edge = rbcd.GraphMeta(num_robots=8, n_max=1200, e_max=5000,
                               s_max=50, p_max=50, d=3, rank=5)
    assert rbcd.use_dense_q(meta_edge, on, itemsize=4)
    assert not rbcd.use_dense_q(meta_edge, on, itemsize=8)


def test_refresh_problem_rebakes_factors(rng):
    """Externally injected weights (checkpoint resume) must be honored by
    the carried problem factors via refresh_problem."""
    from dpgo_tpu.config import SolverParams

    meas, _ = make_measurements(rng, n=16, d=3, num_lc=8,
                                rot_noise=0.05, trans_noise=0.05)
    params = AgentParams(d=3, r=5, num_robots=2,
                         solver=SolverParams(dense_quadratic=True))
    part = partition_contiguous(meas, 2)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    w_new = state.weights * 0.25
    stale = state._replace(weights=w_new)
    fresh = rbcd.refresh_problem(stale, graph, meta, params)
    # Stale factors are unchanged; refreshed ones match a from-scratch bake.
    edges_w = graph.edges._replace(weight=w_new)
    chol_ref = rbcd.precond_chol(edges_w, meta.n_max, meta.s_max, params)
    qbuf_ref = rbcd.dense_q_all(edges_w, meta)
    assert not np.allclose(stale.chol, chol_ref)
    assert np.allclose(fresh.chol, chol_ref)
    assert np.allclose(fresh.Qbuf, qbuf_ref)
