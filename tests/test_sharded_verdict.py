"""Pod-scale fast path (ISSUE 11): the device-resident verdict loop under
shard_map, halo/compute overlap, and the sharded Gauss-Newton-CG tail —
all run on the virtual 8-device CPU mesh.

The contracts pinned here mirror how PR 9 pinned the single-device verdict
loop against the per-eval path:

* the sharded metrics body (psum reductions inside shard_map) produces
  BITWISE-identical rows to the single-device ``_central_metrics_body``
  on the same state — the global-assembly psum adds one owner value to
  zeros per pose (disjoint supports), so it is exact, not merely close;
* ``solve_rbcd_sharded(verdict_every=K)`` terminates at the same round,
  for the same reason, with the same histories as the single-device
  verdict loop (to mesh reduction-order tolerance) and as the sharded
  per-eval driver;
* the overlapped fused round loop is bitwise-equal to the unpipelined
  one (the halo of round k is always ``exchange(X_k)``);
* the host reads exactly one verdict word per K rounds (counted through
  the sanctioned ``rbcd._host_fetch`` seam);
* the sharded GN-CG tail matches ``refine.gn_tail`` on the same iterate
  to f64 tolerance with zero host transfers inside the CG loop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import AgentParams
from dpgo_tpu.models import rbcd, refine
from dpgo_tpu.parallel import (gn_tail_sharded, make_mesh,
                               make_sharded_metrics_body,
                               make_sharded_multi_step, shard_problem,
                               solve_rbcd_sharded)
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import partition_contiguous

from synthetic import make_measurements


def _setup(meas, num_robots, params, dtype=jnp.float64):
    part = partition_contiguous(meas, num_robots)
    graph, meta = rbcd.build_graph(part, params.r, dtype)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, dtype)
    state = rbcd.init_state(graph, meta, X0, params=params)
    return part, graph, meta, state


def _noisy(rng_or_seed, n=48, num_lc=14, noise=0.01):
    rng = np.random.default_rng(rng_or_seed) \
        if isinstance(rng_or_seed, int) else rng_or_seed
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=noise, trans_noise=noise)
    return meas


def test_sharded_divisibility_validated_up_front(rng):
    """The mesh-divisibility error fires before any graph build, naming
    both offending values and the fix."""
    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=6)
    calls = []
    orig = rbcd.build_graph

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    rbcd.build_graph = spy
    try:
        with pytest.raises(ValueError) as ei:
            solve_rbcd_sharded(meas, num_robots=6, mesh=make_mesh(4),
                               params=params, max_iters=4)
    finally:
        rbcd.build_graph = orig
    msg = str(ei.value)
    assert "num_robots=6" in msg and "4" in msg and "make_mesh" in msg
    assert not calls, "validation must precede the graph build"


def test_sharded_metrics_body_bitwise_vs_central(rng):
    """The shard_map metrics body's rows are BITWISE equal to the
    single-device ``_central_metrics_body`` on the same state, both with
    and without the telemetry extras: the global assembly / weight
    collapse psums sum disjoint (or duplicate-identical) owner
    contributions, so no reduction-order slack exists to hide behind."""
    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=8)
    part, graph, meta, state = _setup(meas, 8, params)
    mesh = make_mesh(8)
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    edges_g = edge_set_from_measurements(part.meas_global,
                                         dtype=jnp.float64)
    n_total, num_meas = part.meas_global.num_poses, len(part.meas_global)
    # A couple of rounds so rel_change is finite and weights are live.
    multi = make_sharded_multi_step(mesh, meta, params)
    sh_state = multi(sh_state, sh_graph, 2)
    state = rbcd.rbcd_steps(state, graph, 2, meta, params)
    for telemetry in (False, True):
        body_c = rbcd._central_metrics_body(graph, edges_g, n_total,
                                            num_meas, telemetry)
        body_s = make_sharded_metrics_body(mesh, sh_graph, edges_g,
                                           n_total, num_meas, telemetry)
        vc = np.asarray(jax.jit(body_c)(
            state.X, state.weights, state.ready, state.mu,
            state.rel_change))
        vs = np.asarray(jax.jit(body_s)(
            sh_state.X, sh_state.weights, sh_state.ready, sh_state.mu,
            sh_state.rel_change))
        # The sharded STATE itself agrees only to reduction order, so
        # evaluate the sharded body on rows whose inputs match bitwise:
        vcs = np.asarray(jax.jit(body_s)(
            jnp.asarray(state.X), jnp.asarray(state.weights),
            jnp.asarray(state.ready), jnp.asarray(state.mu),
            jnp.asarray(state.rel_change)))
        np.testing.assert_array_equal(vcs, vc)
        np.testing.assert_allclose(vs, vc, rtol=1e-9, atol=1e-12)


def test_sharded_verdict_matches_single_device_verdict(rng):
    """ACCEPTANCE: ``solve_rbcd_sharded(verdict_every=K)`` terminates at
    the same eval, for the same reason, with the same cost/gradnorm
    histories as the single-device verdict loop."""
    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    res_sd = rbcd.solve_rbcd(meas, 8, params=params, max_iters=40,
                             grad_norm_tol=0.1, eval_every=4,
                             verdict_every=8, dtype=jnp.float64)
    res_sh = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                                max_iters=40, grad_norm_tol=0.1,
                                eval_every=4, verdict_every=8)
    assert res_sh.iterations == res_sd.iterations
    assert res_sh.terminated_by == res_sd.terminated_by == "grad_norm"
    np.testing.assert_allclose(res_sh.cost_history, res_sd.cost_history,
                               rtol=1e-9)
    np.testing.assert_allclose(res_sh.grad_norm_history,
                               res_sd.grad_norm_history, rtol=1e-7)
    np.testing.assert_allclose(np.asarray(res_sh.T), np.asarray(res_sd.T),
                               atol=1e-8)


def test_sharded_verdict_matches_sharded_per_eval(rng):
    """The sharded verdict loop vs the sharded per-eval driver on the
    SAME mesh: identical termination and histories — the verdict-word
    contract carries to the mesh unchanged."""
    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    kw = dict(mesh=make_mesh(8), params=params, max_iters=40,
              grad_norm_tol=0.1, eval_every=4)
    res_pe = solve_rbcd_sharded(meas, 8, **kw)
    res_vw = solve_rbcd_sharded(meas, 8, verdict_every=8, **kw)
    assert res_vw.iterations == res_pe.iterations
    assert res_vw.terminated_by == res_pe.terminated_by
    np.testing.assert_allclose(res_vw.cost_history, res_pe.cost_history,
                               rtol=1e-12)
    np.testing.assert_allclose(res_vw.grad_norm_history,
                               res_pe.grad_norm_history, rtol=1e-9)


def test_sharded_verdict_host_sync_rate(rng):
    """One packed-word fetch per K rounds, counted through the sanctioned
    ``rbcd._host_fetch`` seam (telemetry off: the only other transfers
    are the 2-call terminal epilogue) — ``host_syncs_per_100_rounds ==
    100/K`` on the sharded path."""
    meas = _noisy(7, n=80, num_lc=16, noise=0.1)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    K, rounds = 4, 16
    counted = [0]
    orig = rbcd._host_fetch

    def counting(x):
        counted[0] += 1
        return orig(x)

    rbcd._host_fetch = counting
    try:
        res = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                                 max_iters=rounds, grad_norm_tol=0.0,
                                 eval_every=K, verdict_every=K)
    finally:
        rbcd._host_fetch = orig
    assert res.iterations == rounds and res.terminated_by == "max_iters"
    words = rounds // K
    assert counted[0] == words + 1, counted[0]  # words + fused epilogue
    assert 100.0 * words / rounds == pytest.approx(100.0 / K)


def test_sharded_overlap_matches_unpipelined(rng):
    """The halo-pipelined fused loop is BITWISE equal to the unpipelined
    one: the halo of round k is always ``exchange(X_k)``, only its issue
    point moves."""
    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=8)
    _, graph, meta, state = _setup(meas, 8, params)
    mesh = make_mesh(8)
    sh_state, sh_graph = shard_problem(mesh, state, graph)
    on = make_sharded_multi_step(mesh, meta, params, overlap=True)
    off = make_sharded_multi_step(mesh, meta, params, overlap=False)
    a = on(sh_state, sh_graph, 5)
    b = off(sh_state, sh_graph, 5)
    np.testing.assert_array_equal(np.asarray(a.X), np.asarray(b.X))
    np.testing.assert_array_equal(np.asarray(a.rel_change),
                                  np.asarray(b.rel_change))
    assert int(a.iteration) == int(b.iteration) == 5


def test_sharded_verdict_ppermute_matches_all_gather(rng):
    """The verdict loop composes with the ppermute exchange: identical
    trace and trajectory vs the all_gather arm (the two exchanges are
    bitwise-equal by construction)."""
    meas = _noisy(rng, n=64, num_lc=20)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    kw = dict(params=params, max_iters=40, grad_norm_tol=0.1,
              eval_every=4, verdict_every=8)
    res_a = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), **kw)
    res_p = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8),
                               exchange="ppermute", **kw)
    assert res_p.iterations == res_a.iterations
    assert res_p.terminated_by == res_a.terminated_by
    np.testing.assert_array_equal(np.asarray(res_p.T), np.asarray(res_a.T))


def test_sharded_gn_tail_matches_host_gn_tail(rng):
    """ACCEPTANCE: the device-resident sharded GN-CG tail reaches the
    same final cost as the host-f64 ``refine.gn_tail`` (rel <= 1e-6) from
    the same handoff iterate, through the same gate."""
    meas = _noisy(7, n=80, num_lc=16, noise=0.1)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    res = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                             max_iters=30, grad_norm_tol=0.0,
                             eval_every=10, verdict_every=10)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    e64 = refine.host_edges_f64(part.meas_global)
    Xg0 = np.asarray(rbcd.gather_to_global(res.X, graph,
                                           part.meas_global.num_poses),
                     np.float64)
    cfg = refine.GNTailConfig(max_outer=10, grad_norm_tol=1e-3,
                              cg_max_iters=200)
    host = refine.gn_tail(Xg0, e64, cfg)
    _Xa, sh = gn_tail_sharded(res.X, graph, meta, mesh=make_mesh(8),
                              cfg=cfg)
    assert host.terminated_by == "grad_norm"
    assert sh.terminated_by == "grad_norm"
    assert sh.grad_norm_history[-1] < cfg.grad_norm_tol
    rel = abs(sh.cost_history[-1] - host.cost_history[-1]) \
        / abs(host.cost_history[-1])
    assert rel <= 1e-6, rel


def test_sharded_gn_tail_zero_transfers_inside_cg(rng):
    """The CG loop and the backtracking retraction are device-resident:
    the only host fetches are the per-outer gate scalar and stats vector
    (through ``rbcd._host_fetch``), far fewer than the CG iterations they
    drive."""
    meas = _noisy(7, n=80, num_lc=16, noise=0.1)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    res = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                             max_iters=20, grad_norm_tol=0.0,
                             eval_every=10, verdict_every=10)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    cfg = refine.GNTailConfig(max_outer=6, grad_norm_tol=1e-3,
                              cg_max_iters=200)
    counted = [0]
    orig = rbcd._host_fetch

    def counting(x):
        counted[0] += 1
        return orig(x)

    rbcd._host_fetch = counting
    try:
        _Xa, sh = gn_tail_sharded(res.X, graph, meta, mesh=make_mesh(8),
                                  cfg=cfg)
    finally:
        rbcd._host_fetch = orig
    # One gate fetch per loop entry + one stats fetch per executed outer.
    assert counted[0] == len(sh.grad_norm_history) + sh.outer_iterations \
        + (1 if sh.terminated_by == "no_decrease" else 0)
    assert sh.cg_iterations > counted[0], (sh.cg_iterations, counted[0])


def test_solve_sharded_with_gn_tail_extends_histories(rng):
    """``solve_rbcd_sharded(gn_tail=cfg)`` appends the tail trajectory to
    the returned histories, re-finalizes T from the polished iterate, and
    reports the tail's termination when it converges through the gate."""
    meas = _noisy(7, n=80, num_lc=16, noise=0.1)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    cfg = refine.GNTailConfig(max_outer=8, grad_norm_tol=1e-3,
                              cg_max_iters=200)
    res = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                             max_iters=20, grad_norm_tol=0.0,
                             eval_every=10, verdict_every=10,
                             gn_tail=cfg)
    res_no = solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                                max_iters=20, grad_norm_tol=0.0,
                                eval_every=10, verdict_every=10)
    assert res.terminated_by == "grad_norm"
    assert len(res.cost_history) > len(res_no.cost_history)
    assert res.grad_norm_history[-1] < cfg.grad_norm_tol
    assert res.cost_history[-1] <= res_no.cost_history[-1] + 1e-12
    assert res.T.shape == (meas.num_poses, 3, 4)
    assert np.isfinite(np.asarray(res.T)).all()


def test_sharded_verdict_telemetry_and_report(rng, tmp_path):
    """Telemetry on: the sharded verdict solve emits the same event
    stream schema as the single-device loop (solve_end with the verdict
    word, host_syncs_per_100_rounds == 100/K), the sharded_solve setup
    event carries the overlap/verdict fields, and the report CLI renders
    the 'sharded' section."""
    from dpgo_tpu import obs
    from dpgo_tpu.obs.events import read_events
    from dpgo_tpu.obs.report import render_report

    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        solve_rbcd_sharded(meas, 8, mesh=make_mesh(8), params=params,
                           max_iters=24, grad_norm_tol=0.0, eval_every=4,
                           verdict_every=8)
    events = read_events(f"{run_dir}/events.jsonl")
    setup = [e for e in events if e.get("event") == "sharded_solve"]
    assert setup and setup[0]["mesh_size"] == 8
    assert setup[0]["overlap"] is True
    assert setup[0]["verdict_every"] == 8
    ends = [e for e in events if e.get("event") == "solve_end"]
    assert ends and ends[0]["verdict_every"] == 8
    syncs = [e for e in events if e.get("event") == "metric"
             and e.get("metric") == "host_syncs_per_100_rounds"]
    # Telemetry on: one word + one lazy history fetch per K-round
    # boundary (the single-device verdict loop's accounting too).
    assert syncs and syncs[0]["value"] == pytest.approx(2 * 100.0 / 8)
    txt = render_report(run_dir)
    assert "sharded:" in txt
    assert "verdict sync" in txt


def test_regress_gates_sharded_host_sync_rate(tmp_path):
    """A sharded record whose host-sync rate grows regresses under
    ``report --compare`` exactly like a single-device one — the
    readback-kill gate covers the mesh path."""
    from dpgo_tpu import obs
    from dpgo_tpu.obs.regress import compare_runs

    def fake_run(d, syncs):
        with obs.run_scope(str(d)):
            run = obs.get_run()
            run.set_fingerprint(solver="solve_rbcd_sharded", mesh_size=8,
                                exchange="all_gather", num_robots=8)
            run.metric("solver_cost", 1.0, phase="eval", iteration=8)
            run.metric("solver_grad_norm", 0.05, phase="eval", iteration=8)
            run.metric("host_syncs_per_100_rounds", syncs, phase="solve",
                       fetches=int(syncs), rounds=100)

    fake_run(tmp_path / "a", 0.2)
    fake_run(tmp_path / "b", 12.5)  # someone reopened the readback
    cmp = compare_runs(str(tmp_path / "a"), str(tmp_path / "b"))
    assert cmp["rc"] == 2
    assert "host_syncs_per_100_rounds" in cmp["regressions"]
    fake_run(tmp_path / "c", 0.2)
    assert compare_runs(str(tmp_path / "a"), str(tmp_path / "c"))["rc"] == 0
