"""Live-session layer (``models.incremental``): streamed edge deltas into
the padded bucket layout, warm restarts from exact state, and the
fingerprint/executable-reuse contract."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import AgentParams, Schedule
from dpgo_tpu.models import rbcd
from dpgo_tpu.models.incremental import (LiveProblem, state_from_arrays,
                                         state_to_arrays)
from dpgo_tpu.serve.bucketing import pad_problem
from dpgo_tpu.types import edge_set_from_measurements, loop_closure_mask
from dpgo_tpu.utils.synthetic import make_measurements


def _split_stream(seed=0, n=30, num_lc=14, hold=3, noise=0.02):
    """A synthetic problem with ``hold`` loop closures withheld as the
    stream (num_poses pinned so the pose set is identical)."""
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=noise, trans_noise=noise)
    lc_idx = np.nonzero(loop_closure_mask(meas))[0]
    keep = np.ones(len(meas), bool)
    keep[lc_idx[-hold:]] = False
    base = dataclasses.replace(meas.select(keep), num_poses=meas.num_poses)
    extra = dataclasses.replace(meas.select(~keep), num_poses=meas.num_poses)
    return meas, base, extra


PARAMS = AgentParams(d=3, r=5, num_robots=3, rel_change_tol=0.0)


def _central(graph, part, num_meas):
    return rbcd._make_central_metrics(
        graph, edge_set_from_measurements(part.meas_global,
                                          dtype=jnp.float64),
        part.meas_global.num_poses, num_meas, telemetry=False)


def test_delta_append_matches_full_rebuild_exactly():
    """The masked-append graph must evaluate the SAME objective as a full
    rebuild padded to the same bucket: identical cost and gradient norm at
    an arbitrary iterate (row order differs, the math must not)."""
    meas, base, extra = _split_stream()
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    res0 = live.solve(max_iters=40, grad_norm_tol=1e-6)

    d = live.apply_edges(extra)
    assert d.mode == "delta" and not d.recompiles

    full = rbcd.prepare_problem(meas, 3, params=PARAMS, dtype=jnp.float64,
                                init=None, pallas_sel=False)
    ref = pad_problem(full, live.shape)
    A = 3
    ready = jnp.zeros((A,), bool)
    rel = jnp.zeros((A,))
    w_live = jnp.ones_like(live.padded.graph.edges.weight)
    w_ref = jnp.ones_like(ref.graph.edges.weight)
    v1 = np.asarray(_central(live.padded.graph, live.part, len(meas))(
        res0.state.X, w_live, ready, jnp.asarray(0.1), rel))
    v2 = np.asarray(_central(ref.graph, full.part, len(meas))(
        res0.state.X, w_ref, ready, jnp.asarray(0.1), rel))
    np.testing.assert_allclose(v1[:2], v2[:2], rtol=1e-12, atol=1e-12)


def test_delta_keeps_bucket_and_meta_stable():
    """Executable-reuse contract: a fitting delta leaves the bucket shape
    AND the padded GraphMeta (the jit static argument every compiled
    segment program is keyed on) untouched; a stream too large for the
    padding re-buckets with an honest ``recompiles`` flag."""
    meas, base, extra = _split_stream()
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    shape0, meta0 = live.shape, live.padded.meta
    d = live.apply_edges(extra)
    assert d.mode == "delta"
    assert live.shape == shape0
    assert live.padded.meta == meta0  # same static arg -> jit cache hit

    # A burst far past the edge headroom must grow the bucket.
    n = meas.num_poses
    burst, _ = make_measurements(np.random.default_rng(5), n=n, d=3,
                                 num_lc=80, rot_noise=0.01,
                                 trans_noise=0.01)
    lc = loop_closure_mask(burst)
    burst = dataclasses.replace(burst.select(lc), num_poses=n)
    d2 = live.apply_edges(burst)
    assert d2.mode == "rebucket" and d2.recompiles
    assert live.shape != shape0
    assert len(live.meas) == len(meas) + len(burst)


def test_delta_new_shared_edge_grows_slots_and_publics():
    """A streamed CROSS-robot edge between poses that were never shared
    exercises the slot/public append path; the graph must still match a
    full rebuild."""
    rng = np.random.default_rng(3)
    meas, _ = make_measurements(rng, n=30, d=3, num_lc=6, rot_noise=0.01,
                                trans_noise=0.01)
    live = LiveProblem(meas, 3, params=PARAMS, dtype=jnp.float64)
    s_used_before = int(np.asarray(live.padded.graph.nbr_mask).sum())
    # poses 2 (robot 0) and 27 (robot 2): interior poses, certainly not
    # shared by the odometry chain + few LCs above.
    new = dataclasses.replace(
        meas.select(np.zeros(len(meas), bool)), num_poses=meas.num_poses)
    new = dataclasses.replace(
        new,
        r1=np.zeros(1, np.int32), p1=np.asarray([2], np.int64),
        r2=np.zeros(1, np.int32), p2=np.asarray([27], np.int64),
        R=np.eye(3)[None], t=np.zeros((1, 3)),
        kappa=np.asarray([100.0]), tau=np.asarray([10.0]),
        weight=np.ones(1), is_known_inlier=np.zeros(1, bool))
    npr = meas.num_poses // 3
    expected = int((2, 27 - 2 * npr) not in live._slot_of[0]) + \
        int((0, 2) not in live._slot_of[2])
    assert expected >= 1  # the edge genuinely grows at least one table
    d = live.apply_edges(new)
    assert d.mode == "delta"
    s_used_after = int(np.asarray(live.padded.graph.nbr_mask).sum())
    assert s_used_after == s_used_before + expected

    cat = live.meas
    full = rbcd.prepare_problem(cat, 3, params=PARAMS, dtype=jnp.float64,
                                init=None, pallas_sel=False)
    ref = pad_problem(full, live.shape)
    X = ref.X0
    ready = jnp.zeros((3,), bool)
    rel = jnp.zeros((3,))
    v1 = np.asarray(_central(live.padded.graph, live.part, len(cat))(
        X, jnp.ones_like(live.padded.graph.edges.weight), ready,
        jnp.asarray(0.1), rel))
    v2 = np.asarray(_central(ref.graph, full.part, len(cat))(
        X, jnp.ones_like(ref.graph.edges.weight), ready,
        jnp.asarray(0.1), rel))
    np.testing.assert_allclose(v1[:2], v2[:2], rtol=1e-12, atol=1e-12)


def test_warm_dispatch_reaches_cold_cost():
    """The streaming acceptance contract: after +edges, the warm restart
    converges to the SAME final cost as a cold re-solve (rel <= 1e-6) —
    both run to the block fixed point."""
    meas, base, extra = _split_stream(seed=1, n=40, num_lc=18, hold=2)
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    res0 = live.solve(max_iters=300, grad_norm_tol=1e-9, eval_every=2)

    cold = LiveProblem(meas, 3, params=PARAMS, dtype=jnp.float64)
    resc = cold.solve(max_iters=300, grad_norm_tol=1e-9, eval_every=2)
    resw = live.warm_dispatch(res0, new_edges=extra, max_iters=300,
                              grad_norm_tol=1e-9, eval_every=2)
    rel = abs(resw.cost_history[-1] - resc.cost_history[-1]) / \
        max(1.0, abs(resc.cost_history[-1]))
    assert rel <= 1e-6, (resw.cost_history[-1], resc.cost_history[-1])


def test_warm_dispatch_without_delta_terminates_immediately():
    """Resuming a converged state on the unchanged problem must terminate
    at once with the identical cost — the exact-state contract."""
    meas, base, _ = _split_stream(seed=2)
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    res0 = live.solve(max_iters=300, grad_norm_tol=1e-9, eval_every=2)
    resw = live.warm_dispatch(res0, max_iters=300, grad_norm_tol=1e-9,
                              eval_every=2)
    assert resw.iterations <= 4
    assert resw.cost_history[-1] == res0.cost_history[-1]


def test_warm_dispatch_remaps_gnc_weights():
    """Carried GNC weights follow their measurements onto the new rows:
    an edge down-weighted before the delta stays down-weighted after."""
    meas, base, extra = _split_stream(seed=4)
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    res0 = live.solve(max_iters=20, grad_norm_tol=1e-6)
    st = res0.state
    # Manually zero one loop closure's weight (as a GNC anneal would).
    g = live.padded.graph
    meas_id = np.asarray(g.meas_id)
    is_lc = np.asarray(g.edges.is_lc) > 0
    mask = np.asarray(g.edges.mask) > 0
    a, e = map(int, np.argwhere(is_lc & mask)[0])
    victim = int(meas_id[a, e])
    w = np.asarray(st.weights).copy()
    w[(meas_id == victim) & mask] = 0.125
    st = st._replace(weights=jnp.asarray(w))

    live.apply_edges(extra)
    adapted = live._adapt_state(st, (meas_id, np.asarray(g.edges.mask),
                                     len(base)))
    w2 = np.asarray(adapted.weights)
    id2 = np.asarray(live.padded.graph.meas_id)
    m2 = np.asarray(live.padded.graph.edges.mask) > 0
    rows = (id2 == victim) & m2
    assert rows.any()
    np.testing.assert_allclose(w2[rows], 0.125)
    # streamed edges start at their measurement weight (1 here)
    fresh = (id2 >= len(base)) & m2
    assert fresh.any()
    np.testing.assert_allclose(w2[fresh], 1.0)


def test_new_poses_are_rejected():
    meas, base, _ = _split_stream()
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    bad = dataclasses.replace(base.select([0]),
                              num_poses=base.num_poses + 1,
                              p2=np.asarray([base.num_poses]))
    with pytest.raises(ValueError, match="NEW poses"):
        live.apply_edges(bad)


def test_colored_schedule_falls_back_to_rebuild():
    """COLORED's agent coloring can be invalidated by a new shared edge;
    the delta path must decline and the rebuild recolor."""
    meas, base, extra = _split_stream()
    params = dataclasses.replace(PARAMS, schedule=Schedule.COLORED)
    live = LiveProblem(base, 3, params=params, dtype=jnp.float64)
    d = live.apply_edges(extra)
    assert d.mode in ("repad", "rebucket")


def test_state_codec_round_trip():
    meas, base, _ = _split_stream()
    live = LiveProblem(base, 3, params=PARAMS, dtype=jnp.float64)
    res = live.solve(max_iters=10, grad_norm_tol=1e-6)
    arrays = state_to_arrays(res.state)
    back = state_from_arrays(arrays)
    for f in ("X", "weights", "key", "rel_change", "ready", "gamma",
              "alpha", "mu", "iteration"):
        np.testing.assert_array_equal(np.asarray(getattr(back, f)),
                                      np.asarray(getattr(res.state, f)))
    assert back.chol is None and back.Qbuf is None
