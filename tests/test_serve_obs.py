"""Serving-plane & device observability: end-to-end request tracing,
live /metrics + /statusz endpoints, compile/device profiling, SLO
burn-rate alerting, exporter unit-suffixing, and the single-flight
executable cache."""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams
from dpgo_tpu.obs.exporters import exposition_name, to_prometheus_text
from dpgo_tpu.obs.metrics import MetricsRegistry
from dpgo_tpu.obs.report import (live_report, render_report, render_statusz,
                                 serving_stats)
from dpgo_tpu.serve import (ExecutableCache, OverCapacityError, ServeSLO,
                            SolveRequest, SolveServer)
from dpgo_tpu.utils.synthetic import make_measurements

PARAMS = AgentParams(d=3, r=5, num_robots=2)

#: Prometheus text-format sample line (after HELP/TYPE comments): name,
#: optional label set, value, no trailing garbage.
_PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*")*\})? '
    r'(-?\d+(\.\d+)?([eE][-+]?\d+)?|NaN|\+Inf|-Inf)$')


def _problem(n=24, seed=0, num_lc=5):
    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=num_lc, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _request(meas, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("max_iters", 4)
    kw.setdefault("grad_norm_tol", 1e-12)
    kw.setdefault("eval_every", 2)
    return SolveRequest(meas=meas, num_robots=2, **kw)


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def _spans(events):
    return [e for e in events if e.get("event") == "span"]


# ---------------------------------------------------------------------------
# The acceptance scenario: one traced, scraped, SLO'd serving run
# ---------------------------------------------------------------------------

def test_serving_observability_end_to_end(tmp_path):
    """ACCEPTANCE: a traced serving run exports a valid Chrome trace where
    every completed request shows admission -> queue_wait -> dispatch ->
    reply spans with a flow arrow into its shared batch ``dispatch``
    span; the live ``/metrics`` endpoint returns parseable Prometheus
    text mid-flight including cache compile/hit counters and per-tenant
    SLO burn gauges; ``/statusz`` and ``report --live`` agree."""
    run_dir = str(tmp_path / "run")
    n_req = 4
    with obs.run_scope(run_dir):
        with SolveServer(max_batch=2, batch_window_s=0.05, quantum=64,
                         slo=ServeSLO(latency_s=1e-9, window_s=60.0),
                         metrics_port=0) as srv:
            assert srv.sidecar is not None and srv.sidecar.port > 0
            # Two waves of two: wave 2 re-dispatches wave 1's bucket at
            # the same pow2 batch width, so it must HIT the executable
            # cache (the counter the /metrics assertion below pins).
            tickets = []
            for wave in range(2):
                wave_tickets = [
                    srv.submit(_request(_problem(n=24 + k, seed=2 * wave + k),
                                        tenant=f"t{k % 2}"))
                    for k in range(2)]
                for t in wave_tickets:
                    t.result(timeout=600)
                tickets.extend(wave_tickets)
            # One shed rides the same run (reason-tagged span below).
            shed = srv.submit(_request(_problem(), deadline_s=0.0))
            with pytest.raises(OverCapacityError):
                shed.result(timeout=60)

            base = f"http://{srv.sidecar.host}:{srv.sidecar.port}"
            code, prom = _get(base + "/metrics")
            assert code == 200
            code, hz = _get(base + "/healthz")
            assert code == 200 and json.loads(hz)["ok"] is True
            code, st = _get(base + "/statusz")
            assert code == 200
            status = json.loads(st)
            rc = live_report(f"{srv.sidecar.host}:{srv.sidecar.port}")
            assert rc == 0

    # --- live scrape: well-formed Prometheus text with the counters ----
    for line in prom.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_SAMPLE.match(line), f"bad exposition line: {line!r}"
    assert 'serve_cache_requests_total{outcome="compile"}' in prom
    assert 'serve_cache_requests_total{outcome="hit"}' in prom
    assert "serve_slo_burn_rate" in prom and 'tenant="t0"' in prom
    assert "serve_compile_seconds_total" in prom
    assert "serve_device_time_seconds_total" in prom

    # --- statusz payload ----------------------------------------------
    assert status["queue_depth"] == 0
    assert status["requests_served"] == n_req
    assert status["cache"]["compiles"] >= 1
    assert status["last_batch"]["occupancy"] > 0
    assert status["slo"]["t0"]["latency_burn"] > 1.0
    assert render_statusz(status)  # renders without exploding

    # --- the span graph ------------------------------------------------
    events = obs.read_events(f"{run_dir}/events.jsonl")
    spans = _spans(events)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    for name in ("admission", "prepare", "queue_wait", "dispatch",
                 "batch_member", "reply", "stack", "device_dispatch",
                 "slice", "shed"):
        assert name in by_name, f"missing span {name!r}"
    dispatch_ids = {s["span"] for s in by_name["dispatch"]}
    dispatch_traces = {s["trace"] for s in by_name["dispatch"]}
    # Every completed request: one trace holding admission -> queue_wait
    # -> reply, a batch_member flow arrow into a dispatch span's trace,
    # and a reply linked back from its dispatch span.
    req_traces = {s["trace"] for s in by_name["admission"]
                  if s.get("outcome") == "queued"}
    assert len(req_traces) == n_req + 1  # + the shed request
    completed = {s["trace"] for s in by_name["reply"]}
    assert len(completed) == n_req and completed <= req_traces
    for tr in completed:
        mine = [s for s in spans if s["trace"] == tr]
        assert {"admission", "queue_wait", "reply"} <= \
            {s["name"] for s in mine}
    member_links = {s["link_trace"] for s in by_name["batch_member"]}
    assert member_links == completed
    assert all(s["trace"] in dispatch_traces
               for s in by_name["batch_member"])
    for s in by_name["reply"]:
        assert s["link_span"] in dispatch_ids
    # The shed request's trace closes with a reason-tagged span.
    shed_span = by_name["shed"][0]
    assert shed_span["reason"] == "deadline"
    assert shed_span["trace"] in req_traces - completed
    # Runner spans nest under the shared dispatch.
    assert all(s["parent"] in dispatch_ids for s in by_name["stack"])

    # --- compile & device profiling ------------------------------------
    compiles = [e for e in events if e.get("event") == "compile_profile"]
    # "finalize" became the fused terminal epilogue (certify-aware key).
    assert {c["label"] for c in compiles} >= {"segment", "metrics",
                                              "epilogue:off"}
    for c in compiles:
        assert c["total_s"] > 0 and "key" in c

    # --- SLO burn events through the health machinery ------------------
    burns = [e for e in events if e.get("event") == "anomaly"
             and e.get("kind") == "slo_burn"]
    lat_burns = [b for b in burns if b["slo"] == "latency"]
    assert {b["tenant"] for b in lat_burns} == {"t0", "t1"}
    assert all(b["burn_rate"] > 1.0 for b in burns)

    # --- Chrome trace round-trip ---------------------------------------
    from dpgo_tpu.obs import timeline

    path = timeline.write_chrome_trace(str(tmp_path / "trace.json"),
                                       timeline.merge([run_dir]))
    checks = timeline.validate_chrome_trace(path)
    assert checks["spans"] >= len(spans)
    obj = json.load(open(path))
    arrows = [e for e in obj["traceEvents"] if e.get("ph") == "s"]
    # One arrow per batch mate into dispatch + one per reply out of it.
    assert len(arrows) >= 2 * n_req

    # --- report: serving section carries the SLO story -----------------
    text = render_report(run_dir)
    assert "serving:" in text and "slo burn: tenant" in text
    stats = serving_stats(events)
    assert stats["slo"]["t0"]["alerts"] >= 1
    assert stats["no_traffic"] is False


def test_shed_only_run_reports_no_traffic(tmp_path, capsys):
    """Zero completed requests must not divide by an empty serving
    window: the section renders an explicit no-traffic line and the CLI
    exits 0."""
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        with SolveServer(max_batch=2, batch_window_s=0.0,
                         quantum=64) as srv:
            t = srv.submit(_request(_problem(), deadline_s=0.0))
            with pytest.raises(OverCapacityError):
                t.result(timeout=60)
    events = obs.read_events(f"{run_dir}/events.jsonl")
    stats = serving_stats(events)
    assert stats is not None and stats["no_traffic"] is True
    assert stats["tenants"] == {}
    text = render_report(run_dir)
    assert "no completed requests (no traffic)" in text
    assert "shed: tenant default x1 (deadline)" in text
    from dpgo_tpu.obs.report import main as report_main

    assert report_main([run_dir]) == 0
    assert report_main([run_dir, "--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["serving"]["no_traffic"] is True


def test_live_report_unreachable_is_clean(capsys):
    rc = live_report("127.0.0.1:9")  # discard port: nothing listens
    assert rc == 2
    assert "cannot scrape" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Executable cache: single-flight under concurrency
# ---------------------------------------------------------------------------

def test_executable_cache_single_flight():
    """Parallel get() on one fingerprint must invoke the builder once;
    everyone else blocks on that build and counts as a hit."""
    cache = ExecutableCache()
    fp = {"solver": "x", "rank": 5}
    n = 8
    started = threading.Barrier(n)
    build_entered = threading.Event()
    release_build = threading.Event()
    builds = []

    def builder():
        builds.append(threading.get_ident())
        build_entered.set()
        assert release_build.wait(30)
        return object()

    results = [None] * n

    def go(k):
        started.wait()
        results[k] = cache.get(fp, builder)

    threads = [threading.Thread(target=go, args=(k,)) for k in range(n)]
    for th in threads:
        th.start()
    assert build_entered.wait(30)
    release_build.set()
    for th in threads:
        th.join(30)
    assert len(builds) == 1, "single-flight violated"
    assert all(r is results[0] and r is not None for r in results)
    assert cache.compiles == 1
    assert cache.hits == n - 1
    assert cache.stats() == {"entries": 1, "compiles": 1, "hits": n - 1}


def test_executable_cache_failed_build_retries():
    cache = ExecutableCache()
    fp = {"solver": "y"}
    calls = []

    def bad():
        calls.append(1)
        raise RuntimeError("compile exploded")

    with pytest.raises(RuntimeError):
        cache.get(fp, bad)
    # The in-flight marker is cleared: a retry builds (no deadlock).
    sentinel = object()
    assert cache.get(fp, lambda: sentinel) is sentinel
    assert cache.compiles == 1 and len(calls) == 1


# ---------------------------------------------------------------------------
# Exporter: unit suffixes + HELP hygiene
# ---------------------------------------------------------------------------

def test_exposition_name_unit_suffixing():
    assert exposition_name("queue_wait", "s") == "queue_wait_seconds"
    assert exposition_name("serve_queue_wait_seconds", "s") == \
        "serve_queue_wait_seconds"
    assert exposition_name("payload", "bytes") == "payload_bytes"
    assert exposition_name("comms_bytes_sent", "bytes") == "comms_bytes_sent"
    assert exposition_name("device_time_total", "s") == \
        "device_time_seconds_total"
    assert exposition_name("plain_counter", "") == "plain_counter"
    assert exposition_name("weird", "furlongs") == "weird"


def test_exporter_emits_help_type_and_suffixed_names():
    reg = MetricsRegistry()
    reg.histogram("wait", "queue\nwait", unit="s",
                  buckets=(0.1, 1.0)).observe(0.5)
    reg.counter("unhelped").inc()
    text = to_prometheus_text(reg)
    # Unit suffix lands on every sample and on the HELP/TYPE headers.
    assert "# TYPE wait_seconds histogram" in text
    assert "# HELP wait_seconds queue\\nwait" in text
    assert 'wait_seconds_bucket{le="0.1"}' in text
    assert "wait_seconds_sum" in text and "wait_seconds_count" in text
    assert "wait{" not in text
    # HELP falls back to the family name so every family is documented.
    assert "# HELP unhelped unhelped" in text
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert _PROM_SAMPLE.match(line), line


# ---------------------------------------------------------------------------
# Trace plumbing: explicit-trace emit_span
# ---------------------------------------------------------------------------

def test_emit_span_explicit_trace_and_parent(tmp_path):
    from dpgo_tpu.obs import trace

    with obs.run_scope(str(tmp_path / "run")) as run:
        trace.emit_span(run, "pinned", 1.0, 2.0, 0.5, phase="serve",
                        trace_id=0xabc, parent_id=0xdef, tenant="t9")
        with trace.span("outer"):
            trace.emit_span(run, "inherits", 1.0, 2.0, 0.1)
    events = obs.read_events(str(tmp_path / "run" / "events.jsonl"))
    spans = {e["name"]: e for e in _spans(events)}
    assert spans["pinned"]["trace"] == f"{0xabc:016x}"
    assert spans["pinned"]["parent"] == f"{0xdef:016x}"
    assert spans["pinned"]["tenant"] == "t9"
    assert spans["inherits"]["trace"] == spans["outer"]["trace"]
    assert spans["inherits"]["parent"] == spans["outer"]["span"]


def test_wire_trace_context_joins_server_trace(tmp_path):
    """A client-stamped wire trace context (pack_trace_entries) makes the
    server's ``frontend`` span join the CLIENT's trace id and link back
    to the client's span — one trace from TCP accept to reply."""
    from dpgo_tpu.comms.protocol import (ORIGIN_SERVE_CLIENT,
                                         pack_trace_entries)
    from dpgo_tpu.serve.frontend import _pack_str, handle_request

    with SolveServer(max_batch=2, batch_window_s=0.0, quantum=64) as srv:
        # Telemetry off: the context is popped and dropped, no span.
        frame = {"op": _pack_str("ping")}
        frame.update(pack_trace_entries(0x1234, 0x5678,
                                        ORIGIN_SERVE_CLIENT))
        assert int(handle_request(srv, frame)["ok"]) == 1
        assert "_trace" not in frame  # popped before parsing

        with obs.run_scope(str(tmp_path / "run")):
            frame = {"op": _pack_str("ping")}
            frame.update(pack_trace_entries(0x1234, 0x5678,
                                            ORIGIN_SERVE_CLIENT))
            assert int(handle_request(srv, frame)["ok"]) == 1
    events = obs.read_events(str(tmp_path / "run" / "events.jsonl"))
    fr = [e for e in _spans(events) if e["name"] == "frontend"]
    assert len(fr) == 1
    assert fr[0]["trace"] == f"{0x1234:016x}"
    assert fr[0]["link_span"] == f"{0x5678:016x}"
    assert fr[0]["link_robot"] == ORIGIN_SERVE_CLIENT


# ---------------------------------------------------------------------------
# Profiling plumbing
# ---------------------------------------------------------------------------

def test_profiled_executable_compiles_once_per_static_combo(tmp_path):
    import jax
    import jax.numpy as jnp

    from dpgo_tpu.obs.profile import ProfiledExecutable

    @jax.jit
    def f(x):
        return x * 2.0

    x = jnp.arange(4.0)
    # Telemetry off: plain jit passthrough, no AOT machinery.
    prof = ProfiledExecutable(f, key="k", label="test")
    np.testing.assert_allclose(np.asarray(prof(x)), np.asarray(x) * 2.0)

    g = jax.jit(lambda x, scale: x * (2.0 if scale else 1.0),
                static_argnames=("scale",))
    with obs.run_scope(str(tmp_path / "run")) as run:
        prof = ProfiledExecutable(g, key="k2", label="test",
                                  static_names=("scale",))
        for _ in range(3):
            np.testing.assert_allclose(np.asarray(prof(x, scale=True)),
                                       np.asarray(x) * 2.0)
        np.testing.assert_allclose(np.asarray(prof(x, scale=False)),
                                   np.asarray(x))
        run.events.close()
    events = obs.read_events(str(tmp_path / "run" / "events.jsonl"))
    compiles = [e for e in events if e.get("event") == "compile_profile"]
    # One AOT compile per static combo, NOT per call.
    assert len(compiles) == 2
    assert {json.dumps(c.get("static")) for c in compiles} == \
        {'{"scale": true}', '{"scale": false}'}
    assert all(c["label"] == "test" and c["total_s"] > 0 for c in compiles)
