"""Seeded chaos tests: the full deployment stack (PGOAgent over the
``dpgo_tpu.comms`` loopback fleet) under injected network faults and a
mid-solve robot death.

The acceptance scenario: 10% frame drop + ~2-round delays + one robot
killed mid-solve completes WITHOUT hanging and lands within 1% of the
fault-free run's cost on the same synthetic dataset (evaluated over the
edges among surviving robots).  Every run is seeded — the fault stream is
deterministic per link."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.agent import AgentState, PGOAgent
from dpgo_tpu.comms import (FaultInjector, FaultSpec,
                            RetryPolicy, apply_peer_frame, loopback_fleet,
                            pack_agent_frame)
from dpgo_tpu.config import AgentParams
from dpgo_tpu.obs.events import read_events
from dpgo_tpu.ops import quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import agent_measurements, partition_contiguous
from dpgo_tpu.utils.synthetic import make_measurements

NUM_ROBOTS = 3
ROUNDS = 60
KILL = (2, 40)  # robot 2 dies at round 40

# ~2-round delays: rounds are paced at PACE_S, delays span 1-3 rounds.
PACE_S = 0.004
CHAOS = FaultSpec(drop=0.10, delay=0.25, delay_s=(PACE_S, 3 * PACE_S),
                  reorder=0.05)

POLICY = RetryPolicy(max_attempts=2, base_delay_s=0.002, max_delay_s=0.01,
                     send_timeout_s=0.5, recv_timeout_s=0.5)


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _make_problem(seed=0, n=24, num_lc=12):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.01, trans_noise=0.01)
    return meas, partition_contiguous(meas, NUM_ROBOTS)


def _run_fleet(part, injector=None, kill=None, rounds=ROUNDS,
               staleness=0):
    """Drive a full sync solve over the loopback fleet (the in-process
    twin of examples/tcp_deployment_example.py's robot loop).

    ``staleness=0`` is the PR-2 lockstep schedule, unchanged;
    ``staleness>=1`` runs each robot's exchange through the overlapped
    bus client (publish + prefetch on a background thread while the RTR
    step runs) with per-robot driver threads, the deployment examples'
    overlap mode."""
    params = AgentParams(d=3, r=5, num_robots=NUM_ROBOTS)
    agents = {rid: PGOAgent(rid, params) for rid in range(NUM_ROBOTS)}
    for rid in range(1, NUM_ROBOTS):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    for rid, ag in agents.items():
        ag.set_pose_graph(*agent_measurements(part, rid))

    if staleness > 0:
        # Overlap mode free-runs the bus while robots compile their first
        # step (seconds of GIL-held XLA work that can starve heartbeat
        # threads) — use the deployment examples' tolerant liveness
        # thresholds; dropout detection is lockstep-tested above.
        bus, clients = loopback_fleet(
            NUM_ROBOTS, injector=injector, policy=POLICY,
            round_timeout_s=0.15, miss_limit=100, liveness_timeout_s=10.0)
        for c in clients.values():
            c.channel.start_heartbeat(0.05)
        return _run_fleet_overlapped(part, agents, bus, clients, kill,
                                     rounds, staleness)
    bus, clients = loopback_fleet(
        NUM_ROBOTS, injector=injector, policy=POLICY,
        round_timeout_s=0.15, miss_limit=5, liveness_timeout_s=0.5)
    for c in clients.values():
        c.channel.start_heartbeat(0.05)
    dead: set[int] = set()
    for it in range(rounds):
        if kill is not None and it == kill[1]:
            dead.add(kill[0])
            clients[kill[0]].close()
        for rid, ag in agents.items():
            if rid in dead:
                continue
            clients[rid].publish(
                pack_agent_frame(ag, include_anchor=(rid == 0)),
                timeout=0.5)
        bus.round()
        for rid, ag in agents.items():
            if rid in dead:
                continue
            merged = clients[rid].collect(timeout=0.3)
            if merged is not None:
                for peer, pf in clients[rid].peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
                for lost in clients[rid].lost:
                    ag.mark_neighbor_lost(lost)
            ag.iterate(True)
        if injector is not None:
            time.sleep(PACE_S)
    bus.close()
    for rid, c in clients.items():
        if rid not in dead:
            c.close()
    return agents, bus, clients


def _run_fleet_overlapped(part, agents, bus, clients, kill, rounds,
                          staleness):
    """Overlap-mode fleet driver: the bus relays continuously; each robot
    thread submits its frame to the overlapped client and computes against
    the freshest broadcast (bounded staleness)."""
    import threading

    from dpgo_tpu.comms import TransportClosed

    stop = threading.Event()

    def bus_loop():
        while not stop.is_set():
            if len(bus.lost) == len(bus.channels):
                break
            bus.round()

    def robot_loop(rid):
        ag, client = agents[rid], clients[rid]
        client.start_overlap(staleness, timeout=0.5)
        for it in range(rounds):
            if kill is not None and rid == kill[0] and it == kill[1]:
                client.close()
                return
            frame = pack_agent_frame(ag, include_anchor=(rid == 0))
            try:
                merged = client.exchange(frame, timeout=0.5)
            except TransportClosed:
                return
            if merged is not None:
                for peer, pf in client.peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
                for lost in client.lost:
                    ag.mark_neighbor_lost(lost)
            ag.iterate(True)
            time.sleep(PACE_S)
        try:
            client.drain_overlap(timeout=10.0)
        except TransportClosed:
            pass

    bus_thread = threading.Thread(target=bus_loop, daemon=True)
    bus_thread.start()
    threads = [threading.Thread(target=robot_loop, args=(rid,), daemon=True)
               for rid in agents]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # Stop the relay BEFORE closing anything: closing a transport under a
    # live bus.round() reads as a dead robot ("closed") on the hub.
    stop.set()
    bus_thread.join(timeout=10)
    bus.close()
    for rid, c in clients.items():
        if kill is None or rid != kill[0]:
            c.close()
    return agents, bus, clients


def _team_cost(agents, part, meas, survivors):
    """SE(d) cost of the assembled global trajectory over the edges whose
    BOTH endpoints belong to surviving robots."""
    d = meas.d
    anchor = agents[0].get_global_anchor()
    T = np.zeros((meas.num_poses, d, d + 1))
    for rid in survivors:
        ag = agents[rid]
        if ag.get_global_anchor() is None:
            ag.set_global_anchor(anchor)
        ids = part.global_index[rid][part.global_index[rid] >= 0]
        T[ids] = ag.trajectory_in_global_frame()
    # Robot ownership lives in the robot-local view (meas_global keeps
    # r1 == r2 == 0 by construction); the two share row order.
    pm = part.meas
    keep = np.isin(np.asarray(pm.r1), list(survivors)) & \
        np.isin(np.asarray(pm.r2), list(survivors))
    edges = edge_set_from_measurements(part.meas_global.select(keep),
                                       dtype=jnp.float64)
    return float(quadratic.cost(jnp.asarray(T), edges))


def test_chaos_solve_completes_and_matches_fault_free(tmp_path):
    """The acceptance scenario, telemetry on so the failure story is also
    asserted: 10% drop + multi-round delays + reorders + robot 2 killed at
    round 40.  The run must complete (no hang), the bus and every survivor
    must know robot 2 is gone, and the survivors' final cost must be
    within 1% of the fault-free run on the same dataset."""
    meas, part = _make_problem()
    survivors = [0, 1]

    clean_agents, clean_bus, _ = _run_fleet(part)
    assert clean_bus.lost == set()
    cost_clean = _team_cost(clean_agents, part, meas, survivors)

    injector = FaultInjector(CHAOS, seed=7)
    with obs.run_scope(str(tmp_path / "chaos")) as run:
        agents, bus, clients = _run_fleet(part, injector=injector,
                                          kill=KILL)
        snap = run.registry.snapshot()

    # The network actually hurt, deterministically per link.
    assert injector.stats["dropped"] > 0
    assert injector.stats["delayed"] > 0
    totals = bus.totals()
    assert totals.timeouts > 0  # dropped frames cost bounded waits only

    # Graceful dropout: everyone knows, nobody hung.
    assert bus.lost == {KILL[0]}
    for rid in survivors:
        assert agents[rid].lost_neighbors == [KILL[0]]
        assert agents[rid].get_status().state == AgentState.INITIALIZED
        # Survivors completed essentially every round (late initialization
        # may cost the non-anchor robot a couple of early iterates).
        assert agents[rid].get_status().iteration_number >= ROUNDS - 5

    # Degraded-mode quality: within 1% of the fault-free solve.
    cost_chaos = _team_cost(agents, part, meas, survivors)
    assert cost_chaos == pytest.approx(cost_clean, rel=0.01)

    # Telemetry captured the story: peer_lost events (bus + agents) and
    # the terminal run_summary with network-health totals.
    evs = read_events(str(tmp_path / "chaos" / "events.jsonl"))
    lost_evs = [e for e in evs if e["event"] == "peer_lost"]
    assert {e.get("peer") for e in lost_evs} == {KILL[0]}
    assert any("robot" in e for e in lost_evs)  # agent-side quorum events
    (bus_summary,) = [e for e in evs if e["event"] == "run_summary"
                      and e["channel"] == "bus"]
    assert bus_summary["peers_lost"] == [KILL[0]]
    assert bus_summary["messages_received"] > 0
    assert "comms_stale_dropped" in snap or totals.stale_dropped == 0


def test_chaos_partition_heals_and_solve_finishes():
    """A transient network partition (robot 1 unreachable for 15 rounds)
    freezes its poses on both sides; when the partition heals the solve
    converges to the fault-free optimum — nobody was declared dead because
    the miss/heartbeat thresholds tolerate the outage."""
    meas, part = _make_problem()
    all_robots = [0, 1, 2]

    clean_agents, _, _ = _run_fleet(part)
    cost_clean = _team_cost(clean_agents, part, meas, all_robots)

    spec = FaultSpec(partitions=(("robot1",),))
    injector = FaultInjector(spec, seed=3)
    injector.enabled = False

    params = AgentParams(d=3, r=5, num_robots=NUM_ROBOTS)
    agents = {rid: PGOAgent(rid, params) for rid in range(NUM_ROBOTS)}
    for rid in range(1, NUM_ROBOTS):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    for rid, ag in agents.items():
        ag.set_pose_graph(*agent_measurements(part, rid))
    bus, clients = loopback_fleet(
        NUM_ROBOTS, injector=injector, policy=POLICY,
        round_timeout_s=0.1, miss_limit=100, liveness_timeout_s=30.0)
    for it in range(ROUNDS):
        injector.enabled = 20 <= it < 35  # the outage window
        for rid, ag in agents.items():
            clients[rid].publish(
                pack_agent_frame(ag, include_anchor=(rid == 0)), timeout=0.5)
        bus.round()
        for rid, ag in agents.items():
            merged = clients[rid].collect(timeout=0.3)
            if merged is not None:
                for peer, pf in clients[rid].peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
            ag.iterate(True)
    bus.close()
    for c in clients.values():
        c.close()

    assert bus.lost == set()  # outage tolerated, nobody declared dead
    assert injector.stats["partitioned"] > 0
    cost = _team_cost(agents, part, meas, all_robots)
    assert cost == pytest.approx(cost_clean, rel=0.01)


def test_chaos_overlap_staleness_converges_with_drops():
    """The overlap-mode staleness chaos test: compute/comm overlap at
    staleness=1 PLUS 10% frame drop must land within 1% of the lockstep
    fault-free cost — bounded staleness is exactly the regime the RA-L
    2020 asynchronous convergence result covers, so overlapping round k's
    RTR step with round k's exchange loses nothing."""
    meas, part = _make_problem()
    all_robots = [0, 1, 2]

    clean_agents, clean_bus, _ = _run_fleet(part)
    assert clean_bus.lost == set()
    cost_clean = _team_cost(clean_agents, part, meas, all_robots)

    injector = FaultInjector(FaultSpec(drop=0.10), seed=13)
    agents, bus, clients = _run_fleet(part, injector=injector,
                                      staleness=1, rounds=ROUNDS + 15)
    assert injector.stats["dropped"] > 0
    assert bus.lost == set()
    for rid in all_robots:
        assert agents[rid].get_status().state == AgentState.INITIALIZED
        # Overlap: the exchange thread never blocked the iterate loop for
        # a full round — every robot completed essentially every round.
        assert agents[rid].get_status().iteration_number >= ROUNDS
    cost_overlap = _team_cost(agents, part, meas, all_robots)
    assert cost_overlap == pytest.approx(cost_clean, rel=0.01)


def _lockstep_fleet_round(agents, bus, clients, dead, on_merged=None):
    """One lockstep publish -> relay -> collect/apply -> iterate round over
    the live robots (the _run_fleet body, factored for drivers that mutate
    the fleet between rounds)."""
    for rid, ag in agents.items():
        if rid in dead:
            continue
        clients[rid].publish(pack_agent_frame(ag, include_anchor=(rid == 0)),
                             timeout=0.5)
    bus.round()
    for rid, ag in agents.items():
        if rid in dead:
            continue
        merged = clients[rid].collect(timeout=0.3)
        if merged is not None:
            if on_merged is not None:
                on_merged(rid, ag, clients[rid])
            for peer, pf in clients[rid].peer_frames(merged).items():
                apply_peer_frame(ag, peer, pf,
                                 accept_anchor=(rid != 0 and peer == 0))
            for lost in clients[rid].lost:
                ag.mark_neighbor_lost(lost)
        ag.iterate(True)


def _new_loopback_robot(rid, injector=None):
    """One extra loopback transport pair for a robot joining a live bus."""
    from dpgo_tpu.comms import BusClient, ReliableChannel
    from dpgo_tpu.comms.transport import LoopbackTransport

    t_bus, t_robot = LoopbackTransport.pair("bus", f"robot{rid}",
                                            injector=injector,
                                            wire_format="packed")
    hub_ch = ReliableChannel(t_bus, f"bus->robot{rid}", POLICY, origin=-1)
    client = BusClient(ReliableChannel(t_robot, f"robot{rid}->bus", POLICY),
                       rid)
    return hub_ch, client


def test_chaos_kill_and_join_mid_solve(tmp_path):
    """ACCEPTANCE (elastic fleets): seeded run where robot 2 is KILLED and
    a new robot 3 JOINS mid-solve, under a seeded 5% frame drop.  The
    fleet must terminate, the survivors+joiner cost must land within 1% of
    the fault-free all-robots run over the same edge set, and the joined
    robot's activity must appear in the run's merged event record."""
    n_robots = 4
    joiner, join_at = 3, 15
    kill = (2, 45)
    rounds = 80
    final_team = [0, 1, 3]

    rng = np.random.default_rng(0)
    meas, _ = make_measurements(rng, n=32, d=3, num_lc=16,
                                rot_noise=0.01, trans_noise=0.01)
    part = partition_contiguous(meas, n_robots)

    def split_for(rid):
        """(odometry, private, shared-without-joiner, shared-with-joiner)."""
        odo, priv, shared = agent_measurements(part, rid)
        touches = (np.asarray(shared.r1) == joiner) | \
            (np.asarray(shared.r2) == joiner)
        return odo, priv, shared.select(~touches), shared.select(touches)

    # --- fault-free reference: all four robots from the start -------------
    params4 = AgentParams(d=3, r=5, num_robots=n_robots)
    clean = {rid: PGOAgent(rid, params4) for rid in range(n_robots)}
    for rid in range(1, n_robots):
        clean[rid].set_lifting_matrix(clean[0].get_lifting_matrix())
    for rid, ag in clean.items():
        ag.set_pose_graph(*agent_measurements(part, rid))
    bus_c, clients_c = loopback_fleet(n_robots, policy=POLICY,
                                      round_timeout_s=0.15, miss_limit=5,
                                      liveness_timeout_s=0.5)
    for _ in range(rounds):
        _lockstep_fleet_round(clean, bus_c, clients_c, dead=set())
    bus_c.close()
    for c in clients_c.values():
        c.close()
    assert bus_c.lost == set()
    cost_clean = _team_cost(clean, part, meas, final_team)

    # --- chaos arm: start with 3 robots, join robot 3, kill robot 2 -------
    injector = FaultInjector(FaultSpec(drop=0.05), seed=17)
    params3 = AgentParams(d=3, r=5, num_robots=joiner)
    agents = {rid: PGOAgent(rid, params3) for rid in range(joiner)}
    for rid in range(1, joiner):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    withheld = {}
    for rid in range(joiner):
        odo, priv, shared_kept, shared_joiner = split_for(rid)
        withheld[rid] = shared_joiner
        agents[rid].set_pose_graph(odo, priv, shared_kept)

    with obs.run_scope(str(tmp_path / "join")):
        bus, clients = loopback_fleet(joiner, injector=injector,
                                      policy=POLICY, round_timeout_s=0.15,
                                      miss_limit=50,
                                      liveness_timeout_s=5.0)
        for c in clients.values():
            c.channel.start_heartbeat(0.05)
        dead: set[int] = set()
        admitted: dict[int, set] = {rid: set() for rid in range(joiner)}

        def on_merged(rid, ag, client):
            # The join handshake, survivor side: grow the problem with the
            # withheld inter-robot edges the joiner brings.
            for j in client.joined:
                if j != rid and j not in admitted[rid]:
                    ag.admit_neighbor(j, withheld.get(rid))
                    admitted[rid].add(j)

        for it in range(rounds):
            if it == join_at:
                # Joiner comes up: its own problem includes the shared
                # edges to the survivors; the hub admits it via the
                # hello handshake.
                ag3 = PGOAgent(joiner, params4)
                ag3.set_lifting_matrix(agents[0].get_lifting_matrix())
                ag3.set_pose_graph(*agent_measurements(part, joiner))
                hub_ch, cl3 = _new_loopback_robot(joiner, injector)
                cl3.channel.start_heartbeat(0.05)
                cl3.hello()
                assert bus.admit_hello(hub_ch, timeout=1.0) == joiner
                agents[joiner] = ag3
                clients[joiner] = cl3
                admitted[joiner] = set()
            if it == kill[1]:
                dead.add(kill[0])
                clients[kill[0]].close()
            _lockstep_fleet_round(agents, bus, clients, dead,
                                  on_merged=on_merged)
            if injector is not None:
                time.sleep(PACE_S)
        bus.close()
        for rid, c in clients.items():
            if rid not in dead:
                c.close()

    # The network actually dropped frames, deterministically.
    assert injector.stats["dropped"] > 0
    # The fleet knows who left and who arrived.
    assert bus.lost == {kill[0]}
    assert bus.joined == {joiner}
    for rid in [0, 1]:
        assert agents[rid].lost_neighbors == [kill[0]]
        assert joiner in admitted[rid]
        # quorum grew: the consensus test now spans the joiner too
        assert agents[rid].num_robots == n_robots
    # The joiner aligned into the global frame and took part.
    assert agents[joiner].get_status().state == AgentState.INITIALIZED
    assert agents[joiner].get_status().iteration_number >= \
        (rounds - join_at) - 5

    cost_chaos = _team_cost(agents, part, meas, final_team)
    assert cost_chaos == pytest.approx(cost_clean, rel=0.01)

    # The joined robot's activity is in the merged record: the bus + the
    # survivors announced it, and its own lifecycle/iterate events landed.
    evs = read_events(str(tmp_path / "join" / "events.jsonl"))
    joined_evs = [e for e in evs if e["event"] == "peer_joined"]
    assert {e.get("peer") for e in joined_evs} == {joiner}
    assert any("robot" in e for e in joined_evs)  # agent-side admits
    assert any(e["event"] == "agent_state" and e.get("robot") == joiner
               and e.get("state") == "INITIALIZED" for e in evs)
    assert any(e["event"] == "agent_iterate" and e.get("robot") == joiner
               for e in evs)


def test_chaos_partition_lost_then_healed_revives_with_fresh_state(tmp_path):
    """Regression (lost/revive asymmetry): a partition long enough that
    robot 1 IS declared lost; on heal the driver re-admits it and the
    survivors' agents revive it off its first fresh frame — sequence reset,
    stale cache invalidated, and the solve still converges to the
    fault-free optimum."""
    meas, part = _make_problem()
    all_robots = [0, 1, 2]

    clean_agents, _, _ = _run_fleet(part)
    cost_clean = _team_cost(clean_agents, part, meas, all_robots)

    spec = FaultSpec(partitions=(("robot1",),))
    injector = FaultInjector(spec, seed=5)
    injector.enabled = False

    params = AgentParams(d=3, r=5, num_robots=NUM_ROBOTS)
    agents = {rid: PGOAgent(rid, params) for rid in range(NUM_ROBOTS)}
    for rid in range(1, NUM_ROBOTS):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    for rid, ag in agents.items():
        ag.set_pose_graph(*agent_measurements(part, rid))
    with obs.run_scope(str(tmp_path / "heal")):
        # Tight liveness so the outage DOES cross the dropout threshold.
        bus, clients = loopback_fleet(
            NUM_ROBOTS, injector=injector, policy=POLICY,
            round_timeout_s=0.1, miss_limit=3, liveness_timeout_s=0.05)
        lost_seen = False
        for it in range(ROUNDS + 15):
            injector.enabled = 20 <= it < 32  # the outage window
            if it == 32:
                # Heal: the hub re-admits the robot on its live channel
                # (the rejoin handshake); its queued fresh frames flow
                # again from the next round.
                assert bus.lost == {1}  # the outage DID cross the threshold
                bus.admit(1, bus.channels[1])
            _lockstep_fleet_round(agents, bus, clients, dead=set())
            if bus.lost == {1}:
                lost_seen = True
        bus.close()
        for c in clients.values():
            c.close()

    assert lost_seen
    assert bus.lost == set()
    # Every survivor revived robot 1 (nobody still excludes it).
    for rid in (0, 2):
        assert agents[rid].lost_neighbors == []
    evs = read_events(str(tmp_path / "heal" / "events.jsonl"))
    assert any(e["event"] == "peer_revived" and e.get("peer") == 1
               for e in evs)
    cost = _team_cost(agents, part, meas, all_robots)
    assert cost == pytest.approx(cost_clean, rel=0.01)


def test_chaos_comms_layer_zero_obs_events_when_telemetry_off(monkeypatch):
    """The acceptance fence-throw: with telemetry off, the comms layer —
    channel traffic under faults, bus dropout, the agent's stale-drop and
    peer-lost bookkeeping — adds ZERO obs events and registry calls."""
    from dpgo_tpu.obs import run as obs_run_mod
    from dpgo_tpu.obs import metrics as obs_metrics_mod
    from dpgo_tpu.obs import trace as obs_trace_mod
    from dpgo_tpu.obs.events import EventStream

    def boom(*a, **kw):
        raise AssertionError("telemetry path taken while disabled")

    monkeypatch.setattr(EventStream, "emit", boom)
    monkeypatch.setattr(obs_run_mod, "materialize", boom)
    monkeypatch.setattr(obs, "materialize", boom)
    monkeypatch.setattr(obs_metrics_mod.Counter, "inc", boom)
    monkeypatch.setattr(obs_metrics_mod.Gauge, "set", boom)
    monkeypatch.setattr(obs_metrics_mod.Histogram, "observe", boom)
    monkeypatch.setattr(obs_metrics_mod.Histogram, "observe_many", boom)
    monkeypatch.setattr(obs_trace_mod.Span, "__init__", boom)
    monkeypatch.setattr(obs_trace_mod, "emit_span", boom)
    assert obs.get_run() is None

    injector = FaultInjector(FaultSpec(drop=0.3, reorder=0.5), seed=11)
    bus, clients = loopback_fleet(2, injector=injector, policy=POLICY,
                                  round_timeout_s=0.05,
                                  liveness_timeout_s=0.05)
    # Agent-side transport bookkeeping, no pose graph needed: stale
    # sequence drop and the lost/revive cycle are pure host bookkeeping.
    ag = PGOAgent(0, AgentParams(d=3, r=5, num_robots=2))
    ag.update_neighbor_poses(1, {}, sequence=5)
    ag.update_neighbor_poses(1, {}, sequence=3)   # stale -> dropped
    ag.mark_neighbor_lost(1)
    assert ag.lost_neighbors == [1]
    ag.update_neighbor_poses(1, {}, sequence=6)   # fresh -> revived
    assert ag.lost_neighbors == []

    for _ in range(4):
        for c in clients.values():
            c.publish({"v": np.asarray(1)})
        bus.round()
        for c in clients.values():
            c.collect(timeout=0.1)
    clients[1].close()
    clients[0].publish({"v": np.asarray(2)})
    bus.round()
    assert bus.lost == {1}
    bus.close()
    clients[0].close()
