"""Seeded chaos tests: the full deployment stack (PGOAgent over the
``dpgo_tpu.comms`` loopback fleet) under injected network faults and a
mid-solve robot death.

The acceptance scenario: 10% frame drop + ~2-round delays + one robot
killed mid-solve completes WITHOUT hanging and lands within 1% of the
fault-free run's cost on the same synthetic dataset (evaluated over the
edges among surviving robots).  Every run is seeded — the fault stream is
deterministic per link."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.agent import AgentState, PGOAgent
from dpgo_tpu.comms import (FaultInjector, FaultSpec,
                            RetryPolicy, apply_peer_frame, loopback_fleet,
                            pack_agent_frame)
from dpgo_tpu.config import AgentParams
from dpgo_tpu.obs.events import read_events
from dpgo_tpu.ops import quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import agent_measurements, partition_contiguous
from dpgo_tpu.utils.synthetic import make_measurements

NUM_ROBOTS = 3
ROUNDS = 60
KILL = (2, 40)  # robot 2 dies at round 40

# ~2-round delays: rounds are paced at PACE_S, delays span 1-3 rounds.
PACE_S = 0.004
CHAOS = FaultSpec(drop=0.10, delay=0.25, delay_s=(PACE_S, 3 * PACE_S),
                  reorder=0.05)

POLICY = RetryPolicy(max_attempts=2, base_delay_s=0.002, max_delay_s=0.01,
                     send_timeout_s=0.5, recv_timeout_s=0.5)


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _make_problem(seed=0, n=24, num_lc=12):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.01, trans_noise=0.01)
    return meas, partition_contiguous(meas, NUM_ROBOTS)


def _run_fleet(part, injector=None, kill=None, rounds=ROUNDS,
               staleness=0):
    """Drive a full sync solve over the loopback fleet (the in-process
    twin of examples/tcp_deployment_example.py's robot loop).

    ``staleness=0`` is the PR-2 lockstep schedule, unchanged;
    ``staleness>=1`` runs each robot's exchange through the overlapped
    bus client (publish + prefetch on a background thread while the RTR
    step runs) with per-robot driver threads, the deployment examples'
    overlap mode."""
    params = AgentParams(d=3, r=5, num_robots=NUM_ROBOTS)
    agents = {rid: PGOAgent(rid, params) for rid in range(NUM_ROBOTS)}
    for rid in range(1, NUM_ROBOTS):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    for rid, ag in agents.items():
        ag.set_pose_graph(*agent_measurements(part, rid))

    if staleness > 0:
        # Overlap mode free-runs the bus while robots compile their first
        # step (seconds of GIL-held XLA work that can starve heartbeat
        # threads) — use the deployment examples' tolerant liveness
        # thresholds; dropout detection is lockstep-tested above.
        bus, clients = loopback_fleet(
            NUM_ROBOTS, injector=injector, policy=POLICY,
            round_timeout_s=0.15, miss_limit=100, liveness_timeout_s=10.0)
        for c in clients.values():
            c.channel.start_heartbeat(0.05)
        return _run_fleet_overlapped(part, agents, bus, clients, kill,
                                     rounds, staleness)
    bus, clients = loopback_fleet(
        NUM_ROBOTS, injector=injector, policy=POLICY,
        round_timeout_s=0.15, miss_limit=5, liveness_timeout_s=0.5)
    for c in clients.values():
        c.channel.start_heartbeat(0.05)
    dead: set[int] = set()
    for it in range(rounds):
        if kill is not None and it == kill[1]:
            dead.add(kill[0])
            clients[kill[0]].close()
        for rid, ag in agents.items():
            if rid in dead:
                continue
            clients[rid].publish(
                pack_agent_frame(ag, include_anchor=(rid == 0)),
                timeout=0.5)
        bus.round()
        for rid, ag in agents.items():
            if rid in dead:
                continue
            merged = clients[rid].collect(timeout=0.3)
            if merged is not None:
                for peer, pf in clients[rid].peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
                for lost in clients[rid].lost:
                    ag.mark_neighbor_lost(lost)
            ag.iterate(True)
        if injector is not None:
            time.sleep(PACE_S)
    bus.close()
    for rid, c in clients.items():
        if rid not in dead:
            c.close()
    return agents, bus, clients


def _run_fleet_overlapped(part, agents, bus, clients, kill, rounds,
                          staleness):
    """Overlap-mode fleet driver: the bus relays continuously; each robot
    thread submits its frame to the overlapped client and computes against
    the freshest broadcast (bounded staleness)."""
    import threading

    from dpgo_tpu.comms import TransportClosed

    stop = threading.Event()

    def bus_loop():
        while not stop.is_set():
            if len(bus.lost) == len(bus.channels):
                break
            bus.round()

    def robot_loop(rid):
        ag, client = agents[rid], clients[rid]
        client.start_overlap(staleness, timeout=0.5)
        for it in range(rounds):
            if kill is not None and rid == kill[0] and it == kill[1]:
                client.close()
                return
            frame = pack_agent_frame(ag, include_anchor=(rid == 0))
            try:
                merged = client.exchange(frame, timeout=0.5)
            except TransportClosed:
                return
            if merged is not None:
                for peer, pf in client.peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
                for lost in client.lost:
                    ag.mark_neighbor_lost(lost)
            ag.iterate(True)
            time.sleep(PACE_S)
        try:
            client.drain_overlap(timeout=10.0)
        except TransportClosed:
            pass

    bus_thread = threading.Thread(target=bus_loop, daemon=True)
    bus_thread.start()
    threads = [threading.Thread(target=robot_loop, args=(rid,), daemon=True)
               for rid in agents]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # Stop the relay BEFORE closing anything: closing a transport under a
    # live bus.round() reads as a dead robot ("closed") on the hub.
    stop.set()
    bus_thread.join(timeout=10)
    bus.close()
    for rid, c in clients.items():
        if kill is None or rid != kill[0]:
            c.close()
    return agents, bus, clients


def _team_cost(agents, part, meas, survivors):
    """SE(d) cost of the assembled global trajectory over the edges whose
    BOTH endpoints belong to surviving robots."""
    d = meas.d
    anchor = agents[0].get_global_anchor()
    T = np.zeros((meas.num_poses, d, d + 1))
    for rid in survivors:
        ag = agents[rid]
        if ag.get_global_anchor() is None:
            ag.set_global_anchor(anchor)
        ids = part.global_index[rid][part.global_index[rid] >= 0]
        T[ids] = ag.trajectory_in_global_frame()
    # Robot ownership lives in the robot-local view (meas_global keeps
    # r1 == r2 == 0 by construction); the two share row order.
    pm = part.meas
    keep = np.isin(np.asarray(pm.r1), list(survivors)) & \
        np.isin(np.asarray(pm.r2), list(survivors))
    edges = edge_set_from_measurements(part.meas_global.select(keep),
                                       dtype=jnp.float64)
    return float(quadratic.cost(jnp.asarray(T), edges))


def test_chaos_solve_completes_and_matches_fault_free(tmp_path):
    """The acceptance scenario, telemetry on so the failure story is also
    asserted: 10% drop + multi-round delays + reorders + robot 2 killed at
    round 40.  The run must complete (no hang), the bus and every survivor
    must know robot 2 is gone, and the survivors' final cost must be
    within 1% of the fault-free run on the same dataset."""
    meas, part = _make_problem()
    survivors = [0, 1]

    clean_agents, clean_bus, _ = _run_fleet(part)
    assert clean_bus.lost == set()
    cost_clean = _team_cost(clean_agents, part, meas, survivors)

    injector = FaultInjector(CHAOS, seed=7)
    with obs.run_scope(str(tmp_path / "chaos")) as run:
        agents, bus, clients = _run_fleet(part, injector=injector,
                                          kill=KILL)
        snap = run.registry.snapshot()

    # The network actually hurt, deterministically per link.
    assert injector.stats["dropped"] > 0
    assert injector.stats["delayed"] > 0
    totals = bus.totals()
    assert totals.timeouts > 0  # dropped frames cost bounded waits only

    # Graceful dropout: everyone knows, nobody hung.
    assert bus.lost == {KILL[0]}
    for rid in survivors:
        assert agents[rid].lost_neighbors == [KILL[0]]
        assert agents[rid].get_status().state == AgentState.INITIALIZED
        # Survivors completed essentially every round (late initialization
        # may cost the non-anchor robot a couple of early iterates).
        assert agents[rid].get_status().iteration_number >= ROUNDS - 5

    # Degraded-mode quality: within 1% of the fault-free solve.
    cost_chaos = _team_cost(agents, part, meas, survivors)
    assert cost_chaos == pytest.approx(cost_clean, rel=0.01)

    # Telemetry captured the story: peer_lost events (bus + agents) and
    # the terminal run_summary with network-health totals.
    evs = read_events(str(tmp_path / "chaos" / "events.jsonl"))
    lost_evs = [e for e in evs if e["event"] == "peer_lost"]
    assert {e.get("peer") for e in lost_evs} == {KILL[0]}
    assert any("robot" in e for e in lost_evs)  # agent-side quorum events
    (bus_summary,) = [e for e in evs if e["event"] == "run_summary"
                      and e["channel"] == "bus"]
    assert bus_summary["peers_lost"] == [KILL[0]]
    assert bus_summary["messages_received"] > 0
    assert "comms_stale_dropped" in snap or totals.stale_dropped == 0


def test_chaos_partition_heals_and_solve_finishes():
    """A transient network partition (robot 1 unreachable for 15 rounds)
    freezes its poses on both sides; when the partition heals the solve
    converges to the fault-free optimum — nobody was declared dead because
    the miss/heartbeat thresholds tolerate the outage."""
    meas, part = _make_problem()
    all_robots = [0, 1, 2]

    clean_agents, _, _ = _run_fleet(part)
    cost_clean = _team_cost(clean_agents, part, meas, all_robots)

    spec = FaultSpec(partitions=(("robot1",),))
    injector = FaultInjector(spec, seed=3)
    injector.enabled = False

    params = AgentParams(d=3, r=5, num_robots=NUM_ROBOTS)
    agents = {rid: PGOAgent(rid, params) for rid in range(NUM_ROBOTS)}
    for rid in range(1, NUM_ROBOTS):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    for rid, ag in agents.items():
        ag.set_pose_graph(*agent_measurements(part, rid))
    bus, clients = loopback_fleet(
        NUM_ROBOTS, injector=injector, policy=POLICY,
        round_timeout_s=0.1, miss_limit=100, liveness_timeout_s=30.0)
    for it in range(ROUNDS):
        injector.enabled = 20 <= it < 35  # the outage window
        for rid, ag in agents.items():
            clients[rid].publish(
                pack_agent_frame(ag, include_anchor=(rid == 0)), timeout=0.5)
        bus.round()
        for rid, ag in agents.items():
            merged = clients[rid].collect(timeout=0.3)
            if merged is not None:
                for peer, pf in clients[rid].peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
            ag.iterate(True)
    bus.close()
    for c in clients.values():
        c.close()

    assert bus.lost == set()  # outage tolerated, nobody declared dead
    assert injector.stats["partitioned"] > 0
    cost = _team_cost(agents, part, meas, all_robots)
    assert cost == pytest.approx(cost_clean, rel=0.01)


def test_chaos_overlap_staleness_converges_with_drops():
    """The overlap-mode staleness chaos test: compute/comm overlap at
    staleness=1 PLUS 10% frame drop must land within 1% of the lockstep
    fault-free cost — bounded staleness is exactly the regime the RA-L
    2020 asynchronous convergence result covers, so overlapping round k's
    RTR step with round k's exchange loses nothing."""
    meas, part = _make_problem()
    all_robots = [0, 1, 2]

    clean_agents, clean_bus, _ = _run_fleet(part)
    assert clean_bus.lost == set()
    cost_clean = _team_cost(clean_agents, part, meas, all_robots)

    injector = FaultInjector(FaultSpec(drop=0.10), seed=13)
    agents, bus, clients = _run_fleet(part, injector=injector,
                                      staleness=1, rounds=ROUNDS + 15)
    assert injector.stats["dropped"] > 0
    assert bus.lost == set()
    for rid in all_robots:
        assert agents[rid].get_status().state == AgentState.INITIALIZED
        # Overlap: the exchange thread never blocked the iterate loop for
        # a full round — every robot completed essentially every round.
        assert agents[rid].get_status().iteration_number >= ROUNDS
    cost_overlap = _team_cost(agents, part, meas, all_robots)
    assert cost_overlap == pytest.approx(cost_clean, rel=0.01)


def test_chaos_comms_layer_zero_obs_events_when_telemetry_off(monkeypatch):
    """The acceptance fence-throw: with telemetry off, the comms layer —
    channel traffic under faults, bus dropout, the agent's stale-drop and
    peer-lost bookkeeping — adds ZERO obs events and registry calls."""
    from dpgo_tpu.obs import run as obs_run_mod
    from dpgo_tpu.obs import metrics as obs_metrics_mod
    from dpgo_tpu.obs import trace as obs_trace_mod
    from dpgo_tpu.obs.events import EventStream

    def boom(*a, **kw):
        raise AssertionError("telemetry path taken while disabled")

    monkeypatch.setattr(EventStream, "emit", boom)
    monkeypatch.setattr(obs_run_mod, "materialize", boom)
    monkeypatch.setattr(obs, "materialize", boom)
    monkeypatch.setattr(obs_metrics_mod.Counter, "inc", boom)
    monkeypatch.setattr(obs_metrics_mod.Gauge, "set", boom)
    monkeypatch.setattr(obs_metrics_mod.Histogram, "observe", boom)
    monkeypatch.setattr(obs_metrics_mod.Histogram, "observe_many", boom)
    monkeypatch.setattr(obs_trace_mod.Span, "__init__", boom)
    monkeypatch.setattr(obs_trace_mod, "emit_span", boom)
    assert obs.get_run() is None

    injector = FaultInjector(FaultSpec(drop=0.3, reorder=0.5), seed=11)
    bus, clients = loopback_fleet(2, injector=injector, policy=POLICY,
                                  round_timeout_s=0.05,
                                  liveness_timeout_s=0.05)
    # Agent-side transport bookkeeping, no pose graph needed: stale
    # sequence drop and the lost/revive cycle are pure host bookkeeping.
    ag = PGOAgent(0, AgentParams(d=3, r=5, num_robots=2))
    ag.update_neighbor_poses(1, {}, sequence=5)
    ag.update_neighbor_poses(1, {}, sequence=3)   # stale -> dropped
    ag.mark_neighbor_lost(1)
    assert ag.lost_neighbors == [1]
    ag.update_neighbor_poses(1, {}, sequence=6)   # fresh -> revived
    assert ag.lost_neighbors == []

    for _ in range(4):
        for c in clients.values():
            c.publish({"v": np.asarray(1)})
        bus.round()
        for c in clients.values():
            c.collect(timeout=0.1)
    clients[1].close()
    clients[0].publish({"v": np.asarray(2)})
    bus.round()
    assert bus.lost == {1}
    bus.close()
    clients[0].close()
