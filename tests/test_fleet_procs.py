"""Out-of-process fleet replicas (``serve.fleet.procs``, ISSUE 17).

Tier-1 pins the parent-side contract without spawning children: the
``ProcTicket`` future semantics, the structured replica-death error the
router reroutes on, the ``solve_m`` ``Measurements`` wire round-trip,
and the front-end's ``status``/``drain``/``solve_m`` ops in-process.

The slow-marked tests run REAL child processes: boot + solve over the
packed-v2 TCP front-end, a mid-flight ``SIGKILL`` surfacing as a
reroutable death, drain-for-migration, and a 2-process fleet that loses
zero sessions across an actual process kill.
"""

import os
import threading
import time

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.comms.protocol import unpack_measurements
from dpgo_tpu.config import AgentParams
from dpgo_tpu.serve import ReplicaManager, SolveRequest, SolveServer
from dpgo_tpu.serve.fleet import FleetRouter, ProcServer, ProcTicket
from dpgo_tpu.serve.fleet.procs import _death_error, _result_from_reply
from dpgo_tpu.serve.fleet.router import _is_replica_death
from dpgo_tpu.serve.frontend import (ServeFrontend, _pack_str, _unpack_str,
                                     handle_request, solve_m_frame)
from dpgo_tpu.serve.server import OverCapacityError

from synthetic import make_measurements

#: Consensus unreachable + zero gradient tolerance: solves run their
#: full iteration budget, so kills and drains land mid-flight.
PARAMS = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=-1.0)


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _problem(seed=0, n=24):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=8, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _req(meas, sid=None, iters=2, eval_every=2):
    return SolveRequest(meas=meas, num_robots=2, params=PARAMS,
                        max_iters=iters, grad_norm_tol=0.0,
                        eval_every=eval_every, session_id=sid)


@pytest.fixture(scope="module")
def meas():
    return _problem()


@pytest.fixture(scope="module")
def aot_root(tmp_path_factory, meas):
    """Shared persistent AOT cache: the parent pays the compile once;
    every spawned child disk-loads in milliseconds."""
    root = str(tmp_path_factory.mktemp("aot"))
    with SolveServer(max_batch=2, batch_window_s=0.0,
                     aot_cache_dir=root) as srv:
        srv.solve(_req(meas), timeout=600)
    return root


# ---------------------------------------------------------------------------
# ProcTicket + death classification (no child processes)
# ---------------------------------------------------------------------------

def test_proc_ticket_first_finisher_wins():
    t = ProcTicket(request=None)
    assert not t.done()
    t._finish(result="migrated-marker")
    t._finish(exception=RuntimeError("late pump reply must lose"))
    assert t.done() and t.result(timeout=1) == "migrated-marker"
    t2 = ProcTicket(request=None)
    t2._finish(exception=RuntimeError("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        t2.result(timeout=1)


def test_death_error_reads_as_replica_death_to_the_router():
    # The router reroutes on deaths and fails the caller on request
    # errors; a child's connection dropping mid-RPC must be the former.
    assert _is_replica_death(_death_error("r0", "ConnectionReset"))
    assert _is_replica_death(OverCapacityError("gone", reason="closed"))
    assert not _is_replica_death(ValueError("bad request"))
    assert not _is_replica_death(
        OverCapacityError("busy", reason="queue"))


def test_result_from_reply_builds_an_rbcd_result_view():
    reply = {"ok": np.int8(1), "T": np.zeros((3, 4)),
             "cost_history": np.asarray([2.0, 1.0]),
             "grad_norm_history": np.asarray([0.5, 0.1]),
             "iterations": np.int32(2),
             "terminated_by": _pack_str("max_iters"),
             "recovered": np.int8(1)}
    res = _result_from_reply(reply)
    assert res.iterations == 2 and res.terminated_by == "max_iters"
    assert res.recovered is True and res.cost_history == [2.0, 1.0]


# ---------------------------------------------------------------------------
# solve_m wire round-trip (no sockets)
# ---------------------------------------------------------------------------

def test_solve_m_frame_round_trips_measurements(meas):
    r = _req(meas, sid="sess-7")
    frame = solve_m_frame(r)
    m2 = unpack_measurements(frame, "meas")
    assert m2.d == meas.d and m2.num_poses == meas.num_poses
    for field in ("r1", "p1", "r2", "p2"):
        np.testing.assert_array_equal(getattr(m2, field),
                                      getattr(meas, field))
    for field in ("R", "t", "kappa", "tau", "weight"):
        np.testing.assert_allclose(getattr(m2, field),
                                   getattr(meas, field))
    np.testing.assert_array_equal(m2.is_known_inlier, meas.is_known_inlier)
    assert int(np.asarray(frame["rank"])) == PARAMS.r
    assert float(np.asarray(frame["rel_change_tol"])) == \
        PARAMS.rel_change_tol
    assert _unpack_str(frame["session"]) == "sess-7"
    assert int(np.asarray(frame["max_iters"])) == 2


def test_unpack_measurements_absent_prefix_is_none():
    assert unpack_measurements({}, "meas") is None


def test_handle_request_solve_m_solves_in_process(meas):
    with SolveServer(max_batch=2, batch_window_s=0.0, quantum=64) as srv:
        reply = handle_request(srv, solve_m_frame(_req(meas)))
    assert int(np.asarray(reply["ok"])) == 1
    assert np.asarray(reply["T"]).shape[-1] == 4
    assert int(np.asarray(reply["iterations"])) == 2
    assert len(np.asarray(reply["cost_history"])) >= 1
    # The child's admission wait rides the reply — the out-of-process
    # fleet's autoscaler signal.
    assert float(np.asarray(reply["queue_wait_s"])) >= 0.0


def test_solve_m_without_payload_is_a_structured_error():
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        reply = handle_request(srv, {"op": _pack_str("solve_m"),
                                     "num_robots": np.int32(2)})
    assert int(np.asarray(reply["ok"])) == 0
    assert "meas" in _unpack_str(reply["error"])


# ---------------------------------------------------------------------------
# status / drain front-end ops
# ---------------------------------------------------------------------------

def test_status_op_returns_replica_snapshot_over_tcp():
    import json

    from dpgo_tpu.comms.transport import TcpTransport, connect_tcp

    with SolveServer(max_batch=2, batch_window_s=0.0,
                     replica_id="p7") as srv:
        with ServeFrontend(srv) as fe:
            tr = TcpTransport(connect_tcp("127.0.0.1", fe.port),
                              src="test-client")
            try:
                tr.send({"op": _pack_str("status")})
                reply = tr.recv(timeout=10)
            finally:
                tr.close()
    assert int(np.asarray(reply["ok"])) == 1
    st = json.loads(_unpack_str(reply["status"]))
    assert st["accepting"] is True
    assert st["replica"]["replica_id"] == "p7"


def test_status_reply_wire_is_byte_identical_with_telemetry_off():
    """DPG005 symmetry (ISSUE 20): telemetry off = no clock stamp on the
    heartbeat wire in either direction."""
    assert obs.get_run() is None
    with SolveServer(max_batch=2, batch_window_s=0.0,
                     replica_id="r0") as srv:
        reply = handle_request(srv, {"op": _pack_str("status")})
    assert int(np.asarray(reply["ok"])) == 1
    assert "_ts" not in reply


def test_status_poll_pairs_clocks_with_telemetry_on(tmp_path):
    """Satellite (a)/(d) groundwork: a stamped status poll is popped and
    recorded as the forward clock_sample, and the reply carries the
    replica's own stamp — the reverse leg the parent pairs on."""
    import json as _json

    from dpgo_tpu.comms.protocol import (ORIGIN_FLEET_PARENT, attach_clock,
                                         pop_clock, proc_replica_actor)

    with obs.run_scope(str(tmp_path / "child")):
        with SolveServer(max_batch=2, batch_window_s=0.0,
                         replica_id="r3") as srv:
            frame = {"op": _pack_str("status")}
            attach_clock(frame, ORIGIN_FLEET_PARENT)
            reply = handle_request(srv, frame)
    assert int(np.asarray(reply["ok"])) == 1
    ts = pop_clock(reply)
    assert ts is not None and ts[0] == proc_replica_actor("r3")
    with open(tmp_path / "child" / "events.jsonl") as fh:
        evs = [_json.loads(ln) for ln in fh if ln.strip()]
    (cs,) = [e for e in evs if e["event"] == "clock_sample"]
    assert cs["src"] == ORIGIN_FLEET_PARENT
    assert cs["dst"] == proc_replica_actor("r3")
    assert cs["channel"] == "heartbeat" and cs["kind"] == "status_poll"


def test_manager_fleet_sidecar_serves_aggregated_statusz(tmp_path):
    """The manager's fleet-level sidecar (ISSUE 20): constructed only
    behind the run fence, it serves the per-replica reachability map
    over the live pool and closes leak-clean with the manager."""
    import urllib.request

    from dpgo_tpu.obs import fleetobs

    def make_server(rid):
        return SolveServer(max_batch=2, batch_window_s=0.0,
                           replica_id=rid)

    # Telemetry off: no sidecar object, no HTTP thread.
    mgr = ReplicaManager(make_server, min_replicas=1, metrics_port=0)
    try:
        mgr.start()
        assert mgr.sidecar is None
    finally:
        mgr.close()

    with obs.run_scope(str(tmp_path / "mgr")):
        mgr = ReplicaManager(make_server, min_replicas=2, metrics_port=0)
        try:
            mgr.start()
            assert isinstance(mgr.sidecar, fleetobs.FleetSidecar)
            url = f"http://{mgr.sidecar.host}:{mgr.sidecar.port}/statusz"
            with urllib.request.urlopen(url, timeout=10) as resp:
                st = _read_json_body(resp)
            assert set(st["replicas"]) == {"r0", "r1"}
            assert all(e["reachable"] for e in st["replicas"].values())
            assert st["fleet"]["pool"] == ["r0", "r1"]
        finally:
            mgr.close()
        assert mgr.sidecar is None


def _read_json_body(resp):
    import json as _json

    return _json.loads(resp.read().decode())


def test_drain_op_evacuates_and_finishes_waiters(meas):
    """The drain op must reply to every blocked in-flight RPC with the
    structured closed shed (reroute me), not leave handler threads
    hanging on tickets nobody will finish."""
    # A wide batch window parks the ticket in admission un-dispatched.
    with SolveServer(max_batch=2, batch_window_s=60.0) as srv:
        parked = srv.submit(_req(meas))
        reply = handle_request(srv, {"op": _pack_str("drain")})
        assert int(np.asarray(reply["ok"])) == 1
        assert int(np.asarray(reply["evacuated"])) == 1
        with pytest.raises(OverCapacityError, match="evacuated") as ei:
            parked.result(timeout=10)
        assert ei.value.reason == "closed"


# ---------------------------------------------------------------------------
# Real child processes (slow)
# ---------------------------------------------------------------------------

def test_proc_server_lifecycle_and_sigkill_mid_flight(meas, aot_root):
    """One spawn, the whole surface: boot-to-accepting, a solve over the
    real TCP front-end, the local admission mirror, and a mid-flight
    ``kill -9`` surfacing as the structured death the router reroutes."""
    srv = ProcServer(replica_id="p0", max_batch=2, batch_window_s=0.0,
                     aot_cache_dir=aot_root)
    try:
        st = srv.status()
        assert st["accepting"] is True and st["out_of_process"] is True
        assert st["child_alive"] is True and st["child_pid"] != os.getpid()

        # The admission mirror sheds synchronously, preserving the
        # router's rendezvous fall-through.
        srv.max_queue, saved = 0, srv.max_queue
        with pytest.raises(OverCapacityError) as ei:
            srv.submit(_req(meas))
        assert ei.value.reason == "queue"
        srv.max_queue = saved

        t = srv.submit(_req(meas))
        res = t.result(timeout=600)
        assert res.iterations == 2 and res.terminated_by == "max_iters"
        assert t.queue_wait_s is not None and t.queue_wait_s >= 0.0

        # SIGKILL with a solve in flight: the pump's connection dies and
        # the ticket finishes with a reroutable death error.  (A big
        # iteration budget — the AOT-warm per-round cost is tiny, and
        # the kill must land mid-solve, not after.)
        doomed = srv.submit(_req(meas, iters=20000, eval_every=1))
        srv.kill()
        with pytest.raises(RuntimeError) as ei:
            doomed.result(timeout=60)
        assert _is_replica_death(ei.value)

        st = srv.status()
        assert st["accepting"] is False and st["child_alive"] is False
        with pytest.raises(OverCapacityError) as ei:
            srv.submit(_req(meas))
        assert ei.value.reason == "closed"
    finally:
        srv.close()


def test_proc_server_drain_evacuates_for_migration(meas, aot_root,
                                                  tmp_path):
    """Live-migration drain against a real child: the in-flight solve
    leaves a boundary snapshot in the SHARED session store, drain hands
    the unanswered local ticket back, and the child-side RPC finishes
    with the closed shed."""
    sess_root = str(tmp_path / "sessions")
    srv = ProcServer(replica_id="p1", max_batch=2, batch_window_s=0.0,
                     aot_cache_dir=aot_root, session_store=sess_root,
                     session_every=1, resume_sessions=True)
    try:
        t = srv.submit(_req(meas, sid="mig-1", iters=20000, eval_every=1))
        deadline = time.monotonic() + 120
        sdir = os.path.join(sess_root, "mig-1")
        while time.monotonic() < deadline:
            if os.path.isdir(sdir) and any(
                    f.startswith("snap-") for f in os.listdir(sdir)):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("no boundary snapshot before drain")

        evacuated = srv.drain()
        assert evacuated == [t]
        with pytest.raises(OverCapacityError) as ei:
            t.result(timeout=60)
        assert ei.value.reason == "closed"
        st = srv.status()
        assert st["draining"] is True and st["accepting"] is False
        with pytest.raises(OverCapacityError):
            srv.submit(_req(meas))
    finally:
        srv.close()


def test_proc_fleet_kill9_loses_zero_sessions(meas, aot_root, tmp_path):
    """The fleet acceptance across REAL process boundaries: a
    2-process fleet takes long-running sessions, one replica is
    SIGKILLed mid-solve, and every session completes — migrated via the
    shared snapshot store — while the manager respawns a fresh process."""
    sess_root = str(tmp_path / "sessions")

    def make_server(rid):
        return ProcServer(replica_id=rid, max_batch=2,
                          batch_window_s=0.02, aot_cache_dir=aot_root,
                          session_store=sess_root, session_every=1,
                          resume_sessions=True)

    mgr = ReplicaManager(make_server, min_replicas=2,
                         monitor_interval_s=0.2)
    router = FleetRouter(mgr)
    try:
        tickets = {f"soak-{i}": router.submit(
            _req(meas, sid=f"soak-{i}", iters=600, eval_every=1))
            for i in range(3)}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            import glob
            if glob.glob(os.path.join(sess_root, "*", "snap-*.npz")):
                break
            time.sleep(0.1)
        time.sleep(1.0)
        victim = mgr.replicas()[0].replica_id
        mgr.kill_replica(victim)
        # Zero lost: every session completes its budget — a migrated
        # one reports only its post-resume rounds, so the gate is
        # completion, not a raw iteration count.
        for sid, t in tickets.items():
            res = t.result(timeout=900)
            assert res.terminated_by == "max_iters", sid
        st = mgr.status()
        assert st["respawns"] >= 1
        assert router.migrations >= 1
    finally:
        router.close()
