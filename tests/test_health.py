"""Numerical-health layer (``dpgo_tpu.obs.health``): anomaly detectors,
abort/callback policy, the instrumented solver path, per-agent sentinels,
and the fleet-wide health gossip riding the comms bus."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.obs.events import read_events
from dpgo_tpu.obs.health import (HealthConfig, HealthMonitor,
                                 SolverHealthError, monitor_for)


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _events(d):
    return read_events(os.path.join(d, "events.jsonl"))


# ---------------------------------------------------------------------------
# Detector unit tests
# ---------------------------------------------------------------------------

def test_monitor_for_fence_and_reuse(tmp_path):
    assert monitor_for() is None  # telemetry off -> no detector exists
    with obs.run_scope(str(tmp_path / "r")) as run:
        mon = monitor_for()
        assert isinstance(mon, HealthMonitor)
        assert monitor_for() is mon  # cached on the run
        mon2 = monitor_for(run, HealthConfig(stall_window=3))
        assert mon2 is not mon and monitor_for() is mon2  # config replaces


def test_nan_sentinel_fires_critical_anomaly(tmp_path):
    d = str(tmp_path / "r")
    with obs.run_scope(d) as run:
        mon = HealthMonitor(run)
        fired = mon.observe_solver(4, float("nan"), 1.0)
        assert [a["kind"] for a in fired] == ["non_finite"]
        assert fired[0]["severity"] == "critical"
        assert fired[0]["signals"] == ["cost"]
        # Per-agent rel-change NaN is attributed to the agent.
        fired = mon.observe_solver(6, 1.0, 1.0,
                                   rel_change=np.array([0.1, np.nan]))
        assert fired[0]["agents"] == [1]
    evs = [e for e in _events(d) if e["event"] == "anomaly"]
    assert len(evs) == 2
    assert all(e["phase"] == "health" for e in evs)
    assert evs[0]["iteration"] == 4
    # The counter metric tallied by kind/severity.
    snap = run.registry.snapshot()
    (s,) = snap["anomalies_total"]["series"]
    assert s["value"] == 2.0


def test_cost_spike_is_stage_scoped(tmp_path):
    """Non-monotone cost flags within a GNC stage; a mu transition resets
    the baseline so the legitimate GNC cost jump does not flag."""
    with obs.run_scope(str(tmp_path / "r")) as run:
        mon = HealthMonitor(run, HealthConfig(cost_spike_rtol=0.25))
        assert mon.observe_solver(1, 100.0, 1.0, mu=1e-4) == []
        assert mon.observe_solver(2, 90.0, 1.0, mu=1e-4) == []
        # Within-stage spike beyond 25%: flags.
        (a,) = mon.observe_solver(3, 140.0, 1.0, mu=1e-4)
        assert a["kind"] == "cost_spike" and a["severity"] == "warning"
        # mu annealed -> new stage: a bigger cost is NOT an anomaly.
        assert mon.observe_solver(4, 500.0, 1.0, mu=1.4e-4) == []
        assert mon.anomalies[-1]["stage"] == 0  # spike was in stage 0


def test_grad_explosion_and_stall(tmp_path):
    with obs.run_scope(str(tmp_path / "r")) as run:
        mon = HealthMonitor(run, HealthConfig(grad_explosion_factor=100.0,
                                              stall_window=3,
                                              stall_rtol=1e-3))
        assert mon.observe_solver(1, 10.0, 1.0) == []
        (a,) = mon.observe_solver(2, 9.0, 150.0)
        assert a["kind"] == "grad_explosion" and a["severity"] == "critical"
        # Stall: three evals with < 0.1% improvement, fired exactly once.
        assert mon.observe_solver(3, 9.0, 1.0) == []
        fired = mon.observe_solver(4, 8.9999, 1.0)
        assert [x["kind"] for x in fired] == ["stall"]
        assert mon.observe_solver(5, 8.9998, 1.0) == []  # once per stage


def test_inlier_collapse(tmp_path):
    with obs.run_scope(str(tmp_path / "r")) as run:
        mon = HealthMonitor(run, HealthConfig(inlier_collapse_drop=0.4))
        assert mon.observe_solver(1, 1.0, 1.0, inlier_frac=0.9) == []
        assert mon.observe_solver(2, 1.0, 1.0, inlier_frac=0.8) == []
        (a,) = mon.observe_solver(3, 1.0, 1.0, inlier_frac=0.3)
        assert a["kind"] == "inlier_collapse"
        assert a["running_max"] == pytest.approx(0.9)


def test_cert_refuse_loop(tmp_path):
    with obs.run_scope(str(tmp_path / "r")) as run:
        mon = HealthMonitor(run, HealthConfig(cert_refuse_streak=2))
        assert mon.observe_certificate(False, decidable=False) == []
        (a,) = mon.observe_certificate(False, decidable=False)
        assert a["kind"] == "cert_refuse_loop"
        assert a["refusals"] == 2
        # Streak flagged once; a decidable verdict resets it.
        assert mon.observe_certificate(False, decidable=False) == []
        assert mon.observe_certificate(True, decidable=True) == []
        assert mon.observe_certificate(False, decidable=False) == []
        (b,) = mon.observe_certificate(False, decidable=False)
        assert b["kind"] == "cert_refuse_loop"


def test_callback_and_abort_policy(tmp_path):
    with obs.run_scope(str(tmp_path / "r")) as run:
        seen = []
        mon = HealthMonitor(run, HealthConfig(abort_on=frozenset({"critical"})))
        mon.on_anomaly(seen.append)
        with pytest.raises(SolverHealthError) as ei:
            mon.observe_solver(7, float("inf"), 1.0)
        assert ei.value.anomalies[0]["kind"] == "non_finite"
        assert seen and seen[0]["kind"] == "non_finite"
        # Kind-targeted abort.
        mon2 = HealthMonitor(run, HealthConfig(
            cost_spike_rtol=0.1, abort_on=frozenset({"cost_spike"})))
        mon2.observe_solver(1, 10.0, 1.0)
        with pytest.raises(SolverHealthError):
            mon2.observe_solver(2, 20.0, 1.0)


def test_anomaly_triggers_recorder_dump(tmp_path):
    """The dump policy: a critical anomaly dumps an attached recorder's
    black box (first dump wins)."""
    from dpgo_tpu.obs.recorder import FlightRecorder

    d = str(tmp_path / "r")
    with obs.run_scope(d) as run:
        rec = FlightRecorder.attach(run)
        rec.record_eval(2, {"cost": 1.0, "grad_norm": 0.5})
        mon = HealthMonitor(run)
        mon.observe_solver(4, float("nan"), 1.0)
        assert rec._dumped == "anomaly:non_finite"
        assert os.path.exists(os.path.join(d, "blackbox.npz"))
    evs = _events(d)
    (dump,) = [e for e in evs if e["event"] == "blackbox_dump"]
    assert dump["reason"] == "anomaly:non_finite"


# ---------------------------------------------------------------------------
# Instrumented solver path
# ---------------------------------------------------------------------------

def _tiny_problem(n=40, num_lc=20, seed=0):
    from dpgo_tpu.utils.synthetic import make_measurements

    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=num_lc, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def test_healthy_solve_emits_no_anomalies(tmp_path):
    from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
    from dpgo_tpu.models import rbcd

    d = str(tmp_path / "run")
    with obs.run_scope(d):
        rbcd.solve_rbcd(
            _tiny_problem(), 2,
            params=AgentParams(
                d=3, r=5, num_robots=2,
                robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
                robust_opt_inner_iters=4),
            max_iters=8, eval_every=2, grad_norm_tol=1e-9,
            dtype=jnp.float64)
    assert [e for e in _events(d) if e["event"] == "anomaly"] == []


def test_certify_refuse_loop_reaches_health(tmp_path):
    """certify_solution with f64 verification disabled on an undecidable
    problem feeds the REFUSE-loop detector."""
    from dpgo_tpu.models import certify

    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        monitor_for(run, HealthConfig(cert_refuse_streak=2))
        mon = monitor_for(run)
        # Drive the verdict timeline directly (an undecidable eigensolve
        # needs a large ill-conditioned graph; the wiring is what's under
        # test — certify_solution calls observe_certificate, asserted in
        # test_obs-style integration below).
        mon.observe_certificate(False, decidable=False,
                                source="certify_solution")
        mon.observe_certificate(False, decidable=False,
                                source="certify_solution")
    evs = [e for e in _events(d) if e["event"] == "anomaly"]
    assert [e["kind"] for e in evs] == ["cert_refuse_loop"]


def test_certify_solution_observes_verdict(tmp_path, monkeypatch):
    """The real certify_solution path lands on the monitor's verdict
    stream."""
    from dpgo_tpu.models import certify, local_pgo
    from dpgo_tpu.types import edge_set_from_measurements

    meas = _tiny_problem(n=20, num_lc=8)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    res = local_pgo.solve_local(meas, rank=5)
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        certify.certify_solution(res.X, edges)
        mon = monitor_for(run)
        # One decidable verdict observed -> refusal streak is clear.
        assert mon._cert_refusals == 0
        assert mon.anomalies == []


# ---------------------------------------------------------------------------
# Deployment plane: per-agent sentinels + bus gossip
# ---------------------------------------------------------------------------

def test_agent_nan_neighbor_frame_anomaly(tmp_path):
    from test_agent import exchange, make_agents

    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        agents, _part, _ = make_agents(2, n=12, num_lc=6)
        exchange(agents)
        for ag in agents:
            ag.iterate()
        # Poison one neighbor frame of robot 0 with NaN values.
        nbr = agents[0].get_neighbors()[0]
        poses = agents[0].get_neighbor_public_poses(nbr)
        vals = np.full((len(poses), agents[0].r, agents[0].d + 1), np.nan)
        agents[0].update_neighbor_poses_packed(
            nbr, np.full(len(poses), nbr), np.asarray(poses), vals)
        assert agents[0].health_counters() == (1, 2)  # one critical
        assert agents[1].health_counters() == (0, 0)
        snap = run.registry.snapshot()
    evs = [e for e in _events(d) if e["event"] == "anomaly"]
    (a,) = evs
    assert a["kind"] == "non_finite_neighbor_frame"
    assert a["robot"] == 0 and a["neighbor"] == nbr
    assert a["severity"] == "critical"
    (s,) = [s for s in snap["anomalies_total"]["series"]
            if ("robot", "0") in s["labels"].items()]
    assert s["value"] == 1.0


def test_anomaly_counters_ride_the_bus(tmp_path):
    """pack_agent_frame ships the counters; the hub surfaces grown counts
    as peer_anomaly events; a peer's ingest records the gossip gauge."""
    from test_agent import exchange, make_agents
    from dpgo_tpu.comms.bus import (apply_peer_frame, loopback_fleet,
                                    pack_agent_frame)

    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        agents, _part, _ = make_agents(2, n=12, num_lc=6)
        exchange(agents)
        agents[0]._obs_anomaly("non_finite_rel_change", "critical")
        frame = pack_agent_frame(agents[0])
        assert list(np.asarray(frame["anom"])) == [1, 2]
        # Healthy agent ships no anom entry at all.
        assert "anom" not in pack_agent_frame(agents[1])

        bus, clients = loopback_fleet(1)
        try:
            clients[0].publish(frame)
            merged = bus.round()
            assert "r0|anom" in merged
        finally:
            bus.close()
            for c in clients.values():
                c.close()

        # Receiver-side ingest: the anom entry is popped (never parsed as
        # poses/weights) and lands on the gossip gauge.
        pf = {k.split("|", 1)[1]: v for k, v in merged.items()
              if k.startswith("r0|")}
        apply_peer_frame(agents[1], 0, pf)
        assert "anom" not in pf
        snap = run.registry.snapshot()
    evs = _events(d)
    (pa,) = [e for e in evs if e["event"] == "peer_anomaly"]
    assert pa["peer"] == 0 and pa["count"] == 1
    assert pa["severity"] == "critical"
    gauge = snap["peer_anomalies_seen"]["series"]
    assert any(s["value"] == 1.0 for s in gauge)


def test_health_layer_is_zero_overhead_when_off(monkeypatch):
    """Telemetry off: no HealthMonitor constructed, no recorder buffers
    allocated, no anomaly scan over received frames."""
    from test_agent import exchange, make_agents
    from dpgo_tpu.obs import health as health_mod
    from dpgo_tpu.obs import recorder as recorder_mod
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.models import rbcd

    def boom(*a, **kw):
        raise AssertionError("health/recorder path taken while disabled")

    monkeypatch.setattr(health_mod.HealthMonitor, "__init__", boom)
    monkeypatch.setattr(recorder_mod.FlightRecorder, "__init__", boom)

    assert obs.get_run() is None
    res = rbcd.solve_rbcd(_tiny_problem(), 2,
                          params=AgentParams(d=3, r=5, num_robots=2),
                          max_iters=4, eval_every=2, grad_norm_tol=1e-9,
                          dtype=jnp.float64)
    assert res.iterations > 0

    agents, _part, _ = make_agents(2, n=10, num_lc=4)
    exchange(agents)
    for ag in agents:
        ag.iterate()
    assert all(ag.health_counters() == (0, 0) for ag in agents)
