"""Tests for chordal / odometry initialization (reference DPGO_utils.cpp:377-476)."""

import jax.numpy as jnp
import numpy as np

from dpgo_tpu.ops import chordal
from dpgo_tpu.types import edge_set_from_measurements
from synthetic import make_measurements, trajectory_error


def test_odometry_init_recovers_chain(rng):
    meas, (Rs, ts) = make_measurements(rng, n=20, d=3, num_lc=0)
    T = chordal.odometry_initialization(jnp.asarray(meas.R), jnp.asarray(meas.t))
    assert trajectory_error(T, Rs, ts) < 1e-10


def test_chordal_init_exact_on_noiseless_graph(rng):
    # With exact measurements the chordal relaxation is tight: recovery up to
    # the anchored gauge (analog of testTriangleGraph's 1e-4 golden check,
    # but property-based).
    for d in (2, 3):
        meas, (Rs, ts) = make_measurements(rng, n=15, d=d, num_lc=8)
        edges = edge_set_from_measurements(meas, dtype=jnp.float64)
        T = np.asarray(chordal.chordal_initialization(edges, meas.num_poses))
        assert trajectory_error(T, Rs, ts) < 1e-6, f"d={d}"


def test_chordal_init_noisy_graph_close(rng):
    meas, (Rs, ts) = make_measurements(rng, n=30, d=3, num_lc=15,
                                       rot_noise=0.02, trans_noise=0.02)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    T = np.asarray(chordal.chordal_initialization(edges, meas.num_poses))
    # Rotations must stay valid and the trajectory near truth.
    R = T[..., :3]
    eye = np.broadcast_to(np.eye(3), R.shape)
    assert np.allclose(np.swapaxes(R, -1, -2) @ R, eye, atol=1e-8)
    assert trajectory_error(T, Rs, ts) < 0.5


def test_chordal_on_real_dataset(data_dir):
    # smallGrid3D end-to-end: init must produce valid rotations and a
    # drastically lower cost than a random start.
    from dpgo_tpu.utils.g2o import read_g2o
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.models.local_pgo import lift

    meas = read_g2o(f"{data_dir}/smallGrid3D.g2o")
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    T = chordal.chordal_initialization(edges, meas.num_poses)
    X = lift(T, jnp.eye(3, dtype=jnp.float64))
    f_chordal = float(quadratic.cost(X, edges))

    rng = np.random.default_rng(0)
    Xr = jnp.asarray(rng.standard_normal(np.asarray(X).shape))
    f_rand = float(quadratic.cost(Xr, edges))
    assert f_chordal < 0.01 * f_rand
    R = np.asarray(T[..., :3])
    eye = np.broadcast_to(np.eye(3), R.shape)
    assert np.allclose(np.swapaxes(R, -1, -2) @ R, eye, atol=1e-8)
