"""Tests for (robust) averaging, mirroring reference tests/testUtils.cpp:72-180."""

import jax.numpy as jnp
import numpy as np

from dpgo_tpu.ops import averaging
from dpgo_tpu.utils import lie


def random_rotation(rng, d=3):
    return np.asarray(lie.project_to_rotation(jnp.asarray(rng.standard_normal((d, d)))))


def perturbed(R, rng, angle):
    # Rotate R by `angle` radians about a random axis.
    axis = rng.standard_normal(3)
    axis /= np.linalg.norm(axis)
    q = np.concatenate([np.sin(angle / 2) * axis, [np.cos(angle / 2)]])
    return lie.quat_to_rotation(q) @ R


def test_single_translation_averaging(rng):
    ts = jnp.asarray(rng.standard_normal((10, 3)))
    tau = jnp.asarray(rng.uniform(0.5, 2.0, 10))
    t = averaging.single_translation_averaging(ts, tau)
    expected = (np.asarray(tau)[:, None] * np.asarray(ts)).sum(0) / np.asarray(tau).sum()
    assert np.allclose(t, expected, atol=1e-12)


def test_single_rotation_averaging_trivial(rng):
    # One measurement: average equals the measurement (testUtils.cpp:74-88).
    R = random_rotation(rng)
    out = averaging.single_rotation_averaging(jnp.asarray(R[None]))
    assert np.allclose(out, R, atol=1e-10)


def test_single_rotation_averaging_noisy(rng):
    R = random_rotation(rng)
    Rs = np.stack([perturbed(R, rng, rng.normal(0.0, 0.05)) for _ in range(50)])
    out = np.asarray(averaging.single_rotation_averaging(jnp.asarray(Rs)))
    # Mean should be close to truth (chordal error well below noise).
    assert np.linalg.norm(out - R) < 0.1


def test_robust_rotation_averaging_trivial(rng):
    # Single-measurement robust case (testUtils.cpp:90-103).
    R = random_rotation(rng)
    res = averaging.robust_single_rotation_averaging(jnp.asarray(R[None]))
    assert np.allclose(res.R, R, atol=1e-8)
    assert res.inlier_mask.tolist() == [True]


def test_robust_rotation_averaging_outliers(rng):
    # 10 inliers + 40 outliers; exact inlier-set recovery (testUtils.cpp:105-139).
    R = random_rotation(rng)
    inliers = [perturbed(R, rng, rng.normal(0.0, 0.01)) for _ in range(10)]
    outliers = [random_rotation(rng) for _ in range(40)]
    Rs = jnp.asarray(np.stack(inliers + outliers))
    thresh = lie.angular_to_chordal_so3(0.5)  # generous inlier threshold
    res = averaging.robust_single_rotation_averaging(Rs, error_threshold=thresh)
    mask = np.asarray(res.inlier_mask)
    assert mask[:10].all(), f"lost inliers: {mask[:10]}"
    assert not mask[10:].any(), "outliers accepted"
    assert np.linalg.norm(np.asarray(res.R) - R) < 0.05


def test_robust_pose_averaging_outliers(rng):
    # testUtils.cpp:141-180: pose averaging with outliers.
    R = random_rotation(rng)
    t = rng.standard_normal(3)
    kR, kt = 10, 40
    inl_R = [perturbed(R, rng, rng.normal(0.0, 0.005)) for _ in range(kR)]
    inl_t = [t + 0.01 * rng.standard_normal(3) for _ in range(kR)]
    out_R = [random_rotation(rng) for _ in range(kt)]
    out_t = [t + 5.0 * rng.standard_normal(3) for _ in range(kt)]
    Rs = jnp.asarray(np.stack(inl_R + out_R))
    ts = jnp.asarray(np.stack(inl_t + out_t))
    res = averaging.robust_single_pose_averaging(Rs, ts, error_threshold=1.0)
    mask = np.asarray(res.inlier_mask)
    assert mask[:kR].all()
    assert not mask[kR:].any()
    assert np.linalg.norm(np.asarray(res.R) - R) < 0.05
    assert np.linalg.norm(np.asarray(res.t) - t) < 0.05


def test_robust_averaging_float32(rng):
    """Regression: in float32 the inlier test ``w > 1 - 1e-8`` folds to
    ``w > 1`` (1e-8 is below the f32 spacing at 1.0) and every weight —
    including exact 1s — stopped counting as an inlier, so distributed
    initialization found 0 inliers at TPU deployment precision.  The
    tolerance is now dtype-aware."""
    R = random_rotation(rng)
    # Exact agreement, f32: all inliers, loop must terminate via skip path.
    Rs = jnp.asarray(np.stack([R] * 4), jnp.float32)
    res = averaging.robust_single_rotation_averaging(Rs)
    assert res.weights.dtype == jnp.float32
    assert res.inlier_mask.tolist() == [True] * 4

    # Inliers + outliers, f32: exact inlier-set recovery still works.
    inliers = [perturbed(R, rng, rng.normal(0.0, 0.01)) for _ in range(8)]
    outliers = [random_rotation(rng) for _ in range(12)]
    Rs = jnp.asarray(np.stack(inliers + outliers), jnp.float32)
    thresh = lie.angular_to_chordal_so3(0.5)
    res = averaging.robust_single_rotation_averaging(Rs, error_threshold=thresh)
    mask = np.asarray(res.inlier_mask)
    assert mask[:8].all(), f"lost inliers: {mask[:8]}"
    assert not mask[8:].any(), "outliers accepted"

    ts = jnp.asarray(rng.standard_normal((4, 3)), jnp.float32)
    res = averaging.robust_single_pose_averaging(
        jnp.asarray(np.stack([R] * 4), jnp.float32),
        jnp.broadcast_to(ts[0], (4, 3)))
    assert res.inlier_mask.tolist() == [True] * 4


def test_degenerate_zero_weight_translation_is_zero_not_nan(rng):
    """All-zero weights (GNC rejected every measurement): the documented
    contract is a 0 vector, never NaN — callers detect the failure via
    the empty inlier set, not the value."""
    ts = jnp.asarray(rng.standard_normal((5, 3)))
    t = averaging.single_translation_averaging(ts, tau=jnp.zeros(5))
    assert np.array_equal(np.asarray(t), np.zeros(3))
    # Zero via the mask path too.
    t2 = averaging.single_translation_averaging(
        ts, tau=jnp.ones(5), mask=jnp.zeros(5))
    assert np.array_equal(np.asarray(t2), np.zeros(3))
    # And in f32 (the TPU deployment precision).
    t3 = averaging.single_translation_averaging(
        jnp.asarray(ts, jnp.float32), tau=jnp.zeros(5, jnp.float32))
    assert np.isfinite(np.asarray(t3)).all()


def test_degenerate_zero_weight_rotation_is_finite(rng):
    """Zero-weight rotation averaging projects the zero matrix: an
    arbitrary but FINITE, deterministic rotation — never NaN."""
    Rs = jnp.asarray(np.stack([random_rotation(rng) for _ in range(4)]))
    R = np.asarray(averaging.single_rotation_averaging(
        Rs, kappa=jnp.zeros(4)))
    assert np.isfinite(R).all()
    # A valid member of O(d) (orthonormal rows).
    assert np.allclose(R @ R.T, np.eye(3), atol=1e-6)
    R2 = np.asarray(averaging.single_rotation_averaging(
        Rs, kappa=jnp.zeros(4)))
    assert np.array_equal(R, R2)  # deterministic

    Rp, tp = averaging.single_pose_averaging(
        Rs, jnp.asarray(rng.standard_normal((4, 3))),
        kappa=jnp.zeros(4), tau=jnp.zeros(4))
    assert np.isfinite(np.asarray(Rp)).all()
    assert np.array_equal(np.asarray(tp), np.zeros(3))


def test_all_outlier_robust_averaging_reports_empty_inlier_set(rng):
    """The caller-facing failure signal for degenerate robust averaging:
    mutually-inconsistent measurements under a tight threshold finish
    with finite outputs and an EMPTY inlier mask (the abort-and-retry
    trigger of distributed initialization, ``PGOAgent.cpp:396-400``)."""
    rots = [random_rotation(rng) for _ in range(6)]
    # Ensure genuine mutual disagreement (random rotations are far apart
    # w.h.p.; the fixed seed makes this deterministic).
    Rs = jnp.asarray(np.stack(rots))
    thresh = lie.angular_to_chordal_so3(1e-4)  # nothing can agree
    res = averaging.robust_single_rotation_averaging(
        Rs, error_threshold=thresh)
    assert not np.asarray(res.inlier_mask).any()
    assert np.isfinite(np.asarray(res.R)).all()
    assert np.isfinite(np.asarray(res.weights)).all()

    ts = jnp.asarray(5.0 * rng.standard_normal((6, 3)))
    resp = averaging.robust_single_pose_averaging(
        Rs, ts, error_threshold=1e-4)
    assert not np.asarray(resp.inlier_mask).any()
    assert np.isfinite(np.asarray(resp.R)).all()
    assert np.isfinite(np.asarray(resp.t)).all()


def test_robust_averaging_is_jittable(rng):
    import jax

    R = random_rotation(rng)
    Rs = jnp.asarray(np.stack([perturbed(R, rng, 0.01) for _ in range(5)]))
    fn = jax.jit(
        lambda Rs: averaging.robust_single_rotation_averaging(Rs, error_threshold=0.5)
    )
    res = fn(Rs)
    assert np.asarray(res.inlier_mask).all()
