"""Stitched-winding construction (utils.synthetic.make_stitched_winding):
the scalable certifiably-suboptimal dataset behind the at-scale escape
demo (experiments/staircase_escape_100k.py, VERDICT r4 item 2).
"""

import numpy as np
import jax.numpy as jnp

from dpgo_tpu.models import certify, rbcd
from dpgo_tpu.parallel import certify as dcert
from dpgo_tpu.parallel.sharded import make_mesh
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import partition_contiguous
from dpgo_tpu.utils.synthetic import make_stitched_winding


def test_stitched_winding_is_critical_and_suboptimal():
    """The wound configuration must be (a) first-order critical, (b) a
    strictly suboptimal cost, (c) certificate-FAIL with a genuinely
    negative lambda_min at the weight-scale tolerance."""
    meas, Xw = make_stitched_winding(4, 16)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    from dpgo_tpu.ops import manifold, quadratic

    X = jnp.asarray(Xw, jnp.float64)
    g = manifold.rgrad(X, quadratic.egrad(X, edges))
    assert float(manifold.norm(g)) < 1e-10      # exactly critical
    f = float(quadratic.cost(X, edges))
    assert f > 1.0                              # global optimum costs 0
    cert = certify.certify_solution(X, edges)
    assert not cert.certified
    assert cert.lambda_min < -cert.tol * 10     # decisively negative


def test_stitched_winding_escape_through_sharded_staircase():
    """Medium-scale end-to-end: 8 stitched cycles on an 8-agent mesh go
    descent -> FAIL at r=2 -> escape -> certify at r>=3 near cost 0."""
    meas, Xw = make_stitched_winding(8, 16)
    part = partition_contiguous(meas, 8)
    graph, meta = rbcd.build_graph(part, 2, jnp.float64)
    Xa0 = rbcd.scatter_to_agents(jnp.asarray(Xw, jnp.float64), graph)
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 8, mesh=make_mesh(8), r_min=2, r_max=6,
        rounds_per_rank=1200, dtype=jnp.float64, X0=np.asarray(Xa0))
    assert cert.certified
    assert rank >= 3
    costs = [f for _, f, *_ in hist]
    assert costs[0] > 1.0       # stayed wound through the r=2 descent
    assert costs[-1] < 1e-2     # unwound after the escape


def test_f32_staircase_polishes_before_certifying():
    """The f32 staircase path must run the stationarity POLISH before
    each certificate (round 5: lambda_min(S) at the f32 descent floor
    reads -O(||rgrad||) even at the optimum, so an unpolished f32
    certificate falsely fails).  Small instance on the CPU mesh in f32,
    end to end: escape -> unwind -> polished -> certified."""
    meas, Xw = make_stitched_winding(6, 12)
    part = partition_contiguous(meas, 6)
    graph, meta = rbcd.build_graph(part, 2, jnp.float32)
    Xa0 = rbcd.scatter_to_agents(jnp.asarray(Xw, jnp.float32), graph)
    T, Xa, rank, cert, hist = dcert.solve_staircase_sharded(
        meas, 6, mesh=make_mesh(6), r_min=2, r_max=6,
        rounds_per_rank=900, dtype=jnp.float32, X0=np.asarray(Xa0),
        accel=True)
    assert cert.certified
    assert rank >= 3
    costs = [f for _, f, *_ in hist]
    assert costs[0] > 1.0
    assert costs[-1] < 1e-2
