"""Topology planner: native C++ and Python backends must be bit-identical
(the native planner is the analog of the reference's C++ measurement
ingestion, ``PGOAgent::setPoseGraph`` / ``addSharedLoopClosure``)."""

import numpy as np
import pytest

from dpgo_tpu.utils import graph_plan
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements

NATIVE = graph_plan._graph_lib() is not None


@pytest.mark.skipif(not NATIVE, reason="native library unavailable")
@pytest.mark.parametrize("seed,n,A,lc", [(0, 48, 8, 20), (1, 100, 7, 40),
                                         (2, 30, 3, 12), (3, 20, 1, 5)])
def test_native_matches_python(rng, seed, n, A, lc):
    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=lc)
    part = partition_contiguous(meas, A)
    m = part.meas
    a = graph_plan.plan_native(m.r1, m.p1, m.r2, m.p2, A, part.n_max)
    b = graph_plan.plan_python(m.r1, m.p1, m.r2, m.p2, A, part.n_max)
    for f in a._fields:
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


@pytest.mark.skipif(not NATIVE, reason="native library unavailable")
def test_native_matches_python_on_dataset(data_dir):
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(f"{data_dir}/smallGrid3D.g2o")
    part = partition_contiguous(meas, 5)
    m = part.meas
    a = graph_plan.plan_native(m.r1, m.p1, m.r2, m.p2, 5, part.n_max)
    b = graph_plan.plan_python(m.r1, m.p1, m.r2, m.p2, 5, part.n_max)
    for f in a._fields:
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


@pytest.mark.parametrize("backend", ["native", "python"])
def test_planners_reject_bad_input(backend):
    """Both backends must fail identically on invalid indices (a silent
    wrong plan on one of them would make behavior depend on toolchain
    availability)."""
    if backend == "native" and not NATIVE:
        pytest.skip("native library unavailable")
    plan = getattr(graph_plan, f"plan_{backend}")
    r1 = np.array([0], np.int32)
    p1 = np.array([0], np.int64)
    r2 = np.array([5], np.int32)  # robot out of range for A=2
    p2 = np.array([0], np.int64)
    with pytest.raises(ValueError, match="out of range"):
        plan(r1, p1, r2, p2, 2, 4)
    with pytest.raises(ValueError, match="out of range"):
        plan(np.array([0], np.int32), np.array([9], np.int64),
             np.array([1], np.int32), np.array([0], np.int64), 2, 4)


def test_build_graph_planner_backends_agree(rng):
    """build_graph(planner='python') and the auto backend produce identical
    graphs end to end (payload scatter included)."""
    import jax
    import jax.numpy as jnp

    from dpgo_tpu.models import rbcd

    meas, _ = make_measurements(rng, n=40, d=3, num_lc=16, outlier_lc=3,
                                rot_noise=0.01, trans_noise=0.01)
    part = partition_contiguous(meas, 5)
    g1, m1 = rbcd.build_graph(part, 5, jnp.float64, planner="python")
    g2, m2 = rbcd.build_graph(part, 5, jnp.float64, planner="auto")
    assert m1 == m2
    for t1, t2 in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        assert np.array_equal(np.asarray(t1), np.asarray(t2))


def test_color_agents_valid_coloring():
    """Greedy coloring: adjacent agents never share a color; chain
    partitions 2-color; colors are compact [0, C)."""
    import numpy as np
    from dpgo_tpu.utils.graph_plan import color_agents

    # Chain adjacency: robot a neighbors a-1 and a+1 (contiguous-partition
    # odometry crossings) -> 2 colors.
    A, S = 6, 4
    nbr_robot = np.zeros((A, S), np.int32)
    nbr_mask = np.zeros((A, S))
    for a in range(A):
        s = 0
        for b in (a - 1, a + 1):
            if 0 <= b < A:
                nbr_robot[a, s] = b
                nbr_mask[a, s] = 1.0
                s += 1
    color, C = color_agents(nbr_robot, nbr_mask, A)
    assert C == 2
    for a in range(A):
        for sth in range(S):
            if nbr_mask[a, sth] > 0:
                assert color[a] != color[nbr_robot[a, sth]]
    assert set(color) == set(range(C))


def test_color_agents_triangle():
    import numpy as np
    from dpgo_tpu.utils.graph_plan import color_agents

    nbr_robot = np.array([[1, 2], [0, 2], [0, 1]], np.int32)
    nbr_mask = np.ones((3, 2))
    color, C = color_agents(nbr_robot, nbr_mask, 3)
    assert C == 3
    assert sorted(color) == [0, 1, 2]
