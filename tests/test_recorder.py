"""Flight recorder (``dpgo_tpu.obs.recorder``): ring/snapshot bookkeeping,
black-box dumps, and the ACCEPTANCE scenario — a seeded NaN injection into
one agent's neighbor frame produces an anomaly event + ``blackbox.npz``,
and ``--replay`` reproduces the recorded trajectory from the last good
snapshot bit-for-bit on CPU."""

import json
import math
import os

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import (AgentParams, RobustCostParams, RobustCostType,
                             Schedule, SolverParams)
from dpgo_tpu.obs.events import read_events
from dpgo_tpu.obs.recorder import (FlightRecorder, decode_config,
                                   encode_config, inject_nan, load_blackbox,
                                   main as recorder_main, replay)


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _tiny_problem(n=40, num_lc=20, seed=0):
    from dpgo_tpu.utils.synthetic import make_measurements

    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=num_lc, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _params(**kw):
    return AgentParams(
        d=3, r=5, num_robots=2, rel_change_tol=1e-16,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        robust_opt_inner_iters=4, **kw)


def _run_recorded_solve(run, params, meas, max_iters=10, eval_every=2,
                        fault=None, crash_at=None, snapshot_every=1,
                        verdict_every=None):
    """Drive ``run_rbcd`` the way ``solve_rbcd`` does, with a segment
    wrapper that injects the canonical NaN fault (``inject_nan``) the
    first time the cumulative round count crosses ``fault['iteration']``
    — the recorded-input model of a fault injector corrupting one agent's
    neighbor frame (the poisoned block is exactly what neighbors consume
    on the next exchange)."""
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.utils.partition import partition_contiguous

    rec = FlightRecorder.attach(run, snapshot_every=snapshot_every)
    if fault is not None:
        rec.set_context(fault=fault)

    part = partition_contiguous(meas, params.num_robots)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64,
                                   sel_mode=rbcd.resolved_sel_mode(params))
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    step = lambda s, uw, rs: rbcd.rbcd_step(s, graph, meta, params,
                                            update_weights=uw, restart=rs)
    rounds = {"n": 0}
    applied = {"v": False}

    def seg(s, k, uw, rs):
        s = rbcd.rbcd_segment(s, graph, k, meta, params,
                              first_update_weights=uw, first_restart=rs)
        rounds["n"] += k
        if crash_at is not None and rounds["n"] >= crash_at:
            raise RuntimeError("synthetic driver crash")
        if fault is not None and not applied["v"] \
                and rounds["n"] >= fault["iteration"]:
            s = inject_nan(s, fault["agent"], fault["pose"])
            applied["v"] = True
        return s

    res = rbcd.run_rbcd(state, graph, meta, step, part, max_iters,
                        grad_norm_tol=1e-12, eval_every=eval_every,
                        dtype=jnp.float64, params=params, segment=seg,
                        verdict_every=verdict_every)
    return res, rec


# ---------------------------------------------------------------------------
# Bookkeeping
# ---------------------------------------------------------------------------

def test_config_roundtrip():
    p = _params(schedule=Schedule.COLORED, acceleration=False,
                solver=SolverParams(pallas_tcg=False, max_inner_iters=7))
    enc = encode_config(p)
    json.dumps(enc)  # JSON-safe end to end
    assert decode_config(enc) == p


def test_ring_is_bounded_and_snapshots_rotate(tmp_path):
    with obs.run_scope(str(tmp_path / "r")) as run:
        rec = FlightRecorder(run, capacity=4, snapshot_every=2,
                             max_snapshots=2)
        for i in range(10):
            rec.record_eval(i, {"cost": float(i), "grad_norm": 1.0})
        assert len(rec.ring) == 4
        assert [r["iteration"] for r in rec.ring] == [6, 7, 8, 9]
        assert rec.snapshots.maxlen == 2


def test_dump_writes_npz_and_jsonl(tmp_path):
    d = str(tmp_path / "r")
    with obs.run_scope(d) as run:
        run.set_fingerprint(dataset="synthetic-tiny")
        rec = FlightRecorder.attach(run)
        rec.record_eval(2, {"cost": 1.5, "grad_norm": 0.5,
                            "rel_change": np.array([0.1, float("nan")])})
        path = rec.dump("unit-test")
        assert rec.dump("second-call") == path  # first dump wins
        assert rec._dumped == "unit-test"
    arrays = dict(np.load(path))
    assert arrays["ring_cost"].tolist() == [1.5]
    assert not arrays["ring_healthy"][0]  # NaN rel_change -> unhealthy
    with open(os.path.join(d, "blackbox.jsonl")) as fh:
        lines = [json.loads(ln) for ln in fh]
    assert lines[0]["kind"] == "context"
    assert lines[0]["reason"] == "unit-test"
    assert lines[0]["fingerprint"]["dataset"] == "synthetic-tiny"
    assert lines[1]["kind"] == "round" and lines[1]["iteration"] == 2
    (ev,) = [e for e in read_events(os.path.join(d, "events.jsonl"))
             if e["event"] == "blackbox_dump"]
    assert ev["reason"] == "unit-test"


def test_replay_refuses_problemless_blackbox(tmp_path):
    d = str(tmp_path / "r")
    with obs.run_scope(d) as run:
        rec = FlightRecorder.attach(run)
        rec.record_eval(1, {"cost": 1.0, "grad_norm": 1.0})
        path = rec.dump("no-problem")
    with pytest.raises(ValueError, match="not replayable"):
        replay(path)
    assert recorder_main(["--replay", path]) == 2


# ---------------------------------------------------------------------------
# Clean-run replay (no fault): trajectory reproduces bit-for-bit
# ---------------------------------------------------------------------------

def test_clean_run_replays_bit_for_bit(tmp_path):
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        params = _params()
        _res, rec = _run_recorded_solve(run, params, _tiny_problem(),
                                        max_iters=10, snapshot_every=2)
        path = rec.dump("manual")
    rep = replay(path)
    assert rep.match, rep.mismatches
    assert rep.iterations  # at least one eval replayed
    for a, b in zip(rep.cost, rep.recorded_cost):
        assert a == b  # bitwise
    assert recorder_main(["--replay", path]) == 0


# ---------------------------------------------------------------------------
# ACCEPTANCE: seeded NaN injection -> anomaly + blackbox + exact replay
# ---------------------------------------------------------------------------

def test_nan_injection_anomaly_blackbox_and_exact_replay(tmp_path, capsys):
    d = str(tmp_path / "run")
    fault = {"iteration": 6, "agent": 1, "pose": 0}
    with obs.run_scope(d) as run:
        params = _params()
        res, rec = _run_recorded_solve(run, params, _tiny_problem(),
                                       max_iters=10, fault=fault)
        # The solve ran through the NaN to max_iters (no abort policy).
        assert res.iterations == 10
        assert math.isnan(res.cost_history[-1])

    evs = read_events(os.path.join(d, "events.jsonl"))
    # 1) the anomaly event: the NaN surfaced at the eval after injection.
    anomalies = [e for e in evs if e["event"] == "anomaly"]
    assert anomalies and anomalies[0]["kind"] == "non_finite"
    assert anomalies[0]["severity"] == "critical"
    assert anomalies[0]["iteration"] == fault["iteration"]

    # 2) the black box dumped on the anomaly, not at run end.
    (dump,) = [e for e in evs if e["event"] == "blackbox_dump"]
    assert dump["reason"] == "anomaly:non_finite"
    npz = os.path.join(d, "blackbox.npz")
    assert os.path.exists(npz)
    context, arrays = load_blackbox(npz)
    assert context["fault"] == fault
    # The recorded trajectory went NaN exactly at the fault eval.
    it_col = arrays["ring_iteration"].tolist()
    nan_mask = [math.isnan(c) for c in arrays["ring_cost"].tolist()]
    assert nan_mask == [it >= fault["iteration"] for it in it_col]

    # 3) replay resumes from the last GOOD snapshot (iteration 4 — the
    # snapshot at the fault eval is already poisoned) and reproduces the
    # recorded trajectory bit-for-bit, NaNs included.
    rep = replay(npz)
    assert rep.snapshot_iteration == 4
    assert rep.match, rep.mismatches
    # The dump fired AT the anomaly (first-write-wins), so the failure
    # eval is the recorded frontier; the replay reproduces it exactly.
    assert rep.iterations == [6]
    assert [math.isnan(c) for c in rep.cost] == [True]

    # The CLI agrees (exit 0 = reproduced).
    assert recorder_main(["--replay", npz]) == 0
    out = capsys.readouterr().out
    assert "REPRODUCED bit-for-bit" in out

    # 4) a tampered recording is caught: replace the recorded failure
    # value with a finite one.
    arrays2 = dict(np.load(npz))
    arrays2["ring_cost"] = arrays2["ring_cost"].copy()
    arrays2["ring_cost"][-1] = 123.0
    with open(npz, "wb") as fh:
        np.savez_compressed(fh, **arrays2)
    rep2 = replay(npz)
    assert not rep2.match
    assert recorder_main(["--replay", npz]) == 1


def test_crash_dumps_blackbox(tmp_path):
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        with pytest.raises(RuntimeError, match="synthetic driver crash"):
            _run_recorded_solve(run, _params(), _tiny_problem(),
                                max_iters=10, crash_at=5)
    evs = read_events(os.path.join(d, "events.jsonl"))
    (dump,) = [e for e in evs if e["event"] == "blackbox_dump"]
    assert dump["reason"] == "crash"
    assert os.path.exists(os.path.join(d, "blackbox.npz"))


def test_report_renders_health_and_blackbox(tmp_path, capsys):
    """The report CLI surfaces the anomaly + black-box story."""
    from dpgo_tpu.obs.report import main as report_main

    d = str(tmp_path / "run")
    fault = {"iteration": 6, "agent": 0, "pose": 1}
    with obs.run_scope(d):
        _run_recorded_solve(obs.get_run(), _params(), _tiny_problem(),
                            max_iters=8, fault=fault)
    assert report_main([d]) == 0
    out = capsys.readouterr().out
    assert "numerical health:" in out
    assert "non_finite" in out
    assert "blackbox:" in out and "anomaly:non_finite" in out


# ---------------------------------------------------------------------------
# Verdict-word loop compatibility (ISSUE 9): the fused program and the
# replay path stay on the byte-identical metrics computation
# ---------------------------------------------------------------------------

def test_verdict_history_rows_bitwise_match_central_metrics():
    """The verdict program's device-side history rows must equal the
    standalone ``_make_central_metrics`` program's output BITWISE on the
    same states — the ``_central_metrics_body`` extraction contract that
    lets ``--replay`` (which evaluates through ``_make_central_metrics``)
    verify a verdict-mode recording bit-for-bit."""
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.partition import partition_contiguous

    meas = _tiny_problem()
    params = _params()
    part = partition_contiguous(meas, params.num_robots)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64,
                                   sel_mode=rbcd.resolved_sel_mode(params))
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    n_total = part.meas_global.num_poses
    num_meas = len(part.meas_global)
    edges_g = edge_set_from_measurements(part.meas_global,
                                         dtype=jnp.float64)
    central = rbcd._make_central_metrics(graph, edges_g, n_total,
                                         num_meas, telemetry=True)
    vstep = rbcd.make_verdict_program(
        graph, edges_g, n_total, num_meas, telemetry=True,
        grad_norm_tol=1e-12, robust_params=params.robust, max_evals=4)
    vs = rbcd.init_verdict_state(4, meta.num_robots, jnp.float64,
                                 telemetry=True)
    for k in range(4):
        state = rbcd.rbcd_segment(state, graph, 2, meta, params)
        vs = vstep(state.X, state.weights, state.ready, state.mu,
                   state.rel_change, state.iteration, vs)
        ref = np.asarray(central(state.X, state.weights, state.ready,
                                 state.mu, state.rel_change))
        row = np.asarray(vs.hist)[k]
        assert row.tobytes() == ref.tobytes(), (k, row, ref)


def test_verdict_mode_replay_crosses_boundary_bit_for_bit(tmp_path):
    """ACCEPTANCE (ISSUE 9 satellite): a verdict-mode recorded run with a
    seeded NaN fault dumps a black box whose ``--replay`` resumes from a
    K-boundary snapshot, crosses subsequent verdict boundaries, and
    reproduces the recorded trajectory bit-for-bit (rc 0)."""
    meas = _tiny_problem()
    params = _params()
    fault = {"iteration": 9, "agent": 1, "pose": 3}
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        res, rec = _run_recorded_solve(run, params, meas, max_iters=16,
                                       eval_every=2, fault=fault,
                                       verdict_every=4)
        # The on-device non-finite predicate latched into the verdict
        # word (in-band signal) AND the host monitor re-judged the same
        # rows into the standard anomaly event (stream parity).
        npz = os.path.join(d, "blackbox.npz")
        assert os.path.exists(npz)
        # Snapshots were taken at verdict boundaries by snapshot_state.
        ctx, _arrays = load_blackbox(npz)
        snaps = ctx["snapshots"]
        assert snaps and all(s["iteration"] % 4 == 0 for s in snaps)
        assert any(s["healthy"] for s in snaps)
    evs = read_events(os.path.join(d, "events.jsonl"))
    kinds = {e.get("kind") for e in evs if e.get("event") == "anomaly"}
    assert "non_finite" in kinds
    ends = [e for e in evs if e.get("event") == "solve_end"]
    assert ends and ends[0].get("verdict", {}).get("anomaly") == "non_finite"
    # Exact replay across the verdict boundary: the ring rows came from
    # the fused verdict program's history; the replay recomputes them
    # through _make_central_metrics — bitwise agreement required.
    rep = replay(npz)
    assert rep.match, rep.mismatches
    assert recorder_main(["--replay", npz]) == 0


def test_verdict_mode_emits_identical_event_stream(tmp_path):
    """ACCEPTANCE (ISSUE 9): with telemetry on, the verdict-word loop
    must emit the SAME health/anomaly event stream and the same
    solver-metric trajectory as the pre-fusion per-eval path on a seeded
    NaN-injection run — the K-round fetch coarsens the transfer cadence,
    never the observable events."""
    meas = _tiny_problem()
    params = _params()
    fault = {"iteration": 9, "agent": 1, "pose": 3}
    streams = {}
    for mode, k in (("per_eval", None), ("verdict", 8)):
        d = str(tmp_path / mode)
        with obs.run_scope(d) as run:
            _run_recorded_solve(run, params, meas, max_iters=16,
                                eval_every=2, fault=fault,
                                verdict_every=k)
        streams[mode] = read_events(os.path.join(d, "events.jsonl"))

    def anomalies(evs):
        return [(e["kind"], e["severity"], e["iteration"])
                for e in evs if e.get("event") == "anomaly"]

    def metrics(evs, name):
        # repr round-trips NaN equality (math.nan != math.nan).
        return [(e["iteration"], repr(e["value"])) for e in evs
                if e.get("event") == "metric" and e.get("metric") == name
                and e.get("phase") == "eval"]

    assert anomalies(streams["verdict"]) == anomalies(streams["per_eval"])
    assert anomalies(streams["verdict"]), "fault must surface as anomaly"
    for name in ("solver_cost", "solver_grad_norm", "gnc_mu",
                 "gnc_inlier_fraction"):
        assert metrics(streams["verdict"], name) == \
            metrics(streams["per_eval"], name), name
    assert metrics(streams["verdict"], "solver_cost"), "evals must emit"
    # Identical terminal accounting (iterations, terminated_by).
    (end_v,) = [e for e in streams["verdict"]
                if e.get("event") == "solve_end"]
    (end_p,) = [e for e in streams["per_eval"]
                if e.get("event") == "solve_end"]
    assert (end_v["iterations"], end_v["terminated_by"]) == \
        (end_p["iterations"], end_p["terminated_by"])
