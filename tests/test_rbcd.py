"""Multi-agent sync RBCD tests (reference multi-robot-example semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.config import AgentParams, Schedule, SolverParams
from dpgo_tpu.models import local_pgo, rbcd
from dpgo_tpu.ops import manifold, quadratic
from dpgo_tpu.types import edge_set_from_measurements
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements, trajectory_error


def test_partition_contiguous(rng):
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10)
    part = partition_contiguous(meas, 4)
    assert part.n.sum() == 20
    assert part.n.tolist() == [5, 5, 5, 5]
    cls = part.classify()
    # Edge categories consistent: every shared edge crosses robots.
    shared = cls == 2
    assert np.all(part.meas.r1[shared] != part.meas.r2[shared])
    assert np.all(part.meas.r1[~shared] == part.meas.r2[~shared])
    # Round trip local -> global matches original global ids.
    g1 = part.global_index[part.meas.r1, part.meas.p1]
    assert np.array_equal(g1, part.meas_global.p1)


def test_partition_by_keys(rng):
    import dataclasses

    from dpgo_tpu.utils.partition import partition_by_keys

    # Build a 2-robot measurement set with robot-encoded, NON-dense pose ids
    # (robot 98's ids start at 10).
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10)
    robot_of = (np.arange(20) >= 10).astype(np.int32)
    keyed = dataclasses.replace(
        meas,
        r1=np.where(robot_of[meas.p1] == 0, 97, 98).astype(np.int32),
        r2=np.where(robot_of[meas.p2] == 0, 97, 98).astype(np.int32),
        p1=meas.p1,  # robot 98's local ids are 10..19: not dense from 0
        p2=meas.p2,
    )
    part = partition_by_keys(keyed)
    assert part.num_robots == 2
    assert part.n.tolist() == [10, 10]
    # Local ids densified to 0..9 per robot.
    assert part.meas.p1.max() < 10 and part.meas.p2.max() < 10
    # Global indexing is a bijection onto 0..19.
    gids = np.unique(np.concatenate([part.meas_global.p1, part.meas_global.p2]))
    assert len(gids) == 20
    # The partitioned problem still solves to the same optimum.
    params = AgentParams(d=3, r=5, num_robots=2, schedule=Schedule.JACOBI)
    res = rbcd.solve_rbcd(part.meas_global, 2, params, max_iters=100,
                          grad_norm_tol=1e-5, part=part)
    assert res.grad_norm_history[-1] < 1e-5


def test_local_problems_reproduce_global_cost_and_grad(rng):
    # Sum of per-agent private costs + half-counted shared costs == global
    # cost; per-agent block gradient == global gradient restricted to block.
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=12,
                                rot_noise=0.05, trans_noise=0.05)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, rank=5, dtype=jnp.float64)

    Xg = jnp.asarray(np.random.default_rng(1).standard_normal((24, 5, 4)))
    Xa = rbcd.scatter_to_agents(Xg, graph)
    Z = rbcd.neighbor_buffer(rbcd.public_table(Xa, graph), graph)

    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    g_global = quadratic.egrad(Xg, edges_g)

    for a in range(4):
        buf = jnp.concatenate([Xa[a], Z[a]], axis=0)
        import jax

        g_local = quadratic.egrad(buf, jax.tree.map(lambda x: x[a], graph.edges),
                                  n_out=meta.n_max)
        na = int(graph.n[a])
        expected = g_global[part.global_index[a, :na]]
        assert np.allclose(g_local[:na], expected, atol=1e-10), f"agent {a}"


@pytest.mark.parametrize("schedule", [Schedule.JACOBI, Schedule.GREEDY])
def test_rbcd_converges_noiseless(rng, schedule):
    meas, (Rs, ts) = make_measurements(rng, n=20, d=3, num_lc=10)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=schedule,
                         solver=SolverParams())
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=200, grad_norm_tol=1e-6)
    assert res.grad_norm_history[-1] < 1e-6
    assert trajectory_error(res.T, Rs, ts) < 1e-4


def test_greedy_updates_exactly_one_agent_per_round(rng):
    """The gated greedy path (single dynamic-sliced solve instead of A
    masked solves) must still change exactly one agent's block per round,
    and that agent must be the argmax-gradnorm one the reference driver
    selects (MultiRobotExample.cpp:242-256)."""
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10,
                                rot_noise=0.02, trans_noise=0.02)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.GREEDY)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)

    for _ in range(3):
        # Expected selection: per-agent Riemannian gradnorm at X.
        Z = rbcd.neighbor_buffer(rbcd.public_table(state.X, graph), graph)

        def gn_of(x, z, e, s, m):
            buf = jnp.concatenate([x, z], axis=0)
            return manifold.norm(
                manifold.rgrad(x, quadratic.egrad_ell(buf, e, s, m)))

        gn = jax.vmap(gn_of)(state.X, Z, graph.edges, graph.inc_slot,
                             graph.inc_mask)
        expect = int(jnp.argmax(gn))

        new = rbcd.rbcd_step(state, graph, meta, params)
        changed = [a for a in range(4)
                   if not np.allclose(np.asarray(new.X[a]),
                                      np.asarray(state.X[a]), atol=0)]
        assert changed == [expect]
        state = new


def test_rbcd_matches_centralized_on_noisy_graph(rng):
    meas, _ = make_measurements(rng, n=30, d=3, num_lc=15,
                                rot_noise=0.05, trans_noise=0.05)
    central = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-6,
                                    max_iters=300)
    params = AgentParams(d=3, r=5, num_robots=5, schedule=Schedule.JACOBI)
    res = rbcd.solve_rbcd(meas, 5, params, max_iters=300, grad_norm_tol=1e-4)
    # Distributed must reach (nearly) the centralized optimum.
    assert res.cost_history[-1] <= central.cost * 1.01 + 1e-9


def test_rbcd_cost_monotone_jacobi(rng):
    meas, _ = make_measurements(rng, n=24, d=3, num_lc=10,
                                rot_noise=0.05, trans_noise=0.05)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=30, grad_norm_tol=0.0)
    c = res.cost_history
    # Jacobi RBCD on a partitioned quadratic need not be strictly monotone in
    # theory, but on these graphs it should never increase materially.
    assert all(c[k + 1] <= c[k] * (1 + 1e-6) + 1e-9 for k in range(len(c) - 1))


def test_rbcd_async_schedule_runs(rng):
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8,
                                rot_noise=0.03, trans_noise=0.03)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.ASYNC,
                         async_update_prob=0.5)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=60, grad_norm_tol=1e-3)
    assert res.cost_history[-1] <= res.cost_history[0]


def test_rbcd_se2(rng):
    meas, _ = make_measurements(rng, n=20, d=2, num_lc=8,
                                rot_noise=0.02, trans_noise=0.02)
    # Tight rel-change tol so the consensus gate (reference default 5e-3)
    # doesn't stop the solve early, and a tight local-solver gradnorm tol
    # (the reference's per-step 1e-2 floor would cap global convergence).
    params = AgentParams(d=2, r=3, num_robots=4, schedule=Schedule.JACOBI,
                         rel_change_tol=1e-10,
                         solver=SolverParams(grad_norm_tol=1e-7))
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=100, grad_norm_tol=1e-4)
    assert res.grad_norm_history[-1] < 1e-4


def test_rbcd_smallgrid_vs_centralized(data_dir):
    # The reference demo config: 5 robots on smallGrid3D, r = 5
    # (README.md:31-34, MultiRobotExample gate gradnorm < 0.1).
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(f"{data_dir}/smallGrid3D.g2o")
    central = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-3,
                                    max_iters=300)
    params = AgentParams(d=3, r=5, num_robots=5, schedule=Schedule.JACOBI)
    res = rbcd.solve_rbcd(meas, 5, params, max_iters=100, grad_norm_tol=0.1)
    # Either gate may fire first (the consensus rel-change default 5e-3 is
    # the reference's); what matters is solution quality.
    assert res.terminated_by in ("grad_norm", "consensus")
    assert res.cost_history[-1] <= central.cost * 1.05
    # Anchored output frame: pose 0 is the identity.
    T = np.asarray(res.T)
    assert np.allclose(T[0, :, :3], np.eye(3), atol=1e-8)
    assert np.allclose(T[0, :, 3], 0.0, atol=1e-8)


def test_rbcd_rgd_algorithm(rng):
    """RGD dispatch (reference QuadraticOptimizer.cpp:42-47, 124-149): the
    fixed-step gradient schedule also makes progress on a noisy graph, just
    slower than RTR.  Start from odometry so the init is far from optimal."""
    from dpgo_tpu.config import ROptAlg
    from dpgo_tpu.ops import chordal
    from dpgo_tpu.models.local_pgo import lift
    from dpgo_tpu.types import edge_set_from_measurements

    meas, _ = make_measurements(rng, n=12, d=3, num_lc=6,
                                rot_noise=0.05, trans_noise=0.05)
    params = AgentParams(
        d=3, r=5, num_robots=3, schedule=Schedule.JACOBI,
        rel_change_tol=1e-8,
        solver=SolverParams(algorithm=ROptAlg.RGD, rgd_stepsize=1e-4))
    part = partition_contiguous(meas, 3)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    T0 = chordal.odometry_from_edges(edges_g, part.meas_global.num_poses)
    X0 = rbcd.scatter_to_agents(
        lift(T0, rbcd.lifting_matrix(meta, jnp.float64)), graph)
    state = rbcd.init_state(graph, meta, X0, params=params)
    step = lambda s, uw, rs: rbcd.rbcd_step(s, graph, meta, params,
                                            update_weights=uw, restart=rs)
    res = rbcd.run_rbcd(state, graph, meta, step, part, max_iters=300,
                        grad_norm_tol=1e-2, params=params)
    assert res.cost_history[-1] < res.cost_history[0]
    assert res.grad_norm_history[-1] < 0.5 * res.grad_norm_history[0]


def test_package_sets_full_matmul_precision():
    """Importing dpgo_tpu must raise the default matmul precision: TPU f32
    matmuls otherwise run as bf16 MXU passes (~1e-2 error), which pushes
    iterates off the manifold (retraction stops being a no-op at zero) and
    breaks the 1e-6 suboptimality targets.  A user-chosen precision (either
    env var) wins instead."""
    import os

    import jax

    import dpgo_tpu  # noqa: F401  (import side effect under test)

    expected = (os.environ.get("DPGO_TPU_MATMUL_PRECISION")  # "" = unset
                or os.environ.get("JAX_DEFAULT_MATMUL_PRECISION")
                or "highest")
    assert jax.config.jax_default_matmul_precision == expected


def test_fused_rounds_match_sequential(rng):
    """``rbcd_steps(k)`` (the one-dispatch fori_loop) must reproduce k
    sequential ``rbcd_step`` calls exactly — same trace body, same math."""
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8,
                                rot_noise=0.03, trans_noise=0.03)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state0 = rbcd.init_state(graph, meta, X0, params=params)

    seq = state0
    for _ in range(5):
        seq = rbcd.rbcd_step(seq, graph, meta, params)
    fused = rbcd.rbcd_steps(state0, graph, 5, meta, params)

    assert int(fused.iteration) == int(seq.iteration) == 5
    assert np.allclose(np.asarray(fused.X), np.asarray(seq.X), atol=1e-12)
    assert np.allclose(np.asarray(fused.rel_change),
                       np.asarray(seq.rel_change), atol=1e-12)


def test_solver_uses_fused_segments(rng, monkeypatch):
    """``solve_rbcd`` with ``eval_every > 1`` must route every stretch
    through the fused segment path (one dispatch per eval stretch) and
    still converge to the same answer as per-round stepping."""
    meas, (Rs, ts) = make_measurements(rng, n=20, d=3, num_lc=10)
    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.JACOBI)

    calls = {"fused": 0, "per_round": 0}
    orig = rbcd.rbcd_segment

    def counting(state, graph, k, *a, **kw):
        calls["fused"] += 1
        return orig(state, graph, k, *a, **kw)

    def no_step(*a, **kw):
        calls["per_round"] += 1
        raise AssertionError("segment-driven solve must not single-step")

    monkeypatch.setattr(rbcd, "rbcd_segment", counting)
    monkeypatch.setattr(rbcd, "rbcd_step", no_step)
    res = rbcd.solve_rbcd(meas, 4, params, max_iters=60, grad_norm_tol=1e-6,
                          eval_every=10)
    assert calls["fused"] >= 1
    assert calls["per_round"] == 0
    assert res.grad_norm_history[-1] < 1e-6
    assert trajectory_error(res.T, Rs, ts) < 1e-4


def test_fused_segments_respect_gnc_and_restart_schedule(rng):
    """With acceleration + GNC active, the fused driver must fire the same
    weight-update/restart rounds as the per-round driver: identical final
    weights and iterates for eval_every 1 vs 7."""
    from dpgo_tpu.config import RobustCostParams, RobustCostType

    meas, _ = make_measurements(rng, n=20, d=3, num_lc=10, outlier_lc=3,
                                rot_noise=0.01, trans_noise=0.01)
    params = AgentParams(
        d=3, r=5, num_robots=4, schedule=Schedule.JACOBI, acceleration=True,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        robust_opt_inner_iters=10, restart_interval=15,
        rel_change_tol=1e-14)  # keep the consensus gate out of the picture
    res_a = rbcd.solve_rbcd(meas, 4, params, max_iters=40, grad_norm_tol=0.0,
                            eval_every=1)
    res_b = rbcd.solve_rbcd(meas, 4, params, max_iters=40, grad_norm_tol=0.0,
                            eval_every=7)
    assert np.allclose(np.asarray(res_a.weights), np.asarray(res_b.weights),
                       atol=1e-12)
    assert np.allclose(np.asarray(res_a.X), np.asarray(res_b.X), atol=1e-10)


def test_rbcd_scale_20k_poses_32_agents(rng):
    """BASELINE config #5 scale smoke (the g2o100k dataset itself is
    stripped from the snapshot): a 20k-pose / 24k-edge synthetic graph over
    32 agents must build, initialize, and take fused RBCD rounds through the
    ELL formulation (the only one in budget at this size) with decreasing
    cost.  The full 100k/64 configuration runs the same code path (validated
    out-of-suite; build_graph is O(M) host work)."""
    import jax

    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.ops import quadratic

    meas, _ = make_measurements(rng, n=20_000, d=3, num_lc=4_000,
                                rot_noise=0.01, trans_noise=0.01)
    params = AgentParams(d=3, r=5, num_robots=32, schedule=Schedule.JACOBI)
    part = partition_contiguous(meas, 32)
    graph, meta = rbcd.build_graph(part, params.r, jnp.float64)
    assert rbcd._formulation(meta, params, graph, itemsize=8) == "ell"
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)

    edges_g = edge_set_from_measurements(part.meas_global, dtype=jnp.float64)
    Xg0 = rbcd.gather_to_global(state.X, graph, meas.num_poses)
    f0 = float(quadratic.cost(Xg0, edges_g))

    state = rbcd.rbcd_steps(state, graph, 3, meta, params)
    assert bool(jax.numpy.isfinite(state.X).all())
    Xg = rbcd.gather_to_global(state.X, graph, meas.num_poses)
    f1 = float(quadratic.cost(Xg, edges_g))
    assert f1 < f0


def test_egrad_ell_matches_scatter(rng):
    """The gather-only ELL gradient/Hessian path must agree with the
    scatter-add reference formulation on every agent."""
    import jax

    meas, _ = make_measurements(rng, n=24, d=3, num_lc=12,
                                rot_noise=0.05, trans_noise=0.05)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, rank=5, dtype=jnp.float64)
    Xa = jnp.asarray(np.random.default_rng(3).standard_normal(
        (4, meta.n_max, 5, 4)))
    Z = rbcd.neighbor_buffer(rbcd.public_table(Xa, graph), graph)
    n_buf = meta.n_max + meta.s_max
    for a in range(4):
        e = jax.tree.map(lambda x: x[a], graph.edges)
        buf = jnp.concatenate([Xa[a], Z[a]], axis=0)
        g_ref = quadratic.egrad(buf, e, n_out=meta.n_max)
        g_ell = quadratic.egrad_ell(buf, e, graph.inc_slot[a],
                                    graph.inc_mask[a])
        assert np.allclose(g_ell, g_ref, atol=1e-12), f"agent {a}"
        V = jnp.asarray(rng.standard_normal((meta.n_max, 5, 4)))
        h_ref = quadratic.hessvec(V, e, n_buf=n_buf)
        h_ell = quadratic.hessvec_ell(V, e, graph.inc_slot[a],
                                      graph.inc_mask[a], n_buf=n_buf)
        assert np.allclose(h_ell, h_ref, atol=1e-12), f"agent {a} hessvec"


def test_colored_schedule_converges_and_matches_structure(rng):
    """Schedule.COLORED: one color class fires per round (non-adjacent
    agents only), the sweep cycles deterministically, and the solve reaches
    the same optimum as JACOBI on a well-behaved graph."""
    from dpgo_tpu.config import Schedule

    meas, _ = make_measurements(rng, n=24, d=3, num_lc=10,
                                rot_noise=0.01, trans_noise=0.01)
    part = partition_contiguous(meas, 4)
    graph, meta = rbcd.build_graph(part, 5, jnp.float64)
    assert meta.num_colors >= 2  # contiguous partitions couple neighbors
    color = np.asarray(graph.color)
    # valid coloring vs the neighbor tables
    nr, nm = np.asarray(graph.nbr_robot), np.asarray(graph.nbr_mask) > 0
    for a in range(4):
        for b in nr[a][nm[a]]:
            assert color[a] != color[b]

    params = AgentParams(d=3, r=5, num_robots=4, schedule=Schedule.COLORED,
                         rel_change_tol=0.0)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=params)
    prev = state.X
    # Round k must change only poses of color (k mod C).
    for k in range(meta.num_colors):
        state = rbcd.rbcd_step(state, graph, meta, params)
        changed = np.asarray(jnp.any(state.X != prev, axis=(1, 2, 3)))
        assert not np.any(changed & (color != k % meta.num_colors))
        prev = state.X
    # And the full solve converges like JACOBI does.
    res = rbcd.solve_rbcd(meas, 4, params=params, max_iters=120,
                          grad_norm_tol=0.05, eval_every=meta.num_colors,
                          dtype=jnp.float64)
    assert res.grad_norm_history[-1] < 0.05


def test_colored_fixes_jacobi_oscillation_ais2klinik(data_dir):
    """The VERDICT r2 finding: JACOBI (simultaneous updates of adjacent
    blocks) oscillates on ais2klinik even in plain L2, while the colored
    Gauss-Seidel sweep — the parallelism the RBCD theory actually licenses
    — descends monotonically and ends far below Jacobi's oscillation band.
    """
    import os
    from dpgo_tpu.config import Schedule
    from dpgo_tpu.ops import quadratic
    from dpgo_tpu.types import edge_set_from_measurements
    from dpgo_tpu.utils.g2o import read_g2o

    path = os.path.join(data_dir, "ais2klinik.g2o")
    if not os.path.exists(path):
        pytest.skip("dataset not available")
    meas = read_g2o(path)
    A = 32
    part = partition_contiguous(meas, A)
    edges_g = edge_set_from_measurements(part.meas_global,
                                         dtype=jnp.float64)
    n = meas.num_poses

    def costs_for(sched, sweeps):
        params = AgentParams(d=2, r=3, num_robots=A, schedule=sched,
                             rel_change_tol=0.0)
        graph, meta = rbcd.build_graph(part, 3, jnp.float64)
        X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
        state = rbcd.init_state(graph, meta, X0, params=params)
        per = 1 if sched == Schedule.JACOBI else meta.num_colors
        out = []
        for _ in range(sweeps):
            state = rbcd.rbcd_steps(state, graph, per, meta, params)
            out.append(float(quadratic.cost(
                rbcd.gather_to_global(state.X, graph, n), edges_g)))
        return out

    cj = costs_for(Schedule.JACOBI, 25)
    cc = costs_for(Schedule.COLORED, 25)
    inc_j = sum(1 for a, b in zip(cj, cj[1:]) if b > a + 1e-9)
    inc_c = sum(1 for a, b in zip(cc, cc[1:]) if b > a + 1e-9)
    assert inc_j >= 5          # Jacobi genuinely oscillates here
    assert inc_c == 0          # the colored sweep is monotone
    assert cc[-1] < 0.5 * cj[-1]  # and ends far below the oscillation band


def test_colored_schedule_with_acceleration(rng):
    """COLORED composes with Nesterov acceleration (deterministic lockstep
    like GREEDY, so the reference's async-only prohibition does not
    apply): the accelerated colored solve reaches the gradnorm gate.
    (Measured side-by-side during development: 20 rounds accelerated vs
    30 plain on this problem; only termination is asserted here.)"""
    from dpgo_tpu.config import Schedule

    meas, _ = make_measurements(rng, n=24, d=3, num_lc=10,
                                rot_noise=0.01, trans_noise=0.01)
    res = rbcd.solve_rbcd(meas, 4, params=AgentParams(
        d=3, r=5, num_robots=4, schedule=Schedule.COLORED,
        acceleration=True, restart_interval=30, rel_change_tol=0.0),
        max_iters=200, grad_norm_tol=0.05, eval_every=10,
        dtype=jnp.float64)
    assert res.terminated_by == "grad_norm"
    assert res.grad_norm_history[-1] < 0.05


# ---------------------------------------------------------------------------
# Device-resident verdict loop (ISSUE 9)
# ---------------------------------------------------------------------------

def _verdict_problem(rng, n=50, noise=0.05):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=n // 2,
                                rot_noise=noise, trans_noise=noise)
    return meas


def test_verdict_loop_matches_legacy_histories(rng):
    """Full-run parity: verdict mode reproduces the per-eval loop's
    cost/gradnorm histories bitwise, termination label, round count, and
    (at max_iters, where there is no overshoot) the iterate itself."""
    meas = _verdict_problem(rng)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    a = rbcd.solve_rbcd(meas, 2, params=params, max_iters=24, eval_every=2,
                        grad_norm_tol=1e-9, dtype=jnp.float64)
    b = rbcd.solve_rbcd(meas, 2, params=params, max_iters=24, eval_every=2,
                        grad_norm_tol=1e-9, dtype=jnp.float64,
                        verdict_every=8)
    assert a.cost_history == b.cost_history
    assert a.grad_norm_history == b.grad_norm_history
    assert (a.iterations, a.terminated_by) == (b.iterations, b.terminated_by)
    assert np.array_equal(np.asarray(a.X), np.asarray(b.X))


def test_verdict_loop_termination_latches_mid_window(rng):
    """A gradnorm termination latched between verdict fetches reports the
    same terminal eval/round as the per-eval loop — histories truncated
    at the latched eval, not at the fetch boundary."""
    meas = _verdict_problem(rng)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    a = rbcd.solve_rbcd(meas, 2, params=params, max_iters=200, eval_every=1,
                        grad_norm_tol=2e-2, dtype=jnp.float64)
    b = rbcd.solve_rbcd(meas, 2, params=params, max_iters=200, eval_every=1,
                        grad_norm_tol=2e-2, dtype=jnp.float64,
                        verdict_every=8)
    assert a.terminated_by == "grad_norm"
    assert (a.iterations, a.terminated_by) == (b.iterations, b.terminated_by)
    assert a.cost_history == b.cost_history
    assert a.grad_norm_history == b.grad_norm_history


def test_verdict_loop_fetch_cadence(rng, monkeypatch):
    """Telemetry off, the loop performs exactly rounds/K verdict-word
    fetches plus ONE fused terminal-epilogue fetch — counted through the
    ``_host_fetch`` seam (the bench's host_syncs shim technique)."""
    meas = _verdict_problem(rng)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    count = [0]
    orig = rbcd._host_fetch
    monkeypatch.setattr(rbcd, "_host_fetch",
                        lambda x: (count.__setitem__(0, count[0] + 1),
                                   orig(x))[1])
    res = rbcd.solve_rbcd(meas, 2, params=params, max_iters=32,
                          eval_every=4, grad_norm_tol=0.0,
                          dtype=jnp.float64, verdict_every=16)
    assert res.iterations == 32
    assert count[0] == 32 // 16 + 1  # words + one fused terminal epilogue


def test_verdict_every_must_divide_eval_every(rng):
    meas = _verdict_problem(rng, n=20)
    params = AgentParams(d=3, r=5, num_robots=2)
    with pytest.raises(ValueError, match="verdict_every"):
        rbcd.solve_rbcd(meas, 2, params=params, max_iters=8, eval_every=3,
                        grad_norm_tol=1e-9, dtype=jnp.float64,
                        verdict_every=4)


def test_verdict_word_pack_unpack_roundtrip():
    for status in (rbcd.VERDICT_RUNNING, rbcd.VERDICT_GRAD_NORM,
                   rbcd.VERDICT_CONSENSUS):
        for anom in (rbcd.ANOMALY_NONE, rbcd.ANOMALY_STALL,
                     rbcd.ANOMALY_NON_FINITE):
            for stage in (0, 3, 97):
                w = rbcd.pack_verdict(status, anom, stage)
                dec = rbcd.unpack_verdict(w)
                assert dec["stage"] == stage
                assert dec["status"] == rbcd._VERDICT_STATUS[status]
                assert dec["anomaly"] == rbcd._VERDICT_ANOMALY[anom]


def test_verdict_loop_gnc_weight_updates_match(rng):
    """Robust (GNC) schedule parity: flagged weight-update rounds land on
    the same rounds in verdict mode (host-deterministic schedule_bounds),
    so the mu trajectory and histories agree with the per-eval loop."""
    from dpgo_tpu.config import RobustCostParams, RobustCostType

    meas = _verdict_problem(rng)
    params = AgentParams(
        d=3, r=5, num_robots=2, rel_change_tol=0.0,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        robust_opt_inner_iters=4)
    a = rbcd.solve_rbcd(meas, 2, params=params, max_iters=20, eval_every=2,
                        grad_norm_tol=1e-9, dtype=jnp.float64)
    b = rbcd.solve_rbcd(meas, 2, params=params, max_iters=20, eval_every=2,
                        grad_norm_tol=1e-9, dtype=jnp.float64,
                        verdict_every=4)
    assert a.cost_history == b.cost_history
    assert a.grad_norm_history == b.grad_norm_history
    assert np.array_equal(np.asarray(a.weights), np.asarray(b.weights))
