"""Closed-form small-matrix kernels vs LAPACK references."""

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.ops import smallmat


# Column counts are the SE(d) dimension d in {2, 3} throughout the framework
# (St(r, d) blocks; the r = d local solve still has d <= 3 columns).
@pytest.mark.parametrize("r,d", [(3, 3), (5, 3), (7, 3), (3, 2), (7, 2)])
def test_polar_matches_svd(rng, r, d):
    M = jnp.asarray(rng.standard_normal((64, r, d)))
    U = smallmat.polar_orthonormalize(M)
    # Orthonormal columns
    G = jnp.swapaxes(U, -1, -2) @ U
    assert np.allclose(G, np.eye(d), atol=1e-8)
    # Matches the SVD polar factor
    u, _, vt = np.linalg.svd(np.asarray(M), full_matrices=False)
    assert np.allclose(U, u @ vt, atol=1e-7)


def test_polar_skewed_spectrum(rng):
    # Singular values spanning 1e-2 .. 1e2 (condition 1e4, far beyond any
    # retraction argument): the trace normalization plus fixed
    # Newton-Schulz iterations must still converge.
    u, _, vt = np.linalg.svd(rng.standard_normal((32, 5, 3)),
                             full_matrices=False)
    sv = 10.0 ** rng.uniform(-2, 2, size=(32, 3))
    M = jnp.asarray(u * sv[:, None, :] @ vt)
    U = smallmat.polar_orthonormalize(M)
    G = jnp.swapaxes(U, -1, -2) @ U
    assert np.allclose(G, np.eye(3), atol=1e-6)
    assert np.allclose(U, u @ vt, atol=1e-6)


def test_polar_near_identity(rng):
    # The common case: a tangent step off an orthonormal Y (retraction).
    u, _, vt = np.linalg.svd(rng.standard_normal((16, 5, 3)),
                             full_matrices=False)
    Y = u @ vt
    M = jnp.asarray(Y + 0.05 * rng.standard_normal(Y.shape))
    U = smallmat.polar_orthonormalize(M)
    uu, _, vvt = np.linalg.svd(np.asarray(M), full_matrices=False)
    assert np.allclose(U, uu @ vvt, atol=1e-9)


@pytest.mark.parametrize("k", [3, 4])
def test_cholesky_small(rng, k):
    B = rng.standard_normal((128, k, k))
    A = jnp.asarray(B @ np.swapaxes(B, -1, -2) + 0.1 * np.eye(k))
    L = smallmat.cholesky_small(A)
    assert np.allclose(L @ jnp.swapaxes(L, -1, -2), A, atol=1e-9)
    assert np.allclose(np.triu(np.asarray(L), 1), 0.0)


@pytest.mark.parametrize("k,m", [(4, 5), (3, 7)])
def test_cho_solve_small(rng, k, m):
    B = rng.standard_normal((64, k, k))
    A = jnp.asarray(B @ np.swapaxes(B, -1, -2) + 0.1 * np.eye(k))
    rhs = jnp.asarray(rng.standard_normal((64, k, m)))
    L = smallmat.cholesky_small(A)
    X = smallmat.cho_solve_small(L, rhs)
    assert np.allclose(A @ X, rhs, atol=1e-8)
