"""M6 tests: dual certificate + Riemannian staircase (beyond-reference;
scoped from the T-RO 2021 paper per SURVEY.md section 7, M6 — the reference
repo contains no certification code to mirror, so these tests validate
against first principles: dense eigensolves and a constructed suboptimal
critical point)."""

import numpy as np
import pytest

import jax.numpy as jnp

from dpgo_tpu.config import SolverParams
from dpgo_tpu.models import certify, local_pgo
from dpgo_tpu.ops import solver
from dpgo_tpu.types import Measurements, edge_set_from_measurements
from synthetic import make_measurements


def dense_certificate(X, edges):
    """Assemble S explicitly by applying the operator to basis vectors."""
    n, _, dh = X.shape
    lam = certify.dual_blocks(X, edges)
    m = n * dh
    eye = jnp.eye(m).reshape(m, n, dh).transpose(1, 0, 2)  # [n, m, d+1]
    S_cols = certify.certificate_matvec(eye, edges, lam)
    return np.asarray(S_cols.transpose(1, 0, 2).reshape(m, m))


def test_certificate_operator_matches_dense_eig(rng):
    meas, _ = make_measurements(rng, n=10, d=3, num_lc=5,
                                rot_noise=0.05, trans_noise=0.05)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9,
                                max_iters=500)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    S = dense_certificate(res.X, edges)
    assert np.allclose(S, S.T, atol=1e-9)
    lam_dense = float(np.linalg.eigvalsh(S)[0])
    cert = certify.certify_solution(res.X, edges)
    assert abs(cert.lambda_min - lam_dense) < 1e-6 * max(1.0, abs(lam_dense))
    # Gauge: global-translation directions are in S's nullspace.
    v = np.zeros((10, 4)); v[:, 3] = 1.0
    assert np.abs(S @ v.reshape(-1)).max() < 1e-9


def test_optimal_solution_certifies(rng):
    meas, _ = make_measurements(rng, n=20, d=3, num_lc=8,
                                rot_noise=0.05, trans_noise=0.05)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9, max_iters=500)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    cert = certify.certify_solution(res.X, edges)
    assert cert.stationarity_gap < 1e-6
    assert cert.certified
    assert cert.lambda_min > -1e-6 * cert.sigma


def _winding_cycle(n=12, kappa=10.0, tau=1.0):
    """SE(2) cycle graph whose measurements are all identity — the global
    optimum is the all-identity trajectory (cost 0), but the 'winding'
    configuration R_k = rot(2 pi k / n) is a rank-2 critical point (a
    genuine local minimum for n > 4): the classic suboptimal critical point
    of angular synchronization on a cycle."""
    edges = [(k, (k + 1) % n) for k in range(n)]
    m = len(edges)
    e = np.asarray(edges)
    meas = Measurements(
        d=2, num_poses=n,
        r1=np.zeros(m, np.int32), p1=e[:, 0].astype(np.int64),
        r2=np.zeros(m, np.int32), p2=e[:, 1].astype(np.int64),
        R=np.tile(np.eye(2), (m, 1, 1)), t=np.zeros((m, 2)),
        kappa=np.full(m, kappa), tau=np.full(m, tau),
        weight=np.ones(m), is_known_inlier=np.zeros(m, bool),
    )
    th = 2 * np.pi * np.arange(n) / n
    Rw = np.stack([np.stack([np.cos(th), -np.sin(th)], -1),
                   np.stack([np.sin(th), np.cos(th)], -1)], -2)  # [n, 2, 2]
    Xw = np.concatenate([Rw, np.zeros((n, 2, 1))], axis=-1)  # rank 2 = d
    return meas, jnp.asarray(Xw)


def test_winding_local_minimum_fails_certificate_and_staircase_escapes():
    meas, Xw = _winding_cycle()
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    n = meas.num_poses
    params = SolverParams(initial_radius=1e1, max_inner_iters=50)
    problem = local_pgo.make_problem(edges, n, params.precond_shift)

    # The winding configuration is critical at rank 2: RTR does not move.
    out = solver.rtr_solve(problem, Xw, params, max_iters=200,
                           grad_norm_tol=1e-9)
    assert float(out.grad_norm) < 1e-9
    f_wind = float(out.f)
    assert f_wind > 1.0  # far from the global optimum (cost 0)

    # The certificate must detect suboptimality...
    cert = certify.certify_solution(out.X, edges)
    assert not cert.certified
    assert cert.lambda_min < -1e-3

    # ...and climbing the staircase must reach the certified global optimum
    # (cost 0).  Each escape strictly decreases the cost; this instance
    # passes through a SECOND suboptimal critical point at rank 3 (cost
    # exactly half the winding cost) before certifying at rank 4.
    X = out.X
    costs = [f_wind]
    for _ in range(3):
        X = certify.escape_rank(X, cert.direction, edges)
        out = solver.rtr_solve(problem, X, params, max_iters=400,
                               grad_norm_tol=1e-9)
        X = out.X
        costs.append(float(out.f))
        assert costs[-1] < costs[-2]
        cert = certify.certify_solution(X, edges)
        if cert.certified:
            break
    assert cert.certified
    assert costs[-1] < 1e-9


def test_solve_staircase_end_to_end(rng):
    meas, (Rs, ts) = make_measurements(rng, n=24, d=3, num_lc=10,
                                       rot_noise=0.05, trans_noise=0.05)
    res = certify.solve_staircase(meas, grad_norm_tol=1e-8)
    assert res.certificate.certified
    assert res.rank <= 6
    # Certified solution equals the plain high-rank solve's optimum.
    ref = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-8, max_iters=500)
    assert res.cost <= ref.cost * (1 + 1e-8) + 1e-12


def test_staircase_rounding_handles_rotated_basis(rng):
    # After an escape the solution may leave the initial lifted subspace;
    # rounding must still recover a valid SE(d) trajectory.
    meas, Xw = _winding_cycle()
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    params = SolverParams(initial_radius=1e1, max_inner_iters=50)
    problem = local_pgo.make_problem(edges, meas.num_poses, params.precond_shift)
    out = solver.rtr_solve(problem, Xw, params, max_iters=100, grad_norm_tol=1e-9)
    cert = certify.certify_solution(out.X, edges)
    X3 = certify.escape_rank(out.X, cert.direction, edges)
    out3 = solver.rtr_solve(problem, X3, params, max_iters=300, grad_norm_tol=1e-9)
    ylift = certify._recover_rounding_basis(out3.X, 2)
    T = local_pgo.round_solution(out3.X, ylift)
    R = np.asarray(T[..., :2])
    RtR = np.einsum("nab,nac->nbc", R, R)
    assert np.allclose(RtR, np.eye(2), atol=1e-8)
    assert np.allclose(np.linalg.det(R), 1.0, atol=1e-8)


def test_lambda_min_f64_matches_dense(rng):
    """The host-f64 LOBPCG (the large-sigma verification path) must agree
    with the dense f64 eigensolve on a problem small enough to assemble."""
    meas, _ = make_measurements(rng, n=12, d=3, num_lc=6,
                                rot_noise=0.05, trans_noise=0.05)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9,
                                max_iters=500)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    S = dense_certificate(res.X, edges)
    lam_dense = float(np.linalg.eigvalsh(S)[0])
    lam64, vec, resid = certify.lambda_min_f64(
        np.asarray(res.X, np.float64), edges)
    assert resid < 1e-5
    assert abs(lam64 - lam_dense) < 1e-8 * max(1.0, abs(lam_dense))
    # The returned vector is a genuine eigenvector of S at lam64.
    v = vec.reshape(-1)
    resid = np.abs(S @ v - lam64 * v).max()
    assert resid < 1e-6


def test_certificate_weight_scale_tolerance_and_decidability(rng):
    """Round-5 semantics (VERDICT r4 item 3): tol rides the per-edge
    weight scale, not the spectral radius, and an f32 eigensolve whose
    dtype error exceeds that tolerance must either verify in f64 or
    refuse to certify — never claim a vacuous certificate."""
    meas, _ = make_measurements(rng, n=15, d=3, num_lc=6,
                                rot_noise=0.05, trans_noise=0.05)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9,
                                max_iters=500)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    cert = certify.certify_solution(res.X, edges)
    ws = certify.weight_scale(edges)
    assert cert.weight_scale == ws
    assert cert.tol == pytest.approx(1e-5 * ws)
    assert cert.decidable  # f64 solve: eps * sigma is tiny
    # f32 path on the same problem: force a tolerance far below what an
    # f32 eigensolve can resolve (tiny eta) WITHOUT the f64 fallback —
    # the certificate must refuse rather than claim.
    e32 = edge_set_from_measurements(meas, dtype=jnp.float32)
    X32 = jnp.asarray(res.X, jnp.float32)
    # eta chosen so tol sits BELOW the f32 eigensolve's error band
    # (10 ulps of sigma) but ABOVE what the f64 LOBPCG resolves.
    small_eta = 5e-8
    cert32 = certify.certify_solution(X32, e32, eta=small_eta,
                                      f64_verify="never")
    assert not cert32.decidable
    assert not cert32.certified
    # With the f64 verification enabled (default), the same call decides.
    cert32v = certify.certify_solution(X32, e32, eta=small_eta)
    assert cert32v.decidable
    assert cert32v.lambda_min_f64 is not None
    assert cert32v.certified  # the optimum genuinely certifies
    # An eta below what even f64 resolves must NEVER certify.  Under the
    # two-sided interval rule the outcome is a SOUND FAIL rather than a
    # refusal: the gauge "zeros" are only numerically zero (~1e-7), and
    # at tol ~1e-9 an eigenvalue below -tol genuinely exists
    # (lam_f64 + resid < -tol decides it).  Either refusal or a decided
    # FAIL honors the invariant; certification would not.
    tiny_eta = float(jnp.finfo(jnp.float32).eps) / max(1.0, ws) * 0.01
    cert32r = certify.certify_solution(X32, e32, eta=tiny_eta)
    assert not cert32r.certified


def test_lambda_min_f64_deflated_matches_dense():
    """The gauge-deflated LOBPCG path (auto-enabled at 100k scale, where
    the zero cluster stalls the unconstrained solve — round 5) must agree
    with the dense f64 eigensolve: full-space lambda_min is
    min(complement eigenvalue, gauge zeros), decided on a problem small
    enough to assemble but run with deflate=True explicitly."""
    from dpgo_tpu.utils.synthetic import make_stitched_winding

    meas, Xw = make_stitched_winding(3, 12)   # wound: decisively negative
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    X = jnp.asarray(Xw, jnp.float64)
    S = dense_certificate(X, edges)
    lam_dense = float(np.linalg.eigvalsh(S)[0])
    assert lam_dense < -1e-3                  # genuine negative curvature
    lam64, vec, resid = certify.lambda_min_f64(
        np.asarray(X, np.float64), edges, deflate=True)
    assert resid < 1e-5
    assert abs(lam64 - lam_dense) < 1e-6 * max(1.0, abs(lam_dense))


def test_sparse_certificate_matches_dense(rng):
    """The sparse CSR assembly of S (the shift-invert verification path)
    must equal the dense certificate entry-for-entry, and the
    shift-invert eigensolve must agree with the dense minimum eigenvalue
    on wound (negative) and optimal (certified) micro problems."""
    from dpgo_tpu.utils.synthetic import make_stitched_winding

    meas, _ = make_measurements(rng, n=12, d=3, num_lc=6,
                                rot_noise=0.05, trans_noise=0.05)
    res = local_pgo.solve_local(meas, rank=5, grad_norm_tol=1e-9,
                                max_iters=500)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    S_dense = np.asarray(dense_certificate(res.X, edges))
    S_sp = certify.sparse_certificate(np.asarray(res.X), edges)
    assert np.abs(S_sp.toarray() - S_dense).max() < 1e-9

    # Wound SE(2) micro: decisively negative lambda_min.
    measw, Xw = make_stitched_winding(3, 12)
    edgesw = edge_set_from_measurements(measw, dtype=jnp.float64)
    Sd = np.asarray(dense_certificate(jnp.asarray(Xw, jnp.float64), edgesw))
    lam_dense = float(np.linalg.eigvalsh(Sd)[0])
    lam, vec, resid = certify.lambda_min_f64_shift_invert(
        np.asarray(Xw, np.float64), edgesw, tol_cert=1e-4)
    assert resid < 1e-8
    assert abs(lam - lam_dense) < 1e-8 * max(1.0, abs(lam_dense))
