"""Tests for ``tools.dpgolint``: per-rule fixtures (positive, negative,
suppressed, guard-dominated), the wire-symmetry check over both codec
vocabularies, the seeded-violation smoke on real project files, the
self-check that the tree is clean, and the leakcheck plugin contract."""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.dpgolint import Config, run_lint
from tools.dpgolint.config import project_config

REPO = Path(__file__).resolve().parents[1]


def lint_src(tmp_path, source, rule, config=None, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run_lint([str(p)], config or Config(), rules=[rule])


# ---------------------------------------------------------------------------
# DPG001 jit-purity
# ---------------------------------------------------------------------------

JIT_SRC = """
    import time
    import random
    import jax
    import numpy as np

    def helper(x):
        t = time.time()
        return x * t

    @jax.jit
    def entry(x):
        print("tracing")
        r = random.random()
        s = np.random.default_rng(0).normal()
        v = x.item()
        return helper(x) + r + s + v

    def host_driver(x):
        time.sleep(0.1)          # host code: clocks are fine here
        return float(x)
"""


def test_dpg001_flags_impurities_in_reachable_functions(tmp_path):
    findings = lint_src(tmp_path, JIT_SRC, "DPG001")
    msgs = "\n".join(f.message for f in findings)
    assert "time.time" in msgs                      # via reachable helper
    assert "print()" in msgs
    assert "random.random" in msgs
    assert "np.random.default_rng" in msgs
    assert ".item() host sync" in msgs
    # host_driver is not reachable from any jit entry: nothing of its
    # body is flagged.
    assert "time.sleep" not in msgs
    assert all("host_driver" not in f.message for f in findings)


def test_dpg001_vmap_arg_and_partial_decorator_are_entries(tmp_path):
    src = """
        import time
        import jax
        from functools import partial

        def body(x):
            return time.monotonic() + x

        mapped = jax.vmap(body)

        @partial(jax.jit, static_argnums=0)
        def seg(k, x):
            time.perf_counter()
            return x
    """
    findings = lint_src(tmp_path, src, "DPG001")
    msgs = "\n".join(f.message for f in findings)
    assert "time.monotonic" in msgs and "time.perf_counter" in msgs


def test_dpg001_global_mutation_and_suppression(tmp_path):
    src = """
        import jax

        COUNT = 0

        @jax.jit
        def entry(x):
            global COUNT
            COUNT += 1
            return x

        @jax.jit
        def entry2(x):
            global COUNT  # reviewed: dpgolint: disable=DPG001
            COUNT += 1
            return x
    """
    findings = lint_src(tmp_path, src, "DPG001")
    assert len(findings) == 1 and "global mutation" in findings[0].message


def test_dpg001_jax_random_is_not_flagged(tmp_path):
    src = """
        import jax

        @jax.jit
        def entry(x, key):
            k1, k2 = jax.random.split(key)
            return x + jax.random.normal(k1, x.shape)
    """
    assert lint_src(tmp_path, src, "DPG001") == []


# ---------------------------------------------------------------------------
# DPG002 telemetry fence
# ---------------------------------------------------------------------------

def test_dpg002_unguarded_construction_flagged(tmp_path):
    src = """
        from dpgo_tpu import obs
        from dpgo_tpu.obs.health import HealthMonitor

        def setup():
            mon = HealthMonitor(obs.get_run())
            return mon
    """
    findings = lint_src(tmp_path, src, "DPG002")
    assert len(findings) == 1
    assert "HealthMonitor" in findings[0].message
    assert "telemetry-enabled guard" in findings[0].message


@pytest.mark.parametrize("body", [
    # if-dominated
    """
    run = obs.get_run()
    if run is not None:
        mon = HealthMonitor(run)
    """,
    # early-exit dominated
    """
    run = obs.get_run()
    if run is None:
        return None
    mon = HealthMonitor(run)
    """,
    # two-level guard variable (the run_rbcd `telemetry` idiom)
    """
    run = obs.get_run()
    telemetry = run is not None
    if telemetry:
        mon = HealthMonitor(run)
    """,
    # else-branch of the negated test
    """
    run = obs.get_run()
    if run is None:
        mon = None
    else:
        mon = HealthMonitor(run)
    """,
    # conjunction guard
    """
    run = obs.get_run()
    flag = True
    if run is not None and flag:
        mon = HealthMonitor(run)
    """,
])
def test_dpg002_guard_dominated_constructions_pass(tmp_path, body):
    src = ("from dpgo_tpu import obs\n"
           "from dpgo_tpu.obs.health import HealthMonitor\n\n"
           "def setup():\n"
           + textwrap.indent(textwrap.dedent(body), "    ")
           + "    return mon\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    assert run_lint([str(p)], Config(), rules=["DPG002"]) == []


def test_dpg002_suppression_and_span(tmp_path):
    src = """
        from dpgo_tpu.obs.trace import Span

        def f(run):
            a = Span(run, "x")  # dpgolint: disable=DPG002 -- test fixture
            b = Span(run, "y")
            return a, b
    """
    findings = lint_src(tmp_path, src, "DPG002")
    assert len(findings) == 1 and "Span" in findings[0].message


def test_dpg002_guarded_builder_lambda_passes(tmp_path):
    # The executable-cache idiom: construction deferred into a lambda,
    # dominated by the enclosing early exit.
    src = """
        from dpgo_tpu import obs
        from dpgo_tpu.obs.profile import ProfiledExecutable

        def cached(cache, fp, make):
            run = obs.get_run()
            if run is None:
                return cache.get(fp, make)
            return cache.get(fp, lambda: ProfiledExecutable(make()))
    """
    assert lint_src(tmp_path, src, "DPG002") == []


# ---------------------------------------------------------------------------
# DPG003 host-sync hazards
# ---------------------------------------------------------------------------

HOT_CFG = Config(options={"DPG003": {"per_file": {
    "*": {"hot_functions": ["hot"]}}}})


def test_dpg003_sync_in_loop_flagged(tmp_path):
    src = """
        import numpy as np

        def hot(step, xs, n):
            out = []
            for _ in range(n):
                xs = step(xs)
                out.append(np.asarray(xs))     # implicit transfer
                xs.block_until_ready()
                v = float(step(xs))            # cast of a fresh call result
            return out, v
    """
    findings = lint_src(tmp_path, src, "DPG003", HOT_CFG)
    msgs = "\n".join(f.message for f in findings)
    assert "np.asarray" in msgs
    assert ".block_until_ready()" in msgs
    assert "float() on a call result" in msgs
    assert len(findings) == 3


def test_dpg003_host_values_and_cold_functions_pass(tmp_path):
    src = """
        import numpy as np

        def hot(vec, n):
            for i in range(n):
                f = vec[i]
                x = float(f)          # plain name: already host-side
            y = np.asarray(vec)       # outside any loop: the seam
            return x, y

        def cold(step, xs, n):
            for _ in range(n):
                xs = np.asarray(step(xs))   # not a configured hot path
            return xs
    """
    assert lint_src(tmp_path, src, "DPG003", HOT_CFG) == []


def test_dpg003_suppressed_seam(tmp_path):
    src = """
        import numpy as np

        def hot(step, xs, n):
            while n > 0:
                # sanctioned seam. dpgolint: disable=DPG003
                vec = np.asarray(step(xs))
                n -= 1
            return vec
    """
    assert lint_src(tmp_path, src, "DPG003", HOT_CFG) == []


# ---------------------------------------------------------------------------
# DPG004 lock discipline
# ---------------------------------------------------------------------------

LOCKED_SRC = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0          # guarded-by: _lock
            self._items = []         # guarded-by: _lock

        def good(self):
            with self._lock:
                self._count += 1
                return list(self._items)

        def helper(self):  # holds: _lock
            self._items.append(self._count)

        def good_call(self):
            with self._lock:
                self.helper()
"""


def test_dpg004_locked_accesses_pass(tmp_path):
    assert lint_src(tmp_path, LOCKED_SRC, "DPG004") == []


def test_dpg004_unlocked_access_and_call_flagged(tmp_path):
    # (8-space indent pre-dedent: append methods into the class body)
    src = LOCKED_SRC + textwrap.indent(textwrap.dedent("""
        def bad_read(self):
            return self._count

        def bad_call(self):
            self.helper()
    """), " " * 8)
    findings = lint_src(tmp_path, src, "DPG004")
    msgs = "\n".join(f.message for f in findings)
    assert "read of self._count outside `with self._lock`" in msgs
    assert "call to self.helper() outside `with self._lock`" in msgs
    assert len(findings) == 2


def test_dpg004_suppression(tmp_path):
    src = LOCKED_SRC + textwrap.indent(textwrap.dedent("""
        def snapshot(self):
            # single-threaded init phase. dpgolint: disable=DPG004
            return self._count
    """), " " * 8)
    assert lint_src(tmp_path, src, "DPG004") == []


def test_dpg004_inconsistent_lock_order_flagged(tmp_path):
    src = """
        import threading

        class Two:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """
    findings = lint_src(tmp_path, src, "DPG004")
    assert len(findings) == 1
    assert "inconsistent lock order" in findings[0].message


# ---------------------------------------------------------------------------
# DPG005 wire-schema symmetry
# ---------------------------------------------------------------------------

WIRE_CFG = Config(options={"DPG005": {"per_file": {"*": {
    "pack_functions": ["pack_v1", "pack_v2"],
    "unpack_functions": ["unpack_v1", "unpack_v2"],
}}}})

WIRE_OK = """
    import numpy as np

    KEY = "_meta"

    def pack_v1(prefix, d):
        out = {f"{prefix}_{r}_{p}": v for (r, p), v in d.items()}
        out[KEY] = np.asarray([1])
        return out

    def pack_v2(prefix, robots, poses, vals):
        return {f"{prefix}:r": robots, f"{prefix}:p": poses,
                f"{prefix}:x": vals}

    def unpack_v1(frame, prefix):
        meta = frame.get(KEY)
        return {k: v for k, v in frame.items()
                if k.startswith(prefix + "_")}, meta

    def unpack_v2(frame, prefix):
        if f"{prefix}:r" not in frame:
            return None
        return frame[f"{prefix}:r"], frame[f"{prefix}:p"], \\
            frame[f"{prefix}:x"]
"""


def test_dpg005_symmetric_codecs_pass(tmp_path):
    assert lint_src(tmp_path, WIRE_OK, "DPG005", WIRE_CFG) == []


def test_dpg005_pack_only_key_flagged_in_v2_codec(tmp_path):
    src = WIRE_OK.replace(
        'return {f"{prefix}:r": robots, f"{prefix}:p": poses,',
        'return {f"{prefix}:zz": 0, f"{prefix}:r": robots, '
        'f"{prefix}:p": poses,')
    findings = lint_src(tmp_path, src, "DPG005", WIRE_CFG)
    assert len(findings) == 1
    assert "'*:zz' is packed but never unpacked" in findings[0].message


def test_dpg005_unpack_only_key_flagged_in_v1_codec(tmp_path):
    src = WIRE_OK.replace("meta = frame.get(KEY)",
                          "meta = frame.get(KEY)\n"
                          "        legacy = frame.pop('_legacy', None)")
    findings = lint_src(tmp_path, src, "DPG005", WIRE_CFG)
    assert len(findings) == 1
    assert "'_legacy' is unpacked but never packed" in findings[0].message


def test_dpg005_suppression(tmp_path):
    src = WIRE_OK.replace(
        "out[KEY] = np.asarray([1])",
        "out[KEY] = np.asarray([1])\n"
        "        out['_v3_future'] = 0  # dpgolint: disable=DPG005")
    assert lint_src(tmp_path, src, "DPG005", WIRE_CFG) == []


# ---------------------------------------------------------------------------
# Project self-check + seeded-violation smoke (the acceptance criteria)
# ---------------------------------------------------------------------------

def test_project_tree_is_clean_under_all_passes(monkeypatch):
    monkeypatch.chdir(REPO)
    findings = run_lint(["dpgo_tpu", "tools"], project_config())
    assert findings == [], "\n".join(
        f"{f.location}: {f.rule} {f.message}" for f in findings)


def test_seeded_violations_fail_with_rule_and_location(tmp_path):
    """Acceptance smoke: an unguarded HealthMonitor() and an unlocked
    guarded-attribute write seeded into a copy of the real serving plane
    must fail citing DPG002/DPG004 with file:line."""
    serve = tmp_path / "dpgo_tpu" / "serve"
    serve.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "serve" / "server.py").read_text()
    bad = src.replace(
        "self.cache = ExecutableCache(disk=disk)",
        "self.cache = ExecutableCache(disk=disk)\n"
        "        from ..obs.health import HealthMonitor\n"
        "        self._boom = HealthMonitor(None)")
    bad = bad.replace(
        "        with self._cond:\n            self._n_shed += 1",
        "        self._n_shed += 1")
    assert bad != src
    (serve / "server.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    rules = {f.rule for f in findings}
    assert "DPG002" in rules and "DPG004" in rules, findings
    for f in findings:
        assert f.path.endswith("serve/server.py") and f.line > 0


def test_cli_clean_tree_exits_zero_and_json_shape():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dpgolint", "dpgo_tpu", "tools",
         "--format", "json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["count"] == 0 and out["findings"] == []


def test_cli_baseline_accepts_known_findings(tmp_path):
    # Project-shaped path so the scoped DPG001 pass applies to it.
    mod = tmp_path / "dpgo_tpu" / "models" / "rbcd.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(textwrap.dedent("""
        import jax
        import time

        @jax.jit
        def entry(x):
            time.time()
            return x
    """))
    env = dict(os.environ, PYTHONPATH=str(REPO))
    cmd = [sys.executable, "-m", "tools.dpgolint",
           str(tmp_path / "dpgo_tpu"),
           "--baseline", str(tmp_path / "baseline.json")]
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          env=env, timeout=120)
    assert proc.returncode == 1 and "DPG001" in proc.stdout
    # Accept the debt, then the same tree passes.
    subprocess.run(cmd + ["--write-baseline"], cwd=REPO, check=True,
                   capture_output=True, env=env, timeout=120)
    proc2 = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           env=env, timeout=120)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr


# ---------------------------------------------------------------------------
# leakcheck plugin
# ---------------------------------------------------------------------------

def test_leakcheck_fails_leaking_test_and_passes_clean(tmp_path):
    """A deliberately-leaking fixture test (open socket kept alive) must
    fail under ``-p tests.plugins.leakcheck``; a clean test and an
    ``allow_leaks``-marked one must pass."""
    (tmp_path / "test_fixture_leaks.py").write_text(textwrap.dedent("""
        import socket
        import pytest

        def test_leaky():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            globals()["_keep"] = s      # never closed

        def test_clean():
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", 0))
            finally:
                s.close()

        @pytest.mark.allow_leaks(reason="fixture exercising the opt-out")
        def test_opted_out():
            s = socket.socket()
            globals()["_keep2"] = s
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "test_fixture_leaks.py", "-q",
         "-p", "tests.plugins.leakcheck", "-p", "no:cacheprovider"],
        cwd=tmp_path, env=dict(os.environ, PYTHONPATH=str(REPO)),
        capture_output=True, text=True, timeout=180)
    assert proc.returncode != 0
    assert "test_leaky" in proc.stdout and "leakcheck" in proc.stdout
    assert "sockets still open" in proc.stdout
    # exactly one error (the leak); clean + opted-out tests pass
    assert "3 passed, 1 error" in proc.stdout, proc.stdout


def test_seeded_verdict_loop_sync_violations(tmp_path):
    """ISSUE-9 seams: the device-resident driver's ONE sanctioned
    verdict-word fetch rides a reviewed suppression — but (a) a NEW
    ``_host_fetch`` call seeded into the verdict hot loop and (b) the
    same seeded into ``run_bucket``'s dispatch loop must be flagged by
    DPG003 via the configured ``sync_calls`` seam list, with file:line."""
    # (a) models/rbcd.py: unsanctioned extra fetch in _run_verdict_loop.
    mdir = tmp_path / "dpgo_tpu" / "models"
    mdir.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "models" / "rbcd.py").read_text()
    bad = src.replace(
        "            n_pre = len(eval_its)\n\n    cost_hist",
        "            n_pre = len(eval_its)\n"
        "            _dbg = _host_fetch(state.X)\n\n    cost_hist")
    assert bad != src
    (mdir / "rbcd.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    hits = [f for f in findings if f.rule == "DPG003"
            and "sync seam" in f.message]
    assert hits, findings
    assert all(f.path.endswith("models/rbcd.py") and f.line > 0
               for f in hits)

    # (b) serve/runner.py: unsanctioned fetch inside the bucket loop.
    sdir = tmp_path / "b" / "dpgo_tpu" / "serve"
    sdir.mkdir(parents=True)
    rsrc = (REPO / "dpgo_tpu" / "serve" / "runner.py").read_text()
    rbad = rsrc.replace(
        "            all_terminal = ",
        "            _dbg = rbcd._host_fetch(hist)\n"
        "            all_terminal = ")
    assert rbad != rsrc
    (sdir / "runner.py").write_text(rbad)
    findings = run_lint([str(tmp_path / "b" / "dpgo_tpu")],
                        project_config())
    hits = [f for f in findings if f.rule == "DPG003"
            and "sync seam" in f.message]
    assert hits, findings
    assert all(f.path.endswith("serve/runner.py") for f in hits)


def test_seeded_sharded_gn_tail_sync_violation(tmp_path):
    """ISSUE-11 seam: the sharded GN tail's outer loop reads exactly one
    gate scalar + one stats vector per outer step through the sanctioned
    ``rbcd._host_fetch`` seam — a NEW ``_host_fetch`` call seeded into
    that loop must be flagged by DPG003 via the configured ``sync_calls``
    list, with file:line."""
    pdir = tmp_path / "dpgo_tpu" / "parallel"
    pdir.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "parallel" / "sharded.py").read_text()
    bad = src.replace(
        "            cost_hist.append(f_new)\n            X = X_new",
        "            cost_hist.append(f_new)\n"
        "            _dbg = rbcd._host_fetch(X_new)\n"
        "            X = X_new")
    assert bad != src
    (pdir / "sharded.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    hits = [f for f in findings if f.rule == "DPG003"
            and "sync seam" in f.message]
    assert hits, findings
    assert all(f.path.endswith("parallel/sharded.py") and f.line > 0
               for f in hits)


def test_sanctioned_sharded_gn_tail_fetches_stay_suppressed(tmp_path):
    """The two reviewed GN-tail fetch sites (gate scalar, per-outer
    stats) must remain suppressed on the real tree: stripping either
    suppression makes DPG003 fire at that site."""
    src = (REPO / "dpgo_tpu" / "parallel" / "sharded.py").read_text()
    for marker in (
            "            # dpgolint: disable=DPG003 -- sanctioned "
            "GN-tail gate fetch\n",
            "            # dpgolint: disable=DPG003 -- sanctioned "
            "per-outer stats fetch\n"):
        stripped = src.replace(marker, "")
        assert stripped != src, marker
        pdir = tmp_path / marker.split()[-2] / "dpgo_tpu" / "parallel"
        pdir.mkdir(parents=True)
        (pdir / "sharded.py").write_text(stripped)
        findings = run_lint([str(pdir.parent.parent / "dpgo_tpu")],
                            project_config())
        assert any(f.rule == "DPG003" and "_host_fetch" in f.message
                   for f in findings), (marker, findings)


def test_seeded_resilience_checkpoint_sync_violation(tmp_path):
    """ISSUE-14 seam: the checkpoint gather is the resilience layer's
    ONE sanctioned device->host transfer — a NEW ``_host_fetch`` call
    seeded into the ``checkpoint_arrays`` field loop must be flagged by
    DPG003 via the configured ``sync_calls`` list, with file:line."""
    pdir = tmp_path / "dpgo_tpu" / "parallel"
    pdir.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "parallel" / "resilience.py").read_text()
    bad = src.replace(
        "        host[f] = _host_fetch(v)",
        "        host[f] = _host_fetch(v)\n"
        "        _dbg = _host_fetch(v)")
    assert bad != src
    (pdir / "resilience.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    hits = [f for f in findings if f.rule == "DPG003"
            and "sync seam" in f.message]
    assert hits, findings
    assert all(f.path.endswith("parallel/resilience.py") and f.line > 0
               for f in hits)


def test_sanctioned_resilience_checkpoint_gather_stays_suppressed(
        tmp_path):
    """The reviewed checkpoint-gather fetch must remain suppressed on
    the real tree: stripping the suppression makes DPG003 fire at that
    site, and the real module lints clean under the full policy."""
    src = (REPO / "dpgo_tpu" / "parallel" / "resilience.py").read_text()
    marker = ("        # dpgolint: disable=DPG003 -- sanctioned mesh "
              "checkpoint gather\n")
    stripped = src.replace(marker, "")
    assert stripped != src
    pdir = tmp_path / "dpgo_tpu" / "parallel"
    pdir.mkdir(parents=True)
    (pdir / "resilience.py").write_text(stripped)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    assert any(f.rule == "DPG003" and "_host_fetch" in f.message
               and f.path.endswith("parallel/resilience.py")
               for f in findings), findings


def test_sanctioned_verdict_fetches_stay_suppressed(monkeypatch):
    """The three reviewed verdict-loop fetch sites (word, lazy history,
    terminal bookkeeping) must remain suppressed on the real tree — the
    clean-tree check above covers it, but pin the intent: stripping any
    one suppression makes DPG003 fire at that site."""
    src = (REPO / "dpgo_tpu" / "models" / "rbcd.py").read_text()
    stripped = src.replace(
        "            # dpgolint: disable=DPG003 -- sanctioned "
        "verdict-word fetch\n", "")
    assert stripped != src
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        mdir = os.path.join(td, "dpgo_tpu", "models")
        os.makedirs(mdir)
        with open(os.path.join(mdir, "rbcd.py"), "w") as fh:
            fh.write(stripped)
        findings = run_lint([os.path.join(td, "dpgo_tpu")],
                            project_config())
    assert any(f.rule == "DPG003" and "_host_fetch" in f.message
               for f in findings), findings


def test_project_policy_covers_fleet_subpackage():
    """The serve/fleet sub-subpackage (ISSUE 13) sits one directory level
    deeper than the rest of the tree: pin that the project policy's
    DPG002 globs reach it and that DPG004 (run-everywhere) applies, and
    that the real fleet modules lint clean under the full policy."""
    cfg = project_config()
    for rel in ("dpgo_tpu/serve/fleet/router.py",
                "dpgo_tpu/serve/fleet/manager.py",
                "dpgo_tpu/serve/fleet/aotcache.py"):
        assert cfg.applies("DPG002", rel), rel
        assert cfg.applies("DPG004", rel), rel
    findings = run_lint([str(REPO / "dpgo_tpu" / "serve" / "fleet")],
                        project_config())
    assert findings == [], findings


def test_seeded_multihost_lockstep_sync_violation(tmp_path):
    """ISSUE-17 seam: the multi-host lockstep trades ONLY host bytes —
    ``verdict_sync`` rides the word the driver already fetched, so a
    ``_host_fetch`` call seeded into a loop inside it must be flagged by
    DPG003 via the configured ``sync_calls`` list, with file:line."""
    pdir = tmp_path / "dpgo_tpu" / "parallel"
    pdir.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "parallel" / "multihost.py").read_text()
    bad = src.replace(
        "        self.boundaries += 1\n        run = obs.get_run()",
        "        for _v in (it,):\n"
        "            _dbg = _host_fetch(_v)\n"
        "        self.boundaries += 1\n        run = obs.get_run()")
    assert bad != src
    (pdir / "multihost.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    hits = [f for f in findings if f.rule == "DPG003"
            and "sync seam" in f.message]
    assert hits, findings
    assert all(f.path.endswith("parallel/multihost.py") and f.line > 0
               for f in hits)


def test_seeded_proc_fleet_heartbeat_sync_violation(tmp_path):
    """ISSUE-17 seam: the parent-side pump/heartbeat threads are
    host-only — an ad-hoc ``_rpc`` or a numpy materialization seeded
    into the heartbeat's poll loop must be flagged by DPG003 under the
    ``serve/fleet/procs.py`` scope (both classifiers: the configured
    ``_rpc`` sync seam and the ``np.asarray`` fetcher)."""
    fdir = tmp_path / "dpgo_tpu" / "serve" / "fleet"
    fdir.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "serve" / "fleet" / "procs.py").read_text()
    bad = src.replace(
        "            st = self._beat_once()",
        "            _dbg = self._rpc({\"op\": 0}, timeout=0.1)\n"
        "            _mat = np.asarray(_dbg)\n"
        "            st = self._beat_once()")
    assert bad != src
    (fdir / "procs.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    hits = [f for f in findings if f.rule == "DPG003"
            and f.path.endswith("serve/fleet/procs.py")]
    assert any("sync seam" in f.message for f in hits), findings
    assert any("np.asarray" in f.message for f in hits), findings
    assert all(f.line > 0 for f in hits)


def test_measurements_codec_symmetry_under_dpg005(tmp_path):
    """ISSUE-17 wire vocabulary: ``pack_measurements`` /
    ``unpack_measurements`` (the columnar payload the out-of-process
    replicas solve from) participate in DPG005's symmetry check — a
    pack-only key seeded into the codec is flagged, and the real module
    stays symmetric under the project policy."""
    cfg = project_config()
    for rel in ("dpgo_tpu/parallel/multihost.py",
                "dpgo_tpu/serve/fleet/procs.py"):
        # DPG002 via the package globs, DPG004 everywhere (procs.py's
        # process-table locks carry # guarded-by: annotations), DPG003
        # via the explicit hot-path scope.
        assert cfg.applies("DPG002", rel), rel
        assert cfg.applies("DPG003", rel), rel
        assert cfg.applies("DPG004", rel), rel
    opts = cfg.file_options("DPG005", "dpgo_tpu/comms/protocol.py")
    assert "pack_measurements" in opts["pack_functions"]
    assert "unpack_measurements" in opts["unpack_functions"]

    cdir = tmp_path / "dpgo_tpu" / "comms"
    cdir.mkdir(parents=True)
    src = (REPO / "dpgo_tpu" / "comms" / "protocol.py").read_text()
    bad = src.replace(
        '        f"{prefix}:d": np.int32(meas.d),',
        '        f"{prefix}:zz": np.int32(0),\n'
        '        f"{prefix}:d": np.int32(meas.d),')
    assert bad != src
    (cdir / "protocol.py").write_text(bad)
    findings = run_lint([str(tmp_path / "dpgo_tpu")], project_config())
    hits = [f for f in findings if f.rule == "DPG005"]
    assert any("'*:zz' is packed but never unpacked" in f.message
               for f in hits), findings
