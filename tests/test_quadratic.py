"""Tests for the edge-list quadratic cost against autodiff and dense algebra."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.ops import quadratic
from dpgo_tpu.types import edge_set_from_measurements
from synthetic import make_measurements


@pytest.fixture
def small_problem(rng):
    meas, truth = make_measurements(rng, n=12, d=3, num_lc=6,
                                    rot_noise=0.05, trans_noise=0.05)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    return meas, edges, truth


def random_X(rng, n, r, d):
    return jnp.asarray(rng.standard_normal((n, r, d + 1)))


def test_egrad_matches_autodiff(rng, small_problem):
    meas, edges, _ = small_problem
    n, r, d = meas.num_poses, 5, meas.d
    X = random_X(rng, n, r, d)
    g = quadratic.egrad(X, edges)
    g_ad = jax.grad(lambda X: quadratic.cost(X, edges))(X)
    assert np.allclose(g, g_ad, atol=1e-10)


def test_hessvec_is_gradient_of_quadratic(rng, small_problem):
    meas, edges, _ = small_problem
    n, r, d = meas.num_poses, 5, meas.d
    # All edges private (single buffer): H V == egrad(V) since the cost is
    # purely quadratic (gradient linear, no constant term).
    V = random_X(rng, n, r, d)
    hv = quadratic.hessvec(V, edges, n_buf=n)
    gv = quadratic.egrad(V, edges)
    assert np.allclose(hv, gv, atol=1e-10)
    # Linearity + symmetry <HU, V> == <U, HV>.
    U = random_X(rng, n, r, d)
    lhs = float(jnp.sum(quadratic.hessvec(U, edges, n) * V))
    rhs = float(jnp.sum(U * quadratic.hessvec(V, edges, n)))
    assert abs(lhs - rhs) < 1e-8 * max(1.0, abs(lhs))


def test_cost_halves_connection_laplacian_quadratic(rng, small_problem):
    # f(X) = 0.5 <X H, X> with H the Hessian: for quadratic f with zero
    # linear term, f(X) = 0.5 <hessvec(X), X>.
    meas, edges, _ = small_problem
    X = random_X(rng, meas.num_poses, 5, meas.d)
    f = float(quadratic.cost(X, edges))
    q = 0.5 * float(jnp.sum(quadratic.hessvec(X, edges, meas.num_poses) * X))
    assert np.isclose(f, q, rtol=1e-12)


def test_diag_blocks_match_dense_hessian(rng, small_problem):
    meas, edges, _ = small_problem
    n, r, d = meas.num_poses, 3, meas.d
    dh = d + 1
    # Dense Hessian via jacobian of the (linear) gradient map, restricted to
    # one r-row (the Hessian acts identically on each row of X).
    def grad_row(xrow):
        X = xrow.reshape(n, 1, dh)
        return quadratic.egrad(X, edges).reshape(-1)

    H = jax.jacobian(grad_row)(jnp.zeros(n * dh, jnp.float64))
    blocks = quadratic.diag_blocks(edges, n)
    for k in range(n):
        expected = H[k * dh:(k + 1) * dh, k * dh:(k + 1) * dh]
        assert np.allclose(blocks[k], expected, atol=1e-10), f"pose {k}"


def test_precond_solves_blocks(rng, small_problem):
    meas, edges, _ = small_problem
    n, r = meas.num_poses, 5
    shift = 0.1
    blocks = quadratic.diag_blocks(edges, n)
    chol = quadratic.precond_factors(blocks, shift)
    V = random_X(rng, n, r, meas.d)
    Z = quadratic.precond_apply(chol, V)
    # Z_pose (B + shift I) == V_pose
    dh = meas.d + 1
    for k in range(n):
        Bs = np.asarray(blocks[k]) + shift * np.eye(dh)
        assert np.allclose(np.asarray(Z[k]) @ Bs, np.asarray(V[k]), atol=1e-8)


def test_shared_edge_gradient_treats_neighbor_as_constant(rng):
    # Build a 2-pose buffer where pose 1 is a "neighbor" (fixed): gradient of
    # the local slot must match autodiff wrt the local slot only, and
    # hessvec must ignore the neighbor slot.
    meas, _ = make_measurements(rng, n=2, d=3, num_lc=0)
    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    r = 5
    Xbuf = jnp.asarray(rng.standard_normal((2, r, 4)))

    g_local = quadratic.egrad(Xbuf, edges, n_out=1)
    g_ad = jax.grad(
        lambda x0: quadratic.cost(jnp.concatenate([x0[None], Xbuf[1:]], 0), edges)
    )(Xbuf[0])
    assert np.allclose(g_local[0], g_ad, atol=1e-10)

    V = jnp.asarray(rng.standard_normal((1, r, 4)))
    hv = quadratic.hessvec(V, edges, n_buf=2)
    # Hessian of the local block for edge 0->1 with pose 0 local: B_ii.
    blocks = quadratic.diag_blocks(edges, 2)
    expected = jnp.einsum("rd,de->re", V[0], blocks[0])
    assert np.allclose(hv[0], expected, atol=1e-10)


def test_masked_edges_contribute_nothing(rng, small_problem):
    meas, edges, _ = small_problem
    n = meas.num_poses
    X = random_X(rng, n, 5, meas.d)
    f0 = float(quadratic.cost(X, edges))
    # Append garbage padding edges with mask 0.
    pad = edges._replace(
        i=jnp.concatenate([edges.i, jnp.array([0, 1], jnp.int32)]),
        j=jnp.concatenate([edges.j, jnp.array([2, 3], jnp.int32)]),
        R=jnp.concatenate([edges.R, 100.0 * jnp.ones((2, 3, 3), jnp.float64)]),
        t=jnp.concatenate([edges.t, 100.0 * jnp.ones((2, 3), jnp.float64)]),
        kappa=jnp.concatenate([edges.kappa, jnp.ones(2, jnp.float64)]),
        tau=jnp.concatenate([edges.tau, jnp.ones(2, jnp.float64)]),
        weight=jnp.concatenate([edges.weight, jnp.ones(2, jnp.float64)]),
        mask=jnp.concatenate([edges.mask, jnp.zeros(2, jnp.float64)]),
        is_lc=jnp.concatenate([edges.is_lc, jnp.ones(2, jnp.float64)]),
        fixed_weight=jnp.concatenate([edges.fixed_weight, jnp.zeros(2, jnp.float64)]),
    )
    assert np.isclose(float(quadratic.cost(X, pad)), f0, rtol=1e-14)
    assert np.allclose(quadratic.egrad(X, pad), quadratic.egrad(X, edges), atol=1e-12)
