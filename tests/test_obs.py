"""Run-scoped telemetry subsystem (``dpgo_tpu.obs``): metrics registry,
JSONL event stream, exporters, report CLI, and the instrumented solver /
agent hot paths — including the zero-overhead telemetry-off contract."""

import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.obs import run as run_mod
from dpgo_tpu.obs.events import EventStream, metric_record, read_events
from dpgo_tpu.obs.exporters import (to_prometheus_text,
                                    write_tensorboard_scalars)
from dpgo_tpu.obs.metrics import MetricsRegistry
from dpgo_tpu.obs.report import main as report_main


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    """Every test starts and ends with telemetry off."""
    obs.end_run()
    yield
    obs.end_run()


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_with_labels():
    reg = MetricsRegistry()
    c = reg.counter("msgs", "messages", unit="1")
    c.inc(robot=0)
    c.inc(2, robot=0)
    c.inc(5, robot=1, neighbor=2)
    assert c.value(robot=0) == 3
    assert c.value(robot=1, neighbor=2) == 5
    assert c.value(robot=9) == 0
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("mu")
    g.set(1e-4)
    g.inc(1e-4)
    assert g.value() == pytest.approx(2e-4)

    h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05, phase="solve")
    h.observe_many([0.5, 5.0, 50.0], phase="solve")
    s = h.snapshot_series(phase="solve")
    assert s["count"] == 4
    assert s["counts"] == [1, 1, 1, 1]  # one per bucket + one overflow
    assert s["sum"] == pytest.approx(55.55)

    # Same name returns the same family; a kind change raises.
    assert reg.counter("msgs") is c
    with pytest.raises(ValueError):
        reg.gauge("msgs")

    snap = reg.snapshot()
    assert snap["msgs"]["kind"] == "counter"
    assert {"labels": {"robot": "0"}, "value": 3.0} in snap["msgs"]["series"]
    assert snap["lat"]["buckets"] == [0.1, 1.0, 10.0]
    json.dumps(snap)  # JSON-serializable end to end


def test_registry_thread_safety():
    """Concurrent increments from many threads lose nothing — the registry
    must be callable from the agent's background optimization thread."""
    reg = MetricsRegistry()
    c = reg.counter("n")
    h = reg.histogram("v", buckets=(0.5,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8000
    assert h.snapshot_series()["count"] == 8000


def test_prometheus_text_exposition():
    reg = MetricsRegistry()
    reg.counter("dpgo_msgs", "messages sent").inc(3, robot=1)
    reg.gauge("dpgo_mu").set(2.5e-4)
    h = reg.histogram("dpgo_lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(7.0)
    text = to_prometheus_text(reg)
    assert "# TYPE dpgo_msgs counter" in text
    assert '# HELP dpgo_msgs messages sent' in text
    assert 'dpgo_msgs{robot="1"} 3.0' in text
    # Label-value escaping per the text exposition format: backslash,
    # newline (previously unescaped — it split the sample line), quote.
    reg.counter("dpgo_esc").inc(1, path='a\\b\n"c"')
    esc = to_prometheus_text(reg)
    assert 'dpgo_esc{path="a\\\\b\\n\\"c\\""} 1.0' in esc
    assert "\na" not in esc.split("dpgo_esc", 1)[1].split("\n")[0]
    assert "# TYPE dpgo_lat histogram" in text
    # Cumulative buckets and the +Inf tail.
    assert 'dpgo_lat_bucket{le="0.1"} 1' in text
    assert 'dpgo_lat_bucket{le="1.0"} 2' in text
    assert 'dpgo_lat_bucket{le="+Inf"} 3' in text
    assert "dpgo_lat_count 3" in text
    assert text.endswith("\n")


# ---------------------------------------------------------------------------
# Event stream + shared metric schema
# ---------------------------------------------------------------------------

def test_event_stream_correlation_fields(tmp_path):
    path = str(tmp_path / "events.jsonl")
    es = EventStream(path, run_id="runabc")
    es.emit("alpha", phase="solve", iteration=3)
    es.metric("cost", 1.5, "1", phase="eval", iteration=4)
    es.close()
    evs = read_events(path)
    assert [e["event"] for e in evs] == ["alpha", "metric"]
    for e in evs:
        assert e["run"] == "runabc"
        assert isinstance(e["t_wall"], float)
        assert isinstance(e["t_mono"], float)
    assert [e["seq"] for e in evs] == [0, 1]
    m = evs[1]
    # The in-stream metric event carries the shared schema keys.
    assert (m["metric"], m["value"], m["unit"]) == ("cost", 1.5, "1")
    # Closed stream: emit is a no-op, not a crash.
    es.emit("late")
    assert len(read_events(path)) == 2


def test_metric_record_matches_bench_schema():
    """``bench.py``'s final line and telemetry metric events share one
    record shape: ``metric``/``value``/``unit`` leading keys — the same
    key set BENCH_r0*.json archives."""
    rec = metric_record("rbcd_rounds_per_sec", 1146.2, "rounds/s",
                        vs_baseline=33.4)
    assert list(rec)[:3] == ["metric", "value", "unit"]
    assert rec["vs_baseline"] == 33.4
    # Non-finite floats and numpy scalars serialize cleanly, on the one
    # canonical (Prometheus-style) spelling.
    rec2 = metric_record("m", np.float64(2.0), extra=float("inf"))
    assert rec2["value"] == 2.0 and rec2["extra"] == "+Inf"
    json.dumps(rec2)


def test_event_payloads_coerce_numpy(tmp_path):
    es = EventStream(str(tmp_path / "e.jsonl"), "r")
    es.emit("x", arr=np.arange(3), scalar=np.float32(1.5),
            nested={"a": np.int64(2)}, nan=float("nan"),
            pinf=float("inf"), ninf=float("-inf"))
    es.close()
    # On disk: the canonical non-finite strings (valid JSON).
    raw = json.loads(open(str(tmp_path / "e.jsonl")).readline())
    assert raw["nan"] == "NaN"
    assert raw["pinf"] == "+Inf" and raw["ninf"] == "-Inf"
    # Through read_events: restored to real floats (the round-trip).
    (ev,) = read_events(str(tmp_path / "e.jsonl"))
    assert ev["arr"] == [0, 1, 2]
    assert ev["scalar"] == 1.5
    assert ev["nested"] == {"a": 2}
    import math

    assert math.isnan(ev["nan"])
    assert ev["pinf"] == float("inf") and ev["ninf"] == float("-inf")


def test_nonfinite_convention_unified_across_snapshot_and_prometheus():
    """The metrics snapshot and the Prometheus exposition spell non-finite
    values identically (the satellite: metrics.py stringified str(float)
    while the exporter emitted NaN/+Inf)."""
    reg = MetricsRegistry()
    reg.gauge("g_nan").set(float("nan"))
    reg.gauge("g_inf").set(float("inf"))
    snap = reg.snapshot()
    assert snap["g_nan"]["series"][0]["value"] == "NaN"
    assert snap["g_inf"]["series"][0]["value"] == "+Inf"
    text = to_prometheus_text(reg)
    assert "g_nan NaN" in text
    assert "g_inf +Inf" in text
    json.dumps(snap)


# ---------------------------------------------------------------------------
# Run scoping + artifacts
# ---------------------------------------------------------------------------

def test_run_scope_writes_artifacts(tmp_path):
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        assert obs.get_run() is run
        run.counter("things").inc(7)
        run.event("hello", phase="setup")
    assert obs.get_run() is None
    assert run.closed
    evs = read_events(os.path.join(d, "events.jsonl"))
    assert [e["event"] for e in evs] == ["run_start", "hello", "run_end"]
    snap = json.load(open(os.path.join(d, "metrics.json")))
    assert snap["run"] == run.run_id
    assert snap["metrics"]["things"]["series"][0]["value"] == 7.0
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "things 7.0" in prom
    meta = json.load(open(os.path.join(d, "run.json")))
    assert meta["run"] == run.run_id


def test_start_run_refuses_overlap(tmp_path):
    obs.start_run(str(tmp_path / "a"))
    with pytest.raises(RuntimeError, match="already active"):
        obs.start_run(str(tmp_path / "b"))
    obs.end_run()
    assert obs.get_run() is None
    obs.end_run()  # idempotent


def test_report_cli(tmp_path, capsys):
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        run.metric("solver_cost", 10.0, phase="eval", iteration=1)
        run.metric("solver_cost", 2.0, phase="eval", iteration=5)
        run.event("phase_timings", timings={
            "solve": {"total_s": 1.0, "count": 4, "avg_ms": 250.0}})
        run.histogram("round_latency_seconds").observe(0.01)
    rc = report_main([d])
    out = capsys.readouterr().out
    assert rc == 0
    assert "solver_cost: 2 points, first 10, last 2" in out
    assert "solve: 1.0000s / 4 (250.00 ms avg)" in out
    assert "round_latency_seconds" in out
    assert report_main([str(tmp_path / "missing")]) == 2


def test_tensorboard_export_is_optional(tmp_path):
    """No TensorBoard writer in the environment => graceful None (and if
    one exists, a logdir comes back) — never an ImportError."""
    events = [metric_record("m", 1.0) | {"event": "metric", "seq": 0}]
    out = write_tensorboard_scalars(str(tmp_path), events)
    assert out is None or os.path.isdir(out)


# ---------------------------------------------------------------------------
# Instrumented hot paths
# ---------------------------------------------------------------------------

def _tiny_problem(n=40, num_lc=20, seed=0):
    from dpgo_tpu.utils.synthetic import make_measurements

    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=num_lc, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def test_solve_rbcd_telemetry_stream(tmp_path):
    """A telemetry-on multi-agent solve yields the full acceptance signal
    set: per-iteration cost/grad-norm events, GNC mu trajectory, per-agent
    round latency + relative change, and round counters."""
    from dpgo_tpu.config import (AgentParams, RobustCostParams,
                                 RobustCostType)
    from dpgo_tpu.models import rbcd

    meas = _tiny_problem()
    params = AgentParams(
        d=3, r=5, num_robots=2,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        robust_opt_inner_iters=4)
    d = str(tmp_path / "run")
    with obs.run_scope(d):
        res = rbcd.solve_rbcd(meas, 2, params=params, max_iters=8,
                              eval_every=2, grad_norm_tol=1e-9,
                              dtype=jnp.float64)
    evs = read_events(os.path.join(d, "events.jsonl"))
    kinds = {e["event"] for e in evs}
    assert {"run_start", "solve_start", "metric", "solve_end",
            "run_end"} <= kinds

    costs = [e for e in evs if e.get("metric") == "solver_cost"]
    gns = [e for e in evs if e.get("metric") == "solver_grad_norm"]
    mus = [e for e in evs if e.get("metric") == "gnc_mu"]
    assert len(costs) == len(res.cost_history)
    assert [e["value"] for e in costs] == pytest.approx(res.cost_history)
    assert [e["value"] for e in gns] == pytest.approx(
        res.grad_norm_history)
    assert mus and all(m["value"] > 0 for m in mus)
    assert all("iteration" in e for e in costs)
    # mu anneals across the weight-update schedule (strictly increasing).
    mu_vals = [m["value"] for m in mus]
    assert mu_vals == sorted(mu_vals)

    (end,) = [e for e in evs if e["event"] == "solve_end"]
    assert end["iterations"] == res.iterations
    assert end["terminated_by"] == res.terminated_by

    snap = json.load(open(os.path.join(d, "metrics.json")))["metrics"]
    assert snap["solver_rounds"]["series"][0]["value"] == res.iterations
    lat = {tuple(sorted(s["labels"].items())): s["value"]
           for s in snap["agent_round_latency_seconds"]["series"]}
    assert len(lat) == 2 and all(v > 0 for v in lat.values())
    assert len(snap["agent_rel_change"]["series"]) == 2
    assert snap["round_latency_seconds"]["kind"] == "histogram"


def test_agent_telemetry_comms_gnc_and_lifecycle(tmp_path):
    """The deployment surface: per-neighbor message/byte counters, iterate
    latency + events, GNC weight histogram, and lifecycle transitions."""
    from test_agent import exchange, make_agents
    from dpgo_tpu.config import RobustCostParams, RobustCostType

    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        agents, part, _ = make_agents(
            2, n=12, num_lc=6,
            robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
            robust_opt_inner_iters=2)
        for _ in range(4):
            exchange(agents)
            for ag in agents:
                ag.iterate()
        snap = run.registry.snapshot()
    evs = read_events(os.path.join(d, "events.jsonl"))

    # Lifecycle: both agents reached INITIALIZED (robot 1 via frame
    # alignment after the first pose message).
    states = [(e["robot"], e["state"]) for e in evs
              if e["event"] == "agent_state"]
    assert (0, "INITIALIZED") in states and (1, "INITIALIZED") in states

    # Comms: bytes + messages per direction, receives labeled by neighbor.
    rx = {tuple(sorted(s["labels"].items())): s["value"]
          for s in snap["comms_bytes_received"]["series"]}
    assert (("neighbor", "0"), ("robot", "1")) in rx
    assert (("neighbor", "1"), ("robot", "0")) in rx
    assert all(v > 0 for v in rx.values())
    sent = snap["comms_bytes_sent"]["series"]
    assert len(sent) == 2 and all(s["value"] > 0 for s in sent)
    n_pub = len(agents[0].get_shared_pose_dict())
    r, dd = agents[0].r, agents[0].d
    per_msg = n_pub * r * (dd + 1) * 8  # float64 pose blocks
    got = next(s["value"] for s in sent
               if s["labels"] == {"robot": "0"})
    assert got % per_msg == 0

    # Iterate: latency histogram + per-robot events with iteration numbers.
    its = [e for e in evs if e["event"] == "agent_iterate"]
    assert {e["robot"] for e in its} == {0, 1}
    assert all(e["latency_s"] > 0 for e in its)
    assert snap["agent_iterate_seconds"]["series"]

    # GNC: a weight update happened (inner_iters=2 over 4 rounds) and the
    # weight histogram saw every updatable loop closure.
    gnc = [e for e in evs if e["event"] == "metric"
           and e["metric"] == "gnc_mu"]
    assert gnc and all(e["inlier_fraction"] >= 0 for e in gnc)
    wh = snap["gnc_weight"]["series"]
    assert wh and all(s["count"] > 0 for s in wh)


def test_certificate_telemetry(tmp_path):
    from dpgo_tpu.models import certify, local_pgo

    meas = _tiny_problem(n=20, num_lc=8)
    from dpgo_tpu.types import edge_set_from_measurements

    edges = edge_set_from_measurements(meas, dtype=jnp.float64)
    res = local_pgo.solve_local(meas, rank=5)
    d = str(tmp_path / "run")
    with obs.run_scope(d):
        cert = certify.certify_solution(res.X, edges)
    evs = read_events(os.path.join(d, "events.jsonl"))
    (ev,) = [e for e in evs if e["event"] == "certificate"]
    assert ev["certified"] == cert.certified
    assert ev["eigenvalue_gap"] == pytest.approx(
        (cert.lambda_min_f64 if cert.lambda_min_f64 is not None
         else cert.lambda_min) + cert.tol)
    assert ev["duration_s"] > 0


def test_sharded_solve_telemetry(tmp_path):
    import jax

    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.parallel import sharded

    if not hasattr(jax, "shard_map"):
        # The sharded path itself is broken on this jax build (the seed's
        # test_sharded.py failures have the same cause); the telemetry
        # hooks can only be exercised where the solver runs.
        pytest.skip("jax.shard_map unavailable in this jax build")
    meas = _tiny_problem()
    mesh = sharded.make_mesh(2)
    params = AgentParams(d=3, r=5, num_robots=2)
    d = str(tmp_path / "run")
    with obs.run_scope(d):
        res = sharded.solve_rbcd_sharded(meas, 2, mesh=mesh, params=params,
                                         max_iters=4, eval_every=2,
                                         grad_norm_tol=1e-9,
                                         dtype=jnp.float64)
    assert res.iterations > 0
    evs = read_events(os.path.join(d, "events.jsonl"))
    (sh,) = [e for e in evs if e["event"] == "sharded_solve"]
    assert sh["mesh_size"] == 2
    assert sh["comm_bytes_per_round"] > 0
    (pt,) = [e for e in evs if e["event"] == "phase_timings"]
    assert {"build_graph", "init", "shard"} <= set(pt["timings"])
    assert all(row["count"] == 1 for row in pt["timings"].values())


# ---------------------------------------------------------------------------
# The zero-overhead contract (satellite: telemetry-off smoke test)
# ---------------------------------------------------------------------------

def test_telemetry_off_is_zero_overhead(monkeypatch):
    """With no ambient run, an instrumented solve emits ZERO events, makes
    ZERO registry calls, performs ZERO obs-owned device->host transfers in
    the RBCD round loop, constructs ZERO tracing spans, ZERO health
    detectors, and ZERO flight-recorder buffers — the instrumentation's
    only cost is the ``get_run() is None`` guard."""
    from dpgo_tpu.config import AgentParams
    from dpgo_tpu.models import rbcd
    from dpgo_tpu.obs import health as health_mod
    from dpgo_tpu.obs import metrics as metrics_mod
    from dpgo_tpu.obs import recorder as recorder_mod
    from dpgo_tpu.obs import trace as trace_mod

    def boom(*a, **kw):
        raise AssertionError("telemetry path taken while disabled")

    # Any event emission, any registry mutation, any obs-owned transfer,
    # any span/detector/recorder construction trips the failure.
    monkeypatch.setattr(EventStream, "emit", boom)
    monkeypatch.setattr(run_mod, "materialize", boom)
    monkeypatch.setattr(obs, "materialize", boom)
    monkeypatch.setattr(metrics_mod.Counter, "inc", boom)
    monkeypatch.setattr(metrics_mod.Gauge, "set", boom)
    monkeypatch.setattr(metrics_mod.Histogram, "observe_many", boom)
    monkeypatch.setattr(trace_mod.Span, "__init__", boom)
    monkeypatch.setattr(trace_mod, "emit_span", boom)
    monkeypatch.setattr(health_mod.HealthMonitor, "__init__", boom)
    monkeypatch.setattr(health_mod.HealthMonitor, "observe_solver", boom)
    monkeypatch.setattr(recorder_mod.FlightRecorder, "__init__", boom)
    monkeypatch.setattr(recorder_mod.FlightRecorder, "record_eval", boom)
    # ISSUE 16: the device-profiling layer sits behind the same fence.
    from dpgo_tpu.obs import devprof as devprof_mod
    from dpgo_tpu.obs import ledger as ledger_mod
    monkeypatch.setattr(devprof_mod.DeviceTraceWindow, "__init__", boom)
    monkeypatch.setattr(devprof_mod, "profiled_program", boom)
    monkeypatch.setattr(ledger_mod.PerfLedger, "__init__", boom)
    # ISSUE 20: the fleet-observability layer too — no sampler thread,
    # no fleet HTTP sidecar, no harvest work with telemetry off.
    from dpgo_tpu.obs import fleetobs as fleetobs_mod
    monkeypatch.setattr(fleetobs_mod.ResourceSampler, "__init__", boom)
    monkeypatch.setattr(fleetobs_mod.FleetSidecar, "__init__", boom)
    assert fleetobs_mod.start_resource_sampler() is None
    assert fleetobs_mod.attach_fleet_sidecar(
        fleetobs_mod.ServersFleetSource([])) is None
    assert fleetobs_mod.harvest_generation(None, 0, {}) is None

    assert obs.get_run() is None
    meas = _tiny_problem()
    res = rbcd.solve_rbcd(meas, 2, params=AgentParams(d=3, r=5,
                                                      num_robots=2),
                          max_iters=4, eval_every=2, grad_norm_tol=1e-9,
                          dtype=jnp.float64)
    # Consensus may terminate early on this tiny, well-conditioned problem;
    # what matters is that the solve ran and no telemetry path fired.
    assert res.iterations > 0
    assert res.cost_history


def test_telemetry_off_agent_paths(monkeypatch):
    from test_agent import exchange, make_agents
    from dpgo_tpu.obs import health as health_mod
    from dpgo_tpu.obs import trace as trace_mod

    def boom(*a, **kw):
        raise AssertionError("telemetry path taken while disabled")

    monkeypatch.setattr(EventStream, "emit", boom)
    monkeypatch.setattr(run_mod, "materialize", boom)
    monkeypatch.setattr(trace_mod.Span, "__init__", boom)
    monkeypatch.setattr(trace_mod, "emit_span", boom)
    monkeypatch.setattr(health_mod.HealthMonitor, "__init__", boom)
    monkeypatch.setattr(health_mod, "monitor_for", boom)

    agents, _part, _ = make_agents(2, n=10, num_lc=4)
    for _ in range(2):
        exchange(agents)
        for ag in agents:
            ag.iterate()
    assert all(ag.get_status().iteration_number == 2 for ag in agents)
