"""Serving plane (``dpgo_tpu.serve``): bucketing, executable cache,
batched-vs-sequential parity, admission control, warm pools, SLO
telemetry, and the zero-overhead fence."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams, Schedule
from dpgo_tpu.models import rbcd
from dpgo_tpu.serve import (BucketShape, ExecutableCache, OverCapacityError,
                            SolveRequest, SolveServer, bucket_shape_of,
                            pad_problem, problem_fingerprint, run_bucket)
from dpgo_tpu.serve.cache import fingerprint_key
from dpgo_tpu.serve.server import SolveTicket  # noqa: F401 (API surface)
from dpgo_tpu.utils.synthetic import make_measurements

PARAMS = AgentParams(d=3, r=5, num_robots=2)


def _problem(n=24, seed=0, num_lc=5):
    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=num_lc, rot_noise=0.01,
                                trans_noise=0.01)
    return meas


def _request(meas, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("max_iters", 4)
    kw.setdefault("grad_norm_tol", 1e-12)
    kw.setdefault("eval_every", 2)
    return SolveRequest(meas=meas, num_robots=2, **kw)


# ---------------------------------------------------------------------------
# The prepare/dispatch split (the run_rbcd refactor)
# ---------------------------------------------------------------------------

def test_prepare_dispatch_matches_solve_rbcd():
    meas = _problem()
    a = rbcd.solve_rbcd(meas, 2, params=PARAMS, max_iters=4,
                        grad_norm_tol=1e-12, eval_every=2)
    prob = rbcd.prepare_problem(meas, 2, params=PARAMS)
    b = rbcd.dispatch_prepared(prob, max_iters=4, grad_norm_tol=1e-12,
                               eval_every=2)
    assert a.cost_history == b.cost_history
    assert a.grad_norm_history == b.grad_norm_history
    np.testing.assert_array_equal(np.asarray(a.T), np.asarray(b.T))


def test_prepared_problem_is_reusable():
    prob = rbcd.prepare_problem(_problem(), 2, params=PARAMS)
    r1 = rbcd.dispatch_prepared(prob, max_iters=2, grad_norm_tol=1e-12)
    r2 = rbcd.dispatch_prepared(prob, max_iters=2, grad_norm_tol=1e-12)
    assert r1.cost_history == r2.cost_history


def test_dispatch_without_init_raises():
    prob = rbcd.prepare_problem(_problem(), 2, params=PARAMS, init=None)
    with pytest.raises(ValueError, match="no initial state"):
        rbcd.dispatch_prepared(prob, max_iters=2)


# ---------------------------------------------------------------------------
# Bucketing and padding
# ---------------------------------------------------------------------------

def test_bucket_shapes_coalesce_nearby_and_split_far_sizes():
    pa = rbcd.prepare_problem(_problem(n=24, seed=0), 2, params=PARAMS,
                              init=None, pallas_sel=False)
    pb = rbcd.prepare_problem(_problem(n=28, seed=1), 2, params=PARAMS,
                              init=None, pallas_sel=False)
    pc = rbcd.prepare_problem(_problem(n=200, seed=2, num_lc=40), 2,
                              params=PARAMS, init=None, pallas_sel=False)
    sa, sb = bucket_shape_of(pa, 64), bucket_shape_of(pb, 64)
    sc = bucket_shape_of(pc, 64)
    assert sa == sb  # within one quantum: same bucket
    assert sa != sc  # far apart: different bucket
    assert isinstance(sa, BucketShape)


def test_padded_batched_solve_matches_sequential():
    """A batch of mixed-size problems padded into one bucket must agree
    with per-problem solve_rbcd on costs and trajectories — padding is
    masking, not new math."""
    metas = [_problem(n=24, seed=0), _problem(n=27, seed=1, num_lc=6)]
    seq = [rbcd.solve_rbcd(m, 2, params=PARAMS, max_iters=4,
                           grad_norm_tol=1e-12, eval_every=2)
           for m in metas]
    probs = [rbcd.prepare_problem(m, 2, params=PARAMS, init=None,
                                  pallas_sel=False) for m in metas]
    shapes = [bucket_shape_of(p, 64) for p in probs]
    assert shapes[0] == shapes[1]
    padded = [pad_problem(p, shapes[0]) for p in probs]
    cache = ExecutableCache()
    results, info = run_bucket(padded, cache, max_iters=4,
                               grad_norm_tol=1e-12, eval_every=2)
    assert info["size"] == 2 and info["batch"] == 2
    for a, b in zip(seq, results):
        ra = np.asarray(a.cost_history)
        rb = np.asarray(b.cost_history)
        np.testing.assert_allclose(ra, rb, rtol=1e-8, atol=1e-12)
        np.testing.assert_allclose(np.asarray(a.T), np.asarray(b.T),
                                   atol=1e-7)
        assert a.T.shape == b.T.shape  # sliced back to the real pose count
        np.testing.assert_allclose(np.asarray(a.weights),
                                   np.asarray(b.weights), atol=1e-8)


def test_run_bucket_refuses_mixed_shapes():
    pa = rbcd.prepare_problem(_problem(n=24, seed=0), 2, params=PARAMS,
                              init=None, pallas_sel=False)
    pb = rbcd.prepare_problem(_problem(n=24, seed=1), 2, params=PARAMS,
                              init=None, pallas_sel=False)
    padded_a = pad_problem(pa, bucket_shape_of(pa, 32))
    padded_b = pad_problem(pb, bucket_shape_of(pb, 128))
    with pytest.raises(ValueError, match="never mix incompatible shapes"):
        run_bucket([padded_a, padded_b], ExecutableCache(), max_iters=1)


def test_pad_problem_rejects_too_small_bucket():
    p = rbcd.prepare_problem(_problem(n=40, seed=0), 2, params=PARAMS,
                             init=None, pallas_sel=False)
    tiny = BucketShape(n_max=1, e_max=1, s_max=1, p_max=1, k_inc=1,
                       n_total=1, num_meas=1)
    with pytest.raises(ValueError, match="smaller than problem"):
        pad_problem(p, tiny)


# ---------------------------------------------------------------------------
# The fingerprint-keyed executable cache
# ---------------------------------------------------------------------------

def test_executable_cache_identical_fingerprints_reuse():
    meta = rbcd.GraphMeta(num_robots=2, n_max=32, e_max=64, s_max=8,
                          p_max=8, d=3, rank=5)
    shape = BucketShape(32, 64, 8, 8, 8, 64, 64)
    cache = ExecutableCache()
    builds = []
    fp = problem_fingerprint(meta, PARAMS, jnp.float64, shape, 2, "segment")
    for _ in range(3):
        cache.get(problem_fingerprint(meta, PARAMS, jnp.float64, shape, 2,
                                      "segment"),
                  lambda: builds.append(1) or "exe")
    assert cache.compiles == 1 and len(builds) == 1
    assert cache.hits == 2
    # The key IS the canonical fingerprint: same content, same key.
    assert fingerprint_key(fp) == fingerprint_key(
        problem_fingerprint(meta, PARAMS, jnp.float64, shape, 2, "segment"))


def test_executable_cache_rank_dtype_schedule_miss():
    meta = rbcd.GraphMeta(num_robots=2, n_max=32, e_max=64, s_max=8,
                          p_max=8, d=3, rank=5)
    shape = BucketShape(32, 64, 8, 8, 8, 64, 64)
    cache = ExecutableCache()
    base = problem_fingerprint(meta, PARAMS, jnp.float64, shape, 2, "segment")
    cache.get(base, lambda: "exe")
    # Differing rank
    meta_r6 = rbcd.GraphMeta(num_robots=2, n_max=32, e_max=64, s_max=8,
                             p_max=8, d=3, rank=6)
    cache.get(problem_fingerprint(meta_r6, PARAMS, jnp.float64, shape, 2,
                                  "segment"), lambda: "exe-r6")
    # Differing dtype
    cache.get(problem_fingerprint(meta, PARAMS, jnp.float32, shape, 2,
                                  "segment"), lambda: "exe-f32")
    # Differing schedule
    greedy = AgentParams(d=3, r=5, num_robots=2, schedule=Schedule.GREEDY)
    cache.get(problem_fingerprint(meta, greedy, jnp.float64, shape, 2,
                                  "segment"), lambda: "exe-greedy")
    assert cache.compiles == 4 and cache.hits == 0
    # And every one of those keys is distinct.
    assert len(cache) == 4


def test_warm_pool_precompiles_bucket_executables():
    with SolveServer(max_batch=2, batch_window_s=0.005, quantum=64) as srv:
        warm_req = _request(_problem(n=24, seed=3))
        assert srv.warm([warm_req]) == 1
        compiles_after_warm = srv.cache.compiles
        assert compiles_after_warm >= 3  # segment + metrics + finalize
        res = srv.solve(_request(_problem(n=25, seed=4)), timeout=300)
        assert np.isfinite(res.cost_history[-1])
        # Same bucket, same batch width: the live request reused the
        # warmed executables — the compile counter stayed flat.
        assert srv.cache.compiles == compiles_after_warm
        assert srv.cache.hits >= 3


# ---------------------------------------------------------------------------
# Server: batching, admission control, deadlines
# ---------------------------------------------------------------------------

def test_server_concurrent_mixed_sizes_match_sequential():
    metas = [_problem(n=24 + k, seed=k) for k in range(4)]
    seq = [rbcd.solve_rbcd(m, 2, params=PARAMS, max_iters=4,
                           grad_norm_tol=1e-12, eval_every=2)
           for m in metas]
    with SolveServer(max_batch=4, batch_window_s=0.05, quantum=64) as srv:
        tickets = [srv.submit(_request(m, tenant=f"t{k % 2}"))
                   for k, m in enumerate(metas)]
        results = [t.result(timeout=300) for t in tickets]
    for a, b in zip(seq, results):
        assert abs(a.cost_history[-1] - b.cost_history[-1]) <= \
            1e-8 * max(1.0, abs(a.cost_history[-1]))
        assert np.isfinite(b.cost_history[-1])


def test_admission_queue_full_and_tenant_quota(monkeypatch):
    # Pin the worker so the queue fills deterministically.
    monkeypatch.setattr(SolveServer, "_dispatch_once",
                        lambda self: time.sleep(0.01))
    srv = SolveServer(max_batch=2, max_queue=2, tenant_quota=2,
                      batch_window_s=0.0)
    try:
        m = _problem()
        srv.submit(_request(m, tenant="a"))
        srv.submit(_request(m, tenant="b"))
        with pytest.raises(OverCapacityError) as exc:
            srv.submit(_request(m, tenant="c"))
        assert exc.value.reason == "queue"
    finally:
        srv.close()
    # Per-tenant quota, queue not full.
    monkeypatch.setattr(SolveServer, "_dispatch_once",
                        lambda self: time.sleep(0.01))
    srv = SolveServer(max_batch=2, max_queue=16, tenant_quota=1,
                      batch_window_s=0.0)
    try:
        t1 = srv.submit(_request(m, tenant="a"))
        with pytest.raises(OverCapacityError) as exc:
            srv.submit(_request(m, tenant="a"))
        assert exc.value.reason == "tenant_quota"
        srv.submit(_request(m, tenant="b"))  # other tenants unaffected
    finally:
        srv.close()
    # Close sheds whatever was still queued, with a clean reason.
    with pytest.raises(OverCapacityError) as exc:
        t1.result(timeout=5)
    assert exc.value.reason == "closed"


def test_deadline_expired_request_is_shed():
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        t = srv.submit(_request(_problem(), deadline_s=0.0))
        with pytest.raises(OverCapacityError) as exc:
            t.result(timeout=30)
        assert exc.value.reason == "deadline"


def test_bad_request_reports_instead_of_killing_worker():
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        bad = _problem()
        t = srv.submit(SolveRequest(meas=bad, num_robots=0, params=PARAMS))
        with pytest.raises(Exception):
            t.result(timeout=60)
        # The worker survived: a good request still completes.
        res = srv.solve(_request(_problem(n=24, seed=9)), timeout=300)
        assert np.isfinite(res.cost_history[-1])


# ---------------------------------------------------------------------------
# SLO telemetry and the zero-overhead fence
# ---------------------------------------------------------------------------

def test_serving_slo_metrics_and_report_section(tmp_path):
    run_dir = str(tmp_path / "serve_run")
    with obs.run_scope(run_dir):
        with SolveServer(max_batch=4, batch_window_s=0.05,
                         quantum=64) as srv:
            tickets = [srv.submit(_request(_problem(n=24 + k, seed=k),
                                           tenant=f"t{k % 2}"))
                       for k in range(3)]
            for t in tickets:
                t.result(timeout=300)
            # A shed lands in the same run.
            shed = srv.submit(_request(_problem(), deadline_s=0.0))
            with pytest.raises(OverCapacityError):
                shed.result(timeout=30)
    from dpgo_tpu.obs.report import render_report, report_data

    text = render_report(run_dir)
    assert "serving:" in text
    assert "tenant t0" in text and "latency p50" in text
    assert "shed:" in text
    data = report_data(run_dir)
    srv_stats = data["serving"]
    assert srv_stats["tenants"]["t0"]["requests"] >= 1
    assert srv_stats["tenants"]["t0"]["latency_p50_s"] is not None
    assert srv_stats["tenants"]["t0"]["latency_p99_s"] is not None
    assert srv_stats["batches"]["count"] >= 1
    assert srv_stats["batches"]["mean_occupancy"] is not None
    assert any(s["reason"] == "deadline" for s in srv_stats["shed"])
    # Histograms landed in the metrics snapshot with tenant labels.
    assert "serve_solve_latency_seconds" in data["metrics"]
    assert "serve_requests_total" in data["metrics"]


def test_telemetry_off_serving_constructs_no_obs_objects(monkeypatch, tmp_path):
    """The zero-overhead acceptance gate, extended to the serve plane:
    with no ambient run, a full submit -> batch -> result cycle must
    construct no obs objects and emit nothing — no spans, no HTTP
    sidecar threads (even with metrics_port set), no device profiler
    (even with profile_dir set), no SLO trackers, no AOT/cost_analysis
    profiling wrappers."""
    import dpgo_tpu.obs.events as events_mod
    import dpgo_tpu.obs.health as health_mod
    import dpgo_tpu.obs.metrics as metrics_mod
    import dpgo_tpu.obs.profile as profile_mod
    import dpgo_tpu.obs.run as run_mod
    import dpgo_tpu.obs.trace as trace_mod
    import dpgo_tpu.serve.server as server_mod
    import dpgo_tpu.serve.statusz as statusz_mod

    assert obs.get_run() is None

    def boom(*a, **kw):
        raise AssertionError("obs touched with telemetry off")

    monkeypatch.setattr(events_mod.EventStream, "emit", boom)
    monkeypatch.setattr(run_mod, "materialize", boom)
    monkeypatch.setattr(obs, "materialize", boom)
    monkeypatch.setattr(run_mod.TelemetryRun, "set_fingerprint", boom)
    monkeypatch.setattr(metrics_mod.MetricsRegistry, "counter", boom)
    monkeypatch.setattr(metrics_mod.MetricsRegistry, "gauge", boom)
    monkeypatch.setattr(metrics_mod.MetricsRegistry, "histogram", boom)
    monkeypatch.setattr(metrics_mod.Counter, "inc", boom)
    monkeypatch.setattr(metrics_mod.Gauge, "set", boom)
    monkeypatch.setattr(metrics_mod.Histogram, "observe_many", boom)
    monkeypatch.setattr(trace_mod.Span, "__init__", boom)
    monkeypatch.setattr(trace_mod, "emit_span", boom)
    monkeypatch.setattr(health_mod.HealthMonitor, "__init__", boom)
    monkeypatch.setattr(statusz_mod.MetricsSidecar, "__init__", boom)
    monkeypatch.setattr(profile_mod.ProfiledExecutable, "__init__", boom)
    monkeypatch.setattr(profile_mod.ProfilerWindow, "__init__", boom)
    monkeypatch.setattr(profile_mod, "aot_compile_profile", boom)
    monkeypatch.setattr(server_mod._SloTracker, "__init__", boom)

    from dpgo_tpu.serve import ServeSLO

    with SolveServer(max_batch=2, batch_window_s=0.005, quantum=64,
                     metrics_port=0, profile_dir=str(tmp_path / "prof"),
                     slo=ServeSLO(latency_s=1e-9)) as srv:
        assert srv.sidecar is None
        assert srv._profiler is None
        res = srv.solve(_request(_problem(n=24, seed=11)), timeout=300)
        # Shed paths are fenced too.
        t = srv.submit(_request(_problem(), deadline_s=0.0))
        with pytest.raises(OverCapacityError):
            t.result(timeout=30)
        assert srv._slo_state == {}
    assert np.isfinite(res.cost_history[-1])


def test_submissions_from_many_threads_are_safe():
    metas = [_problem(n=24, seed=k) for k in range(4)]
    results = [None] * 4
    with SolveServer(max_batch=4, batch_window_s=0.05, quantum=64) as srv:
        def go(k):
            results[k] = srv.solve(_request(metas[k]), timeout=300)

        threads = [threading.Thread(target=go, args=(k,)) for k in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    assert all(r is not None and np.isfinite(r.cost_history[-1])
               for r in results)


def test_run_bucket_verdict_mode_matches_legacy():
    """ISSUE-9 batched verdict vector: run_bucket(verdict_every=K)
    reproduces the legacy per-eval batch's histories, per-problem
    termination labels, and round counts — including members that latch
    termination at different evals — with one [B] word fetch per K
    rounds."""
    metas = [_problem(n=24, seed=0), _problem(n=27, seed=1, num_lc=6)]
    probs = [rbcd.prepare_problem(m, 2, params=PARAMS, init=None,
                                  pallas_sel=False) for m in metas]
    shapes = [bucket_shape_of(p, 64) for p in probs]
    padded = [pad_problem(p, shapes[0]) for p in probs]
    res_a, info_a = run_bucket(padded, ExecutableCache(), max_iters=8,
                               grad_norm_tol=1e-3, eval_every=2)
    res_b, info_b = run_bucket(padded, ExecutableCache(), max_iters=8,
                               grad_norm_tol=1e-3, eval_every=2,
                               verdict_every=4)
    # info["rounds"] may include the verdict window's polish overshoot
    # (the host learns of termination at the K boundary); the REPORTED
    # per-problem results must be identical.
    assert info_b["rounds"] >= info_a["rounds"]
    for a, b in zip(res_a, res_b):
        assert (a.iterations, a.terminated_by) == \
            (b.iterations, b.terminated_by)
        assert a.cost_history == b.cost_history
        assert a.grad_norm_history == b.grad_norm_history
    with pytest.raises(ValueError, match="verdict_every"):
        run_bucket(padded, ExecutableCache(), max_iters=4,
                   grad_norm_tol=1e-3, eval_every=3, verdict_every=4)


def test_server_verdict_every_plumbs_to_dispatch():
    """SolveServer(verdict_every=K) solves through the batched verdict
    loop and returns the same result as the legacy server; a request
    whose eval_every does not divide K falls back to the legacy loop
    rather than erroring."""
    meas = _problem()
    with SolveServer(max_batch=4, verdict_every=4) as srv:
        t = srv.submit(_request(meas, eval_every=2))
        r_v = t.result(timeout=60)
        t2 = srv.submit(_request(meas, eval_every=3))  # incompatible -> legacy
        r_l = t2.result(timeout=60)
    with SolveServer(max_batch=4) as srv:
        r_ref = srv.submit(_request(meas, eval_every=2)).result(timeout=60)
    assert r_v.cost_history == r_ref.cost_history
    assert np.isfinite(r_l.cost_history).all() \
        if hasattr(np.asarray(r_l.cost_history), 'all') else True
