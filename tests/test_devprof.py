"""Device-time attribution (ISSUE 16): XLA profile ingestion, the
measured overlap split, the adaptive overlap gate, and the merged
timeline's device track.

The attribution unit tests run on hand-built Chrome trace events so the
interval algebra is pinned exactly (container nesting, cross-lane
hiding, leaf-only op tables); the integration tests drive the real
``jax.profiler`` on the virtual CPU mesh — same plumbing the TPU path
uses, with the attribution numbers treated as shapes, not truths.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.obs import devprof, timeline
from dpgo_tpu.obs.events import read_events
from dpgo_tpu.parallel import make_mesh, solve_rbcd_sharded

from synthetic import make_measurements


def _dev_event(op, ts, dur, tid, pid=0):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": ts, "dur": dur,
            "name": op, "args": {"hlo_op": op}}


def test_classify_op_prefix_tables():
    for op in ("all-gather.1", "all-reduce-start.2", "reduce-scatter.3",
               "collective-permute.4", "ppermute", "All-Reduce.5"):
        assert devprof.classify_op(op) == "collective", op
    for op in ("fusion.1", "while.2", "dot.3", "custom-call.4", "copy.5"):
        assert devprof.classify_op(op) == "compute", op


def test_attribute_trace_containers_and_cross_lane_hiding():
    """The pinned scenario: lane A's ``while`` container encloses a
    40 us fusion and a 60 us all-reduce; lane B computes for 80 us.
    Interval algebra must not double-count the container, compute is
    busy-minus-collective, and the hidden fraction is the all-reduce
    time concurrent with lane B's compute ([40, 80) of [40, 100))."""
    events = [
        _dev_event("while.9", 0.0, 100.0, tid=1),
        _dev_event("fusion.1", 0.0, 40.0, tid=1),
        _dev_event("all-reduce.3", 40.0, 60.0, tid=1),
        _dev_event("fusion.2", 0.0, 80.0, tid=2),
        {"ph": "X", "pid": 0, "tid": 3, "ts": 0, "dur": 500,
         "name": "host_thing", "args": {}},   # no hlo_op: not a device op
        {"ph": "i", "pid": 0, "tid": 1, "ts": 5, "name": "marker",
         "args": {"hlo_op": "x"}},            # not an X slice
    ]
    att = devprof.attribute_trace(events, num_rounds=2)
    assert att["lanes"] == 2
    assert att["window_s"] == pytest.approx(100e-6)
    assert att["compute_s"] == pytest.approx(120e-6)      # 40 + 80, no 100
    assert att["collective_s"] == pytest.approx(60e-6)
    assert att["idle_s"] == pytest.approx(20e-6)          # 2*100 - 180
    assert att["collective_hidden_s"] == pytest.approx(40e-6)
    assert att["overlap_efficiency_measured"] == pytest.approx(2.0 / 3.0)
    assert att["per_round"]["compute_s"] == pytest.approx(60e-6)
    assert att["per_round"]["collective_s"] == pytest.approx(30e-6)
    # top_ops is leaf-only (no `while` container) and merges op families.
    tops = {t["op"]: t for t in att["top_ops"]}
    assert "while" not in tops
    assert tops["fusion"]["total_s"] == pytest.approx(120e-6)
    assert tops["fusion"]["count"] == 2
    assert tops["all-reduce"]["kind"] == "collective"
    # Slices are leaf-only, window-relative, lane-indexed.
    assert {s["op"] for s in att["slices"]} == \
        {"fusion.1", "all-reduce.3", "fusion.2"}
    ar = next(s for s in att["slices"] if s["op"] == "all-reduce.3")
    assert ar["lane"] == 0 and ar["t0_s"] == pytest.approx(40e-6)
    assert ar["kind"] == "collective"


def test_attribute_trace_no_device_ops_is_zeroed():
    att = devprof.attribute_trace(
        [{"ph": "X", "pid": 0, "tid": 0, "ts": 0, "dur": 5,
          "name": "host", "args": {}}], num_rounds=4)
    assert att["lanes"] == 0
    assert att["compute_s"] == att["collective_s"] == att["idle_s"] == 0.0
    assert att["overlap_efficiency_measured"] == 0.0
    assert att["slices"] == [] and att["top_ops"] == []


def test_decide_overlap_hysteresis_and_evidence():
    """The arbiter: overlap wins only when its A/B efficiency clears the
    threshold; the record carries both walls, both rates, and (when
    present) each arm's measured attribution evidence."""
    att = {"overlap_efficiency_measured": 0.4,
           "per_round": {"collective_s": 2e-3, "compute_s": 5e-3}}
    arms = {"lockstep": {"seconds": 1.0, "rounds": 8, "attribution": att},
            "overlapped": {"seconds": 0.90, "rounds": 8}}
    rec = devprof.decide_overlap(arms, threshold=0.05)
    assert rec["overlap"] is True
    assert rec["efficiency"] == pytest.approx(0.10)
    assert rec["lockstep_seconds"] == 1.0
    assert rec["overlapped_rounds_per_s"] == pytest.approx(8 / 0.90)
    assert rec["lockstep_overlap_efficiency_measured"] == 0.4
    assert rec["lockstep_collective_s_per_round"] == pytest.approx(2e-3)
    assert "overlapped_overlap_efficiency_measured" not in rec
    # Inside the hysteresis band the simpler lockstep schedule wins.
    arms["overlapped"]["seconds"] = 0.97
    rec = devprof.decide_overlap(arms, threshold=0.05)
    assert rec["overlap"] is False
    assert rec["efficiency"] == pytest.approx(0.03)
    # Overlap slower than lockstep: clearly off.
    arms["overlapped"]["seconds"] = 1.2
    assert devprof.decide_overlap(arms, threshold=0.0)["overlap"] is False


def test_device_trace_window_emits_attribution_event(tmp_path):
    """A real profiler window around a jitted program yields a
    schema-complete ``device_attribution`` event and the measured-
    efficiency gauge — the CI profiling smoke's core assertion."""
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.eye(96, dtype=jnp.float32)
    jax.block_until_ready(f(x))  # compile outside the window
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        win = devprof.DeviceTraceWindow(
            str(tmp_path / "prof"), plane="solve").start()
        for _ in range(3):
            jax.block_until_ready(f(x))
        att = win.stop(num_rounds=3, label="unit_matmul")
        gauge = obs.get_run().gauge("device_overlap_efficiency_measured")
        assert gauge.value(label="unit_matmul") == pytest.approx(
            att["overlap_efficiency_measured"])
    assert att is not None and att["lanes"] >= 1
    assert att["compute_s"] > 0.0
    evs = [e for e in read_events(f"{run_dir}/events.jsonl")
           if e.get("event") == "device_attribution"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["phase"] == "solve" and ev["label"] == "unit_matmul"
    for key in ("lanes", "num_rounds", "window_s", "compute_s",
                "collective_s", "idle_s", "per_round",
                "collective_hidden_s", "overlap_efficiency_measured",
                "top_ops", "slices", "trace_files", "profile_dir"):
        assert key in ev, key
    assert ev["num_rounds"] == 3 and ev["trace_files"] >= 1
    assert ev["slices"] and all(
        {"lane", "op", "kind", "t0_s", "dur_s"} <= set(s) for s in
        ev["slices"])


def test_device_trace_window_without_run_emits_nothing(tmp_path):
    """Outside a run the window still attributes (bench.py's opt-in
    path) but emits no event and survives a double stop/close."""
    f = jax.jit(lambda x: x * 2.0)
    jax.block_until_ready(f(jnp.ones(8)))
    win = devprof.DeviceTraceWindow(str(tmp_path / "p"), plane="solve")
    win.start()
    jax.block_until_ready(f(jnp.ones(8)))
    att = win.stop(num_rounds=1)
    assert att is None or att["lanes"] >= 0
    assert win.stop() is None          # already stopped: no-op
    win.close()                        # idempotent


def _noisy(rng, n=48, num_lc=14):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.01, trans_noise=0.01)
    return meas


def test_sharded_overlap_auto_gates_off_with_evidence(rng, tmp_path):
    """ISSUE 16 acceptance: on the shared-core CPU mesh the adaptive
    gate turns overlap OFF (there is no interconnect to hide behind, and
    MULTICHIP_r06 measured the pipelined schedule as a net loss), records
    an ``overlap_decision`` event carrying the A/B walls plus per-arm
    measured attribution, and the solve proper is BITWISE the forced
    ``overlap=False`` solve — calibration segments are discarded."""
    meas = _noisy(rng)
    params = AgentParams(d=3, r=5, num_robots=4, rel_change_tol=0.0)
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        res_auto = solve_rbcd_sharded(meas, 4, mesh=make_mesh(4),
                                      params=params, max_iters=8,
                                      grad_norm_tol=0.0, eval_every=4,
                                      overlap="auto")
    events = read_events(f"{run_dir}/events.jsonl")
    decisions = [e for e in events if e.get("event") == "overlap_decision"]
    assert len(decisions) == 1
    dec = decisions[0]
    assert dec["phase"] == "setup" and dec["mesh_size"] == 4
    assert dec["overlap"] is False
    for key in ("efficiency", "threshold", "lockstep_seconds",
                "overlapped_seconds", "lockstep_rounds_per_s",
                "overlapped_rounds_per_s", "calib_rounds"):
        assert key in dec, key
    from dpgo_tpu.parallel.sharded import _AUTO_THRESHOLD
    assert dec["threshold"] == pytest.approx(_AUTO_THRESHOLD)
    # Telemetry was on, so the decision carries measured evidence and the
    # evidence windows emitted their own attribution events.
    assert "lockstep_overlap_efficiency_measured" in dec
    assert "overlapped_collective_s_per_round" in dec
    labels = {e.get("label") for e in events
              if e.get("event") == "device_attribution"}
    assert {"auto_lockstep", "auto_overlapped"} <= labels
    # The setup event reflects the gated schedule.
    setup = [e for e in events if e.get("event") == "sharded_solve"]
    assert setup and setup[0]["overlap"] is False
    # Bitwise parity with the forced reference mode the gate picked.
    res_off = solve_rbcd_sharded(meas, 4, mesh=make_mesh(4), params=params,
                                 max_iters=8, grad_norm_tol=0.0,
                                 eval_every=4, overlap=False)
    assert res_auto.cost_history == res_off.cost_history
    np.testing.assert_array_equal(np.asarray(res_auto.T),
                                  np.asarray(res_off.T))


def test_overlap_auto_single_device_shortcut(rng, tmp_path):
    """A 1-device mesh has no collectives to hide: the gate resolves to
    lockstep without calibrating and says why."""
    meas = _noisy(rng, n=24, num_lc=6)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        solve_rbcd_sharded(meas, 2, mesh=make_mesh(1), params=params,
                           max_iters=4, grad_norm_tol=0.0, eval_every=2,
                           overlap="auto")
    decisions = [e for e in read_events(f"{run_dir}/events.jsonl")
                 if e.get("event") == "overlap_decision"]
    assert len(decisions) == 1
    assert decisions[0]["overlap"] is False
    assert decisions[0]["reason"] == "single_device_mesh"
    assert decisions[0]["calib_rounds"] == 0


def test_profiled_sharded_run_merged_trace_device_track(rng, tmp_path):
    """Satellite: a profiled 2-shard run merges into a schema-valid
    Chrome trace carrying BOTH host spans and device attribution slices,
    the latter on their own `device` process track (pid 1000) with
    per-lane threads."""
    meas = _noisy(rng, n=32, num_lc=8)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        solve_rbcd_sharded(meas, 2, mesh=make_mesh(2), params=params,
                           max_iters=8, grad_norm_tol=0.0, eval_every=4,
                           overlap="auto")
    tl = timeline.merge([run_dir])
    trace_path = timeline.write_chrome_trace(
        str(tmp_path / "trace.json"), tl)
    counts = timeline.validate_chrome_trace(trace_path)
    assert counts["spans"] > 0
    with open(trace_path) as fh:
        obj = json.load(fh)
    evs = obj["traceEvents"]
    device_slices = [e for e in evs
                     if e.get("ph") == "X" and e.get("pid") == 1000]
    host_spans = [e for e in evs
                  if e.get("ph") == "X" and e.get("pid") != 1000]
    assert device_slices, "no device attribution slices on the trace"
    assert host_spans, "no host spans on the trace"
    assert all(e["args"].get("kind") in ("compute", "collective")
               for e in device_slices)
    # The device track is named, with one thread per lane.
    procs = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert procs.get(1000) == "device"
    lane_names = {e["args"]["name"] for e in evs
                  if e.get("ph") == "M" and e.get("name") == "thread_name"
                  and e.get("pid") == 1000}
    assert lane_names and all(n.startswith("device lane ")
                              for n in lane_names)
    # The overlap decision renders as an instant on the host timeline.
    assert any(e.get("ph") == "i" and e.get("name") == "overlap_decision"
               for e in evs)


def test_telemetry_off_devprof_is_fenced(monkeypatch, rng):
    """Zero-overhead extension (ISSUE 16): with no ambient run, the
    sharded solve — including ``overlap="auto"`` — constructs no
    DeviceTraceWindow, no PerfLedger, and never calls the profiled-
    program prober; the gate still calibrates (clean host timing is not
    telemetry) and returns a working solve."""
    from dpgo_tpu.obs import ledger as ledger_mod

    def boom(*a, **kw):
        raise AssertionError("devprof telemetry path taken while disabled")

    monkeypatch.setattr(devprof.DeviceTraceWindow, "__init__", boom)
    monkeypatch.setattr(devprof, "profiled_program", boom)
    monkeypatch.setattr(devprof, "attribute_profile_dir", boom)
    monkeypatch.setattr(ledger_mod.PerfLedger, "__init__", boom)

    assert obs.get_run() is None
    meas = _noisy(rng, n=24, num_lc=6)
    params = AgentParams(d=3, r=5, num_robots=2, rel_change_tol=0.0)
    res = solve_rbcd_sharded(meas, 2, mesh=make_mesh(2), params=params,
                             max_iters=4, grad_norm_tol=0.0, eval_every=2,
                             overlap="auto")
    assert res.iterations > 0 and res.cost_history
