"""Tests for the lifted SE(d) product manifold ops."""

import jax
import jax.numpy as jnp
import numpy as np

from dpgo_tpu.ops import manifold
from dpgo_tpu.utils import lie


def random_point(key, n=7, r=5, d=3):
    kY, kp = jax.random.split(key)
    Y = lie.random_stiefel(kY, r, d, batch=(n,), dtype=jnp.float64)
    p = jax.random.normal(kp, (n, r), jnp.float64)
    return manifold.join(Y, p)


def test_project_restores_orthonormality():
    key = jax.random.PRNGKey(0)
    X = random_point(key) + 0.1 * jax.random.normal(key, (7, 5, 4), jnp.float64)
    Xp = manifold.project(X)
    Y, _ = manifold.split(Xp)
    YtY = np.asarray(jnp.swapaxes(Y, -1, -2) @ Y)
    assert np.allclose(YtY, np.broadcast_to(np.eye(3), (7, 3, 3)), atol=1e-12)


def test_tangent_project_properties():
    key = jax.random.PRNGKey(1)
    X = random_point(key)
    V = jax.random.normal(jax.random.PRNGKey(2), X.shape, jnp.float64)
    PV = manifold.tangent_project(X, V)
    # Idempotent.
    assert np.allclose(manifold.tangent_project(X, PV), PV, atol=1e-12)
    # Tangency: sym(Y^T W) = 0 per block.
    Y, _ = manifold.split(X)
    W, _ = manifold.split(PV)
    S = manifold.sym(jnp.swapaxes(Y, -1, -2) @ W)
    assert np.allclose(S, 0.0, atol=1e-12)
    # Orthogonality of the residual: <V - PV, T> = 0 for tangent T.
    T2 = manifold.tangent_project(X, jax.random.normal(jax.random.PRNGKey(3), X.shape, jnp.float64))
    assert abs(float(manifold.inner(V - PV, T2))) < 1e-10


def test_retract_stays_on_manifold_and_is_first_order():
    key = jax.random.PRNGKey(4)
    X = random_point(key)
    V = manifold.tangent_project(X, jax.random.normal(jax.random.PRNGKey(5), X.shape, jnp.float64))
    X1 = manifold.retract(X, V)
    Y1, _ = manifold.split(X1)
    YtY = np.asarray(jnp.swapaxes(Y1, -1, -2) @ Y1)
    assert np.allclose(YtY, np.broadcast_to(np.eye(3), YtY.shape), atol=1e-12)
    # First-order: R_X(tV) = X + tV + O(t^2).
    for t in [1e-3, 1e-4]:
        Xt = manifold.retract(X, t * V)
        err = float(jnp.max(jnp.abs(Xt - (X + t * V))))
        assert err < 10 * t * t * float(manifold.norm(V)) ** 2


def test_rhess_symmetry():
    # The Riemannian Hessian must be self-adjoint on the tangent space.
    key = jax.random.PRNGKey(6)
    X = random_point(key, n=4)
    eg = jax.random.normal(jax.random.PRNGKey(7), X.shape, jnp.float64)

    # A synthetic symmetric Euclidean Hessian: H(V) = A V + V B with A sym.
    A = jax.random.normal(jax.random.PRNGKey(8), (4, 5, 5), jnp.float64)
    A = A + jnp.swapaxes(A, -1, -2)

    def ehess(V):
        return jnp.einsum("nab,nbc->nac", A, V)

    U = manifold.tangent_project(X, jax.random.normal(jax.random.PRNGKey(9), X.shape, jnp.float64))
    V = manifold.tangent_project(X, jax.random.normal(jax.random.PRNGKey(10), X.shape, jnp.float64))
    HU = manifold.ehess_to_rhess(X, eg, ehess(U), U)
    HV = manifold.ehess_to_rhess(X, eg, ehess(V), V)
    lhs = float(manifold.inner(HU, V))
    rhs = float(manifold.inner(U, HV))
    assert abs(lhs - rhs) < 1e-8 * max(1.0, abs(lhs))
