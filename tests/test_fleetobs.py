"""Fleet-wide observability (``obs.fleetobs``, ISSUE 20): resource
sampling behind the telemetry fence, fail-open cross-process harvest +
crash forensics, clock-offset recovery over the fleet planes' stamp
channels, the aggregated ``/metrics``/``/statusz`` sidecar with dead
replicas MARKED (never fatal), ``report --live --fleet``'s partial
view, and ``regress --soak``'s flat-memory gate."""

import io
import json
import os
import threading
import urllib.request
import warnings

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.comms.protocol import (ORIGIN_FLEET_PARENT, attach_clock,
                                     mh_rank_actor, pop_clock,
                                     proc_replica_actor)
from dpgo_tpu.obs import fleetobs, timeline
from dpgo_tpu.obs.exporters import (merge_prometheus_texts,
                                    relabel_prometheus_text,
                                    validate_prometheus_text)
from dpgo_tpu.obs.regress import soak_memory_gate
from dpgo_tpu.obs.report import live_report


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _read_events(run_dir):
    path = os.path.join(str(run_dir), "events.jsonl")
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


# ---------------------------------------------------------------------------
# Actor bands + the channel clock codec
# ---------------------------------------------------------------------------

def test_actor_bands_are_disjoint():
    assert mh_rank_actor(0) == -100 and mh_rank_actor(3) == -103
    assert proc_replica_actor("r0") == -200
    assert proc_replica_actor("r7") == -207
    assert proc_replica_actor(2) == -202
    # Non-numeric ids still land inside the replica band, deterministic.
    a = proc_replica_actor("weird-id")
    assert a == proc_replica_actor("weird-id") and -297 <= a <= -200
    assert ORIGIN_FLEET_PARENT == -5


def test_attach_pop_clock_round_trip_and_fail_open():
    frame = {"x": np.zeros(3)}
    attach_clock(frame, ORIGIN_FLEET_PARENT)
    ts = pop_clock(frame)
    assert ts is not None and ts[0] == ORIGIN_FLEET_PARENT
    assert ts[1] > 0.0 and ts[2] > 0.0
    assert "_ts" not in frame and set(frame) == {"x"}
    # Unstamped: pop is a no-op None; mangled: dropped, never fatal.
    assert pop_clock({"x": 1}) is None
    assert pop_clock({"_ts": np.zeros(0)}) is None


# ---------------------------------------------------------------------------
# ResourceSampler (stdlib-only, fenced, leakcheck-clean start/stop)
# ---------------------------------------------------------------------------

def test_sample_resources_reads_this_process():
    s = fleetobs.sample_resources()
    assert s["threads"] >= 1
    if os.path.isdir("/proc/self/fd"):
        assert s["open_fds"] >= 3
    assert s["rss_bytes"] is None or s["rss_bytes"] > 1 << 20


def test_resource_sampler_fence_returns_none_without_run():
    assert obs.get_run() is None
    before = threading.active_count()
    assert fleetobs.start_resource_sampler() is None
    assert threading.active_count() == before


def test_resource_sampler_emits_gauges_and_soak_series(tmp_path):
    """Satellite (d): the sampler thread starts and stops leakcheck-clean
    (the plugin asserts no leaked thread after the test) and its samples
    land both as labeled gauges and as ``metric`` events."""
    with obs.run_scope(str(tmp_path / "run")) as run:
        sampler = fleetobs.start_resource_sampler(
            interval_s=60.0, queue_depth=lambda: 5, replica="r0")
        assert isinstance(sampler, fleetobs.ResourceSampler)
        sampler.sample_once()
        assert sampler.samples >= 1
        g = run.registry.gauge("process_threads")
        assert g.value(replica="r0") >= 1
        assert run.registry.gauge("serve_queue_depth_sampled").value(
            replica="r0") == 5.0
        sampler.close()
        assert not sampler._thread.is_alive()
    evs = _read_events(tmp_path / "run")
    rss = [e for e in evs if e.get("metric") == "process_rss_bytes"]
    assert rss and all(e["replica"] == "r0" and e["phase"] == "fleet"
                       for e in rss)


# ---------------------------------------------------------------------------
# Harvest + crash forensics
# ---------------------------------------------------------------------------

def _fake_rank_dir(tmp_path, name, actor, word=None, torn=False):
    """A hand-built worker run dir: a homing span, optionally the last
    published verdict, optionally a torn final line (SIGKILL mid-write)."""
    d = tmp_path / name
    d.mkdir(parents=True)
    lines = [
        {"event": "span", "name": "worker_boot", "phase": "comms",
         "robot": actor, "t0_mono": 1.0, "t0_wall": 100.0, "dur_s": 0.01,
         "t_mono": 1.01, "t_wall": 100.01, "seq": 0},
    ]
    if word is not None:
        lines.append({"event": "verdict_publish", "phase": "comms",
                      "robot": actor, "seq_boundary": 2, "iteration": 8,
                      "word": word, "key": "dpgo/mh/g0/s2/r1",
                      "t_mono": 2.0, "t_wall": 101.0, "seq": 1})
    with open(d / "events.jsonl", "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")
        if torn:
            fh.write('{"event": "span", "name": "iter')  # killed mid-write
    return str(d)


def test_harvest_run_dir_is_fail_open():
    out = fleetobs.harvest_run_dir("/nonexistent/run-dir")
    assert out["events"] == 0 and out["tail"] == []
    assert "error" in out


def test_harvest_run_dir_torn_tail_and_last_verdict(tmp_path):
    from dpgo_tpu.models.rbcd import VERDICT_RUNNING, pack_verdict

    word = int(pack_verdict(VERDICT_RUNNING))
    d = _fake_rank_dir(tmp_path, "g0-r1", mh_rank_actor(1), word=word,
                       torn=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        out = fleetobs.harvest_run_dir(d)
    assert out["truncated"] is True and out["events"] == 2
    assert out["tail"][-1]["event"] == "verdict_publish"
    lv = out["last_verdict"]
    assert lv["word"] == word and lv["seq"] == 2 and lv["iteration"] == 8
    assert lv["decoded"]["status"] == "running"


def test_harvest_generation_emits_postmortem_and_process_lost(tmp_path):
    from dpgo_tpu.models.rbcd import VERDICT_RUNNING, pack_verdict

    word = int(pack_verdict(VERDICT_RUNNING))
    d0 = _fake_rank_dir(tmp_path, "g0-r0", mh_rank_actor(0))
    d1 = _fake_rank_dir(tmp_path, "g0-r1", mh_rank_actor(1), word=word,
                        torn=True)
    with obs.run_scope(str(tmp_path / "launcher")) as run:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            post = fleetobs.harvest_generation(
                run, 0, {0: d0, 1: d1},
                outcomes={0: "process_lost", 1: "signal:SIGKILL"},
                records={0: {"ok": False, "kind": "process_lost",
                             "t_record_mono": 3.0, "t_record_wall": 103.0}},
                plane="multihost", lost_actor=mh_rank_actor)
    assert set(post["ranks"]) == {"0", "1"}
    assert post["ranks"]["1"]["last_verdict"]["word"] == word
    assert post["ranks"]["0"]["record"]["kind"] == "process_lost"
    evs = _read_events(tmp_path / "launcher")
    (pm,) = [e for e in evs if e["event"] == "generation_postmortem"]
    assert pm["plane"] == "multihost" and "1" in pm["ranks"]
    # The SIGKILLed rank gets the instant on ITS OWN track; the survivor
    # (process_lost = orderly structured exit) does not.
    (lost,) = [e for e in evs if e["event"] == "process_lost"]
    assert lost["robot"] == mh_rank_actor(1) and lost["rank"] == 1
    assert lost["last_event"] == "verdict_publish"
    # Reverse launcher<->rank clock leg off the record stamp.
    (cs,) = [e for e in evs if e["event"] == "clock_sample"]
    assert cs["src"] == mh_rank_actor(0) and cs["channel"] == "harvest"
    # The harvest span anchors the launcher stream's identity.
    assert any(e.get("event") == "span"
               and e.get("name") == "harvest_generation"
               and e.get("robot") == ORIGIN_FLEET_PARENT for e in evs)


def test_harvest_generation_fence_returns_none_without_run(tmp_path):
    assert fleetobs.harvest_generation(None, 0, {0: str(tmp_path)}) is None


# ---------------------------------------------------------------------------
# Clock-offset recovery across the fleet stamp channels (satellite d)
# ---------------------------------------------------------------------------

def _write_events(d, lines):
    d.mkdir(parents=True)
    with open(d / "events.jsonl", "w") as fh:
        for ln in lines:
            fh.write(json.dumps(ln) + "\n")
    return str(d)


def test_clock_offset_recovered_across_heartbeat_wire(tmp_path):
    """A child replica whose monotonic clock reads 5 s AHEAD of the
    parent's: bidirectional heartbeat stamp pairs recover the skew
    (latency cancels) within tolerance; a second, send-only replica is
    used latency-biased and flagged ``bidirectional: false``."""
    SKEW, LAT = 5.0, 0.010
    launcher = ORIGIN_FLEET_PARENT
    child, oneway = proc_replica_actor("r0"), proc_replica_actor("r1")
    parent_lines = [{"event": "span", "name": "harvest", "phase": "fleet",
                     "robot": launcher, "t0_mono": 0.0, "t0_wall": 1e5,
                     "dur_s": 0.01, "t_mono": 0.01, "t_wall": 1e5,
                     "seq": 0}]
    child_lines = [{"event": "span", "name": "replica_boot",
                    "phase": "comms", "robot": child,
                    "t0_mono": SKEW, "t0_wall": 1e5, "dur_s": 0.01,
                    "t_mono": SKEW + 0.01, "t_wall": 1e5, "seq": 0}]
    oneway_lines = [{"event": "span", "name": "replica_boot",
                     "phase": "comms", "robot": oneway,
                     "t0_mono": 2.0, "t0_wall": 1e5, "dur_s": 0.01,
                     "t_mono": 2.01, "t_wall": 1e5, "seq": 0}]
    for k in range(6):
        t = 1.0 + 0.1 * k  # true (parent-clock) send instant
        # Parent -> child: received on the child's skewed clock.
        child_lines.append({
            "event": "clock_sample", "phase": "comms", "src": launcher,
            "dst": child, "channel": "heartbeat", "kind": "status_poll",
            "t_send_mono": t, "t_mono": t + LAT + SKEW,
            "t_wall": 1e5, "seq": k + 1})
        # Child -> parent: the status-reply stamp, popped by the parent.
        parent_lines.append({
            "event": "clock_sample", "phase": "comms", "src": child,
            "dst": launcher, "channel": "heartbeat",
            "kind": "status_reply", "t_send_mono": t + LAT / 2 + SKEW,
            "t_mono": t + LAT * 1.5, "t_wall": 1e5, "seq": k + 1})
        # One-way replica: the parent hears it, it never hears back.
        parent_lines.append({
            "event": "clock_sample", "phase": "comms", "src": oneway,
            "dst": launcher, "channel": "heartbeat",
            "kind": "status_reply", "t_send_mono": t, "t_mono": t + LAT,
            "t_wall": 1e5, "seq": 100 + k})
    p = _write_events(tmp_path / "parent", parent_lines)
    c = _write_events(tmp_path / "child", child_lines)
    o = _write_events(tmp_path / "oneway", oneway_lines)
    tl = timeline.merge([p, c, o])
    # The parent stream is the reference (actor -5 beats robot homing).
    assert tl.offsets["reference"] == p
    by_path = {s["path"]: s for s in tl.offsets["streams"]}
    assert by_path[c]["offset_s"] == pytest.approx(SKEW, abs=0.01)
    assert by_path[c]["aligned"] and by_path[o]["aligned"]
    flags = {tuple(sorted(pr["streams"])): pr["bidirectional"]
             for pr in tl.offsets["pairs"]}
    assert flags[tuple(sorted((p, c)))] is True
    assert flags[tuple(sorted((p, o)))] is False
    # Rebased: the child's span now sits near parent t=5->0.
    boot = [e for e in tl.events if e.get("name") == "replica_boot"
            and e.get("robot") == child]
    assert boot[0]["t0_mono"] == pytest.approx(0.0, abs=0.02)


def test_fleet_trace_merges_onto_plane_tracks(tmp_path):
    """Launcher + victim + survivor streams merge into ONE validated
    Chrome trace with the launcher/rank tracks separated and the kill
    visible as a ``process_lost`` instant on the victim's track."""
    launcher = ORIGIN_FLEET_PARENT
    r0, r1 = mh_rank_actor(0), mh_rank_actor(1)
    lead = _write_events(tmp_path / "launcher", [
        {"event": "span", "name": "harvest_generation", "phase": "fleet",
         "robot": launcher, "t0_mono": 3.0, "t0_wall": 1e5, "dur_s": 0.05,
         "t_mono": 3.05, "t_wall": 1e5, "seq": 0},
        {"event": "generation_start", "generation": 0, "world_size": 2,
         "t_mono": 0.5, "t_wall": 1e5, "seq": 1},
        {"event": "process_lost", "robot": r1, "rank": 1,
         "outcome": "signal:SIGKILL", "plane": "multihost",
         "t_mono": 3.01, "t_wall": 1e5, "seq": 2},
    ])
    surv = _write_events(tmp_path / "g0-r0", [
        {"event": "span", "name": "worker_boot", "phase": "comms",
         "robot": r0, "t0_mono": 1.0, "t0_wall": 1e5, "dur_s": 0.2,
         "t_mono": 1.2, "t_wall": 1e5, "seq": 0},
        {"event": "span", "name": "barrier_wait", "phase": "comms",
         "robot": r0, "seq_boundary": 0, "t0_mono": 2.0, "t0_wall": 1e5,
         "dur_s": 0.03, "t_mono": 2.03, "t_wall": 1e5, "seq": 1},
    ])
    vict = _write_events(tmp_path / "g0-r1", [
        {"event": "span", "name": "worker_boot", "phase": "comms",
         "robot": r1, "t0_mono": 1.1, "t0_wall": 1e5, "dur_s": 0.2,
         "t_mono": 1.3, "t_wall": 1e5, "seq": 0},
        {"event": "verdict_publish", "robot": r1, "seq_boundary": 0,
         "iteration": 4, "word": 17, "key": "dpgo/mh/g0/s0/r1",
         "t_mono": 2.0, "t_wall": 1e5, "seq": 1},
    ])
    out = str(tmp_path / "fleet_trace.json")
    info = fleetobs.write_fleet_trace(
        [lead, surv, vict, str(tmp_path / "never-wrote-events")], out)
    assert info["trace"] == out and info["streams"] == 3
    assert info["spans"] >= 4
    with open(out) as fh:
        trace = json.load(fh)
    names = {e["pid"]: e["args"]["name"]
             for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names[200] == "launcher"
    assert names[300] == "rank 0" and names[301] == "rank 1"
    lost = [e for e in trace["traceEvents"]
            if e.get("ph") == "i" and e["name"] == "process_lost"]
    assert lost and lost[0]["pid"] == 301  # the victim's own track
    pubs = [e for e in trace["traceEvents"]
            if e.get("ph") == "i" and e["name"] == "verdict_publish"]
    assert pubs and pubs[0]["pid"] == 301


# ---------------------------------------------------------------------------
# Prometheus merge + the aggregated fleet sidecar
# ---------------------------------------------------------------------------

CHILD_TEXT = """# HELP solve_requests_total requests
# TYPE solve_requests_total counter
solve_requests_total{tenant="a"} 3
# HELP process_rss_bytes rss
# TYPE process_rss_bytes gauge
process_rss_bytes 1048576
"""


def test_merge_prometheus_texts_labels_children_not_parent():
    parent = ("# HELP fleet_replica_queue_depth q\n"
              "# TYPE fleet_replica_queue_depth gauge\n"
              'fleet_replica_queue_depth{replica="r0"} 2\n')
    merged = merge_prometheus_texts(
        {"": parent, "r0": CHILD_TEXT, "r1": CHILD_TEXT})
    counts = validate_prometheus_text(merged)
    assert counts["families"] == 3 and counts["samples"] == 5
    # Child samples get replica labels; the parent's pass through as-is.
    assert 'solve_requests_total{replica="r0",tenant="a"} 3' in merged
    assert 'process_rss_bytes{replica="r1"} 1048576' in merged
    assert 'fleet_replica_queue_depth{replica="r0"} 2' in merged
    assert 'replica=""' not in merged
    # Family-grouped: exactly one header per family.
    assert merged.count("# TYPE process_rss_bytes gauge") == 1


def test_relabel_preserves_existing_labels():
    out = relabel_prometheus_text(CHILD_TEXT, {"replica": "r9"})
    assert 'solve_requests_total{replica="r9",tenant="a"} 3' in out
    validate_prometheus_text(out)


class _FakeReplicaServer:
    """Just enough server surface for the fleet source: a status dict
    and an optional child ``/metrics`` URL."""

    def __init__(self, rid, metrics_url=None, status=None, boom=False):
        self.replica_id = rid
        self.metrics_url = metrics_url
        self._status = status or {"accepting": True, "queue_depth": 1,
                                  "requests_served": 4}
        self._boom = boom

    def status(self):
        if self._boom:
            raise ConnectionResetError("child socket gone")
        return dict(self._status)


class _ChildScrapeServer:
    """A real HTTP endpoint serving a fixed Prometheus text — stands in
    for one child replica's MetricsSidecar."""

    def __init__(self, text):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        body = text.encode()

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.url = "http://127.0.0.1:%d/metrics" % self.httpd.server_address[1]
        self._t = threading.Thread(target=self.httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._t.join(timeout=5.0)


def test_fleet_sidecar_fence_returns_none_without_run():
    assert obs.get_run() is None
    src = fleetobs.ServersFleetSource([])
    assert fleetobs.attach_fleet_sidecar(src) is None


def test_fleet_sidecar_aggregates_and_marks_dead_replicas(tmp_path):
    """Acceptance: the aggregated ``/metrics`` line-validates with the
    parent's per-replica gauges plus each live child's samples
    relabeled; a dead replica drops out of the merge and is MARKED
    unreachable in ``/statusz`` — the scrape never 500s (satellite c)."""
    import urllib.error

    child = _ChildScrapeServer(CHILD_TEXT)
    try:
        with obs.run_scope(str(tmp_path / "run")) as run:
            run.gauge("fleet_replica_queue_depth", "q").set(
                2.0, replica="r0")
            servers = [
                _FakeReplicaServer("r0", metrics_url=child.url),
                _FakeReplicaServer("r1", metrics_url="http://127.0.0.1:9/m",
                                   boom=True),
                _FakeReplicaServer("r2", status={"accepting": False,
                                                 "closed": True}),
            ]
            with fleetobs.attach_fleet_sidecar(
                    fleetobs.ServersFleetSource(servers),
                    scrape_timeout_s=0.5) as sidecar:
                assert isinstance(sidecar, fleetobs.FleetSidecar)
                base = f"http://{sidecar.host}:{sidecar.port}"
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=10) as r:
                    text = r.read().decode()
                counts = validate_prometheus_text(text)
                assert counts["samples"] >= 3
                assert 'fleet_replica_queue_depth{replica="r0"} 2' in text
                assert ('solve_requests_total{replica="r0",tenant="a"} 3'
                        in text)
                assert 'process_rss_bytes{replica="r0"} 1048576' in text

                with urllib.request.urlopen(base + "/statusz",
                                            timeout=10) as r:
                    st = json.load(r)
                assert st["fleet"] == {"replicas": 3}
                reps = st["replicas"]
                assert reps["r0"]["reachable"] is True
                assert reps["r1"]["reachable"] is False
                assert "ConnectionResetError" in reps["r1"]["error"]
                assert reps["r2"]["reachable"] is False  # closed = dead

                # Satellite (c): report --live renders the PARTIAL fleet
                # view with the dead replicas marked — rc 0, not rc 2.
                out = io.StringIO()
                rc = live_report(f"{sidecar.host}:{sidecar.port}", out=out)
                assert rc == 0
                txt = out.getvalue()
                assert "1/3 reachable" in txt
                assert "replica r1: ** UNREACHABLE **" in txt
                assert "replica r0: queue 1" in txt

                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(base + "/bogus", timeout=10)
                assert ei.value.code == 404
                ei.value.close()
    finally:
        child.close()


def test_live_report_unreachable_aggregate_is_rc2(capsys):
    rc = live_report("127.0.0.1:9", timeout=0.5, out=io.StringIO())
    assert rc == 2


# ---------------------------------------------------------------------------
# regress --soak: the flat-memory gate over the sampler series
# ---------------------------------------------------------------------------

def _soak_run(tmp_path, name, series):
    d = tmp_path / name
    with obs.run_scope(str(d)) as run:
        for who, vals in series.items():
            for v in vals:
                run.metric("process_rss_bytes", v, "B", phase="fleet",
                           replica=who)
    return str(d)


def test_soak_gate_flat_memory_passes(tmp_path):
    mb = 1 << 20
    d = _soak_run(tmp_path, "flat", {
        "r0": [100 * mb + i % 3 * mb for i in range(12)],
        "r1": [140 * mb] * 12})
    gate = soak_memory_gate(d)
    assert gate["rc"] == 0 and gate["regressions"] == []
    assert gate["series"]["r0"]["regressed"] is False


def test_soak_gate_catches_a_leaking_replica(tmp_path):
    mb = 1 << 20
    d = _soak_run(tmp_path, "leak", {
        "r0": [100 * mb] * 12,                              # flat
        "r1": [100 * mb + i * 20 * mb for i in range(12)]})  # +20MiB/sample
    gate = soak_memory_gate(d)
    assert gate["rc"] == 2 and gate["regressions"] == ["r1"]
    assert gate["series"]["r1"]["growth_bytes"] > 150 * mb
    # The CLI contract: exit 2 on growth.
    from dpgo_tpu.obs.regress import main as regress_main
    assert regress_main(["--soak", d, "--json"]) == 2


def test_soak_gate_too_few_samples_is_a_skip_not_a_pass(tmp_path):
    d = _soak_run(tmp_path, "short", {"r0": [1.0, 2.0, 3.0]})
    gate = soak_memory_gate(d)
    assert gate["rc"] == 0
    assert gate["series"]["r0"]["skipped"] is True
    e = _soak_run(tmp_path, "empty", {})
    gate = soak_memory_gate(e)
    assert gate.get("skipped") is True and "no " in gate["reason"]
