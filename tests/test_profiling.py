"""Profiling hooks (SURVEY.md section 5 tracing equivalent)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu.utils import profiling


def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with profiling.trace(logdir):
        with profiling.annotate("work"):
            x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
            np.asarray(x)
    found = [os.path.join(r, f) for r, _d, fs in os.walk(logdir) for f in fs]
    assert found, "profiler produced no trace files"


def test_round_timer_accumulates():
    t = profiling.RoundTimer()
    with t.phase("solve", sync_fn=lambda: jnp.ones(4)):
        jnp.ones(8)
    t.start("exchange")
    t.stop("exchange")
    with t.phase("solve"):
        pass
    assert t.counts == {"solve": 2, "exchange": 1}
    assert all(v >= 0.0 for v in t.totals.values())
    s = t.summary()
    assert "solve" in s and "exchange" in s


def test_round_timer_nested_phases():
    """Distinct phases nest freely; the inner window is contained in the
    outer's accumulated time."""
    t = profiling.RoundTimer()
    with t.phase("outer"):
        with t.phase("inner"):
            pass
    assert t.counts == {"outer": 1, "inner": 1}
    assert t.totals["outer"] >= t.totals["inner"] >= 0.0
    # Re-entering the same phase name while it is open: the second start()
    # overwrites the mark (one open window per name), and the single stop
    # closes it — counted once, no dangling mark.
    t2 = profiling.RoundTimer()
    t2.start("p")
    t2.start("p")
    t2.stop("p")
    assert t2.counts["p"] == 1
    with pytest.raises(ValueError):
        t2.stop("p")  # the overwritten mark is gone


def test_round_timer_stop_without_start_raises():
    t = profiling.RoundTimer()
    with pytest.raises(ValueError, match="without a matching start"):
        t.stop("never_started")
    # The error names the phases that ARE open — the actionable detail
    # when a phase string is mistyped mid-refactor.
    t.start("solve")
    with pytest.raises(ValueError, match=r"open phases: solve"):
        t.stop("slove")
    assert "solve" in t._t0  # the open window survives the failed stop


def test_round_timer_stop_guard_precedes_sync():
    """A never-started stop must fail fast WITHOUT materializing the sync
    value — no device->host transfer paid for a window that never
    opened."""

    class Probe:
        materialized = False

        def __array__(self, dtype=None, copy=None):
            Probe.materialized = True
            return np.zeros(1)

    t = profiling.RoundTimer()
    with pytest.raises(ValueError, match="without a matching start"):
        t.stop("never_started", sync=Probe())
    assert not Probe.materialized


def test_round_timer_sync_fence_materializes_device_value():
    """``stop(sync=x)`` must force a device->host materialization — on the
    tunneled-TPU platform a transfer is the only trustworthy fence."""

    class Probe:
        materialized = False

        def __array__(self, dtype=None, copy=None):
            Probe.materialized = True
            return np.zeros(1)

    t = profiling.RoundTimer()
    t.start("solve")
    t.stop("solve", sync=Probe())
    assert Probe.materialized, "sync value was not materialized"
    # And a real device value round-trips without error.
    t.start("solve")
    dt = t.stop("solve", sync=jnp.arange(8.0) * 2.0)
    assert dt >= 0.0


def test_round_timer_as_dict_and_reset():
    t = profiling.RoundTimer()
    with t.phase("solve"):
        pass
    with t.phase("solve"):
        pass
    t.start("exchange")
    t.stop("exchange")
    d = t.as_dict()
    assert set(d) == {"solve", "exchange"}
    assert d["solve"]["count"] == 2
    assert d["solve"]["total_s"] == pytest.approx(t.totals["solve"])
    assert d["solve"]["avg_ms"] == pytest.approx(
        1e3 * t.totals["solve"] / 2)
    # as_dict is a snapshot payload (JSON-ready plain types).
    import json

    json.dumps(d)

    t.start("open")  # in-flight mark must be dropped by reset too
    t.reset()
    assert t.totals == {} and t.counts == {}
    with pytest.raises(ValueError):
        t.stop("open")
    # Reusable after reset.
    with t.phase("solve"):
        pass
    assert t.counts == {"solve": 1}
