"""Profiling hooks (SURVEY.md section 5 tracing equivalent)."""

import os

import jax.numpy as jnp
import numpy as np

from dpgo_tpu.utils import profiling


def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with profiling.trace(logdir):
        with profiling.annotate("work"):
            x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
            np.asarray(x)
    found = [os.path.join(r, f) for r, _d, fs in os.walk(logdir) for f in fs]
    assert found, "profiler produced no trace files"


def test_round_timer_accumulates():
    t = profiling.RoundTimer()
    with t.phase("solve", sync_fn=lambda: jnp.ones(4)):
        jnp.ones(8)
    t.start("exchange")
    t.stop("exchange")
    with t.phase("solve"):
        pass
    assert t.counts == {"solve": 2, "exchange": 1}
    assert all(v >= 0.0 for v in t.totals.values())
    s = t.summary()
    assert "solve" in s and "exchange" in s
