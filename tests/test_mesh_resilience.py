"""Pod-scale resilience (ISSUE 14): mesh-elastic checkpoints, the
deterministic collective fault injector, and the anomaly-triggered
rewind supervisor behind ``solve_rbcd_sharded(resilience=...)``.

The contracts pinned here:

* **Kill-a-device acceptance** — a device lost mid-solve on the
  8-virtual-device mesh loses at most K rounds of progress: the
  supervisor resumes from the last verdict-boundary checkpoint on a
  4-device mesh, the final cost matches the undisturbed run within
  rtol 1e-6, and the resumed history is a numerically-pinned suffix of
  the undisturbed one.
* **Anomaly rewind** — an injected NaN halo trips the verdict word's
  latched ``non_finite`` anomaly, the supervisor rewinds, and the solve
  converges within 1% of fault-free (exact on the virtual mesh).
* **Zero new steady-state syncs** — ``host_syncs_per_100_rounds ==
  100/K`` is unchanged with resilience enabled, counted through the
  sanctioned ``rbcd._host_fetch`` seam; the checkpoint gather rides its
  own ``resilience._host_fetch`` seam instead.
* **Fail-open storage** — corrupt checkpoints (truncated / bit-flipped
  / wrong-schema) quarantine and recovery falls back to the previous
  boundary, mirroring PR 10's session-store matrix; a global-index
  mismatch degrades to a cold restart.
* **Watchdog** — a hung fetch surfaces as a phase-naming, structured
  ``MeshFaultError`` instead of a silent hang, and the supervisor
  recovers from it like any other mesh fault.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.config import AgentParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.parallel import (CollectiveFaultInjector, DeviceLostError,
                               MeshFaultError, MeshFaultSpec,
                               ResilienceConfig, Watchdog, make_mesh,
                               shrink_mesh_size, solve_rbcd_sharded)
from dpgo_tpu.parallel import resilience as resilience_mod
from dpgo_tpu.parallel import sharded as sharded_mod
from dpgo_tpu.serve.session import SessionStore
from dpgo_tpu.utils.partition import partition_contiguous

from synthetic import make_measurements


@pytest.fixture(autouse=True)
def _no_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


def _noisy(seed, n=80, num_lc=16, noise=0.1):
    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=noise, trans_noise=noise)
    return meas


_PARAMS = AgentParams(d=3, r=5, num_robots=8, rel_change_tol=0.0)
_K, _ROUNDS = 4, 24
_REF = {}


def _solve(meas, mesh_size=8, resilience=None, **kw):
    return solve_rbcd_sharded(
        meas, num_robots=8, mesh=make_mesh(mesh_size), params=_PARAMS,
        max_iters=_ROUNDS, verdict_every=_K, grad_norm_tol=0.0,
        eval_every=_K, resilience=resilience, **kw)


def _ref(meas):
    """The undisturbed reference run, computed once per process."""
    if "res" not in _REF:
        _REF["res"] = _solve(meas)
    return _REF["res"]


def _graph_for(meas, num_robots=8):
    part = partition_contiguous(meas, num_robots)
    graph, meta = rbcd.build_graph(part, _PARAMS.r, jnp.float64)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float64)
    state = rbcd.init_state(graph, meta, X0, params=_PARAMS)
    return graph, meta, state


# ---------------------------------------------------------------------------
# Config + small-piece contracts (fast, tier-1)
# ---------------------------------------------------------------------------

def test_resilience_config_validation(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_dir"):
        ResilienceConfig()
    with pytest.raises(ValueError, match="rewind_on"):
        ResilienceConfig(checkpoint_dir=str(tmp_path),
                         rewind_on=("non_finite", "flux_capacitor"))
    with pytest.raises(ValueError, match="checkpoint_every"):
        ResilienceConfig(checkpoint_dir=str(tmp_path), checkpoint_every=0)
    with pytest.raises(ValueError, match="fetch_deadline_s"):
        ResilienceConfig(checkpoint_dir=str(tmp_path),
                         fetch_deadline_s=0.0)
    with pytest.raises(ValueError, match="max_rewinds"):
        ResilienceConfig(checkpoint_dir=str(tmp_path), max_rewinds=-1)
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"), keep=4)
    assert cfg.resolve_store().keep == 4


def test_resilience_requires_verdict_loop(tmp_path):
    """Resilience rides the verdict-boundary contract: asking for it on
    the per-eval driver is a config error, named as such."""
    meas = _noisy(3, n=24, num_lc=6, noise=0.01)
    with pytest.raises(ValueError, match="verdict_every"):
        solve_rbcd_sharded(
            meas, num_robots=8, mesh=make_mesh(1), params=_PARAMS,
            max_iters=4,
            resilience=ResilienceConfig(checkpoint_dir=str(tmp_path)))


def test_shrink_mesh_size_respects_divisibility():
    assert shrink_mesh_size(8, 8) == 4
    assert shrink_mesh_size(4, 8) == 2
    assert shrink_mesh_size(2, 8) == 1
    assert shrink_mesh_size(1, 8) == 1       # nowhere left: same mesh
    assert shrink_mesh_size(4, 12) == 3      # next divisor, not half
    assert shrink_mesh_size(8, 8, min_size=4) == 4
    assert shrink_mesh_size(4, 8, min_size=4) == 4  # floor reached


def test_watchdog_deadline_names_phase():
    """A fetch that exceeds the deadline raises a structured, phase-naming
    MeshFaultError (mirroring RoundTimer.stop's open-phase guard), and
    the watchdog stays usable for the post-rewind fetch."""
    wd = Watchdog(0.15)
    release = threading.Event()
    try:
        with pytest.raises(MeshFaultError) as ei:
            wd.fetch(lambda x: release.wait(30.0), None, "sharded_verdict")
        assert ei.value.kind == "fetch_timeout"
        assert ei.value.phase == "sharded_verdict"
        assert "sharded_verdict" in str(ei.value)
        assert "watchdog deadline" in str(ei.value)
        # The stuck worker was abandoned: a fresh fetch works immediately.
        assert wd.fetch(lambda x: x + 1, 41, "gn_tail") == 42
    finally:
        release.set()
        wd.close()
    with pytest.raises(ValueError, match="deadline"):
        Watchdog(0.0)


def test_fetch_guard_composes_with_counting_shim():
    """The guard wraps whatever rbcd._host_fetch currently is, so a
    test's counting shim installed first keeps counting; the seam is
    restored on exit."""
    counted = [0]
    orig = rbcd._host_fetch

    def shim(x):
        counted[0] += 1
        return orig(x)

    rbcd._host_fetch = shim
    try:
        with resilience_mod.fetch_guard(Watchdog(5.0), None,
                                        ["sharded_verdict"], close=True):
            assert rbcd._host_fetch is not shim
            out = rbcd._host_fetch(jnp.asarray([1.0, 2.0]))
            np.testing.assert_array_equal(out, [1.0, 2.0])
        assert rbcd._host_fetch is shim
    finally:
        rbcd._host_fetch = orig
    assert counted[0] == 1


def test_injector_dispatch_poison_is_seeded_and_counted():
    """Same seed -> same poisoned (agent, pose); different seed moves it.
    The poison lands on a PUBLIC pose so the next exchange carries it."""
    _graph, _meta, state = _graph_for(_noisy(3, n=24, num_lc=6,
                                             noise=0.01))

    def poisoned(seed):
        inj = CollectiveFaultInjector(
            MeshFaultSpec(nan_halo_rounds=(2,)), seed=seed)
        inj.arm(_graph)
        st = state
        for _ in range(3):
            st = inj.before_dispatch(st, 1)
        assert inj.stats["rounds_dispatched"] == 3
        assert inj.stats["halo_nan"] == 1
        bad = np.argwhere(~np.isfinite(np.asarray(st.X)))
        assert bad.size, "no NaN landed"
        a, p = int(bad[0][0]), int(bad[0][1])
        assert p in set(np.asarray(_graph.pub_idx)[a].tolist())
        return a, p

    assert poisoned(11) == poisoned(11)
    assert poisoned(11) != poisoned(12)


def test_injector_wrap_exchange_and_installed_hooks():
    """wrap_exchange corrupts one seeded neighbor-buffer slot at trace
    level (a no-op while disabled); installed() sets and restores both
    module hooks."""
    inj = CollectiveFaultInjector(MeshFaultSpec(nan_halo_rounds=(0,)),
                                  seed=2)
    Z0 = jnp.zeros((4, 6), jnp.float64)
    wrapped = inj.wrap_exchange(lambda Xl: Z0)
    out = np.asarray(wrapped(None))
    assert np.isnan(out).sum() == 1
    assert inj.stats["links_wrapped"] == 1
    inj.enabled = False
    np.testing.assert_array_equal(np.asarray(wrapped(None)), np.asarray(Z0))
    inj.enabled = True

    assert rbcd._exchange_wrap is None and sharded_mod._gather_wrap is None
    with inj.installed():
        # Bound methods compare equal (never `is`): check the target.
        assert rbcd._exchange_wrap.__self__ is inj
        assert sharded_mod._gather_wrap.__self__ is inj
    assert rbcd._exchange_wrap is None and sharded_mod._gather_wrap is None


def test_injector_fetch_side_device_loss_and_hang():
    inj = CollectiveFaultInjector(
        MeshFaultSpec(device_loss_rounds=(0,), lost_device=5), seed=1)
    with pytest.raises(DeviceLostError) as ei:
        inj.on_fetch("sharded_verdict")
    assert ei.value.device == 5 and ei.value.kind == "device_loss"
    assert ei.value.phase == "sharded_verdict"
    assert inj.stats["device_loss"] == 1
    inj.on_fetch("sharded_verdict")  # fires once, then clean

    hang = CollectiveFaultInjector(
        MeshFaultSpec(hang_rounds=(0,), hang_s=0.05), seed=1)
    t0 = time.perf_counter()
    hang.on_fetch("gn_tail")
    assert time.perf_counter() - t0 >= 0.04
    assert hang.stats["hung_fetches"] == 1
    hang.release_hangs()


def test_boundary_cb_checkpoints_clean_and_rewinds_anomalous(tmp_path):
    """The supervisor's boundary hook: clean boundaries checkpoint (mesh
    tags included), anomalous words raise AnomalyRewind — even terminal
    ones (a solve that latched non_finite 'converged' on garbage) —
    and anomalies outside the policy pass through un-rewound."""
    graph, _meta, state = _graph_for(_noisy(3, n=24, num_lc=6,
                                            noise=0.01))
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path), rewind_on=(
        "non_finite",))
    sup = resilience_mod.CheckpointSupervisor(cfg, cfg.resolve_store(),
                                              graph, session_id="s")
    sup.attach_mesh(8)
    clean = rbcd.pack_verdict(rbcd.VERDICT_RUNNING)
    sup.boundary_cb(4, 1, state, clean, False)
    assert sup.checkpoints == 1
    snap = sup.store.load_newest("s")
    assert snap.iteration == 4 and snap.mesh_shape == (8,)
    np.testing.assert_array_equal(snap.global_index,
                                  np.asarray(graph.global_index))

    bad = rbcd.pack_verdict(rbcd.VERDICT_RUNNING, rbcd.ANOMALY_NON_FINITE)
    with pytest.raises(resilience_mod.AnomalyRewind) as ei:
        sup.boundary_cb(8, 2, state, bad, False)
    assert ei.value.anomaly == "non_finite" and ei.value.iteration == 8
    with pytest.raises(resilience_mod.AnomalyRewind):
        sup.boundary_cb(8, 2, state, bad, True)  # terminal, still garbage
    # A latched stall is outside this policy's rewind_on: no rewind, and
    # the anomalous state is never checkpointed either.
    stall = rbcd.pack_verdict(rbcd.VERDICT_RUNNING, rbcd.ANOMALY_STALL)
    sup.boundary_cb(8, 2, state, stall, False)
    assert sup.checkpoints == 1


@pytest.mark.parametrize("corrupt", ["truncate", "bitflip", "schema"])
def test_corrupt_checkpoint_falls_back_a_boundary(tmp_path, corrupt):
    """The 3-way corruption matrix (PR 10's session-store test) on the
    resilience save path: a corrupt newest checkpoint quarantines and
    recovery resumes from the boundary before it."""
    graph, _meta, state = _graph_for(_noisy(3, n=24, num_lc=6,
                                            noise=0.01))
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path))
    sup = resilience_mod.CheckpointSupervisor(cfg, cfg.resolve_store(),
                                              graph)
    sup.attach_mesh(8)
    # Boundary writes land off-thread (async_checkpoint) with
    # last-writer-wins coalescing; drain between saves so both
    # boundaries land (as they would with K rounds of compute between
    # them) and before poking at the files directly.
    sup.save(state, 4, 1)
    sup.store.flush()
    sup.save(state, 8, 2)
    sup.store.flush()
    sdir = tmp_path / cfg.session_id
    path = sdir / "snap-00000008.npz"
    if corrupt == "schema":
        blob = dict(np.load(path, allow_pickle=False))
        blob["__schema__"] = np.asarray(99, np.int64)
        with open(path, "wb") as fh:
            np.savez_compressed(fh, **blob)
    elif corrupt == "truncate":
        raw = path.read_bytes()
        path.write_bytes(raw[: len(raw) // 3])
    else:
        raw = bytearray(path.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        path.write_bytes(bytes(raw))
    fault = DeviceLostError("boom", phase="sharded_verdict", device=7)
    new_size, host_state, it, nwu = sup.recover(fault, 8, 8)
    assert (new_size, it, nwu) == (4, 4, 1)
    assert host_state is not None
    names = sorted(p.name for p in sdir.iterdir())
    assert "snap-00000008.npz.quarantined" in names
    assert sup.fault_kinds == ["device_loss"]


def test_global_index_mismatch_degrades_to_cold_restart(tmp_path):
    """A snapshot keyed to a DIFFERENT agent->pose layout is unusable:
    recovery fails open to a cold restart instead of mis-resuming."""
    graph, _meta, state = _graph_for(_noisy(3, n=24, num_lc=6,
                                            noise=0.01))
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path))
    store = cfg.resolve_store()
    sup = resilience_mod.CheckpointSupervisor(cfg, store, graph)
    sup.attach_mesh(8)
    host = resilience_mod.checkpoint_arrays(state)
    store.save(cfg.session_id, resilience_mod._host_state(host),
               iteration=4, mesh_shape=(8,),
               global_index=np.asarray(graph.global_index) + 1)
    new_size, host_state, it, nwu = sup.recover(
        MeshFaultError("hang", phase="gn_tail", kind="fetch_timeout"),
        8, 8)
    assert host_state is None and (it, nwu) == (0, 0)
    assert sup.cold_restarts == 1 and new_size == 4


def test_rewind_budget_exhaustion_is_structured(tmp_path):
    graph, _meta, _state = _graph_for(_noisy(3, n=24, num_lc=6,
                                             noise=0.01))
    cfg = ResilienceConfig(checkpoint_dir=str(tmp_path), max_rewinds=1)
    sup = resilience_mod.CheckpointSupervisor(cfg, cfg.resolve_store(),
                                              graph)
    sup.recover(DeviceLostError("x", phase="p", device=0), 8, 8)
    with pytest.raises(MeshFaultError) as ei:
        sup.recover(DeviceLostError("x", phase="p", device=1), 4, 8)
    assert ei.value.kind == "rewind_budget"
    assert "budget exhausted" in str(ei.value)


def test_checkpoint_gather_has_its_own_seam(tmp_path, monkeypatch):
    """The checkpoint gather must route through resilience._host_fetch,
    NOT rbcd._host_fetch — that separation is WHY the driver's sync-rate
    contract holds with resilience enabled."""
    _graph, _meta, state = _graph_for(_noisy(3, n=24, num_lc=6,
                                             noise=0.01))
    rbcd_counted, rz_counted = [], []
    orig = rbcd._host_fetch
    monkeypatch.setattr(rbcd, "_host_fetch",
                        lambda x: (rbcd_counted.append(0), orig(x))[1])
    orig_rz = resilience_mod._host_fetch
    monkeypatch.setattr(resilience_mod, "_host_fetch",
                        lambda x: (rz_counted.append(0), orig_rz(x))[1])
    host = resilience_mod.checkpoint_arrays(state)
    assert len(rz_counted) == len(host) > 0
    assert not rbcd_counted


# ---------------------------------------------------------------------------
# End-to-end chaos on the virtual 8-device mesh (slow; the CI mesh-chaos
# suite runs these unfiltered)
# ---------------------------------------------------------------------------

def test_device_loss_resumes_on_smaller_mesh(tmp_path):
    """Kill-a-device acceptance: at most K rounds lost, resume on a
    4-device mesh, final cost within rtol 1e-6 of the undisturbed run,
    history a numerically-pinned suffix — plus the telemetry/report
    surface for the whole fault story."""
    from dpgo_tpu.obs.events import read_events
    from dpgo_tpu.obs.report import render_report

    meas = _noisy(7)
    ref = _ref(meas)
    fault_round = 9
    inj = CollectiveFaultInjector(
        MeshFaultSpec(device_loss_rounds=(fault_round,), lost_device=3),
        seed=5)
    run_dir = str(tmp_path / "run")
    with obs.run_scope(run_dir):
        res = _solve(meas, resilience=ResilienceConfig(
            checkpoint_dir=str(tmp_path / "ck"), injector=inj))
    assert res.recovered
    rz = res.resilience
    assert rz["recoveries"] == 1 and rz["cold_restarts"] == 0
    assert rz["mesh_sizes"] == [8, 4]
    assert rz["fault_kinds"] == ["device_loss"]
    assert rz["injector"]["device_loss"] == 1
    # Final-cost parity and the pinned suffix.
    np.testing.assert_allclose(res.cost_history[-1], ref.cost_history[-1],
                               rtol=1e-6)
    nsuf = len(res.cost_history)
    np.testing.assert_allclose(res.cost_history,
                               ref.cost_history[-nsuf:], rtol=1e-6)
    assert res.iterations == ref.iterations
    # At most K rounds of verdict-CONFIRMED progress lost: the resume
    # point is exactly the last checkpoint taken before the fault, and
    # checkpoints land every K rounds.  In dispatched rounds the rewind
    # spans < 2K — the word fetch for boundary b runs after the
    # speculative b..b+K segment is dispatched, so a loss injected at
    # dispatch round r is observed at boundary b* >= r - K and resumes
    # from b* - K.
    events = read_events(f"{run_dir}/events.jsonl")
    rewinds = [e for e in events if e.get("event") == "mesh_rewind"]
    assert len(rewinds) == 1 and rewinds[0]["cold"] is False
    assert rewinds[0]["mesh_from"] == 8 and rewinds[0]["mesh_to"] == 4
    ri = events.index(rewinds[0])
    cps_before = [e["iteration"] for e in events[:ri]
                  if e.get("event") == "mesh_checkpoint"]
    assert cps_before
    assert rewinds[0]["resume_iteration"] == cps_before[-1]
    assert fault_round - rewinds[0]["resume_iteration"] < 2 * _K
    assert [e for e in events if e.get("event") == "mesh_fault"
            and e.get("kind") == "device_loss"]
    overhead = [e for e in events if e.get("event") == "metric"
                and e.get("metric") == "mesh_recovery_overhead_s"]
    assert overhead and overhead[0]["value"] > 0
    txt = render_report(run_dir)
    assert "resilience:" in txt and "rewind [device_loss]" in txt
    assert "mesh 8 -> 4 devices" in txt


def test_nan_halo_trips_anomaly_rewind(tmp_path):
    """An injected NaN halo payload trips the verdict anomaly latch
    (non_finite), rewinds on the SAME mesh (anomalies are numerical, not
    topological), and converges within 1% of fault-free."""
    meas = _noisy(7)
    ref = _ref(meas)
    inj = CollectiveFaultInjector(MeshFaultSpec(nan_halo_rounds=(10,)),
                                  seed=3)
    res = _solve(meas, resilience=ResilienceConfig(
        checkpoint_dir=str(tmp_path / "ck"), injector=inj))
    assert res.recovered
    rz = res.resilience
    assert rz["fault_kinds"] == ["anomaly:non_finite"]
    assert rz["mesh_sizes"] == [8, 8]
    assert rz["injector"]["halo_nan"] == 1
    rel = abs(res.cost_history[-1] - ref.cost_history[-1]) \
        / abs(ref.cost_history[-1])
    assert rel < 0.01
    assert np.isfinite(np.asarray(res.X)).all()


def test_double_device_loss_reshards_8_4_2(tmp_path):
    """Two device losses: 8 -> 4 -> 2 devices, the history suffix still
    pinned against the undisturbed run within rtol 1e-6 — the
    checkpoint layout is genuinely mesh-shape-independent."""
    meas = _noisy(7)
    ref = _ref(meas)
    inj = CollectiveFaultInjector(
        MeshFaultSpec(device_loss_rounds=(9, 17), lost_device=0), seed=5)
    res = _solve(meas, resilience=ResilienceConfig(
        checkpoint_dir=str(tmp_path / "ck"), injector=inj))
    rz = res.resilience
    assert rz["recoveries"] == 2
    assert rz["mesh_sizes"] == [8, 4, 2]
    nsuf = len(res.cost_history)
    np.testing.assert_allclose(res.cost_history,
                               ref.cost_history[-nsuf:], rtol=1e-6)
    np.testing.assert_allclose(res.cost_history[-1], ref.cost_history[-1],
                               rtol=1e-6)


def test_resilience_sync_rate_unchanged(tmp_path):
    """host_syncs_per_100_rounds == 100/K with resilience ENABLED: the
    checkpoint gathers ride already-paid verdict boundaries through the
    resilience plane's own seam, adding zero fetches to the sanctioned
    rbcd._host_fetch count (words + one fused terminal epilogue)."""
    meas = _noisy(7)
    counted = [0]
    orig = rbcd._host_fetch

    def shim(x):
        counted[0] += 1
        return orig(x)

    rbcd._host_fetch = shim
    try:
        res = _solve(meas, resilience=ResilienceConfig(
            checkpoint_dir=str(tmp_path / "ck")))
    finally:
        rbcd._host_fetch = orig
    words = _ROUNDS // _K
    assert counted[0] == words + 1
    assert res.resilience["checkpoints"] >= words - 1


def test_hung_fetch_watchdog_rewind(tmp_path):
    """A hung collective (simulated at the fetch seam) exceeds the
    watchdog deadline, surfaces as MeshFaultError(kind=fetch_timeout),
    and the supervisor rewinds and finishes the solve — no silent hang,
    no leaked watchdog threads (leakcheck covers this file in CI)."""
    meas = _noisy(7)
    ref = _ref(meas)
    inj = CollectiveFaultInjector(
        MeshFaultSpec(hang_rounds=(9,), hang_s=120.0), seed=3)
    res = _solve(meas, resilience=ResilienceConfig(
        checkpoint_dir=str(tmp_path / "ck"), injector=inj,
        fetch_deadline_s=2.0))
    rz = res.resilience
    assert rz["fault_kinds"] == ["fetch_timeout"]
    assert rz["injector"]["hung_fetches"] == 1
    assert rz["mesh_sizes"] == [8, 4]  # timeouts reshard like losses
    np.testing.assert_allclose(res.cost_history[-1], ref.cost_history[-1],
                               rtol=1e-6)
