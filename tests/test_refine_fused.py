"""Single-readback fused refinement (models.refine_fused): the on-device
df32 recenter must reproduce the host f64 recenter, and the fused
pipeline must reach a HOST-VERIFIED 1e-6 gap with no mid-pipeline sync.
"""

import jax.numpy as jnp
import numpy as np

from dpgo_tpu.config import AgentParams, SolverParams
from dpgo_tpu.models import rbcd, refine, refine_fused
from dpgo_tpu.ops import df32
from dpgo_tpu.utils.partition import partition_contiguous
from synthetic import make_measurements


def _problem(rng, n=40, A=3, r=5, rounds=60):
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=n // 2,
                                rot_noise=0.02, trans_noise=0.02)
    params = AgentParams(d=3, r=r, num_robots=A, rel_change_tol=0.0,
                         solver=SolverParams(grad_norm_tol=1e-12,
                                             max_inner_iters=10))
    part = partition_contiguous(meas, A)
    graph, meta = rbcd.build_graph(part, r, jnp.float32)
    X0 = rbcd.centralized_chordal_init(part, meta, graph, jnp.float32)
    state = rbcd.init_state(graph, meta, X0, params=params)
    state = rbcd.rbcd_steps(state, graph, rounds, meta, params)
    Xg32 = np.asarray(rbcd.gather_to_global(state.X, graph,
                                            meas.num_poses), np.float32)
    return meas, part, graph, meta, params, Xg32


def test_recenter_device_matches_host(rng):
    """Device df32 recenter vs host f64 recenter at the same f32 input:
    reference point, f_ref, and the shipped f32 constants must agree."""
    meas, part, graph, meta, params, Xg32 = _problem(rng)
    gp = refine_fused.build_global_df(part.meas_global)
    edges_g = refine.host_edges_f64(part.meas_global)

    fns = refine_fused.make_fused_fns(meta, params, meas.num_poses)
    target = df32.from_f64(np.float64(0.0))  # unused by recenter outputs
    R, f_ref, consts, rho32, thr = fns.recenter(
        jnp.asarray(Xg32), gp, graph, target)

    host = refine.recenter(np.asarray(Xg32, np.float64), graph, meta,
                           params, edges_g)

    # Projected reference point: polar factors are unique -> df32 vs f64
    # projection agree to the df32 floor.
    R64 = df32.to_f64(R)
    assert np.max(np.abs(R64 - host.Xg)) < 1e-9

    # Reference cost to ~1e-11 relative (df32 pairwise fold vs numpy f64).
    f_dev = float(df32.to_f64(f_ref))
    assert abs(f_dev - host.f_ref) / host.f_ref < 1e-9

    # Reference point / neighbor tables round the same f64 projection.
    for name in ("R", "Rz"):
        dev = np.asarray(getattr(consts, name), np.float64)
        hst = np.asarray(getattr(host.consts, name), np.float64)
        scale = max(np.abs(hst).max(), 1e-12)
        assert np.max(np.abs(dev - hst)) < 3e-6 * scale, name

    # Gradient-family constants: the device path computes them from the
    # f64-GRADE measurement data (gp carries df32 of the f64 parse),
    # while refine.recenter uses the graph's f32-rounded edges — so the
    # truth here is a direct f64 global recompute from the f64 edges.
    e64 = refine.np_edges_batched(edges_g)
    G_glob, rR64, rt64, _ = refine._np_egrad(host.Xg[None], e64,
                                             host.Xg.shape[0])
    G_glob = G_glob[0]
    d = meta.d
    RY = host.Xg[..., :d]
    S0_glob = refine._np_sym(np.swapaxes(RY, -1, -2) @ G_glob[..., :d])
    g0_glob = G_glob.copy()
    g0_glob[..., :d] -= RY @ S0_glob
    gi_np = np.asarray(graph.global_index)
    pm = np.asarray(graph.pose_mask)[..., None, None]
    for name, ref_arr in (("G_ref", G_glob[gi_np] * pm),
                          ("g0", g0_glob[gi_np] * pm),
                          ("S0", S0_glob[gi_np] * pm)):
        dev = np.asarray(getattr(consts, name), np.float64)
        scale = max(np.abs(ref_arr).max(), 1e-12)
        assert np.max(np.abs(dev - ref_arr)) < 3e-6 * scale, name

    # Global residuals (oracle inputs) against the f64 recompute.
    rho_R, rho_t = [np.asarray(x, np.float64) for x in rho32]
    assert np.max(np.abs(rho_R - rR64[0])) < 3e-6 * max(
        np.abs(rR64).max(), 1e-12)
    assert np.max(np.abs(rho_t - rt64[0])) < 3e-6 * max(
        np.abs(rt64).max(), 1e-12)

    # Preconditioner factors agree with the host build (f32 vs f64 build
    # of the same blocks: looser tolerance).
    dev = np.asarray(consts.chol, np.float64)
    hst = np.asarray(host.consts.chol, np.float64)
    assert np.max(np.abs(dev - hst)) < 1e-4 * max(np.abs(hst).max(), 1.0)


def test_fused_pipeline_reaches_verified_gap(rng):
    """End-to-end: descent iterate -> two fused cycles -> single readback
    -> HOST f64 verify at 1e-6 relative suboptimality."""
    from dpgo_tpu.models.local_pgo import solve_local

    meas, part, graph, meta, params, Xg32 = _problem(rng, rounds=80)
    res = solve_local(meas, rank=meta.rank, grad_norm_tol=1e-11,
                      max_iters=400, dtype=jnp.float64)
    f_opt = float(res.cost)

    rel_gap = 1e-6
    gp = refine_fused.build_global_df(part.meas_global)
    edges_g = refine.host_edges_f64(part.meas_global)
    target = df32.from_f64(np.float64(f_opt * (1.0 + 0.3 * rel_gap)))

    fns = refine_fused.make_fused_fns(meta, params, meas.num_poses,
                                      max_rounds=96, check_every=4)
    out = refine_fused.run_fused_cycles(fns, jnp.asarray(Xg32), gp, graph,
                                        target, cycles=2)
    X64 = refine_fused.assemble_f64(out, graph)
    X64 = refine._np_project_manifold(X64, meta.d)
    f = refine.global_cost(X64, edges_g)
    gap = f / f_opt - 1.0
    assert gap <= rel_gap, f"verified gap {gap:.3e}"

    # The on-device oracle's estimate must agree with the host verify at
    # the oracle's error budget (<< the 0.7x stopping margin).
    f_oracle = float(df32.to_f64(df32.DF(out.f_ref_hi, out.f_ref_lo))) \
        + float(out.delta)
    assert abs(f_oracle - f) / f_opt < 1e-8


def test_oracle_exits_immediately_when_converged(rng):
    """A cycle starting below target must exit its while_loop at round 0
    (this is what makes over-provisioned cycle counts nearly free)."""
    meas, part, graph, meta, params, Xg32 = _problem(rng, rounds=60)
    gp = refine_fused.build_global_df(part.meas_global)
    edges_g = refine.host_edges_f64(part.meas_global)
    f_now = refine.global_cost(
        refine._np_project_manifold(np.asarray(Xg32, np.float64), meta.d),
        edges_g)
    # Target ABOVE the current cost: already converged by construction.
    target = df32.from_f64(np.float64(f_now * (1.0 + 1e-3)))
    fns = refine_fused.make_fused_fns(meta, params, meas.num_poses,
                                      max_rounds=64, check_every=4)
    R, f_ref, consts, rho32, thr = fns.recenter(
        jnp.asarray(Xg32), gp, graph, target)
    D, rounds, delta = fns.refine(consts, graph, gp, rho32, thr)
    assert int(rounds) == 0
    assert float(delta) <= float(thr)
