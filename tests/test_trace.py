"""Distributed tracing for the deployment plane: spans, cross-process
trace context, clock-offset estimation, the merged Perfetto timeline, and
the fleet report — including the acceptance chaos scenario (4-robot
loopback fleet, 10% drop, one robot killed mid-solve) and the telemetry-
off zero-overhead fence extended to tracing."""

import json
import os
import time

import numpy as np
import pytest

from dpgo_tpu import obs
from dpgo_tpu.obs import timeline, trace
from dpgo_tpu.obs.events import read_events, read_events_meta
from dpgo_tpu.obs.report import main as report_main

NUM_ROBOTS = 4
ROUNDS = 40
KILL = (3, 25)      # robot 3 dies at round 25
PACE_S = 0.003


@pytest.fixture(autouse=True)
def _no_leaked_ambient_run():
    obs.end_run()
    yield
    obs.end_run()


# ---------------------------------------------------------------------------
# Span primitives
# ---------------------------------------------------------------------------

def test_span_nesting_and_event_schema(tmp_path):
    d = str(tmp_path / "run")
    with obs.run_scope(d):
        with trace.span("outer", phase="compute", robot=2) as outer:
            outer.add(items=3)
            with trace.span("inner", phase="comms", robot=2) as inner:
                pass
        lone = trace.start_span("lone", phase="eval")
        lone.end(ok=True)
    evs = [e for e in read_events(os.path.join(d, "events.jsonl"))
           if e["event"] == "span"]
    by_name = {e["name"]: e for e in evs}
    # inner closed first (context exit order), parented under outer,
    # sharing its trace id.
    assert [e["name"] for e in evs] == ["inner", "outer", "lone"]
    assert by_name["inner"]["parent"] == by_name["outer"]["span"]
    assert by_name["inner"]["trace"] == by_name["outer"]["trace"]
    assert by_name["outer"]["items"] == 3
    assert by_name["outer"]["robot"] == 2
    assert by_name["lone"]["ok"] is True
    assert "parent" not in by_name["lone"]
    for e in evs:
        assert len(e["span"]) == 16 and len(e["trace"]) == 16
        assert e["dur_s"] >= 0.0
        assert e["t0_mono"] <= e["t_mono"]


def test_span_is_noop_without_run():
    assert trace.span("x") is trace.NULL_SPAN
    assert trace.start_span("x") is None
    with trace.span("x") as sp:
        sp.add(a=1).end()  # all no-ops
    assert trace.current_span() is None


# ---------------------------------------------------------------------------
# Wire trace context (optional frame entries, both codecs, old-peer safe)
# ---------------------------------------------------------------------------

def test_trace_wire_entries_ride_both_codecs():
    from dpgo_tpu.comms import (decode_payload, encode_payload,
                                pack_pose_set, pack_trace_entries,
                                unpack_pose_set, unpack_trace_entries)

    poses = {(0, 1): np.eye(5, 4), (1, 2): np.ones((5, 4))}
    frame = pack_pose_set("pose", poses)
    frame.update(pack_trace_entries(0x1234, 0x5678, 1))
    for wire_format in ("packed", "npz"):
        decoded = decode_payload(encode_payload(frame, wire_format))
        # An old peer's pose parsing is undisturbed by the extra entries.
        got = unpack_pose_set(dict(decoded), "pose")
        assert set(got) == set(poses)
        ctx = unpack_trace_entries(decoded)
        assert ctx is not None
        trace_id, span_id, robot, t_mono, t_wall = ctx
        assert (trace_id, span_id, robot) == (0x1234, 0x5678, 1)
        assert t_mono > 0 and t_wall > 0
        # pop=True removed the entries from the frame.
        assert unpack_trace_entries(decoded) is None


def test_trace_wire_entries_mangled_is_dropped():
    from dpgo_tpu.comms import (TRACE_IDS_KEY, TRACE_T_KEY,
                                unpack_trace_entries)

    assert unpack_trace_entries({}) is None
    bad = {TRACE_IDS_KEY: np.asarray([1], np.int64),       # too short
           TRACE_T_KEY: np.asarray([1.0, 2.0])}
    assert unpack_trace_entries(bad) is None


def test_telemetry_off_wire_carries_no_trace_or_clock_entries():
    """With telemetry off the wire is byte-identical to the untraced
    protocol: no clock stamp, no trace context, and no Span is ever
    constructed (the zero-overhead acceptance fence for tracing)."""
    from dpgo_tpu.comms import BusClient, ReliableChannel
    from dpgo_tpu.comms.protocol import (CLOCK_KEY, TRACE_IDS_KEY,
                                         TRACE_T_KEY)
    from dpgo_tpu.comms.transport import LoopbackTransport

    assert obs.get_run() is None
    t_robot, t_bus = LoopbackTransport.pair("robot0", "bus")
    client = BusClient(ReliableChannel(t_robot, "robot0->bus"), 0)
    client.publish({"x": np.arange(3)})
    frame = t_bus.recv(timeout=1.0)
    assert CLOCK_KEY not in frame
    assert TRACE_IDS_KEY not in frame and TRACE_T_KEY not in frame
    assert set(frame) == {"x", "_seq", "_kind"}


def test_telemetry_on_wire_carries_trace_and_clock_entries(tmp_path):
    from dpgo_tpu.comms import BusClient, ReliableChannel
    from dpgo_tpu.comms.protocol import (CLOCK_KEY, TRACE_IDS_KEY,
                                         TRACE_T_KEY)
    from dpgo_tpu.comms.transport import LoopbackTransport

    with obs.run_scope(str(tmp_path / "run")):
        t_robot, t_bus = LoopbackTransport.pair("robot0", "bus")
        client = BusClient(ReliableChannel(t_robot, "robot0->bus"), 0)
        client.publish({"x": np.arange(3)})
        frame = t_bus.recv(timeout=1.0)
        assert CLOCK_KEY in frame
        assert np.asarray(frame[CLOCK_KEY])[0] == 0.0  # origin robot 0
        ids = np.asarray(frame[TRACE_IDS_KEY])
        assert ids[2] == 0 and ids[0] > 0 and ids[1] > 0
        assert TRACE_T_KEY in frame


# ---------------------------------------------------------------------------
# Clock-offset estimation + span merge (synthetic, known injected offset)
# ---------------------------------------------------------------------------

OFFSET_S = 1.7                  # robot 1's clock runs 1.7s ahead
LATENCY_S = 0.005
JITTER_S = 0.001


def _write_stream(path, robot, events):
    with open(path, "w") as fh:
        for i, e in enumerate(events):
            fh.write(json.dumps({"run": f"r{robot}", "seq": i, **e}) + "\n")


def _synthetic_pair(tmp_path, n_samples=60, seed=0):
    """Two event files: robot 0 on the true clock, robot 1 shifted by
    OFFSET_S, exchanging stamped frames with ~LATENCY_S +- JITTER_S."""
    rng = np.random.default_rng(seed)
    t_wall0 = 1_700_000_000.0
    a_events, b_events = [], []
    for k in range(n_samples):
        t = 10.0 + 0.05 * k
        lat_ab = LATENCY_S + float(rng.normal(0, JITTER_S))
        lat_ba = LATENCY_S + float(rng.normal(0, JITTER_S))
        # 0 -> 1: sent on A's clock, received on B's (shifted) clock.
        b_events.append({
            "event": "clock_sample", "phase": "comms", "src": 0, "dst": 1,
            "t_mono": t + abs(lat_ab) + OFFSET_S, "t_wall": t_wall0 + t,
            "t_send_mono": t, "t_send_wall": t_wall0 + t})
        # 1 -> 0.
        a_events.append({
            "event": "clock_sample", "phase": "comms", "src": 1, "dst": 0,
            "t_mono": t + abs(lat_ba), "t_wall": t_wall0 + t,
            "t_send_mono": t + OFFSET_S, "t_send_wall": t_wall0 + t})
        # One iterate span per robot per round, same TRUE start time.
        a_events.append({
            "event": "span", "phase": "compute", "name": "iterate",
            "robot": 0, "trace": f"{k:016x}", "span": f"{k:016x}",
            "t_mono": t + 0.01, "t_wall": t_wall0 + t,
            "t0_mono": t, "t0_wall": t_wall0 + t, "dur_s": 0.01,
            "iteration": k})
        b_events.append({
            "event": "span", "phase": "compute", "name": "iterate",
            "robot": 1, "trace": f"{k:016x}", "span": f"{k + 1:016x}",
            "t_mono": t + 0.01 + OFFSET_S, "t_wall": t_wall0 + t,
            "t0_mono": t + OFFSET_S, "t0_wall": t_wall0 + t,
            "dur_s": 0.01, "iteration": k})
    pa, pb = str(tmp_path / "robot0.jsonl"), str(tmp_path / "robot1.jsonl")
    _write_stream(pa, 0, a_events)
    _write_stream(pb, 1, b_events)
    return pa, pb


def test_clock_offset_estimated_within_tolerance(tmp_path):
    pa, pb = _synthetic_pair(tmp_path)
    tl = timeline.merge([pa, pb])
    s0, s1 = tl.streams
    assert s0.aligned and s1.aligned
    assert s0.offset == 0.0                      # reference stream
    # Symmetric latency cancels: the estimate lands within a few jitter
    # standard deviations of the injected 1.7s.
    assert s1.offset == pytest.approx(OFFSET_S, abs=0.003)
    assert s1.uncertainty is not None
    # Uncertainty is honest: about half the RTT plus spread.
    assert 0.0 < s1.uncertainty < 0.05
    (pair,) = tl.offsets["pairs"]
    assert pair["bidirectional"] is True
    assert pair["samples"] == 120


def test_span_merge_rebases_onto_common_timeline(tmp_path):
    pa, pb = _synthetic_pair(tmp_path)
    tl = timeline.merge([pa, pb])
    spans = [e for e in tl.events if e.get("event") == "span"]
    by_round = {}
    for e in spans:
        by_round.setdefault(e["iteration"], {})[e["robot"]] = e
    # Per round the two robots started simultaneously in TRUE time; after
    # rebasing their t0 must agree within the estimation tolerance
    # (before rebasing they disagreed by 1.7s).
    for k, pair in by_round.items():
        assert abs(pair[0]["t0_mono"] - pair[1]["t0_mono"]) < 0.01
    # The merged order interleaves the two robots round by round.
    order = [e["robot"] for e in sorted(spans,
                                        key=lambda e: e["t0_mono"])]
    assert order[:4].count(0) == 2 and order[:4].count(1) == 2


def test_one_way_samples_flagged_latency_biased(tmp_path):
    pa, pb = _synthetic_pair(tmp_path)
    # Strip B's samples of A -> only one direction remains.
    evs, _ = read_events_meta(pb)
    one_way = [e for e in evs if e.get("event") != "clock_sample"]
    _write_stream(pb, 1, one_way)
    tl = timeline.merge([pa, pb])
    (pair,) = tl.offsets["pairs"]
    assert pair["bidirectional"] is False
    # Offset still recovered to within the (unremovable) one-way latency.
    assert tl.streams[1].offset == pytest.approx(OFFSET_S,
                                                 abs=2 * LATENCY_S + 0.01)


def test_unaligned_stream_is_flagged(tmp_path):
    pa, pb = _synthetic_pair(tmp_path)
    # Remove ALL clock samples: no path between the two clock domains.
    for p, rid in ((pa, 0), (pb, 1)):
        evs, _ = read_events_meta(p)
        _write_stream(p, rid,
                      [e for e in evs if e.get("event") != "clock_sample"])
    tl = timeline.merge([pa, pb])
    flags = {s.path: s.aligned for s in tl.streams}
    assert sum(flags.values()) == 1  # only the reference is aligned


# ---------------------------------------------------------------------------
# Traced loopback fleet (the deployment plane end to end)
# ---------------------------------------------------------------------------

def _make_problem(num_robots, seed=0, n=24, num_lc=12):
    from dpgo_tpu.utils.partition import partition_contiguous
    from dpgo_tpu.utils.synthetic import make_measurements

    rng = np.random.default_rng(seed)
    meas, _ = make_measurements(rng, n=n, d=3, num_lc=num_lc,
                                rot_noise=0.01, trans_noise=0.01)
    return meas, partition_contiguous(meas, num_robots)


def _run_fleet(part, num_robots, injector=None, kill=None, rounds=ROUNDS,
               pace_s=0.0):
    """Lockstep loopback fleet driver (the in-process twin of the TCP
    example's robot loop), traced when a run is ambient."""
    from dpgo_tpu.agent import PGOAgent
    from dpgo_tpu.comms import (RetryPolicy, apply_peer_frame,
                                loopback_fleet, pack_agent_frame)
    from dpgo_tpu.config import AgentParams

    from dpgo_tpu.utils.partition import agent_measurements

    params = AgentParams(d=3, r=5, num_robots=num_robots)
    agents = {rid: PGOAgent(rid, params) for rid in range(num_robots)}
    for rid in range(1, num_robots):
        agents[rid].set_lifting_matrix(agents[0].get_lifting_matrix())
    for rid, ag in agents.items():
        ag.set_pose_graph(*agent_measurements(part, rid))
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.002,
                         max_delay_s=0.01, send_timeout_s=0.5,
                         recv_timeout_s=0.5)
    bus, clients = loopback_fleet(num_robots, injector=injector,
                                  policy=policy, round_timeout_s=0.15,
                                  miss_limit=5, liveness_timeout_s=0.5)
    for c in clients.values():
        c.channel.start_heartbeat(0.05)
    dead = set()
    for it in range(rounds):
        if kill is not None and it == kill[1]:
            dead.add(kill[0])
            clients[kill[0]].close()
        for rid, ag in agents.items():
            if rid in dead:
                continue
            clients[rid].publish(
                pack_agent_frame(ag, include_anchor=(rid == 0)),
                timeout=0.5)
        bus.round()
        for rid, ag in agents.items():
            if rid in dead:
                continue
            merged = clients[rid].collect(timeout=0.3)
            if merged is not None:
                for peer, pf in clients[rid].peer_frames(merged).items():
                    apply_peer_frame(ag, peer, pf,
                                     accept_anchor=(rid != 0 and peer == 0))
                for lost in clients[rid].lost:
                    ag.mark_neighbor_lost(lost)
            ag.iterate(True)
        if pace_s:
            time.sleep(pace_s)
    bus.close()
    for rid, c in clients.items():
        if rid not in dead:
            c.close()
    return agents, bus


def test_traced_loopback_solve_produces_valid_chrome_trace(tmp_path):
    """A traced 2-robot loopback solve exports a schema-valid Chrome
    trace with at least one cross-robot flow edge per round — the CI
    traced-deployment smoke."""
    rounds = 8
    meas, part = _make_problem(2)
    d = str(tmp_path / "run")
    with obs.run_scope(d):
        _run_fleet(part, 2, rounds=rounds)

    tl = timeline.merge([d])
    trace_path = timeline.write_chrome_trace(
        str(tmp_path / "trace.json"), tl)
    with open(trace_path) as fh:
        obj = json.load(fh)          # the file parses as plain JSON
    counts = timeline.validate_chrome_trace(obj)
    assert counts["spans"] > 4 * rounds   # publish/collect/scatter/iterate
    assert counts["cross_robot_flows"] >= rounds
    assert counts["pids"] >= 3            # bus + 2 robots
    # Round-trips through the validator from the PATH form too.
    assert timeline.validate_chrome_trace(trace_path) == counts
    # Every robot's iterate spans are present as X events on its track.
    xs = [e for e in obj["traceEvents"] if e.get("ph") == "X"
          and e.get("name") == "iterate"]
    assert {e["pid"] for e in xs} == {2, 3}
    # Flow arrows bind sender publish time to receiver scatter: the
    # start must not be after the finish (validator also enforces).
    names = {e.get("name") for e in obj["traceEvents"]}
    assert {"publish", "collect", "scatter", "bus_round", "frame"} <= names


def test_chaos_traced_fleet_merged_trace_and_report(tmp_path, capsys):
    """The acceptance scenario: a traced 4-robot loopback chaos run (10%
    drop, robot 3 killed mid-solve) produces a merged Chrome trace where
    every surviving robot's rounds share the timeline, cross-robot frame
    edges render as flows, and the report CLI prints per-robot busy/wait
    and critical-path stats (text and --json)."""
    from dpgo_tpu.comms import FaultInjector, FaultSpec

    meas, part = _make_problem(NUM_ROBOTS)
    injector = FaultInjector(FaultSpec(drop=0.10), seed=7)
    d = str(tmp_path / "chaos")
    with obs.run_scope(d):
        agents, bus = _run_fleet(part, NUM_ROBOTS, injector=injector,
                                 kill=KILL, pace_s=PACE_S)
    assert injector.stats["dropped"] > 0
    assert bus.lost == {KILL[0]}
    survivors = [r for r in range(NUM_ROBOTS) if r != KILL[0]]

    # -- merged trace ------------------------------------------------------
    tl = timeline.merge([d])
    trace_path = timeline.write_chrome_trace(str(tmp_path / "t.json"), tl)
    counts = timeline.validate_chrome_trace(trace_path)
    assert counts["cross_robot_flows"] > 0
    evs = tl.events
    per_robot_iters = {
        r: {e["iteration"] for e in evs if e.get("event") == "span"
            and e.get("name") == "iterate" and e.get("robot") == r}
        for r in survivors}
    for r in survivors:
        # Every survivor's rounds appear on the common timeline (late
        # initialization may cost the non-anchor robots a few iterates).
        assert len(per_robot_iters[r]) >= ROUNDS - 6, \
            f"robot {r}: {len(per_robot_iters[r])} rounds on timeline"
    # The killed robot stops appearing after its death round.
    dead_iters = {e["iteration"] for e in evs if e.get("event") == "span"
                  and e.get("name") == "iterate"
                  and e.get("robot") == KILL[0]}
    assert dead_iters and max(dead_iters) <= KILL[1] + 1

    # -- report CLI --------------------------------------------------------
    assert report_main([d]) == 0
    out = capsys.readouterr().out
    assert "fleet timeline:" in out
    assert "busy" in out and "wait" in out
    assert "critical path over" in out
    assert "stragglers" in out

    assert report_main(["--json", d]) == 0
    rec = json.loads(capsys.readouterr().out)
    ft = rec["fleet_timeline"]
    assert ft["num_flow_links"] > 0
    for r in survivors:
        row = ft["robots"][str(r)] if str(r) in ft["robots"] \
            else ft["robots"][r]
        assert row["busy_s"] > 0
        assert row["iterations"] >= ROUNDS - 6
    assert ft["round_critical_path"]["rounds"] > 0


# ---------------------------------------------------------------------------
# Report CLI satellites
# ---------------------------------------------------------------------------

def test_report_cli_errors_on_missing_and_empty_dirs(tmp_path, capsys):
    missing = str(tmp_path / "nope")
    assert report_main([missing]) == 2
    assert "not a run directory" in capsys.readouterr().err

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert report_main([empty]) == 2
    assert "empty run directory" in capsys.readouterr().err

    assert report_main(["--json", missing]) == 2


def test_report_json_output_schema(tmp_path, capsys):
    d = str(tmp_path / "run")
    with obs.run_scope(d) as run:
        run.metric("solver_cost", 1.5, phase="eval", iteration=1)
        with trace.span("iterate", phase="compute", robot=0):
            pass
    assert report_main(["--json", d]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["run"] == run.run_id
    assert rec["truncated"] is False
    assert rec["event_kinds"]["span"] == 1
    assert rec["fleet_timeline"]["robots"]
    assert "metrics" in rec


# ---------------------------------------------------------------------------
# Truncated-tail tolerance (robot killed mid-write)
# ---------------------------------------------------------------------------

def test_read_events_tolerates_truncated_final_line(tmp_path):
    p = str(tmp_path / "e.jsonl")
    with open(p, "w") as fh:
        fh.write(json.dumps({"event": "a", "seq": 0}) + "\n")
        fh.write(json.dumps({"event": "b", "seq": 1}) + "\n")
        fh.write('{"event": "c", "se')          # killed mid-write
    with pytest.warns(RuntimeWarning, match="truncated final event line"):
        evs = read_events(p)
    assert [e["event"] for e in evs] == ["a", "b"]
    with pytest.warns(RuntimeWarning):
        evs, truncated = read_events_meta(p)
    assert truncated and len(evs) == 2


def test_read_events_still_raises_on_mid_file_corruption(tmp_path):
    p = str(tmp_path / "e.jsonl")
    with open(p, "w") as fh:
        fh.write(json.dumps({"event": "a"}) + "\n")
        fh.write("{definitely not json}\n")
        fh.write(json.dumps({"event": "c"}) + "\n")
    with pytest.raises(ValueError, match="corrupt event line"):
        read_events(p)


def test_timeline_cli(tmp_path, capsys):
    pa, pb = _synthetic_pair(tmp_path)
    out = str(tmp_path / "fleet.json")
    assert timeline.main([pa, pb, "-o", out, "--report"]) == 0
    printed = capsys.readouterr().out
    assert "flow edges" in printed and "clock" in printed
    counts = timeline.validate_chrome_trace(out)
    assert counts["spans"] == 120
    assert timeline.main([str(tmp_path / "missing")]) == 2
