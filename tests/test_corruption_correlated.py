"""Perceptual-aliasing (correlated) corruption protocol
(utils.synthetic.corrupt_loop_closures_correlated): the generated false
loop closures must be MUTUALLY consistent inside each cluster — that is
the property that makes this the hard case for single-anneal GNC — and
the iterated-GNC pipeline must still reject them on a small problem.
"""

import numpy as np
import jax.numpy as jnp

from dpgo_tpu.config import AgentParams, RobustCostParams, RobustCostType
from dpgo_tpu.models import rbcd
from dpgo_tpu.types import loop_closure_mask
from dpgo_tpu.utils.synthetic import (corrupt_loop_closures_correlated,
                                      integrate_odometry_np,
                                      rejection_scores)
from synthetic import make_measurements as make_meas_test


def _problem(rng, n=120, num_lc=60):
    meas, _ = make_meas_test(rng, n=n, d=3, num_lc=num_lc,
                             rot_noise=0.01, trans_noise=0.01)
    return meas


def test_clusters_are_mutually_consistent(rng):
    """Within one cluster, every false edge must agree with the SAME
    rigid transform between the two dead-reckoned segments: composing
    edge i's claim about segment-B's frame must match edge j's, far
    more tightly than the edges agree with the true geometry."""
    meas = _problem(rng)
    cor, idx = corrupt_loop_closures_correlated(meas, 0.4, clusters=2,
                                                seed=3, rot_noise=0.0,
                                                trans_noise=0.0)
    assert len(idx) == round(0.4 * loop_closure_mask(meas).sum())
    Rs, ts = integrate_odometry_np(meas)

    # Group the injected edges by (p1 - p2) offset: all members of one
    # cluster share the segment offset a - b by construction.
    offs = cor.p1[idx] - cor.p2[idx]
    for off in np.unique(offs):
        rows = idx[offs == off]
        if len(rows) < 2:
            continue
        # Recover each edge's implied transform T = X_a M X_b^{-1}
        # (world frame of segment B according to that edge).
        Ts = []
        for row in rows:
            ia, ib = int(cor.p1[row]), int(cor.p2[row])
            R_T = Rs[ia] @ cor.R[row] @ Rs[ib].T
            t_T = ts[ia] + Rs[ia] @ cor.t[row] - R_T @ ts[ib]
            Ts.append((R_T, t_T))
        R0, t0 = Ts[0]
        for R_T, t_T in Ts[1:]:
            assert np.abs(R_T - R0).max() < 1e-8
            assert np.abs(t_T - t0).max() < 1e-8
        # And the implied transform is GROSS (far from identity), i.e.
        # the cluster actually lies about the geometry.
        assert np.abs(R0 - np.eye(3)).max() > 0.05 or \
            np.linalg.norm(t0) > 0.5


def test_iterated_gnc_rejects_correlated_clusters(rng):
    """Slow-ish smoke: the full iterated-GNC pipeline on a small graph
    with 2 aliasing clusters at 25% — recall must be high (the clusters
    must not capture the solution) and precision must not collapse."""
    meas = _problem(rng, n=100, num_lc=80)
    cor, idx = corrupt_loop_closures_correlated(meas, 0.25, clusters=2,
                                                seed=1)
    params = AgentParams(
        d=3, r=5, num_robots=4,
        robust=RobustCostParams(cost_type=RobustCostType.GNC_TLS),
        rel_change_tol=0.0)
    res, w, kept = rbcd.solve_rbcd_robust_iterated(
        cor, 4, params, passes=3, max_iters=900, grad_norm_tol=0.0,
        eval_every=300, dtype=jnp.float32)
    prec, rec, n_rej = rejection_scores(w, cor, idx)
    assert rec >= 0.9, f"recall {rec:.3f}"
    assert prec >= 0.8, f"precision {prec:.3f}"
