"""TCP front-end of the serving plane: wire round-trip, frame-size caps,
and structured error replies.  The full solve-over-TCP test is
slow-marked (real sockets + solver compile) and runs in the CI serving
job; the protocol-level tests stay in tier-1."""

import numpy as np
import pytest

from dpgo_tpu.comms.protocol import ProtocolError
from dpgo_tpu.comms.transport import TcpTransport, connect_tcp
from dpgo_tpu.config import AgentParams
from dpgo_tpu.models import rbcd
from dpgo_tpu.serve import SolveServer
from dpgo_tpu.serve.frontend import (ServeFrontend, _pack_str, _unpack_str,
                                     handle_request, solve_g2o)
from dpgo_tpu.utils.g2o import write_g2o
from dpgo_tpu.utils.synthetic import make_measurements

PARAMS = AgentParams(d=3, r=5, num_robots=2)


def _g2o_bytes(tmp_path, n=24, seed=0):
    meas, _ = make_measurements(np.random.default_rng(seed), n=n, d=3,
                                num_lc=5, rot_noise=0.01, trans_noise=0.01)
    path = str(tmp_path / f"prob_{n}_{seed}.g2o")
    write_g2o(meas, path)
    with open(path, "rb") as fh:
        return fh.read()


def test_frontend_ping_and_unknown_op():
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        with ServeFrontend(srv) as fe:
            sock = connect_tcp("127.0.0.1", fe.port)
            tr = TcpTransport(sock, src="test-client")
            try:
                tr.send({"op": _pack_str("ping")})
                assert int(np.asarray(tr.recv(timeout=10)["ok"])) == 1
                tr.send({"op": _pack_str("launch-missiles")})
                reply = tr.recv(timeout=10)
                assert int(np.asarray(reply["ok"])) == 0
                assert "unknown op" in _unpack_str(reply["error"])
            finally:
                tr.close()


def test_client_side_frame_cap_raises_protocol_error(tmp_path):
    raw = _g2o_bytes(tmp_path)
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        with ServeFrontend(srv) as fe:
            with pytest.raises(ProtocolError, match="exceeds"):
                solve_g2o("127.0.0.1", fe.port, raw, num_robots=2,
                          max_frame_bytes=256)


def test_server_side_frame_cap_reports_structured_error(tmp_path):
    raw = _g2o_bytes(tmp_path)
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        # --max-frame-mb analog: a cap smaller than the upload.
        with ServeFrontend(srv, max_frame_bytes=1024) as fe:
            sock = connect_tcp("127.0.0.1", fe.port)
            tr = TcpTransport(sock, src="test-client")
            try:
                tr.send({"op": _pack_str("solve"),
                         "g2o": np.frombuffer(raw, np.uint8),
                         "num_robots": np.int32(2)})
                reply = tr.recv(timeout=10)
                assert int(np.asarray(reply["ok"])) == 0
                assert "protocol error" in _unpack_str(reply["error"])
            finally:
                tr.close()


def test_handle_request_solves_g2o_payload_in_process(tmp_path):
    """The frontend handler parses uploaded g2o bytes without temp files
    (read_g2o bytes input) and returns the result arrays."""
    raw = _g2o_bytes(tmp_path)
    with SolveServer(max_batch=2, batch_window_s=0.0, quantum=64) as srv:
        reply = handle_request(srv, {
            "op": _pack_str("solve"),
            "g2o": np.frombuffer(raw, np.uint8),
            "num_robots": np.int32(2),
            "max_iters": np.int32(4),
            "grad_norm_tol": np.float64(1e-12),
            "eval_every": np.int32(2),
            "tenant": _pack_str("acme"),
        })
    assert int(np.asarray(reply["ok"])) == 1
    assert np.isfinite(np.asarray(reply["cost_history"])).all()
    assert reply["T"].shape[-2:] == (3, 4)
    assert _unpack_str(reply["terminated_by"]) in (
        "grad_norm", "consensus", "max_iters")


def test_handle_request_bad_payload_structured_error():
    with SolveServer(max_batch=2, batch_window_s=0.0) as srv:
        reply = handle_request(srv, {
            "op": _pack_str("solve"),
            "g2o": np.frombuffer(b"VERTEX_SE3:QUAT 0 garbage\n", np.uint8),
            "num_robots": np.int32(2),
        })
    assert int(np.asarray(reply["ok"])) == 0
    assert _unpack_str(reply["error"])


def test_tcp_serve_solve_roundtrip(tmp_path):
    """Full solve over a real socket, compared against the library path.
    Slow-marked: runs in the CI serving job, not tier-1."""
    raw = _g2o_bytes(tmp_path, n=30, seed=3)
    from dpgo_tpu.utils.g2o import read_g2o

    meas = read_g2o(raw)
    ref = rbcd.solve_rbcd(meas, 2, params=PARAMS, max_iters=4,
                          grad_norm_tol=1e-12, eval_every=2)
    with SolveServer(max_batch=2, batch_window_s=0.0, quantum=64) as srv:
        with ServeFrontend(srv) as fe:
            out = solve_g2o("127.0.0.1", fe.port, raw, num_robots=2,
                            max_iters=4, grad_norm_tol=1e-12, eval_every=2,
                            timeout=300)
    assert out["ok"]
    assert abs(out["cost_history"][-1] - ref.cost_history[-1]) <= \
        1e-8 * max(1.0, abs(ref.cost_history[-1]))
    assert out["T"].shape == np.asarray(ref.T).shape
